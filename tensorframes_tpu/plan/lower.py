"""Lowering: turn a plan chain into (ideally) ONE dispatch per block.

``execute_plan`` is the pending computation of every plan-carrying
frame. It resolves the chain to its effective source, splits it into
segments at filters and joins (:mod:`.rules`), and runs each segment
either

* **fused** — the segment's included map stages compose into a single
  :class:`~tensorframes_tpu.program.Program` (map_rows stages enter in
  their vmapped form) that dispatches through the ordinary
  ``map_blocks`` machinery, so the jit cache, input donation, the
  prefetch window, and the sharded paths all apply unchanged; or
* **per-stage fallback** — the exact single-verb execution, taken when
  a runtime barrier shows up (ragged source cells, a host-callback
  stage, a trace failure) or when fusing would not help (a bare single
  map keeps its specialized path, lead-dim bucketing included).

A segment ending in a ``join`` node runs its probe-side maps fused as
above, then executes the hash join through the SAME
:func:`~tensorframes_tpu.frame._hash_join_cols` core the eager path
uses — over only the columns the needed-columns pass kept on either
side.

``execute_aggregate`` is the pending computation of a plan-recorded
keyed ``aggregate``: the upstream fused map Program composes with a
segment-reduce epilogue into ONE Program per block whose ``[K, ...]``
partial tables tree-combine across blocks — the mapped value columns
are never materialized. When a float sum/mean would reassociate across
blocks (tree-combining is then not bit-identical to the unfused global
reduction), the cost model picks the **concat epilogue** instead: the
fused map runs per block with device-resident outputs and ONE segment
dispatch reduces the concatenation — the exact program, values, and row
order of the unfused path. ``lower_reduce`` does the same for
whole-frame ``reduce_blocks``/``reduce_rows`` (scan epilogue for the
pairwise fold), returning per-block partials for the verbs' unchanged
combine step.

Fused programs are cached by stage identity so steady-state serving
loops (rebuild the chain each batch from the same pre-compiled
Programs) reuse one XLA executable instead of re-tracing per force.

Observability: ``tftpu_plan_*`` metrics are registered at import (the
fused-stages/epilogue counters, the intermediate-bytes-avoided counter,
the plan-lowering-seconds histogram, per-reason fallback counters, and
per-decision cost-model counters) and ``plan.lower`` / ``plan.execute``
spans plus ``plan.cost`` decision instants land on the structured trace
timeline when tracing is on.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability import events as _events
from ..observability.metrics import counter as _counter
from ..observability.metrics import histogram as _histogram
from ..utils import get_logger
from ..utils import profiling
from . import ir
from . import rules as _rules
from . import stats as _stats
from .rules import SegmentPlan, plan_segment, split_segments

logger = get_logger(__name__)

__all__ = [
    "execute_plan", "execute_aggregate", "lower_reduce",
    "canonical_table_order", "fold_partial_tables",
]

# Registered at import so expositions always carry the plan family
# (a process that never fused reads 0 — the series does not vanish).
_FUSED_STAGES = _counter(
    "tftpu_plan_fused_stages_total",
    "Map stages executed inside a fused (single-dispatch) plan segment",
)
_BYTES_AVOIDED = _counter(
    "tftpu_plan_intermediate_bytes_avoided_total",
    "Bytes of intermediate stage outputs never materialized because the "
    "chain ran fused (consumed in-register or pruned by select pushdown)",
)
_LOWER_SECONDS = _histogram(
    "tftpu_plan_lowering_seconds",
    "Wall-clock of lowering one segment to its fused Program "
    "(cache lookup + composition)",
)
_FALLBACKS = {
    reason: _counter(
        "tftpu_plan_fallback_total",
        "Plan segments that fell back to per-stage execution, by reason",
        labels={"reason": reason},
    )
    for reason in (
        "ragged", "host_callback", "trace_error", "single_stage",
        "computed_key",
    )
}
# Whole-pipeline epilogues that fused into the plan, by consuming verb.
_FUSED_EPILOGUES = {
    verb: _counter(
        "tftpu_plan_fused_epilogues_total",
        "Aggregate/reduce/join epilogues executed inside the plan "
        "(mapped inputs never materialized), by verb",
        labels={"verb": verb},
    )
    for verb in ("aggregate", "reduce_blocks", "reduce_rows", "join")
}
# Cost-model decisions, by decision kind (plan/rules.py decide_*).
_COST_DECISIONS = {
    kind: _counter(
        "tftpu_plan_cost_decisions_total",
        "Lowering choices made by the plan cost model, by decision",
        labels={"decision": kind},
    )
    for kind in (
        "fuse", "split_single_stage", "epilogue_per_block",
        "epilogue_concat", "bucket_segments", "host_segment_reduce",
        # kernel selection (ISSUE 12): which lowering serves each
        # measured straggler — plan/rules.decide_segment_reduce /
        # decide_decode_attention / decide_ragged_gather
        "pallas_segment_reduce", "jit_segment_reduce",
        "pallas_decode_attn", "xla_decode_attn", "pallas_ragged_gather",
        # adaptive optimizer (ISSUE 14): aggregate pushdown below
        # joins, multi-join reordering, and stats-fed re-optimization
        # (plan/rules.plan_pushdown / decide_pushdown /
        # decide_join_order; TFTPU_REOPT=0 removes them all)
        "pushdown_aggregate", "pushdown_ineligible",
        "pushdown_skipped_selective", "reorder_joins",
        "join_order_static", "reoptimized",
    )
}


def _note_decision(decision: "_rules.Decision") -> None:
    """Count + trace one cost-model decision (the decision log the
    bench's ``# plan |`` summary and post-hoc trace reads)."""
    c = _COST_DECISIONS.get(decision.kind)
    if c is not None:
        c.inc()
    if _events.TRACER.enabled:
        _events.TRACER.instant(
            "plan.cost", cat="plan",
            decision=decision.kind, reason=decision.reason,
            **{k: str(v) for k, v in decision.details.items()},
        )


def _lowering_seconds_mean() -> Optional[float]:
    """Mean observed lowering wall-clock — the live-metrics input the
    cost model's fuse decision records for post-hoc inspection."""
    try:
        if _LOWER_SECONDS.count:
            return _LOWER_SECONDS.sum / _LOWER_SECONDS.count
    except Exception:  # pragma: no cover - metrics internals moved
        pass
    return None


# -- per-stage / per-strategy wall observation (ISSUE 17) -------------------
# EXPLAIN ANALYZE needs every executed plan stage to leave a profile
# entry (wall, rows, bytes, strategy, compile-vs-run split), and the
# latency-driven decide_* feedback needs every strategy dispatch to
# land in the stats sidecar's EWMA table. Both series pre-register at
# import with CLOSED label sets (TFL003): stage kinds here, strategy
# kinds as the decide_* kinds they mirror.

#: Closed stage-kind set for tftpu_plan_stage_wall_seconds.
_STAGE_KINDS = (
    "fused", "per_stage", "join", "join_chain", "aggregate",
    "pushdown", "reduce",
)
_STAGE_WALL = {
    s: _histogram(
        "tftpu_plan_stage_wall_seconds",
        "Observed wall-clock of one executed plan stage, by stage kind "
        "(the metric shadow of the EXPLAIN ANALYZE per-stage profile)",
        labels={"stage": s},
    )
    for s in _STAGE_KINDS
}

#: Closed (decision, strategy) pairs for tftpu_plan_strategy_wall_seconds.
_STRATEGY_WALL_PAIRS = (
    ("fuse", "fuse"), ("fuse", "split_single_stage"),
    ("epilogue", "epilogue_per_block"), ("epilogue", "epilogue_concat"),
    ("segment_reduce", "host_segment_reduce"),
    ("segment_reduce", "pallas_segment_reduce"),
    ("segment_reduce", "jit_segment_reduce"),
    ("ragged_gather", "pallas_ragged_gather"),
    ("ragged_gather", "host_stack"),
    ("decode_attention", "pallas_decode_attn"),
    ("decode_attention", "xla_decode_attn"),
)
_STRATEGY_WALL = {
    pair: _histogram(
        "tftpu_plan_strategy_wall_seconds",
        "Observed wall-clock of one strategy's dispatch, by (decision, "
        "strategy) — the histogram shadow of the EWMA table that feeds "
        "latency-driven plan decisions",
        labels={"decision": pair[0], "strategy": pair[1]},
    )
    for pair in _STRATEGY_WALL_PAIRS
}


#: Decisions whose strategies include a pallas kernel: their walls are
#: unrepresentative under TFTPU_PALLAS_FORCE (the CPU interpreter runs
#: the kernel orders of magnitude slower than any real backend), so
#: forced runs must not feed the EWMA table a later unforced run (or a
#: sidecar-sharing real run) would act on.
_KERNEL_DECISIONS = ("segment_reduce", "ragged_gather", "decode_attention")


def observe_strategy_wall(decision: str, strategy: str,
                          wall_s: float) -> None:
    """Record one observed strategy dispatch wall: the pre-registered
    histogram plus the stats sidecar's per-(decision, strategy) EWMA
    table — the feedback input the decide_* functions consult."""
    h = _STRATEGY_WALL.get((decision, strategy))
    if h is not None:
        h.observe(wall_s)
    if decision in _KERNEL_DECISIONS:
        from .. import kernels as _kernels

        if _kernels.force_active():
            return
    _stats.observe_strategy_wall(decision, strategy, wall_s)


# Per-force profile collector: execute_plan / execute_aggregate push a
# frame, every executed stage notes itself into the topmost frame, and
# the force records the popped entries into the stats sidecar under its
# plan fingerprint. A STACK (not a single slot) because forces nest —
# gathering a join's build side forces an independent pipeline whose
# stages belong to ITS fingerprint, not the outer one (and whose wall
# the outer profile sees only through its own join stage entry).
_PROFILE_TLS = threading.local()


def _profile_push() -> list:
    stack = getattr(_PROFILE_TLS, "stack", None)
    if stack is None:
        stack = _PROFILE_TLS.stack = []
    frame: list = []
    stack.append(frame)
    return frame


def _profile_pop(frame: list) -> Optional[list]:
    """Detach ``frame`` from the stack (idempotent — record sites pop
    first, the owner's finally pops again harmlessly)."""
    stack = getattr(_PROFILE_TLS, "stack", None)
    if stack is None:
        return None
    try:
        stack.remove(frame)
    except ValueError:
        return None
    return frame


def _profile_note(stage: str, wall_s: float, *, rows: Optional[int] = None,
                  nbytes: Optional[int] = None,
                  strategy: Optional[str] = None,
                  compile_s: Optional[float] = None) -> None:
    """One executed stage's profile entry: always observed on the
    pre-registered stage-wall histogram, appended to the active force's
    collector when one is open."""
    h = _STAGE_WALL.get(stage)
    if h is not None:
        h.observe(wall_s)
    stack = getattr(_PROFILE_TLS, "stack", None)
    if not stack:
        return
    entry: Dict[str, object] = {"stage": stage, "wall_s": float(wall_s)}
    if rows is not None:
        entry["rows"] = int(rows)
    if nbytes is not None:
        entry["bytes"] = int(nbytes)
    if strategy is not None:
        entry["strategy"] = strategy
    if compile_s is not None:
        entry["compile_s"] = float(compile_s)
    stack[-1].append(entry)

# fused-Program cache: steady-state loops rebuild chains from the same
# stage Programs every iteration; re-composing (and re-jitting) per
# force would throw the executable away each time. Keyed by stage
# identity + needed outputs + source input specs; values pin the stage
# Programs so ids stay live, and hits verify identity against id reuse.
_CACHE_LOCK = threading.Lock()
_FUSED_CACHE: "OrderedDict[tuple, tuple]" = OrderedDict()
_FUSED_CACHE_MAX = 64


def clear_fused_cache() -> None:
    """Drop every cached fused Program. ``ops.segment.disable_pallas``
    calls this when the pallas kill-switch trips: per-block aggregate
    epilogues embed ``segment_sum``'s pallas-vs-XLA branch at TRACE
    time, so a program traced while pallas was enabled would keep
    failing from the cache forever — re-tracing after the switch picks
    the XLA scatter and the fused path recovers."""
    with _CACHE_LOCK:
        _FUSED_CACHE.clear()


def _input_specs(plan: SegmentPlan, schema):
    """Block-level input specs for the fused program, demoted exactly as
    ``_normalize_program`` would (gather_feeds casts at the boundary)."""
    from .. import dtypes as dt
    from ..program import TensorSpec

    demote = dt.demotion_active()
    specs = []
    for name in plan.source_inputs:
        col = schema[name]
        dtype = dt.demote(col.dtype) if demote else col.dtype
        specs.append(TensorSpec(name, dtype, col.block_shape))
    return specs


def _output_specs(plan: SegmentPlan):
    """Output specs of the fused program: each computed name's spec from
    its producing stage, lifted to block level (map_rows outputs gain
    the leading batch dim their vmapped form produces)."""
    from ..program import TensorSpec
    from ..shape import Unknown

    by_name = {}
    for n in plan.included:
        for o in (n.program.outputs or []):
            shape = o.shape.prepend(Unknown) if n.rows else o.shape
            by_name[o.name] = TensorSpec(o.name, o.dtype, shape)
    return [by_name[name] for name in plan.computed_names]


def _fused_program(plan: SegmentPlan, schema):
    """Build (or fetch) the composed Program for one segment: stages
    applied in order over a shared column environment, each map_rows
    stage entering as ``jax.vmap`` of its cell function, outputs
    restricted to what the segment's consumer needs."""
    from .. import dtypes as dt
    from ..program import Program

    in_specs = _input_specs(plan, schema)
    key = (
        tuple(
            (id(n.program), n.rows, n.out_names) for n in plan.included
        ),
        tuple(plan.computed_names),
        tuple(
            (s.name, s.dtype.name, tuple(s.shape.dims)) for s in in_specs
        ),
        bool(dt.demotion_active()),
    )
    with _CACHE_LOCK:
        hit = _FUSED_CACHE.get(key)
        if hit is not None:
            fused, pinned = hit
            if len(pinned) == len(plan.included) and all(
                p is n.program for p, n in zip(pinned, plan.included)
            ):
                _FUSED_CACHE.move_to_end(key)
                return fused

    import jax

    stages = [
        (jax.vmap(n.program.fn) if n.rows else n.program.fn,
         tuple(n.program.input_names), tuple(n.out_names))
        for n in plan.included
    ]
    result_names = tuple(plan.computed_names)

    def fn(feeds: Dict[str, object]) -> Dict[str, object]:
        env = dict(feeds)
        for stage_fn, in_names, out_names in stages:
            outs = stage_fn({k: env[k] for k in in_names})
            for k in out_names:
                env[k] = outs[k]
        return {name: env[name] for name in result_names}

    fused = Program(fn, in_specs, _output_specs(plan),
                    fetch_order=list(result_names))
    with _CACHE_LOCK:
        _FUSED_CACHE[key] = (fused, tuple(n.program for n in plan.included))
        while len(_FUSED_CACHE) > _FUSED_CACHE_MAX:
            _FUSED_CACHE.popitem(last=False)
    return fused


def _pruned_source(frame, names: Sequence[str]):
    """``frame`` restricted to ``names`` with its physical identity
    (mesh, axis, process-local markers) preserved — the plain
    ``select()`` intentionally drops sharding metadata, but the fused
    dispatch must see the source exactly as the per-stage verbs would."""
    from ..frame import TensorFrame

    names = list(names)
    if list(frame.schema.names) == names:
        return frame
    schema = frame.schema.select(names)
    if frame.is_materialized:
        out = TensorFrame(
            [{n: b[n] for n in names} for b in frame.blocks()], schema
        )
    else:
        out = TensorFrame(
            None, schema,
            pending=lambda: [
                {n: b[n] for n in names} for b in frame.blocks()
            ],
        )
    for attr in ("_mesh", "_axis", "_process_local_cols"):
        if hasattr(frame, attr):
            setattr(out, attr, getattr(frame, attr))
    return out


def _apply_mask(block: Dict[str, object], names: Sequence[str],
                mask_name: str) -> Dict[str, object]:
    """Row-subset one block by its (already computed) mask column — THE
    single-process filter contract, shared by ``TensorFrame.filter``'s
    legacy path and the fused plan path so they cannot diverge:
    bool[rows] masks only, loud row-count mismatches, device columns
    gathered in HBM (only the mask crosses to host)."""
    from ..frame import _block_num_rows, _is_jax_array

    m = np.asarray(block[mask_name])
    if m.dtype != np.bool_ or m.ndim != 1:
        raise ValueError(
            f"filter predicate output {mask_name!r} must be bool[rows]; "
            f"got {m.dtype} with shape {m.shape}"
        )
    rows = _block_num_rows({n: block[n] for n in names})
    if m.shape[0] != rows:
        # must fail LOUDLY: jax gather clamps out-of-bounds indices, so
        # an oversized mask would silently duplicate the last row on
        # device columns where numpy's boolean index raises
        raise ValueError(
            f"filter predicate output {mask_name!r} has {m.shape[0]} "
            f"rows for a block of {rows}"
        )
    out: Dict[str, object] = {}
    idx = None
    for name in names:
        v = block[name]
        if isinstance(v, list):
            out[name] = [x for x, keep in zip(v, m) if keep]
        elif _is_jax_array(v):
            if idx is None:
                import jax.numpy as jnp

                idx = jnp.asarray(np.flatnonzero(m))
            out[name] = v[idx]
        else:
            out[name] = np.asarray(v)[m]
    return out


def _segment_ragged(source, input_names: Sequence[str]) -> bool:
    """True when any fused input column holds ragged cells in any source
    block — the fused (block-level) program cannot feed them; per-stage
    map_rows has the grouped-dispatch path for exactly this."""
    from ..ops.executor import block_is_ragged

    src = set(source.schema.names)
    names = [n for n in input_names if n in src]
    return any(block_is_ragged(b, names) for b in source.blocks())


def _avoided_bytes(plan: SegmentPlan, blocks) -> int:
    """Bytes the fused run never materialized: per avoided output, total
    rows x known cell extent x itemsize (Unknown inner dims skipped —
    an estimate must never overclaim)."""
    from ..frame import _block_num_rows
    from ..shape import Unknown

    rows = sum(_block_num_rows(b) for b in blocks)
    total = 0
    for _, spec in plan.avoided_outputs:
        dims = list(spec.shape.dims)
        if dims and dims[0] == Unknown:
            dims = dims[1:]
        if any(d == Unknown for d in dims):
            continue
        cell = 1
        for d in dims:
            cell *= int(d)
        itemsize = np.dtype(spec.dtype.np_dtype).itemsize
        total += rows * cell * itemsize
    return total


def _run_fused(source, plan: SegmentPlan):
    """One dispatch per block: compose, hand to map_blocks (jit cache /
    donation / prefetch / sharded paths unchanged), re-key to the
    segment's result columns, apply the filter mask if present."""
    from ..frame import TensorFrame, _block_num_rows
    from ..ops.verbs import map_blocks

    t0 = time.perf_counter()
    src_cols = [
        n for n in source.schema.names
        if n in set(plan.source_inputs) | set(plan.pass_through)
    ]
    pruned = _pruned_source(source, src_cols)
    fused = _fused_program(plan, pruned.schema)
    lower_dt = time.perf_counter() - t0
    _LOWER_SECONDS.observe(lower_dt)
    if _events.TRACER.enabled:
        _events.TRACER.emit_complete(
            "plan.lower", t0, lower_dt,
            args={"stages": len(plan.included)}, cat="plan",
        )
    t_f0 = time.perf_counter()
    mapped = map_blocks(fused, pruned)
    blocks = mapped.blocks()
    keep = list(plan.final_names)
    if plan.has_filter:
        out_blocks = [
            _apply_mask(b, keep, plan.mask_name) for b in blocks
        ]
        # same observability contract as the legacy filter: one span,
        # INPUT-rows convention (mask compute + gather wall-clock)
        from ..utils import profiling

        profiling.record(
            "filter", time.perf_counter() - t_f0,
            sum(_block_num_rows(b) for b in blocks),
        )
    else:
        out_blocks = [{n: b[n] for n in keep} for b in blocks]
    _FUSED_STAGES.inc(len(plan.included))
    avoided = _avoided_bytes(plan, blocks)
    _BYTES_AVOIDED.inc(avoided)
    _profile_note(
        "fused", time.perf_counter() - t0,
        rows=sum(_block_num_rows(b) for b in blocks),
        nbytes=avoided, strategy="fuse", compile_s=lower_dt,
    )
    result = TensorFrame(
        out_blocks, plan.nodes[-1].schema.select(keep)
    )
    if not plan.has_filter and mapped.is_sharded:
        result._mesh = mapped.mesh
        result._axis = getattr(mapped, "_axis", None)
    return result


def _run_per_stage(source, plan: SegmentPlan):
    """Exact single-verb execution of the segment's nodes (the honest
    fallback: barriers split the plan, they never change semantics)."""
    from ..frame import TensorFrame, _block_num_rows
    from ..ops.verbs import map_blocks, map_rows

    t_seg0 = time.perf_counter()
    cur = source
    for n in plan.nodes:
        if n.kind == "map":
            cur = (map_rows if n.rows else map_blocks)(n.program, cur)
        elif n.kind == "select":
            cur = cur.select(list(n.names))
        elif n.kind == "filter":
            from ..utils import profiling

            names = list(n.schema.names)
            t_f0 = time.perf_counter()
            in_blocks = cur.blocks()
            out_blocks = [
                _apply_mask(b, names, n.mask_name) for b in in_blocks
            ]
            profiling.record(
                "filter", time.perf_counter() - t_f0,
                sum(_block_num_rows(b) for b in in_blocks),
            )
            cur = TensorFrame(out_blocks, n.schema)
    keep = list(plan.final_names)
    if list(cur.schema.names) != keep:
        cur = _pruned_source(cur, keep)
    blocks = cur.blocks()
    _profile_note(
        "per_stage", time.perf_counter() - t_seg0,
        rows=sum(_block_num_rows(b) for b in blocks),
        strategy="split_single_stage",
    )
    return cur



def _gather_right(plan: SegmentPlan) -> Dict[str, object]:
    """Force + gather a join segment's (pruned) build side. The build
    side is an INDEPENDENT pipeline: the select escapes the lowering
    re-entrancy guard so it records on ITS plan and pushdown genuinely
    prunes the build chain (a guarded select would take the legacy
    pending path and force every build column first)."""
    from ..frame import _merged_global_columns

    right = plan.join_node.right
    r_needed = set(plan.right_needed or [])
    r_names = [n for n in right.schema.names if n in r_needed]
    with ir.allow_planning():
        if list(right.schema.names) != r_names:
            right_p = right.select(r_names)
        else:
            right_p = right
        return _merged_global_columns(right_p, r_names, "join")


def _run_join(cur, plan: SegmentPlan, rcols: Optional[Dict] = None):
    """Execute a segment's trailing join node: gather the (pruned)
    probe side, force the (pruned) build side, and run the SAME hash
    join core the eager path runs (frame._hash_join_cols). Returns a
    one-block frame holding exactly the join outputs the consumer
    needs — build-side pushdown selects the right frame down to
    ``right_needed`` first, so a lazy right chain never computes (or
    match-expands) dead columns. ``rcols`` passes pre-gathered build
    columns (the join-chain path forces every build side up front and
    must not force them twice)."""
    from ..frame import (
        TensorFrame,
        _block_num_rows,
        _hash_join_cols,
    )
    from ..frame import _merged_global_columns

    jn = plan.join_node
    t0 = time.perf_counter()
    if rcols is None:
        rcols = _gather_right(plan)
    lcols = _merged_global_columns(cur, list(cur.schema.names), "join")
    out = _hash_join_cols(lcols, rcols, jn.spec)
    keep = list(plan.join_out_names)
    out = {n: out[n] for n in keep}
    # same observability contract as the eager join span: INPUT rows
    rows_in = _block_num_rows(lcols) + _block_num_rows(rcols)
    profiling.record("join", time.perf_counter() - t0, rows_in)
    _FUSED_EPILOGUES["join"].inc()
    _profile_note(
        "join", time.perf_counter() - t0, rows=rows_in,
        strategy="hash_join",
    )
    return TensorFrame([out], jn.schema.select(keep))


# ---------------------------------------------------------------------------
# adaptive optimizer (ISSUE 14): join-chain reordering + aggregate
# pushdown below joins + stats feedback. All of it gates on BOTH
# ``plan_fusion`` and ``plan_reopt`` (TFTPU_REOPT=0 restores the PR 7
# static lowering exactly), and every rewrite is bit-identical to the
# unrewritten path by construction — see plan/rules.py eligibility.
# ---------------------------------------------------------------------------

def _strip_join(plan: SegmentPlan) -> SegmentPlan:
    """A join segment's inner (pre-join) part as its own plan: the map
    stages run, the probe columns project, the join itself does not."""
    return dataclasses.replace(
        plan, join_node=None, right_needed=None, join_out_names=None
    )


def _as_key_array(v):
    """Key column → array form ``group_ids`` accepts (host list columns
    become object arrays, the same convention as the join core)."""
    if isinstance(v, list):
        u = np.empty(len(v), dtype=object)
        u[:] = v
        return u
    return np.asarray(v)


def _union_key_arrays(a_cols, b_cols):
    """Per-key union arrays for membership encoding — built by the SAME
    helper the join core uses (``frame._key_union_col``), so NaN/string
    semantics cannot drift from ``_hash_join_cols``."""
    from ..frame import _key_union_col

    return [_key_union_col(a, b) for a, b in zip(a_cols, b_cols)]


def _keys_unique(rcols: Dict[str, object], keys: Sequence[str]) -> bool:
    """True when the key tuple is unique per row (the m=1 condition
    every adaptive join rewrite needs: with at most one match per key,
    joins neither duplicate nor scale rows, so they commute and
    degenerate to semi-join filters)."""
    from ..frame import _block_num_rows
    from ..ops.keys import group_ids

    nr = _block_num_rows({k: rcols[k] for k in keys})
    if nr == 0:
        return True
    _, _, ng = group_ids([_as_key_array(rcols[k]) for k in keys])
    return ng == nr


def _join_stat_key(index: int, keys: Sequence[str]) -> str:
    """Stable per-level stats key inside one plan fingerprint."""
    return f"{index}:{'+'.join(keys)}"


def _note_reoptimized(why: str, details: Dict[str, object]) -> None:
    """Count + trace one stats-informed (feedback) decision — the
    ``reoptimized`` series the acceptance criteria key on."""
    _note_decision(_rules.Decision("reoptimized", why, details))


def _note_flip(decision: "_rules.Decision") -> None:
    """When a decide_* choice flipped on observed strategy walls (the
    evidence rides ``details["latency_flip"]``), count it as a
    ``reoptimized`` decision too — same contract as join reordering."""
    if decision.details.get("latency_flip"):
        _note_reoptimized(
            "strategy chosen from observed per-strategy walls "
            "(stats sidecar latency table) instead of the static rule",
            {"decision": decision.kind,
             "observed_wall_s": decision.details.get("observed_wall_s")},
        )


def _sequential_joins(cur, jplans: List[SegmentPlan], rights):
    """Original-order join execution over pre-gathered build sides (the
    runtime fallback when a chain's m=1 check fails after the build
    sides were already forced)."""
    for k, (p, rc) in enumerate(zip(jplans, rights)):
        if k > 0:
            cur = _pruned_source(cur, p.final_names)
        cur = _run_join(cur, p, rcols=rc)
    return cur


def _run_join_chain(cur, jplans: List[SegmentPlan], fusion_on: bool,
                    fp: Optional[str]):
    """Execute a run of consecutive join segments, reordered by the
    cost model where eligibility holds (plan/rules.plan_join_chain:
    all-inner, base-rooted keys, no build-side callbacks; runtime m=1
    via unique build keys). Ineligible chains run exactly as today;
    eligible ones pre-rename every column to its final (output-schema)
    name so the hash joins execute in any order without the rename
    chains interfering — output rows are the base rows, in base order,
    that match every build side, whatever the order."""
    from ..frame import TensorFrame, _block_num_rows, _hash_join_cols
    from ..frame import _JoinSpec, _merged_global_columns

    chain, why_not = _rules.plan_join_chain(jplans)
    if chain is None:
        _note_decision(_rules.Decision(
            "join_order_static",
            f"multi-join chain keeps recorded order: {why_not}",
            {"joins": len(jplans)},
        ))
        for p in jplans:
            cur = _run_one_segment(cur, p, fusion_on)
        return cur

    estimates = [
        getattr(p.join_node.right, "estimated_rows", None)
        for p in jplans
    ]
    base = _run_one_segment(cur, _strip_join(jplans[0]), fusion_on)
    rights = [_gather_right(p) for p in jplans]
    for p, rc in zip(jplans, rights):
        if not _keys_unique(rc, p.join_node.spec.keys):
            _note_decision(_rules.Decision(
                "join_order_static",
                "build side has duplicate join keys — m>1 joins "
                "duplicate rows positionally and do not commute",
                {"joins": len(jplans)},
            ))
            return _sequential_joins(base, jplans, rights)

    build_rows = [
        _block_num_rows({k: rc[k] for k in p.join_node.spec.keys})
        for p, rc in zip(jplans, rights)
    ]
    rec = _stats.lookup(fp) if fp else None
    sels: List[Optional[float]] = []
    for idx, lev in enumerate(chain["levels"]):
        obs = ((rec or {}).get("joins") or {}).get(
            _join_stat_key(idx, lev["keys"]), {}
        )
        sels.append(obs.get("row_sel"))
    order, decision, used_stats = _rules.decide_join_order(
        build_rows, sels, estimates
    )
    _note_decision(decision)
    if used_stats:
        _note_reoptimized(
            "join order chosen from observed per-join selectivities "
            "(stats sidecar) instead of build-side size",
            {"order": list(order)},
        )

    base_rename = chain["base_rename"]
    bcols = _merged_global_columns(
        base, [n for n in base.schema.names if n in base_rename], "join"
    )
    lcols = {base_rename[n]: v for n, v in bcols.items()}
    obs_joins: Dict[str, dict] = {}
    all_finals = chain["all_finals"]
    for idx in order:
        lev = chain["levels"][idx]
        rr = lev["right_rename"]
        rcols_f = {rr[n]: v for n, v in rights[idx].items() if n in rr}
        exec_keys = lev["exec_keys"]
        espec = _JoinSpec(
            keys=tuple(exec_keys),
            how="inner",
            lname=tuple(
                (n, n) for n in all_finals
                if n not in exec_keys and n not in lev["nonkey_finals"]
            ),
            rname=tuple((n, n) for n in lev["nonkey_finals"]),
            fill_value=None,
        )
        t_j = time.perf_counter()
        rows_in = _block_num_rows(lcols)
        lcols = _hash_join_cols(lcols, rcols_f, espec)
        rows_out = _block_num_rows(lcols)
        profiling.record(
            "join", time.perf_counter() - t_j,
            rows_in + build_rows[idx],
        )
        _FUSED_EPILOGUES["join"].inc()
        _profile_note(
            "join_chain", time.perf_counter() - t_j,
            rows=rows_in + build_rows[idx], strategy="reordered_join",
        )
        obs_joins[_join_stat_key(idx, lev["keys"])] = {
            "build_rows": int(build_rows[idx]),
            "row_sel": round(rows_out / rows_in, 6) if rows_in else 1.0,
        }
    if fp:
        _stats.record_execution(fp, joins=obs_joins)
    last = jplans[-1]
    keep = list(last.join_out_names)
    out = {n: lcols[n] for n in keep}
    return TensorFrame([out], last.join_node.schema.select(keep))


def _execute_plans(cur, plans: Sequence[SegmentPlan], fusion_on: bool,
                   fp: Optional[str] = None):
    """Run a sequence of segment plans over ``cur``. With the adaptive
    optimizer on, maximal runs of consecutive join segments (only the
    first may carry map stages) route through the reordering path;
    everything else — and everything under TFTPU_REOPT=0 /
    TFTPU_FUSION=0 — executes segment-by-segment exactly as before."""
    adaptive = fusion_on and _stats.reopt_enabled()
    i, n = 0, len(plans)
    while i < n:
        j = i
        if adaptive and plans[i].has_join:
            while (
                j + 1 < n
                and plans[j + 1].has_join
                and not plans[j + 1].included
                and not plans[j + 1].has_filter
            ):
                j += 1
        if j > i:
            cur = _run_join_chain(cur, list(plans[i:j + 1]), fusion_on,
                                  fp)
            i = j + 1
        else:
            cur = _run_one_segment(cur, plans[i], fusion_on)
            i += 1
    return cur


def _plan_segments(
    source, nodes: Sequence[ir.PlanNode], final_names: Sequence[str]
) -> List[SegmentPlan]:
    """Split + backward needed-columns pass: segment k must produce what
    segment k+1 reads off its source — k+1's fused inputs plus its
    pass-through columns (join segments map the requirement back
    through the join's rename tables, see rules.plan_segment)."""
    segments = split_segments(nodes)
    plans: List[Optional[SegmentPlan]] = [None] * len(segments)
    need = list(final_names)
    for k in range(len(segments) - 1, -1, -1):
        src_names = (
            source.schema.names if k == 0
            else list(segments[k - 1][-1].schema.names)
        )
        plans[k] = plan_segment(segments[k], need, src_names)
        req = set(plans[k].source_inputs) | set(plans[k].pass_through)
        need = [n for n in src_names if n in req]
    return plans


def _run_one_segment(cur, plan: SegmentPlan, fusion_on: bool):
    """Execute one segment (inner stages + optional trailing join) over
    ``cur``, honoring the escape hatch and the runtime barriers."""
    if not fusion_on:
        cur = _run_per_stage(cur, plan)
        return _run_join(cur, plan) if plan.has_join else cur
    if not plan.included and not plan.has_filter:
        # pushdown pruned every stage (or the segment was pure
        # projection): no program to dispatch — just project
        cur = _pruned_source(cur, plan.final_names)
        return _run_join(cur, plan) if plan.has_join else cur
    fused_ok = plan.fusable
    reason = None
    if fused_ok and any(
        ir.program_has_callback(n.program) for n in plan.included
    ):
        fused_ok, reason = False, "host_callback"
    if fused_ok and _segment_ragged(cur, plan.source_inputs):
        fused_ok, reason = False, "ragged"
    timed_choice = False
    if reason is None:
        # the cost model speaks only when no hard barrier already
        # decided; its fuse/split choice is counted + traced. A fusable
        # segment is a REAL choice (both strategies are bit-identical),
        # so its dispatch wall feeds the latency table and observed
        # walls may flip it back to the per-stage replay.
        timed_choice = plan.fusable
        decision = _rules.decide_fuse(
            plan, _lowering_seconds_mean(),
            observed_walls=(
                _stats.strategy_walls("fuse") if timed_choice else None
            ),
        )
        _note_decision(decision)
        _note_flip(decision)
        fused_ok = decision.kind == "fuse"
    if fused_ok:
        t_strat = time.perf_counter()
        try:
            cur = _run_fused(cur, plan)
        except Exception as e:
            from ..validation import ValidationError

            if isinstance(e, (ValidationError, ValueError)):
                raise  # genuine contract violations stay loud
            logger.debug("fused segment failed, replaying "
                         "per-stage: %s", e)
            _FALLBACKS["trace_error"].inc()
            cur = _run_per_stage(cur, plan)
        else:
            if timed_choice:
                observe_strategy_wall(
                    "fuse", "fuse", time.perf_counter() - t_strat
                )
    else:
        if reason is not None:
            _FALLBACKS[reason].inc()
        elif len(plan.included) <= 1:
            _FALLBACKS["single_stage"].inc()
        t_strat = time.perf_counter()
        cur = _run_per_stage(cur, plan)
        if timed_choice:
            observe_strategy_wall(
                "fuse", "split_single_stage",
                time.perf_counter() - t_strat,
            )
    return _run_join(cur, plan) if plan.has_join else cur


def execute_plan(node: ir.PlanNode) -> List[Dict[str, object]]:
    """Force a plan-carrying frame: lower its chain and return the final
    blocks (the frame's ``pending`` contract)."""
    source, nodes = ir.resolve_chain(node)
    final_names = list(node.schema.names)
    if not nodes:  # degenerate: the node chain collapsed to its source
        return [
            {n: b[n] for n in final_names} for b in source.blocks()
        ]

    plans = _plan_segments(source, nodes, final_names)

    from ..config import get_config

    # the escape hatch is honored at FORCE time too: a chain recorded
    # while fusion was on still executes per-stage when the user turns
    # plan_fusion off before forcing (the knob exists to rule fusion
    # out — it must rule it out for already-built frames as well)
    fusion_on = bool(get_config().plan_fusion)
    fp = None
    if fusion_on and _stats.reopt_enabled():
        # every adaptive execution fingerprints now (not just join
        # runs): the per-stage profile EXPLAIN ANALYZE reads back is
        # keyed here, and the hash is a few node signatures — cheap
        # next to any dispatch
        fp = _stats.chain_fingerprint(source, nodes)
        # the frame drops its plan chain at force time (buffer-pinning
        # discipline), so EXPLAIN ANALYZE needs the fingerprint stashed
        # on the frame itself to find this execution's profile later
        f_res = node.frame()
        if f_res is not None:
            try:
                f_res._plan_fp = fp
            except AttributeError:  # pragma: no cover - exotic frames
                pass
    prof = _profile_push() if fp else None
    t_exec = time.perf_counter()
    try:
        # strategy-wall observations inside this dispatch attribute to
        # THIS pipeline (fingerprint prefix) as well as the host-global
        # table: per-workload keying, ISSUE 18 (v2 sidecar format)
        with _stats.workload_scope(fp[:12] if fp else None):
            with ir.lowering():
                cur = _execute_plans(source, plans, fusion_on, fp)
            out = [{n: b[n] for n in final_names} for b in cur.blocks()]
    finally:
        entries = _profile_pop(prof) if prof is not None else None
    wall = time.perf_counter() - t_exec
    if fp:
        _stats.record_execution(fp, wall_s=wall, profile=entries)
    if _events.TRACER.enabled:
        args = {"segments": len(plans)}
        if fp:
            args["fp"] = fp
        _events.TRACER.emit_complete(
            "plan.execute", t_exec, wall, args=args, cat="plan",
        )
    return out


# ---------------------------------------------------------------------------
# whole-pipeline epilogues: aggregate / reduce fused onto the map chain
# ---------------------------------------------------------------------------

def _value_dtype(plan: SegmentPlan, schema, name: str):
    """np dtype of value column ``name`` as the fused run produces it:
    a stage output's spec dtype when computed, else the (demotion-
    aware) source column dtype."""
    from .. import dtypes as dt

    for n in plan.included:
        for o in (n.program.outputs or []):
            if o.name == name:
                return np.dtype(o.dtype.np_dtype)
    col = schema[name]
    d = dt.demote(col.dtype) if dt.demotion_active() else col.dtype
    return np.dtype(d.np_dtype)


def _compose_with_epilogue(
    plan: SegmentPlan,
    schema,
    value_names: Sequence[str],
    cache_key: tuple,
    extra_specs: Sequence,
    epilogue,
    extra_pinned: tuple = (),
):
    """The shared compose-and-cache core of every epilogue builder:
    demotion-aware input specs over the segment's source inputs plus
    the pass-through value columns (plus any ``extra_specs``, e.g. the
    segment-id slice), the fused-Program cache lookup/insert with
    pinned-identity validation (stage programs + ``extra_pinned``, so
    id() reuse can never alias a stale entry), and the stage-threading
    function body. ``epilogue(env)`` maps the post-stage column
    environment to the program outputs."""
    import jax

    from .. import dtypes as dt
    from ..program import Program, TensorSpec, analyze_program

    in_names = list(plan.source_inputs)
    for x in value_names:
        if x in plan.pass_through and x not in in_names:
            in_names.append(x)
    demote = dt.demotion_active()
    in_specs = []
    for name in in_names:
        col = schema[name]
        dtype = dt.demote(col.dtype) if demote else col.dtype
        in_specs.append(TensorSpec(name, dtype, col.block_shape))
    in_specs.extend(extra_specs)

    key = (
        cache_key,
        tuple((id(n.program), n.rows, n.out_names) for n in plan.included),
        tuple((s.name, s.dtype.name, tuple(s.shape.dims)) for s in in_specs),
        bool(demote),
    )
    pinned_expect = tuple(n.program for n in plan.included) + tuple(
        extra_pinned
    )
    with _CACHE_LOCK:
        hit = _FUSED_CACHE.get(key)
        if hit is not None:
            fused, pinned = hit
            if len(pinned) == len(pinned_expect) and all(
                p is q for p, q in zip(pinned, pinned_expect)
            ):
                _FUSED_CACHE.move_to_end(key)
                return fused

    stages = [
        (jax.vmap(n.program.fn) if n.rows else n.program.fn,
         tuple(n.program.input_names), tuple(n.out_names))
        for n in plan.included
    ]

    def fn(feeds: Dict[str, object]) -> Dict[str, object]:
        env = dict(feeds)
        for stage_fn, s_ins, s_outs in stages:
            outs_ = stage_fn({k: env[k] for k in s_ins})
            for k2 in s_outs:
                env[k2] = outs_[k2]
        return epilogue(env)

    fused = analyze_program(Program(fn, in_specs))
    with _CACHE_LOCK:
        _FUSED_CACHE[key] = (fused, pinned_expect)
        while len(_FUSED_CACHE) > _FUSED_CACHE_MAX:
            _FUSED_CACHE.popitem(last=False)
    return fused


def _fused_agg_program(plan: SegmentPlan, schema, seg_info, num_segments):
    """Compose the segment's map stages with a segment-reduce epilogue
    into ONE block-level Program: inputs are the stages' source columns,
    any pass-through value columns, and the per-block ``__tftpu_seg__``
    id slice; outputs are the ``[K, ...]`` partial tables (plus a count
    table per mean). Cached by stage identity + op set + K, like the
    plain fused-map Programs."""
    import jax
    import jax.numpy as jnp

    from .. import dtypes as dt
    from ..ops.segment import segment_sum as _segment_sum
    from ..program import TensorSpec
    from ..shape import Shape, Unknown

    ops = tuple((x, op) for x, op, _ in seg_info)
    K = int(num_segments)

    def epilogue(env: Dict[str, object]) -> Dict[str, object]:
        sids = env.pop("__tftpu_seg__")
        outs: Dict[str, object] = {}
        for x, op in ops:
            v = env[x]
            if op in ("reduce_sum", "reduce_mean"):
                outs[x] = _segment_sum(v, sids, num_segments=K)
                if op == "reduce_mean":
                    outs["__cnt__" + x] = jax.ops.segment_sum(
                        jnp.ones(v.shape[:1], v.dtype), sids,
                        num_segments=K,
                    )
            elif op == "reduce_min":
                outs[x] = jax.ops.segment_min(v, sids, num_segments=K)
            else:  # reduce_max (callers gate the op set)
                outs[x] = jax.ops.segment_max(v, sids, num_segments=K)
        return outs

    return _compose_with_epilogue(
        plan, schema,
        value_names=[x for x, _, _ in seg_info],
        cache_key=("agg", ops, K),
        extra_specs=[TensorSpec("__tftpu_seg__", dt.int32,
                                Shape((Unknown,)))],
        epilogue=epilogue,
    )


def _epilogue_value_bytes(
    plan: SegmentPlan, schema, seg_info, n_rows: int
) -> int:
    """Estimated bytes of the mapped value columns (the concat
    epilogue's device-residency cost; Unknown inner dims skipped so the
    estimate never overclaims)."""
    from ..shape import Unknown

    total = 0
    for x, _, _ in seg_info:
        try:
            dims = list(schema[x].cell_shape.dims)
        except KeyError:
            dims = []
        if any(d == Unknown for d in dims):
            continue
        cell = 1
        for d in dims:
            cell *= int(d)
        total += n_rows * cell * _value_dtype(plan, schema, x).itemsize
    return total


def execute_aggregate(node: ir.PlanNode) -> List[Dict[str, object]]:
    """Force a plan-recorded keyed aggregate: fuse the upstream map
    chain with a segment-reduce epilogue (strategy chosen by the cost
    model), or fall back honestly — the per-stage chain replay plus the
    eager host aggregate, counted by reason. The mapped value columns
    are never host-materialized on any fused path."""
    from ..config import get_config

    adaptive = bool(get_config().plan_fusion) and _stats.reopt_enabled()
    prof = _profile_push() if adaptive else None
    try:
        return _execute_aggregate(node, prof)
    finally:
        if prof is not None:
            _profile_pop(prof)


def _execute_aggregate(
    node: ir.PlanNode, prof: Optional[list]
) -> List[Dict[str, object]]:
    """``execute_aggregate``'s body. The wrapper owns the profile
    frame; the record sites here pop it (idempotently) so the per-stage
    profile lands in the same sidecar write as the aggregate's stats."""
    import jax.numpy as jnp

    from ..config import get_config
    from ..frame import _block_num_rows
    from ..ops.keys import frame_group_ids
    from ..ops.verbs import _empty_agg_blocks, _segment_reduce_best

    t_exec = time.perf_counter()
    source, nodes = ir.resolve_chain(node)
    inner = [n for n in nodes if n is not node]
    keys = list(node.keys)
    out_names = list(node.out_names)
    seg_info = list(node.spec)
    need = list(dict.fromkeys(keys + out_names))
    fusion_on = bool(get_config().plan_fusion)

    def host_fallback(frame, reason: Optional[str]) -> List[Dict[str, object]]:
        """Chain already executed into ``frame``; run the eager host
        epilogue over it (bit-identical to TFTPU_FUSION=0)."""
        if reason is not None:
            c = _FALLBACKS.get(reason)
            if c is not None:
                c.inc()
            f = node.frame()
            if f is not None and reason in (
                "computed_key", "ragged", "host_callback"
            ):
                ir.mark_unfused(f, "aggregate", {
                    "computed_key": "group key is computed by a chained "
                                    "stage (group by a source column, or "
                                    "materialize the chain first)",
                    "ragged": "value column holds ragged cells (run "
                              "analyze() to densify)",
                    "host_callback": "a chained stage contains a host "
                                     "callback (keep callbacks out of "
                                     "aggregated chains)",
                }[reason])
        if frame.num_rows == 0:
            return _empty_agg_blocks(node.schema)
        from ..ops.verbs import _host_fast_aggregate

        out_key_cols, out_cols, _n = _host_fast_aggregate(
            node.program, frame, keys, seg_info, out_names
        )
        block = dict(out_key_cols)
        block.update({x: out_cols[x] for x in out_names})
        profiling.record(
            "aggregate", time.perf_counter() - t_exec, _n
        )
        return [block]

    with ir.lowering():
        if not inner:
            return host_fallback(source, None)
        plans = _plan_segments(source, inner, need)
        adaptive = fusion_on and _stats.reopt_enabled()
        fp = _stats.chain_fingerprint(source, nodes) if adaptive else None
        if fp:
            f_fp = node.frame()
            if f_fp is not None:
                try:
                    f_fp._plan_fp = fp
                except AttributeError:  # pragma: no cover
                    pass

        # ---- aggregate pushdown below a trailing join chain (the
        # ISSUE 14 rewrite): eligible shapes run the partial aggregate
        # BELOW the join(s) and filter whole groups above — rows never
        # match-expand. Ineligible shapes keep today's path, counted,
        # with the fixable causes recorded as TFG110 evidence. --------
        if adaptive and plans[-1].has_join:
            push, misses = _rules.plan_pushdown(
                plans, keys, seg_info, node.schema
            )
            if push is None:
                if misses:
                    f_res = node.frame()
                    if f_res is not None:
                        for m in misses:
                            ir.mark_pushdown_miss(f_res, m)
                    _note_decision(_rules.Decision(
                        "pushdown_ineligible", misses[0]["detail"],
                        {"cause": misses[0]["cause"]},
                    ))
            else:
                rec = _stats.lookup(fp)
                do_push, decision, used_stats = _rules.decide_pushdown(
                    push, rec
                )
                if used_stats:
                    _note_reoptimized(
                        "pushdown choice informed by observed row "
                        "survival through the joins (stats sidecar)",
                        {"decision": decision.kind},
                    )
                if do_push:
                    mid_p = _execute_plans(
                        source, plans[:push.start], fusion_on, fp
                    )
                    blocks = _pushdown_aggregate(
                        mid_p, plans, push, node, seg_info, fusion_on,
                        fp, decision, t_exec, prof,
                    )
                    if blocks is not None:
                        return blocks
                    # runtime-ineligible (duplicate build keys, ragged
                    # cells): finish exactly as the static path would,
                    # from the already-computed prefix
                    cur = _execute_plans(
                        mid_p, plans[push.start:-1], fusion_on, fp
                    )
                    cur = _run_one_segment(cur, plans[-1], fusion_on)
                    return host_fallback(cur, None)
                _note_decision(decision)  # pushdown_skipped_selective

        mid = _execute_plans(source, plans[:-1], fusion_on, fp)
        last = plans[-1]

        reason = None
        if not fusion_on or last.has_join or last.has_filter or not last.included:
            # join/filter-tailed pipelines run their tail through the
            # plan (probe-side maps fused, pushdown applied) and apply
            # the segment epilogue DIRECTLY on the tail's output — no
            # user-visible intermediate frame ever exists, but the
            # epilogue itself dispatched separately, so it does NOT
            # count as fused (the join/filter tail already recorded its
            # own in-plan execution). A bare pass-through tail (or the
            # escape hatch) likewise takes the eager epilogue; none of
            # these are fallbacks to count either.
            cur = _run_one_segment(mid, last, fusion_on)
            return host_fallback(cur, None)
        computed = set()
        for n in last.included:
            computed |= set(n.out_names)
        if any(k in computed for k in keys):
            reason = "computed_key"
        elif any(
            ir.program_has_callback(n.program) for n in last.included
        ):
            reason = "host_callback"
        elif _segment_ragged(mid, last.source_inputs):
            reason = "ragged"
        if reason is not None:
            cur = _run_one_segment(mid, last, fusion_on)
            return host_fallback(cur, reason)

        # ---- fused epilogue -------------------------------------------
        t0 = time.perf_counter()
        src_cols = [
            n for n in mid.schema.names
            if n in set(last.source_inputs) | set(last.pass_through)
        ]
        pruned = _pruned_source(mid, src_cols)
        blocks = pruned.blocks()
        rows = [_block_num_rows(b) for b in blocks]
        n_total = sum(rows)
        if n_total == 0:
            return _empty_agg_blocks(node.schema)
        # group ids encode ONCE from the (cached) key dictionary —
        # steady-state repeated aggregates skip the re-encode entirely
        seg_ids, group_key_cols, num_groups = frame_group_ids(mid, keys)

        ops_key = tuple((x, op) for x, op, _ in seg_info)
        # feedback: a recurring aggregate's observed group counts warm
        # the segment-bucket history, so a fresh process that
        # historically saw K proliferate buckets on its FIRST force
        # instead of re-learning (and re-tracing) per distinct count
        rec_agg = _stats.lookup(fp) if fp else None
        if rec_agg:
            hist = (rec_agg.get("agg") or {}).get("counts") or []
            if hist:
                _rules.warm_segment_bucket(ops_key, hist)
                _note_reoptimized(
                    "segment-bucket history warm-started from observed "
                    "group counts (stats sidecar)",
                    {"counts": [int(c) for c in hist]},
                )
        ops_and_dtypes = [
            (op, _value_dtype(last, pruned.schema, x))
            for x, op, _ in seg_info
        ]
        decision = _rules.decide_epilogue(
            ops_and_dtypes, num_groups,
            _epilogue_value_bytes(last, pruned.schema, seg_info, n_total),
            observed_walls=_stats.strategy_walls("epilogue"),
        )
        _note_decision(decision)
        _note_flip(decision)
        k_eff, bucket_dec = _rules.decide_segment_bucket(
            ops_key, num_groups
        )
        if bucket_dec is not None:
            _note_decision(bucket_dec)

        from ..ops.executor import gather_feeds

        lower_dt = 0.0
        try:
            if decision.kind == "epilogue_per_block":
                fused = _fused_agg_program(
                    last, pruned.schema, seg_info, k_eff
                )
                lower_dt = time.perf_counter() - t0
                _LOWER_SECONDS.observe(lower_dt)
                compiled = fused.compiled()
                base_ins = [
                    s.name for s in fused.inputs
                    if s.name != "__tftpu_seg__"
                ]
                partials = []
                off = 0
                for b, nb in zip(blocks, rows):
                    if nb == 0:
                        continue
                    feeds = gather_feeds(b, base_ins, fused)
                    feeds["__tftpu_seg__"] = np.ascontiguousarray(
                        seg_ids[off:off + nb], dtype=np.int32
                    )
                    off += nb
                    partials.append(
                        compiled.run_block(feeds, to_numpy=False)
                    )
                totals = dict(partials[0])
                for p in partials[1:]:
                    for x, op in ops_key:
                        if op in ("reduce_sum", "reduce_mean"):
                            totals[x] = totals[x] + p[x]
                            if op == "reduce_mean":
                                cx = "__cnt__" + x
                                totals[cx] = totals[cx] + p[cx]
                        elif op == "reduce_min":
                            totals[x] = jnp.minimum(totals[x], p[x])
                        else:
                            totals[x] = jnp.maximum(totals[x], p[x])
                out_cols = {}
                for x, op in ops_key:
                    v = totals[x]
                    if op == "reduce_mean":
                        c = totals["__cnt__" + x]
                        c = c.reshape((-1,) + (1,) * (v.ndim - 1))
                        v = (v / c).astype(totals[x].dtype)
                    out_cols[x] = np.asarray(v)[:num_groups]
            else:
                # concat epilogue: fused map per block, outputs stay on
                # device, ONE segment dispatch over the concatenation —
                # the exact program + row order of the unfused path
                from .. import dtypes as dt

                parts: Dict[str, list] = {x: [] for x, _, _ in seg_info}
                if last.included:
                    fused_map = _fused_program(last, pruned.schema)
                    lower_dt = time.perf_counter() - t0
                    _LOWER_SECONDS.observe(lower_dt)
                    compiled = fused_map.compiled()
                    for b, nb in zip(blocks, rows):
                        if nb == 0:
                            continue
                        feeds = gather_feeds(
                            b, fused_map.input_names, fused_map
                        )
                        outs = compiled.run_block(feeds, to_numpy=False)
                        for x in last.computed_names:
                            if x in parts:
                                parts[x].append(outs[x])
                seg_vals = {}
                demote = dt.demotion_active()
                for x, _, _ in seg_info:
                    if parts[x]:
                        seg_vals[x] = (
                            parts[x][0] if len(parts[x]) == 1
                            else jnp.concatenate(parts[x])
                        )
                    else:  # pass-through value column, straight off source
                        vals = np.concatenate([
                            np.asarray(b[x]) for b in blocks if len(b[x])
                        ])
                        if demote:
                            tgt = dt.demote(pruned.schema[x].dtype)
                            if vals.dtype != tgt.np_dtype:
                                vals = vals.astype(tgt.np_dtype)
                        seg_vals[x] = jnp.asarray(vals)
                res = _segment_reduce_best(
                    ops_key, k_eff, seg_vals, seg_ids
                )
                out_cols = {
                    x: np.asarray(res[x])[:num_groups] for x, _ in ops_key
                }
        except Exception as e:
            from ..validation import ValidationError

            if isinstance(e, (ValidationError, ValueError)):
                raise
            logger.debug(
                "fused aggregate epilogue failed, replaying eagerly: %s", e
            )
            cur = _run_one_segment(mid, last, fusion_on)
            return host_fallback(cur, "trace_error")

    _FUSED_STAGES.inc(len(last.included))
    _FUSED_EPILOGUES["aggregate"].inc()
    avoided = SegmentPlan(
        nodes=[], included=[], excluded=[], final_names=[],
        computed_names=[], pass_through=[], source_inputs=[],
        mask_name=None,
        avoided_outputs=[
            (o.name, o)
            for n in last.included for o in (n.program.outputs or [])
        ],
    )
    _BYTES_AVOIDED.inc(_avoided_bytes(avoided, blocks))
    block = dict(zip(keys, group_key_cols))
    block.update({x: out_cols[x] for x in out_names})
    profiling.record("aggregate", time.perf_counter() - t_exec, n_total)
    ep_wall = time.perf_counter() - t0
    observe_strategy_wall("epilogue", decision.kind, ep_wall)
    _profile_note(
        "aggregate", ep_wall, rows=n_total, strategy=decision.kind,
        compile_s=lower_dt,
    )
    if fp:
        _stats.record_execution(
            fp, agg={"num_groups": int(num_groups)},
            wall_s=time.perf_counter() - t_exec,
            profile=_profile_pop(prof) if prof is not None else None,
        )
    if _events.TRACER.enabled:
        _events.TRACER.emit_complete(
            "plan.execute", t_exec, time.perf_counter() - t_exec,
            args={"segments": len(plans), "verb": "aggregate",
                  "epilogue": decision.kind}, cat="plan",
        )
    return [block]


def _pushdown_aggregate(
    mid, plans: Sequence[SegmentPlan], push, node, seg_info,
    fusion_on: bool, fp: Optional[str], decision, t_exec: float,
    prof: Optional[list] = None,
) -> Optional[List[Dict[str, object]]]:
    """Execute an eligible aggregate-below-join rewrite: the partial
    aggregate runs over the pushed side's full row set (maps fused, one
    segment-reduce dispatch), and each pushed inner join degenerates to
    a whole-group semi-join filter over the partial tables — rows never
    match-expand through the join, and the build sides force only their
    key columns (pure build-side value stages never compute; callback
    stages still execute via the select path's keep rule).

    Bit-identity holds by construction: group encoding is lexicographic
    (row-order independent), a group's join key is functionally
    determined by the group (keys ⊆ group keys), build keys are unique
    (m=1 — verified here, BEFORE any probe-side stage runs, so the
    static fallback never replays a stage), and every (op, dtype) is
    reassoc-safe, making per-group partials exact whatever the backend.

    Returns the result blocks, or None when a runtime condition fails —
    the caller then finishes on the static path, counted."""
    from ..frame import _merged_global_columns
    from ..ops.keys import frame_group_ids, group_ids
    from ..ops.verbs import (
        _demote_cast,
        _empty_agg_blocks,
        _segment_reduce_best,
    )

    keys = list(node.keys)
    out_names = list(node.out_names)
    ops_key = tuple((x, op) for x, op, _ in seg_info)
    base_plan = _strip_join(plans[push.start])

    def runtime_miss(cause: str, subject: str, detail: str, fix: str):
        f_res = node.frame()
        if f_res is not None:
            ir.mark_pushdown_miss(f_res, {
                "cause": cause, "subject": subject, "detail": detail,
                "fix": fix,
            })
        _note_decision(_rules.Decision(
            "pushdown_ineligible", detail, {"cause": cause},
        ))

    level_keys: List[Optional[Dict[str, object]]] = [None] * len(
        push.levels
    )
    if push.side == "left":
        # a host callback in a build-side chain bars the rewrite: the
        # key-column force here plus a later runtime fallback's full
        # force would run the callback twice (a pure build chain just
        # recomputes — cheap and side-effect free)
        for lev in push.levels:
            right = plans[lev.plan_index].join_node.right
            rnode = getattr(right, "_plan", None)
            if rnode is not None and not right.is_materialized:
                _, rnodes = ir.resolve_chain(rnode)
                if any(
                    n.kind == "map"
                    and ir.program_has_callback(n.program)
                    for n in rnodes
                ):
                    runtime_miss(
                        "build_callback", "+".join(lev.spec.keys),
                        "a build-side stage contains a host callback; "
                        "the pushdown's key-only force plus a runtime "
                        "fallback would execute it twice",
                        "keep host callbacks out of joined build "
                        "chains, or materialize the build side first",
                    )
                    return None
        # force every pushed build side down to its key columns and
        # verify m=1 BEFORE any probe-side stage runs (the fallback
        # must never replay a stage — callbacks execute exactly once);
        # innermost level first, matching the static path's forcing
        # order for build-side effects
        for li in range(len(push.levels) - 1, -1, -1):
            lev = push.levels[li]
            spec = lev.spec
            right = plans[lev.plan_index].join_node.right
            kcols = list(spec.keys)
            with ir.allow_planning():
                rsel = (
                    right.select(kcols)
                    if list(right.schema.names) != kcols else right
                )
                rcols = _merged_global_columns(rsel, kcols, "join")
            if not _keys_unique(rcols, spec.keys):
                runtime_miss(
                    "duplicate_build_keys", "+".join(spec.keys),
                    f"build side of the join on {list(spec.keys)} has "
                    "duplicate keys — m>1 matches scale group partials "
                    "and bar the whole-group rewrite",
                    "drop_duplicates the build side on its join keys, "
                    "or accept the aggregate-above path",
                )
                return None
            level_keys[li] = rcols
        B = _run_one_segment(mid, base_plan, fusion_on)
    else:  # side == 'right': aggregate the build frame below the join
        lev = push.levels[0]
        spec = lev.spec
        jn = plans[lev.plan_index].join_node
        right = jn.right
        # a callback anywhere the fallback would replay (probe maps) or
        # the pushed side would force twice bars the rewrite outright
        if any(
            ir.program_has_callback(n.program)
            for n in base_plan.included
        ):
            runtime_miss(
                "probe_callback", "+".join(spec.keys),
                "a probe-side stage contains a host callback; a "
                "runtime fallback after running it would execute the "
                "callback twice",
                "keep host callbacks out of aggregated join chains",
            )
            return None
        for k in spec.keys:
            if jn.schema[k].dtype.name != right.schema[k].dtype.name:
                runtime_miss(
                    "key_dtype_mismatch", k,
                    f"join key {k!r} has dtype "
                    f"{jn.schema[k].dtype.name} on the probe side but "
                    f"{right.schema[k].dtype.name} on the build side — "
                    "the output key column comes from the probe side",
                    "cast the key columns to one dtype before joining",
                )
                return None
        # probe side runs its maps (keys only — plan_segment pruned the
        # probe requirement down to the join keys), then m=1 check
        B_left = _run_one_segment(mid, base_plan, fusion_on)
        lkcols = _merged_global_columns(
            B_left, list(spec.keys), "join"
        )
        if not _keys_unique(lkcols, spec.keys):
            runtime_miss(
                "duplicate_build_keys", "+".join(spec.keys),
                f"probe side of the join on {list(spec.keys)} has "
                "duplicate keys — each build row would repeat once per "
                "matching probe row",
                "drop_duplicates the probe side on its join keys, or "
                "accept the aggregate-above path",
            )
            return None
        level_keys[0] = lkcols
        rneed = list(dict.fromkeys(
            list(push.key_base) + list(push.val_base.values())
        ))
        with ir.allow_planning():
            B = (
                right.select(rneed)
                if list(right.schema.names) != rneed else right
            )
            B.blocks()

    if B.num_rows == 0:
        _note_decision(decision)
        profiling.record("aggregate", time.perf_counter() - t_exec, 0)
        return _empty_agg_blocks(node.schema)

    # partial aggregate over the pushed side's full row set: cached key
    # encode + ONE segment-reduce dispatch (backend per the cost model)
    seg_ids, group_key_cols, num_groups = frame_group_ids(
        B, push.key_base
    )
    val_cols = {}
    for x in out_names:
        vals = B.column_values(push.val_base[x])
        if vals.dtype == object:
            # the unrewritten path raises identically for ragged value
            # cells — same contract, same wording
            raise ValueError(
                f"Column {push.val_base[x]!r} is ragged; aggregate "
                "requires uniform cells (run analyze() first)."
            )
        val_cols[x] = _demote_cast(
            vals, node.program.input(f"{x}_input")
        )
    out_cols = _segment_reduce_best(
        ops_key, num_groups, val_cols, seg_ids
    )

    # each pushed inner join = a whole-group semi-join filter (the
    # lexicographic group order is row-order independent, so the
    # surviving groups keep exactly the unrewritten output order)
    mask = np.ones(num_groups, dtype=bool)
    for lev, rcols in zip(push.levels, level_keys):
        if lev.how != "inner":
            continue  # left joins keep every group
        g_arrays = [
            group_key_cols[keys.index(fin)] for fin in lev.key_finals
        ]
        r_arrays = [rcols[k] for k in lev.spec.keys]
        codes, _, _ = group_ids(_union_key_arrays(g_arrays, r_arrays))
        mask &= np.isin(codes[:num_groups], codes[num_groups:])

    n_base = int(len(seg_ids))
    counts = np.bincount(seg_ids, minlength=num_groups)
    surviving_rows = int(counts[mask].sum())
    survival = (surviving_rows / n_base) if n_base else 1.0
    _note_decision(dataclasses.replace(decision, details={
        **decision.details,
        "num_groups": int(num_groups),
        "groups_kept": int(mask.sum()),
        "base_rows": n_base,
        "survival": round(survival, 4),
    }))
    _profile_note(
        "pushdown", time.perf_counter() - t_exec, rows=n_base,
        strategy="pushdown_below_join",
    )
    if fp:
        _stats.record_execution(
            fp,
            push={"survival": round(survival, 6),
                  "levels": len(push.levels)},
            agg={"num_groups": int(num_groups)},
            wall_s=time.perf_counter() - t_exec,
            profile=_profile_pop(prof) if prof is not None else None,
        )
    if not mask.any():
        profiling.record(
            "aggregate", time.perf_counter() - t_exec, n_base
        )
        return _empty_agg_blocks(node.schema)
    surv = np.flatnonzero(mask)
    block: Dict[str, object] = {}
    for i, fin in enumerate(keys):
        block[fin] = group_key_cols[i][surv]
    for x in out_names:
        block[x] = np.asarray(out_cols[x])[surv]
    profiling.record("aggregate", time.perf_counter() - t_exec, n_base)
    if _events.TRACER.enabled:
        _events.TRACER.emit_complete(
            "plan.execute", t_exec, time.perf_counter() - t_exec,
            args={"segments": len(plans), "verb": "aggregate",
                  "epilogue": "pushdown_below_join"}, cat="plan",
        )
    return [block]


def pushdown_misses(frame) -> List[dict]:
    """TFG110 evidence for ``lint_plan``: the fixable causes blocking
    an aggregate-below-join pushdown on ``frame`` — the static
    eligibility walk re-run over the recorded plan (pure; never forces
    the frame, same contract as ``chain_barriers``) plus any runtime
    causes the lowering recorded via ``ir.mark_pushdown_miss``
    (duplicate build-side keys are only discoverable at force time)."""
    out = list(ir.pushdown_miss_log(frame))
    node = getattr(frame, "_plan", None)
    if node is None or node.kind != "aggregate":
        return out
    source, nodes = ir.resolve_chain(node)
    inner = [n for n in nodes if n is not node]
    if not inner or not any(n.kind == "join" for n in inner):
        return out
    keys = list(node.keys)
    need = list(dict.fromkeys(keys + list(node.out_names)))
    try:
        plans = _plan_segments(source, inner, need)
        if not plans or not plans[-1].has_join:
            return out
        push, misses = _rules.plan_pushdown(
            plans, keys, list(node.spec), node.schema
        )
    except Exception:  # pragma: no cover - lint must never raise
        return out
    if push is None:
        seen = {(m.get("cause"), m.get("subject")) for m in out}
        out.extend(
            m for m in misses
            if (m.get("cause"), m.get("subject")) not in seen
        )
    return out


def estimate_materialized_bytes(frame) -> Optional[int]:
    """Host-byte estimate of materializing ``frame``: ``estimated_rows``
    (never forces a lazy chain) × the schema's dense per-row width.
    Unknown cell dims count as 1 and host columns as a pointer-sized
    cell — a deliberate LOWER bound, so TFG111's larger-than-budget
    finding never fires on an estimate that could legitimately be
    smaller. None when the row count is unknowable pre-force."""
    rows = frame.estimated_rows
    if rows is None:
        return None
    per_row = 0
    for info in frame.schema:
        if info.is_device:
            elems = 1
            for d in info.cell_shape.dims:
                if isinstance(d, int):
                    elems *= max(1, d)
            per_row += elems * np.dtype(info.dtype.np_dtype).itemsize
        else:
            per_row += 8
    return int(rows) * per_row


def oversized_materializations(frame) -> List[dict]:
    """TFG111 evidence for ``lint_plan``: forced ``to_host``/
    ``to_numpy`` materializations on ``frame``'s chain whose estimated
    bytes exceed the block-store budget
    (``config.block_budget_bytes`` / ``TFTPU_BLOCK_BUDGET_MB``) — the
    workload the streaming partitioner exists for. Checks the frame
    itself and its chain source (the two places ``ir.mark_barrier``
    records the materialization); pure, never forces a lazy frame."""
    from ..config import get_config

    budget = get_config().block_budget_bytes
    if budget <= 0:
        return []
    out: List[dict] = []
    node = getattr(frame, "_plan", None)
    source = ir.resolve_chain(node)[0] if node is not None else None
    seen = set()
    for f in (frame, source):
        if f is None or id(f) in seen:
            continue
        seen.add(id(f))
        reason = getattr(f, "_fusion_barrier", None)
        if not reason or "to_host" not in str(reason):
            continue
        est = estimate_materialized_bytes(f)
        if est is None or est <= budget:
            continue
        out.append({
            "reason": str(reason),
            "estimated_bytes": int(est),
            "budget_bytes": int(budget),
            "rows": int(f.estimated_rows or 0),
        })
    return out


def lower_reduce(
    frame, program, out_names: Sequence[str], mode: str
) -> Optional[tuple]:
    """Fuse a whole-frame reduce onto ``frame``'s recorded map chain:
    one composed Program per block computes the chained stages AND the
    reduce epilogue (the reduce program applied block-level for
    ``reduce_blocks``; the pairwise lax.scan fold for ``reduce_rows``),
    so the mapped columns are never materialized. Returns
    ``(per_block_partials, input_rows)`` for the verbs' unchanged
    combine step (the row count rides along so the caller's profiling
    span never forces the still-lazy frame), or None when the chain is
    ineligible (no plan, barriers, multi-process feeds — sharded
    single-process chains ARE eligible since ISSUE 10) — the caller
    then takes the eager path, which forces the frame through the
    ordinary plan lowering."""
    import jax

    if getattr(frame, "_plan", None) is None or not ir.fusion_enabled():
        return None
    if frame.is_materialized:
        return None
    # Sharded chains fuse too (ISSUE 10): the fused per-block Program
    # dispatches through the unified AOT path, so a sharded feed is an
    # ordinary dispatch — XLA SPMD computes the reduce across the mesh
    # and the partial that reaches the host combine is block-sized.
    # Multi-process fleets still take the eager path: the combine step
    # below host-gathers per-block partials, and a rank cannot asarray
    # a non-addressable global partial (data-plane limit, not dispatch
    # eligibility — ROADMAP #4's out-of-core combine owns it).
    if jax.process_count() > 1:
        return None
    # record the epilogue on the IR (branch bookkeeping included: a
    # later consumer of the same lazy frame re-sources on it, so the
    # shared prefix materializes once instead of refusing per branch)
    node = ir.PlanNode(
        "reduce",
        parent=ir.node_for_parent(frame),
        program=program,
        out_names=list(out_names),
        spec=mode,
        schema=frame.schema,
    )
    node._extended = True  # terminal: nothing chains on a reduce
    source, nodes = ir.resolve_chain(node)
    inner = [n for n in nodes if n is not node]
    if not inner or any(n.kind not in ("map", "select") for n in inner):
        return None
    plan = plan_segment(inner, list(out_names), source.schema.names)
    if not plan.included:
        return None
    if any(ir.program_has_callback(n.program) for n in plan.included):
        _FALLBACKS["host_callback"].inc()
        return None
    src_cols = [
        n for n in source.schema.names
        if n in set(plan.source_inputs) | set(plan.pass_through)
    ]
    pruned = _pruned_source(source, src_cols)
    if _segment_ragged(pruned, plan.source_inputs):
        _FALLBACKS["ragged"].inc()
        return None

    t0 = time.perf_counter()
    fused = _fused_reduce_program(plan, pruned.schema, program,
                                  list(out_names), mode)
    _LOWER_SECONDS.observe(time.perf_counter() - t0)
    from ..frame import _block_num_rows
    from ..ops.executor import gather_feeds

    compiled = fused.compiled()
    partials: List[Dict[str, np.ndarray]] = []
    blocks = pruned.blocks()
    n_rows = 0
    try:
        for b in blocks:
            nb = _block_num_rows(b)
            if nb == 0:
                continue
            n_rows += nb
            feeds = gather_feeds(b, fused.input_names, fused)
            res = compiled.run_block(feeds, to_numpy=False)
            partials.append({x: np.asarray(res[x]) for x in out_names})
    except Exception as e:
        from ..validation import ValidationError

        if isinstance(e, (ValidationError, ValueError)):
            raise
        logger.debug("fused reduce failed, replaying eagerly: %s", e)
        _FALLBACKS["trace_error"].inc()
        return None
    if not partials:
        return None  # all-empty frame: the eager path owns the error
    _FUSED_STAGES.inc(len(plan.included))
    _FUSED_EPILOGUES["reduce_" + mode].inc()
    _profile_note(
        "reduce", time.perf_counter() - t0, rows=n_rows,
        strategy="fused_" + mode,
        compile_s=None,
    )
    avoided = [
        (o.name, o)
        for n in plan.included for o in (n.program.outputs or [])
    ]
    plan_for_bytes = SegmentPlan(
        nodes=[], included=[], excluded=[], final_names=[],
        computed_names=[], pass_through=[], source_inputs=[],
        mask_name=None, avoided_outputs=avoided,
    )
    _BYTES_AVOIDED.inc(_avoided_bytes(plan_for_bytes, blocks))
    return partials, n_rows


def _fused_reduce_program(
    plan: SegmentPlan, schema, reduce_program, out_names: List[str],
    mode: str,
):
    """Compose map stages with a reduce epilogue into one block-level
    Program: ``blocks`` mode applies the reduce program's function to
    the chained columns under the ``x_input`` naming contract;
    ``rows`` mode applies the SAME pairwise lax.scan fold the eager
    reduce_rows runs (executor.pair_fold_body), so fold semantics
    cannot diverge. Cached by stage + reduce-program identity."""
    value_names = list(out_names)
    if mode == "rows":
        from ..ops.executor import pair_fold_body

        fold = pair_fold_body(reduce_program, value_names)

        def epilogue(env):
            return fold({x: env[x] for x in value_names})
    else:
        def epilogue(env):
            outs = reduce_program.fn(
                {f"{x}_input": env[x] for x in value_names}
            )
            return {x: outs[x] for x in value_names}

    return _compose_with_epilogue(
        plan, schema,
        value_names=value_names,
        cache_key=("reduce", mode, id(reduce_program), tuple(out_names)),
        extra_specs=[],
        epilogue=epilogue,
        extra_pinned=(reduce_program,),
    )


# ---------------------------------------------------------------------------
# incremental aggregate maintenance (ISSUE 20): per-chunk partial
# tables folded into the full aggregate. The eligibility gate
# (rules.incremental_fold_safe per (op, out dtype), pass-through group
# keys, no joins, no host callbacks) lives with the registered-query
# endpoint; THIS is the fold itself — plain host arithmetic, because
# every admitted (op, dtype) pair is exactly associative/commutative
# (int/bool sums are modular adds; min/max are order-free), so the
# fold is bit-identical to one aggregation over the whole table BY
# CONSTRUCTION, not by tolerance.
# ---------------------------------------------------------------------------

def _table_rows(table: Dict[str, object]) -> int:
    for v in table.values():
        return len(v)
    return 0


def _key_scalar(v):
    """Dict-key form of one group-key cell (numpy scalar → python)."""
    return v.item() if isinstance(v, np.generic) else v


def canonical_table_order(table: Dict[str, object],
                          keys: Sequence[str]) -> Dict[str, object]:
    """Sort an aggregate table's rows by its group-key columns — the
    ONE deterministic row order registered query endpoints serve, so a
    folded refresh, a full recompute, and a ``TFTPU_FUSION=0`` oracle
    run are byte-comparable without caring which order each path
    discovered the groups in. Host sort over python key tuples (group
    counts, not row counts — string keys included); value columns ride
    the same permutation untouched."""
    n = _table_rows(table)
    keycols = [table[k] for k in keys if k in table]
    if n <= 1 or not keycols:
        return dict(table)
    order = sorted(
        range(n),
        key=lambda i: tuple(_key_scalar(c[i]) for c in keycols),
    )
    out: Dict[str, object] = {}
    for name, col in table.items():
        if isinstance(col, list):
            out[name] = [col[i] for i in order]
        else:
            arr = np.asarray(col)
            out[name] = arr[np.asarray(order, dtype=np.intp)]
    return out


#: fold op per admitted reducer — each exactly associative/commutative
#: for every dtype incremental_fold_safe admits.
_FOLD_OPS = {
    "reduce_sum": np.add,
    "reduce_min": np.minimum,
    "reduce_max": np.maximum,
}


def fold_partial_tables(
    partials: Sequence[Dict[str, object]],
    keys: Sequence[str],
    ops: Sequence[Tuple[str, str]],
    schema,
) -> Dict[str, object]:
    """Fold per-chunk aggregate partial tables into the full table.

    ``partials`` are the per-chunk aggregate outputs (each already one
    row per group KEY SEEN IN THAT CHUNK); ``ops`` is the terminal
    aggregate node's ``[(out_name, op)]`` spec (every op a
    ``_FOLD_OPS`` member — the caller's eligibility walk guarantees
    it); ``schema`` the aggregate node's result schema, used to type
    empty outputs. Groups accumulate in a dict keyed by the python key
    tuple; the result comes back in :func:`canonical_table_order`.
    Value dtypes are preserved end to end (partials carry the
    aggregate's own output dtypes; numpy same-dtype arithmetic keeps
    them), so int sums fold modularly exactly like the segment
    reduction they replace."""
    keys = list(keys)
    for out_name, op in ops:
        if op not in _FOLD_OPS:
            raise ValueError(
                f"fold_partial_tables: {out_name!r} uses {op!r}, not a "
                f"foldable reducer {sorted(_FOLD_OPS)} — the "
                "eligibility walk must decline before folding"
            )
    acc: "OrderedDict[tuple, Dict[str, object]]" = OrderedDict()
    key_cells: Dict[tuple, tuple] = {}
    for table in partials:
        n = _table_rows(table)
        if n == 0:
            continue
        kcols = [table[k] for k in keys]
        for i in range(n):
            kt = tuple(_key_scalar(c[i]) for c in kcols)
            row = acc.get(kt)
            if row is None:
                acc[kt] = {
                    out: np.asarray(table[out])[i] for out, _ in ops
                }
                key_cells[kt] = tuple(c[i] for c in kcols)
            else:
                for out, op in ops:
                    row[out] = _FOLD_OPS[op](
                        row[out], np.asarray(table[out])[i]
                    )
    out: Dict[str, object] = {}
    groups = list(acc)
    for j, k in enumerate(keys):
        cells = [key_cells[g][j] for g in groups]
        info = schema[k] if schema is not None and k in schema else None
        np_dtype = getattr(getattr(info, "dtype", None), "np_dtype", None)
        if np_dtype is not None and np.dtype(np_dtype) != object:
            out[k] = np.asarray(cells, dtype=np_dtype)
        else:
            out[k] = np.asarray(cells, dtype=object)
    for out_name, _ in ops:
        cells = [acc[g][out_name] for g in groups]
        if cells:
            out[out_name] = np.stack([np.asarray(c) for c in cells])
        else:
            info = schema[out_name] if schema is not None else None
            np_dtype = getattr(getattr(info, "dtype", None),
                               "np_dtype", np.float64)
            out[out_name] = np.zeros((0,), dtype=np_dtype)
    return canonical_table_order(out, keys)
