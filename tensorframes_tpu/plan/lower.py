"""Lowering: turn a plan chain into (ideally) ONE dispatch per block.

``execute_plan`` is the pending computation of every plan-carrying
frame. It resolves the chain to its effective source, splits it into
segments at filters (:mod:`.rules`), and runs each segment either

* **fused** — the segment's included map stages compose into a single
  :class:`~tensorframes_tpu.program.Program` (map_rows stages enter in
  their vmapped form) that dispatches through the ordinary
  ``map_blocks`` machinery, so the jit cache, input donation, the
  prefetch window, and the sharded paths all apply unchanged; or
* **per-stage fallback** — the exact single-verb execution, taken when
  a runtime barrier shows up (ragged source cells, a host-callback
  stage, a trace failure) or when fusing would not help (a bare single
  map keeps its specialized path, lead-dim bucketing included).

Fused programs are cached by stage identity so steady-state serving
loops (rebuild the chain each batch from the same pre-compiled
Programs) reuse one XLA executable instead of re-tracing per force.

Observability: ``tftpu_plan_*`` metrics are registered at import (the
fused-stages counter, the intermediate-bytes-avoided counter, the
plan-lowering-seconds histogram, and per-reason fallback counters) and
``plan.lower`` / ``plan.execute`` spans land on the structured trace
timeline when tracing is on.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..observability import events as _events
from ..observability.metrics import counter as _counter
from ..observability.metrics import histogram as _histogram
from ..utils import get_logger
from . import ir
from .rules import SegmentPlan, plan_segment, split_segments

logger = get_logger(__name__)

__all__ = ["execute_plan"]

# Registered at import so expositions always carry the plan family
# (a process that never fused reads 0 — the series does not vanish).
_FUSED_STAGES = _counter(
    "tftpu_plan_fused_stages_total",
    "Map stages executed inside a fused (single-dispatch) plan segment",
)
_BYTES_AVOIDED = _counter(
    "tftpu_plan_intermediate_bytes_avoided_total",
    "Bytes of intermediate stage outputs never materialized because the "
    "chain ran fused (consumed in-register or pruned by select pushdown)",
)
_LOWER_SECONDS = _histogram(
    "tftpu_plan_lowering_seconds",
    "Wall-clock of lowering one segment to its fused Program "
    "(cache lookup + composition)",
)
_FALLBACKS = {
    reason: _counter(
        "tftpu_plan_fallback_total",
        "Plan segments that fell back to per-stage execution, by reason",
        labels={"reason": reason},
    )
    for reason in ("ragged", "host_callback", "trace_error", "single_stage")
}

# fused-Program cache: steady-state loops rebuild chains from the same
# stage Programs every iteration; re-composing (and re-jitting) per
# force would throw the executable away each time. Keyed by stage
# identity + needed outputs + source input specs; values pin the stage
# Programs so ids stay live, and hits verify identity against id reuse.
_CACHE_LOCK = threading.Lock()
_FUSED_CACHE: "OrderedDict[tuple, tuple]" = OrderedDict()
_FUSED_CACHE_MAX = 64


def _input_specs(plan: SegmentPlan, schema):
    """Block-level input specs for the fused program, demoted exactly as
    ``_normalize_program`` would (gather_feeds casts at the boundary)."""
    from .. import dtypes as dt
    from ..program import TensorSpec

    demote = dt.demotion_active()
    specs = []
    for name in plan.source_inputs:
        col = schema[name]
        dtype = dt.demote(col.dtype) if demote else col.dtype
        specs.append(TensorSpec(name, dtype, col.block_shape))
    return specs


def _output_specs(plan: SegmentPlan):
    """Output specs of the fused program: each computed name's spec from
    its producing stage, lifted to block level (map_rows outputs gain
    the leading batch dim their vmapped form produces)."""
    from ..program import TensorSpec
    from ..shape import Unknown

    by_name = {}
    for n in plan.included:
        for o in (n.program.outputs or []):
            shape = o.shape.prepend(Unknown) if n.rows else o.shape
            by_name[o.name] = TensorSpec(o.name, o.dtype, shape)
    return [by_name[name] for name in plan.computed_names]


def _fused_program(plan: SegmentPlan, schema):
    """Build (or fetch) the composed Program for one segment: stages
    applied in order over a shared column environment, each map_rows
    stage entering as ``jax.vmap`` of its cell function, outputs
    restricted to what the segment's consumer needs."""
    from .. import dtypes as dt
    from ..program import Program

    in_specs = _input_specs(plan, schema)
    key = (
        tuple(
            (id(n.program), n.rows, n.out_names) for n in plan.included
        ),
        tuple(plan.computed_names),
        tuple(
            (s.name, s.dtype.name, tuple(s.shape.dims)) for s in in_specs
        ),
        bool(dt.demotion_active()),
    )
    with _CACHE_LOCK:
        hit = _FUSED_CACHE.get(key)
        if hit is not None:
            fused, pinned = hit
            if len(pinned) == len(plan.included) and all(
                p is n.program for p, n in zip(pinned, plan.included)
            ):
                _FUSED_CACHE.move_to_end(key)
                return fused

    import jax

    stages = [
        (jax.vmap(n.program.fn) if n.rows else n.program.fn,
         tuple(n.program.input_names), tuple(n.out_names))
        for n in plan.included
    ]
    result_names = tuple(plan.computed_names)

    def fn(feeds: Dict[str, object]) -> Dict[str, object]:
        env = dict(feeds)
        for stage_fn, in_names, out_names in stages:
            outs = stage_fn({k: env[k] for k in in_names})
            for k in out_names:
                env[k] = outs[k]
        return {name: env[name] for name in result_names}

    fused = Program(fn, in_specs, _output_specs(plan),
                    fetch_order=list(result_names))
    with _CACHE_LOCK:
        _FUSED_CACHE[key] = (fused, tuple(n.program for n in plan.included))
        while len(_FUSED_CACHE) > _FUSED_CACHE_MAX:
            _FUSED_CACHE.popitem(last=False)
    return fused


def _pruned_source(frame, names: Sequence[str]):
    """``frame`` restricted to ``names`` with its physical identity
    (mesh, axis, process-local markers) preserved — the plain
    ``select()`` intentionally drops sharding metadata, but the fused
    dispatch must see the source exactly as the per-stage verbs would."""
    from ..frame import TensorFrame

    names = list(names)
    if list(frame.schema.names) == names:
        return frame
    schema = frame.schema.select(names)
    if frame.is_materialized:
        out = TensorFrame(
            [{n: b[n] for n in names} for b in frame.blocks()], schema
        )
    else:
        out = TensorFrame(
            None, schema,
            pending=lambda: [
                {n: b[n] for n in names} for b in frame.blocks()
            ],
        )
    for attr in ("_mesh", "_axis", "_process_local_cols"):
        if hasattr(frame, attr):
            setattr(out, attr, getattr(frame, attr))
    return out


def _apply_mask(block: Dict[str, object], names: Sequence[str],
                mask_name: str) -> Dict[str, object]:
    """Row-subset one block by its (already computed) mask column — THE
    single-process filter contract, shared by ``TensorFrame.filter``'s
    legacy path and the fused plan path so they cannot diverge:
    bool[rows] masks only, loud row-count mismatches, device columns
    gathered in HBM (only the mask crosses to host)."""
    from ..frame import _block_num_rows, _is_jax_array

    m = np.asarray(block[mask_name])
    if m.dtype != np.bool_ or m.ndim != 1:
        raise ValueError(
            f"filter predicate output {mask_name!r} must be bool[rows]; "
            f"got {m.dtype} with shape {m.shape}"
        )
    rows = _block_num_rows({n: block[n] for n in names})
    if m.shape[0] != rows:
        # must fail LOUDLY: jax gather clamps out-of-bounds indices, so
        # an oversized mask would silently duplicate the last row on
        # device columns where numpy's boolean index raises
        raise ValueError(
            f"filter predicate output {mask_name!r} has {m.shape[0]} "
            f"rows for a block of {rows}"
        )
    out: Dict[str, object] = {}
    idx = None
    for name in names:
        v = block[name]
        if isinstance(v, list):
            out[name] = [x for x, keep in zip(v, m) if keep]
        elif _is_jax_array(v):
            if idx is None:
                import jax.numpy as jnp

                idx = jnp.asarray(np.flatnonzero(m))
            out[name] = v[idx]
        else:
            out[name] = np.asarray(v)[m]
    return out


def _segment_ragged(source, input_names: Sequence[str]) -> bool:
    """True when any fused input column holds ragged cells in any source
    block — the fused (block-level) program cannot feed them; per-stage
    map_rows has the grouped-dispatch path for exactly this."""
    from ..ops.executor import block_is_ragged

    src = set(source.schema.names)
    names = [n for n in input_names if n in src]
    return any(block_is_ragged(b, names) for b in source.blocks())


def _avoided_bytes(plan: SegmentPlan, blocks) -> int:
    """Bytes the fused run never materialized: per avoided output, total
    rows x known cell extent x itemsize (Unknown inner dims skipped —
    an estimate must never overclaim)."""
    from ..frame import _block_num_rows
    from ..shape import Unknown

    rows = sum(_block_num_rows(b) for b in blocks)
    total = 0
    for _, spec in plan.avoided_outputs:
        dims = list(spec.shape.dims)
        if dims and dims[0] == Unknown:
            dims = dims[1:]
        if any(d == Unknown for d in dims):
            continue
        cell = 1
        for d in dims:
            cell *= int(d)
        itemsize = np.dtype(spec.dtype.np_dtype).itemsize
        total += rows * cell * itemsize
    return total


def _run_fused(source, plan: SegmentPlan):
    """One dispatch per block: compose, hand to map_blocks (jit cache /
    donation / prefetch / sharded paths unchanged), re-key to the
    segment's result columns, apply the filter mask if present."""
    from ..frame import TensorFrame
    from ..ops.verbs import map_blocks

    t0 = time.perf_counter()
    src_cols = [
        n for n in source.schema.names
        if n in set(plan.source_inputs) | set(plan.pass_through)
    ]
    pruned = _pruned_source(source, src_cols)
    fused = _fused_program(plan, pruned.schema)
    lower_dt = time.perf_counter() - t0
    _LOWER_SECONDS.observe(lower_dt)
    if _events.TRACER.enabled:
        _events.TRACER.emit_complete(
            "plan.lower", t0, lower_dt,
            args={"stages": len(plan.included)}, cat="plan",
        )
    t_f0 = time.perf_counter()
    mapped = map_blocks(fused, pruned)
    blocks = mapped.blocks()
    keep = list(plan.final_names)
    if plan.has_filter:
        out_blocks = [
            _apply_mask(b, keep, plan.mask_name) for b in blocks
        ]
        # same observability contract as the legacy filter: one span,
        # INPUT-rows convention (mask compute + gather wall-clock)
        from ..frame import _block_num_rows
        from ..utils import profiling

        profiling.record(
            "filter", time.perf_counter() - t_f0,
            sum(_block_num_rows(b) for b in blocks),
        )
    else:
        out_blocks = [{n: b[n] for n in keep} for b in blocks]
    _FUSED_STAGES.inc(len(plan.included))
    _BYTES_AVOIDED.inc(_avoided_bytes(plan, blocks))
    result = TensorFrame(
        out_blocks, plan.nodes[-1].schema.select(keep)
    )
    if not plan.has_filter and mapped.is_sharded:
        result._mesh = mapped.mesh
        result._axis = getattr(mapped, "_axis", None)
    return result


def _run_per_stage(source, plan: SegmentPlan):
    """Exact single-verb execution of the segment's nodes (the honest
    fallback: barriers split the plan, they never change semantics)."""
    from ..frame import TensorFrame
    from ..ops.verbs import map_blocks, map_rows

    cur = source
    for n in plan.nodes:
        if n.kind == "map":
            cur = (map_rows if n.rows else map_blocks)(n.program, cur)
        elif n.kind == "select":
            cur = cur.select(list(n.names))
        elif n.kind == "filter":
            from ..frame import _block_num_rows
            from ..utils import profiling

            names = list(n.schema.names)
            t_f0 = time.perf_counter()
            in_blocks = cur.blocks()
            out_blocks = [
                _apply_mask(b, names, n.mask_name) for b in in_blocks
            ]
            profiling.record(
                "filter", time.perf_counter() - t_f0,
                sum(_block_num_rows(b) for b in in_blocks),
            )
            cur = TensorFrame(out_blocks, n.schema)
    keep = list(plan.final_names)
    if list(cur.schema.names) != keep:
        cur = _pruned_source(cur, keep)
    cur.blocks()
    return cur


def execute_plan(node: ir.PlanNode) -> List[Dict[str, object]]:
    """Force a plan-carrying frame: lower its chain and return the final
    blocks (the frame's ``pending`` contract)."""
    source, nodes = ir.resolve_chain(node)
    final_names = list(node.schema.names)
    if not nodes:  # degenerate: the node chain collapsed to its source
        return [
            {n: b[n] for n in final_names} for b in source.blocks()
        ]

    segments = split_segments(nodes)
    # backward pass: segment k must produce what segment k+1 reads off
    # its source — k+1's fused inputs plus its pass-through columns
    plans: List[Optional[SegmentPlan]] = [None] * len(segments)
    need = final_names
    for k in range(len(segments) - 1, -1, -1):
        src_names = (
            source.schema.names if k == 0
            else list(segments[k - 1][-1].schema.names)
        )
        plans[k] = plan_segment(segments[k], need, src_names)
        req = set(plans[k].source_inputs) | set(plans[k].pass_through)
        need = [n for n in src_names if n in req]

    from ..config import get_config

    # the escape hatch is honored at FORCE time too: a chain recorded
    # while fusion was on still executes per-stage when the user turns
    # plan_fusion off before forcing (the knob exists to rule fusion
    # out — it must rule it out for already-built frames as well)
    fusion_on = bool(get_config().plan_fusion)
    t_exec = time.perf_counter()
    cur = source
    with ir.lowering():
        for plan in plans:
            if not fusion_on:
                cur = _run_per_stage(cur, plan)
                continue
            if not plan.included and not plan.has_filter:
                # pushdown pruned every stage (or the segment was pure
                # projection): no program to dispatch — just project
                cur = _pruned_source(cur, plan.final_names)
                continue
            fused_ok = plan.fusable
            reason = None
            if fused_ok and any(
                ir.program_has_callback(n.program) for n in plan.included
            ):
                fused_ok, reason = False, "host_callback"
            if fused_ok and _segment_ragged(cur, plan.source_inputs):
                fused_ok, reason = False, "ragged"
            if fused_ok:
                try:
                    cur = _run_fused(cur, plan)
                except Exception as e:
                    from ..validation import ValidationError

                    if isinstance(e, (ValidationError, ValueError)):
                        raise  # genuine contract violations stay loud
                    logger.debug("fused segment failed, replaying "
                                 "per-stage: %s", e)
                    _FALLBACKS["trace_error"].inc()
                    cur = _run_per_stage(cur, plan)
            else:
                if reason is not None:
                    _FALLBACKS[reason].inc()
                elif len(plan.included) <= 1:
                    _FALLBACKS["single_stage"].inc()
                cur = _run_per_stage(cur, plan)
    if _events.TRACER.enabled:
        _events.TRACER.emit_complete(
            "plan.execute", t_exec, time.perf_counter() - t_exec,
            args={"segments": len(segments)}, cat="plan",
        )
    return [{n: b[n] for n in final_names} for b in cur.blocks()]
