"""Verified UDF lifting: synthesis + bounded bit-exact equivalence.

The static front-end (:mod:`tensorframes_tpu.analysis.lifting`) validates
a numpy UDF's AST against a closed allowlist; this module turns a
validated candidate into a pure-jnp Program and *proves* the swap safe
before it happens:

* **Synthesis** walks the candidate AST with a numpy-as-dtype-oracle
  evaluator: each op's result dtype is computed by applying the *real*
  numpy op to zero-size probe arrays (python scalars stay raw so weak
  promotion matches), operands are explicitly cast, and the jnp
  counterpart applied — reproducing numpy/NEP50 promotion (int÷int→f64,
  ``np.sum(int32)``→int64, f32+pyfloat→f32) without hand-derived rules.
* **Verification** runs both the original numpy function and the
  synthesized program over a bounded exhaustive corpus on the actual
  block dtypes — dtype-boundary values (±0.0, finfo/iinfo extremes,
  ±inf, NaN, the sign-lattice hazard values), block sizes
  {0,1,2,5,8,13} — and demands *bit exactness*: same dtype, same shape,
  same bytes. Anything less stays a callback. The envelope is the IEEE
  *normal* range: XLA flushes subnormals (DAZ/FTZ on CPU and TPU alike)
  while host numpy keeps gradual underflow, so subnormal bits are
  backend-defined on BOTH paths and excluded from the corpus rather
  than letting an unwinnable comparison veto every float lift.
* **Policy declines** draw the same exactness line the adaptive
  optimizer's reassoc_safe gate draws: float ``sum``/``mean``/``prod``
  never lift (numpy's pairwise accumulation order is not bit-stable
  against an XLA reduce — measured divergence starts at 8 elements);
  64-bit int ``mean`` doesn't either (numpy computes it in f64, where
  values past 2^53 round order-sensitively), and float ``min``/``max``
  don't because a signed-zero tie at the extremum resolves
  position-dependently in numpy itself (measured: ``np.min([+0.,-0.])``
  is ``-0`` but ``np.min([-0.,+0.])`` is ``+0``) and order-free in XLA;
  int/bool min/max/sum are exact (modular for sum), so those lift.
  Elementwise ``np.minimum``/``np.maximum`` are positional, match
  exactly, and stay liftable — only the *reductions* are policy-bound.

A lifted Program contains no callback primitive, so it enters the
existing fusion/pushdown/cost machinery unchanged — a map→UDF→aggregate
chain compiles to one dispatch. Every decision (lift or decline, with
the taxonomy reason and offending AST node) lands in a bounded log read
by ``lint --lift-report`` and the TFG112 rule, and in the
``tftpu_lift_total`` counter family.
"""

from __future__ import annotations

import ast
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis.lifting import (
    LiftCandidate,
    LiftDeclined,
    detect_mutable_closures,
    inspect_udf,
)
from ..config import get_config
from ..observability.metrics import counter as _counter
from ..utils import get_logger

logger = get_logger(__name__)

__all__ = [
    "build_udf_program",
    "fingerprint_token",
    "lift_log",
    "clear_lift_log",
    "lift_report",
    "LIFT_FORMAT_VERSION",
]

#: Bumped whenever synthesis or verification semantics change — joins the
#: compile-cache fingerprint env slot so executables synthesized under
#: different lifting rules never collide.
LIFT_FORMAT_VERSION = 1

# Registered at import so expositions always carry the family (a process
# that never lifted reads 0 — the series does not vanish).
_LIFT_EVENTS = {
    outcome: _counter(
        "tftpu_lift_total",
        "Verified-lift decisions on captured numpy UDFs, by outcome",
        labels={"outcome": outcome},
    )
    for outcome in ("lifted", "declined")
}

#: Bounded decision log: one dict per capture-time lift decision
#: ({"udf", "lifted", "reason", "node", "lineno", "outputs", "wall_s"}).
#: Read by ``lint --lift-report`` and the TFG112 rule.
_LIFT_LOG: deque = deque(maxlen=512)
_LIFT_LOCK = threading.Lock()  # lint: guarded

#: Block sizes of the verification corpus; 8 and 13 straddle numpy's
#: pairwise-summation unroll width so accumulation-order divergence is
#: actually exercised, 0/1/2 cover the empty/degenerate edges.
_CORPUS_SIZES = (0, 1, 2, 5, 8, 13)

#: Distinct cyclic fill phases per corpus size (each input additionally
#: offsets by its own index, so multi-input UDFs see unaligned values).
_CORPUS_PHASES = (0, 11)


def fingerprint_token() -> dict:
    """The lifting contribution to the compile-cache environment
    fingerprint: a config flip or synthesis-rule bump must miss."""
    return {
        "enabled": bool(get_config().udf_lifting),
        "version": LIFT_FORMAT_VERSION,
    }


def _record(udf_name: str, lifted: bool, reason: Optional[str],
            node: Optional[str], lineno: Optional[int],
            outputs: Sequence[str], wall_s: float,
            detail: str = "") -> dict:
    rec = {
        "udf": udf_name,
        "lifted": lifted,
        "reason": reason,
        "node": node,
        "lineno": lineno,
        "outputs": list(outputs),
        "wall_s": round(wall_s, 6),
        "detail": detail,
    }
    _LIFT_EVENTS["lifted" if lifted else "declined"].inc()
    with _LIFT_LOCK:
        _LIFT_LOG.append(rec)
    return rec


def lift_log() -> List[dict]:
    """Snapshot of the bounded lift-decision log, oldest first."""
    with _LIFT_LOCK:
        return [dict(r) for r in _LIFT_LOG]


def clear_lift_log() -> None:
    with _LIFT_LOCK:
        _LIFT_LOG.clear()


def lift_report() -> str:
    """The ``lint --lift-report`` payload: one line per decision."""
    rows = lift_log()
    if not rows:
        return "lift-report: no UDF capture decisions recorded"
    lines = [f"lift-report: {len(rows)} decision(s)"]
    for r in rows:
        if r["lifted"]:
            lines.append(
                f"  LIFTED   {r['udf']} -> {', '.join(r['outputs']) or '?'}"
                f" (verify {r['wall_s']:.3f}s)"
            )
        else:
            at = f" at {r['node']}" if r["node"] else ""
            ln = f" line {r['lineno']}" if r["lineno"] else ""
            lines.append(
                f"  DECLINED {r['udf']}: {r['reason']}{at}{ln}"
                + (f" — {r['detail']}" if r["detail"] else "")
            )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Output naming shared by the callback wrapper and the synthesizer
# ---------------------------------------------------------------------------

def as_output_dict(res, fn_name: str) -> Dict[str, object]:
    """The same naming rule ``program_from_function`` applies: dicts pass
    through, tuples become ``<name>_<i>``, singles take the UDF name."""
    if isinstance(res, dict):
        return dict(res)
    if isinstance(res, (tuple, list)):
        return {f"{fn_name}_{i}": v for i, v in enumerate(res)}
    return {fn_name: res}


# ---------------------------------------------------------------------------
# Synthesis: numpy-as-dtype-oracle AST evaluation
# ---------------------------------------------------------------------------

class _V:
    """An evaluated value: the traced jnp side plus a zero-size numpy
    probe that carries exact numpy promotion semantics. Python scalar
    constants keep their raw value on both sides (weak typing)."""

    __slots__ = ("jx", "probe", "is_scalar")

    def __init__(self, jx, probe, is_scalar=False):
        self.jx = jx
        self.probe = probe
        self.is_scalar = is_scalar


_PY_BINOPS = {
    ast.Add: lambda a, b: a + b, ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b, ast.Div: lambda a, b: a / b,
    ast.FloorDiv: lambda a, b: a // b, ast.Mod: lambda a, b: a % b,
    ast.Pow: lambda a, b: a ** b,
}
_NP_BINOPS = {
    ast.Add: "add", ast.Sub: "subtract", ast.Mult: "multiply",
    ast.Div: "true_divide", ast.FloorDiv: "floor_divide",
    ast.Mod: "mod", ast.Pow: "power",
}
_CMP_NP = {
    ast.Eq: "equal", ast.NotEq: "not_equal", ast.Lt: "less",
    ast.LtE: "less_equal", ast.Gt: "greater", ast.GtE: "greater_equal",
}
#: ops whose result is bool but whose operands are used as-is
_PREDICATES = {
    "isnan", "isinf", "isfinite",
    "logical_and", "logical_or", "logical_not", "logical_xor",
}
_REDUCTIONS = {"sum", "mean", "prod", "min", "max", "amin", "amax"}
_METHOD_TO_NP = {"sum": "sum", "mean": "mean", "prod": "prod",
                 "min": "min", "max": "max", "clip": "clip"}


def _np_op(name: str):
    fn = getattr(np, name, None)
    if fn is None:  # pragma: no cover - allowlist and numpy agree
        raise LiftDeclined(f"unsupported-call:np.{name}", node="Call")
    return fn


def _jnp_op(name: str):
    import jax.numpy as jnp

    alias = {"min": "min", "amin": "min", "max": "max", "amax": "max",
             "abs": "abs", "absolute": "abs", "invert": "invert",
             "true_divide": "true_divide", "mod": "mod"}
    fn = getattr(jnp, alias.get(name, name), None)
    if fn is None:
        raise LiftDeclined(f"unsupported-call:np.{name}", node="Call")
    return fn


class _Synthesizer:
    """Evaluate a validated candidate body against jnp feeds, with the
    numpy dtype oracle deciding every cast. Raises LiftDeclined for the
    dtype-dependent policy declines (float reductions) the static
    front-end cannot see."""

    def __init__(self, cand: LiftCandidate, probes: Dict[str, np.ndarray]):
        self.c = cand
        self.probes = probes  # param -> zero-size np array

    # -- coercion helpers ---------------------------------------------
    def _cast(self, v: _V, dtype):
        import jax.numpy as jnp

        if v.is_scalar:
            return jnp.asarray(v.probe, dtype=dtype)
        return v.jx.astype(dtype) if v.jx.dtype != dtype else v.jx

    def _apply_oracle(self, np_name: str, vals: List[_V],
                      node: ast.AST) -> _V:
        """Elementwise op: probe numpy for the result dtype, cast every
        operand to it, run the jnp counterpart, pin the result dtype."""
        with np.errstate(all="ignore"):
            probe_res = _np_op(np_name)(*[v.probe for v in vals])
        if all(v.is_scalar for v in vals):
            # constant folding on the host: real execution would run this
            # in numpy before the arrays ever see it
            return _V(probe_res, probe_res, is_scalar=True)
        dt_res = np.asarray(probe_res).dtype
        self._check_dtype(dt_res, node)
        jargs = [self._cast(v, dt_res) for v in vals]
        out = _jnp_op(np_name)(*jargs)
        if out.dtype != dt_res:
            out = out.astype(dt_res)
        return _V(out, np.zeros(np.asarray(probe_res).shape
                                if np.asarray(probe_res).ndim else (),
                                dtype=dt_res))

    def _apply_predicate(self, np_name: str, vals: List[_V],
                         node: ast.AST) -> _V:
        with np.errstate(all="ignore"):
            probe_res = _np_op(np_name)(*[v.probe for v in vals])
        if all(v.is_scalar for v in vals):
            return _V(probe_res, probe_res, is_scalar=True)
        jargs = [v.probe if v.is_scalar else v.jx for v in vals]
        out = _jnp_op(np_name)(*jargs)
        return _V(out, np.zeros(np.asarray(probe_res).shape, dtype=bool))

    def _apply_compare(self, np_name: str, a: _V, b: _V,
                       node: ast.AST) -> _V:
        if a.is_scalar and b.is_scalar:
            with np.errstate(all="ignore"):
                r = _np_op(np_name)(a.probe, b.probe)
            return _V(r, r, is_scalar=True)
        # numpy compares in the common operand type
        common = np.result_type(a.probe, b.probe)
        self._check_dtype(common, node)
        with np.errstate(all="ignore"):
            probe_res = _np_op(np_name)(a.probe, b.probe)
        out = _jnp_op(np_name)(self._cast(a, common), self._cast(b, common))
        return _V(out, np.zeros(np.asarray(probe_res).shape,
                                dtype=np.asarray(probe_res).dtype))

    def _apply_reduction(self, np_name: str, v: _V, node: ast.AST) -> _V:
        import jax.numpy as jnp

        if v.is_scalar:
            raise LiftDeclined("unsupported-syntax:scalar-reduction",
                               node="Call",
                               lineno=getattr(node, "lineno", None))
        in_dtype = v.probe.dtype
        canon = {"amin": "min", "amax": "max"}.get(np_name, np_name)
        if canon in ("sum", "mean", "prod") and np.issubdtype(
            in_dtype, np.floating
        ):
            raise LiftDeclined(
                "float-reduction", node="Call",
                lineno=getattr(node, "lineno", None),
                detail=f"np.{canon} over {in_dtype} accumulates in an "
                       "order numpy (pairwise) and XLA do not share — "
                       "not bit-stable, stays a callback (same exactness "
                       "line as the optimizer's reassoc_safe gate)")
        if canon in ("min", "max") and np.issubdtype(
            in_dtype, np.floating
        ):
            # measured: np.min([+0.,-0.]) returns -0 but
            # np.min([-0.,+0.]) returns +0 (position-dependent), while
            # XLA's reduce returns -0 either way — a signed-zero tie at
            # the extremum makes the float result order-sensitive on
            # numpy's OWN side, so no order-free synthesis can match
            raise LiftDeclined(
                "float-reduction", node="Call",
                lineno=getattr(node, "lineno", None),
                detail=f"np.{canon} over {in_dtype}: signed-zero ties "
                       "at the extremum resolve position-dependently in "
                       "numpy and order-free in XLA — not bit-stable, "
                       "stays a callback")
        if canon in ("min", "max"):
            out = getattr(jnp, canon)(v.jx)
            dt_res = in_dtype
        else:
            # sum/prod accumulate in the numpy result dtype (int64 for
            # int/bool input — modular, order-free); mean accumulates
            # exactly in f64 for int inputs small enough to stay < 2^53
            with np.errstate(all="ignore"):
                probe_res = _np_op(canon)(np.zeros((0,), in_dtype)) \
                    if canon != "mean" else np.float64(0)
            if canon == "mean" and np.dtype(in_dtype).itemsize >= 8:
                # int64 mean is computed in f64 on both sides, but
                # values past 2^53 are inexact there and numpy's
                # pairwise order then rounds differently from an XLA
                # reduce — same exactness line as float reductions
                raise LiftDeclined(
                    "float-reduction", node="Call",
                    lineno=getattr(node, "lineno", None),
                    detail=f"np.mean over {in_dtype} accumulates in "
                           "float64, inexact past 2^53 and therefore "
                           "order-sensitive — not bit-stable, stays a "
                           "callback")
            if canon == "mean":
                # numpy divides the exact f64 sum by the count;
                # jnp.mean multiplies by the reciprocal, and XLA's
                # algebraic simplifier rewrites divide-by-constant the
                # same way — off by one ulp on e.g.
                # mean([7,100,-1,-2,-7]). The optimization barrier
                # keeps the true division in the compiled program.
                from jax import lax

                dt_res = np.mean(np.zeros((1,), in_dtype)).dtype
                self._check_dtype(dt_res, node)
                total = jnp.sum(v.jx.astype(dt_res))
                total, count = lax.optimization_barrier(
                    (total, jnp.asarray(float(v.jx.size), dt_res)))
                out = total / count
            else:
                dt_res = np.asarray(probe_res).dtype
                self._check_dtype(dt_res, node)
                out = getattr(jnp, canon)(v.jx.astype(dt_res))
            if out.dtype != dt_res:
                out = out.astype(dt_res)
        return _V(out, np.zeros((), dtype=dt_res))

    def _check_dtype(self, dtype, node) -> None:
        d = np.dtype(dtype)
        ok = d == np.bool_ or np.issubdtype(d, np.integer) or d in (
            np.dtype(np.float16), np.dtype(np.float32), np.dtype(np.float64)
        )
        if not ok:
            raise LiftDeclined(
                "unsupported-dtype", node=type(node).__name__,
                lineno=getattr(node, "lineno", None),
                detail=f"{d} has no verified lowering")

    # -- evaluation ---------------------------------------------------
    def run(self, feeds) -> Dict[str, object]:
        env: Dict[str, _V] = {}
        for p in self.c.params:
            env[p] = _V(feeds[p], self.probes[p])
        for name, val in self.c.consts.items():
            env[name] = _V(val, val, is_scalar=True)
        ret: Optional[ast.expr] = None
        for st in self.c.body:
            if isinstance(st, ast.Assign):
                env[st.targets[0].id] = self._eval(st.value, env)
            else:  # Return — validator guarantees it is last
                ret = st.value
        assert ret is not None
        return self._outputs(ret, env)

    def _outputs(self, value: ast.expr, env) -> Dict[str, object]:
        if isinstance(value, ast.Dict):
            return {k.value: self._eval(v, env).jx
                    for k, v in zip(value.keys, value.values)}
        if isinstance(value, (ast.Tuple, ast.List)):
            return {f"{self.c.name}_{i}": self._eval(v, env).jx
                    for i, v in enumerate(value.elts)}
        return {self.c.name: self._eval(value, env).jx}

    def _eval(self, node: ast.expr, env: Dict[str, _V]) -> _V:
        if isinstance(node, ast.Name):
            return env[node.id]
        if isinstance(node, ast.Constant):
            return _V(node.value, node.value, is_scalar=True)
        if isinstance(node, ast.BinOp):
            a = self._eval(node.left, env)
            b = self._eval(node.right, env)
            if a.is_scalar and b.is_scalar:
                # python evaluates scalar-scalar before numpy sees it:
                # stay weak by using the python operator
                r = _PY_BINOPS[type(node.op)](a.probe, b.probe)
                return _V(r, r, is_scalar=True)
            return self._apply_oracle(_NP_BINOPS[type(node.op)], [a, b],
                                      node)
        if isinstance(node, ast.UnaryOp):
            v = self._eval(node.operand, env)
            if v.is_scalar:
                r = (-v.probe if isinstance(node.op, ast.USub)
                     else +v.probe if isinstance(node.op, ast.UAdd)
                     else ~v.probe)
                return _V(r, r, is_scalar=True)
            name = {"USub": "negative", "UAdd": "positive",
                    "Invert": "invert"}[type(node.op).__name__]
            return self._apply_oracle(name, [v], node)
        if isinstance(node, ast.Compare):
            a = self._eval(node.left, env)
            b = self._eval(node.comparators[0], env)
            return self._apply_compare(_CMP_NP[type(node.ops[0])], a, b,
                                       node)
        if isinstance(node, ast.Call):
            return self._call(node, env)
        raise LiftDeclined(f"unsupported-syntax:{type(node).__name__}",
                           node=type(node).__name__,
                           lineno=getattr(node, "lineno", None))

    def _call(self, node: ast.Call, env) -> _V:
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id in self.c.np_aliases:
            name = f.attr
            args = [self._eval(a, env) for a in node.args]
        elif isinstance(f, ast.Attribute):
            # method spelling x.sum() — receiver first, then args
            name = _METHOD_TO_NP[f.attr]
            args = [self._eval(f.value, env)] + [
                self._eval(a, env) for a in node.args]
        elif isinstance(f, ast.Name) and f.id == "abs":
            name = "abs"
            args = [self._eval(a, env) for a in node.args]
        else:  # pragma: no cover - validator blocks this
            raise LiftDeclined("unsupported-syntax:Call", node="Call",
                               lineno=node.lineno)
        canon = {"absolute": "abs"}.get(name, name)
        if canon in _REDUCTIONS:
            if len(args) != 1:
                raise LiftDeclined(
                    "unsupported-syntax:reduction-arguments", node="Call",
                    lineno=node.lineno,
                    detail="only full single-array reductions lift")
            return self._apply_reduction(canon, args[0], node)
        if canon in _PREDICATES:
            return self._apply_predicate(canon, args, node)
        if canon == "where" and len(args) == 3:
            cond, x, y = args
            with np.errstate(all="ignore"):
                probe_res = np.where(cond.probe, x.probe, y.probe)
            dt_res = probe_res.dtype
            self._check_dtype(dt_res, node)
            import jax.numpy as jnp

            c = cond.probe if cond.is_scalar else (
                cond.jx if cond.jx.dtype == np.bool_
                else cond.jx.astype(bool))
            out = jnp.where(c, self._cast(x, dt_res), self._cast(y, dt_res))
            return _V(out, np.zeros(probe_res.shape, dtype=dt_res))
        return self._apply_oracle(canon, args, node)


# ---------------------------------------------------------------------------
# Verification corpus
# ---------------------------------------------------------------------------

def _boundary_pool(dtype: np.dtype) -> np.ndarray:
    """Deterministic 1-D pool of hazard values for one dtype: ±0.0,
    ±1, finfo/iinfo extremes, subnormals, ±inf, NaN, and the PR 3
    sign-lattice hazard band (negatives / signed zeros / tiny
    positives)."""
    d = np.dtype(dtype)
    if d == np.bool_:
        return np.array([True, False, True, True, False], dtype=d)
    if np.issubdtype(d, np.integer):
        info = np.iinfo(d)
        vals = [0, 1, 2, 3, 5, 7, 100]
        for v in (-1, -2, -7, -100):
            if v >= info.min:
                vals.append(v)
        vals += [info.min, info.max, info.min + 1, info.max - 1]
        vals = [v for v in vals if info.min <= v <= info.max]
        return np.array(vals, dtype=d)
    info = np.finfo(d)
    # The verified envelope is the IEEE NORMAL range: subnormal inputs
    # are deliberately absent. XLA executes with DAZ/FTZ (a plain add
    # flushes a subnormal operand to zero on CPU; TPU vector units flush
    # f32 subnormals in hardware) while host numpy keeps gradual
    # underflow, so subnormal bits are backend-defined and can NEVER
    # verify against the callback oracle — including them would turn
    # every float lift into a decline. ±tiny (the smallest normal) stays
    # in the pool to pin the underflow boundary itself.
    vals = [
        0.0, -0.0, 1.0, -1.0, 0.5, -0.5, 2.0, -2.5, 7.0, 3.140625,
        float(info.max), float(-info.max), float(info.tiny),
        float(-info.tiny), float(info.eps), float(1.0 + info.eps),
        # sign-lattice hazard band: values whose sign/zero classification
        # diverges across naive rewrites
        -3.5, -1e-30, 1e-30, -1e-7, 1e-7,
        float("inf"), float("-inf"), float("nan"),
    ]
    arr = np.array(vals, dtype=d)
    # narrow dtypes (f16) turn some hazard values subnormal on
    # conversion — drop those, keep zeros/inf/NaN and normals
    keep = ~np.isfinite(arr) | (arr == 0) | (np.abs(arr) >= info.tiny)
    return arr[keep]


def _corpus_block(pool: np.ndarray, n: int, trailing: Tuple[int, ...],
                  phase: int) -> np.ndarray:
    """Cyclic fill of a (n, *trailing) block from the pool, rolled by
    ``phase`` so multiple inputs never align."""
    total = n
    for t in trailing:
        total *= t
    if total == 0:
        return np.zeros((n,) + trailing, dtype=pool.dtype)
    idx = (np.arange(total) + phase) % len(pool)
    return pool[idx].reshape((n,) + trailing)


def _input_shapes(spec) -> Tuple[int, ...]:
    """Concrete trailing dims of a block spec (lead dim is the corpus
    size; Unknown trailing dims probe at 3)."""
    from ..shape import Unknown

    dims = list(spec.shape.dims)[1:]  # drop the lead (block) dim
    return tuple(3 if d is Unknown or d == Unknown else int(d)
                 for d in dims)


def verify_candidate(cand: LiftCandidate, specs: Dict[str, object],
                     synth_fn: Callable) -> None:
    """Bounded exhaustive equivalence: run the original numpy UDF and
    the synthesized jnp function over the boundary corpus and demand
    bit-exact agreement (dtype + shape + bytes) on every output.
    Raises LiftDeclined('verify-mismatch' | 'probe-failure') on any
    divergence; returns silently when every case agrees."""
    import jax
    import jax.numpy as jnp

    jitted = jax.jit(synth_fn)
    sizes = [s for s in _CORPUS_SIZES if s > 0 or not cand.has_reduction]
    pools = {}
    for p in cand.params:
        spec = specs[p]
        d = np.dtype(spec.dtype.np_dtype)
        pools[p] = _boundary_pool(d)

    for n in sizes:
        for phase in _CORPUS_PHASES:
            feeds_np = {}
            for i, p in enumerate(cand.params):
                trailing = _input_shapes(specs[p])
                feeds_np[p] = _corpus_block(
                    pools[p], n, trailing, phase + 5 * i + n)
            try:
                with np.errstate(all="ignore"):
                    ref = as_output_dict(
                        cand.fn(*[feeds_np[p] for p in cand.params]),
                        cand.name)
                ref = {k: np.asarray(v) for k, v in ref.items()}
            except Exception as e:
                raise LiftDeclined(
                    "probe-failure", node=None,
                    detail=f"reference raised {type(e).__name__} on "
                           f"corpus block n={n}: {e}")
            try:
                got = jitted({p: jnp.asarray(feeds_np[p])
                              for p in cand.params})
                got = {k: np.asarray(v) for k, v in got.items()}
            except LiftDeclined:
                # dtype-dependent policy declines (float-reduction,
                # unsupported-dtype) surface during tracing — keep the
                # taxonomy reason, do not relabel as probe-failure
                raise
            except Exception as e:
                raise LiftDeclined(
                    "probe-failure", node=None,
                    detail=f"synthesized program raised "
                           f"{type(e).__name__} on corpus block n={n}: "
                           f"{e}")
            if set(ref) != set(got):
                raise LiftDeclined(
                    "verify-mismatch",
                    detail=f"output names differ: {sorted(ref)} vs "
                           f"{sorted(got)}")
            for k in ref:
                r, g = ref[k], got[k]
                if r.dtype != g.dtype or r.shape != g.shape \
                        or r.tobytes() != g.tobytes():
                    raise LiftDeclined(
                        "verify-mismatch",
                        detail=f"output {k!r} diverges on corpus block "
                               f"n={n} phase={phase}: reference "
                               f"{r.dtype}{list(r.shape)} vs synthesized "
                               f"{g.dtype}{list(g.shape)} (bit-exact "
                               "comparison)")


# ---------------------------------------------------------------------------
# Program construction
# ---------------------------------------------------------------------------

def _udf_params(fn, specs: Dict[str, object]) -> List[str]:
    import inspect as _inspect

    sig = _inspect.signature(fn)
    params = [p.name for p in sig.parameters.values()
              if p.kind in (p.POSITIONAL_OR_KEYWORD, p.KEYWORD_ONLY)]
    missing = [p for p in params if p not in specs]
    if missing:
        raise ValueError(
            f"numpy_udf parameter(s) {missing} do not match any known "
            f"input; available: {sorted(specs)}")
    return params


def _build_callback_program(fn, params: List[str],
                            specs: Dict[str, object], fn_name: str):
    """The reference path: the UDF runs on host per block behind
    ``jax.pure_callback``. Output shapes/dtypes are discovered by
    probing the numpy function on small ones-blocks at trace time (two
    probe sizes disambiguate batch-covariant dims, the analyze_program
    rule)."""
    import jax

    from ..program import Program

    def _probe_shapes(lead_shapes):
        probe_ins = [np.ones(s, dtype=np.dtype(specs[p].dtype.np_dtype))
                     for p, s in zip(params, lead_shapes)]
        with np.errstate(all="ignore"):
            out = as_output_dict(fn(*probe_ins), fn_name)
        return {k: np.asarray(v) for k, v in out.items()}

    def callback_fn(feeds):
        arrs = [feeds[p] for p in params]
        shapes = [tuple(int(d) for d in a.shape) for a in arrs]

        def with_lead(n):
            return [((n,) + s[1:]) if len(s) else s for s in shapes]

        try:
            out_a = _probe_shapes(with_lead(3))
            out_b = _probe_shapes(with_lead(4))
        except Exception as e:
            raise TypeError(
                f"numpy_udf {fn_name!r} failed shape probing "
                f"({type(e).__name__}: {e}); the UDF must be total on "
                "ones-filled blocks") from e
        lead = shapes[0][0] if shapes and shapes[0] else None
        result_shapes = {}
        for k, va in out_a.items():
            vb = out_b[k]
            dims = tuple(
                (lead if (da != db and lead is not None) else da)
                for da, db in zip(va.shape, vb.shape))
            result_shapes[k] = jax.ShapeDtypeStruct(dims, va.dtype)

        def host(*xs):
            with np.errstate(all="ignore"):
                out = as_output_dict(fn(*[np.asarray(x) for x in xs]),
                                     fn_name)
            return {k: np.asarray(v, dtype=result_shapes[k].dtype)
                    for k, v in out.items()}

        res = jax.pure_callback(host, result_shapes, *arrs)
        return dict(res)

    inputs = [specs[p] for p in params]
    return Program(callback_fn, inputs)


def _build_lifted_program(cand: LiftCandidate, params: List[str],
                          specs: Dict[str, object]):
    from ..program import Program

    probes = {
        p: np.zeros((0,) + _input_shapes(specs[p]),
                    dtype=np.dtype(specs[p].dtype.np_dtype))
        for p in params
    }

    def lifted_fn(feeds):
        return _Synthesizer(cand, probes).run(feeds)

    inputs = [specs[p] for p in params]
    return Program(lifted_fn, inputs), lifted_fn


def build_udf_program(fn, specs: Dict[str, object], *,
                      subject: str = "") -> "object":
    """Capture a numpy UDF as a Program: lifted when synthesis verifies
    bit-exactly, a counted host callback otherwise.

    ``specs`` maps input names to TensorSpecs (block shapes). The
    returned Program is fully analyzed; lifted programs carry
    ``_tftpu_lifted=True`` (no callback primitive — fuses), callback
    programs carry ``_tftpu_lift_info`` with the taxonomy decline
    reason that TFG112 and ``--lift-report`` surface.
    """
    from .. import dtypes as dt
    from ..program import Program, TensorSpec, analyze_program

    fn_name = getattr(fn, "__name__", "udf")
    if fn_name == "<lambda>":
        fn_name = "udf"
    params = _udf_params(fn, specs)
    cfg = get_config()

    demoted_specs = specs
    if dt.demotion_active():
        demoted_specs = {
            name: TensorSpec(s.name, dt.demote(s.dtype), s.shape)
            for name, s in specs.items()
        }

    t0 = time.perf_counter()
    info: Optional[dict] = None
    lifted_program = None
    if not cfg.udf_lifting:
        info = _record(fn_name, False, "lifting-disabled", None, None,
                       [], time.perf_counter() - t0,
                       detail="config.udf_lifting is off (TFTPU_LIFT=0)")
    elif dt.demotion_active():
        info = _record(fn_name, False, "demotion-active", None, None,
                       [], time.perf_counter() - t0,
                       detail="x64 demotion rewrites input dtypes at the "
                              "device boundary; the numpy reference "
                              "semantics are not reproducible bit-exactly")
    else:
        try:
            cand = inspect_udf(fn)
            program, lifted_fn = _build_lifted_program(
                cand, params, specs)
            verify_candidate(cand, specs, lifted_fn)
            lifted_program = analyze_program(program)
            info = _record(
                fn_name, True, None, None, None,
                [o.name for o in lifted_program.outputs],
                time.perf_counter() - t0)
        except LiftDeclined as d:
            info = _record(fn_name, False, d.reason, d.node, d.lineno,
                           [], time.perf_counter() - t0, detail=d.detail)
        except Exception as e:  # pragma: no cover - synthesis bug guard
            logger.warning("lift synthesis failed unexpectedly: %s", e)
            info = _record(fn_name, False, "probe-failure", None, None,
                           [], time.perf_counter() - t0,
                           detail=f"{type(e).__name__}: {e}")

    if lifted_program is not None:
        lifted_program._tftpu_lifted = True
        lifted_program._tftpu_has_callback = False
        lifted_program._tftpu_lift_info = info
        return lifted_program

    program = _build_callback_program(fn, params, demoted_specs, fn_name)
    program = analyze_program(program)
    program._tftpu_has_callback = True
    program._tftpu_lift_info = info
    return program
