"""Lazy verb-chain fusion: logical plans over frames, lowered to one
XLA dispatch per block.

The reference ran one TF Session per partition *per operation*; the
port's frames were already lazy, but a chain like ``map_blocks ->
map_rows -> select`` still materialized every intermediate and paid a
fresh jit dispatch (plus device<->host transfers and output validation)
per stage. This package records chains as a small plan IR instead
(:mod:`.ir`), prunes and segments them (:mod:`.rules`), and lowers each
maximal fusable run into a single composed Program dispatched once per
block through the unchanged executor machinery (:mod:`.lower`).

Fused and per-stage execution are bit-identical by contract; barriers
(ragged cells, host callbacks, trim row-count changes, data-dependent
filters, explicit materialization) split the plan honestly instead of
changing semantics. ``TFTPU_FUSION=0`` / ``configure(plan_fusion=False)``
disables planning entirely.

Since ISSUE 14 the package is also the **adaptive query optimizer**:
eligible aggregates push below joins (the join degenerates to a
whole-group semi-join filter, so rows never match-expand), multi-join
chains reorder by estimated — then observed — selectivity, and a
per-plan-fingerprint stats sidecar (:mod:`.stats`, persisted under
``TFTPU_COMPILE_CACHE``) feeds measured cardinalities back into the
cost model so the second execution of a recurring pipeline picks
better lowerings than the first (counted as ``reoptimized``
decisions). Every rewrite is gated on reassoc-safe exactness and m=1
joins, so results stay bit-identical; ``TFTPU_REOPT=0`` /
``configure(plan_reopt=False)`` restores the static cost model.

Importing this package registers the ``tftpu_plan_*`` metrics family,
so expositions carry it from process start.
"""

from .ir import (  # noqa: F401
    PlanNode,
    chain_barriers,
    explain_plan,
    fusion_enabled,
    mark_barrier,
    mark_pushdown_miss,
    mark_unfused,
    node_for_parent,
    parent_is_fusable,
    program_has_callback,
    pushdown_miss_log,
    resolve_chain,
    unfused_epilogues,
)
from .lower import execute_aggregate, execute_plan, lower_reduce  # noqa: F401
from .rules import (  # noqa: F401
    Decision,
    PushdownPlan,
    SegmentPlan,
    decide_epilogue,
    decide_fuse,
    decide_join_order,
    decide_pushdown,
    decide_segment_bucket,
    plan_join_chain,
    plan_pushdown,
    plan_segment,
    reassoc_safe,
    split_segments,
)
from .stats import (  # noqa: F401
    chain_fingerprint,
    reopt_enabled,
)

__all__ = [
    "Decision",
    "PlanNode",
    "PushdownPlan",
    "SegmentPlan",
    "chain_barriers",
    "chain_fingerprint",
    "decide_epilogue",
    "decide_fuse",
    "decide_join_order",
    "decide_pushdown",
    "decide_segment_bucket",
    "execute_aggregate",
    "execute_plan",
    "explain_plan",
    "fusion_enabled",
    "lower_reduce",
    "plan_join_chain",
    "plan_pushdown",
    "plan_segment",
    "pushdown_miss_log",
    "reassoc_safe",
    "reopt_enabled",
    "split_segments",
    "unfused_epilogues",
]
