"""Lazy verb-chain fusion: logical plans over frames, lowered to one
XLA dispatch per block.

The reference ran one TF Session per partition *per operation*; the
port's frames were already lazy, but a chain like ``map_blocks ->
map_rows -> select`` still materialized every intermediate and paid a
fresh jit dispatch (plus device<->host transfers and output validation)
per stage. This package records chains as a small plan IR instead
(:mod:`.ir`), prunes and segments them (:mod:`.rules`), and lowers each
maximal fusable run into a single composed Program dispatched once per
block through the unchanged executor machinery (:mod:`.lower`).

Fused and per-stage execution are bit-identical by contract; barriers
(ragged cells, host callbacks, trim row-count changes, data-dependent
filters, explicit materialization) split the plan honestly instead of
changing semantics. ``TFTPU_FUSION=0`` / ``configure(plan_fusion=False)``
disables planning entirely.

Importing this package registers the ``tftpu_plan_*`` metrics family,
so expositions carry it from process start.
"""

from .ir import (  # noqa: F401
    PlanNode,
    chain_barriers,
    explain_plan,
    fusion_enabled,
    mark_barrier,
    mark_unfused,
    node_for_parent,
    parent_is_fusable,
    program_has_callback,
    resolve_chain,
    unfused_epilogues,
)
from .lower import execute_aggregate, execute_plan, lower_reduce  # noqa: F401
from .rules import (  # noqa: F401
    Decision,
    SegmentPlan,
    decide_epilogue,
    decide_fuse,
    decide_segment_bucket,
    plan_segment,
    reassoc_safe,
    split_segments,
)

__all__ = [
    "Decision",
    "PlanNode",
    "SegmentPlan",
    "chain_barriers",
    "decide_epilogue",
    "decide_fuse",
    "decide_segment_bucket",
    "execute_aggregate",
    "execute_plan",
    "explain_plan",
    "fusion_enabled",
    "lower_reduce",
    "plan_segment",
    "reassoc_safe",
    "split_segments",
    "unfused_epilogues",
]
