"""Per-plan-fingerprint execution statistics: the feedback half of the
adaptive optimizer (ROADMAP #4, HiFrames-style).

The PR 7 cost model makes static, one-shot decisions. This module
closes the loop: every adaptive lowering records what it OBSERVED —
per-join build-side cardinalities and row selectivities, aggregate
group counts, segment wall-clock — keyed by a stable **plan
fingerprint** (a content hash of the logical chain: node kinds, stage
program signatures, join specs, aggregate ops — no ``id()``/``hash()``,
so the same pipeline rebuilt in a new process keys identically). The
next execution of the same pipeline consults the record and picks a
better lowering — join order by observed selectivity instead of build
size, pushdown skipped when the joins are observed to discard most
rows, segment-bucket history warm-started — each consultation counted
as a ``reoptimized`` decision in ``tftpu_plan_cost_decisions_total``.

Persistence: records live in memory and, when ``TFTPU_COMPILE_CACHE``
names a directory, as one JSON sidecar per fingerprint under
``<cache>/planstats/`` (write-temp → ``os.replace``, same durability
discipline as the AOT store). Sidecar problems follow the AOT store's
contract exactly: a corrupt, stale, or unreadable record is counted,
quarantined (unlinked), and the decision falls back to static — a
stats problem can never fail a dispatch or change results (stats are
hints; correctness never depends on them).

``TFTPU_REOPT=0`` (``config.plan_reopt``) disables the whole adaptive
layer: :func:`lookup` returns None, :func:`record_execution` no-ops,
and the lowering keeps the PR 7 static paths bit-identically.
"""

from __future__ import annotations

import copy
import hashlib
import json
import os
import threading
from collections import OrderedDict
from contextlib import contextmanager
from typing import Dict, List, Optional

from ..observability.metrics import counter as _counter
from ..utils import get_logger

logger = get_logger(__name__)

__all__ = [
    "FORMAT_VERSION",
    "reopt_enabled",
    "chain_fingerprint",
    "lookup",
    "record_execution",
    "clear_memory",
    "sidecar_dir",
    "observe_strategy_wall",
    "strategy_walls",
    "reset_strategy_walls",
    "workload_scope",
    "current_workload",
    "SW_FORMAT_VERSION",
    "STRATEGY_WALL_ALPHA",
    "STRATEGY_WALL_MIN_SAMPLES",
    "STRATEGY_STALE_OBS",
]

#: Sidecar record format; a version bump quarantines old records.
FORMAT_VERSION = 1

# Registered at import (TFL003): the sidecar family expositions from
# process start even when re-optimization never engages.
_SIDECAR_EVENTS = {
    event: _counter(
        "tftpu_plan_reopt_sidecar_total",
        "Plan-stats sidecar operations (the adaptive optimizer's "
        "feedback store under TFTPU_COMPILE_CACHE), by event",
        labels={"event": event},
    )
    for event in ("load", "store", "quarantine")
}

_LOCK = threading.Lock()
_MEM: "OrderedDict[str, dict]" = OrderedDict()
_MEM_MAX = 256
#: Bound on per-record observation lists (recent distinct group counts).
_OBS_MAX = 16


def reopt_enabled() -> bool:
    """True when the adaptive optimizer may rewrite plans and consult
    or record stats (``TFTPU_REOPT=0`` / ``configure(plan_reopt=False)``
    is the escape hatch back to the static cost model)."""
    from ..config import get_config

    return bool(get_config().plan_reopt)


def sidecar_dir() -> Optional[str]:
    """The sidecar directory under the compile cache root, or None when
    no cache dir is configured (stats then stay in-memory only)."""
    from ..config import get_config

    root = get_config().compilation_cache_dir
    if not root:
        return None
    return os.path.join(root, "planstats")


# ---------------------------------------------------------------------------
# plan fingerprinting: a stable content key for one logical chain
# ---------------------------------------------------------------------------

def _program_sig(program) -> object:
    """Stable signature of a stage/reduce Program: named input/output
    specs (dtype + cell dims). Deliberately NOT the jaxpr — the
    fingerprint must be cheap enough to compute per force, and a
    collision only merges two pipelines' stats (hints, not keys for
    correctness)."""
    try:
        ins = [
            (s.name, s.dtype.name, [str(d) for d in s.shape.dims])
            for s in (program.inputs or [])
        ]
        outs = [
            (s.name, s.dtype.name, [str(d) for d in s.shape.dims])
            for s in (program.outputs or [])
        ]
        return {"in": ins, "out": outs}
    except Exception:  # pragma: no cover - exotic program-likes
        return {"in": [], "out": []}


def _frame_sig(frame) -> object:
    try:
        return [(c.name, c.dtype.name) for c in frame.schema]
    except Exception:  # pragma: no cover
        return []


def _node_sig(node) -> object:
    sig: Dict[str, object] = {"kind": node.kind}
    if node.kind == "map":
        sig["rows"] = bool(node.rows)
        sig["out"] = list(node.out_names)
        sig["program"] = _program_sig(node.program)
    elif node.kind == "select":
        sig["names"] = list(node.names)
    elif node.kind == "filter":
        sig["mask"] = node.mask_name
    elif node.kind == "join":
        spec = node.spec
        sig["keys"] = list(spec.keys)
        sig["how"] = spec.how
        sig["lname"] = [list(p) for p in spec.lname]
        sig["rname"] = [list(p) for p in spec.rname]
        sig["right"] = _frame_sig(node.right)
    elif node.kind == "aggregate":
        sig["keys"] = list(node.keys)
        sig["ops"] = [[x, op] for x, op, _ in (node.spec or ())]
    elif node.kind == "reduce":
        sig["mode"] = str(node.spec)
        sig["out"] = list(node.out_names)
    return sig


def chain_fingerprint(source, nodes) -> str:
    """sha256 content key of one resolved plan chain (source schema +
    per-node signatures). Stable across processes for the same rebuilt
    pipeline — the property the sidecar's survives-restarts contract
    needs."""
    payload = {
        "v": FORMAT_VERSION,
        "source": _frame_sig(source),
        "nodes": [_node_sig(n) for n in nodes],
    }
    blob = json.dumps(payload, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


# ---------------------------------------------------------------------------
# the stats table: in-memory cache over the on-disk sidecar
# ---------------------------------------------------------------------------

def _sidecar_path(fp: str) -> Optional[str]:
    d = sidecar_dir()
    if d is None:
        return None
    return os.path.join(d, f"{fp}.json")


def _valid(rec: object, fp: str) -> bool:
    """A sidecar record is usable only when it is structurally what
    this version writes AND names the fingerprint it sits under —
    anything else (corrupt JSON handled by the caller, a format bump,
    a file copied under the wrong name) is stale and quarantines."""
    return (
        isinstance(rec, dict)
        and rec.get("v") == FORMAT_VERSION
        and rec.get("fp") == fp
        and isinstance(rec.get("execs"), int)
    )


def _quarantine(path: str, why: str) -> None:
    _SIDECAR_EVENTS["quarantine"].inc()
    logger.warning(
        "plan-stats sidecar %s is %s; quarantining (static decisions "
        "continue — stats are hints, never correctness)", path, why,
    )
    try:
        os.unlink(path)
    except OSError:  # pragma: no cover - already gone / perms
        pass


def lookup(fp: str) -> Optional[dict]:
    """The stats record for one plan fingerprint, or None (no history,
    re-optimization disabled, or a quarantined sidecar). Never raises."""
    if not reopt_enabled():
        return None
    with _LOCK:
        hit = _MEM.get(fp)
        if hit is not None:
            _MEM.move_to_end(fp)
            # deep copy: the record nests dicts that record_execution
            # merges into — a shallow copy would let a concurrent merge
            # mutate what this caller is reading outside the lock
            return copy.deepcopy(hit)
    path = _sidecar_path(fp)
    if path is None or not os.path.exists(path):
        return None
    try:
        with open(path, "r") as f:
            rec = json.load(f)
    except (OSError, ValueError) as e:
        _quarantine(path, f"unreadable ({type(e).__name__})")
        return None
    if not _valid(rec, fp):
        _quarantine(path, "stale (format/fingerprint mismatch)")
        return None
    _SIDECAR_EVENTS["load"].inc()
    with _LOCK:
        _MEM[fp] = rec
        while len(_MEM) > _MEM_MAX:
            _MEM.popitem(last=False)
    return copy.deepcopy(rec)


#: Bound on recorded per-stage profile entries (one execution's stages).
_PROFILE_MAX = 32


def _merge(rec: dict, *, agg: Optional[dict], joins: Optional[dict],
           push: Optional[dict], wall_s: Optional[float],
           profile: Optional[List[dict]] = None) -> dict:
    rec["execs"] = int(rec.get("execs", 0)) + 1
    if agg:
        a = rec.setdefault("agg", {})
        a.update({k: v for k, v in agg.items() if k != "num_groups"})
        if "num_groups" in agg:
            a["num_groups"] = int(agg["num_groups"])
            counts = [int(c) for c in a.get("counts", [])]
            if int(agg["num_groups"]) not in counts:
                counts.append(int(agg["num_groups"]))
            a["counts"] = counts[-_OBS_MAX:]
    if joins:
        j = rec.setdefault("joins", {})
        for key, obs in joins.items():
            j.setdefault(key, {}).update(obs)
    if push:
        rec.setdefault("push", {}).update(push)
    if wall_s is not None:
        rec["wall_s"] = round(float(wall_s), 6)
    if profile:
        # replace, not merge: the profile is the LAST execution's
        # per-stage breakdown (wall/rows/bytes/strategy/compile split)
        # — EXPLAIN ANALYZE shows what just happened, not an average
        rec["profile"] = [
            {k: (round(float(v), 6) if isinstance(v, float) else v)
             for k, v in entry.items()}
            for entry in profile[:_PROFILE_MAX]
        ]
    return rec


def record_execution(fp: str, *, agg: Optional[dict] = None,
                     joins: Optional[dict] = None,
                     push: Optional[dict] = None,
                     wall_s: Optional[float] = None,
                     profile: Optional[List[dict]] = None) -> None:
    """Merge one execution's observations into the record and persist
    the sidecar (best-effort: a write failure logs and moves on)."""
    if not reopt_enabled():
        return
    with _LOCK:
        rec = _MEM.get(fp)
        if rec is None:
            rec = {"v": FORMAT_VERSION, "fp": fp, "execs": 0}
        # deep copy before merging: _merge mutates nested dicts, and
        # records handed out by lookup() must stay frozen snapshots
        rec = _merge(copy.deepcopy(rec), agg=agg, joins=joins,
                     push=push, wall_s=wall_s, profile=profile)
        _MEM[fp] = rec
        _MEM.move_to_end(fp)
        while len(_MEM) > _MEM_MAX:
            _MEM.popitem(last=False)
    path = _sidecar_path(fp)
    if path is None:
        return
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump(rec, f, sort_keys=True)
        os.replace(tmp, path)
        _SIDECAR_EVENTS["store"].inc()
    except OSError as e:  # pragma: no cover - disk-full etc.
        logger.debug("plan-stats sidecar write failed: %s", e)


def clear_memory() -> None:
    """Drop the in-memory table (tests; the sidecar is untouched)."""
    global _SW_LOADED
    with _LOCK:
        _MEM.clear()
    with _SW_LOCK:
        _SW.clear()
        _SW_WL.clear()
        _SW_LOADED = False


# ---------------------------------------------------------------------------
# the strategy-wall table: observed per-(decision, strategy) latency
# ---------------------------------------------------------------------------
# The fingerprinted records above answer "what did THIS pipeline do".
# Kernel/epilogue strategy choices need the complementary question:
# "what does each strategy COST on this host, whatever the pipeline" —
# host vs pallas vs jit segment-reduce, fused vs per-stage, per-block
# vs concat epilogue. One process-wide table keyed (decision, strategy)
# holds an EWMA of observed dispatch wall with a sample count, persisted
# as ONE sidecar (`strategy_walls.json`) under the same write-temp →
# atomic-replace / quarantine-on-corrupt contract as the per-fingerprint
# records. Entries not refreshed within STRATEGY_STALE_OBS observations
# of their decision are stale and dropped (counted as quarantine), the
# same hygiene the selectivity records get from _valid().
#
# v2 adds PER-WORKLOAD tables keyed by a chain-fingerprint prefix: a
# join-heavy pipeline and a pointwise scoring pipeline can legitimately
# disagree about, say, per-block vs concat epilogue on the same host.
# ``workload_scope(fp[:12])`` (installed by execute_plan around each
# dispatch) routes observations into BOTH the workload table and the
# global one; lookups prefer the workload table only once it is
# evidence-grade (≥ STRATEGY_WALL_MIN_SAMPLES samples on ≥ 2
# strategies — one-sided evidence can't rank), else fall back to the
# global table. Old v1 sidecars quarantine on load (format bump).

#: Strategy-wall sidecar format; a bump quarantines old sidecars.
#: (v1 → v2: per-workload tables joined the global one, ISSUE 18.)
SW_FORMAT_VERSION = 2
#: EWMA smoothing factor for observed strategy walls.
STRATEGY_WALL_ALPHA = 0.3
#: Minimum samples per strategy before a latency-driven flip may engage.
STRATEGY_WALL_MIN_SAMPLES = 2
#: An entry unrefreshed for this many observations of its decision is
#: stale: dropped instead of consulted (a strategy that stopped being
#: exercised months of observations ago is not evidence).
STRATEGY_STALE_OBS = 256

_SW_LOCK = threading.Lock()
_SW: Dict[str, dict] = {}
# workload fingerprint-prefix → {decision: {"obs": int, "strategies": {}}}
_SW_WL: Dict[str, Dict[str, dict]] = {}  # lint: guarded (under _SW_LOCK)
_SW_LOADED = False
# the active workload scope is per-thread: prefetch workers dispatching
# different chains concurrently must not cross-attribute their walls
_SW_SCOPE = threading.local()


@contextmanager
def workload_scope(workload: Optional[str]):
    """Attribute strategy-wall observations on this thread to
    ``workload`` (a chain-fingerprint prefix) for the duration.
    ``None`` is a no-op scope (observations stay global-only).
    Scopes nest; the innermost wins."""
    prev = getattr(_SW_SCOPE, "wl", None)
    _SW_SCOPE.wl = workload
    try:
        yield
    finally:
        _SW_SCOPE.wl = prev


def current_workload() -> Optional[str]:
    """The workload key observations on this thread attribute to."""
    return getattr(_SW_SCOPE, "wl", None)


def _sw_path() -> Optional[str]:
    d = sidecar_dir()
    if d is None:
        return None
    return os.path.join(d, "strategy_walls.json")


def _sw_valid(rec: object) -> bool:
    # v1 sidecars (no "workloads" slot, pre-workload keying) quarantine:
    # their global EWMAs may encode walls a single dominant workload
    # produced, which is exactly the attribution bug v2 fixes
    return (
        isinstance(rec, dict)
        and rec.get("v") == SW_FORMAT_VERSION
        and rec.get("kind") == "strategy_walls"
        and isinstance(rec.get("tables"), dict)
        and isinstance(rec.get("workloads"), dict)
    )


def _sw_merge_table(dst: Dict[str, dict], tables: dict) -> None:
    for decision, table in tables.items():
        if not isinstance(table, dict):
            continue
        mem = dst.setdefault(decision, {"obs": 0, "strategies": {}})
        mem["obs"] = max(int(mem.get("obs", 0)), int(table.get("obs", 0)))
        for strat, ent in (table.get("strategies") or {}).items():
            if isinstance(ent, dict) and "ewma_s" in ent:
                mem["strategies"].setdefault(strat, dict(ent))


def _sw_load_locked() -> None:
    """Merge the on-disk table into memory once per process (under
    _SW_LOCK). Corrupt/stale files quarantine exactly like records."""
    global _SW_LOADED
    if _SW_LOADED:
        return
    _SW_LOADED = True
    path = _sw_path()
    if path is None or not os.path.exists(path):
        return
    try:
        with open(path, "r") as f:
            rec = json.load(f)
    except (OSError, ValueError) as e:
        _quarantine(path, f"unreadable ({type(e).__name__})")
        return
    if not _sw_valid(rec):
        _quarantine(path, "stale (format/kind mismatch)")
        return
    _SIDECAR_EVENTS["load"].inc()
    _sw_merge_table(_SW, rec["tables"])
    for wl, tables in rec["workloads"].items():
        if isinstance(tables, dict):
            _sw_merge_table(_SW_WL.setdefault(wl, {}), tables)


def _sw_prune_one_locked(table: Optional[dict], decision: str) -> None:
    if not table:
        return
    obs = int(table.get("obs", 0))
    stale = [
        s for s, e in table["strategies"].items()
        if obs - int(e.get("last_obs", 0)) > STRATEGY_STALE_OBS
    ]
    for s in stale:
        del table["strategies"][s]
        _SIDECAR_EVENTS["quarantine"].inc()
        logger.warning(
            "strategy-wall entry (%s, %s) is stale (unrefreshed for >%d "
            "observations); dropping (static decisions continue)",
            decision, s, STRATEGY_STALE_OBS,
        )


def _sw_prune_locked(decision: str) -> None:
    _sw_prune_one_locked(_SW.get(decision), decision)
    for tables in _SW_WL.values():
        _sw_prune_one_locked(tables.get(decision), decision)


def _sw_fold_locked(table: dict, strategy: str, wall_s: float) -> None:
    table["obs"] = int(table.get("obs", 0)) + 1
    ent = table["strategies"].get(strategy)
    if ent is None:
        ent = {"ewma_s": float(wall_s), "n": 0}
        table["strategies"][strategy] = ent
    else:
        a = STRATEGY_WALL_ALPHA
        ent["ewma_s"] = a * float(wall_s) + (1.0 - a) * float(ent["ewma_s"])
    ent["ewma_s"] = round(float(ent["ewma_s"]), 9)
    ent["n"] = int(ent.get("n", 0)) + 1
    ent["last_obs"] = table["obs"]


def observe_strategy_wall(decision: str, strategy: str,
                          wall_s: float) -> None:
    """Fold one observed dispatch wall into the (decision, strategy)
    EWMA — the global table always, and the active :func:`workload_scope`
    table too when one is installed — and persist both (best-effort).
    No-op when re-optimization is disabled — TFTPU_REOPT=0 freezes the
    static cost model."""
    if not reopt_enabled():
        return
    wl = current_workload()
    with _SW_LOCK:
        _sw_load_locked()
        _sw_fold_locked(
            _SW.setdefault(decision, {"obs": 0, "strategies": {}}),
            strategy, wall_s,
        )
        if wl is not None:
            _sw_fold_locked(
                _SW_WL.setdefault(wl, {}).setdefault(
                    decision, {"obs": 0, "strategies": {}}
                ),
                strategy, wall_s,
            )
        _sw_prune_locked(decision)
        snapshot = copy.deepcopy(_SW)
        wl_snapshot = copy.deepcopy(_SW_WL)
    path = _sw_path()
    if path is None:
        return
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump({"v": SW_FORMAT_VERSION, "kind": "strategy_walls",
                       "tables": snapshot, "workloads": wl_snapshot},
                      f, sort_keys=True)
        os.replace(tmp, path)
        _SIDECAR_EVENTS["store"].inc()
    except OSError as e:  # pragma: no cover - disk-full etc.
        logger.debug("strategy-wall sidecar write failed: %s", e)


def reset_strategy_walls(unlink_sidecar: bool = True) -> None:
    """Drop the strategy-wall table — memory and (by default) the
    sidecar file. For tests and the bench's decision-flip smoke leg,
    which inject synthetic walls to force a flip and must not leave
    them behind for real runs to act on. ``unlink_sidecar=False``
    forgets only this process's memory (the per-test isolation hook:
    the table stays empty because the file is not re-merged either)."""
    global _SW_LOADED
    with _SW_LOCK:
        _SW.clear()
        _SW_WL.clear()
        _SW_LOADED = True  # do not re-merge the file being dropped
    if not unlink_sidecar:
        return
    path = _sw_path()
    if path is not None:
        try:
            os.unlink(path)
        except OSError:
            pass


def strategy_walls(decision: str) -> Dict[str, dict]:
    """Observed-wall entries for one decision: ``{strategy: {"ewma_s",
    "n", "last_obs"}}``, stale entries already dropped. Inside a
    :func:`workload_scope`, the workload's own table answers — but only
    once it is evidence-grade (≥ STRATEGY_WALL_MIN_SAMPLES samples on
    ≥ 2 strategies; a table that has only ever seen one strategy cannot
    rank alternatives) — else the global table is the fallback. Empty
    when re-optimization is disabled or nothing was observed. Never
    raises."""
    if not reopt_enabled():
        return {}
    wl = current_workload()
    with _SW_LOCK:
        _sw_load_locked()
        _sw_prune_locked(decision)
        if wl is not None:
            table = (_SW_WL.get(wl) or {}).get(decision)
            if table:
                ranked = [
                    e for e in table["strategies"].values()
                    if int(e.get("n", 0)) >= STRATEGY_WALL_MIN_SAMPLES
                ]
                if len(ranked) >= 2:
                    return copy.deepcopy(table["strategies"])
        table = _SW.get(decision)
        if not table:
            return {}
        return copy.deepcopy(table["strategies"])
