"""Unit tests for the hash/range-partitioned exchange (ops/exchange.py,
VERDICT r4 #2 — ≙ Catalyst's shuffle exchange, DebugRowOps.scala:583).

The cross-PROCESS data plane is exercised by the real 2-/4-process
fleets in tests/test_distributed.py; here the partitioners' invariants
(cross-process determinism is a pure function of VALUES) and the
single-process degenerate exchange run on the virtual mesh.
"""

import numpy as np

from tensorframes_tpu.ops import exchange as xch


def test_content_hash_is_value_determined():
    """Same values → same hashes, independent of array order, container
    (list vs ndarray), or integer width — the property that makes
    partition assignment agree across processes."""
    a = np.asarray([5, -3, 7, 5], np.int64)
    b = np.asarray([7, 5, 5, -3], np.int32)  # same values, other order/width
    ha = xch.content_hash64([a])
    hb = xch.content_hash64([b])
    assert ha[0] == ha[3] == hb[1] == hb[2]
    assert ha[1] == hb[3] and ha[2] == hb[0]
    # strings: list and object-array containers agree
    hs1 = xch.content_hash64([["x", "yy", "x"]])
    hs2 = xch.content_hash64([np.asarray(["yy", "x"], dtype=object)])
    assert hs1[0] == hs1[2] == hs2[1] and hs1[1] == hs2[0]
    # floats: int-typed and float-typed SAME semantics stay separate
    # hashes per dtype family is fine, but -0.0/+0.0 and NaN/NaN agree
    hf = xch.content_hash64([np.asarray([0.0, -0.0, np.nan, np.nan])])
    assert hf[0] == hf[1] and hf[2] == hf[3]
    # f32 and f64 carrying the same value agree (both canonicalize f64)
    h32 = xch.content_hash64([np.asarray([1.5, 2.5], np.float32)])
    h64 = xch.content_hash64([np.asarray([1.5, 2.5], np.float64)])
    np.testing.assert_array_equal(h32, h64)


def test_content_hash_spreads():
    """Sanity: 10k distinct keys spread over 8 partitions within 2x of
    uniform (splitmix64 avalanche)."""
    part = xch.partition_by_hash([np.arange(10_000)], 8)
    counts = np.bincount(part, minlength=8)
    assert counts.min() > 10_000 / 8 / 2, counts
    assert counts.max() < 10_000 / 8 * 2, counts


def test_lex_geq_matches_python_tuples():
    rng = np.random.default_rng(0)
    cols = [rng.integers(0, 4, 200), rng.integers(0, 4, 200)]
    cols = [c.astype(np.int64) for c in cols]
    for asc in [(True, True), (True, False), (False, True)]:
        for split in [(1, 2), (0, 0), (3, 3)]:
            got = xch._lex_geq(cols, split, asc)

            def key(i, j):
                return (
                    cols[0][i] if asc[0] else -cols[0][i],
                    cols[1][i] if asc[1] else -cols[1][i],
                ) if j is None else (
                    split[0] if asc[0] else -split[0],
                    split[1] if asc[1] else -split[1],
                )

            want = np.asarray(
                [key(i, None) >= key(i, 0) for i in range(200)]
            )
            np.testing.assert_array_equal(got, want, err_msg=str((asc, split)))


def test_partition_by_range_orders_partitions():
    """Partition ids must be monotone along the requested sort order:
    sorting the frame and reading partition ids gives a non-decreasing
    sequence, and ids cover a reasonable spread (splitters from the
    deterministic sample)."""
    rng = np.random.default_rng(1)
    k = rng.integers(0, 1000, 5000).astype(np.int64)
    part = xch.partition_by_range([k], 4, [True])
    order = np.argsort(k, kind="stable")
    assert (np.diff(part[order]) >= 0).all()
    assert part.min() == 0 and part.max() == 3
    counts = np.bincount(part, minlength=4)
    assert counts.min() > 5000 / 4 / 3, counts  # rough balance
    # descending: partition 0 must hold the LARGEST keys
    part_d = xch.partition_by_range([k], 4, [False])
    order_d = np.argsort(-k, kind="stable")
    assert (np.diff(part_d[order_d]) >= 0).all()
    assert k[part_d == 0].min() >= k[part_d == 3].max()


def test_partition_by_range_multikey_strings():
    names = np.asarray(
        ["b", "a", "c", "a", "b", "c", "a", "b"] * 50, dtype=object
    )
    sub = np.tile(np.arange(8), 50).astype(np.int64)
    part = xch.partition_by_range([names, sub], 3, [True, False])
    # monotone along the (name asc, sub desc) lexicographic order
    from tensorframes_tpu.ops.keys import _unique_inverse

    c0 = _unique_inverse(names)[1]
    order = np.lexsort((-sub, c0))
    assert (np.diff(part[order]) >= 0).all()
    assert part.max() >= 1  # actually split somewhere


def test_exchange_rows_single_process_identity():
    cols = {
        "v": np.arange(6, dtype=np.float32),
        "s": ["a", "b", "c", "d", "e", "f"],
    }
    part = np.zeros(6, np.int64)
    out = xch.exchange_rows(cols, part)
    np.testing.assert_array_equal(out["v"], cols["v"])
    assert out["s"] == cols["s"]
    stats = xch.last_exchange_stats
    assert stats is not None
    assert len(stats["sent"]) == 1 and len(stats["received"]) == 1


def test_global_frame_bytes_counts_cells():
    cols = {
        "v": np.zeros((10, 4), np.float32),  # 160 bytes
        "s": ["xx"] * 10,  # 20 bytes of utf-8
    }
    got = xch.global_frame_bytes(cols)
    assert got == 160 + 20, got


def test_sort_values_exchange_guard_message():
    """With the exchange disabled and a tiny budget, a multi-process
    sort must raise the actionable guard — single-process frames never
    hit the guard (no replication happens)."""
    import tensorframes_tpu as tfs
    from tensorframes_tpu.config import configure

    fr = tfs.frame_from_arrays(
        {"k": np.arange(100).astype(np.float32)}
    )
    configure(relational_broadcast_bytes=8, relational_exchange=False)
    try:
        # single process: spans is False, so the sort takes the local
        # path and succeeds regardless of the budget
        out = fr.sort_values("k")
        v = np.asarray(out.column_values("k"))
        assert (np.diff(v) >= 0).all()
    finally:
        configure(
            relational_broadcast_bytes=64 << 20, relational_exchange=True
        )


def test_content_hash_mixed_dtype_families_agree():
    """Code-review r5: the broadcast join compares key unions after
    numpy promotion (int+float -> f64), so 5 must hash like 5.0 — a
    size-triggered switch to the hash exchange must not change which
    rows match. Bool/int/uint/float and numeric OBJECT cells all hash
    through canonical f64 bits."""
    ints = xch.content_hash64([np.asarray([5, 0, -3], np.int64)])
    flts = xch.content_hash64([np.asarray([5.0, -0.0, -3.0])])
    np.testing.assert_array_equal(ints, flts)
    bools = xch.content_hash64([np.asarray([True, False])])
    ones = xch.content_hash64([np.asarray([1.0, 0.0])])
    np.testing.assert_array_equal(bools, ones)
    objs = xch.content_hash64([[5, 0.0, True, "x"]])
    assert objs[0] == ints[0] and objs[1] == flts[1]
    assert objs[2] == bools[0]


def test_exchange_chunked_rounds_reassemble(monkeypatch):
    """Code-review r5: the all_to_all pads to the max payload — chunked
    rounds bound per-round memory under skew. Force multi-round via a
    tiny round budget and check byte-exact reassembly."""
    monkeypatch.setattr(xch, "_EXCHANGE_ROUND_BYTES", 1 << 16)
    rng = np.random.default_rng(3)
    cols = {
        "v": rng.standard_normal(50_000).astype(np.float64),  # 400 KB
        "s": [f"row{i}" for i in range(50_000)],
    }
    out = xch.exchange_rows(cols, np.zeros(50_000, np.int64))
    np.testing.assert_array_equal(out["v"], cols["v"])
    assert out["s"] == cols["s"]
    stats = xch.last_exchange_stats
    assert stats["rounds"] > 1, stats


def test_simulated_shuffle_join_equals_global():
    """The shuffle-join invariant, simulated in-process: hash-partition
    BOTH sides into P buckets, join each bucket pair locally, and the
    union must equal the global join — over the key types the real
    fleet test can't sweep (strings, NaN floats, ±0.0, and int-vs-float
    sides whose equality only appears after numpy promotion)."""
    import pandas as pd

    P = 4
    rng = np.random.default_rng(5)

    def global_join(l, r):
        return pd.merge(
            pd.DataFrame(l), pd.DataFrame(r), on="k", how="inner"
        )

    def check(left, right):
        want = global_join(left, right)
        lpart = xch.partition_by_hash([left["k"]], P)
        rpart = xch.partition_by_hash([right["k"]], P)
        pieces = []
        for p in range(P):
            lsub = {n: np.asarray(v, dtype=object)[lpart == p].tolist()
                    if isinstance(v, list) else v[lpart == p]
                    for n, v in left.items()}
            rsub = {n: np.asarray(v, dtype=object)[rpart == p].tolist()
                    if isinstance(v, list) else v[rpart == p]
                    for n, v in right.items()}
            if len(lsub["k"]) and len(rsub["k"]):
                pieces.append(global_join(lsub, rsub))
        got = pd.concat(pieces) if pieces else want.iloc[:0]
        key = lambda df: sorted(
            map(repr, df[["k", "v", "w"]].to_numpy().tolist())
        )
        assert key(got) == key(want)
        assert len(want) > 0  # the sweep actually joined something

    # INT64 left vs FLOAT64 right: equality appears only after numpy
    # promotion; the canonical-f64 hash must colocate 5 with 5.0
    lk_i = rng.integers(0, 12, 60)  # stays int64
    assert lk_i.dtype == np.int64
    rk_f = rng.integers(0, 12, 40).astype(np.float64)
    rk_f[0] = -0.0  # ±0.0 must meet (+0.0 keys exist on the left)
    check(
        {"k": lk_i, "v": np.arange(60, dtype=np.float64)},
        {"k": rk_f, "w": np.arange(40, dtype=np.float64)},
    )
    # STRING keys as host lists (the per-cell crc path)
    check(
        {"k": [f"s{v}" for v in rng.integers(0, 9, 50)],
         "v": np.arange(50, dtype=np.float64)},
        {"k": [f"s{v}" for v in rng.integers(0, 9, 30)],
         "w": np.arange(30, dtype=np.float64)},
    )
    # NaN float keys: pandas merge matches NaN to NaN (hash must
    # colocate every NaN in one partition for that to survive)
    lk_n = rng.integers(0, 6, 40).astype(np.float64)
    rk_n = rng.integers(0, 6, 25).astype(np.float64)
    lk_n[[1, 7]] = np.nan
    rk_n[[2]] = np.nan
    check(
        {"k": lk_n, "v": np.arange(40, dtype=np.float64)},
        {"k": rk_n, "w": np.arange(25, dtype=np.float64)},
    )


def test_simulated_range_sort_equals_global():
    """The range-sort invariant, simulated in-process: partition by
    sampled splitters, sort each partition, concatenate in partition
    order — must equal the global stable sort, including NaN keys
    (numpy convention: NaN last ascending) and multi-key descending."""
    rng = np.random.default_rng(6)
    P = 4
    k1 = rng.standard_normal(500)
    k1[[7, 123, 400]] = np.nan
    k2 = rng.integers(0, 5, 500).astype(np.int64)
    tag = np.arange(500)

    for asc in ([True, True], [True, False]):
        part = xch.partition_by_range([k1, k2], P, asc)
        from tensorframes_tpu.ops.keys import _unique_inverse

        def order_of(idx):
            c1 = _unique_inverse(k1[idx])[1]
            c2 = _unique_inverse(k2[idx])[1]
            ks = [c2 if asc[1] else -c2, c1 if asc[0] else -c1]
            return idx[np.lexsort(ks)]

        got = np.concatenate(
            [order_of(np.flatnonzero(part == p)) for p in range(P)]
        )
        want = order_of(np.arange(500))
        np.testing.assert_array_equal(tag[got], tag[want])
