"""Weight-only int8 quantization: round-trip accuracy, pytree behavior,
transformer integration (embedding quality + generation), HBM accounting."""

import numpy as np

import jax
import jax.numpy as jnp

import tensorframes_tpu as tfs
from tensorframes_tpu.ops import quantize as qt
from tensorframes_tpu.models import generation as gen
from tensorframes_tpu.models import transformer as tr


def test_quantize_roundtrip_accuracy():
    rng = np.random.default_rng(0)
    w = rng.standard_normal((64, 32)).astype(np.float32)
    q = qt.quantize(w)
    assert q.q.dtype == jnp.int8 and q.q.shape == w.shape
    assert q.scale.shape == (1, 32)  # per output channel
    back = np.asarray(q.dequantize())
    # symmetric int8: worst-case error is scale/2 per element
    err = np.abs(back - w)
    bound = np.asarray(q.scale)[0] / 2 + 1e-7
    assert (err <= bound).all()


def test_quantize_zero_and_outlier_channels():
    w = np.zeros((16, 4), np.float32)
    w[:, 1] = 1000.0  # outlier channel must not poison others
    w[:, 2] = 0.001
    q = qt.quantize(w)
    back = np.asarray(q.dequantize())
    np.testing.assert_allclose(back[:, 0], 0.0)
    np.testing.assert_allclose(back[:, 1], 1000.0, rtol=1e-2)
    np.testing.assert_allclose(back[:, 2], 0.001, rtol=1e-2)


def test_quantized_tensor_is_pytree_and_jits():
    w = np.random.default_rng(1).standard_normal((8, 8)).astype(np.float32)
    q = qt.quantize(w)
    fn = jax.jit(lambda x, q: x @ qt.asarray(q, x.dtype))
    x = jnp.ones((2, 8), jnp.float32)
    out = fn(x, q)  # QuantizedTensor crosses the jit boundary as a pytree
    np.testing.assert_allclose(np.asarray(out), np.asarray(x @ w), atol=0.2)


def test_quantize_tree_skips_small_and_int_leaves():
    params = {
        "w": np.random.default_rng(2).standard_normal((16, 16)).astype(np.float32),
        "b": np.zeros((16,), np.float32),
        "steps": np.asarray(3),
    }
    out = qt.quantize_tree(params)
    assert isinstance(out["w"], qt.QuantizedTensor)
    assert not isinstance(out["b"], qt.QuantizedTensor)
    assert not isinstance(out["steps"], qt.QuantizedTensor)


def test_transformer_quantized_embeddings_close():
    cfg = tr.tiny()
    params = tr.init_params(cfg, seed=0)
    qparams = tr.quantize_params(params)
    tokens, _ = tr.synthetic_batch(cfg, 4, 16, seed=0)
    full = np.asarray(tr.forward(cfg, params, tokens), np.float32)
    quant = np.asarray(tr.forward(cfg, qparams, tokens), np.float32)
    # int8 weights: embeddings stay close in cosine similarity per row
    a = full.reshape(4, -1)
    b = quant.reshape(4, -1)
    cos = (a * b).sum(1) / (np.linalg.norm(a, axis=1) * np.linalg.norm(b, axis=1))
    assert (cos > 0.99).all(), cos
    # ~4x weight compression on the quantized leaves
    assert qt.tree_nbytes(qparams) < 0.65 * qt.tree_nbytes(params)


def test_quantized_generation_runs():
    cfg = gen.gpt_tiny()
    params = tr.init_params(cfg, seed=0)
    qparams = tr.quantize_params(params)
    prompts = np.random.default_rng(3).integers(0, cfg.vocab_size, (2, 6)).astype(np.int32)
    toks = np.asarray(gen.generate(cfg, qparams, prompts, 5))
    assert toks.shape == (2, 5)
    assert (toks >= 0).all() and (toks < cfg.vocab_size).all()


def test_quantized_scoring_via_map_blocks():
    cfg = tr.tiny()
    params = tr.quantize_params(tr.init_params(cfg, seed=0))
    tokens, _ = tr.synthetic_batch(cfg, 6, 12, seed=1)
    df = tfs.frame_from_arrays({"tokens": tokens}, num_blocks=2)
    prog = tr.embed_program(cfg, params)
    out = tfs.map_blocks(lambda tokens: prog(tokens), df)
    emb = np.stack([r["embedding"] for r in out.collect()])
    assert emb.shape == (6, cfg.hidden)
    assert np.isfinite(emb).all()


def test_quantize_tree_idempotent():
    """Re-quantizing an already-quantized tree passes leaves through
    untouched (tree_map must not descend into QuantizedTensor and
    quantize its scale array)."""
    params = {"w": np.random.default_rng(4).standard_normal((16, 16)).astype(np.float32)}
    q1 = qt.quantize_tree(params)
    q2 = qt.quantize_tree(q1)
    assert isinstance(q2["w"], qt.QuantizedTensor)
    assert not isinstance(q2["w"].scale, qt.QuantizedTensor)
    np.testing.assert_array_equal(
        np.asarray(q2["w"].dequantize()), np.asarray(q1["w"].dequantize())
    )


def test_quantized_tree_checkpoints(tmp_path):
    """QuantizedTensor trees ride the npz checkpoint backend like any
    other params (int8 q + f32 scale are just pytree leaves)."""
    from tensorframes_tpu.checkpoint import Checkpointer

    cfg = tr.tiny()
    qparams = tr.quantize_params(tr.init_params(cfg, seed=0))
    ck = Checkpointer(str(tmp_path), backend="npz")
    ck.save(1, qparams)
    back = ck.restore(step=1, like=qparams)
    lq = qparams["layers"][0]["attn"]["qkv"]
    lb = back["layers"][0]["attn"]["qkv"]
    assert isinstance(lb, qt.QuantizedTensor)
    np.testing.assert_array_equal(np.asarray(lb.q), np.asarray(lq.q))
    np.testing.assert_array_equal(np.asarray(lb.scale), np.asarray(lq.scale))
    # restored tree scores identically
    tokens, _ = tr.synthetic_batch(cfg, 2, 8, seed=0)
    np.testing.assert_array_equal(
        np.asarray(tr.forward(cfg, qparams, tokens), np.float32),
        np.asarray(tr.forward(cfg, back, tokens), np.float32),
    )


def test_quantized_conv_models_close():
    """VGG/Inception int8 trees: same scoring path, close logits."""
    from tensorframes_tpu.models import inception as inc
    from tensorframes_tpu.models import vgg

    for mod in (vgg, inc):
        cfg = mod.tiny()
        params = mod.init_params(cfg, seed=0)
        qparams = mod.quantize_params(params)
        imgs = mod.synthetic_images(cfg, 2, seed=0)
        a = np.asarray(mod.forward(cfg, params, imgs), np.float32)
        b = np.asarray(mod.forward(cfg, qparams, imgs), np.float32)
        cos = (a * b).sum() / (np.linalg.norm(a) * np.linalg.norm(b))
        assert cos > 0.98, (mod.__name__, cos)
        assert qt.tree_nbytes(qparams) < 0.5 * qt.tree_nbytes(params)


def test_int8_frozen_weights_survive_to_executable():
    """VERDICT r2 #7: make the int8 claim a NUMBER before TPU validates
    it. Round 3 found the serious bug hiding here: with weights embedded
    as HLO literals, XLA CONSTANT-FOLDED the dequantize back into a full
    f32 weight — the quantized program had byte-identical cost to f32,
    i.e. int8 did nothing. The fix is two-part: (a) the executor hoists
    program constants to runtime arguments (config.hoist_constants), and
    (b) MatMul/Conv consume QuantizedTensor natively — int8 enters the
    contraction, the per-channel scale multiplies the output, no f32
    weight is ever materialized.

    This test pins the structural facts any backend must preserve:
    the int8 weight reaches the compiled executable as ``s8`` (not
    folded), the program's hoisted parameter bytes are ~4x smaller, and
    the numerics hold. (The HBM *traffic* number is a TPU measurement —
    the CPU backend materializes the convert regardless; see BASELINE.md
    TPU checklist.)"""
    import numpy as np
    import jax

    from tensorframes_tpu.graphdef import GraphNode, _Attr, program_from_graphdef

    rng = np.random.default_rng(0)
    w = rng.standard_normal((512, 512)).astype(np.float32)

    def build(quant):
        dtype_a = _Attr()
        dtype_a.type = 1
        shape_a = _Attr()
        shape_a.shape = [-1, 512]
        val_a = _Attr()
        val_a.tensor = w
        nodes = [
            GraphNode("x", "Placeholder", [], {"dtype": dtype_a, "shape": shape_a}),
            GraphNode("w", "Const", [], {"value": val_a}),
            GraphNode("m", "MatMul", ["x", "w"], {}),
        ]
        return program_from_graphdef(nodes, fetches=["m"], quantize_weights=quant)

    def hoisted_compile(prog):
        from tensorframes_tpu.program import HoistedProgram

        hp = HoistedProgram(
            prog.fn, {"x": jax.ShapeDtypeStruct((8, 512), np.float32)}
        )
        return hp.aot_compile().as_text(), hp.const_bytes()

    hlo_f32, bytes_f32 = hoisted_compile(build(False))
    hlo_q, bytes_q = hoisted_compile(build(True))
    assert "s8[512,512]" in hlo_q, "int8 weight was folded out of the HLO"
    assert "s8[" not in hlo_f32
    # 1 MiB f32 weight vs 256 KiB int8 + 2 KiB f32 scales ≈ 4.0x
    assert bytes_f32 > 3.9 * bytes_q, (bytes_f32, bytes_q)
    # and the programs still agree numerically
    x = rng.standard_normal((4, 512)).astype(np.float32)
    got_q = np.asarray(build(True).fn({"x": x})["m"])
    want = x @ w
    np.testing.assert_allclose(got_q, want, rtol=0.05, atol=0.05 * np.abs(want).max())


def test_fused_dequant_matmul_matches_dequantize():
    """ops/quantize.matmul: (x @ q) * s must equal x @ (q * s) — the
    per-output-channel scale commutes out of the contraction, which is
    what lets int8 weights stream from HBM without a materialized
    dequantized copy (VERDICT r3 #4)."""
    import jax.numpy as jnp
    import numpy as np

    from tensorframes_tpu.ops import quantize as qz

    rng = np.random.default_rng(0)
    w = rng.standard_normal((64, 48)).astype(np.float32)
    x = rng.standard_normal((16, 64)).astype(np.float32)
    qt = qz.quantize(w)
    got = qz.matmul(jnp.asarray(x), qt)
    want = jnp.asarray(x) @ qt.dequantize(jnp.float32)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )
    # plain weights pass straight through (cast to x.dtype)
    got_plain = qz.matmul(jnp.asarray(x), w)
    np.testing.assert_allclose(
        np.asarray(got_plain), x @ w, rtol=1e-6, atol=1e-6
    )
    # a scale layout that spans contracted axes falls back to explicit
    # dequantize (correctness over fusion)
    qt_row = qz.quantize(w, channel_axis=0)  # scale [in, 1]: no commute
    got_row = qz.matmul(jnp.asarray(x), qt_row)
    want_row = jnp.asarray(x) @ qt_row.dequantize(jnp.float32)
    np.testing.assert_allclose(
        np.asarray(got_row), np.asarray(want_row), rtol=2e-5, atol=2e-5
    )


def test_pallas_int8_matmul_matches_structural_fusion():
    """Round 5 (VERDICT r4 #3 'consider'): the pallas in-kernel-dequant
    matmul must agree with quantize.matmul's structural fusion across
    shapes (incl. non-tile-multiple dims and 3-D activations), run in
    interpret mode on CPU. The real-TPU speed adjudication lives in
    dev/tpu_smoke.py."""
    import jax.numpy as jnp

    from tensorframes_tpu.ops import quantize as qz

    rng = np.random.default_rng(0)
    for (m_shape, k, n) in [((4,), 96, 160), ((2, 3), 128, 256),
                            ((5,), 70, 100)]:
        x = jnp.asarray(
            rng.standard_normal((*m_shape, k)), jnp.float32
        )
        w = qz.quantize(
            jnp.asarray(rng.standard_normal((k, n)), jnp.float32)
        )
        want = qz.matmul(x, w)
        got = qz.matmul_pallas_int8(x, w, interpret=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )


def test_pallas_int8_matmul_gate_defaults_off():
    """The kernel is opt-in until hardware adjudicates it: with the
    config flag off (default), quantize.matmul must not attempt pallas
    on any backend."""
    import jax.numpy as jnp

    from tensorframes_tpu.config import get_config
    from tensorframes_tpu.ops import quantize as qz

    assert get_config().pallas_int8_matmul is False
    x = jnp.ones((2, 32), jnp.float32)
    w = qz.quantize(jnp.ones((32, 64), jnp.float32))
    assert not qz._pallas_int8_eligible(x, w)
    # and the default path still answers
    out = qz.matmul(x, w)
    assert out.shape == (2, 64)
