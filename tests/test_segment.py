"""Custom pallas segment-sum kernel tests (interpreter mode on CPU): the
one-hot MXU formulation must agree with XLA's scatter-based segment_sum
across padding edge cases, and the dispatcher must stay correct."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorframes_tpu.ops import segment


def _ref(values, seg_ids, num_segments):
    return np.asarray(
        jax.ops.segment_sum(values, seg_ids, num_segments=num_segments)
    )


@pytest.mark.parametrize(
    "n,d,s",
    [
        (10, 3, 4),        # everything unaligned
        (256, 128, 8),     # exactly tile-aligned
        (300, 130, 9),     # crosses tile and lane boundaries
        (5, 1, 1),         # single segment, tiny
    ],
)
def test_pallas_matches_xla(n, d, s):
    rng = np.random.default_rng(n + d + s)
    values = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    seg_ids = jnp.asarray(rng.integers(0, s, n), jnp.int32)
    got = np.asarray(
        segment.segment_sum_pallas(values, seg_ids, s, interpret=True)
    )
    np.testing.assert_allclose(got, _ref(values, seg_ids, s), rtol=1e-5, atol=1e-5)


def test_empty_segments_are_zero():
    values = jnp.ones((4, 2), jnp.float32)
    seg_ids = jnp.asarray([0, 0, 3, 3], jnp.int32)
    got = np.asarray(segment.segment_sum_pallas(values, seg_ids, 5, interpret=True))
    np.testing.assert_array_equal(got[1], [0, 0])
    np.testing.assert_array_equal(got[2], [0, 0])
    np.testing.assert_array_equal(got[4], [0, 0])
    np.testing.assert_array_equal(got[0], [2, 2])


def test_unsorted_segment_ids():
    # the kernel does not require key-sorted rows
    values = jnp.asarray([[1.0], [2.0], [4.0], [8.0]], jnp.float32)
    seg_ids = jnp.asarray([1, 0, 1, 0], jnp.int32)
    got = np.asarray(segment.segment_sum_pallas(values, seg_ids, 2, interpret=True))
    np.testing.assert_array_equal(got, [[10.0], [5.0]])


def test_dispatcher_cpu_falls_back_to_xla():
    # on CPU the dispatcher must use XLA (pallas TPU kernels don't run
    # natively here) and still be correct, preserving dtype
    values = jnp.asarray(np.random.default_rng(0).standard_normal((20, 4)))
    seg_ids = jnp.asarray(np.random.default_rng(1).integers(0, 3, 20), jnp.int32)
    got = segment.segment_sum(values, seg_ids, 3)
    assert got.dtype == values.dtype
    np.testing.assert_allclose(np.asarray(got), _ref(values, seg_ids, 3), rtol=1e-6)


def test_aggregate_fast_path_still_correct():
    import tensorframes_tpu as tfs

    rng = np.random.default_rng(2)
    n = 200
    frame = tfs.frame_from_arrays(
        {
            "k": rng.integers(0, 7, n),
            "v": rng.standard_normal(n).astype(np.float32),
        },
        num_blocks=3,
    )
    with tfs.with_graph():
        v_input = tfs.block(frame, "v", tf_name="v_input")
        agg = tfs.aggregate(
            tfs.reduce_sum(v_input, axis=0, name="v"), frame.group_by("k")
        )
    got = {r["k"]: r["v"] for r in agg.collect()}
    ks = np.asarray(frame.column_values("k"))
    vs = np.asarray(frame.column_values("v"))
    for k in np.unique(ks):
        assert got[int(k)] == pytest.approx(float(vs[ks == k].sum()), rel=1e-5)


def test_disable_pallas_kill_switch():
    """A runtime Mosaic failure flips the kill-switch; segment_sum keeps
    working through XLA's scatter path."""
    was = segment._pallas_disabled
    try:
        segment.disable_pallas("test")
        assert not segment.pallas_enabled()
        values = jnp.asarray(
            np.random.default_rng(0).standard_normal((32, 4)), jnp.float32
        )
        seg_ids = jnp.asarray(
            np.random.default_rng(1).integers(0, 5, 32), jnp.int32
        )
        got = segment.segment_sum(values, seg_ids, 5)
        np.testing.assert_allclose(
            np.asarray(got), _ref(values, seg_ids, 5), rtol=1e-6
        )
    finally:
        segment._pallas_disabled = was


def test_aggregate_retries_after_kernel_compile_failure(monkeypatch):
    """aggregate's segment fast path must survive a first-call kernel
    failure: disable pallas, re-trace, return the right answer."""
    import tensorframes_tpu as tfs
    from tensorframes_tpu.ops import verbs

    real = verbs._seg_fast_for.__wrapped__
    calls = {"n": 0}

    def flaky(ops, num_groups):
        fn = real(ops, num_groups)

        def wrapper(vals, sids):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("Mosaic failed to compile TPU kernel")
            return fn(vals, sids)

        return wrapper

    from functools import lru_cache

    monkeypatch.setattr(verbs, "_seg_fast_for", lru_cache(maxsize=8)(flaky))
    # pin the JITTED segment path: on the CPU backend float sums
    # normally take the host bincount lowering (no kernel to fail),
    # which would leave the retry-under-test unreached
    monkeypatch.setattr(segment, "host_segment_eligible", lambda *a: False)
    was = segment._pallas_disabled
    try:
        segment._pallas_disabled = False
        rng = np.random.default_rng(3)
        n = 100
        frame = tfs.frame_from_arrays(
            {
                "k": rng.integers(0, 4, n),
                "v": rng.standard_normal(n).astype(np.float32),
            }
        )
        with tfs.with_graph():
            v_input = tfs.block(frame, "v", tf_name="v_input")
            agg = tfs.aggregate(
                tfs.reduce_sum(v_input, axis=0, name="v"), frame.group_by("k")
            )
        got = {r["k"]: r["v"] for r in agg.collect()}
        assert calls["n"] == 2  # failed once, retried once
        assert not segment.pallas_enabled()
        ks = np.asarray(frame.column_values("k"))
        vs = np.asarray(frame.column_values("v"))
        for k in np.unique(ks):
            assert got[int(k)] == pytest.approx(
                float(vs[ks == k].sum()), rel=1e-5
            )
    finally:
        segment._pallas_disabled = was


# ---------------------------------------------------------------------------
# segment_reduce_host edge pins (ISSUE 12 bugfix sweep)
# ---------------------------------------------------------------------------

def test_host_reduce_zero_rows_returns_zeros_and_nan_means():
    """Empty feed: ``np.asarray([])`` is float64 and bincount rejects
    float ids — the host path must short-circuit instead, producing
    zeros for sums and 0/0 → NaN for means (the jitted program's exact
    empty-segment bits), in the value dtype, without warnings."""
    import warnings

    for seg_ids in (np.asarray([], np.int64), []):
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            out = segment.segment_reduce_host(
                (("a", "reduce_sum"), ("b", "reduce_mean")),
                3,
                {"a": np.asarray([], np.float32),
                 "b": np.asarray([], np.float64)},
                seg_ids,
            )
        assert out["a"].dtype == np.float32
        np.testing.assert_array_equal(out["a"], np.zeros(3, np.float32))
        assert out["b"].dtype == np.float64
        assert np.isnan(out["b"]).all()


def test_host_reduce_all_padding_segments_mean_is_silent_nan():
    """Segments past the max observed id (the bucketing shape): means
    read NaN on the padded slots without a numpy warning leaking, and
    the real slots carry the bincount answer."""
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out = segment.segment_reduce_host(
            (("v", "reduce_mean"),),
            6,
            {"v": np.asarray([2.0, 4.0, 10.0], np.float32)},
            np.asarray([1, 1, 3]),
        )
    assert out["v"][1] == pytest.approx(3.0)
    assert out["v"][3] == pytest.approx(10.0)
    assert np.isnan(out["v"][[0, 2, 4, 5]]).all()


def test_host_reduce_list_seg_ids_cast_to_int():
    """Python-list ids (the eager path can hand them over) bincount
    fine after the intp cast."""
    out = segment.segment_reduce_host(
        (("v", "reduce_sum"),), 2,
        {"v": np.asarray([1.5, 2.5, 4.0], np.float32)},
        [0, 1, 0],
    )
    np.testing.assert_allclose(out["v"], [5.5, 2.5])
