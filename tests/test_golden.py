"""Golden-program oracle tests.

≙ the reference's cross-language oracle (ExtractNodes.scala:13-76): there,
the Scala DSL's emitted GraphDef node protos were asserted byte-identical
to what real Python TensorFlow produced. Here the oracle is the JAX tracer
itself: the DSL's compiled Program must lower to the SAME jaxpr (and the
same StableHLO module) as the equivalent hand-written jnp function traced
directly. Divergence means the DSL is emitting different primitives than
the native API — exactly the regression the reference's suite guarded
against.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.dsl.node import compile_fetches


def _feeds(**shapes):
    return {
        name: jnp.zeros(shape, jnp.float64) for name, shape in shapes.items()
    }


def _jaxpr(fn, feeds):
    return str(jax.make_jaxpr(fn)(feeds))


def _stablehlo(fn, feeds):
    text = jax.jit(fn).lower(feeds).as_text()
    # strip location metadata and module naming — semantically irrelevant
    text = re.sub(r"loc\([^)]*\)", "", text)
    text = re.sub(r"#loc\d*( = .*)?", "", text)
    text = re.sub(r"@\w+", "@f", text)
    text = re.sub(r"module\s+@\S+", "module", text)
    return "\n".join(l.rstrip() for l in text.splitlines() if l.strip())


def _dsl_program(build):
    with tfs.with_graph():
        fetches = build()
        return compile_fetches(
            fetches if isinstance(fetches, (list, tuple)) else [fetches]
        )


CASES = [
    (
        "add_constant",
        lambda: tfs.add(tfs.placeholder(np.float64, (None,), name="x"), 3.0, name="z"),
        lambda feeds: {"z": feeds["x"] + 3.0},
        {"x": (4,)},
    ),
    (
        "identity",
        lambda: tfs.identity(tfs.placeholder(np.float64, (None,), name="x"), name="y"),
        lambda feeds: {"y": feeds["x"]},
        {"x": (4,)},
    ),
    (
        "reduce_sum_axis0",
        lambda: tfs.reduce_sum(
            tfs.placeholder(np.float64, (None, 2), name="x"), axis=0, name="s"
        ),
        lambda feeds: {"s": feeds["x"].sum(axis=0)},
        {"x": (4, 2)},
    ),
    (
        "composite_mean",
        lambda: tfs.div(
            tfs.add(
                tfs.placeholder(np.float64, (None,), name="a"),
                tfs.placeholder(np.float64, (None,), name="b"),
                name="t",
            ),
            2.0,
            name="m",
        ),
        lambda feeds: {"m": (feeds["a"] + feeds["b"]) / 2.0},
        {"a": (4,), "b": (4,)},
    ),
]


@pytest.mark.parametrize("name,build,ref,shapes", CASES, ids=[c[0] for c in CASES])
def test_dsl_jaxpr_matches_native(name, build, ref, shapes):
    program = _dsl_program(build)
    feeds = _feeds(**shapes)
    got = _jaxpr(lambda f: program.fn(f), feeds)
    want = _jaxpr(ref, feeds)
    assert got == want, f"\n--- DSL ---\n{got}\n--- native ---\n{want}"


@pytest.mark.parametrize("name,build,ref,shapes", CASES, ids=[c[0] for c in CASES])
def test_dsl_stablehlo_matches_native(name, build, ref, shapes):
    program = _dsl_program(build)
    feeds = _feeds(**shapes)
    got = _stablehlo(lambda f: program.fn(f), feeds)
    want = _stablehlo(ref, feeds)
    assert got == want, f"\n--- DSL ---\n{got}\n--- native ---\n{want}"


def test_saved_program_roundtrip_preserves_stablehlo(tmp_path):
    """A Program serialized via jax.export and reloaded lowers to the same
    computation (≙ GraphDef save/load parity, test/dsl.scala:109-112)."""
    from tensorframes_tpu.program import load_program, save_program

    program = _dsl_program(
        lambda: tfs.add(tfs.placeholder(np.float64, (None,), name="x"), 1.0, name="z")
    )
    path = str(tmp_path / "prog.tfsp")
    save_program(program, path, batch=4)
    loaded = load_program(path)
    feeds = {"x": np.arange(4, dtype=np.float64)}
    out_a = program.fn({k: jnp.asarray(v) for k, v in feeds.items()})
    out_b = loaded.fn({k: jnp.asarray(v) for k, v in feeds.items()})
    np.testing.assert_allclose(
        np.asarray(out_a["z"]), np.asarray(out_b["z"])
    )
