"""Sharded-frame execution over a virtual 8-device mesh.

The reference tests "distributed" by partition count in local mode
(SURVEY.md §4); here it's by device count — every verb must produce the
same results on a device-sharded frame as on host blocks, with map outputs
staying sharded in device memory.
"""

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu import dtypes as dt
from tensorframes_tpu.parallel import device_count, make_mesh


pytestmark = pytest.mark.skipif(
    device_count() < 8, reason="needs 8 (virtual) devices"
)


def _frame(n=64, vec=False):
    if vec:
        arr = np.arange(2 * n, dtype=np.float32).reshape(n, 2)
        return tfs.frame_from_arrays({"x": arr})
    return tfs.frame_from_arrays({"x": np.arange(n, dtype=np.float32)})


def test_make_mesh_shapes():
    m = make_mesh()
    assert m.devices.size == device_count()
    m2 = make_mesh({"dp": 2, "tp": -1})
    assert m2.shape["dp"] == 2 and m2.shape["tp"] == device_count() // 2
    with pytest.raises(ValueError):
        make_mesh({"dp": 3})  # 8 not divisible


def test_to_device_shards_rows():
    df = _frame(64).to_device()
    assert df.is_sharded
    [b] = df.blocks()
    x = b["x"]
    assert {s.data.shape[0] for s in x.addressable_shards} == {8}


def test_sharded_map_blocks_matches_host():
    host = _frame(64)
    dev = host.to_device()
    x = tfs.block(dev, "x")
    z = (x * 2.0 + 1.0).named("z")
    host_out = tfs.map_blocks(z, host).column_values("z")
    dev_frame = tfs.map_blocks(z, dev)
    dev_out = dev_frame.column_values("z")
    assert np.allclose(host_out, dev_out)


def test_sharded_map_output_stays_on_device_and_sharded():
    import jax

    dev = _frame(64).to_device()
    x = tfs.block(dev, "x")
    out = tfs.map_blocks((x + 1.0).named("z"), dev)
    [b] = out.blocks()
    z = b["z"]
    assert isinstance(z, jax.Array)
    # XLA propagated the batch sharding through the program
    assert len(z.addressable_shards) == 8
    assert {s.data.shape[0] for s in z.addressable_shards} == {8}


def test_sharded_chained_maps_fuse_on_device():
    dev = _frame(64).to_device()
    x = tfs.block(dev, "x")
    step1 = tfs.map_blocks((x * 2.0).named("a"), dev)
    a = tfs.block(step1, "a")
    step2 = tfs.map_blocks((a + 5.0).named("b"), step1)
    out = step2.column_values("b")
    assert np.allclose(out, np.arange(64) * 2.0 + 5.0)


def test_sharded_reduce_blocks():
    host = _frame(64, vec=True)
    dev = host.to_device()
    x_input = tfs.block(dev, "x", tf_name="x_input")
    x = tfs.reduce_sum(x_input, axis=0, name="x")
    res = tfs.reduce_blocks(x, dev)
    expected = np.arange(128, dtype=np.float32).reshape(64, 2).sum(axis=0)
    assert np.allclose(res, expected)


def test_sharded_reduce_rows():
    dev = _frame(16).to_device()
    x1 = tfs.placeholder(dt.float32, [], name="x_1")
    x2 = tfs.placeholder(dt.float32, [], name="x_2")
    x = tfs.add(x1, x2, name="x")
    assert tfs.reduce_rows(x, dev) == float(np.arange(16).sum())


def test_sharded_map_rows():
    dev = _frame(24).to_device()
    x = tfs.row(dev, "x")
    out = tfs.map_rows((x * 3.0).named("z"), dev).column_values("z")
    assert np.allclose(out, np.arange(24) * 3.0)


def test_sharded_aggregate():
    df = tfs.frame_from_arrays(
        {
            "key": np.arange(40, dtype=np.int64) % 4,
            "v": np.arange(40, dtype=np.float32),
        }
    ).to_device()
    v_input = tfs.block(df, "v", tf_name="v_input")
    v = tfs.reduce_sum(v_input, axis=0, name="v")
    res = tfs.aggregate(v, df.group_by("key")).collect()
    for k in range(4):
        expected = sum(float(i) for i in range(40) if i % 4 == k)
        assert res[k]["v"] == expected


def test_sharded_aggregate_string_keys_device_plan():
    """String keys ride the dictionary-encoding device plan (one host
    pass over the key column; values reduce on device) and match the
    host-path result and ordering."""
    import string

    n = 4000
    labels = [string.ascii_lowercase[i % 7] for i in range(n)]
    vals = np.arange(n, dtype=np.float64)
    dev = tfs.frame_from_rows(
        [{"k": labels[i], "v": float(i)} for i in range(n)]
    ).to_device()
    assert dev.is_sharded
    v_input = tfs.block(dev, "v", tf_name="v_input")
    v = tfs.reduce_sum(v_input, axis=0, name="v")
    res = tfs.aggregate(v, dev.group_by("k")).collect()
    want = {}
    for lab, x in zip(labels, vals):
        want[lab] = want.get(lab, 0.0) + x
    assert [r["k"] for r in res] == sorted(want)  # lexicographic order
    assert {r["k"]: r["v"] for r in res} == pytest.approx(want)


def test_sharded_aggregate_huge_span_int_keys():
    """Integer keys with span >> 2^20 exceed the dense plan but ride the
    dictionary plan: K = #distinct groups, not the key span."""
    rng = np.random.default_rng(3)
    base = rng.choice(np.arange(0, 2**40, 2**33, dtype=np.int64), size=4000)
    vals = rng.normal(size=4000)
    dev = tfs.frame_from_arrays({"key": base, "v": vals}).to_device()
    v_input = tfs.block(dev, "v", tf_name="v_input")
    v = tfs.reduce_sum(v_input, axis=0, name="v")
    res = tfs.aggregate(v, dev.group_by("key")).collect()
    want = {}
    for k, x in zip(base, vals):
        want[int(k)] = want.get(int(k), 0.0) + float(x)
    assert [r["key"] for r in res] == sorted(want)
    for r in res:
        assert r["v"] == pytest.approx(want[r["key"]], rel=1e-9)


def test_sharded_aggregate_composite_string_int_keys():
    """Composite (string, int) group keys through the dictionary plan."""
    n = 2000
    rows = [
        {"a": "xy"[i % 2], "b": np.int64((i // 2) % 3), "v": float(i)}
        for i in range(n)
    ]
    dev = tfs.frame_from_rows(rows).to_device()
    v_input = tfs.block(dev, "v", tf_name="v_input")
    v = tfs.reduce_sum(v_input, axis=0, name="v")
    res = tfs.aggregate(v, dev.group_by("a", "b")).collect()
    want = {}
    for r in rows:
        key = (r["a"], int(r["b"]))
        want[key] = want.get(key, 0.0) + r["v"]
    assert [(r["a"], r["b"]) for r in res] == sorted(want)
    assert {(r["a"], r["b"]): r["v"] for r in res} == pytest.approx(want)


def test_to_host_roundtrip():
    host = _frame(32)
    back = host.to_device().to_host(num_blocks=4)
    assert not back.is_sharded
    assert back.num_blocks == 4
    assert np.allclose(back.column_values("x"), host.column_values("x"))


def test_uneven_rows_shard():
    # 61 rows over 8 devices — jax handles uneven batch sharding
    df = tfs.frame_from_arrays({"x": np.arange(61, dtype=np.float32)}).to_device()
    x = tfs.block(df, "x")
    out = tfs.map_blocks((x + 1.0).named("z"), df).column_values("z")
    assert np.allclose(out, np.arange(61) + 1.0)


def test_sharded_first_returns_python_scalars():
    df = _frame(16).to_device()
    row = df.first()
    assert isinstance(row["x"], float)


def test_precompiled_aggregate_keeps_segment_fast_path():
    df = tfs.frame_from_arrays(
        {
            "key": np.arange(24, dtype=np.int64) % 3,
            "v": np.arange(24, dtype=np.float32),
        }
    )
    v_input = tfs.block(df, "v", tf_name="v_input")
    v = tfs.reduce_sum(v_input, axis=0, name="v")
    prog = tfs.compile_program(v, df, reduce_mode="blocks")
    assert prog.seg_info is not None  # fast-path info survives precompile
    res = tfs.aggregate(prog, df.group_by("key")).collect()
    for k in range(3):
        assert res[k]["v"] == sum(float(i) for i in range(24) if i % 3 == k)


def test_frame_from_process_local_single_process():
    """Single-process degenerate case: local rows == global rows; schema
    validation matches frame_from_arrays' error contract."""
    import numpy as np
    import pytest

    from tensorframes_tpu.parallel import frame_from_process_local, make_mesh

    mesh = make_mesh({"dp": 8})
    fr = frame_from_process_local(
        {"v": np.arange(16, dtype=np.float32)}, mesh=mesh, axis="dp"
    )
    assert fr.num_rows == 16 and fr.is_sharded
    s = tfs.reduce_blocks(lambda v_input: {"v": v_input.sum(axis=0)}, fr)
    assert float(s) == float(np.arange(16).sum())
    with pytest.raises(ValueError, match="expected 16"):
        frame_from_process_local(
            {"a": np.arange(16, dtype=np.float32), "b": np.arange(8.0)},
            mesh=mesh,
        )
    # host-only columns are accepted PROCESS-LOCAL since round 3 (string
    # aggregate keys across processes) — but cannot define the global row
    # count on their own
    with pytest.raises(ValueError, match="at least one device column"):
        frame_from_process_local({"s": np.array(["x", "y"])}, mesh=mesh)
    fr2 = frame_from_process_local(
        {"v": np.arange(16, dtype=np.float32),
         "s": [f"g{i % 2}" for i in range(16)]},
        mesh=mesh, axis="dp",
    )
    assert fr2.num_rows == 16
    # single process: local rows ARE the global rows, so materializing
    # the host column is fine
    assert list(fr2.column_values("s")) == [f"g{i % 2}" for i in range(16)]
    with tfs.with_graph():
        v_input = tfs.block(fr2, "v", tf_name="v_input")
        agg = tfs.aggregate(
            tfs.reduce_sum(v_input, axis=0, name="v"), fr2.group_by("s")
        )
    got = {str(r["s"]): r["v"] for r in agg.collect()}
    assert got == {
        "g0": float(sum(range(0, 16, 2))),
        "g1": float(sum(range(1, 16, 2))),
    }


def test_sharded_reduce_rows_on_device():
    """reduce_rows on a sharded frame: per-shard scan fold + all_gather
    merge in one program, matching the host path exactly (f64 data keeps
    every fold order exact)."""
    import tensorframes_tpu as tfs

    rng = np.random.default_rng(0)
    vals = rng.integers(0, 1000, 4000).astype(np.float64)
    host = tfs.frame_from_arrays({"x": vals}, num_blocks=4)
    dev = tfs.frame_from_arrays({"x": vals}).to_device()

    red = lambda x_1, x_2: {"x": x_1 + x_2}
    a = tfs.reduce_rows(red, host)
    b = tfs.reduce_rows(red, dev)
    assert float(a) == float(b) == float(vals.sum())


def test_sharded_reduce_rows_with_tail():
    import tensorframes_tpu as tfs

    vals = np.arange(4001, dtype=np.float64)  # 8 devices -> 1 tail row
    dev = tfs.frame_from_arrays({"x": vals}).to_device()
    assert dev.num_blocks == 2
    got = tfs.reduce_rows(lambda x_1, x_2: {"x": x_1 + x_2}, dev)
    assert float(got) == float(vals.sum())


def test_sharded_reduce_rows_vector_cells():
    import tensorframes_tpu as tfs

    rng = np.random.default_rng(1)
    vals = rng.integers(0, 100, (800, 3)).astype(np.float64)
    dev = tfs.frame_from_arrays({"x": vals}).to_device()
    got = tfs.reduce_rows(lambda x_1, x_2: {"x": x_1 + x_2}, dev)
    np.testing.assert_allclose(np.asarray(got), vals.sum(axis=0))


def test_sharded_reduce_rows_after_trim_falls_back():
    """A trimmed map can leave a sharded frame with a row count the mesh
    no longer divides; reduce_rows must fall back to the host fold."""
    import tensorframes_tpu as tfs

    dev = tfs.frame_from_arrays({"x": np.arange(4000, dtype=np.float64)}).to_device()
    trimmed = tfs.map_blocks(lambda x: {"x": x[:5]}, dev, trim=True)
    got = tfs.reduce_rows(lambda x_1, x_2: {"x": x_1 + x_2}, trimmed)
    assert float(got) == float(np.arange(5).sum())


def test_sharded_aggregate_after_trim_falls_back():
    """Same trimmed-shape hazard for the device-aggregate fast path: a
    row count the mesh no longer divides must decline to the host path
    instead of crashing inside shard_map."""
    import tensorframes_tpu as tfs

    dev = tfs.frame_from_arrays(
        {
            "key": np.arange(4000, dtype=np.int64) % 4,
            "x": np.arange(4000, dtype=np.float64),
        }
    ).to_device()
    trimmed = tfs.map_blocks(
        lambda key, x: {"key": key[:5], "x": x[:5]}, dev, trim=True
    )
    x_input = tfs.block(trimmed, "x", tf_name="x_input")
    x = tfs.reduce_sum(x_input, axis=0, name="x")
    res = tfs.aggregate(x, trimmed.group_by("key")).collect()
    # per-shard the first 5 rows of each 500-row shard survive
    host = {}
    for r in trimmed.collect():
        host[r["key"]] = host.get(r["key"], 0.0) + r["x"]
    assert {r["key"]: r["x"] for r in res} == host


def test_grouped_count_rides_fast_path():
    """count() builds its fetch via the DSL so segment_reduce_info
    recognizes it (a plain lambda would take the generic chunked path)."""
    import tensorframes_tpu as tfs

    dev = tfs.frame_from_arrays(
        {"key": np.arange(4000, dtype=np.int64) % 3}
    ).to_device()
    out = dev.group_by("key").count()
    got = {r["key"]: r["count"] for r in out.collect()}
    assert got == {0: 1334, 1: 1333, 2: 1333}


def test_trimmed_sharded_frame_is_verb_composable():
    """trim=True on a sharded frame re-balances the output to to_device
    invariants (divisible main block + host tail), so the full chain
    trimmed map → map → aggregate → collect stays on the device fast
    paths and equals the host-path result (SURVEY §7 hard-part 3)."""
    import tensorframes_tpu as tfs
    from tensorframes_tpu.ops.device_agg import try_aggregate_device

    n = 4000
    keys = np.arange(n, dtype=np.int64) % 5
    vals = np.arange(n, dtype=np.float64)
    dev = tfs.frame_from_arrays({"key": keys, "x": vals}).to_device()
    # keep the first 1003 global rows: 1003 % 8 != 0 pre-balance
    trimmed = tfs.map_blocks(
        lambda key, x: {"key": key[:1003], "x": x[:1003]}, dev, trim=True
    )
    blocks = trimmed.blocks()
    assert trimmed.is_sharded
    assert blocks[0]["x"].shape[0] == 1000  # divisible main block
    assert len(blocks) == 2 and len(blocks[1]["x"]) == 3  # host tail
    # downstream map chains on device
    mapped = tfs.map_blocks(lambda x: {"y": x * 2.0}, trimmed)
    # aggregate rides the device plan again (guard no longer trips)
    y_input = tfs.block(mapped, "y", tf_name="y_input")
    fetch = tfs.reduce_sum(y_input, axis=0, name="y")
    seg_info = [("y", "reduce_sum", "y_input")]
    mapped.blocks()
    assert (
        try_aggregate_device(mapped, ["key"], seg_info, ["y"])
        is not None
    )
    res = tfs.aggregate(fetch, mapped.group_by("key")).collect()
    want = {}
    for k, v in zip(keys[:1003], vals[:1003]):
        want[int(k)] = want.get(int(k), 0.0) + 2.0 * float(v)
    assert {r["key"]: r["y"] for r in res} == pytest.approx(want)
    # reduce_rows also stays sharded-eligible
    got = tfs.reduce_rows(lambda x_1, x_2: {"x": x_1 + x_2}, trimmed)
    assert float(got) == pytest.approx(float(vals[:1003].sum()))


def test_tiny_frame_to_device_all_tail():
    """Fewer rows than devices: the sharded main block is empty and all
    rows live in the host tail; every verb must still answer."""
    import tensorframes_tpu as tfs

    fr = tfs.frame_from_arrays({"x": np.arange(3, dtype=np.float32)}).to_device()
    assert fr.num_rows == 3
    out = tfs.map_blocks(lambda x: {"y": x * 2.0}, fr)
    assert [r["y"] for r in out.collect()] == [0.0, 2.0, 4.0]
    assert float(tfs.reduce_blocks(lambda x_input: {"x": x_input.sum(axis=0)}, fr)) == 3.0
    assert float(tfs.reduce_rows(lambda x_1, x_2: {"x": x_1 + x_2}, fr)) == 3.0
