"""Inception-v3 family tests (BASELINE config 4): forward shapes, scoring
through map_blocks, and architecture sanity at the tiny test scale."""

import numpy as np

import tensorframes_tpu as tfs
from tensorframes_tpu.models import inception as inc


def test_tiny_forward_shape():
    cfg = inc.tiny()
    params = inc.init_params(cfg, seed=0)
    images = inc.synthetic_images(cfg, 2, seed=0)
    logits = inc.forward(cfg, params, images)
    assert logits.shape == (2, cfg.num_classes)
    assert np.isfinite(np.asarray(logits)).all()


def test_scoring_via_map_blocks():
    cfg = inc.tiny()
    params = inc.init_params(cfg, seed=0)
    images = inc.synthetic_images(cfg, 6, seed=1)
    df = tfs.frame_from_arrays({"images": images}, num_blocks=2)
    prog = inc.scoring_program(cfg, params)
    out = tfs.map_blocks(lambda images: prog(images), df)
    scores = np.stack([r["scores"] for r in out.collect()])
    assert scores.shape == (6, cfg.num_classes)
    assert np.allclose(scores.sum(axis=1), 1.0, atol=1e-4)
    labels = out.column_values("label")
    assert labels.dtype == np.int32
    assert (labels >= 0).all() and (labels < cfg.num_classes).all()


def test_channel_alignment_and_param_count():
    cfg = inc.tiny()
    # every width is lane-aligned (multiple of 8) regardless of scale
    for c in (32, 48, 64, 96, 192, 320, 384, 448):
        assert cfg.ch(c) % 8 == 0 and cfg.ch(c) >= 8
    params = inc.init_params(cfg, seed=0)
    n = inc.param_count(params)
    assert n > 10_000  # real multi-block network, not a stub
    # full-scale config widths match the paper's channel plan
    full = inc.inception_v3()
    assert full.ch(384) == 384 and full.ch(192) == 192


def test_batch_invariance():
    """Scoring a row alone equals scoring it inside a batch (pure fn)."""
    cfg = inc.tiny()
    params = inc.init_params(cfg, seed=2)
    images = inc.synthetic_images(cfg, 3, seed=3)
    all_logits = np.asarray(inc.forward(cfg, params, images))
    one = np.asarray(inc.forward(cfg, params, images[1:2]))
    np.testing.assert_allclose(all_logits[1:2], one, rtol=2e-4, atol=2e-4)
