"""TensorFrame container + analyze/append_shape tests
(≙ ExtraOperationsSuite: analyze on scalars/vectors, multi-partition,
ragged; BasicOperationsSuite fixtures)."""

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu import dtypes as dt
from tensorframes_tpu.shape import Unknown


def test_from_rows_scalars():
    df = tfs.frame_from_rows([{"x": float(i)} for i in range(10)])
    assert df.num_rows == 10
    assert df.schema["x"].dtype is dt.float64
    assert df.schema["x"].cell_shape.rank == 0
    assert [r["x"] for r in df.collect()] == [float(i) for i in range(10)]


def test_from_rows_vectors_start_unknown():
    # list columns get Unknown dims pre-analyze
    # (≙ ColumnInformation.scala:124-138 ArrayType recursion)
    df = tfs.frame_from_rows([{"y": [1.0, 2.0]} for _ in range(4)])
    assert df.schema["y"].cell_shape.dims == (Unknown,)


def test_analyze_refines_shapes():
    # ≙ the README reduce example flow (README.md:100-109)
    df = tfs.frame_from_rows([{"y": [float(i), float(-i)]} for i in range(10)])
    df2 = tfs.analyze(df)
    assert df2.schema["y"].cell_shape.dims == (2,)
    assert "[?,2]" in tfs.explain(df2)


def test_analyze_ragged_keeps_unknown():
    # ragged rows merge to Unknown (≙ ExtraOperationsSuite ragged, :73-84)
    df = tfs.frame_from_rows(
        [{"y": [1.0]}, {"y": [1.0, 2.0]}, {"y": [1.0, 2.0, 3.0]}]
    )
    df2 = tfs.analyze(df)
    assert df2.schema["y"].cell_shape.dims == (Unknown,)


def test_analyze_multi_block():
    # shapes merged across partitions (≙ ExtraOperationsSuite :62-71)
    df = tfs.frame_from_rows(
        [{"y": [float(i), 0.0]} for i in range(9)], num_blocks=3
    )
    assert df.num_blocks == 3
    df2 = tfs.analyze(df)
    assert df2.schema["y"].cell_shape.dims == (2,)


def test_append_shape():
    # manual shape declaration (≙ core.py:381-399)
    df = tfs.frame_from_rows([{"y": [1.0, 2.0]} for _ in range(4)])
    df2 = tfs.append_shape(df, "y", [2])
    assert df2.schema["y"].cell_shape.dims == (2,)
    # None entries mean Unknown
    df3 = tfs.append_shape(df, "y", [None])
    assert df3.schema["y"].cell_shape.dims == (Unknown,)


def test_from_arrays_dense_shapes_immediate():
    df = tfs.frame_from_arrays({"m": np.zeros((6, 3, 4), dtype=np.float32)})
    assert df.schema["m"].dtype is dt.float32
    assert df.schema["m"].cell_shape.dims == (3, 4)


def test_from_pandas_roundtrip():
    import pandas as pd

    pdf = pd.DataFrame({"a": [1.0, 2.0, 3.0], "s": ["x", "y", "z"]})
    df = tfs.frame_from_pandas(pdf)
    assert df.schema["a"].dtype is dt.float64
    assert df.schema["s"].dtype is dt.string
    assert df.to_pandas()["s"].tolist() == ["x", "y", "z"]


def test_repartition():
    df = tfs.frame_from_rows([{"x": float(i)} for i in range(10)], num_blocks=2)
    df2 = df.repartition(3)
    assert df2.num_blocks == 3
    assert df2.num_rows == 10
    assert [r["x"] for r in df2.collect()] == [float(i) for i in range(10)]


def test_select_and_alias():
    df = tfs.frame_from_rows([{"a": 1.0, "b": 2.0}])
    assert df.select(["b"]).columns == ["b"]
    df2 = df.alias_column("a", "c")
    assert df2.first()["c"] == 1.0


def test_group_by_missing_key_errors():
    df = tfs.frame_from_rows([{"a": 1.0}])
    with pytest.raises(KeyError):
        df.group_by("nope")


def test_host_string_column_rides_along():
    df = tfs.frame_from_rows(
        [{"x": float(i), "s": f"row{i}"} for i in range(4)]
    )
    assert df.schema["s"].dtype is dt.string
    with tfs.with_graph():
        x = tfs.block(df, "x")
        z = (x * 2.0).named("z")
        out = tfs.map_blocks(z, df).collect()
    assert out[2]["s"] == "row2" and out[2]["z"] == 4.0


def test_block_placeholder_rejects_host_column():
    df = tfs.frame_from_rows([{"s": "a"}])
    with pytest.raises(TypeError):
        tfs.block(df, "s")


def test_rich_frame_verb_methods():
    """Verb methods on the frame (≙ Implicits.RichDataFrame) delegate to
    the functional API."""
    import numpy as np

    df = tfs.frame_from_arrays({"x": np.arange(10.0)}, num_blocks=2)
    out = df.map_blocks(lambda x: {"y": x * 2})
    assert out.column_values("y").tolist() == (np.arange(10.0) * 2).tolist()
    trimmed = df.map_blocks_trimmed(lambda x: {"m": x.max(keepdims=True)})
    assert trimmed.num_rows == 2  # one row per block
    rows = df.map_rows(lambda x: {"z": x + 1})
    assert rows.column_values("z").tolist() == (np.arange(10.0) + 1).tolist()
    assert float(df.reduce_blocks(lambda x_input: {"x": x_input.sum(0)})) == 45.0
    assert float(
        df.reduce_rows(lambda x_1, x_2: {"x": x_1 + x_2})
    ) == 45.0
    assert "x" in df.analyze().explain_tensors()
    g = tfs.frame_from_arrays(
        {"k": np.array([1, 1, 2]), "v": np.array([1.0, 2.0, 3.0])}
    )
    agg = g.group_by("k").aggregate(lambda v_input: {"v": v_input.sum(0)})
    assert {r["k"]: r["v"] for r in agg.collect()} == {1: 3.0, 2: 3.0}


def test_concurrent_materialization_runs_once():
    """Threads forcing the same lazy frame at the same instant run the
    pending computation exactly once (guards the _force_lock)."""
    import threading
    import time

    import numpy as np

    from tensorframes_tpu.frame import TensorFrame
    from tensorframes_tpu.schema import ColumnInfo, Schema
    from tensorframes_tpu.shape import Shape, Unknown

    calls = []
    n_threads = 4
    barrier = threading.Barrier(n_threads)

    def pending():
        calls.append(1)
        time.sleep(0.2)  # hold the critical section so racers overlap
        return [{"x": np.arange(5.0)}]

    schema = Schema([ColumnInfo("x", dt.float64, Shape((Unknown,)))])
    frame = TensorFrame(None, schema, pending=pending)
    results = [None] * n_threads

    def force(i):
        barrier.wait()  # all threads hit blocks() together
        results[i] = frame.blocks()

    ts = [threading.Thread(target=force, args=(i,)) for i in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(calls) == 1, f"pending ran {len(calls)} times"
    assert all(r is results[0] for r in results)


def test_explain_detailed_layout():
    import numpy as np

    df = tfs.frame_from_arrays({"x": np.arange(10.0)}, num_blocks=3)
    text = tfs.explain(df, detailed=True)
    assert "3 block(s), 10 row(s)" in text
    assert "block 0" in text and "block 2" in text
    assert "host-resident" in text


def test_concurrent_verbs_on_one_frame():
    """Thread-safety stress (SURVEY §5: the reference delegates this to
    Spark's task model; here it's the frame's own contract): many threads
    force the same lazy frame and run verbs concurrently — one
    materialization, consistent results, no torn blocks."""
    import threading

    import tensorframes_tpu as tfs

    n = 10_000
    base = tfs.frame_from_arrays(
        {"x": np.arange(n, dtype=np.float64)}, num_blocks=8
    )
    lazy = tfs.map_blocks(lambda x: {"y": x * 2.0}, base)  # shared, unforced
    results, errors = [], []

    def worker(i):
        try:
            if i % 2 == 0:
                s = tfs.reduce_blocks(
                    lambda y_input: {"y": y_input.sum(axis=0)}, lazy
                )
                results.append(float(s))
            else:
                results.append(float(lazy.column_values("y").sum()))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    expect = float(np.arange(n, dtype=np.float64).sum() * 2)
    assert all(abs(r - expect) < 1e-3 for r in results), results


def test_describe():
    import tensorframes_tpu as tfs

    rng = np.random.default_rng(0)
    x = rng.standard_normal(1000)
    k = rng.integers(0, 10, 1000)
    fr = tfs.frame_from_arrays(
        {"x": x, "k": k, "s": [str(i) for i in range(1000)]}, num_blocks=4
    )
    d = tfs.describe(fr)
    assert set(d) == {"x", "k"}  # host string column excluded
    assert d["x"]["count"] == 1000
    assert d["x"]["mean"] == pytest.approx(float(x.mean()), abs=1e-9)
    assert d["x"]["std"] == pytest.approx(float(x.std()), rel=1e-6)
    assert d["k"]["min"] == float(k.min()) and d["k"]["max"] == float(k.max())
    with pytest.raises(ValueError, match="scalar numeric"):
        tfs.describe(fr, columns=["s"])
    # sharded frames describe through the same path
    d2 = tfs.describe(tfs.frame_from_arrays({"x": x[:64]}).to_device())
    assert d2["x"]["count"] == 64
    assert d2["x"]["mean"] == pytest.approx(float(x[:64].mean()), abs=1e-9)


def test_describe_empty_and_conditioning():
    import tensorframes_tpu as tfs

    d = tfs.describe(tfs.frame_from_arrays({"x": np.zeros(0)}))
    assert d["x"]["count"] == 0 and np.isnan(d["x"]["mean"])
    # huge mean, tiny std: the naive sum-of-squares identity would report 0
    x = 1e6 + np.random.default_rng(0).standard_normal(4000)
    got = tfs.describe(tfs.frame_from_arrays({"x": x}, num_blocks=4))["x"]
    assert got["std"] == pytest.approx(float(x.std()), rel=1e-3)


def test_take_and_groupby_count():
    import tensorframes_tpu as tfs

    rng = np.random.default_rng(0)
    k = rng.integers(0, 3, 50)
    fr = tfs.frame_from_arrays(
        {"k": k, "v": rng.standard_normal(50)}, num_blocks=4
    )
    head = fr.take(5)
    assert len(head) == 5
    assert [r["k"] for r in head] == list(k[:5])
    assert fr.take(500) == fr.collect()

    counted = fr.group_by("k").count()
    got = {r["k"]: r["count"] for r in counted.collect()}
    for key in np.unique(k):
        assert got[int(key)] == int((k == key).sum())


def test_filter_plain_function():
    """df.filter keeps matching rows across device and host columns; the
    mask computes on device via map_blocks (the reference had no filter
    — Spark's `where` ran upstream; standalone frames need it native)."""
    df = tfs.frame_from_arrays(
        {"x": np.arange(10, dtype=np.float32)}, num_blocks=3
    )
    out = df.filter(lambda x: {"keep": x > 4.0})
    vals = np.asarray(out.column_values("x"))
    np.testing.assert_array_equal(vals, np.arange(5, 10, dtype=np.float32))
    assert out.schema.names == df.schema.names


def test_filter_host_columns_and_sharded():
    rows = [{"x": float(i), "tag": f"r{i}"} for i in range(8)]
    df = tfs.frame_from_rows(rows, num_blocks=2)
    out = df.filter(lambda x: {"keep": (x % 2.0) == 0.0})
    got = out.collect()
    assert [r["tag"] for r in got] == ["r0", "r2", "r4", "r6"]

    dev = tfs.frame_from_arrays(
        {"x": np.arange(16, dtype=np.float32)}
    ).to_device()
    flt = dev.filter(lambda x: {"keep": x < 3.0})
    np.testing.assert_array_equal(
        np.asarray(flt.column_values("x")), [0.0, 1.0, 2.0]
    )


def test_filter_bad_predicate_errors():
    df = tfs.frame_from_arrays({"x": np.arange(4, dtype=np.float32)})
    with pytest.raises(ValueError, match="bool"):
        # dtype is only knowable when the mask computes — at force time
        df.filter(lambda x: {"keep": x * 2.0}).collect()
    with pytest.raises(ValueError, match="exactly one"):
        df.filter(lambda x: {"a": x > 1.0, "b": x > 2.0})


def test_filter_is_lazy():
    # like every sibling transform, filter returns a PENDING frame —
    # the mask+gather run when blocks()/collect() force it (tracing for
    # schema analysis happens eagerly; data computation does not)
    df = tfs.frame_from_arrays({"x": np.arange(4, dtype=np.float32)})
    flt = df.filter(lambda x: {"keep": x > 1.0})
    assert not flt.is_materialized
    got = np.asarray(flt.column_values("x"))
    np.testing.assert_array_equal(got, [2.0, 3.0])


def test_sort_values_single_and_multi_key():
    df = tfs.frame_from_rows(
        [
            {"k": 2.0, "g": "b", "tag": "x"},
            {"k": 1.0, "g": "b", "tag": "y"},
            {"k": 1.0, "g": "a", "tag": "z"},
            {"k": 3.0, "g": "a", "tag": "w"},
        ],
        num_blocks=2,
    )
    got = df.sort_values("k").collect()
    assert [r["k"] for r in got] == [1.0, 1.0, 2.0, 3.0]
    # multi-key: g primary, k secondary; host string keys sort too
    got2 = df.sort_values(["g", "k"]).collect()
    assert [(r["g"], r["k"]) for r in got2] == [
        ("a", 1.0), ("a", 3.0), ("b", 1.0), ("b", 2.0)
    ]
    # pandas-style per-key ascending list
    got_mixed = df.sort_values(["g", "k"], ascending=[False, True]).collect()
    assert [(r["g"], r["k"]) for r in got_mixed] == [
        ("b", 1.0), ("b", 2.0), ("a", 1.0), ("a", 3.0)
    ]
    with pytest.raises(ValueError, match="entries"):
        df.sort_values(["g", "k"], ascending=[True])

    got3 = df.sort_values("k", ascending=False).collect()
    assert [r["k"] for r in got3] == [3.0, 2.0, 1.0, 1.0]
    # DESCENDING keeps tie stability: the two k=1.0 rows stay in input
    # order (y before z), not reversed
    assert [r["tag"] for r in got3] == ["w", "x", "y", "z"]
    with pytest.raises(KeyError):
        df.sort_values("nope")


def test_sort_values_mixed_type_and_nan_keys():
    """ADVICE r3: sort keys now ride ops/keys._unique_inverse, the same
    encoder join/aggregate use — a NaN float among string keys must sort
    deterministically (type-name/repr total order), not raise numpy's
    bare TypeError from '<'."""
    import math

    df = tfs.frame_from_rows(
        [
            {"k": "b", "v": 0.0},
            {"k": math.nan, "v": 1.0},
            {"k": "a", "v": 2.0},
            {"k": math.nan, "v": 3.0},
        ]
    )
    got = df.sort_values("k").collect()
    # deterministic total order: float NaN ('float' < 'str' by type
    # name) before the strings; NaN ties keep input order (stable)
    assert [r["v"] for r in got] == [1.0, 3.0, 2.0, 0.0]
    # descending reverses the key order (b, a, NaN) with ties stable
    got_d = df.sort_values("k", ascending=False).collect()
    assert [r["v"] for r in got_d] == [0.0, 2.0, 1.0, 3.0]


def test_sort_values_non_scalar_key_raises():
    """ADVICE r3: a vector key column must raise the actionable error,
    not silently flatten into per-element codes before lexsort fails."""
    df = tfs.frame_from_arrays(
        {"emb": np.ones((4, 3), np.float32), "v": np.arange(4.0)}
    )
    with pytest.raises(ValueError, match="non-scalar"):
        df.sort_values("emb").collect()


def test_limit_spans_blocks():
    df = tfs.frame_from_rows(
        [{"x": float(i), "s": f"r{i}"} for i in range(10)], num_blocks=4
    )
    got = df.limit(5).collect()
    assert [r["x"] for r in got] == [0.0, 1.0, 2.0, 3.0, 4.0]
    assert [r["s"] for r in got] == ["r0", "r1", "r2", "r3", "r4"]
    assert df.limit(0).collect() == []
    assert len(df.limit(99).collect()) == 10
    with pytest.raises(ValueError):
        df.limit(-1)


def test_join_inner_matches_pandas():
    """Inner hash join golden-matched against pandas.merge: multi-match
    expansion, string keys through the native dictionary encode, column
    name clashes suffixed, pandas-like ordering."""
    import pandas as pd

    left_rows = [
        {"k": "a", "v": 1.0, "tag": "l0"},
        {"k": "b", "v": 2.0, "tag": "l1"},
        {"k": "a", "v": 3.0, "tag": "l2"},
        {"k": "c", "v": 4.0, "tag": "l3"},
    ]
    right_rows = [
        {"k": "a", "w": 10.0, "tag": "r0"},
        {"k": "a", "w": 20.0, "tag": "r1"},
        {"k": "b", "w": 30.0, "tag": "r2"},
        {"k": "d", "w": 40.0, "tag": "r3"},
    ]
    lf = tfs.frame_from_rows(left_rows, num_blocks=2)
    rf = tfs.frame_from_rows(right_rows, num_blocks=2)
    got = lf.join(rf, on="k").collect()

    want = pd.merge(
        pd.DataFrame(left_rows), pd.DataFrame(right_rows),
        on="k", how="inner",
    )
    assert len(got) == len(want) == 5
    for g, (_, w) in zip(got, want.iterrows()):
        assert g["k"] == w["k"]
        assert g["v"] == w["v"]
        assert g["w"] == w["w"]
        assert g["tag_x"] == w["tag_x"]
        assert g["tag_y"] == w["tag_y"]


def test_join_int_keys_and_empty_result():
    lf = tfs.frame_from_arrays(
        {"id": np.asarray([1, 2, 3]), "v": np.asarray([1.0, 2.0, 3.0])}
    )
    rf = tfs.frame_from_arrays(
        {"id": np.asarray([2, 3, 9]), "w": np.asarray([20.0, 30.0, 90.0])}
    )
    got = lf.join(rf, on="id").collect()
    assert [(r["id"], r["v"], r["w"]) for r in got] == [
        (2, 2.0, 20.0), (3, 3.0, 30.0)
    ]
    none = lf.join(
        tfs.frame_from_arrays(
            {"id": np.asarray([7]), "w": np.asarray([0.0])}
        ),
        on="id",
    ).collect()
    assert none == []
    # zero-row sides must give an empty join, not a group_ids crash
    empty = lf.filter(lambda id: {"keep": id > 99})
    assert lf.join(empty.select(["id"]), on="id").collect() == []
    with pytest.raises(ValueError, match="fill_value"):
        lf.join(rf, on="id", how="outer")  # outer requires explicit fills
    with pytest.raises(ValueError, match="fill_value"):
        lf.join(rf, on="id", how="left")  # left requires explicit fills
    with pytest.raises(ValueError, match="cross"):
        lf.join(rf, on="id", how="cross")


def test_join_left_with_fill_matches_pandas():
    import pandas as pd

    left_rows = [{"k": i, "v": float(i)} for i in range(5)]
    right_rows = [{"k": 1, "w": 10.0}, {"k": 1, "w": 11.0}, {"k": 3, "w": 30.0}]
    lf = tfs.frame_from_rows(left_rows, num_blocks=2)
    rf = tfs.frame_from_rows(right_rows)
    got = lf.join(rf, on="k", how="left", fill_value=-1.0).collect()

    want = pd.merge(
        pd.DataFrame(left_rows), pd.DataFrame(right_rows),
        on="k", how="left",
    ).fillna(-1.0)
    assert len(got) == len(want) == 6
    for g, (_, w) in zip(got, want.iterrows()):
        assert g["k"] == w["k"] and g["v"] == w["v"] and g["w"] == w["w"]

    # per-column fill dict + empty right side
    empty_r = tfs.frame_from_rows(right_rows).filter(
        lambda w: {"keep": w > 99.0}
    )
    all_filled = lf.join(
        empty_r, on="k", how="left", fill_value={"w": 0.0}
    ).collect()
    assert [r["w"] for r in all_filled] == [0.0] * 5

    # a lossy fill into an int column raises instead of truncating
    int_r = tfs.frame_from_arrays(
        {"k": np.asarray([1]), "c": np.asarray([7])}
    )
    with pytest.raises(ValueError, match="representable"):
        lf.join(int_r, on="k", how="left", fill_value=-1.5).collect()
    # ADVICE r3: a NaN fill into an int column gets the SAME friendly
    # error, not numpy's raw 'cannot convert float NaN to integer'
    with pytest.raises(ValueError, match="representable"):
        lf.join(
            int_r, on="k", how="left", fill_value=float("nan")
        ).collect()
    # a missing dict entry raises EAGERLY at join() time
    with pytest.raises(ValueError, match="no entry"):
        lf.join(int_r, on="k", how="left", fill_value={"x": 0})

    # multi-dim right columns broadcast the fill across cell dims
    emb_r = tfs.frame_from_arrays(
        {"k": np.asarray([1, 3]),
         "e": np.asarray([[1.0, 2.0], [3.0, 4.0]], np.float32)}
    )
    je = lf.join(emb_r, on="k", how="left", fill_value=0.0).collect()
    assert np.asarray(je[0]["e"]).shape == (2,)
    got_rows = {r["k"]: np.asarray(r["e"]).tolist() for r in je}
    assert got_rows[1] == [1.0, 2.0] and got_rows[0] == [0.0, 0.0]


def test_join_outer_matches_pandas():
    """VERDICT r4 #8: outer join golden-matched against pandas.merge
    (sort=False ordering: left-ordered part first, unmatched right rows
    after, in right order), with explicit per-side fills."""
    import pandas as pd

    left_rows = [
        {"k": "a", "v": 1.0, "tag": "l0"},
        {"k": "b", "v": 2.0, "tag": "l1"},
        {"k": "a", "v": 3.0, "tag": "l2"},
        {"k": "x", "v": 4.0, "tag": "l3"},
    ]
    right_rows = [
        {"k": "a", "w": 10.0, "tag": "r0"},
        {"k": "d", "w": 40.0, "tag": "r1"},
        {"k": "a", "w": 20.0, "tag": "r2"},
        {"k": "e", "w": 50.0, "tag": "r3"},
    ]
    lf = tfs.frame_from_rows(left_rows, num_blocks=2)
    rf = tfs.frame_from_rows(right_rows, num_blocks=2)
    fills = {"v": -1.0, "w": -2.0, "tag": "<none>"}
    got = lf.join(rf, on="k", how="outer", fill_value=fills).collect()

    want = pd.merge(
        pd.DataFrame(left_rows), pd.DataFrame(right_rows),
        on="k", how="outer", sort=False,
    )
    want["v"] = want["v"].fillna(-1.0)
    want["w"] = want["w"].fillna(-2.0)
    want[["tag_x", "tag_y"]] = want[["tag_x", "tag_y"]].fillna("<none>")
    assert len(got) == len(want) == 8
    # pandas' outer row order is version-dependent (3.x key-sorts even
    # under sort=False) — golden-match the CONTENT as a multiset, then
    # pin OUR documented order below
    def as_set(rows):
        return sorted(
            (r["k"], r["v"], r["w"], r["tag_x"], r["tag_y"]) for r in rows
        )

    assert as_set(got) == as_set(want.to_dict("records"))
    # our order: left-ordered matched/unmatched-left part first, then
    # unmatched right rows in right order
    assert [r["k"] for r in got] == [
        "a", "a", "b", "a", "a", "x", "d", "e"
    ]
    # fill dict must cover BOTH sides for outer
    with pytest.raises(ValueError, match="no entry"):
        lf.join(rf, on="k", how="outer", fill_value={"w": 0.0, "tag": ""})
    # empty left side: outer keeps every right row, left columns filled
    empty_l = lf.filter(lambda v: {"keep": v > 99.0})
    eo = empty_l.join(rf, on="k", how="outer", fill_value=fills).collect()
    assert [r["k"] for r in eo] == ["a", "d", "a", "e"]
    assert all(r["v"] == -1.0 and r["tag_x"] == "<none>" for r in eo)


def test_join_right_matches_pandas():
    """VERDICT r4 #8: right join = mirrored left join, canonical column
    order restored, pandas-like right-row ordering."""
    import pandas as pd

    left_rows = [
        {"k": 1, "v": 1.0, "tag": "l0"},
        {"k": 2, "v": 2.0, "tag": "l1"},
        {"k": 1, "v": 3.0, "tag": "l2"},
    ]
    right_rows = [
        {"k": 2, "w": 20.0, "tag": "r0"},
        {"k": 9, "w": 90.0, "tag": "r1"},
        {"k": 1, "w": 10.0, "tag": "r2"},
    ]
    lf = tfs.frame_from_rows(left_rows)
    rf = tfs.frame_from_rows(right_rows)
    got = lf.join(
        rf, on="k", how="right",
        fill_value={"v": -1.0, "tag": "<none>"},
    ).collect()
    want = pd.merge(
        pd.DataFrame(left_rows), pd.DataFrame(right_rows),
        on="k", how="right", sort=False,
    )
    want["v"] = want["v"].fillna(-1.0)
    want["tag_x"] = want["tag_x"].fillna("<none>")
    assert len(got) == len(want) == 4
    for g, (_, w) in zip(got, want.iterrows()):
        assert (
            g["k"] == w["k"]
            and g["v"] == w["v"]
            and g["w"] == w["w"]
            and g["tag_x"] == w["tag_x"]
            and g["tag_y"] == w["tag_y"]
        ), (g, dict(w))
    # column order is canonical: keys, left columns, right columns
    assert list(got[0].keys()) == ["k", "v", "tag_x", "w", "tag_y"]
    # right join requires fills for the LEFT columns
    with pytest.raises(ValueError, match="fill_value"):
        lf.join(rf, on="k", how="right")


def test_sort_values_device_path_matches_host_and_stays_on_device():
    """VERDICT r3 #7: sorting a device-resident frame must run on device
    (jnp.lexsort -> lax.sort) and keep the result columns in HBM, with
    the exact ordering semantics of the host path — ints, floats with
    NaN (canonical NaN sorts last ascending, numpy's convention),
    multi-key, per-key descending, and tie stability."""
    import jax

    rng = np.random.default_rng(0)
    g = rng.integers(0, 5, 64)
    k = rng.standard_normal(64).astype(np.float32)
    k[[3, 17, 40]] = np.nan
    # a SIGN-BIT NaN (what x86 0.0/0.0 produces): must sort with the
    # other NaNs, not reflect to the front of the device order
    k[11] = np.frombuffer(np.uint32(0xFFC00000).tobytes(), np.float32)[0]
    tag = np.arange(64)

    host = tfs.frame_from_arrays({"g": g, "k": k, "tag": tag})
    dev = tfs.frame_from_arrays({"g": g, "k": k, "tag": tag}).to_device()

    for by, asc in (
        ("k", True),
        ("k", False),
        (["g", "k"], True),
        (["g", "k"], [False, True]),
        ("g", False),  # int keys, ties stay stable
    ):
        want = host.sort_values(by, ascending=asc).collect()
        got_frame = dev.sort_values(by, ascending=asc)
        [blk] = got_frame.blocks()
        assert isinstance(blk["k"], jax.Array), "result left the device"
        got = got_frame.collect()
        w_tags = [r["tag"] for r in want]
        g_tags = [int(r["tag"]) for r in got]
        assert g_tags == w_tags, f"order diverged for by={by} asc={asc}"


def test_sort_values_device_bool_and_int_dtypes():
    import jax

    vals = np.array([True, False, True, False])
    small = np.array([3, -7, 3, 127], np.int8)
    u = np.array([9, 2, 9, 1], np.uint8)
    dev = tfs.frame_from_arrays(
        {"b": vals, "i": small, "u": u, "tag": np.arange(4)}
    ).to_device()
    got = dev.sort_values(["b", "i", "u"]).collect()
    host = tfs.frame_from_arrays(
        {"b": vals, "i": small, "u": u, "tag": np.arange(4)}
    ).sort_values(["b", "i", "u"]).collect()
    assert [int(r["tag"]) for r in got] == [r["tag"] for r in host]


def test_filter_device_frame_stays_on_device():
    """Device-frame filter gathers in HBM: result columns remain jax
    Arrays (only the mask crosses to host), matching the host path's
    rows exactly."""
    import jax

    x = np.arange(32.0)
    dev = tfs.frame_from_arrays({"x": x, "tag": np.arange(32)}).to_device()
    flt = dev.filter(lambda x: {"keep": x % 3.0 == 0.0})
    blks = flt.blocks()
    assert all(isinstance(b["x"], jax.Array) for b in blks)
    got = sorted(float(r["x"]) for r in flt.collect())
    want = sorted(float(v) for v in x[x % 3 == 0])
    assert got == want
    # host parity
    host = tfs.frame_from_arrays({"x": x, "tag": np.arange(32)})
    hgot = sorted(float(r["x"]) for r in host.filter(
        lambda x: {"keep": x % 3.0 == 0.0}
    ).collect())
    assert hgot == want


def test_drop_duplicates_matches_pandas():
    """Round 5: drop_duplicates/distinct — keep-first in global row
    order, every key type the aggregate encoder handles, NaN keys
    collapse (the grouping convention, same as pandas)."""
    import pandas as pd

    rows = [
        {"k": "a", "g": 1, "v": 0.0},
        {"k": "b", "g": 1, "v": 1.0},
        {"k": "a", "g": 1, "v": 2.0},   # dup of (a,1) on subset
        {"k": "a", "g": 2, "v": 3.0},
        {"k": "b", "g": 1, "v": 4.0},   # dup of (b,1)
    ]
    fr = tfs.frame_from_rows(rows, num_blocks=2)
    got = fr.drop_duplicates(subset=["k", "g"]).collect()
    want = pd.DataFrame(rows).drop_duplicates(
        subset=["k", "g"], keep="first"
    )
    assert [(r["k"], r["g"], r["v"]) for r in got] == [
        tuple(t) for t in want.to_numpy()
    ]

    # full-row distinct; NaN keys collapse to one row like pandas
    nan_rows = [
        {"x": float("nan"), "y": 1.0},
        {"x": 2.0, "y": 1.0},
        {"x": float("nan"), "y": 1.0},
        {"x": 2.0, "y": 1.0},
    ]
    nf = tfs.frame_from_rows(nan_rows)
    dv = nf.distinct().collect()
    wv = pd.DataFrame(nan_rows).drop_duplicates()
    assert len(dv) == len(wv) == 2
    # single-column subset keeps the other columns from the FIRST row
    s = fr.drop_duplicates(subset="k").collect()
    assert [(r["k"], r["v"]) for r in s] == [("a", 0.0), ("b", 1.0)]
    # non-scalar key cells raise with guidance
    ef = tfs.frame_from_arrays({"e": np.zeros((4, 3), np.float32)})
    with pytest.raises(ValueError, match="non-scalar"):
        ef.drop_duplicates().collect()
    # device frames dedup too (through the host merge)
    dd = tfs.frame_from_arrays(
        {"k": np.asarray([3, 1, 3, 1, 2])}
    ).to_device()
    assert [r["k"] for r in dd.drop_duplicates().collect()] == [3, 1, 2]
