"""Foreign frozen-graph ingestion: the reference's own GraphDef fixtures
(src/test/resources/graph.pb, graph2.pb — loaded by
PythonInterface.scala:115-118 / test/dsl.scala:109-112) must decode and
execute through the verbs."""

import os

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.graphdef import parse_graphdef, program_from_graphdef

_FIXTURES = "/root/reference/src/test/resources"


def _fixture(name: str) -> str:
    p = os.path.join(_FIXTURES, name)
    if not os.path.exists(p):
        pytest.skip(f"reference fixture {name} unavailable")
    return p


def test_parse_graph_pb_nodes():
    with open(_fixture("graph.pb"), "rb") as f:
        nodes = parse_graphdef(f.read())
    by_name = {n.name: n for n in nodes}
    assert set(by_name) == {"matrix1", "x"}
    assert by_name["x"].op == "Placeholder"
    assert by_name["matrix1"].op == "Const"
    # matrix1 = [[3.0, 3.0]] float32 (the 1x2 constant the fixture embeds)
    val = by_name["matrix1"].attrs["value"].tensor
    np.testing.assert_array_equal(val, np.full((1, 2), 3.0, np.float32))


def test_graph_pb_const_fetch_executes():
    prog = tfs.load_graphdef(_fixture("graph.pb"), fetches=["matrix1"])
    out = prog.fn({})
    np.testing.assert_array_equal(
        np.asarray(out["matrix1"]), np.full((1, 2), 3.0, np.float32)
    )


def test_graph2_pb_runs_through_map_blocks():
    """graph2.pb: out = Add(z_1, z_2) over float [2,2] placeholders.
    relax_lead_dim widens the fixed lead dim so the frozen graph maps
    over arbitrary block row counts."""
    prog = tfs.load_graphdef(
        _fixture("graph2.pb"), fetches=["out"], relax_lead_dim=True
    )
    assert prog.input_names == ["z_1", "z_2"]
    a = np.arange(12, dtype=np.float32).reshape(6, 2)
    b = np.ones((6, 2), np.float32)
    df = tfs.frame_from_arrays({"z_1": a, "z_2": b}, num_blocks=2)
    res = tfs.map_blocks(prog, df)
    got = np.concatenate([blk["out"] for blk in res.blocks()])
    np.testing.assert_array_equal(got, a + b)


def test_graph_pb_placeholder_feeds_map_blocks():
    """graph.pb's x placeholder (float [2]) + matmul-free scoring: feed x
    as a block column and fetch a Const-backed product via the DSL-less
    path — here just identity on x through the graph's placeholder."""
    prog = tfs.load_graphdef(
        _fixture("graph.pb"), fetches=["matrix1", "x"], relax_lead_dim=True
    )
    x = np.arange(4, dtype=np.float32)
    df = tfs.frame_from_arrays({"x": x}, num_blocks=1)
    res = tfs.map_blocks(prog, df, trim=True)
    rows = res.blocks()[0]
    np.testing.assert_array_equal(rows["x"], x)


def test_synthetic_reducer_roundtrip():
    """A Sum-with-reduction_indices graph (the shape the reference DSL's
    build_reducer emits, DslImpl.scala:175-200) — built here with TF if
    available, else skipped; exercises Const-axis reducers end to end."""
    tf = pytest.importorskip("tensorflow")
    g = tf.Graph()
    with g.as_default():
        x = tf.compat.v1.placeholder(tf.float64, shape=[None, 3], name="x")
        tf.reduce_sum(x, axis=[0], name="total")
    data = g.as_graph_def().SerializeToString()
    prog = program_from_graphdef(parse_graphdef(data), fetches=["total"])
    feeds = {"x": np.arange(12, dtype=np.float64).reshape(4, 3)}
    out = prog.fn(feeds)
    np.testing.assert_array_equal(
        np.asarray(out["total"]), feeds["x"].sum(axis=0)
    )


def test_unsupported_op_raises_with_name():
    tf = pytest.importorskip("tensorflow")
    g = tf.Graph()
    with g.as_default():
        x = tf.compat.v1.placeholder(tf.float32, shape=[2, 2], name="x")
        tf.linalg.cholesky(x, name="c")
    data = g.as_graph_def().SerializeToString()
    with pytest.raises(ValueError, match="Cholesky"):
        program_from_graphdef(parse_graphdef(data))


def test_tf_cross_check_elementwise_graph():
    """Golden cross-check against real TensorFlow execution (the spirit of
    the reference's ExtractNodes oracle, ExtractNodes.scala:13-76)."""
    tf = pytest.importorskip("tensorflow")
    g = tf.Graph()
    with g.as_default():
        a = tf.compat.v1.placeholder(tf.float32, shape=[None, 4], name="a")
        b = tf.compat.v1.placeholder(tf.float32, shape=[None, 4], name="b")
        c = tf.math.divide(tf.identity(a) + b * 2.0, 4.0, name="c")
        tf.reduce_min(c, axis=[1], name="m")
    data = g.as_graph_def().SerializeToString()
    rng = np.random.default_rng(7)
    feeds = {
        "a": rng.normal(size=(5, 4)).astype(np.float32),
        "b": rng.normal(size=(5, 4)).astype(np.float32),
    }
    with tf.compat.v1.Session(graph=g) as sess:
        want = sess.run("m:0", {"a:0": feeds["a"], "b:0": feeds["b"]})
    prog = program_from_graphdef(parse_graphdef(data), fetches=["m"])
    got = np.asarray(prog.fn(feeds)["m"])
    np.testing.assert_allclose(got, want, rtol=1e-6)


def _varint(x: int) -> bytes:
    out = b""
    while True:
        b = x & 0x7F
        x >>= 7
        out += bytes([b | (0x80 if x else 0)])
        if not x:
            return out


def test_half_val_const_decodes_bit_patterns():
    """fp16 Consts stored in the typed half_val field (bit patterns as
    varints) must decode to real values, not silent zeros."""
    from tensorframes_tpu.graphdef import _parse_tensor

    half_bits = [0x3E00, 0x4100]  # fp16 1.5, 2.5
    payload = b"".join(_varint(b) for b in half_bits)
    proto = (
        b"\x08\x13"  # dtype = 19 (DT_HALF)
        + b"\x12\x04\x12\x02\x08\x02"  # shape { dim { size: 2 } }
        + b"\x6a" + _varint(len(payload)) + payload  # half_val packed
    )
    arr = _parse_tensor(proto)
    assert arr.dtype == np.float16
    np.testing.assert_array_equal(arr, np.asarray([1.5, 2.5], np.float16))


def test_string_const_rejected_on_consumption():
    """String Consts PARSE (SavedModel graphs carry dead saver strings)
    but consuming or fetching one raises — the host-only contract moved
    from parse time to use time in round 3."""
    from tensorframes_tpu.graphdef import _StringTensor, _parse_tensor

    proto = b"\x08\x07" + b"\x42\x02hi"  # dtype=DT_STRING, string_val="hi"
    t = _parse_tensor(proto)
    assert isinstance(t, _StringTensor) and t.values == [b"hi"]

    tf = pytest.importorskip("tensorflow")
    with tf.Graph().as_default() as g:
        tf.constant("dead-string", name="s")        # never consumed
        x = tf.compat.v1.placeholder(tf.float32, [None], name="x")
        tf.identity(x * 2.0, name="y")
    data = g.as_graph_def().SerializeToString()
    prog = program_from_graphdef(parse_graphdef(data), fetches=["y"])
    out = prog.fn({"x": np.asarray([1.0, 2.0], np.float32)})
    np.testing.assert_allclose(np.asarray(out["y"]), [2.0, 4.0])

    # fetching the string const raises at IMPORT with the host-only
    # message (consts are fully known then)
    with pytest.raises(ValueError, match="string"):
        program_from_graphdef(parse_graphdef(data), fetches=["s"])


def test_malformed_bytes_raise_value_error():
    """Corrupt/truncated input surfaces as ValueError naming the format,
    not a bare IndexError from the wire decoder."""
    from tensorframes_tpu.graphdef import parse_graphdef

    with pytest.raises(ValueError, match="GraphDef"):
        parse_graphdef(b"\x0a\xff\xff\xff")  # truncated LEN field
    with pytest.raises(ValueError, match="GraphDef"):
        parse_graphdef(bytes(range(1, 64)))  # arbitrary junk


def test_load_graphdef_on_non_proto_file(tmp_path):
    p = tmp_path / "junk.pb"
    p.write_bytes(b"this is not a protobuf at all \xff\xfe")
    with pytest.raises(ValueError, match="GraphDef"):
        tfs.load_graphdef(str(p))


# ---------------------------------------------------------------------------
# round 3: dynamic-shape op tier + iterative evaluator
# ---------------------------------------------------------------------------


def test_kmeans_assignment_graph_golden():
    """The reference's OWN k-means assignment graph
    (tensorframes_snippets/kmeans.py:28-45): Square/Shape/StridedSlice/
    ExpandDims/Pack/Tile/ArgMin with a SHAPE-DERIVED dynamic Tile
    multiple — the TF1 idiom that XLA's static shapes fold at trace time.
    Golden-matched against a TF session."""
    tf = pytest.importorskip("tensorflow")
    k, num_features = 3, 4
    rng = np.random.default_rng(0)
    init_centers = rng.normal(size=(k, num_features))
    g = tf.Graph()
    with g.as_default():
        points = tf.compat.v1.placeholder(
            tf.float64, shape=[None, num_features], name="points"
        )
        num_points = tf.shape(points)[0]
        centers = tf.constant(init_centers)
        squares = tf.reduce_sum(tf.square(points), axis=1)
        center_squares = tf.reduce_sum(tf.square(centers), axis=1)
        prods = tf.matmul(points, centers, transpose_b=True)
        t1 = tf.tile(
            tf.expand_dims(center_squares, 0), tf.stack([num_points, 1])
        )
        t2 = tf.tile(tf.expand_dims(squares, 1), tf.stack([1, k]))
        distances = tf.identity(t1 + t2 - 2 * prods, name="distances")
        tf.argmin(distances, 1, name="indexes")
        tf.reduce_min(distances, 1, name="min_distances")
        tf.tile(tf.constant([1]), tf.stack([num_points]), name="count")
    data = g.as_graph_def().SerializeToString()
    block = rng.normal(size=(17, num_features))
    with tf.compat.v1.Session(graph=g) as sess:
        want = sess.run(
            ["distances:0", "indexes:0", "min_distances:0", "count:0"],
            {"points:0": block},
        )
    prog = program_from_graphdef(
        parse_graphdef(data),
        fetches=["distances", "indexes", "min_distances", "count"],
    )
    out = prog.fn({"points": block})
    np.testing.assert_allclose(np.asarray(out["distances"]), want[0], rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(out["indexes"]), want[1])
    np.testing.assert_allclose(
        np.asarray(out["min_distances"]), want[2], rtol=1e-6
    )
    np.testing.assert_array_equal(np.asarray(out["count"]), want[3])


def test_kmeans_graph_through_map_blocks():
    """Same graph JITTED through the map_blocks verb — the shape-derived
    Tile multiples must fold during tracing (≙ the reference runs this
    exact graph via tfs.map_blocks, kmeans.py:65)."""
    tf = pytest.importorskip("tensorflow")
    k, num_features = 2, 4
    rng = np.random.default_rng(1)
    init_centers = rng.normal(size=(k, num_features))
    g = tf.Graph()
    with g.as_default():
        points = tf.compat.v1.placeholder(
            tf.float64, shape=[None, num_features], name="features"
        )
        num_points = tf.shape(points)[0]
        centers = tf.constant(init_centers)
        squares = tf.reduce_sum(tf.square(points), axis=1)
        center_squares = tf.reduce_sum(tf.square(centers), axis=1)
        prods = tf.matmul(points, centers, transpose_b=True)
        t1 = tf.tile(
            tf.expand_dims(center_squares, 0), tf.stack([num_points, 1])
        )
        t2 = tf.tile(tf.expand_dims(squares, 1), tf.stack([1, k]))
        distances = t1 + t2 - 2 * prods
        tf.argmin(distances, 1, name="indexes")
    data = g.as_graph_def().SerializeToString()
    prog = program_from_graphdef(
        parse_graphdef(data), fetches=["indexes"], relax_lead_dim=True
    )
    feats = rng.normal(size=(24, num_features))
    df = tfs.frame_from_arrays({"features": feats}, num_blocks=3)
    res = tfs.map_blocks(prog, df, trim=True)
    got = np.concatenate([blk["indexes"] for blk in res.blocks()])
    d = (
        (feats ** 2).sum(1)[:, None]
        + (init_centers ** 2).sum(1)[None, :]
        - 2 * feats @ init_centers.T
    )
    np.testing.assert_array_equal(got, d.argmin(1))


def _float_attr_placeholder_nodes():
    from tensorframes_tpu.graphdef import GraphNode, _Attr

    dtype_a = _Attr()
    dtype_a.type = 1  # DT_FLOAT
    shape_a = _Attr()
    shape_a.shape = [2]
    return GraphNode("x", "Placeholder", [], {"dtype": dtype_a, "shape": shape_a})


def test_deep_chain_evaluates_without_recursion_limit():
    """2,500 sequential ops — deeper than Python's ~1000-frame recursion
    limit. The explicit work-stack evaluator must handle it (a
    ResNet-152-class frozen graph is this shape)."""
    from tensorframes_tpu.graphdef import GraphNode

    nodes = [_float_attr_placeholder_nodes()]
    prev = "x"
    for i in range(2500):
        nodes.append(GraphNode(f"n{i}", "Identity", [prev], {}))
        prev = f"n{i}"
    prog = program_from_graphdef(nodes, fetches=[prev])
    out = prog.fn({"x": np.asarray([1.5, -2.0], np.float32)})
    np.testing.assert_array_equal(
        np.asarray(out[prev]), np.asarray([1.5, -2.0], np.float32)
    )


def test_cyclic_graph_raises():
    from tensorframes_tpu.graphdef import GraphNode

    nodes = [
        _float_attr_placeholder_nodes(),
        GraphNode("a", "Identity", ["b"], {}),
        GraphNode("b", "Identity", ["a"], {}),
    ]
    prog = program_from_graphdef(nodes, fetches=["a"])
    with pytest.raises(ValueError, match="cycle"):
        prog.fn({"x": np.zeros(2, np.float32)})


def test_cast_unsupported_dtype_enum_raises_value_error():
    """ADVICE r2: a bad DstT enum must raise the module's descriptive
    ValueError, not a bare KeyError."""
    from tensorframes_tpu.graphdef import GraphNode, _Attr

    cast_a = _Attr()
    cast_a.type = 100  # no such DataType
    nodes = [
        _float_attr_placeholder_nodes(),
        GraphNode("c", "Cast", ["x"], {"DstT": cast_a}),
    ]
    prog = program_from_graphdef(nodes, fetches=["c"])
    with pytest.raises(ValueError, match="Cast node 'c'"):
        prog.fn({"x": np.zeros(2, np.float32)})


def test_partial_val_fill_pads_with_last_value():
    """ADVICE r2: TensorProto with 1 < len(vals) < shape-size follows
    TF's fill convention (remainder repeats the last value)."""
    from tensorframes_tpu.graphdef import _parse_tensor

    payload = b"".join(
        __import__("struct").pack("<f", v) for v in (1.0, 2.0)
    )
    proto = (
        b"\x08\x01"  # dtype = DT_FLOAT
        + b"\x12\x04\x12\x02\x08\x04"  # shape { dim { size: 4 } }
        + b"\x2a" + _varint(len(payload)) + payload  # float_val packed
    )
    arr = _parse_tensor(proto)
    np.testing.assert_array_equal(
        arr, np.asarray([1.0, 2.0, 2.0, 2.0], np.float32)
    )


def test_f64_conv_graph_stays_faithful():
    """Regression (r3 review): with no ``compute_dtype`` policy the
    importer must keep a DT_DOUBLE conv/matmul graph exactly f64 — an
    unconditional f32 ``preferred_element_type`` is narrower than the
    operands and raises at trace time on this jax build."""
    tf = pytest.importorskip("tensorflow")

    from tensorframes_tpu.graphdef import parse_graphdef, program_from_graphdef

    rng = np.random.default_rng(7)
    w = rng.standard_normal((3, 3, 2, 4))
    with tf.Graph().as_default() as g:
        x = tf.compat.v1.placeholder(tf.float64, [None, 8, 8, 2], name="x")
        c = tf.constant(w, dtype=tf.float64, name="w")
        y = tf.nn.conv2d(x, c, strides=[1, 1, 1, 1], padding="SAME", name="y")
        tf.linalg.matmul(
            tf.reshape(y, [-1, 8 * 8 * 4]),
            tf.constant(rng.standard_normal((8 * 8 * 4, 3)), tf.float64),
            name="out",
        )
    data = g.as_graph_def().SerializeToString()
    prog = program_from_graphdef(parse_graphdef(data), fetches=["out"])
    xv = rng.standard_normal((2, 8, 8, 2))
    got = prog.fn({"x": xv})["out"]
    assert got.dtype == np.float64
    with tf.compat.v1.Session(graph=g) as sess:
        want = sess.run("out:0", {"x:0": xv})
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-10)

    # the bf16 policy must leave f64 graphs untouched too (its cast and
    # its f32-accumulation override are both f32-operand-only)
    prog_b = program_from_graphdef(
        parse_graphdef(data), fetches=["out"], compute_dtype="bfloat16"
    )
    got_b = prog_b.fn({"x": xv})["out"]
    assert got_b.dtype == np.float64
    np.testing.assert_allclose(np.asarray(got_b), want, atol=1e-10)


def test_multi_output_ops_match_tf():
    """Multi-output tier (round 3): Split/SplitV/Unpack/TopKV2 evaluate
    to tuples; consumers (and explicit fetches) select outputs via the
    ``:k`` ref suffix — previously any ``:k>0`` ref was rejected. Non-
    multi-output producers still reject ``:k>0`` refs loudly."""
    tf = pytest.importorskip("tensorflow")

    from tensorframes_tpu.graphdef import parse_graphdef, program_from_graphdef

    rng = np.random.default_rng(0)
    xv = rng.standard_normal((4, 12)).astype(np.float32)
    with tf.Graph().as_default() as g:
        x = tf.compat.v1.placeholder(tf.float32, [None, 12], name="x")
        a, b, c = tf.split(x, 3, axis=1, name="sp")
        s1, s2 = tf.split(x, [5, 7], axis=1, name="spv")
        u0, u1, u2, u3 = tf.unstack(x, num=4, axis=0, name="un")
        tv, ti = tf.math.top_k(x, k=3, name="tk")
        tf.add(a * 2.0 + b - c[:, :4], s1[:, :4], name="mix")
        tf.add(u1, u2, name="mix2")
        tf.identity(tv, name="tkv")
        tf.identity(tf.cast(ti, tf.float32), name="tki")
    data = g.as_graph_def().SerializeToString()

    fetches = ["mix", "mix2", "tkv", "tki"]
    prog = program_from_graphdef(parse_graphdef(data), fetches=fetches)
    got = prog.fn({"x": xv})
    with tf.compat.v1.Session(graph=g) as sess:
        want = sess.run([f + ":0" for f in fetches], {"x:0": xv})
    for name, w in zip(fetches, want):
        np.testing.assert_allclose(np.asarray(got[name]), w, atol=1e-6)

    # a ':k>0' FETCH of a single-output producer is rejected too (it
    # would otherwise silently return output :0)
    with pytest.raises(ValueError, match="single-output"):
        program_from_graphdef(parse_graphdef(data), fetches=["mix:1"])

    # :k>0 into a single-output producer still rejected by name
    with tf.Graph().as_default() as g2:
        x2 = tf.compat.v1.placeholder(tf.float32, [None, 3], name="x")
        tf.constant(np.eye(3, dtype=np.float32))
        bm = tf.raw_ops.FusedBatchNorm(
            x=tf.reshape(x2, [-1, 1, 1, 3]), scale=[1.0, 1.0, 1.0],
            offset=[0.0, 0.0, 0.0], mean=[], variance=[],
            is_training=True,
        )
        tf.identity(bm.batch_mean, name="stats")
    data2 = g2.as_graph_def().SerializeToString()
    with pytest.raises(ValueError, match="multi-output"):
        program_from_graphdef(parse_graphdef(data2), fetches=["stats"])


def test_partitioned_call_unfrozen_tf_function():
    """Un-frozen ``tf.function`` exports (round 3): the graph keeps
    PartitionedCall wrappers and a FunctionDefLibrary instead of inlined
    nodes; the importer parses the library (clean-room FunctionDef
    decode) and evaluates call bodies with the FunctionDef ref
    convention (``node:port:index``) — including NESTED calls and
    multi-output functions. ≙ "GraphDefs produced by any TF program"
    (PythonInterface.scala:115-118) extended past the frozen family."""
    tf = pytest.importorskip("tensorflow")

    from tensorframes_tpu.graphdef import parse_graphdef, program_from_graphdef

    @tf.function
    def leaf(x):
        return tf.tanh(x)

    @tf.function
    def mid(x):
        a, b = tf.split(leaf(x), 2, axis=1)
        return a + b, a * b  # multi-output function

    @tf.function
    def top(x):
        s, p = mid(x * 0.5)
        return s - p

    cf = top.get_concrete_function(tf.TensorSpec([None, 8], tf.float32))
    data = cf.graph.as_graph_def().SerializeToString()
    nodes = parse_graphdef(data)
    assert nodes.library  # the function bodies came through the parser
    prog = program_from_graphdef(nodes, relax_lead_dim=True)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((5, 8)).astype(np.float32)
    got = np.asarray(prog.fn({prog.inputs[0].name: x})[prog.fetch_order[0]])
    want = top(x).numpy()
    np.testing.assert_allclose(got, want, atol=1e-6)

    # unsupported ops INSIDE function bodies are named at import time
    @tf.function
    def bad(x):
        return tf.linalg.cholesky(x)

    @tf.function
    def calls_bad(x):
        return bad(x) + 1.0

    cf2 = calls_bad.get_concrete_function(
        tf.TensorSpec([None, 4, 4], tf.float32)
    )
    with pytest.raises(ValueError, match="Cholesky"):
        program_from_graphdef(
            parse_graphdef(cf2.graph.as_graph_def().SerializeToString())
        )


def test_extended_elementwise_ops_match_tf():
    """The long-tail activation/math tier (Elu/Selu/Softplus/LeakyRelu
    with its alpha attr/trig/Log1p/...) — one TF-golden sweep."""
    tf = pytest.importorskip("tensorflow")

    from tensorframes_tpu.graphdef import parse_graphdef, program_from_graphdef

    rng = np.random.default_rng(3)
    xv = (rng.standard_normal((4, 6)) * 0.8).astype(np.float32)
    with tf.Graph().as_default() as g:
        x = tf.compat.v1.placeholder(tf.float32, [None, 6], name="x")
        tf.nn.elu(x, name="elu")
        tf.nn.selu(x, name="selu")
        tf.nn.softplus(x, name="softplus")
        tf.nn.leaky_relu(x, alpha=0.1, name="leaky")
        tf.math.sin(x, name="sin")
        tf.math.atan2(x, x + 2.0, name="atan2")
        tf.math.log1p(tf.abs(x), name="log1p")
        tf.math.reciprocal(x + 3.0, name="recip")
        tf.math.sign(x, name="sign")
    data = g.as_graph_def().SerializeToString()
    fetches = ["elu", "selu", "softplus", "leaky", "sin", "atan2",
               "log1p", "recip", "sign"]
    prog = program_from_graphdef(parse_graphdef(data), fetches=fetches)
    got = prog.fn({"x": xv})
    with tf.compat.v1.Session(graph=g) as sess:
        want = sess.run([f + ":0" for f in fetches], {"x:0": xv})
    for name, w in zip(fetches, want):
        np.testing.assert_allclose(
            np.asarray(got[name]), w, atol=1e-6, err_msg=name
        )


def test_mod_truncated_semantics_and_quantize_library_guard():
    """TF's Mod is truncated (sign of dividend), not floor-modulo; and
    quantize_weights on a library-bearing graph is rejected loudly
    rather than silently no-opping (round-3 review)."""
    tf = pytest.importorskip("tensorflow")

    from tensorframes_tpu.graphdef import parse_graphdef, program_from_graphdef

    with tf.Graph().as_default() as g:
        x = tf.compat.v1.placeholder(tf.float32, [None], name="x")
        tf.raw_ops.Mod(x=x, y=tf.constant([3.0]), name="m")
    data = g.as_graph_def().SerializeToString()
    prog = program_from_graphdef(parse_graphdef(data), fetches=["m"])
    xv = np.asarray([-7.5, 7.5, -6.0], np.float32)
    got = np.asarray(prog.fn({"x": xv})["m"])
    with tf.compat.v1.Session(graph=g) as sess:
        want = sess.run("m:0", {"x:0": xv})
    np.testing.assert_allclose(got, want)  # [-1.5, 1.5, -0.0]

    @tf.function
    def wrapped(x):
        return tf.nn.relu(x)

    @tf.function
    def outer(x):
        return wrapped(x) + 1.0

    cf = outer.get_concrete_function(tf.TensorSpec([None, 2], tf.float32))
    nodes = parse_graphdef(cf.graph.as_graph_def().SerializeToString())
    with pytest.raises(ValueError, match="function library"):
        program_from_graphdef(nodes, quantize_weights=True)


def test_shape_and_scan_op_tier_matches_tf():
    """Slice/ZerosLike/OnesLike/BroadcastTo/OneHot/Cumsum/Cumprod/Rank/
    Size — TF-golden sweep; Cumsum's exclusive/reverse modes reject by
    name."""
    tf = pytest.importorskip("tensorflow")

    from tensorframes_tpu.graphdef import parse_graphdef, program_from_graphdef

    rng = np.random.default_rng(5)
    xv = rng.standard_normal((3, 6)).astype(np.float32)
    iv = rng.integers(0, 4, (3,)).astype(np.int32)
    with tf.Graph().as_default() as g:
        x = tf.compat.v1.placeholder(tf.float32, [None, 6], name="x")
        idx = tf.compat.v1.placeholder(tf.int32, [None], name="idx")
        tf.slice(x, [0, 2], [-1, 3], name="sl")
        tf.zeros_like(x, name="zl")
        tf.ones_like(x, name="ol")
        tf.broadcast_to(tf.reduce_sum(x, axis=1, keepdims=True), [3, 6],
                        name="bc")
        tf.one_hot(idx, 4, on_value=2.0, off_value=-1.0, name="oh")
        tf.cumsum(x, axis=1, name="cs")
        tf.math.cumprod(tf.abs(x) + 0.5, axis=0, name="cp")
        tf.add(tf.cast(tf.rank(x), tf.float32),
               tf.cast(tf.size(x), tf.float32), name="rs")
    data = g.as_graph_def().SerializeToString()
    fetches = ["sl", "zl", "ol", "bc", "oh", "cs", "cp", "rs"]
    prog = program_from_graphdef(parse_graphdef(data), fetches=fetches)
    got = prog.fn({"x": xv, "idx": iv})
    with tf.compat.v1.Session(graph=g) as sess:
        want = sess.run([f + ":0" for f in fetches],
                        {"x:0": xv, "idx:0": iv})
    for name, w in zip(fetches, want):
        np.testing.assert_allclose(
            np.asarray(got[name]), w, atol=1e-6, err_msg=name
        )

    with tf.Graph().as_default() as g2:
        x2 = tf.compat.v1.placeholder(tf.float32, [None, 4], name="x")
        tf.cumsum(x2, axis=1, exclusive=True, name="bad")
    with pytest.raises(ValueError, match="exclusive"):
        prog2 = program_from_graphdef(
            parse_graphdef(g2.as_graph_def().SerializeToString()),
            fetches=["bad"],
        )
        prog2.fn({"x": np.ones((2, 4), np.float32)})


def test_recursive_function_library_raises_at_import():
    """ADVICE r3: a (malformed) self- or mutually-recursive
    FunctionDefLibrary must raise the module's clean ValueError at
    IMPORT time — the seen-set dedup walk alone passes such graphs, and
    the first _eval_function call would then hit Python's
    RecursionError."""
    from tensorframes_tpu.graphdef import (
        FunctionDef, GraphNode, GraphNodes, _Attr,
    )

    def call_attr(fname):
        a = _Attr()
        a.func = fname
        return a

    # self-recursion: f's body calls f
    fd = FunctionDef(
        "f", ["arg"], ["out"],
        [GraphNode("again", "PartitionedCall", ["arg"],
                   {"f": call_attr("f")})],
        {"out": "again:output:0"},
    )
    main = [
        _float_attr_placeholder_nodes(),
        GraphNode("call", "PartitionedCall", ["x"],
                  {"f": call_attr("f")}),
    ]
    with pytest.raises(ValueError, match="call cycle"):
        program_from_graphdef(
            GraphNodes(main, {"f": fd}), fetches=["call"]
        )

    # mutual recursion: f -> g -> f
    fd_f = FunctionDef(
        "f", ["arg"], ["out"],
        [GraphNode("cg", "PartitionedCall", ["arg"],
                   {"f": call_attr("g")})],
        {"out": "cg:output:0"},
    )
    fd_g = FunctionDef(
        "g", ["arg"], ["out"],
        [GraphNode("cf", "PartitionedCall", ["arg"],
                   {"f": call_attr("f")})],
        {"out": "cf:output:0"},
    )
    with pytest.raises(ValueError, match="f -> g -> f"):
        program_from_graphdef(
            GraphNodes(main, {"f": fd_f, "g": fd_g}), fetches=["call"]
        )


def _ld(field: int, payload: bytes) -> bytes:
    return bytes([(field << 3) | 2]) + _varint(len(payload)) + payload


def _vf(field: int, value: int) -> bytes:
    return bytes([(field << 3) | 0]) + _varint(value)


def _node_bytes(name, op, inputs=(), attrs=()):
    b = _ld(1, name.encode()) + _ld(2, op.encode())
    for i in inputs:
        b += _ld(3, i.encode())
    for k, v in attrs:
        b += _ld(5, _ld(1, k.encode()) + _ld(2, v))
    return b


def _tiny_graphdef_bytes():
    """x = Placeholder(float, [2]); y = Identity(x)."""
    dtype_attr = _vf(6, 1)  # AttrValue.type = DT_FLOAT
    shape_attr = _ld(7, _ld(2, _vf(1, 2)))  # shape { dim { size: 2 } }
    x = _node_bytes(
        "x", "Placeholder",
        attrs=[("dtype", dtype_attr), ("shape", shape_attr)],
    )
    y = _node_bytes("y", "Identity", inputs=["x"], attrs=[("T", dtype_attr)])
    return _ld(1, x) + _ld(1, y)


def _signature_entry(key, inputs, outputs):
    sig = b""
    for arg, ref in inputs.items():
        sig += _ld(1, _ld(1, arg.encode()) + _ld(2, _ld(1, ref.encode())))
    for arg, ref in outputs.items():
        sig += _ld(2, _ld(1, arg.encode()) + _ld(2, _ld(1, ref.encode())))
    return _ld(5, _ld(1, key.encode()) + _ld(2, sig))


def _meta_graph_bytes(tags, graphdef, sig_entries):
    info = b"".join(_ld(4, t.encode()) for t in tags)
    return _ld(1, info) + _ld(2, graphdef) + sig_entries


def test_saved_model_multiple_meta_graphs(tmp_path):
    """ADVICE r3: a SavedModel carrying several meta graphs (train +
    serve tag-sets) must serve the signature from whichever meta graph
    HOLDS it — first-only decoding raised KeyError even though the
    signature existed. Hand-built wire bytes: no TF dependency."""
    from tensorframes_tpu.graphdef import (
        parse_saved_model, parse_saved_model_meta_graphs,
    )

    gd = _tiny_graphdef_bytes()
    train_mg = _meta_graph_bytes(
        ["train"], gd, _signature_entry(
            "train_step", {"inp": "x:0"}, {"out": "y:0"}
        ),
    )
    serve_mg = _meta_graph_bytes(
        ["serve"], gd, _signature_entry(
            "serving_default", {"inp": "x:0"}, {"out": "y:0"}
        ),
    )
    sm = _ld(2, train_mg) + _ld(2, serve_mg)  # train FIRST

    metas = parse_saved_model_meta_graphs(sm)
    assert [tags for _, _, tags in metas] == [["train"], ["serve"]]
    assert list(metas[0][1]) == ["train_step"]
    assert list(metas[1][1]) == ["serving_default"]

    # parse_saved_model prefers the serve-tagged meta graph
    _, sigs = parse_saved_model(sm)
    assert "serving_default" in sigs

    # load_saved_model finds serving_default in the SECOND meta graph
    sm_dir = tmp_path / "sm"
    sm_dir.mkdir()
    (sm_dir / "saved_model.pb").write_bytes(sm)
    prog = tfs.load_saved_model(str(sm_dir))
    xv = np.asarray([1.5, -2.0], np.float32)
    out = prog.fn({"x": xv})
    np.testing.assert_array_equal(np.asarray(out["out"]), xv)

    # ... and the train-tagged signature resolves too (lives in mg[0])
    prog_t = tfs.load_saved_model(str(sm_dir), signature="train_step")
    out_t = prog_t.fn({"x": xv})
    np.testing.assert_array_equal(np.asarray(out_t["out"]), xv)

    # an absent signature reports signatures across ALL meta graphs
    with pytest.raises(KeyError, match="2 meta graph"):
        tfs.load_saved_model(str(sm_dir), signature="nope")


def test_compute_dtype_auto_resolution(monkeypatch):
    """VERDICT r3 #3: the import path serves bfloat16 BY DEFAULT on
    accelerator backends (the f32-only import trailed the native bf16
    model ~5x on the chip); CPU stays f32-faithful so golden tests
    compare bit-for-bit, and an explicit None opts out anywhere."""
    import jax

    from tensorframes_tpu import graphdef as gd

    assert gd._resolve_compute_dtype("auto") is None  # cpu suite
    assert gd._resolve_compute_dtype(None) is None
    assert gd._resolve_compute_dtype("bfloat16") == "bfloat16"
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert gd._resolve_compute_dtype("auto") == "bfloat16"
    assert gd._resolve_compute_dtype(None) is None


def test_image_serving_op_tier_matches_tf():
    """Round-4 importer tier: the ops frozen detection/segmentation/
    preprocessing graphs lean on — legacy image resizes in every
    align_corners/half_pixel_centers combination, depth/space shuffles,
    GatherNd, MirrorPad, AddN, band-part, ReverseV2, LogSoftmax,
    Xdivy/DivNoNan — all golden-matched against TF running the same
    frozen bytes."""
    tf = pytest.importorskip("tensorflow")

    rng = np.random.default_rng(0)
    img = rng.standard_normal((2, 5, 7, 4)).astype(np.float32)
    m = rng.standard_normal((3, 6, 6)).astype(np.float32)
    idx = np.asarray([[1, 2], [0, 0], [1, 4]], np.int32)

    with tf.Graph().as_default() as g:
        x = tf.compat.v1.placeholder(tf.float32, [2, 5, 7, 4], name="x")
        mm = tf.compat.v1.placeholder(tf.float32, [3, 6, 6], name="m")
        ii = tf.compat.v1.placeholder(tf.int32, [3, 2], name="ii")
        tf.compat.v1.image.resize_bilinear(x, [8, 9], name="rb")
        tf.compat.v1.image.resize_bilinear(
            x, [8, 9], align_corners=True, name="rba"
        )
        tf.compat.v1.image.resize_bilinear(
            x, [8, 9], half_pixel_centers=True, name="rbh"
        )
        tf.compat.v1.image.resize_nearest_neighbor(x, [3, 4], name="rn")
        tf.compat.v1.image.resize_nearest_neighbor(
            x, [3, 4], align_corners=True, name="rna"
        )
        # 5->9 rows: align scale (5-1)/(9-1)=0.5 puts source coords at
        # exact .5 — TF rounds half AWAY from zero, np.rint would not
        tf.compat.v1.image.resize_nearest_neighbor(
            x, [9, 7], align_corners=True, name="rnah"
        )
        # const table gathered by PLACEHOLDER indices (embedding-lookup
        # shape): the table is trace-time numpy, the indices traced
        tf.gather_nd(
            tf.constant(np.arange(24, dtype=np.float32).reshape(4, 3, 2)),
            ii % 2, name="gnc",
        )
        tf.compat.v1.image.resize_nearest_neighbor(
            x, [3, 4], half_pixel_centers=True, name="rnh"
        )
        tf.nn.space_to_depth(
            tf.compat.v1.image.resize_bilinear(x, [6, 8]), 2, name="sd"
        )
        tf.nn.depth_to_space(x, 2, name="ds")
        tf.gather_nd(x, ii, name="gn")
        tf.pad(mm, [[0, 0], [1, 2], [2, 1]], mode="REFLECT", name="mr")
        tf.pad(mm, [[0, 0], [1, 2], [2, 1]], mode="SYMMETRIC", name="ms")
        tf.add_n([mm, mm * 2.0, mm - 1.0], name="an")
        tf.linalg.band_part(mm, 1, 2, name="bp")
        tf.linalg.band_part(mm, -1, 0, name="bpl")
        tf.reverse(mm, axis=[1, 2], name="rv")
        tf.nn.log_softmax(mm, name="ls")
        tf.identity(
            tf.math.xdivy(mm, tf.abs(mm) - tf.abs(mm)), name="xd"
        )  # y==0 path
        tf.math.divide_no_nan(mm, mm - mm, name="dn")  # y==0 everywhere
        tf.identity(
            tf.math.xlogy(tf.nn.relu(mm), tf.abs(mm)), name="xl"
        )  # x==0 path where relu clamps
        tf.reduce_all(mm > -10.0, axis=1, name="ra")
        tf.reduce_any(mm > 0.5, axis=[0, 2], name="ry")
    data = g.as_graph_def().SerializeToString()
    fetches = [
        "rb", "rba", "rbh", "rn", "rna", "rnah", "rnh", "sd", "ds",
        "gn", "gnc",
        "mr", "ms", "an", "bp", "bpl", "rv", "ls", "xd", "xl", "dn",
        "ra", "ry",
    ]
    prog = program_from_graphdef(
        parse_graphdef(data), fetches=fetches, compute_dtype=None
    )
    got = prog.fn({"x": img, "m": m, "ii": idx})
    with tf.compat.v1.Session(graph=g) as sess:
        want = sess.run(
            [f + ":0" for f in fetches], {"x:0": img, "m:0": m, "ii:0": idx}
        )
    for name, w in zip(fetches, want):
        np.testing.assert_allclose(
            np.asarray(got[name]).astype(np.float64),
            np.asarray(w).astype(np.float64),
            atol=1e-5, err_msg=name,
        )


def test_compute_dtype_auto_logs_bf16_once(monkeypatch, caplog):
    """ADVICE r4: "auto" silently flipping imports to bf16 must be
    traceable — one INFO line per process the first time auto resolves
    to bfloat16, none on later resolutions."""
    import jax
    import logging

    from tensorframes_tpu import graphdef as gd

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(gd, "_auto_bf16_logged", False)
    with caplog.at_level(logging.INFO, logger="tensorframes_tpu.graphdef"):
        assert gd._resolve_compute_dtype("auto") == "bfloat16"
        assert gd._resolve_compute_dtype("auto") == "bfloat16"
    hits = [r for r in caplog.records if "bfloat16" in r.message]
    assert len(hits) == 1 and "compute_dtype=None" in hits[0].getMessage()


def test_unresolved_variable_error_type():
    """ADVICE r4: an unbound VarHandleOp raises the DEDICATED subclass
    (still a ValueError for old callers) so load_saved_model's
    TF-freezing fallback can tell it from genuine lowering errors."""
    from tensorframes_tpu.graphdef import GraphNode, UnresolvedVariableError

    node = GraphNode(name="w", op="VarHandleOp", inputs=[], attrs={})
    with pytest.raises(UnresolvedVariableError) as ei:
        program_from_graphdef([node], fetches=["w"])
    assert isinstance(ei.value, ValueError)
    assert "no bound value" in str(ei.value)


def test_bundle_truncated_index_raises_bundle_error():
    """ADVICE r4: a block handle whose tag byte would sit exactly at
    EOF must surface as BundleError (the documented fallback contract),
    not IndexError."""
    from tensorframes_tpu.bundle import BundleError, _parse_table_block

    data = bytes(16)
    with pytest.raises(BundleError):
        _parse_table_block(data, 8, 8)  # off+size == len(data)
    with pytest.raises(BundleError):
        _parse_table_block(data, 8, 12)  # past EOF
