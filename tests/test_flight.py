"""Flight-recorder tests: ring semantics, redaction, dump triggers
(guard-raise, StaticAnalysisError, unhandled crash), and the kill -9
black box — the spooled records a SIGKILLed process leaves behind must
reconstruct what it was dispatching (the end-to-end acceptance of
ISSUE 6's recorder: fault-injection/crash tests produce a recoverable
black box, following the tests/test_crash_resume.py subprocess
pattern)."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.observability import flight

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture(autouse=True)
def _fresh_recorder():
    """Give each test an empty ring with no spool, restoring the
    process recorder afterwards (the CI session may have armed
    TFTPU_FLIGHT_DIR for the whole suite)."""
    saved_dir = flight.RECORDER.spool_dir
    saved_ring = flight.RECORDER.records()
    flight.RECORDER.set_spool_dir(None)
    flight.RECORDER.clear()
    yield
    flight.RECORDER.set_spool_dir(saved_dir)
    flight.RECORDER.clear()
    for rec in saved_ring:
        flight.RECORDER._ring.append(rec)


# ---------------------------------------------------------------------------
# ring + redaction semantics
# ---------------------------------------------------------------------------

def test_ring_is_bounded_and_ordered():
    rec = flight.FlightRecorder(capacity=5)
    for i in range(12):
        rec.record("tick", i=i)
    got = rec.records()
    assert len(got) == 5
    assert [r["i"] for r in got] == [7, 8, 9, 10, 11]  # oldest dropped
    assert rec.total_records == 12
    seqs = [r["seq"] for r in got]
    assert seqs == sorted(seqs)


def test_redaction_blanks_secrets_and_array_contents():
    fields = flight.redact_fields({
        "api_key": "sk-123456",
        "auth_token": "abc",
        "weights": np.arange(1000.0),
        "note": "x" * 500,
        "n": 7,
        "frac": 0.5,
        "bad": float("nan"),
    })
    assert fields["api_key"] == "[redacted]"
    assert fields["auth_token"] == "[redacted]"
    assert fields["weights"].startswith("<array shape=(1000,)")
    assert "1.0" not in fields["weights"]  # never values
    assert len(fields["note"]) < 250
    assert fields["n"] == 7 and fields["frac"] == 0.5
    assert fields["bad"] == "nan"  # strict-JSON-safe
    json.dumps(fields)  # the whole record must serialize strictly


def test_dispatches_are_recorded_with_shapes():
    df = tfs.frame_from_arrays({"x": np.arange(16.0)}, num_blocks=2)
    program = tfs.compile_program(lambda x: {"y": x + 1.0}, df)
    tfs.map_blocks(program, df).collect()
    dispatches = [
        r for r in flight.RECORDER.records() if r["kind"] == "dispatch"
    ]
    assert len(dispatches) >= 2  # one per block
    d = dispatches[-1]
    assert d["entry"] == "block"
    assert "y" in d["outputs"]
    assert d["shapes"]["x"] == [8]
    assert d["seconds"] >= 0


def test_failing_dispatch_recorded_before_error_propagates():
    from tensorframes_tpu.resilience import faults

    df = tfs.frame_from_arrays({"x": np.arange(8.0)}, num_blocks=1)
    program = tfs.compile_program(lambda x: {"y": x * 2.0}, df)
    with faults.inject("executor.run_block", RuntimeError("chip fell off")):
        with pytest.raises(RuntimeError):
            tfs.map_blocks(program, df).collect()
    kinds = [r["kind"] for r in flight.RECORDER.records()]
    assert "fault.injected" in kinds
    errs = [
        r for r in flight.RECORDER.records() if r["kind"] == "dispatch.error"
    ]
    assert errs and errs[-1]["error"] == "RuntimeError"
    assert "chip fell off" in errs[-1]["message"]
    assert errs[-1]["shapes"]["x"] == [8]


def test_retry_and_guard_records():
    from tensorframes_tpu.resilience import (
        RetryError, RetryPolicy, StepGuard, retry_call,
    )

    def flaky():
        raise OSError("wobble")

    with pytest.raises(RetryError):
        retry_call(flaky, policy=RetryPolicy(max_attempts=2, backoff=0.0,
                                             seed=0))
    kinds = [r["kind"] for r in flight.RECORDER.records()]
    assert "retry" in kinds and "retry.exhausted" in kinds

    g = StepGuard(policy="skip", check="metrics")
    g.admit(1, {"w": 1.0}, {"loss": float("nan")}, prev_state={"w": 0.0})
    trips = [
        r for r in flight.RECORDER.records() if r["kind"] == "guard.trip"
    ]
    assert trips and trips[-1]["policy"] == "skip"


# ---------------------------------------------------------------------------
# dump triggers
# ---------------------------------------------------------------------------

def test_manual_dump_writes_header_then_ring(tmp_path):
    flight.record("tick", i=1)
    flight.record("tick", i=2)
    path = str(tmp_path / "pm.jsonl")
    out = flight.dump(path, reason="test", exc=ValueError("boom"))
    assert out == path
    rows = [json.loads(ln) for ln in open(path)]
    assert rows[0]["kind"] == "postmortem"
    assert rows[0]["reason"] == "test"
    assert rows[0]["error"] == "ValueError"
    assert "run_id" in rows[0] and "process_index" in rows[0]
    assert [r["i"] for r in rows[1:] if r["kind"] == "tick"] == [1, 2]


def test_dump_without_spool_dir_is_a_noop():
    flight.record("tick")
    assert flight.dump(reason="nowhere-to-write") is None


def test_repeated_dumps_never_overwrite(tmp_path):
    """A guard-raise black box must survive a later crash dump: the
    per-process dump counter keeps default-path filenames unique."""
    flight.set_spool_dir(str(tmp_path))
    flight.record("tick", i=1)
    p1 = flight.dump(reason="guard-raise")
    flight.record("tick", i=2)
    p2 = flight.dump(reason="crash")
    assert p1 != p2 and os.path.exists(p1) and os.path.exists(p2)
    first = [json.loads(ln) for ln in open(p1)]
    assert first[0]["reason"] == "guard-raise"
    assert [r.get("i") for r in first[1:]] == [1]


def test_guard_raise_dumps_postmortem(tmp_path):
    from tensorframes_tpu.resilience import NonFiniteError, StepGuard

    flight.set_spool_dir(str(tmp_path))
    g = StepGuard(policy="raise", check="metrics")
    with pytest.raises(NonFiniteError):
        g.admit(3, {"w": 1.0}, {"loss": float("inf")},
                prev_state={"w": 0.0})
    dumps = [f for f in os.listdir(tmp_path) if f.startswith("postmortem_")]
    assert len(dumps) == 1
    rows = [json.loads(ln) for ln in open(tmp_path / dumps[0])]
    assert rows[0]["reason"] == "guard-raise"
    assert rows[0]["error"] == "NonFiniteError"
    assert any(r["kind"] == "guard.trip" for r in rows[1:])


def test_static_analysis_error_dumps_postmortem(tmp_path):
    from tensorframes_tpu.analysis.diagnostics import (
        Diagnostic, DiagnosticReport,
    )
    from tensorframes_tpu.validation import StaticAnalysisError

    flight.set_spool_dir(str(tmp_path))
    report = DiagnosticReport(
        [Diagnostic("TFG104", "error", "donated input aliased")],
        subject="prog",
    )
    with pytest.raises(StaticAnalysisError):
        report.raise_on_errors()
    dumps = [f for f in os.listdir(tmp_path) if f.startswith("postmortem_")]
    assert len(dumps) == 1
    rows = [json.loads(ln) for ln in open(tmp_path / dumps[0])]
    assert rows[0]["reason"] == "static-analysis"
    sa = [r for r in rows[1:] if r["kind"] == "static_analysis.error"]
    assert sa and sa[0]["codes"] == "TFG104"


# ---------------------------------------------------------------------------
# crash black box (subprocess)
# ---------------------------------------------------------------------------

_CRASHER = """
import os, sys, time
import numpy as np
import tensorframes_tpu as tfs
from tensorframes_tpu.resilience import faults

mode = sys.argv[1]  # "uncaught" | "spin"
df = tfs.frame_from_arrays({"x": np.arange(16.0)}, num_blocks=2)
program = tfs.compile_program(lambda x: {"y": x * 3.0}, df)
tfs.map_blocks(program, df).collect()   # healthy dispatches first
print("READY", flush=True)
if mode == "uncaught":
    # a fault-injected dispatch failure that nobody catches: the
    # excepthook must leave a postmortem naming the failing dispatch
    with faults.inject("executor.run_block", RuntimeError("injected loss")):
        tfs.map_blocks(program, df).collect()
else:
    while True:  # spin dispatching until SIGKILL lands
        tfs.map_blocks(program, df).collect()
        time.sleep(0.01)
"""


def _spawn_crasher(flight_dir: str, mode: str):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["TFTPU_FLIGHT_DIR"] = flight_dir
    env["TFTPU_RUN_ID"] = "flighttest"
    return subprocess.Popen(
        [sys.executable, "-c", _CRASHER, mode],
        env=env, cwd=_REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )


def test_uncaught_fault_injection_leaves_postmortem_with_dispatch(tmp_path):
    """ISSUE 6 acceptance: a fault-injected crash leaves a flight
    recorder dump containing the failing dispatch."""
    fdir = str(tmp_path / "flight")
    proc = _spawn_crasher(fdir, "uncaught")
    out, err = proc.communicate(timeout=300)
    assert proc.returncode != 0, f"crasher should have died\n{out}\n{err}"
    assert "READY" in out
    dumps = [f for f in os.listdir(fdir) if f.startswith("postmortem_")]
    assert len(dumps) == 1, (os.listdir(fdir), err)
    rows = [json.loads(ln) for ln in open(os.path.join(fdir, dumps[0]))]
    assert rows[0]["reason"] == "crash"
    assert rows[0]["run_id"] == "flighttest"
    assert rows[0]["error"] == "RuntimeError"
    kinds = [r["kind"] for r in rows[1:]]
    assert "dispatch" in kinds            # the healthy history
    assert "fault.injected" in kinds
    errs = [r for r in rows[1:] if r["kind"] == "dispatch.error"]
    assert errs, "the failing dispatch must be in the black box"
    assert "injected loss" in errs[-1]["message"]


def test_kill9_leaves_recoverable_blackbox(tmp_path):
    """No Python runs at SIGKILL — the line-flushed spool must still
    hold the recent dispatches, and read_blackbox must tolerate a torn
    final line."""
    fdir = str(tmp_path / "flight")
    proc = _spawn_crasher(fdir, "spin")
    try:
        deadline = time.time() + 180
        spooled = []
        while time.time() < deadline:
            if os.path.isdir(fdir):
                spooled = [
                    f for f in os.listdir(fdir) if f.startswith("flight_")
                ]
                if spooled and any(
                    os.path.getsize(os.path.join(fdir, f)) > 500
                    for f in spooled
                ):
                    break
            if proc.poll() is not None:
                out, err = proc.communicate()
                raise AssertionError(
                    f"crasher exited early (rc={proc.returncode})\n"
                    f"stdout: {out}\nstderr: {err}"
                )
            time.sleep(0.02)
        assert spooled, "spool never materialized"
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
        assert proc.returncode == -signal.SIGKILL
    finally:
        if proc.poll() is None:  # pragma: no cover - cleanup on test bugs
            proc.kill()
    records = flight.read_blackbox(fdir)
    assert records, "black box came back empty"
    dispatches = [r for r in records if r["kind"] == "dispatch"]
    assert dispatches
    assert dispatches[-1]["entry"] == "block"
    assert dispatches[-1]["shapes"]["x"] == [8]
    # seq ordering survives reassembly
    seqs = [r["seq"] for r in records]
    assert seqs == sorted(seqs)


def test_spool_rotation_bounds_disk(tmp_path):
    rec = flight.FlightRecorder(capacity=10, spool_dir=str(tmp_path))
    for i in range(55):
        rec.record("tick", i=i)
    files = [f for f in os.listdir(tmp_path) if f.startswith("flight_")]
    assert len(files) == 2  # live segment + one rotated ".1"
    total_lines = sum(
        len(open(tmp_path / f).read().splitlines()) for f in files
    )
    assert total_lines <= 20  # 2 * capacity
