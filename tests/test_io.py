"""Data-loader tests: batch iteration semantics and the prefetch pipeline
(ordering, device placement, exception propagation)."""

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu import io as tfio


def _frame(n=20):
    return tfs.frame_from_arrays(
        {"x": np.arange(float(n)), "y": np.arange(n, dtype=np.int64)},
        num_blocks=3,
    )


def test_iterate_batches_covers_all_rows():
    batches = list(tfio.iterate_batches(_frame(20), batch_size=6))
    assert [len(b["x"]) for b in batches] == [6, 6, 6, 2]
    got = np.concatenate([b["x"] for b in batches])
    np.testing.assert_array_equal(np.sort(got), np.arange(20.0))


def test_iterate_batches_drop_remainder_and_shuffle():
    batches = list(
        tfio.iterate_batches(
            _frame(20), batch_size=6, shuffle=True, seed=1, drop_remainder=True
        )
    )
    assert [len(b["x"]) for b in batches] == [6, 6, 6]
    flat = np.concatenate([b["x"] for b in batches])
    assert not np.array_equal(flat, np.arange(18.0))  # actually shuffled
    # x and y stay row-aligned through the shuffle
    for b in batches:
        np.testing.assert_array_equal(b["x"].astype(np.int64), b["y"])


def test_iterate_batches_column_subset():
    batches = list(tfio.iterate_batches(_frame(8), columns=["y"], batch_size=4))
    assert all(set(b) == {"y"} for b in batches)


def test_prefetch_preserves_order_and_places_on_device():
    import jax

    frame = _frame(20)
    out = list(
        tfio.prefetch_to_device(
            tfio.iterate_batches(frame, batch_size=5), size=2
        )
    )
    assert len(out) == 4
    for b in out:
        assert isinstance(b["x"], jax.Array)
    got = np.concatenate([np.asarray(b["x"]) for b in out])
    np.testing.assert_array_equal(got, np.arange(20.0))


def test_prefetch_propagates_source_exception():
    def bad_source():
        yield {"x": np.zeros(2)}
        raise RuntimeError("source broke")

    it = tfio.prefetch_to_device(bad_source(), size=2)
    next(it)
    with pytest.raises(RuntimeError, match="source broke"):
        next(it)


def test_prefetch_with_sharding():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tensorframes_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": 8})
    sh = NamedSharding(mesh, P("dp"))
    frame = _frame(16)
    out = list(
        tfio.prefetch_to_device(
            tfio.iterate_batches(frame, columns=["x"], batch_size=8),
            sharding=sh,
        )
    )
    assert len(out) == 2
    assert out[0]["x"].sharding == sh


def test_prefetch_early_stop_releases_worker():
    import threading
    import time

    frame = _frame(40)
    it = tfio.prefetch_to_device(
        tfio.iterate_batches(frame, batch_size=2), size=2
    )
    next(it)
    it.close()  # consumer bails early
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if not any(t.name == "tfs-prefetch" and t.is_alive()
                   for t in threading.enumerate()):
            break
        time.sleep(0.05)
    assert not any(
        t.name == "tfs-prefetch" and t.is_alive() for t in threading.enumerate()
    ), "prefetch worker still alive after consumer close()"


def test_iterate_batches_rejects_empty_selection():
    with pytest.raises(ValueError, match="no columns"):
        list(tfio.iterate_batches(_frame(4), columns=[]))


def test_prefetch_source_raises_mid_stream():
    """Regression: a batch iterator failing MID-stream must surface to
    the consumer promptly — every staged good batch is delivered, then
    the very next ``__next__`` re-raises instead of draining silently or
    hanging."""
    def bad_source():
        for i in range(3):
            yield {"x": np.full(2, float(i))}
        raise RuntimeError("disk died at batch 3")

    it = tfio.prefetch_to_device(bad_source(), size=8)
    got = []
    with pytest.raises(RuntimeError, match="disk died"):
        for b in it:
            got.append(float(np.asarray(b["x"])[0]))
    assert got == [0.0, 1.0, 2.0]  # nothing lost, nothing extra


def test_prefetch_immediate_source_failure():
    def dead_source():
        raise OSError("no such dataset")
        yield  # pragma: no cover

    it = tfio.prefetch_to_device(dead_source(), size=2)
    with pytest.raises(OSError, match="no such dataset"):
        next(it)


def test_prefetch_shutdown_join_is_bounded():
    """close() must return promptly even while the worker is between
    batches (bounded join, not an unbounded wait)."""
    import time

    def slow_source():
        for i in range(100):
            time.sleep(0.05)
            yield {"x": np.zeros(2)}

    it = tfio.prefetch_to_device(slow_source(), size=2, join_timeout=2.0)
    next(it)
    t0 = time.time()
    it.close()
    assert time.time() - t0 < 3.0


# ---------------------------------------------------------------------------
# Frame persistence
# ---------------------------------------------------------------------------

def test_save_load_roundtrip_dense(tmp_path):
    rng = np.random.default_rng(0)
    d = {
        "x": rng.standard_normal(37).astype(np.float32),
        "m": rng.standard_normal((37, 3)).astype(np.float64),
        "i": rng.integers(0, 100, 37),
    }
    fr = tfs.frame_from_arrays(d, num_blocks=3)
    fr.save(str(tmp_path / "fr"))
    back = tfs.load_frame(str(tmp_path / "fr"), num_blocks=5)
    assert back.num_blocks == 5
    assert back.num_rows == 37
    for c in d:
        assert back.schema[c].dtype == fr.schema[c].dtype
        np.testing.assert_array_equal(back.column_values(c), d[c])


def test_save_load_roundtrip_host_and_ragged(tmp_path):
    rows = [
        {"s": "alpha", "v": [1.0, 2.0]},
        {"s": "beta", "v": [3.0]},          # ragged
        {"s": "gamma", "v": [4.0, 5.0, 6.0]},
    ]
    fr = tfs.frame_from_rows(rows, num_blocks=2)
    fr.save(str(tmp_path / "fr"))
    back = tfs.load_frame(str(tmp_path / "fr"))
    got = back.collect()
    assert [r["s"] for r in got] == ["alpha", "beta", "gamma"]
    assert [list(np.asarray(r["v"]).ravel()) for r in got] == [
        [1.0, 2.0], [3.0], [4.0, 5.0, 6.0]
    ]


def test_save_load_device_frame(tmp_path):
    d = {"x": np.arange(64, dtype=np.float32)}
    fr = tfs.frame_from_arrays(d).to_device()
    fr.save(str(tmp_path / "fr"))
    back = tfs.load_frame(str(tmp_path / "fr"))
    np.testing.assert_array_equal(back.column_values("x"), d["x"])
    # loaded frames run through the verbs like any other
    out = tfs.map_blocks(lambda x: {"y": x * 2.0}, back)
    assert float(out.column_values("y").sum()) == float(d["x"].sum() * 2)


def test_load_rejects_future_format(tmp_path):
    import json

    fr = tfs.frame_from_arrays({"x": np.arange(4, dtype=np.float32)})
    fr.save(str(tmp_path / "fr"))
    man = tmp_path / "fr" / "frame.json"
    m = json.loads(man.read_text())
    m["format_version"] = 99
    man.write_text(json.dumps(m))
    with pytest.raises(ValueError, match="format_version"):
        tfs.load_frame(str(tmp_path / "fr"))


def test_save_load_bf16_and_hazard_names(tmp_path):
    """bfloat16 survives the npz round-trip (raw-bytes storage) and column
    names colliding with savez parameters ('file') are safe."""
    import ml_dtypes

    d = {
        "file": np.arange(8, dtype=np.float32),
        "b": np.arange(8, dtype=ml_dtypes.bfloat16),
    }
    fr = tfs.frame_from_arrays(dict(d))
    fr.save(str(tmp_path / "fr"))
    back = tfs.load_frame(str(tmp_path / "fr"))
    got = back.column_values("b")
    assert got.dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(got.astype(np.float32), np.arange(8, dtype=np.float32))
    np.testing.assert_array_equal(back.column_values("file"), d["file"])


def test_resave_over_existing(tmp_path):
    """Atomic swap: re-saving a different frame over an existing directory
    fully replaces it (no stale columns from the first save)."""
    p = str(tmp_path / "fr")
    tfs.frame_from_rows([{"s": "host", "x": 1.0}]).save(p)  # has host pickle
    tfs.frame_from_arrays({"y": np.arange(6, dtype=np.float32)}).save(p)
    back = tfs.load_frame(p)
    assert back.columns == ["y"]
    import os
    assert not os.path.exists(os.path.join(p, "host_columns.pkl"))
    np.testing.assert_array_equal(back.column_values("y"), np.arange(6, dtype=np.float32))


def test_save_trailing_slash(tmp_path):
    p = str(tmp_path / "fr")
    tfs.frame_from_arrays({"x": np.arange(4, dtype=np.float32)}).save(p)
    # re-save through a trailing-slash alias must not destroy the frame
    tfs.frame_from_arrays({"x": np.arange(5, dtype=np.float32)}).save(p + "/")
    back = tfs.load_frame(p)
    np.testing.assert_array_equal(back.column_values("x"), np.arange(5, dtype=np.float32))


def test_sharded_save_load_single_process(tmp_path):
    """save_frame_sharded/load_frame_sharded degrade to one part on a
    single process and round-trip through the verbs."""
    x = np.arange(64, dtype=np.float32)
    fr = tfs.frame_from_arrays({"x": x}).to_device()
    part = tfs.io.save_frame_sharded(fr, str(tmp_path / "sf"))
    assert part.endswith("part-0")
    back = tfs.io.load_frame_sharded(str(tmp_path / "sf"))
    assert back.is_sharded
    np.testing.assert_array_equal(np.asarray(back.column_values("x")), x)
    tot = tfs.reduce_blocks(lambda x_input: {"x": x_input.sum(axis=0)}, back)
    assert float(tot) == float(x.sum())


# ---------------------------------------------------------------------------
# CSV ingestion
# ---------------------------------------------------------------------------

def _write(p, text):
    p.write_text(text)
    return str(p)


def test_read_csv_native_types(tmp_path):
    path = _write(
        tmp_path / "t.csv",
        "id,score,name\n1,0.5,alpha\n2,1.25,beta\n3,,gamma\n",
    )
    fr = tfs.read_csv(path)
    assert fr.schema["id"].dtype.name == "int64"
    assert fr.schema["score"].dtype.name == "float64"
    np.testing.assert_array_equal(fr.column_values("id"), [1, 2, 3])
    sc = fr.column_values("score")
    assert sc[0] == 0.5 and sc[1] == 1.25 and np.isnan(sc[2])
    assert [r["name"] for r in fr.collect()] == ["alpha", "beta", "gamma"]
    # and the frame runs through the verbs
    out = tfs.map_blocks(lambda id: {"id2": id * 2}, fr)
    np.testing.assert_array_equal(out.column_values("id2"), [2, 4, 6])


def test_read_csv_quoted_falls_back(tmp_path):
    path = _write(
        tmp_path / "q.csv",
        'k,txt\n1,"hello, world"\n2,"line"\n',
    )
    fr = tfs.read_csv(path)
    np.testing.assert_array_equal(fr.column_values("k"), [1, 2])
    assert [r["txt"] for r in fr.collect()] == ["hello, world", "line"]


def test_read_csv_native_matches_python(tmp_path):
    from tensorframes_tpu import native

    rng = np.random.default_rng(0)
    n = 500
    lines = ["a,b,c"]
    for i in range(n):
        lines.append(f"{rng.integers(-5, 5)},{rng.standard_normal():.6f},s{i}")
    path = _write(tmp_path / "p.csv", "\n".join(lines) + "\n")

    fr_native = tfs.read_csv(path)
    import unittest.mock as mock

    with mock.patch.object(native, "available", lambda: False):
        fr_python = tfs.read_csv(path)
    for col in ("a", "b"):
        np.testing.assert_allclose(
            fr_native.column_values(col), fr_python.column_values(col)
        )
    assert [r["c"] for r in fr_native.collect()] == [
        r["c"] for r in fr_python.collect()
    ]


def test_read_csv_dtype_override_and_errors(tmp_path):
    path = _write(tmp_path / "o.csv", "a\n1\n2\n")
    fr = tfs.read_csv(path, dtypes={"a": "float64"})
    assert fr.schema["a"].dtype.name == "float64"
    bad = _write(tmp_path / "bad.csv", "a\n1\nnope\n")
    with pytest.raises(ValueError):
        tfs.read_csv(bad, dtypes={"a": "int64"})


def test_read_csv_empty_and_crlf(tmp_path):
    empty = _write(tmp_path / "e.csv", "x,y\n")
    fr = tfs.read_csv(empty)
    assert fr.num_rows == 0 and fr.columns == ["x", "y"]
    crlf = _write(tmp_path / "c.csv", "x,s\r\n7,hi\r\n8,yo\r\n")
    fr2 = tfs.read_csv(crlf)
    np.testing.assert_array_equal(fr2.column_values("x"), [7, 8])
    assert [r["s"] for r in fr2.collect()] == ["hi", "yo"]


def test_read_csv_malformed_and_edge_rows(tmp_path):
    # extra fields beyond the header are dropped (no phantom rows)
    p = _write(tmp_path / "x.csv", "a,b\n1.0,2.0,3.0,4.0\n5.0,6.0\n")
    fr = tfs.read_csv(p)
    assert fr.num_rows == 2
    np.testing.assert_array_equal(fr.column_values("a"), [1.0, 5.0])
    np.testing.assert_array_equal(fr.column_values("b"), [2.0, 6.0])
    # int64 overflow errors instead of silently clamping
    p2 = _write(tmp_path / "o.csv", "a\n99999999999999999999\n")
    with pytest.raises((OverflowError, ValueError)):
        tfs.read_csv(p2, dtypes={"a": "int64"})
    # CRLF blank lines are skipped like the csv-module path
    p3 = _write(tmp_path / "b.csv", "a,s\r\n1,x\r\n\r\n2,y\r\n")
    fr3 = tfs.read_csv(p3)
    assert fr3.num_rows == 2
    np.testing.assert_array_equal(fr3.column_values("a"), [1, 2])


def test_read_csv_header_only_with_override(tmp_path):
    p = _write(tmp_path / "h.csv", "id,name\n")
    fr = tfs.read_csv(p, dtypes={"name": "string", "id": "int64"})
    assert fr.num_rows == 0
    assert fr.schema["id"].dtype.name == "int64"
    assert fr.schema["name"].dtype.name == "string"


def test_write_csv_roundtrip(tmp_path):
    d = {
        "i": np.arange(5),
        "f": np.linspace(0, 1, 5),
        "s": [f"n{i}" for i in range(5)],
    }
    fr = tfs.frame_from_arrays(d)
    path = str(tmp_path / "out.csv")
    tfs.write_csv(fr, path)
    back = tfs.read_csv(path)
    np.testing.assert_array_equal(back.column_values("i"), d["i"])
    np.testing.assert_allclose(back.column_values("f"), d["f"])
    assert [r["s"] for r in back.collect()] == d["s"]
    with pytest.raises(ValueError, match="scalar columns"):
        tfs.write_csv(
            tfs.frame_from_arrays({"m": np.ones((3, 2))}), str(tmp_path / "m.csv")
        )


def test_read_csv_quoted_header_and_inference(tmp_path):
    """Quoted headers/samples go through real csv parsing (not naive
    split), so quoted fields with delimiters don't corrupt names/types."""
    p = _write(
        tmp_path / "qh.csv",
        'name,score\n"Doe, Jane",5\n"Roe, Rich",7\n',
    )
    fr = tfs.read_csv(p)
    assert fr.columns == ["name", "score"]
    assert fr.schema["score"].dtype.name == "int64"
    np.testing.assert_array_equal(fr.column_values("score"), [5, 7])
    assert [r["name"] for r in fr.collect()] == ["Doe, Jane", "Roe, Rich"]


def test_read_csv_bad_dtype_override_raises(tmp_path):
    p = _write(tmp_path / "d.csv", "a\n1\n")
    with pytest.raises(ValueError, match="unsupported dtype"):
        tfs.read_csv(p, dtypes={"a": "int32"})
