"""Data-loader tests: batch iteration semantics and the prefetch pipeline
(ordering, device placement, exception propagation)."""

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu import io as tfio


def _frame(n=20):
    return tfs.frame_from_arrays(
        {"x": np.arange(float(n)), "y": np.arange(n, dtype=np.int64)},
        num_blocks=3,
    )


def test_iterate_batches_covers_all_rows():
    batches = list(tfio.iterate_batches(_frame(20), batch_size=6))
    assert [len(b["x"]) for b in batches] == [6, 6, 6, 2]
    got = np.concatenate([b["x"] for b in batches])
    np.testing.assert_array_equal(np.sort(got), np.arange(20.0))


def test_iterate_batches_drop_remainder_and_shuffle():
    batches = list(
        tfio.iterate_batches(
            _frame(20), batch_size=6, shuffle=True, seed=1, drop_remainder=True
        )
    )
    assert [len(b["x"]) for b in batches] == [6, 6, 6]
    flat = np.concatenate([b["x"] for b in batches])
    assert not np.array_equal(flat, np.arange(18.0))  # actually shuffled
    # x and y stay row-aligned through the shuffle
    for b in batches:
        np.testing.assert_array_equal(b["x"].astype(np.int64), b["y"])


def test_iterate_batches_column_subset():
    batches = list(tfio.iterate_batches(_frame(8), columns=["y"], batch_size=4))
    assert all(set(b) == {"y"} for b in batches)


def test_prefetch_preserves_order_and_places_on_device():
    import jax

    frame = _frame(20)
    out = list(
        tfio.prefetch_to_device(
            tfio.iterate_batches(frame, batch_size=5), size=2
        )
    )
    assert len(out) == 4
    for b in out:
        assert isinstance(b["x"], jax.Array)
    got = np.concatenate([np.asarray(b["x"]) for b in out])
    np.testing.assert_array_equal(got, np.arange(20.0))


def test_prefetch_propagates_source_exception():
    def bad_source():
        yield {"x": np.zeros(2)}
        raise RuntimeError("source broke")

    it = tfio.prefetch_to_device(bad_source(), size=2)
    next(it)
    with pytest.raises(RuntimeError, match="source broke"):
        next(it)


def test_prefetch_with_sharding():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tensorframes_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": 8})
    sh = NamedSharding(mesh, P("dp"))
    frame = _frame(16)
    out = list(
        tfio.prefetch_to_device(
            tfio.iterate_batches(frame, columns=["x"], batch_size=8),
            sharding=sh,
        )
    )
    assert len(out) == 2
    assert out[0]["x"].sharding == sh


def test_prefetch_early_stop_releases_worker():
    import threading
    import time

    frame = _frame(40)
    it = tfio.prefetch_to_device(
        tfio.iterate_batches(frame, batch_size=2), size=2
    )
    next(it)
    it.close()  # consumer bails early
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if not any(t.name == "tfs-prefetch" and t.is_alive()
                   for t in threading.enumerate()):
            break
        time.sleep(0.05)
    assert not any(
        t.name == "tfs-prefetch" and t.is_alive() for t in threading.enumerate()
    ), "prefetch worker still alive after consumer close()"


def test_iterate_batches_rejects_empty_selection():
    with pytest.raises(ValueError, match="no columns"):
        list(tfio.iterate_batches(_frame(4), columns=[]))
