"""Driver-contract tests: entry() compiles and runs; dryrun_multichip
builds a real dp/tp/sp mesh and executes one sharded training step."""

import sys

import numpy as np
import pytest

sys.path.insert(0, "/root/repo")

import __graft_entry__ as graft
from tensorframes_tpu.parallel import device_count


def test_entry_jittable():
    import jax

    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    out = np.asarray(out)
    assert out.ndim == 2 and np.isfinite(out).all()


@pytest.mark.skipif(device_count() < 8, reason="needs 8 virtual devices")
def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


def test_dryrun_multichip_1():
    graft.dryrun_multichip(1)
