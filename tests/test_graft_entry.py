"""Driver-contract tests: entry() compiles and runs; dryrun_multichip
builds a real dp/tp/sp mesh and executes one sharded training step."""

import sys

import numpy as np
import pytest

sys.path.insert(0, "/root/repo")

import __graft_entry__ as graft
from tensorframes_tpu.parallel import device_count


def test_entry_jittable():
    import jax

    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    out = np.asarray(out)
    assert out.ndim == 2 and np.isfinite(out).all()


@pytest.mark.skipif(device_count() < 8, reason="needs 8 virtual devices")
def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


def test_dryrun_multichip_1():
    graft.dryrun_multichip(1)


def test_dryrun_multichip_16_subprocess():
    """16 virtual devices (VERDICT r2 #9): the conftest pins this process
    to 8, so the 16-way case runs in a fresh subprocess the way the
    driver invokes it."""
    import os
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(16)"],
        cwd=repo_root,
        env=env,
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    assert "dryrun frozen-graph OK" in r.stdout
