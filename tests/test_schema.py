"""ColumnInfo / Schema tests (≙ ColumnInformation + DataFrameInfo)."""

import pytest

from tensorframes_tpu import dtypes as dt
from tensorframes_tpu.schema import ColumnInfo, Schema
from tensorframes_tpu.shape import Shape, Unknown


def _col(name, dtype=dt.float64, dims=(Unknown,)):
    return ColumnInfo(name, dtype, Shape(dims))


def test_cell_vs_block_shape():
    c = _col("x", dims=(Unknown, 2))
    assert c.block_shape.dims == (Unknown, 2)
    assert c.cell_shape.dims == (2,)


def test_block_shape_needs_lead_dim():
    with pytest.raises(ValueError):
        ColumnInfo("x", dt.float64, Shape.empty())


def test_host_columns_scalar_only():
    # ≙ datatypes.scala:577-581 single-scalar strings
    ColumnInfo("s", dt.string, Shape((Unknown,)))
    with pytest.raises(ValueError):
        ColumnInfo("s", dt.string, Shape((Unknown, 3)))


def test_merge_dtype_conflict():
    a = _col("x", dt.float64)
    b = _col("x", dt.float32)
    with pytest.raises(dt.UnsupportedTypeError):
        a.merge(b)


def test_merge_shapes():
    a = _col("x", dims=(5, 2))
    b = _col("x", dims=(7, 2))
    assert a.merge(b).block_shape.dims == (Unknown, 2)


def test_schema_lookup_and_errors():
    s = Schema([_col("a"), _col("b")])
    assert s.names == ["a", "b"]
    assert "a" in s
    with pytest.raises(KeyError) as e:
        s["zzz"]
    assert "a" in str(e.value)  # error enumerates available columns
    with pytest.raises(ValueError):
        Schema([_col("a"), _col("a")])


def test_schema_transforms():
    s = Schema([_col("a"), _col("b")])
    assert s.select(["b"]).names == ["b"]
    s2 = s.append([_col("c")])
    assert s2.names == ["a", "b", "c"]
    s3 = s.replace(_col("a", dt.int32))
    assert s3["a"].dtype is dt.int32


def test_explain_rendering():
    s = Schema([_col("y", dims=(Unknown, 2))])
    text = s.explain()
    assert "root" in text
    assert "y" in text and "[?,2]" in text
