"""The x64 demotion option (VERDICT r1 next-step 2): reference-parity
Double/Long columns can demote to f32/i32 at the device boundary —
``configure(demote_x64_on_tpu=True)`` applies on real TPU backends,
``"always"`` forces it anywhere (this suite runs it on the CPU mesh).
Accounting surfaces in ``explain(detailed=True)``."""

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu import dtypes as dt
from tensorframes_tpu.config import configure, get_config


@pytest.fixture
def demoted():
    old = get_config().demote_x64_on_tpu
    configure(demote_x64_on_tpu="always")
    yield
    configure(demote_x64_on_tpu=old)


def test_demotion_inactive_by_default():
    assert get_config().demote_x64_on_tpu is False
    assert not dt.demotion_active()


def test_ragged_map_rows_demotes(demoted):
    """The grouped ragged dispatch honors the demoted input spec (it
    bypasses gather_feeds, so it casts explicitly)."""
    rows = [{"v": list(np.arange(3 + (i % 2), dtype=np.float64))}
            for i in range(6)]
    fr = tfs.frame_from_rows(rows, num_blocks=1)
    out = tfs.map_rows(lambda v: {"s": v.sum()}, fr)
    assert out.schema["s"].dtype is dt.float32
    got = out.blocks()[0]["s"]
    assert got.dtype == np.float32
    want = [float(np.arange(3 + (i % 2)).sum()) for i in range(6)]
    np.testing.assert_allclose(got, want)


def test_map_blocks_outputs_f32_under_demotion(demoted):
    df = tfs.frame_from_arrays({"x": np.arange(10, dtype=np.float64)})
    out = tfs.map_blocks(lambda x: {"z": x * 2.0 + 1.0}, df)
    assert out.schema["z"].dtype is dt.float32
    vals = out.column_values("z")
    assert vals.dtype == np.float32
    np.testing.assert_allclose(vals, np.arange(10) * 2.0 + 1.0, rtol=1e-6)
    # the input column itself is untouched on the host
    assert out.schema["x"].dtype is dt.float64


def test_dsl_program_demotes(demoted):
    df = tfs.frame_from_rows([{"x": float(i)} for i in range(8)])
    with tfs.with_graph():
        x = tfs.block(df, "x")
        out = tfs.map_blocks(tfs.add(x, 3, name="z"), df)
    assert out.schema["z"].dtype is dt.float32
    assert [r["z"] for r in out.collect()] == [float(i) + 3 for i in range(8)]


def test_to_device_demotes_storage_and_schema(demoted):
    df = tfs.frame_from_arrays(
        {
            "k": np.arange(4000, dtype=np.int64) % 7,
            "x": np.arange(4000, dtype=np.float64),
        }
    ).to_device()
    assert df.schema["x"].dtype is dt.float32
    assert df.schema["k"].dtype is dt.int32
    main = df.blocks()[0]
    assert main["x"].dtype == np.float32
    # verbs compose in the 32-bit world, incl. the device aggregate plan
    with tfs.with_graph():
        x_in = tfs.block(df, "x", tf_name="x_input")
        res = tfs.aggregate(
            tfs.reduce_sum(x_in, axis=0, name="x"), df.group_by("k")
        ).collect()
    want = {}
    for i in range(4000):
        want[i % 7] = want.get(i % 7, 0.0) + float(i)
    got = {r["k"]: r["x"] for r in res}
    assert set(got) == set(want)
    for k in want:
        assert got[k] == pytest.approx(want[k], rel=1e-5)


def test_reduce_rows_under_demotion(demoted):
    df = tfs.frame_from_arrays({"x": np.arange(100, dtype=np.float64)})
    got = tfs.reduce_rows(lambda x_1, x_2: {"x": x_1 + x_2}, df)
    assert float(got) == pytest.approx(4950.0)


def test_explain_accounts_for_demotion(demoted):
    df = tfs.frame_from_arrays({"x": np.arange(4, dtype=np.float64)})
    text = tfs.explain(df, detailed=True)
    assert "x64 demotion active" in text
    assert "x" in text


def test_no_demotion_when_disabled():
    assert get_config().demote_x64_on_tpu is False
    df = tfs.frame_from_arrays({"x": np.arange(10, dtype=np.float64)})
    out = tfs.map_blocks(lambda x: {"z": x * 2.0}, df)
    assert out.schema["z"].dtype is dt.float64


def test_aggregate_and_reduce_store_demoted_dtypes(demoted):
    """The manual-feed verb paths (aggregate value columns, reduce_rows
    and reduce_blocks feeds) honor the demotion boundary: stored blocks
    match the 32-bit schema and reductions execute in 32-bit."""
    df = tfs.frame_from_arrays(
        {
            "k": np.arange(100, dtype=np.int64) % 4,
            "x": np.arange(100, dtype=np.float64),
        }
    )
    agg = tfs.aggregate(
        lambda x_input: {"x": x_input.sum(0)}, df.group_by("k")
    )
    assert agg.schema["x"].dtype.name == "float32"
    assert np.asarray(agg.blocks()[0]["x"]).dtype == np.float32
    # vector cells: reduce results keep array form, exposing the dtype
    # (scalar reduces unwrap to python floats by contract)
    vdf = tfs.frame_from_arrays(
        {"x": np.arange(40, dtype=np.float64).reshape(20, 2)}
    )
    r1 = tfs.reduce_blocks(lambda x_input: {"x": x_input.sum(axis=0)}, vdf)
    assert np.asarray(r1).dtype == np.float32
    r2 = tfs.reduce_rows(lambda x_1, x_2: {"x": x_1 + x_2}, vdf)
    assert np.asarray(r2).dtype == np.float32
