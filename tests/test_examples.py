"""Example-workload tests (≙ the reference's snippet demos, SURVEY.md §2.4,
here exercised as real tested code): k-means, geometric/harmonic means,
and batch image inference."""

import sys
import os

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import tensorframes_tpu as tfs  # noqa: E402
from examples import geom_mean, kmeans  # noqa: E402


def test_kmeans_recovers_separated_clusters():
    rng = np.random.default_rng(0)
    true = np.array([[0.0, 0.0], [8.0, 8.0], [-8.0, 8.0]], np.float32)
    pts = np.concatenate(
        [t + rng.standard_normal((60, 2)).astype(np.float32) * 0.3 for t in true]
    )
    rng.shuffle(pts)
    frame = tfs.frame_from_arrays({"features": pts}, num_blocks=3)
    centers, iters = kmeans.kmeans(frame, k=3, num_iters=25, seed=1)
    got = np.asarray(sorted(centers.tolist()))
    want = np.asarray(sorted(true.tolist()))
    np.testing.assert_allclose(got, want, atol=0.3)
    assert iters <= 25


def test_kmeans_step_moves_centers_toward_data():
    pts = np.array([[0.0, 0.0], [0.2, 0.0], [10.0, 10.0], [10.2, 10.0]], np.float32)
    frame = tfs.frame_from_arrays({"features": pts}, num_blocks=2)
    centers = np.array([[1.0, 1.0], [9.0, 9.0]], np.float32)
    new = kmeans.kmeans_step(frame, centers)
    np.testing.assert_allclose(new[0], [0.1, 0.0], atol=1e-5)
    np.testing.assert_allclose(new[1], [10.1, 10.0], atol=1e-5)


def test_geometric_mean_by_key():
    frame = tfs.frame_from_arrays(
        {"key": np.array([1, 1, 1, 2, 2]), "x": np.array([1.0, 2.0, 4.0, 3.0, 27.0])}
    )
    got = geom_mean.geometric_mean_by_key(frame, "key", "x")
    assert got[1] == pytest.approx(2.0)       # (1·2·4)^(1/3)
    assert got[2] == pytest.approx(9.0)       # (3·27)^(1/2)


def test_harmonic_mean_by_key():
    frame = tfs.frame_from_arrays(
        {"key": np.array([1, 1], dtype=np.int64), "x": np.array([1.0, 3.0])}
    )
    got = geom_mean.harmonic_mean_by_key(frame, "key", "x")
    assert got[1] == pytest.approx(1.5)       # 2 / (1 + 1/3)


def test_image_inference_example():
    from examples import image_inference
    from tensorframes_tpu.models import inception as inc

    cfg = inc.tiny()
    params = inc.init_params(cfg, seed=0)
    images = inc.synthetic_images(cfg, 4, seed=0)
    frame = tfs.frame_from_arrays({"pix": images}, num_blocks=2)
    scored = image_inference.score_images(
        frame, cfg, params, image_col="pix", to_device=False
    )
    rows = scored.collect()
    assert len(rows) == 4
    assert all(0 <= r["label"] < cfg.num_classes for r in rows)
    assert all(abs(float(np.sum(r["scores"])) - 1.0) < 1e-4 for r in rows)


def test_text_generation_example():
    from examples import text_generation as tg
    from tensorframes_tpu.models import generation as gen
    from tensorframes_tpu.models import transformer as tr

    cfg = gen.gpt_tiny()
    params = tr.init_params(cfg, seed=0)
    prompts = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (4, 6)
    ).astype(np.int32)
    frame = tfs.frame_from_arrays(
        {"p": prompts, "doc_id": np.arange(4)}, num_blocks=2
    )
    out = tg.generate_over_frame(frame, cfg, params, 5, prompt_col="p")
    rows = out.collect()
    assert len(rows) == 4
    assert all(len(r["generated"]) == 5 for r in rows)
    assert sorted(r["doc_id"] for r in rows) == [0, 1, 2, 3]
    # matches direct generation on the same rows
    want = np.asarray(gen.generate(cfg, params, prompts[:2], 5))
    np.testing.assert_array_equal(
        np.stack([rows[0]["generated"], rows[1]["generated"]]), want
    )


def test_image_inference_int8_example():
    from examples import image_inference
    from tensorframes_tpu.models import inception as inc

    cfg = inc.tiny()
    params = inc.init_params(cfg, seed=0)
    images = inc.synthetic_images(cfg, 4, seed=0)
    frame = tfs.frame_from_arrays({"images": images}, num_blocks=2)
    out = image_inference.score_images_int8(frame, cfg, params, to_device=False)
    rows = out.collect()
    assert len(rows) == 4
    scores = np.stack([r["scores"] for r in rows])
    np.testing.assert_allclose(scores.sum(axis=1), 1.0, atol=1e-3)


def test_multihost_demo_end_to_end():
    """Run the demo launcher for real: two OS processes rendezvous and
    print the same cross-process total."""
    import subprocess

    r = subprocess.run(
        [sys.executable, "-m", "examples.multihost_demo"],
        capture_output=True,
        text=True,
        timeout=150,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert r.stdout.count("total(w)=824.0") == 2, r.stdout


def test_train_logreg_example(tmp_path):
    from examples import train_logreg
    from tensorframes_tpu.models import logreg

    x, y = logreg.make_synthetic_mnist(256, seed=0)
    frame = tfs.frame_from_arrays({"features": x, "label_true": y})
    params, losses = train_logreg.train(
        frame, num_steps=15, checkpoint_dir=str(tmp_path)
    )
    assert len(losses) == 15
    assert losses[-1] < losses[0]
    # resume: asking for 20 total runs only the remaining 5
    _, more = train_logreg.train(
        frame, num_steps=20, checkpoint_dir=str(tmp_path)
    )
    assert len(more) == 5


def test_foreign_graph_example():
    """examples/foreign_graph.py: a frozen TF GraphDef (the reference's
    own fixture when present, an inline byte-equivalent otherwise) scores
    a frame through map_blocks."""
    from examples import foreign_graph

    res = foreign_graph.run()
    assert res["inputs"] == ["z_1", "z_2"]
    assert res["rows"] == 10
    # sum of (a + 0.5) over a = 0..19  ->  190 + 10
    assert res["sum"] == 190.0 + 10.0
    # the inline builder must stay byte-faithful to the decoder
    prog = tfs.program_from_graphdef(
        tfs.parse_graphdef(foreign_graph._inline_add_graph()),
        fetches=["out"],
    )
    assert prog.input_names == ["z_1", "z_2"]


def test_relational_pipeline_example():
    """filter → join → aggregate → sort, cross-checked against a plain
    numpy/pandas-free reimplementation."""
    from examples import relational_pipeline as rp

    out = rp.run(n_users=20, n_events=500, seed=3)
    assert len(out["top"]) == 3
    # scores strictly ordered descending, all positive totals exist
    scores = [s for _, s in out["top"]]
    assert scores == sorted(scores, reverse=True)

    # golden: recompute with raw numpy from the SAME data arrays (the
    # pipeline is under test, not the example's RNG stream)
    ctry, uid, score = rp.make_data(20, 500, 3)
    keep = score >= 0.5
    totals = {}
    for u, s in zip(uid[keep], score[keep]):
        c = ctry[int(u)]
        totals[c] = totals.get(c, 0.0) + float(s)
    want = sorted(totals.items(), key=lambda kv: -kv[1])[:3]
    for (gc, gs), (wc, ws) in zip(out["top"], want):
        assert gc == wc
        assert abs(gs - ws) < 0.1


def test_fault_injection_example(capsys):
    """All three resilience drills in the example recover (transient IO
    faults absorbed, poison batch skipped, torn checkpoint fallback)."""
    from examples import fault_injection

    fault_injection.main()
    out = capsys.readouterr().out
    assert "all drills recovered" in out
    assert "all absorbed" in out
    assert "final state finite = True" in out
    assert "fell back" in out
