"""Deterministic property sweep: the five verbs against a numpy oracle
across dtypes × cell shapes × block counts × residency — the shotgun
counterpart of the dtype-parity suite (≙ the reference's type-
parameterized CommonOperationsSuite replayed over a config grid)."""

import itertools

import jax.numpy as jnp
import numpy as np
import pytest

import tensorframes_tpu as tfs

DTYPES = [np.float32, np.float64, np.int32, np.int64]
CELLS = [(), (3,)]
BLOCKS = [1, 3, 8]


def _mk(rng, n, cell, dtype):
    if np.issubdtype(dtype, np.integer):
        return rng.integers(-50, 50, (n, *cell)).astype(dtype)
    return rng.standard_normal((n, *cell)).astype(dtype)


@pytest.mark.parametrize(
    "dtype,cell,nb",
    list(itertools.product(DTYPES, CELLS, BLOCKS)),
    ids=lambda v: str(getattr(v, "__name__", v)),
)
def test_map_and_reduce_sweep(dtype, cell, nb):
    rng = np.random.default_rng(hash((str(dtype), cell, nb)) % 2**32)
    n = 25
    x = _mk(rng, n, cell, dtype)
    frame = tfs.frame_from_arrays({"x": x}, num_blocks=nb)

    # map_blocks: elementwise double, dtype preserved
    out = tfs.map_blocks(lambda x: {"y": x + x}, frame)
    y = out.column_values("y")
    assert y.dtype == dtype
    np.testing.assert_array_equal(y, x + x)

    # float comparisons carry atol as well as rtol: XLA CPU's threaded
    # reduction split varies with machine load, so f32 summation order
    # (and hence last-ulp rounding) is not stable across runs — a
    # near-zero component sum would flake an rtol-only assert.
    # map_rows: per-row sum cell → scalar
    if cell:
        rsum = tfs.map_rows(lambda x: {"s": x.sum()}, frame)
        np.testing.assert_allclose(
            rsum.column_values("s"), x.sum(axis=1), rtol=1e-5, atol=1e-5
        )

    # reduce_blocks: total sum via the x_input contract. jnp.sum promotes
    # int32 → int64 under x64, and the fetch/input dtype contract (no
    # implicit casting, ≙ datatypes.scala:155-161) rightly rejects that —
    # reducers must state their accumulation dtype.
    tot = tfs.reduce_blocks(
        lambda x_input: {"x": x_input.sum(axis=0, dtype=x_input.dtype)}, frame
    )
    np.testing.assert_allclose(np.asarray(tot), x.sum(axis=0), rtol=1e-5, atol=1e-5)

    # reduce_rows: pairwise max
    mx = tfs.reduce_rows(
        lambda x_1, x_2: {"x": jnp.maximum(x_1, x_2)}, frame
    )
    np.testing.assert_array_equal(np.asarray(mx), x.max(axis=0))


@pytest.mark.parametrize("nb", BLOCKS)
def test_aggregate_sweep(nb):
    rng = np.random.default_rng(nb)
    n = 60
    k = rng.integers(0, 7, n)
    v = rng.standard_normal(n).astype(np.float32)
    frame = tfs.frame_from_arrays({"k": k, "v": v}, num_blocks=nb)
    agg = tfs.aggregate(
        lambda v_input: {"v": v_input.sum(axis=0)}, frame.group_by("k")
    )
    got = {r["k"]: r["v"] for r in agg.collect()}
    for key in np.unique(k):
        # abs slack too: group sums can land near zero, where rel-only
        # tolerance is ~1 ulp of the partial sums (see the comment in
        # test_map_and_reduce_sweep)
        assert got[int(key)] == pytest.approx(
            float(v[k == key].sum()), rel=1e-5, abs=1e-5
        )


def test_sweep_device_residency():
    """The same oracle holds for device frames (sharded over the mesh)."""
    rng = np.random.default_rng(9)
    x = rng.standard_normal(64).astype(np.float32)
    frame = tfs.frame_from_arrays({"x": x}).to_device()
    out = tfs.map_blocks(lambda x: {"y": x * 3.0}, frame)
    np.testing.assert_allclose(out.column_values("y"), x * 3.0, rtol=1e-6)
    tot = tfs.reduce_blocks(lambda x_input: {"x": x_input.sum(axis=0)}, frame)
    assert float(tot) == pytest.approx(float(x.sum()), rel=1e-5)


def test_bf16_map_and_reduce():
    """bfloat16 columns ride the verbs end to end (device dtype in the
    registry; numpy side via ml_dtypes)."""
    import ml_dtypes

    x = np.arange(32, dtype=np.float32).astype(ml_dtypes.bfloat16)
    frame = tfs.frame_from_arrays({"x": x})
    assert frame.schema["x"].dtype.name == "bfloat16"
    out = tfs.map_blocks(lambda x: {"y": x * 2}, frame)
    got = out.column_values("y")
    assert got.dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(
        got.astype(np.float32), (x * 2).astype(np.float32)
    )
    tot = tfs.reduce_blocks(
        lambda x_input: {"x": x_input.sum(axis=0, dtype=x_input.dtype)}, frame
    )
    assert float(np.asarray(tot).astype(np.float32)) == float(
        x.astype(np.float32).sum()
    )


@pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
@pytest.mark.parametrize("keydtype", [np.int32, np.int64, "str"])
def test_join_sweep_vs_pandas(how, keydtype):
    """Round 5: every join kind against pandas.merge over random keyed
    frames (multi-match expansion, unmatched rows on both sides,
    string and int keys). Row order is compared as a sorted multiset —
    pandas' outer ordering is version-dependent."""
    pd = pytest.importorskip("pandas")
    import zlib

    # crc, not hash(): python string hashing is salted per interpreter
    # run, and an unreproducible seed makes any failure unbisectable
    rng = np.random.default_rng(
        zlib.crc32(f"{how}-{keydtype}".encode())
    )

    def keys(n):
        raw = rng.integers(0, 8, n)
        if keydtype == "str":
            return [f"k{v}" for v in raw]
        return raw.astype(keydtype)

    nl, nr = 23, 17
    left = {"k": keys(nl), "v": rng.standard_normal(nl)}
    right = {"k": keys(nr), "w": rng.standard_normal(nr)}
    lf = tfs.frame_from_arrays(dict(left), num_blocks=2)
    rf = tfs.frame_from_arrays(dict(right), num_blocks=3)
    kwargs = {}
    if how != "inner":
        kwargs["fill_value"] = {"v": -9.0, "w": -7.0}
    got = lf.join(rf, on="k", how=how, **kwargs).collect()

    want = pd.merge(
        pd.DataFrame(left), pd.DataFrame(right), on="k", how=how,
    )
    if "v" in want:
        want["v"] = want["v"].fillna(-9.0)
    want["w"] = want["w"].fillna(-7.0)

    def norm(rows):
        return sorted(
            (str(r["k"]), round(float(r["v"]), 9), round(float(r["w"]), 9))
            for r in rows
        )

    assert len(got) == len(want)
    assert norm(got) == norm(want.to_dict("records"))
