"""VGG-16 family tests (≙ the reference's read_image.py VGG snippet):
forward shapes, preprocessing, top-k scoring through map_blocks."""

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.models import vgg


def test_tiny_forward_shape():
    cfg = vgg.tiny()
    params = vgg.init_params(cfg, seed=0)
    images = vgg.synthetic_images(cfg, 2, seed=0)
    logits = vgg.forward(cfg, params, images)
    assert logits.shape == (2, cfg.num_classes)
    assert np.isfinite(np.asarray(logits)).all()


def test_preprocess_central_crop_and_mean():
    rng = np.random.default_rng(0)
    images = rng.uniform(0, 255, (2, 40, 48, 3)).astype(np.float32)
    out = np.asarray(vgg.preprocess(images, 32))
    assert out.shape == (2, 32, 32, 3)
    # crop is central: offsets (4, 8); mean subtracted per channel
    expect = images[:, 4:36, 8:40, :] - np.asarray(vgg._RGB_MEAN, np.float32)
    np.testing.assert_allclose(out, expect, rtol=1e-6)
    with pytest.raises(ValueError, match="smaller than crop"):
        vgg.preprocess(images, 64)


def test_scoring_via_map_blocks_topk():
    cfg = vgg.tiny()
    params = vgg.init_params(cfg, seed=0)
    images = vgg.synthetic_images(cfg, 6, seed=1)
    df = tfs.frame_from_arrays({"images": images}, num_blocks=2)
    prog = vgg.scoring_program(cfg, params, top_k=3)
    out = tfs.map_blocks(lambda images: prog(images), df)
    scores = np.stack([r["scores"] for r in out.collect()])
    assert scores.shape == (6, cfg.num_classes)
    np.testing.assert_allclose(scores.sum(axis=1), 1.0, atol=1e-4)
    idx = np.stack([r["top_idx"] for r in out.collect()])
    val = np.stack([r["top_val"] for r in out.collect()])
    assert idx.shape == (6, 3) and val.shape == (6, 3)
    # top-1 of top_k equals argmax of the full score vector, values sorted
    np.testing.assert_array_equal(idx[:, 0], scores.argmax(axis=1))
    assert (np.diff(val, axis=1) <= 1e-7).all()


def test_param_naming_and_count():
    cfg = vgg.tiny()
    params = vgg.init_params(cfg, seed=0)
    # slim checkpoint naming: conv{stage}_{i}, fc6/fc7/fc8
    for name in ("conv1_1", "conv3_3", "conv5_3", "fc6", "fc7", "fc8"):
        assert name in params
    assert len([k for k in params if k.startswith("conv")]) == 13
    assert vgg.param_count(params) > 10_000
    # full-scale config matches the paper's channel plan
    full = vgg.vgg_16()
    assert full.ch(512) == 512 and full.fc == 4096


def test_batch_invariance():
    cfg = vgg.tiny()
    params = vgg.init_params(cfg, seed=2)
    images = vgg.synthetic_images(cfg, 3, seed=3)
    all_logits = np.asarray(vgg.forward(cfg, params, images))
    one = np.asarray(vgg.forward(cfg, params, images[1:2]))
    np.testing.assert_allclose(all_logits[1:2], one, rtol=2e-4, atol=2e-4)
