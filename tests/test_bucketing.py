"""Lead-dim bucketing: map_rows over arbitrary block sizes must keep the
jit cache O(log n) instead of compiling once per distinct row count
(SURVEY §7 hard-part 1; ≙ the reference's per-shape dynamic handling,
DataOps.scala:103-144, which re-ran analysis per block instead)."""

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu import ColumnInfo, Schema, Shape, Unknown
from tensorframes_tpu import dtypes as dt
from tensorframes_tpu.config import configure, get_config
from tensorframes_tpu.ops.executor import bucket_rows


@pytest.fixture
def bucket_cfg():
    old = (get_config().min_bucket, get_config().max_bucket_doublings)
    configure(min_bucket=8, max_bucket_doublings=30)
    yield
    configure(min_bucket=old[0], max_bucket_doublings=old[1])


def test_bucket_rows_bounds(bucket_cfg):
    assert bucket_rows(1) == 8
    assert bucket_rows(8) == 8
    assert bucket_rows(9) == 16
    assert bucket_rows(100) == 128
    configure(max_bucket_doublings=2)
    # beyond the largest bucket (8*2^2=32): exact-shape compile
    assert bucket_rows(33) == 33


def test_map_rows_bounded_compiles_over_varied_block_sizes(bucket_cfg):
    """19 distinct block sizes share a bounded number of vmap compiles,
    and the padded rows never leak into results. Bucketing is adaptive:
    the first 3 distinct sizes compile exactly (zero padded work for
    partitioner-produced frames, which have at most two sizes); after
    that, sizes pad to power-of-two buckets — O(3 + log n) compiles."""
    sizes = list(range(1, 20))
    blocks = []
    off = 0
    for s in sizes:
        blocks.append({"x": np.arange(off, off + s, dtype=np.float64)})
        off += s
    schema = Schema([ColumnInfo("x", dt.float64, Shape((Unknown,)))])
    fr = tfs.TensorFrame(blocks, schema)
    program = tfs.compile_program(lambda x: {"y": x * 2.0 + 1.0}, fr, block=False)
    out = tfs.map_rows(program, fr)
    got = np.concatenate([np.atleast_1d(b["y"]) for b in out.blocks()])
    np.testing.assert_array_equal(got, np.arange(off, dtype=np.float64) * 2.0 + 1.0)
    # exact sizes {1,2,3} then buckets {8,16,32}: six compiles, not 19
    assert program.compiled().cache_sizes()["vmap"] <= 6


def test_map_rows_partitioner_frames_never_pad(bucket_cfg):
    """Frames from the internal partitioner (at most two distinct block
    sizes) stay on exact-shape compiles — no padded compute, ever."""
    fr = tfs.frame_from_arrays(
        {"x": np.arange(1001, dtype=np.float64)}, num_blocks=4
    )  # blocks of 251 and 250 rows
    program = tfs.compile_program(lambda x: {"y": x + 1.0}, fr, block=False)
    tfs.map_rows(program, fr).blocks()
    assert program.compiled().cache_sizes()["vmap"] == 2  # exact, unpadded


def test_ragged_map_rows_grouped_dispatch(bucket_cfg):
    """Ragged cells run one vmapped dispatch per distinct cell shape
    (not one per row), with correct per-row results."""
    lens = [3, 7, 3, 5, 7, 3, 5, 3]
    rows = [{"v": np.arange(n, dtype=np.float64)} for n in lens]
    fr = tfs.frame_from_rows(rows, num_blocks=1)
    program = tfs.compile_program(
        lambda v: {"s": v.sum()}, fr, block=False
    )
    out = tfs.map_rows(program, fr)
    got = [r["s"] for r in out.collect()]
    expect = [float(np.arange(n).sum()) for n in lens]
    assert got == pytest.approx(expect)
    # 3 distinct cell shapes, every group ≤ 8 rows → ≤ 3 vmap compiles
    assert program.compiled().cache_sizes()["vmap"] <= 3


def test_ragged_map_rows_ragged_output(bucket_cfg):
    """Shape-preserving programs over ragged cells keep ragged outputs."""
    lens = [2, 4, 2, 3]
    rows = [{"v": np.arange(n, dtype=np.float64)} for n in lens]
    fr = tfs.frame_from_rows(rows, num_blocks=1)
    out = tfs.map_rows(lambda v: {"w": v * 10.0}, fr)
    got = [r["w"] for r in out.collect()]
    for g, n in zip(got, lens):
        np.testing.assert_array_equal(np.asarray(g), np.arange(n) * 10.0)


def test_map_rows_bucketing_respects_reduction_semantics(bucket_cfg):
    """Padded rows are replicas of real rows and are sliced off — a
    program whose per-row result depends on the whole cell (sum) must
    still be exact for every real row."""
    rng = np.random.default_rng(0)
    vals = rng.normal(size=(13, 4))
    fr = tfs.frame_from_arrays({"m": vals}, num_blocks=1)
    out = tfs.map_rows(lambda m: {"t": m.sum()}, fr)
    got = np.asarray([r["t"] for r in out.collect()])
    np.testing.assert_allclose(got, vals.sum(axis=1), rtol=1e-12)


def test_ragged_map_rows_single_device_put_globally(bucket_cfg, monkeypatch):
    """VERDICT r3 #5: the ragged path must batch every shape-group's
    feeds into ONE device_put call — per-group transfers multiply
    per-call link latency by the shape count (the r3 TPU run collapsed
    23x on this). Round 4 strengthened per-block to GLOBAL: rows group
    across every ragged block at once, so a multi-BLOCK ragged frame
    still makes exactly one staged transfer, and compiles stay pinned
    at one per (shape, bucket) regardless of block count."""
    import jax

    from tensorframes_tpu.ops import verbs as verbs_mod

    calls = []
    real_put = jax.device_put

    def counting_put(x, *a, **kw):
        # count only the ragged path's staged-feeds transfers (a list of
        # feed dicts) — the patch is global, and per-shape constant
        # hoisting legitimately device_puts its own consts
        if isinstance(x, list) and x and isinstance(x[0], dict):
            calls.append(1)
        return real_put(x, *a, **kw)

    monkeypatch.setattr(verbs_mod.jax, "device_put", counting_put)

    # 3 distinct shapes spread over FOUR blocks
    lens = [2, 4, 2, 3, 4, 2, 3, 3] * 4
    rows = [{"v": np.arange(n, dtype=np.float64)} for n in lens]
    fr = tfs.frame_from_rows(rows, num_blocks=4)
    out = tfs.map_rows(lambda v: {"s": v.sum()}, fr)
    got = np.asarray([r["s"] for r in out.collect()])
    np.testing.assert_allclose(got, [sum(range(n)) for n in lens])
    assert len(calls) == 1, f"expected 1 device_put, saw {len(calls)}"

    # every group fits one 8-row bucket -> exactly 3 vmap compiles,
    # block count contributes nothing
    prog = tfs.compile_program(
        lambda v: {"s": v.sum()}, fr, block=False
    )
    out2 = tfs.map_rows(prog, fr)
    out2.collect()
    assert prog.compiled().cache_sizes()["vmap"] <= 3


def test_ragged_map_rows_wave_split_correct(bucket_cfg, monkeypatch):
    """Over-cap ragged batches split into byte-capped WAVES (one staged
    device_put each) instead of going group-at-a-time: force a tiny cap
    so every group lands in its own wave, and results still match. Peak
    host memory is bounded to one wave's staged copies by construction
    (feeds are built lazily per wave)."""
    from tensorframes_tpu.ops import verbs as verbs_mod

    monkeypatch.setattr(verbs_mod, "_RAGGED_STAGE_BYTES", 64)
    lens = [3, 9, 3, 5, 9, 5, 3] * 3
    rows = [{"x": np.arange(n, dtype=np.float32)} for n in lens]
    fr = tfs.frame_from_rows(rows, num_blocks=3)
    out = tfs.map_rows(lambda x: {"s": x.sum()}, fr)
    got = [float(r["s"]) for r in out.collect()]
    assert got == [float(np.arange(n).sum()) for n in lens]
