"""Observability subsystem tests: instrument semantics under threads,
Chrome trace_event schema validity, Prometheus exposition format, and
the end-to-end acceptance path — one ``train_on_frame`` run emitting a
valid trace, an exposition carrying executor + retry/guard counters,
and a JSONL step log with per-step loss and rows/s."""

import json
import os
import threading
import urllib.request

import numpy as np
import pytest

_REPO_DIR = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

import tensorframes_tpu as tfs
from tensorframes_tpu.observability import (
    REGISTRY,
    MetricsRegistry,
    StepTelemetry,
    events,
    metrics,
)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Give every test a zeroed process registry and an empty, disabled
    tracer — then RESTORE the pre-test accumulations afterwards. The
    restore matters: under TFTPU_OBS_EXPORT the conftest exports the
    session-wide registry + trace as the CI artifact, and these tests
    must not gut the rest of the suite's data on their way through
    (what each test itself accumulated is discarded — that is noise)."""
    was_enabled = events.TRACER.enabled
    saved_metrics = {}
    for m in REGISTRY.collect():
        if isinstance(m, metrics.Histogram):
            saved_metrics[id(m)] = (list(m._counts), m._sum, m._count)
        else:
            saved_metrics[id(m)] = m._value
    with events.TRACER._lock:
        saved_trace = (
            list(events.TRACER._events),
            set(events.TRACER._named_threads),
            events.TRACER.dropped,
        )
    REGISTRY.reset()
    events.clear()
    events.disable()
    yield
    REGISTRY.reset()
    for m in REGISTRY.collect():
        saved = saved_metrics.get(id(m))
        if saved is None:
            continue  # registered during the test: stays zeroed
        if isinstance(m, metrics.Histogram):
            m._counts, m._sum, m._count = list(saved[0]), saved[1], saved[2]
        else:
            m._value = saved
    with events.TRACER._lock:
        events.TRACER._events = saved_trace[0]
        events.TRACER._named_threads = saved_trace[1]
        events.TRACER.dropped = saved_trace[2]
    events.TRACER.enabled = was_enabled


# ---------------------------------------------------------------------------
# metrics registry semantics
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_semantics():
    reg = MetricsRegistry()
    c = reg.counter("t_total")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)  # counters are monotone

    g = reg.gauge("t_depth")
    g.set(3)
    g.inc()
    g.dec(2)
    assert g.value == 2

    h = reg.histogram("t_seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    assert h.count == 4
    assert h.sum == pytest.approx(55.55)
    cum = dict(h.cumulative())
    assert cum[0.1] == 1 and cum[1.0] == 2 and cum[10.0] == 3
    assert cum[float("inf")] == 4


def test_registry_get_or_create_and_kind_conflicts():
    reg = MetricsRegistry()
    a = reg.counter("t_total", labels={"k": "1"})
    assert reg.counter("t_total", labels={"k": "1"}) is a
    # same name, different labels → sibling series of the same family
    b = reg.counter("t_total", labels={"k": "2"})
    assert b is not a
    with pytest.raises(ValueError):
        reg.gauge("t_total")  # family kind conflict
    with pytest.raises(ValueError):
        reg.histogram("t_total", labels={"k": "3"})


def test_counters_exact_under_threads():
    reg = MetricsRegistry()
    c = reg.counter("t_total")
    h = reg.histogram("t_lat", buckets=(0.5,))
    g = reg.gauge("t_gauge")

    def work():
        for _ in range(1000):
            c.inc()
            h.observe(0.25)
            g.inc()

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    assert h.count == 8000
    assert dict(h.cumulative())[0.5] == 8000
    assert g.value == 8000


def test_reset_zeroes_but_keeps_registrations():
    reg = MetricsRegistry()
    c = reg.counter("t_total")
    c.inc(7)
    reg.reset()
    assert c.value == 0
    # the SAME object is still registered: new increments still export
    c.inc(2)
    assert any(
        d["name"] == "t_total" and d["value"] == 2 for d in reg.snapshot()
    )


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def test_prometheus_exposition_format():
    reg = MetricsRegistry()
    reg.counter("t_ops_total", "ops help", labels={"site": 'a"b\\c'}).inc(3)
    reg.gauge("t_depth", "depth help").set(1.5)
    reg.histogram("t_lat_seconds", buckets=(0.1, 1.0)).observe(0.5)
    text = reg.to_prometheus()
    lines = text.splitlines()
    assert "# HELP t_ops_total ops help" in lines
    assert "# TYPE t_ops_total counter" in lines
    assert 't_ops_total{site="a\\"b\\\\c"} 3' in lines
    assert "# TYPE t_depth gauge" in lines
    assert "t_depth 1.5" in lines
    assert "# TYPE t_lat_seconds histogram" in lines
    assert 't_lat_seconds_bucket{le="0.1"} 0' in lines
    assert 't_lat_seconds_bucket{le="1"} 1' in lines
    assert 't_lat_seconds_bucket{le="+Inf"} 1' in lines
    assert "t_lat_seconds_sum 0.5" in lines
    assert "t_lat_seconds_count 1" in lines
    # one HELP/TYPE header per family, before its samples
    assert lines.index("# TYPE t_ops_total counter") < lines.index(
        't_ops_total{site="a\\"b\\\\c"} 3'
    )


def test_jsonl_snapshot_round_trips():
    reg = MetricsRegistry()
    reg.counter("t_total", labels={"k": "v"}).inc(2)
    reg.histogram("t_lat", buckets=(1.0,)).observe(0.5)
    rows = [json.loads(line) for line in reg.to_jsonl().splitlines()]
    by_name = {r["name"]: r for r in rows}
    assert by_name["t_total"]["value"] == 2
    assert by_name["t_total"]["labels"] == {"k": "v"}
    assert by_name["t_lat"]["count"] == 1
    assert by_name["t_lat"]["buckets"]["+Inf"] == 1
    assert all("ts" in r for r in rows)


def test_metrics_server_serves_prometheus_and_jsonl():
    reg = MetricsRegistry()
    reg.counter("t_scraped_total").inc(9)
    server = metrics.metrics_server(port=0, registry=reg)
    try:
        port = server.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ).read().decode()
        assert "t_scraped_total 9" in body
        jl = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics.json", timeout=10
        ).read().decode()
        assert json.loads(jl.splitlines()[0])["name"] == "t_scraped_total"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=10
            )
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# event tracer
# ---------------------------------------------------------------------------

def test_chrome_trace_schema_and_nesting():
    events.enable()
    with events.span("outer", rows=10):
        with events.span("inner"):
            pass
    events.instant("mark", step=3)
    trace = events.to_chrome_trace()
    assert json.loads(json.dumps(trace)) == trace  # JSON-serializable
    evs = trace["traceEvents"]
    xs = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert set(xs) == {"outer", "inner"}
    for e in xs.values():
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
    # nesting by time containment on one thread
    outer, inner = xs["outer"], xs["inner"]
    assert outer["tid"] == inner["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert outer["args"] == {"rows": 10}
    inst = [e for e in evs if e["ph"] == "i"]
    assert inst and inst[0]["name"] == "mark" and inst[0]["args"] == {"step": 3}
    meta = [e for e in evs if e["ph"] == "M"]
    assert any(m["name"] == "thread_name" for m in meta)


def test_tracer_disabled_records_nothing_and_buffer_bounds():
    with events.span("ignored"):
        pass
    assert events.to_chrome_trace()["traceEvents"] == []
    t = events.Tracer(max_events=3)
    t.enable()
    for i in range(10):
        t.instant(f"e{i}")
    assert len(t.to_chrome_trace()["traceEvents"]) <= 3
    assert t.dropped > 0
    assert t.to_chrome_trace()["otherData"]["dropped_events"] == t.dropped


def test_trace_records_worker_thread_tids():
    events.enable()
    tids = []

    def work():
        with events.span("worker-span"):
            tids.append(threading.get_ident())

    t = threading.Thread(target=work, name="obs-worker")
    t.start()
    t.join()
    with events.span("main-span"):
        pass
    evs = events.to_chrome_trace()["traceEvents"]
    xs = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert xs["worker-span"]["tid"] == tids[0]
    assert xs["worker-span"]["tid"] != xs["main-span"]["tid"]
    names = {
        e["args"]["name"] for e in evs if e["ph"] == "M"
    }
    assert "obs-worker" in names


def test_trace_args_numpy_and_nonfinite_safe(tmp_path):
    """numpy-typed and non-finite args must not poison the export."""
    events.enable()
    events.instant("watermark", step=np.int64(7), bad=float("nan"),
                   arr=np.arange(2))
    with events.span("s", rows=np.int32(5)):
        pass
    path = tmp_path / "t.json"
    events.save(str(path))
    trace = json.loads(path.read_text())  # strict parse
    inst = [e for e in trace["traceEvents"] if e["ph"] == "i"][0]
    assert inst["args"]["step"] == 7
    assert inst["args"]["bad"] is None
    assert isinstance(inst["args"]["arr"], str)


def test_profiling_spans_land_on_timeline():
    from tensorframes_tpu.utils import profiling

    events.enable()
    with profiling.span("layered", rows=4):
        pass
    profiling.record("recorded", 0.01, rows=2)
    names = {
        e["name"]
        for e in events.to_chrome_trace()["traceEvents"]
        if e["ph"] == "X"
    }
    assert {"layered", "recorded"} <= names


# ---------------------------------------------------------------------------
# instrumented layers
# ---------------------------------------------------------------------------

def _snap():
    return {
        (d["name"], tuple(sorted(d["labels"].items()))): d
        for d in REGISTRY.snapshot()
    }


def test_executor_cache_hit_miss_counters():
    s0 = _snap()
    misses0 = s0[("tftpu_executor_jit_cache_misses_total", ())]["value"]
    compiles0 = s0[("tftpu_executor_compile_seconds", ())]["count"]
    runs0 = s0[("tftpu_executor_first_run_seconds", ())]["count"]
    df = tfs.frame_from_arrays({"x": np.arange(16.0)}, num_blocks=2)
    program = tfs.compile_program(lambda x: {"y": x + 1}, df)
    tfs.map_blocks(program, df).collect()
    s1 = _snap()
    # deltas, not session cumulatives: with the persistent store or
    # warmup in play elsewhere in the session, misses can be served
    # without a compile (disk hit) and compiles can happen without a
    # miss (warm) — but a fresh program with no store support must
    # compile exactly once per miss, and its first run is timed
    # separately from the compile (ISSUE 5 accounting split)
    d_miss = s1[("tftpu_executor_jit_cache_misses_total", ())]["value"] - misses0
    d_compile = s1[("tftpu_executor_compile_seconds", ())]["count"] - compiles0
    d_first = s1[("tftpu_executor_first_run_seconds", ())]["count"] - runs0
    assert d_miss >= 1
    from tensorframes_tpu.compilecache import active_store

    if active_store() is None:  # a live store may serve misses from disk
        assert d_compile == d_miss
        assert d_first == d_miss
    hits1 = s1[("tftpu_executor_jit_cache_hits_total", ())]["value"]
    tfs.map_blocks(program, df).collect()
    s2 = _snap()
    # re-running the same frame+program adds hits, not misses/compiles
    assert s2[("tftpu_executor_jit_cache_hits_total", ())]["value"] > hits1
    assert (s2[("tftpu_executor_jit_cache_misses_total", ())]["value"]
            - misses0 == d_miss)
    assert (s2[("tftpu_executor_compile_seconds", ())]["count"]
            - compiles0 == d_compile)


def test_sharded_dispatch_compile_accounting_split():
    """ISSUE 10: with the legacy path folded into the unified AOT
    pipeline, the compile/first-run split holds on EVERY dispatch —
    sharded feeds included (they used to ride the lazy-jit path, where
    the first call lumped compile+run into compile-seconds). A sharded
    dispatch's compile-seconds observation must be trace+XLA only,
    with the first execution timed separately."""
    from tensorframes_tpu.compilecache import active_store
    from tensorframes_tpu.parallel import device_count

    if device_count() < 8:
        pytest.skip("needs 8 (virtual) devices")
    df = tfs.frame_from_arrays(
        {"x": np.arange(64.0, dtype=np.float32)}
    ).to_device()
    assert df.is_sharded
    program = tfs.compile_program(
        lambda x: {"y": x * 2.0 + 1.0}, df
    )
    s0 = _snap()
    fb0 = s0[("tftpu_executor_fallback_dispatch_total", ())]["value"]
    out = tfs.map_blocks(program, df).column_values("y")
    np.testing.assert_allclose(out, np.arange(64.0) * 2.0 + 1.0)
    s1 = _snap()
    d_miss = (s1[("tftpu_executor_jit_cache_misses_total", ())]["value"]
              - s0[("tftpu_executor_jit_cache_misses_total", ())]["value"])
    d_compile = (s1[("tftpu_executor_compile_seconds", ())]["count"]
                 - s0[("tftpu_executor_compile_seconds", ())]["count"])
    d_first = (s1[("tftpu_executor_first_run_seconds", ())]["count"]
               - s0[("tftpu_executor_first_run_seconds", ())]["count"])
    assert d_miss >= 1
    # the sharded dispatch rode the unified AOT path, not the fallback
    assert s1[("tftpu_executor_fallback_dispatch_total", ())]["value"] == fb0
    if active_store() is None:  # a live store may serve misses from disk
        assert d_compile == d_miss
    assert d_first == d_miss  # first run timed on the sharded path too


def test_fallback_dispatch_observes_neither_histogram(monkeypatch):
    """The counted lazy-jit fallback (AOT build raised) must not lump
    its compile+run into either histogram — that would resurrect the
    pre-unification accounting caveat the docs no longer carry."""
    from tensorframes_tpu.ops.executor import CompiledProgram

    monkeypatch.setattr(
        CompiledProgram, "_build_aot_impl",
        lambda self, *a, **k: (_ for _ in ()).throw(
            RuntimeError("forced AOT build failure")
        ),
    )
    df = tfs.frame_from_arrays({"x": np.arange(8.0)}, num_blocks=1)
    program = tfs.compile_program(lambda x: {"y": x - 3.0}, df)
    s0 = _snap()
    out = tfs.map_blocks(program, df).column_values("y")
    np.testing.assert_array_equal(out, np.arange(8.0) - 3.0)
    s1 = _snap()
    assert (s1[("tftpu_executor_fallback_dispatch_total", ())]["value"]
            - s0[("tftpu_executor_fallback_dispatch_total", ())]["value"]) == 1
    assert (s1[("tftpu_executor_compile_seconds", ())]["count"]
            == s0[("tftpu_executor_compile_seconds", ())]["count"])
    assert (s1[("tftpu_executor_first_run_seconds", ())]["count"]
            == s0[("tftpu_executor_first_run_seconds", ())]["count"])


def test_padding_waste_counter():
    from tensorframes_tpu.ops.executor import pad_lead_dim

    pad_lead_dim({"x": np.zeros((3, 2))}, 3, 8)
    assert _snap()[("tftpu_executor_padding_waste_rows_total", ())]["value"] == 5
    pad_lead_dim({"x": np.zeros((8, 2))}, 8, 8)  # no-op pad adds nothing
    assert _snap()[("tftpu_executor_padding_waste_rows_total", ())]["value"] == 5


def test_prefetch_metrics():
    from tensorframes_tpu.io import prefetch_to_device

    batches = [{"x": np.full((4,), i, np.float32)} for i in range(6)]
    out = list(prefetch_to_device(iter(batches), size=2))
    assert len(out) == 6
    s = _snap()
    assert s[("tftpu_prefetch_batches_total", ())]["value"] == 6
    assert s[("tftpu_prefetch_consumer_wait_seconds", ())]["count"] == 6
    assert s[("tftpu_prefetch_producer_wait_seconds", ())]["count"] >= 1
    # finished stream: no phantom staged batches in the snapshot
    assert s[("tftpu_prefetch_queue_depth", ())]["value"] == 0


def test_retry_and_fault_counters():
    from tensorframes_tpu.resilience import RetryError, RetryPolicy, retry_call
    from tensorframes_tpu.resilience import faults

    calls = []

    def flaky():
        calls.append(1)
        raise OSError("wobble")

    with pytest.raises(RetryError):
        retry_call(
            flaky, policy=RetryPolicy(max_attempts=3, backoff=0.0, seed=0)
        )
    s = _snap()
    assert s[("tftpu_retry_attempts_total", ())]["value"] == 2
    assert s[("tftpu_retry_exhaustions_total", ())]["value"] == 1

    with faults.inject("obs.test.site", OSError("boom")):
        with pytest.raises(OSError):
            faults.fault_point("obs.test.site")
    assert _snap()[("tftpu_fault_injections_fired_total", ())]["value"] == 1


def test_guard_trip_counter_by_policy():
    from tensorframes_tpu.resilience import StepGuard

    g = StepGuard(policy="skip", check="metrics")
    state, admitted = g.admit(1, {"w": 1.0}, {"loss": float("nan")},
                              prev_state={"w": 0.0})
    assert not admitted
    trips = _snap()[("tftpu_guard_trips_total", (("policy", "skip"),))]
    assert trips["value"] == 1
    # the other policies' series exist (pre-registered), reading 0
    assert _snap()[("tftpu_guard_trips_total", (("policy", "rollback"),))][
        "value"
    ] == 0


def test_checkpoint_metrics_and_crc_failures(tmp_path):
    ck = tfs.Checkpointer(str(tmp_path), backend="npz")
    state = {"w": np.arange(8.0), "b": np.float64(2.0)}
    ck.save(1, state)
    ck.save(2, state)
    s = _snap()
    assert s[("tftpu_checkpoint_save_seconds", ())]["count"] == 2
    assert s[("tftpu_checkpoint_save_bytes_total", ())]["value"] > 0
    ck.restore(like=state)
    s = _snap()
    assert s[("tftpu_checkpoint_restore_seconds", ())]["count"] == 1
    assert s[("tftpu_checkpoint_restore_bytes_total", ())]["value"] > 0
    # corrupt the newest step: fallback restore counts a CRC failure
    npz = tmp_path / "step_2" / "arrays.npz"
    npz.write_bytes(npz.read_bytes()[:-7])
    step, _ = ck.restore_latest(like=state)
    assert step == 1
    assert _snap()[("tftpu_checkpoint_crc_failures_total", ())]["value"] >= 1


# ---------------------------------------------------------------------------
# latency quantiles + snapshot/diff (ISSUE 6)
# ---------------------------------------------------------------------------

def test_histogram_quantile_interpolates():
    reg = MetricsRegistry()
    h = reg.histogram("t_lat_q", buckets=(0.1, 0.2, 0.4, 1.0))
    assert h.quantile(0.5) is None  # empty
    for v in (0.05,) * 50 + (0.15,) * 30 + (0.3,) * 15 + (0.8,) * 5:
        h.observe(v)
    # 100 observations: p50 sits inside the first bucket (50 of 100)
    assert h.quantile(0.5) == pytest.approx(0.1)
    # p80 at the 0.2 bound (cum 80), p95 at 0.4 (cum 95)
    assert h.quantile(0.8) == pytest.approx(0.2)
    assert h.quantile(0.95) == pytest.approx(0.4)
    # interpolation inside a bucket: rank 90 is 2/3 through (0.2, 0.4]
    assert h.quantile(0.9) == pytest.approx(0.2 + 0.2 * (10 / 15))
    with pytest.raises(ValueError):
        h.quantile(1.5)
    # +Inf overflow clamps to the largest finite bound
    h2 = reg.histogram("t_lat_q2", buckets=(0.1,))
    h2.observe(5.0)
    assert h2.quantile(0.99) == 0.1
    qs = h.quantiles()
    assert set(qs) == {"p50", "p95", "p99"}


def test_verb_and_dispatch_latency_histograms_populate():
    from tensorframes_tpu.observability import latency

    df = tfs.frame_from_arrays({"x": np.arange(32.0)}, num_blocks=2)
    program = tfs.compile_program(lambda x: {"y": x + 1.0}, df)
    tfs.map_blocks(program, df).collect()
    vh = latency.verb_histogram("map_blocks")
    assert vh is not None and vh.count >= 1
    dh = latency.dispatch_histogram("block")
    assert dh.count >= 2  # one per block
    rows = latency.quantile_summary()
    series = {
        (r["name"], tuple(sorted(r["labels"].items()))) for r in rows
    }
    assert ("tftpu_dispatch_latency_seconds",
            (("entry", "block"),)) in series
    for r in rows:
        assert r["p50"] is not None and r["p99"] >= r["p50"]
    lines = latency.summary_lines()
    assert any(ln.startswith("verb:map_blocks ") for ln in lines)
    assert any(ln.startswith("dispatch:block ") for ln in lines)


def test_trace_events_dropped_counter():
    t = events.Tracer(max_events=2)
    t.enable()
    before = _snap()[("tftpu_trace_events_dropped_total", ())]["value"]
    for i in range(6):
        t.instant(f"e{i}")
    after = _snap()[("tftpu_trace_events_dropped_total", ())]["value"]
    assert after - before == t.dropped > 0
    assert t.to_chrome_trace()["otherData"]["dropped_events"] == t.dropped


def test_jsonl_rows_carry_run_context():
    from tensorframes_tpu.observability import context

    reg = MetricsRegistry()
    reg.counter("t_stamped_total").inc()
    row = json.loads(reg.to_jsonl().splitlines()[0])
    assert row["run_id"] == context.run_id()
    assert row["process_index"] == context.process_index()


def test_step_log_lines_carry_run_context(tmp_path):
    from tensorframes_tpu.observability import context

    path = tmp_path / "steps.jsonl"
    with StepTelemetry(jsonl_path=str(path), rows_per_step=8) as t:
        t(1, {"loss": 0.5})
        t(2, {"loss": 0.25})
    rows = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert [r["step"] for r in rows] == [1, 2]
    for r in rows:
        # additive fields: the original five keys stay intact
        assert {"step", "ts", "step_seconds", "loss",
                "rows_per_sec"} <= set(r)
        assert r["run_id"] == context.run_id()
        assert r["process_index"] == context.process_index()


def _write_snap(path, metrics, latency=None):
    from tensorframes_tpu.observability import snapshot

    obj = {"schema": snapshot.SCHEMA, "metrics": metrics,
           "latency": latency or {}}
    path.write_text(json.dumps(obj))
    return str(path)


def test_diff_identical_snapshots_is_clean(tmp_path):
    from tensorframes_tpu.observability import cli

    m = {"add3_rows_per_sec": 1e6, "chain3_wall_s": 0.02}
    a = _write_snap(tmp_path / "a.json", m)
    b = _write_snap(tmp_path / "b.json", dict(m))
    assert cli.main(["diff", a, b]) == 0


def test_diff_flags_2x_latency_regression(tmp_path):
    from tensorframes_tpu.observability import cli, snapshot

    a = _write_snap(tmp_path / "a.json", {"chain3_wall_s": 0.02},
                    {"verb:map_blocks": {"count": 5, "mean": 0.01,
                                         "p50": 0.01, "p95": 0.02,
                                         "p99": 0.03}})
    b = _write_snap(tmp_path / "b.json", {"chain3_wall_s": 0.04},
                    {"verb:map_blocks": {"count": 5, "mean": 0.02,
                                         "p50": 0.02, "p95": 0.04,
                                         "p99": 0.06}})
    assert cli.main(["diff", a, b]) == 1
    assert cli.main(["diff", a, b, "--warn-only"]) == 0
    # direction-aware: the reverse diff is an improvement, not a gate
    assert cli.main(["diff", b, a]) == 0
    # machinery check: the latency series flattened and compared
    old, _ = snapshot.load_metrics(a)
    new, _ = snapshot.load_metrics(b)
    res = snapshot.diff_metrics(old, new)
    names = {r["metric"] for r in res["regressions"]}
    assert "chain3_wall_s" in names
    assert "latency.verb:map_blocks.p99" in names
    assert not any(".count" in n for n in names)  # counts never gate


def test_diff_no_common_metrics_fails_except_warn_only(tmp_path):
    from tensorframes_tpu.observability import cli

    a = _write_snap(tmp_path / "a.json", {"left_only_wall_s": 1.0})
    b = _write_snap(tmp_path / "b.json", {"right_only_wall_s": 1.0})
    # zero overlap is a usage error (broken bench run / name drift) …
    assert cli.main(["diff", a, b]) == 2
    # … but the warn-only contract is "never block the build"
    assert cli.main(["diff", a, b, "--warn-only"]) == 0


def test_diff_throughput_drop_and_per_metric_threshold(tmp_path):
    from tensorframes_tpu.observability import cli

    a = _write_snap(tmp_path / "a.json", {"x_rows_per_sec": 1000.0})
    b = _write_snap(tmp_path / "b.json", {"x_rows_per_sec": 800.0})
    # -20% is inside the default ±50% band …
    assert cli.main(["diff", a, b]) == 0
    # … but trips a tightened per-metric threshold
    assert cli.main(["diff", a, b, "--metric", "x_rows_per_sec=0.1"]) == 1


def test_diff_reads_committed_bench_round_and_bench_stdout(tmp_path):
    from tensorframes_tpu.observability import cli, snapshot

    round_path = os.path.join(_REPO_DIR, "BENCH_r05.json")
    metrics, meta = snapshot.load_metrics(round_path)
    assert meta["source"] == "bench-round"
    assert metrics["gpt_tiny_decode_tokens_per_sec"] == 31166.0
    # a fresh "bench stdout" with one metric at a third (a -67% drop,
    # well past the default ±50% band): the diff sees it
    text = "\n".join(
        f"# {k}={v if k != 'gpt_tiny_decode_tokens_per_sec' else v / 3}"
        for k, v in metrics.items() if not k.startswith("headline.")
    )
    out = tmp_path / "bench_out.txt"
    out.write_text(text + "\n")
    assert cli.main(["diff", round_path, str(out)]) == 1
    assert cli.main(["diff", round_path, str(out), "--warn-only"]) == 0


def test_parse_bench_latency_lines():
    from tensorframes_tpu.observability import snapshot

    text = (
        "# add3_rows_per_sec=123456\n"
        "# latency | verb:map_blocks count=12 p50=0.000120s "
        "p95=0.000500s p99=0.000900s mean=0.000200s\n"
        '{"metric": "headline", "value": 75.5}\n'
    )
    m = snapshot.parse_bench_text(text)
    assert m["add3_rows_per_sec"] == 123456.0
    assert m["latency.verb:map_blocks.p50"] == pytest.approx(0.00012)
    assert m["latency.verb:map_blocks.count"] == 12
    assert m["headline.value"] == 75.5


def test_report_cli_on_metrics_jsonl(tmp_path, capsys):
    from tensorframes_tpu.observability import cli

    reg = MetricsRegistry()
    reg.counter("t_report_total").inc(3)
    h = reg.histogram("t_report_latency_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    path = tmp_path / "metrics.jsonl"
    reg.write_jsonl(str(path))
    assert cli.main(["report", str(path)]) == 0
    out = capsys.readouterr().out
    assert "t_report_total" in out
    assert "t_report_latency_seconds.p50" in out


# ---------------------------------------------------------------------------
# satellites: profiling rename, logging level control
# ---------------------------------------------------------------------------

def test_record_bytes_alias_deprecated():
    from tensorframes_tpu.utils import profiling

    profiling.reset_metrics()
    try:
        with pytest.warns(DeprecationWarning):
            profiling.record("legacy", 1.0, bytes=10.0)
        profiling.record("legacy", 1.0, bytes_accessed=5.0)
        assert profiling.metrics()["legacy"].bytes == 15.0
        with pytest.raises(TypeError):
            profiling.record("legacy", 1.0, bytes=1.0, bytes_accessed=1.0)
        with pytest.raises(TypeError):
            # an explicit 0.0 still conflicts — the guard is identity,
            # not truthiness
            profiling.record("legacy", 1.0, bytes=1.0, bytes_accessed=0.0)
        with pytest.raises(TypeError):
            profiling.record("legacy", 1.0, nonsense=1.0)
    finally:
        profiling.reset_metrics()


def test_log_level_env_rereads_and_set_level(monkeypatch):
    import logging as stdlog

    from tensorframes_tpu.utils import logging as tlog

    root = stdlog.getLogger("tensorframes_tpu")
    original = root.level
    try:
        monkeypatch.setenv("TFTPU_LOG", "DEBUG")
        tlog.get_logger("tensorframes_tpu.obs_test")
        assert root.level == stdlog.DEBUG
        # env change is honored on the NEXT call, not frozen at first use
        monkeypatch.setenv("TFTPU_LOG", "ERROR")
        tlog.get_logger("tensorframes_tpu.obs_test")
        assert root.level == stdlog.ERROR
        # explicit set_level pins, beating the env
        tlog.set_level("INFO")
        tlog.get_logger("tensorframes_tpu.obs_test")
        assert root.level == stdlog.INFO
        with pytest.raises(ValueError):
            tlog.set_level("NOT_A_LEVEL")
        tlog.clear_level()
        tlog.get_logger("tensorframes_tpu.obs_test")
        assert root.level == stdlog.ERROR
    finally:
        tlog.clear_level()
        root.setLevel(original)


# ---------------------------------------------------------------------------
# acceptance: one train_on_frame run → trace + exposition + step log
# ---------------------------------------------------------------------------

def test_train_on_frame_emits_full_telemetry(tmp_path):
    import jax

    from tensorframes_tpu import training

    events.enable()
    rng = np.random.default_rng(0)
    n = 256
    frame = tfs.frame_from_arrays({
        "x": rng.standard_normal((n, 4)).astype(np.float32),
        "y": rng.standard_normal((n,)).astype(np.float32),
    })

    @jax.jit
    def step(w, batch):
        grad = jax.grad(
            lambda w: ((batch["x"] @ w - batch["y"]) ** 2).mean()
        )(w)
        w = w - 0.01 * grad
        loss = ((batch["x"] @ w - batch["y"]) ** 2).mean()
        return w, {"loss": loss}

    steps_log = tmp_path / "steps.jsonl"
    with StepTelemetry(jsonl_path=str(steps_log)) as telemetry:
        _, ran = training.train_on_frame(
            step,
            np.zeros((4,), np.float32),
            frame,
            ["x", "y"],
            batch_size=64,
            num_steps=4,
            checkpointer=tfs.Checkpointer(str(tmp_path / "ck"), backend="npz"),
            save_every=2,
            guard="skip",
            telemetry=telemetry,
        )
    assert ran == 4

    # (a) valid Chrome trace_event JSON
    trace_path = tmp_path / "trace.json"
    events.save(str(trace_path))
    trace = json.loads(trace_path.read_text())
    assert isinstance(trace["traceEvents"], list) and trace["traceEvents"]
    names = {e["name"] for e in trace["traceEvents"]}
    assert "train.step" in names
    assert "checkpoint.save" in names
    for e in trace["traceEvents"]:
        assert {"ph", "name", "pid", "tid"} <= set(e) or e["ph"] == "M"
        if e["ph"] == "X":
            assert "ts" in e and "dur" in e

    # (b) Prometheus exposition: executor cache counters + a
    # retry/guard counter are present (registered at import, so they
    # appear even at 0), and the train counters moved
    prom = REGISTRY.to_prometheus()
    assert "tftpu_executor_jit_cache_hits_total" in prom
    assert "tftpu_executor_jit_cache_misses_total" in prom
    assert "tftpu_retry_attempts_total" in prom
    assert 'tftpu_guard_trips_total{policy="skip"}' in prom
    assert "tftpu_train_steps_total 4" in prom

    # (c) JSONL step log with per-step loss and rows/s
    rows = [json.loads(line) for line in steps_log.read_text().splitlines()]
    assert [r["step"] for r in rows] == [1, 2, 3, 4]
    for r in rows:
        assert isinstance(r["loss"], float) and np.isfinite(r["loss"])
        assert r["rows_per_sec"] is not None and r["rows_per_sec"] > 0
        assert r["step_seconds"] is not None and r["step_seconds"] >= 0

    # JSONL registry snapshot for the same run
    snap_path = tmp_path / "metrics.jsonl"
    REGISTRY.write_jsonl(str(snap_path))
    snap_names = {
        json.loads(line)["name"]
        for line in snap_path.read_text().splitlines()
    }
    assert "tftpu_train_step_seconds" in snap_names
