"""Cross-hop request tracing (ISSUE 17): one request id from Router
ingress through a replica's batcher flush.

Four surfaces under test:

* **the header** — ``trace_header_value``/``parse_trace_header``
  roundtrip, garbled values degrade to ``(None, None)``, and the
  thread-local ``request_scope`` binds/restores exception-safely;
* **HTTP adoption** — a POST carrying ``X-Tftpu-Trace`` lands its
  request id on the replica's ``serving.request``/``serving.flush``
  spans and bumps ``tftpu_serving_request_trace_total``;
* **redrive stability** — the id IS the idempotency key: a crashed
  first attempt and its redrive carry the SAME id on the wire, and the
  router's ``router.request`` span joins the surviving replica's spans
  on it;
* **the merged-timeline acceptance** — a 2-process run (subprocess
  replica + in-process router, one ``TFTPU_RUN_ID``) merges into one
  Perfetto timeline where a single request id spans BOTH pids
  (subprocess pattern follows tests/test_trace_merge.py).
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.observability import context, events, merge
from tensorframes_tpu.serving import (
    Router,
    RouterConfig,
    Server,
    ServingConfig,
    serve_http,
)
from tensorframes_tpu.serving import metrics as sm

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
WIDTH = 4


def _schema(width=WIDTH):
    return tfs.Schema([
        tfs.ColumnInfo(
            "x", tfs.dtypes.float32, tfs.Shape((tfs.Unknown, width))
        )
    ])


def _program(width=WIDTH):
    holder = type("F", (), {"schema": _schema(width)})()
    return tfs.compile_program(
        lambda x: {"y": x * 2.0 + 1.0}, holder, block=False
    )


def _server(**cfg_kwargs) -> Server:
    cfg = dict(max_batch_rows=8, max_latency_s=0.002, max_queue_rows=128)
    cfg.update(cfg_kwargs)
    srv = Server(ServingConfig(**cfg))
    srv.register("score", _program())
    return srv


def _post(url, body=None, raw=None, headers=None, timeout=20):
    data = raw if raw is not None else json.dumps(body or {}).encode()
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(url, data=data, headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def _spans(name):
    return [
        e for e in events.to_chrome_trace()["traceEvents"]
        if e.get("name") == name and e.get("ph") == "X"
    ]


@pytest.fixture
def _tracing():
    """Tracer on, drained before and after (other tests' spans must not
    leak into these assertions)."""
    was = events.TRACER.enabled
    events.clear()
    events.enable()
    yield
    events.clear()
    if not was:
        events.disable()


# ---------------------------------------------------------------------------
# the header + the thread-local scope
# ---------------------------------------------------------------------------

def test_trace_header_roundtrip_and_garble_tolerance():
    val = context.trace_header_value("req-abc123")
    rid, run = context.parse_trace_header(val)
    assert rid == "req-abc123"
    assert run == context.run_id()
    # degraded inputs: telemetry must never fail a request
    assert context.parse_trace_header(None) == (None, None)
    assert context.parse_trace_header("") == (None, None)
    assert context.parse_trace_header("x" * 300) == (None, None)
    assert context.parse_trace_header(";;;=") == (None, None)
    rid, run = context.parse_trace_header("just-an-id")
    assert rid == "just-an-id" and run is None
    rid, run = context.parse_trace_header("id;run=r1;extra=zz")
    assert rid == "id" and run == "r1"


def test_request_scope_binds_and_restores_per_thread():
    context.clear_request()
    assert context.current_request() is None
    with context.request_scope("outer"):
        assert context.current_request() == "outer"
        with context.request_scope("inner"):
            assert context.current_request() == "inner"
        assert context.current_request() == "outer"
        # exception-safe restore
        with pytest.raises(RuntimeError):
            with context.request_scope("doomed"):
                raise RuntimeError("boom")
        assert context.current_request() == "outer"
        # another thread sees ITS binding, not ours
        seen = []
        t = threading.Thread(target=lambda: seen.append(
            context.current_request()
        ))
        t.start()
        t.join()
        assert seen == [None]
    assert context.current_request() is None


# ---------------------------------------------------------------------------
# HTTP adoption: header → submit → batcher spans
# ---------------------------------------------------------------------------

def test_http_header_binds_request_id_onto_serving_spans(_tracing):
    srv = _server()
    srv.start()
    httpd = serve_http(srv)
    port = httpd.server_address[1]
    try:
        t0 = sm.REQUEST_TRACE.value
        status, body = _post(
            f"http://127.0.0.1:{port}/v1/score",
            {"inputs": {"x": [[1.0] * WIDTH]}},
            headers={context.TRACE_HEADER:
                     context.trace_header_value("req-http-1")},
        )
        assert status == 200, body
        np.testing.assert_allclose(
            np.asarray(body["outputs"]["y"]), [[3.0] * WIDTH]
        )
        assert sm.REQUEST_TRACE.value == t0 + 1
        reqs = [
            e for e in _spans("serving.request")
            if e["args"].get("request_id") == "req-http-1"
        ]
        assert len(reqs) == 1, (
            "the adopted id must ride the per-request span"
        )
        flushes = [
            e for e in _spans("serving.flush")
            if "req-http-1" in e["args"].get("request_ids", [])
        ]
        assert flushes, "the flush span lists the ids it served"

        # per-endpoint latency quantiles surfaced on stats() (satellite:
        # cardinality lives in the JSON body, NOT the registry — TFL003)
        lat = srv.stats()["latency"]
        assert "score" in lat
        assert {"p50", "p95", "p99"} <= set(lat["score"])
        assert 0.0 <= lat["score"]["p50"] <= lat["score"]["p99"]
    finally:
        httpd.shutdown()
        srv.stop(drain=True)


def test_submit_without_header_falls_back_to_idempotency_key(_tracing):
    srv = _server()
    srv.start()
    try:
        fut = srv.submit(
            "score", {"x": np.ones((1, WIDTH), np.float32)},
            idempotency_key="idem-7",
        )
        fut.result(10.0)
        ids = {
            e["args"].get("request_id")
            for e in _spans("serving.request")
        }
        assert "idem-7" in ids, (
            "an in-process submit that never touched the HTTP adapter "
            "must still be traceable by its idempotency key"
        )
    finally:
        srv.stop(drain=True)


# ---------------------------------------------------------------------------
# redrive: one id across both attempts, router ↔ replica spans join
# ---------------------------------------------------------------------------

class _HeaderRecordingCrasher:
    """A fake replica that records the trace header of every POST and
    then dies wordlessly — the crash-before-dispatch window."""

    def __init__(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        outer = self
        self.trace_headers = []

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_GET(self):
                body = json.dumps({
                    "state": "running", "running": True,
                    "queued_rows": {}, "endpoints": ["score"],
                }).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                outer.trace_headers.append(
                    self.headers.get(context.TRACE_HEADER)
                )
                self.rfile.read(
                    int(self.headers.get("Content-Length", 0))
                )
                try:
                    self.connection.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                self.close_connection = True

            def log_message(self, *args):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        ).start()
        self.port = self.httpd.server_address[1]

    def stop(self):
        self.httpd.shutdown()


def test_redrive_keeps_one_request_id_across_attempts(_tracing):
    srv = _server()
    srv.start()
    httpd = serve_http(srv)
    real_port = httpd.server_address[1]
    crasher = _HeaderRecordingCrasher()
    router = Router(
        replicas={0: f"127.0.0.1:{crasher.port}",
                  1: f"127.0.0.1:{real_port}"},
        config=RouterConfig(poll_s=0.05),
    )
    router.start()
    try:
        deadline = time.monotonic() + 10.0
        while router.live_count() < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert router.live_count() == 2
        # rank 0 (the crasher, load 0) wins the tie-break → attempt 1
        # crashes, the redrive lands on the real replica
        status, body = router.dispatch(
            "score", {"inputs": {"x": [[1.0] * WIDTH]}},
            deadline_s=20.0,
        )
        assert status == 200, body
        assert body["replica"] == 1

        # the wire: the crashed attempt carried a parseable header
        assert len(crasher.trace_headers) == 1
        rid0, run0 = context.parse_trace_header(crasher.trace_headers[0])
        assert rid0 and run0 == context.run_id()

        # the router's ingress span names the SAME id — stable across
        # the redrive because the id IS the idempotency key
        ingress = _spans("router.request")
        assert len(ingress) == 1
        assert ingress[0]["args"]["request_id"] == rid0
        assert ingress[0]["args"]["attempts"] == 2

        # ...and the surviving replica's span joins on it: the very
        # edge `observability merge` uses to stitch the timeline
        served = [
            e for e in _spans("serving.request")
            if e["args"].get("request_id") == rid0
        ]
        assert len(served) == 1
    finally:
        router.stop()
        crasher.stop()
        httpd.shutdown()
        srv.stop(drain=True)


# ---------------------------------------------------------------------------
# the acceptance: 2-process merged timeline, one id across both pids
# ---------------------------------------------------------------------------

# the replica process: serve one scoring endpoint over HTTP with the
# tracer on, write the bound port for the parent, save a shard when the
# parent signals done (file sentinel — the pattern works under any
# start method, unlike signals)
_REPLICA = """
import json, os, sys, time
import tensorframes_tpu as tfs
from tensorframes_tpu.observability import events
from tensorframes_tpu.serving import Server, ServingConfig, serve_http

shard_dir, port_file, done_file = sys.argv[1:4]
events.enable()
schema = tfs.Schema([
    tfs.ColumnInfo("x", tfs.dtypes.float32, tfs.Shape((tfs.Unknown, 4)))
])
holder = type("F", (), {"schema": schema})()
program = tfs.compile_program(
    lambda x: {"y": x * 2.0 + 1.0}, holder, block=False
)
srv = Server(ServingConfig(
    max_batch_rows=8, max_latency_s=0.002, max_queue_rows=128
))
srv.register("score", program)
srv.start()
httpd = serve_http(srv)
with open(port_file + ".tmp", "w") as f:
    f.write(str(httpd.server_address[1]))
os.replace(port_file + ".tmp", port_file)
deadline = time.monotonic() + 60.0
while not os.path.exists(done_file) and time.monotonic() < deadline:
    time.sleep(0.02)
httpd.shutdown()
srv.stop(drain=True)
path = events.save_shard(shard_dir)
print("SHARD", path, flush=True)
"""


@pytest.mark.slow
def test_two_process_trace_merges_with_one_request_id(tmp_path):
    run_id = "tracehop"
    shard_dir = str(tmp_path / "shards")
    os.makedirs(shard_dir)
    port_file = str(tmp_path / "port")
    done_file = str(tmp_path / "done")

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["TFTPU_RUN_ID"] = run_id
    env["TFTPU_PROCESS_INDEX"] = "1"
    proc = subprocess.Popen(
        [sys.executable, "-c", _REPLICA, shard_dir, port_file, done_file],
        env=env, cwd=_REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )

    saved_ctx = (context._run_id, context._process_index,
                 context._num_processes)
    context._reset_for_tests()
    context.bind(run_id=run_id, process_index=0)
    was_enabled = events.TRACER.enabled
    events.clear()
    events.enable()
    router = None
    try:
        deadline = time.monotonic() + 60.0
        while not os.path.exists(port_file):
            assert time.monotonic() < deadline, "replica never came up"
            assert proc.poll() is None, proc.communicate()[1]
            time.sleep(0.02)
        port = int(open(port_file).read())

        router = Router(
            replicas={1: f"127.0.0.1:{port}"},
            config=RouterConfig(poll_s=0.05),
        )
        router.start()
        while router.live_count() < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        status, body = router.dispatch(
            "score", {"inputs": {"x": [[1.0] * WIDTH]}},
            deadline_s=30.0,
        )
        assert status == 200, body
        router.stop()
        router = None

        ingress = _spans("router.request")
        assert len(ingress) == 1
        rid = ingress[0]["args"]["request_id"]
        events.save_shard(shard_dir)

        open(done_file, "w").close()
        out, err = proc.communicate(timeout=60)
        assert proc.returncode == 0, f"stdout: {out}\nstderr: {err}"
        assert "SHARD" in out
    finally:
        if router is not None:
            router.stop()
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
        events.clear()
        if not was_enabled:
            events.disable()
        context._reset_for_tests()
        context.bind(run_id=saved_ctx[0], process_index=saved_ctx[1],
                     num_processes=saved_ctx[2])

    shards = merge.find_shards(shard_dir, run_id=run_id)
    assert len(shards) == 2
    merged = json.loads(json.dumps(merge.merge_traces(shards)))
    evs = merged["traceEvents"]
    assert merged["otherData"]["run_id"] == run_id
    # ONE request id spans both processes: the router's ingress span on
    # pid 0 and the replica's serving spans on pid 1
    ingress = [
        e for e in evs
        if e.get("name") == "router.request"
        and e["args"].get("request_id") == rid
    ]
    served = [
        e for e in evs
        if e.get("name") == "serving.request"
        and e["args"].get("request_id") == rid
    ]
    assert len(ingress) == 1 and ingress[0]["pid"] == 0
    assert len(served) == 1 and served[0]["pid"] == 1
    flushes = [
        e for e in evs
        if e.get("name") == "serving.flush"
        and rid in e["args"].get("request_ids", [])
    ]
    assert len(flushes) == 1 and flushes[0]["pid"] == 1
