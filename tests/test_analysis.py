"""tfguard: the pre-execution static analyzer (ISSUE 3).

Contract under test (docs/analysis.md):

* each rule fires on a seeded-bad fixture program and stays silent on
  the clean example programs;
* the pass is purely static — a lint performs zero XLA compiles and
  zero device transfers (the executor's jit-cache / compile-seconds
  metrics are the witness);
* ``strict=True`` on the verbs raises ``StaticAnalysisError`` on
  error-severity diagnostics, before any dispatch;
* the CLI lints an exported StableHLO bundle end-to-end;
* every diagnostic increments the pre-registered
  ``tftpu_analysis_diagnostics_total{code=}`` counter.
"""

import json
import subprocess
import sys
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu import dtypes as dt
from tensorframes_tpu.analysis import (
    CODES,
    Diagnostic,
    DiagnosticReport,
    analyze_frame,
    lint_program,
    save_jsonl,
)
from tensorframes_tpu.analysis.cli import main as cli_main
from tensorframes_tpu.frame import TensorFrame
from tensorframes_tpu.observability.metrics import REGISTRY
from tensorframes_tpu.program import Program, TensorSpec
from tensorframes_tpu.shape import Shape

REPO = Path(__file__).resolve().parent.parent


def _codes(report):
    return {d.code for d in report}


def _frame(n=16, blocks=2, dtype=np.float32, name="x"):
    return tfs.frame_from_arrays(
        {name: np.arange(n, dtype=dtype) + 1.0}, num_blocks=blocks
    )


@pytest.fixture
def restore_config():
    cfg = tfs.configure()
    saved = {
        k: getattr(cfg, k)
        for k in ("demote_x64_on_tpu", "donate_inputs", "max_bucket_doublings")
    }
    yield
    tfs.configure(**saved)


# ---------------------------------------------------------------------------
# clean programs stay silent
# ---------------------------------------------------------------------------

def test_clean_program_is_clean():
    p = tfs.compile_program(lambda x: {"z": x + 3.0}, _frame())
    report = p.lint()
    assert len(report) == 0
    assert "clean" in report.pretty()


def test_clean_example_programs_stay_silent():
    # the shipped example programs must not regress into findings
    from tensorframes_tpu.models import logreg

    feats, _ = logreg.make_synthetic_mnist(8)
    fr = tfs.frame_from_arrays({"features": feats})
    scoring = logreg.scoring_program(logreg.init_params())
    p = tfs.compile_program(lambda features: scoring(features), fr)
    assert len(p.lint()) == 0


# ---------------------------------------------------------------------------
# TFG101 recompile-storm
# ---------------------------------------------------------------------------

def test_tfg101_inner_unknown_dim_fires():
    spec = TensorSpec("x", dt.float32, Shape([-1, -1]))
    p = Program(lambda feeds: {"y": feeds["x"] * 2.0}, [spec])
    report = lint_program(p)
    [d] = report.by_code("TFG101")
    assert d.severity == "warn"
    assert "bucket table" in d.message
    assert d.subject == "x"


def test_tfg101_silent_when_only_lead_dim_unknown():
    spec = TensorSpec("x", dt.float32, Shape([-1, 8]))
    p = Program(lambda feeds: {"y": feeds["x"] * 2.0}, [spec])
    assert not lint_program(p).by_code("TFG101")


def test_tfg101_bucketing_disabled_fires(restore_config):
    tfs.configure(max_bucket_doublings=0)
    spec = TensorSpec("x", dt.float32, Shape([-1]))
    p = Program(lambda feeds: {"y": feeds["x"] * 2.0}, [spec])
    msgs = [d.message for d in lint_program(p).by_code("TFG101")]
    assert any("bucketing is disabled" in m for m in msgs)


def test_tfg101_block_shape_storm_via_analyze_frame():
    base = _frame(4, blocks=1)
    blocks = [
        {"x": np.arange(n, dtype=np.float32) + 1.0} for n in (1, 2, 4, 8)
    ]
    stormy = TensorFrame(blocks, base.schema)
    report = analyze_frame(stormy, lambda x: {"z": x * 2.0}, block=True)
    [d] = [d for d in report.by_code("TFG101") if d.subject == "frame"]
    assert "4 distinct block row counts" in d.message


def test_tfg101_no_storm_on_partitioner_blocks():
    # the partitioner yields at most two distinct sizes — never a storm
    fr = tfs.frame_from_arrays(
        {"x": np.arange(7, dtype=np.float32)}, num_blocks=3
    )
    fr.blocks()
    report = analyze_frame(fr, lambda x: {"z": x * 2.0}, block=True)
    assert not [d for d in report.by_code("TFG101") if d.subject == "frame"]


# ---------------------------------------------------------------------------
# TFG102 f64-leak
# ---------------------------------------------------------------------------

def test_tfg102_f64_const_under_demotion_fires(restore_config):
    tfs.configure(demote_x64_on_tpu="always")
    fr = tfs.frame_from_arrays({"v": np.arange(4, dtype=np.float64) + 1.0})
    leak = np.float64(2.0)  # the old DSL zeros/ones default, in miniature
    p = tfs.compile_program(lambda v: {"w": v * jnp.asarray(leak)}, fr)
    diags = p.lint().by_code("TFG102")
    assert diags and all(d.severity == "warn" for d in diags)
    assert any("demotion boundary" in d.message for d in diags)


def test_tfg102_info_without_demotion():
    fr = _frame()
    leak = np.float64(2.0)
    p = tfs.compile_program(lambda x: {"w": x * jnp.asarray(leak)}, fr)
    diags = p.lint().by_code("TFG102")
    assert diags and all(d.severity == "info" for d in diags)


def test_tfg102_silent_on_genuine_f64_program():
    fr = tfs.frame_from_arrays({"v": np.arange(4, dtype=np.float64) + 1.0})
    p = tfs.compile_program(lambda v: {"w": v * 2.0}, fr)
    assert not p.lint().by_code("TFG102")


def test_tfg102_seed_fixture_old_dsl_default(restore_config):
    # the seed fixture from the satellite: explicit np.float64 DSL const
    tfs.configure(demote_x64_on_tpu="always")
    fr = tfs.frame_from_arrays({"v": np.float64([1.0, 2.0, 3.0])})
    with tfs.with_graph():
        v = tfs.block(fr, "v")
        fetch = tfs.add(v, tfs.constant(np.float64(1.0)), name="w")
        p = tfs.compile_program(fetch, fr)
    assert p.lint().by_code("TFG102")


# ---------------------------------------------------------------------------
# TFG103 unused-input
# ---------------------------------------------------------------------------

def test_tfg103_unused_input_fires():
    fr = tfs.frame_from_arrays({
        "x": np.arange(8, dtype=np.float32),
        "y": np.arange(8, dtype=np.float32),
    })
    p = tfs.compile_program(lambda x, y: {"z": x + 1.0}, fr)
    [d] = p.lint().by_code("TFG103")
    assert d.subject == "y" and d.severity == "info"
    assert "dead fetch" in d.message


def test_tfg103_silent_when_all_inputs_used():
    fr = tfs.frame_from_arrays({
        "x": np.arange(8, dtype=np.float32),
        "y": np.arange(8, dtype=np.float32),
    })
    p = tfs.compile_program(lambda x, y: {"z": x + y}, fr)
    assert not p.lint().by_code("TFG103")


# ---------------------------------------------------------------------------
# TFG104 donation-alias
# ---------------------------------------------------------------------------

def test_tfg104_error_when_donation_enabled(restore_config):
    tfs.configure(donate_inputs=True)
    p = tfs.compile_program(lambda x: {"x": x * 1.0}, _frame())
    [d] = p.lint().by_code("TFG104")
    assert d.severity == "error"
    assert "donat" in d.message


def test_tfg104_downgrades_to_info_when_donation_off(restore_config):
    tfs.configure(donate_inputs=False)
    p = tfs.compile_program(lambda x: {"x": x * 1.0}, _frame())
    [d] = p.lint().by_code("TFG104")
    assert d.severity == "info"


def test_tfg104_silent_on_renamed_output():
    p = tfs.compile_program(lambda x: {"x_out": x * 1.0}, _frame())
    assert not p.lint().by_code("TFG104")


# ---------------------------------------------------------------------------
# TFG105 nan-hazard
# ---------------------------------------------------------------------------

def test_tfg105_log_of_unproven_operand_fires():
    p = tfs.compile_program(lambda x: {"l": jnp.log(x)}, _frame())
    [d] = p.lint().by_code("TFG105")
    assert "log" in d.subject and d.severity == "warn"
    assert "StepGuard" in d.fix  # ties into resilience.guards


def test_tfg105_silent_when_operand_provably_positive():
    p = tfs.compile_program(
        lambda x: {"l": jnp.log(jnp.exp(x) + 1.0)}, _frame()
    )
    assert not p.lint().by_code("TFG105")


def test_tfg105_division_by_unproven_denominator_fires():
    fr = tfs.frame_from_arrays({
        "x": np.arange(8, dtype=np.float32),
        "y": np.arange(8, dtype=np.float32),
    })
    p = tfs.compile_program(lambda x, y: {"q": x / y}, fr)
    assert p.lint().by_code("TFG105")


def test_tfg105_silent_for_positive_literal_denominator():
    p = tfs.compile_program(lambda x: {"q": x / 2.0}, _frame())
    assert not p.lint().by_code("TFG105")


def test_tfg105_rsqrt_fires_sqrt_of_square_silent():
    fr = _frame()
    p1 = tfs.compile_program(lambda x: {"r": jax_rsqrt(x)}, fr)
    assert p1.lint().by_code("TFG105")
    p2 = tfs.compile_program(lambda x: {"s": jnp.sqrt(jnp.square(x))}, fr)
    assert not p2.lint().by_code("TFG105")


def jax_rsqrt(x):
    from jax import lax

    return lax.rsqrt(x)


def test_tfg105_concatenate_meets_operand_signs():
    # concat of a positive and an unknown-sign part is NOT positive: the
    # log hazard must still fire (review finding: ins[0]-only was unsound)
    p = tfs.compile_program(
        lambda x: {"l": jnp.log(jnp.concatenate([jnp.exp(x), x]))}, _frame()
    )
    assert p.lint().by_code("TFG105")
    # all-positive parts stay positive: silent
    p2 = tfs.compile_program(
        lambda x: {"l": jnp.log(jnp.concatenate([jnp.exp(x), jnp.exp(x)]))},
        _frame(),
    )
    assert not p2.lint().by_code("TFG105")


def test_tfg105_negative_literal_denominator_is_nonzero_safe():
    # -2.0 is not positive but IS provably nonzero: no div hazard
    p = tfs.compile_program(lambda x: {"q": x / -2.0}, _frame())
    assert not p.lint().by_code("TFG105")


def test_strict_reaches_pandas_path(restore_config):
    pd = pytest.importorskip("pandas")
    tfs.configure(donate_inputs=True)
    pdf = pd.DataFrame({"x": np.arange(4.0, dtype=np.float64)})
    # warn-only program: strict admits it through the pandas interop
    out = tfs.map_blocks(lambda x: {"z": x + 1.0}, pdf, strict=True)
    assert "z" in out.columns


def test_tfg105_softmax_denominator_is_not_flagged():
    # sum(exp(x)) over a concrete non-empty axis is provably positive —
    # the logreg scoring softmax must stay clean
    p = tfs.compile_program(
        lambda x: {"s": jnp.exp(x) / jnp.sum(jnp.exp(x))}, _frame()
    )
    assert not p.lint().by_code("TFG105")


# ---------------------------------------------------------------------------
# TFG106 hbm-budget
# ---------------------------------------------------------------------------

def test_tfg106_fires_against_tiny_budget():
    p = tfs.compile_program(lambda x: {"z": x + 3.0}, _frame())
    [d] = p.lint(hbm_budget_bytes=10).by_code("TFG106")
    assert "exceeds the device budget" in d.message
    assert d.severity == "warn"


def test_tfg106_silent_under_roomy_budget():
    p = tfs.compile_program(lambda x: {"z": x + 3.0}, _frame())
    assert not p.lint(hbm_budget_bytes=1 << 30).by_code("TFG106")


def test_tfg106_uses_memoized_cost_analysis_without_compiling():
    p = tfs.compile_program(lambda x: {"z": x + 3.0}, _frame())
    p.cost_analysis(probe=8)  # deliberate AOT compile, OUTSIDE the lint
    [d] = p.lint(hbm_budget_bytes=10).by_code("TFG106")
    assert "cost model" in d.message


# ---------------------------------------------------------------------------
# TFG108 cache-fingerprint-unstable
# ---------------------------------------------------------------------------

def test_tfg108_fires_on_nondeterministic_capture():
    # np.random without a seed runs at TRACE time: every rebuild bakes
    # a different constant into the jaxpr → the persistent compile
    # cache would miss on every process start
    p = tfs.compile_program(lambda x: {"y": x + np.random.rand()}, _frame())
    [d] = p.lint().by_code("TFG108")
    assert d.severity == "warn"
    assert "miss storm" in d.message
    assert "seed" in d.explain()


def test_tfg108_silent_on_deterministic_program():
    w = np.arange(3.0)
    p = tfs.compile_program(
        lambda x: {"y": x[:, None] * w[None, :] + 2.0}, _frame()
    )
    assert not p.lint().by_code("TFG108")


def test_tfg108_silent_on_seeded_random_capture():
    # random captures built OUTSIDE the traced fn (or from a seeded
    # RNG inside it) are a fixed constant on every rebuild: stable
    c = np.random.default_rng(42).standard_normal(3)
    p = tfs.compile_program(
        lambda x: {"y": x[:, None] + c[None, :]}, _frame()
    )
    assert not p.lint().by_code("TFG108")


def test_tfg108_sharded_frame_lints_under_mesh_without_dispatch():
    """ISSUE 10: a sharded frame's programs lint under the frame's mesh
    context — sharding constraints/collectives trace exactly as the
    executor dispatches them — and the two-trace stability probe stays
    purely static (the executor's jit metrics are the witness: zero
    compiles, zero dispatches)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tensorframes_tpu.ops.executor import (
        _COMPILE_SECONDS,
        _JIT_HITS,
        _JIT_MISSES,
    )
    from tensorframes_tpu.parallel import device_count

    if device_count() < 8:
        pytest.skip("needs 8 (virtual) devices")
    fr = tfs.frame_from_arrays(
        {"x": np.arange(64, dtype=np.float32)}
    ).to_device()
    assert fr.is_sharded
    mesh = fr.mesh

    def fn(x):
        y = jax.lax.with_sharding_constraint(
            x * 2.0, NamedSharding(mesh, P("dp"))
        )
        return {"y": y}

    before = (_JIT_HITS.value, _JIT_MISSES.value, _COMPILE_SECONDS.count)
    report = analyze_frame(fr, fn)
    after = (_JIT_HITS.value, _JIT_MISSES.value, _COMPILE_SECONDS.count)
    assert before == after, "sharded lint must not touch the jit path"
    # deterministic sharding annotations are stable across rebuilds
    assert not report.by_code("TFG108")


def test_tfg108_names_the_unstable_sharding_axis():
    """A sharding annotation whose axis flips between rebuilds keys a
    different fingerprint every process start (the layout axes joined
    the store key with the unified AOT dispatch): TFG108 must fire and
    the explain() must NAME the unstable axis, not report an opaque
    hash mismatch."""
    import itertools

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tensorframes_tpu.parallel import device_count, make_mesh

    if device_count() < 8:
        pytest.skip("needs 8 (virtual) devices")
    mesh = make_mesh({"dp": 2, "tp": 4})
    flip = itertools.cycle(["dp", "tp"])

    def fn(x):
        # axis picked from mutating state at TRACE time: every rebuild
        # constrains to a different mesh axis — the seeded instability
        y = jax.lax.with_sharding_constraint(
            x + 1.0, NamedSharding(mesh, P(next(flip)))
        )
        return {"y": y}

    fr = tfs.frame_from_arrays({"x": np.arange(64, dtype=np.float32)},
                               num_blocks=1)
    p = tfs.compile_program(fn, fr)
    [d] = lint_program(p, mesh=mesh).by_code("TFG108")
    assert d.severity == "warn"
    assert "jaxpr" in d.message  # the component that moved is named
    assert "unstable axis: dp/tp" in d.message
    assert "sharding" in d.explain()  # fix names the sharding practice


def test_tfg108_sharded_unstable_capture_still_caught():
    """The classic unseeded-capture miss storm is caught on sharded
    programs too — probed under the mesh with the input shardings in
    the probed key, exactly as the store fingerprints dispatches."""
    from tensorframes_tpu.parallel import batch_sharding, device_count, make_mesh

    if device_count() < 8:
        pytest.skip("needs 8 (virtual) devices")
    mesh = make_mesh()
    fr = tfs.frame_from_arrays({"x": np.arange(64, dtype=np.float32)},
                               num_blocks=1)
    p = tfs.compile_program(lambda x: {"y": x + np.random.rand()}, fr)
    sh = {"x": batch_sharding(mesh, 1)}
    [d] = lint_program(p, mesh=mesh, shardings=sh).by_code("TFG108")
    assert "miss storm" in d.message
    # the moved component is named (an inline scalar capture lands in
    # the jaxpr text itself)
    assert "unstable component(s): jaxpr" in d.message


# ---------------------------------------------------------------------------
# purity: a lint performs zero XLA compiles and zero device transfers
# ---------------------------------------------------------------------------

def test_lint_is_purely_static():
    from tensorframes_tpu.ops.executor import (
        _COMPILE_SECONDS,
        _JIT_HITS,
        _JIT_MISSES,
    )

    fr = tfs.frame_from_arrays({
        "x": np.arange(8, dtype=np.float32),
        "y": np.arange(8, dtype=np.float32),
    })
    programs = [
        tfs.compile_program(lambda x: {"l": jnp.log(x)}, fr),
        tfs.compile_program(lambda x, y: {"z": x + 1.0}, fr),
        tfs.compile_program(lambda x: {"x": x * 1.0}, fr),
    ]
    before = (_JIT_HITS.value, _JIT_MISSES.value, _COMPILE_SECONDS.count)
    for p in programs:
        p.lint(hbm_budget_bytes=1 << 30)
    analyze_frame(fr, lambda x: {"z": x * 2.0})
    after = (_JIT_HITS.value, _JIT_MISSES.value, _COMPILE_SECONDS.count)
    assert before == after, "lint must not touch the executor's jit path"


# ---------------------------------------------------------------------------
# strict= on the verbs
# ---------------------------------------------------------------------------

def test_strict_raises_on_error_severity(restore_config):
    tfs.configure(donate_inputs=True)
    fr = _frame()
    p = tfs.compile_program(lambda x: {"x": x * 1.0}, fr)
    with pytest.raises(tfs.StaticAnalysisError) as ei:
        tfs.map_blocks(p, fr, trim=True, strict=True)
    assert ei.value.diagnostics
    assert ei.value.diagnostics[0].code == "TFG104"
    assert isinstance(ei.value, tfs.ValidationError)  # error-family contract


def test_strict_off_does_not_raise(restore_config):
    tfs.configure(donate_inputs=True)
    fr = _frame()
    p = tfs.compile_program(lambda x: {"x": x * 1.0}, fr)
    out = tfs.map_blocks(p, fr, trim=True).blocks()
    assert len(out) >= 1


def test_strict_clean_program_executes(restore_config):
    fr = _frame(8, blocks=1)
    out = tfs.map_blocks(lambda x: {"z": x + 3.0}, fr, strict=True)
    np.testing.assert_allclose(
        out.column_values("z"), np.arange(8, dtype=np.float32) + 4.0
    )


def test_strict_warn_only_does_not_raise():
    fr = _frame(8, blocks=1)
    # log hazard is warn-severity: strict admits it (strict raises on error)
    out = tfs.map_rows(lambda x: {"l": jnp.log(x)}, fr, strict=True)
    assert out.column_values("l").shape == (8,)


def test_strict_on_fluent_forms(restore_config):
    tfs.configure(donate_inputs=True)
    fr = _frame()
    p = tfs.compile_program(lambda x: {"x": x * 1.0}, fr)
    with pytest.raises(tfs.StaticAnalysisError):
        fr.map_blocks_trimmed(p, strict=True)


# ---------------------------------------------------------------------------
# reporting / telemetry surfaces
# ---------------------------------------------------------------------------

def test_diagnostic_explain_carries_fix_and_catalog_pointer():
    p = tfs.compile_program(lambda x: {"l": jnp.log(x)}, _frame())
    [d] = p.lint().by_code("TFG105")
    text = d.explain()
    assert "fix:" in text and "docs/analysis.md#tfg105" in text


def test_report_ordering_and_counts(restore_config):
    tfs.configure(donate_inputs=True)
    fr = tfs.frame_from_arrays({
        "x": np.arange(8, dtype=np.float32),
        "y": np.arange(8, dtype=np.float32),
    })
    p = tfs.compile_program(lambda x, y: {"x": jnp.log(x)}, fr)
    report = p.lint()
    codes = [d.code for d in report]
    assert codes[0] == "TFG104"  # errors sort first
    counts = report.counts_by_severity()
    assert counts["error"] == 1 and counts["info"] == 1
    assert counts["warn"] >= 1


def test_report_jsonl_round_trip(tmp_path):
    p = tfs.compile_program(lambda x: {"l": jnp.log(x)}, _frame())
    report = p.lint()
    rows = [json.loads(ln) for ln in report.to_jsonl().splitlines()]
    assert any(r["code"] == "TFG105" for r in rows)
    out = tmp_path / "diag.jsonl"
    n = save_jsonl(str(out))
    assert n >= 1 and out.stat().st_size > 0


def test_metrics_counter_increments_by_code():
    def counter_value(code):
        for m in REGISTRY.collect():
            if m.name == "tftpu_analysis_diagnostics_total" and \
                    dict(m.labels).get("code") == code:
                return m.value
        raise AssertionError("counter family missing")

    before = counter_value("TFG103")
    fr = tfs.frame_from_arrays({
        "x": np.arange(8, dtype=np.float32),
        "y": np.arange(8, dtype=np.float32),
    })
    tfs.compile_program(lambda x, y: {"z": x + 1.0}, fr).lint()
    assert counter_value("TFG103") == before + 1


def test_full_code_catalog_preregistered_in_exposition():
    expo = REGISTRY.to_prometheus()
    for code in CODES:
        assert f'code="{code}"' in expo, f"{code} series missing at zero"


def test_invalid_code_and_severity_rejected():
    with pytest.raises(ValueError, match="unknown diagnostic code"):
        Diagnostic("TFG999", "warn", "nope")
    with pytest.raises(ValueError, match="unknown severity"):
        Diagnostic("TFG101", "fatal", "nope")


def test_analyze_frame_on_dsl_fetches():
    fr = _frame(8, blocks=1)
    with tfs.with_graph():
        x = tfs.block(fr, "x")
        fetch = tfs.log(x, name="lx")
    report = analyze_frame(fr, [fetch])
    assert "TFG105" in _codes(report)


def test_analyze_frame_never_forces_a_lazy_frame():
    fr = _frame(8, blocks=1)
    lazy = tfs.map_blocks(lambda x: {"z": x + 1.0}, fr)  # pending compute
    assert not lazy.is_materialized
    analyze_frame(lazy, lambda z: {"w": z * 2.0})
    assert not lazy.is_materialized


# ---------------------------------------------------------------------------
# CLI: StableHLO bundles end-to-end
# ---------------------------------------------------------------------------

def test_cli_lints_exported_bundle(tmp_path, capsys):
    fr = _frame(8, blocks=1)
    p = tfs.compile_program(lambda x: {"z": x + 3.0}, fr)
    bundle = tmp_path / "add3.stablehlo"
    tfs.save_program(p, str(bundle))
    rc = cli_main([str(bundle)])
    out = capsys.readouterr().out
    assert rc == 0 and "clean" in out and str(bundle) in out


def test_cli_strict_exit_code_on_error_bundle(tmp_path, capsys, restore_config):
    tfs.configure(donate_inputs=True)
    fr = _frame(8, blocks=1)
    p = tfs.compile_program(lambda x: {"x": x * 1.0}, fr)  # donation alias
    bundle = tmp_path / "alias.stablehlo"
    tfs.save_program(p, str(bundle))
    assert cli_main([str(bundle)]) == 0  # non-strict: report only
    capsys.readouterr()
    assert cli_main(["--strict", str(bundle)]) == 1
    assert "TFG104" in capsys.readouterr().out


def test_cli_json_output(tmp_path, capsys):
    fr = _frame(8, blocks=1)
    p = tfs.compile_program(lambda x: {"z": x + 3.0}, fr)
    bundle = tmp_path / "add3.stablehlo"
    tfs.save_program(p, str(bundle))
    assert cli_main(["--json", str(bundle)]) == 0
    payload = json.loads(capsys.readouterr().out.strip())
    assert payload["counts"] == {"error": 0, "warn": 0, "info": 0}


def test_cli_unreadable_bundle_exit_2(tmp_path, capsys):
    bogus = tmp_path / "bogus.stablehlo"
    bogus.write_bytes(b"not a bundle")
    assert cli_main([str(bogus)]) == 2


# ---------------------------------------------------------------------------
# DSL dtype-policy satellite
# ---------------------------------------------------------------------------

def test_dsl_zeros_ones_follow_float_policy_default():
    # x64 on, demotion off (the suite default): policy is float64 —
    # reference-parity programs unchanged
    with tfs.with_graph():
        assert tfs.zeros((2,)).dtype is dt.float64
        assert tfs.ones((2,)).dtype is dt.float64
        assert tfs.fill((2,), 1.5).dtype is dt.float64


def test_dsl_zeros_ones_fill_demoted_policy(restore_config):
    tfs.configure(demote_x64_on_tpu="always")
    with tfs.with_graph():
        assert tfs.zeros((2,)).dtype is dt.float32
        assert tfs.ones((2,)).dtype is dt.float32
        assert tfs.fill((2,), 1.5).dtype is dt.float32
        # explicit dtype still wins (the documented escape hatch)
        assert tfs.zeros((2,), dtype=np.float64).dtype is dt.float64
        # int fills keep frame inference (int64), not the float policy
        assert tfs.fill((2,), 3).dtype is dt.int64


def test_dsl_constant_dtype_override():
    with tfs.with_graph():
        node = tfs.constant([1.0, 2.0], dtype=np.float32)
        assert node.dtype is dt.float32


# ---------------------------------------------------------------------------
# repo self-lint (dev/lint_rules.py) — the CI lint job's second leg
# ---------------------------------------------------------------------------

def test_repo_self_lint_is_green():
    proc = subprocess.run(
        [sys.executable, str(REPO / "dev" / "lint_rules.py")],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_lint_rules_catches_seeded_violations(tmp_path):
    bad = tmp_path / "tensorframes_tpu" / "bad.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import jax\n"
        "from tensorframes_tpu.observability.metrics import counter\n"
        "_cache = {}\n"
        "def f(x):\n"
        "    _cache[x] = jax.jit(lambda v: v)\n"
        "    return counter('late_metric')\n"
    )
    proc = subprocess.run(
        [sys.executable, str(REPO / "dev" / "lint_rules.py"), str(bad)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 1
    assert "TFL001" in proc.stdout  # bare jax.jit
    assert "TFL002" in proc.stdout  # unguarded module state
    assert "TFL003" in proc.stdout  # late metric registration
