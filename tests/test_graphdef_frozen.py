"""Frozen convolutional graphs through the GraphDef importer.

The reference's headline workload (BASELINE config 4) is Inception-v3
*frozen-graph* batch inference: a serialized ``GraphDef`` from any TF
program scored over a frame (PythonInterface.scala:115-118). This file
freezes a real keras Inception-v3 (random weights — no downloads) with
TensorFlow, decodes the ~2200-node graph with the bundled clean-room
parser, lowers it to jax (Conv2D/pool/concat/batchnorm-decomposition
ops), executes through ``map_blocks``, and cross-checks against TF
running the very same frozen bytes — the ExtractNodes-style golden
oracle at full-model scale."""

import os

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.graphdef import parse_graphdef, program_from_graphdef

tf = pytest.importorskip("tensorflow")


@pytest.fixture(scope="module")
def frozen_inception():
    """Full-depth keras InceptionV3 at 75x75 input (the minimum), frozen
    to a constant GraphDef."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    tf.keras.utils.set_random_seed(0)
    model = tf.keras.applications.InceptionV3(
        weights=None, input_shape=(75, 75, 3)
    )
    fn = tf.function(lambda x: model(x, training=False))
    cf = fn.get_concrete_function(
        tf.TensorSpec([None, 75, 75, 3], tf.float32)
    )
    frozen = convert_variables_to_constants_v2(cf)
    return frozen.graph.as_graph_def().SerializeToString()


def test_frozen_inception_v3_matches_tf(frozen_inception):
    nodes = parse_graphdef(frozen_inception)
    assert len(nodes) > 2000  # full-depth model, not a toy
    prog = program_from_graphdef(nodes, relax_lead_dim=True)
    [inp] = prog.inputs
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 75, 75, 3)).astype(np.float32)

    # golden: TF executes the same frozen bytes
    gd = tf.compat.v1.GraphDef()
    gd.ParseFromString(frozen_inception)
    with tf.Graph().as_default() as g:
        tf.import_graph_def(gd, name="")
        with tf.compat.v1.Session(graph=g) as sess:
            want = sess.run(
                f"{prog.fetch_order[0]}:0", {f"{inp.name}:0": x}
            )

    # verb-level: score the frame through map_blocks
    frame = tfs.frame_from_arrays({inp.name: x}, num_blocks=1)
    out = tfs.map_blocks(prog, frame)
    got = np.asarray(out.column_values(prog.fetch_order[0]))
    assert got.shape == (2, 1000)
    np.testing.assert_allclose(got, want, atol=1e-5)
    assert (got.argmax(1) == want.argmax(1)).all()


@pytest.mark.parametrize(
    "ctor_name,shape",
    [
        ("MobileNetV2", (96, 96, 3)),
        ("ResNet50", (64, 64, 3)),
        ("EfficientNetB0", (64, 64, 3)),
    ],
)
def test_frozen_model_zoo_matches_tf(ctor_name, shape):
    """Importer generality across frozen keras families: MobileNetV2
    (depthwise convs, Relu6, residual AddV2, Pad), ResNet50 (strided
    convs, MaxPool, Pad, Squeeze), and EfficientNetB0 (SE blocks:
    swish Sigmoid·Mul, Mean-keepdims, IdentityN) — golden-compared
    against TF."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    tf.keras.utils.set_random_seed(3)
    model = getattr(tf.keras.applications, ctor_name)(
        weights=None, input_shape=shape
    )
    fn = tf.function(lambda x: model(x, training=False))
    cf = fn.get_concrete_function(tf.TensorSpec([None, *shape], tf.float32))
    data = convert_variables_to_constants_v2(cf).graph.as_graph_def(
    ).SerializeToString()

    prog = program_from_graphdef(parse_graphdef(data), relax_lead_dim=True)
    [inp] = prog.inputs
    rng = np.random.default_rng(4)
    x = rng.standard_normal((2, *shape)).astype(np.float32)
    got = np.asarray(prog.fn({inp.name: x})[prog.fetch_order[0]])

    gd = tf.compat.v1.GraphDef()
    gd.ParseFromString(data)
    with tf.Graph().as_default() as g:
        tf.import_graph_def(gd, name="")
        with tf.compat.v1.Session(graph=g) as sess:
            want = sess.run(f"{prog.fetch_order[0]}:0", {f"{inp.name}:0": x})
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_frozen_graph_scores_sharded_frame():
    """An imported frozen graph runs over a SHARDED frame like any other
    program — device plan, batch dim split over the mesh."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    tf.keras.utils.set_random_seed(5)
    model = tf.keras.Sequential(
        [
            tf.keras.layers.Input((8, 8, 3)),
            tf.keras.layers.Conv2D(4, 3, padding="same", activation="relu"),
            tf.keras.layers.GlobalAveragePooling2D(),
            tf.keras.layers.Dense(3),
        ]
    )
    fn = tf.function(lambda x: model(x, training=False))
    cf = fn.get_concrete_function(tf.TensorSpec([None, 8, 8, 3], tf.float32))
    data = convert_variables_to_constants_v2(cf).graph.as_graph_def(
    ).SerializeToString()
    prog = program_from_graphdef(parse_graphdef(data), relax_lead_dim=True)
    [inp] = prog.inputs
    rng = np.random.default_rng(6)
    x = rng.standard_normal((64, 8, 8, 3)).astype(np.float32)

    host = tfs.frame_from_arrays({inp.name: x})
    dev = host.to_device()
    assert dev.is_sharded
    out_h = np.asarray(
        tfs.map_blocks(prog, host).column_values(prog.fetch_order[0])
    )
    out_d = np.asarray(
        tfs.map_blocks(prog, dev).column_values(prog.fetch_order[0])
    )
    np.testing.assert_allclose(out_d, out_h, atol=1e-5)


def test_frozen_small_cnn_with_pools_matches_tf():
    """A compact CNN covering the conv-op family the big model misses:
    DepthwiseConv2d, MaxPool+AvgPool both paddings, BiasAdd, Relu6."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    tf.keras.utils.set_random_seed(1)
    model = tf.keras.Sequential(
        [
            tf.keras.layers.Conv2D(
                8, 3, strides=2, padding="same", input_shape=(16, 16, 3)
            ),
            tf.keras.layers.ReLU(max_value=6.0),
            tf.keras.layers.DepthwiseConv2D(3, padding="valid"),
            tf.keras.layers.MaxPool2D(2, padding="same"),
            tf.keras.layers.AveragePooling2D(2, 1, padding="same"),
            tf.keras.layers.Flatten(),
            tf.keras.layers.Dense(5),
        ]
    )
    fn = tf.function(lambda x: model(x, training=False))
    cf = fn.get_concrete_function(tf.TensorSpec([2, 16, 16, 3], tf.float32))
    frozen = convert_variables_to_constants_v2(cf)
    data = frozen.graph.as_graph_def().SerializeToString()

    rng = np.random.default_rng(2)
    x = rng.standard_normal((2, 16, 16, 3)).astype(np.float32)
    prog = program_from_graphdef(parse_graphdef(data))
    [inp] = prog.inputs
    got = np.asarray(prog.fn({inp.name: x})[prog.fetch_order[0]])

    gd = tf.compat.v1.GraphDef()
    gd.ParseFromString(data)
    with tf.Graph().as_default() as g:
        tf.import_graph_def(gd, name="")
        with tf.compat.v1.Session(graph=g) as sess:
            want = sess.run(f"{prog.fetch_order[0]}:0", {f"{inp.name}:0": x})
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_load_saved_model_roundtrip(tmp_path):
    """SavedModel → frozen signature → importer → matches the live keras
    model (tensorflow used only at conversion time)."""
    tf.keras.utils.set_random_seed(7)
    model = tf.keras.Sequential(
        [
            tf.keras.layers.Input((6,)),
            tf.keras.layers.Dense(4, activation="relu"),
            tf.keras.layers.Dense(2),
        ]
    )
    sm_dir = str(tmp_path / "sm")
    tf.saved_model.save(model, sm_dir)
    prog = tfs.load_saved_model(sm_dir, relax_lead_dim=True)
    [inp] = prog.inputs
    rng = np.random.default_rng(8)
    x = rng.standard_normal((5, 6)).astype(np.float32)
    got = np.asarray(prog.fn({inp.name: x})[prog.fetch_order[0]])
    want = model(x, training=False).numpy()
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_load_saved_model_unknown_signature(tmp_path):
    tf.keras.utils.set_random_seed(9)
    model = tf.keras.Sequential(
        [tf.keras.layers.Input((3,)), tf.keras.layers.Dense(1)]
    )
    sm_dir = str(tmp_path / "sm2")
    tf.saved_model.save(model, sm_dir)
    with pytest.raises(KeyError, match="serving_default|available"):
        tfs.load_saved_model(sm_dir, signature="nope")


def test_quantized_import_close_to_f32(tmp_path):
    """quantize_weights=True stores conv/dense filters as per-channel
    int8; outputs stay close to the f32 import and the weight consts
    actually shrink."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    from tensorframes_tpu.graphdef import load_graphdef

    tf.keras.utils.set_random_seed(11)
    model = tf.keras.Sequential(
        [
            tf.keras.layers.Input((12, 12, 3)),
            tf.keras.layers.Conv2D(8, 3, padding="same", activation="relu"),
            tf.keras.layers.GlobalAveragePooling2D(),
            tf.keras.layers.Dense(4),
        ]
    )
    fn = tf.function(lambda x: model(x, training=False))
    cf = fn.get_concrete_function(tf.TensorSpec([None, 12, 12, 3], tf.float32))
    data = convert_variables_to_constants_v2(cf).graph.as_graph_def(
    ).SerializeToString()
    p = tmp_path / "m.pb"
    p.write_bytes(data)

    full = tfs.load_graphdef(str(p), relax_lead_dim=True)
    quant = load_graphdef(str(p), relax_lead_dim=True, quantize_weights=True)
    rng = np.random.default_rng(12)
    x = rng.standard_normal((4, 12, 12, 3)).astype(np.float32)
    [inp] = full.inputs
    out_f = np.asarray(full.fn({inp.name: x})[full.fetch_order[0]])
    out_q = np.asarray(quant.fn({inp.name: x})[quant.fetch_order[0]])
    # int8 weight error is small but nonzero
    assert not np.array_equal(out_f, out_q)
    np.testing.assert_allclose(out_q, out_f, atol=0.05, rtol=0.1)


def test_imported_graph_exports_to_stablehlo(tmp_path):
    """Conversion pipeline: frozen TF GraphDef → Program → StableHLO
    artifact (save_program/jax.export) → reload → same results. The
    artifact needs neither TF nor the original graph — the TF-to-TPU
    redistribution story in one round-trip."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    tf.keras.utils.set_random_seed(13)
    model = tf.keras.Sequential(
        [
            tf.keras.layers.Input((10, 10, 3)),
            tf.keras.layers.Conv2D(6, 3, padding="same", activation="relu"),
            tf.keras.layers.GlobalAveragePooling2D(),
            tf.keras.layers.Dense(4),
        ]
    )
    fn = tf.function(lambda x: model(x, training=False))
    cf = fn.get_concrete_function(tf.TensorSpec([None, 10, 10, 3], tf.float32))
    data = convert_variables_to_constants_v2(cf).graph.as_graph_def(
    ).SerializeToString()
    p = tmp_path / "m.pb"
    p.write_bytes(data)

    prog = tfs.load_graphdef(str(p), relax_lead_dim=True)
    art = str(tmp_path / "m.stablehlo")
    tfs.save_program(prog, art)
    back = tfs.load_program(art)

    rng = np.random.default_rng(14)
    for n in (3, 7):  # symbolic batch dim survives the round-trip
        x = rng.standard_normal((n, 10, 10, 3)).astype(np.float32)
        [inp] = prog.inputs
        want = np.asarray(prog.fn({inp.name: x})[prog.fetch_order[0]])
        got = np.asarray(back.fn({inp.name: x})[prog.fetch_order[0]])
        np.testing.assert_allclose(got, want, atol=1e-6)


def test_fused_batch_norm_inference_matches_tf():
    """TF1-era frozen graphs keep FusedBatchNorm un-decomposed; the
    inference lowering must match TF (the published Inception frozen
    checkpoints are exactly this shape)."""
    g = tf.Graph()
    with g.as_default():
        x = tf.compat.v1.placeholder(tf.float32, [None, 5, 5, 4], name="x")
        rng = np.random.default_rng(20)
        scale = tf.constant(rng.uniform(0.5, 2.0, 4).astype(np.float32))
        offset = tf.constant(rng.normal(size=4).astype(np.float32))
        mean = tf.constant(rng.normal(size=4).astype(np.float32))
        var = tf.constant(rng.uniform(0.2, 3.0, 4).astype(np.float32))
        y, _, _ = tf.compat.v1.nn.fused_batch_norm(
            x, scale, offset, mean=mean, variance=var,
            epsilon=1e-3, is_training=False,
        )
        tf.identity(y, name="out")
    data = g.as_graph_def().SerializeToString()
    xv = np.random.default_rng(21).standard_normal((3, 5, 5, 4)).astype(
        np.float32
    )
    prog = program_from_graphdef(parse_graphdef(data), fetches=["out"])
    got = np.asarray(prog.fn({"x": xv})["out"])
    with tf.compat.v1.Session(graph=g) as sess:
        want = sess.run("out:0", {"x:0": xv})
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_secondary_outputs_rejected():
    """Consuming a multi-output node's :1/:2 (FusedBatchNorm batch
    stats) must raise at import — the evaluator is single-output and
    would silently substitute :0."""
    g = tf.Graph()
    with g.as_default():
        x = tf.compat.v1.placeholder(tf.float32, [None, 4, 4, 2], name="x")
        c = tf.constant(np.ones(2, np.float32))
        y, bm, _ = tf.compat.v1.nn.fused_batch_norm(
            x, c, c, mean=c, variance=c, is_training=False
        )
        tf.identity(bm, name="stats")  # consumes output :1
    data = g.as_graph_def().SerializeToString()
    with pytest.raises(ValueError, match="output"):
        program_from_graphdef(parse_graphdef(data), fetches=["stats"])


def test_load_saved_model_quantize_weights(tmp_path):
    """ADVICE r2: quantize_weights reaches the SavedModel loader too (API
    symmetry with load_graphdef) — int8 per-channel weights, scoring
    close to the float model."""
    tf.keras.utils.set_random_seed(11)
    model = tf.keras.Sequential(
        [
            tf.keras.layers.Input((6,)),
            tf.keras.layers.Dense(8, activation="relu"),
            tf.keras.layers.Dense(3),
        ]
    )
    sm_dir = str(tmp_path / "smq")
    tf.saved_model.save(model, sm_dir)
    prog = tfs.load_saved_model(sm_dir, relax_lead_dim=True, quantize_weights=True)
    [inp] = prog.inputs
    rng = np.random.default_rng(12)
    x = rng.standard_normal((5, 6)).astype(np.float32)
    got = np.asarray(prog.fn({inp.name: x})[prog.fetch_order[0]])
    want = model(x, training=False).numpy()
    # int8 per-channel quantization: close, not bit-equal
    np.testing.assert_allclose(got, want, rtol=0.1, atol=0.1)


def test_quantized_import_shrinks_weight_bytes(tmp_path):
    """VERDICT r2 #7: the int8 story as a NUMBER before TPU counters can
    validate it. The environment-independent measurement is the
    program's true weight residency — ``HoistedProgram.const_bytes()``
    sums the hoisted constant leaves, which for the quantized import are
    int8 ``q`` + per-channel f32 scales. A weight-dominated model must
    shrink ~4x. (The XLA *cost-model* bytes-accessed ratio is emitted by
    bench.py's ``# int8 |`` row on the TPU backend — the CPU compiler's
    fusion of the constant dequantize proved to depend on process-boot
    details, so a unit test cannot pin it.)"""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    from tensorframes_tpu.program import HoistedProgram

    tf.keras.utils.set_random_seed(21)
    model = tf.keras.Sequential(
        [
            tf.keras.layers.Input((512,)),
            tf.keras.layers.Dense(2048, activation="relu"),
            tf.keras.layers.Dense(2048, activation="relu"),
            tf.keras.layers.Dense(512),
        ]
    )
    fn = tf.function(lambda x: model(x, training=False))
    cf = fn.get_concrete_function(tf.TensorSpec([None, 512], tf.float32))
    data = convert_variables_to_constants_v2(cf).graph.as_graph_def(
    ).SerializeToString()
    p = tmp_path / "dense.pb"
    p.write_bytes(data)

    import jax

    def const_bytes(prog):
        [inp] = prog.inputs
        abstract = {
            inp.name: jax.ShapeDtypeStruct((2, 512), np.float32)
        }
        return HoistedProgram(prog.fn, abstract).const_bytes()

    full = tfs.load_graphdef(str(p), relax_lead_dim=True)
    quant = tfs.load_graphdef(str(p), relax_lead_dim=True,
                              quantize_weights=True)
    bf, bq = const_bytes(full), const_bytes(quant)
    assert bf > 4_000_000  # ~5.2M params f32: weights dominate
    # int8 q + f32 per-channel scales: ~4x smaller; >=3x leaves slack
    # for the scales and non-filter constants
    assert bf / bq >= 3.0, f"f32={bf}B int8={bq}B ratio={bf/bq:.2f}"


def test_compute_dtype_bf16_close_to_f32(tmp_path):
    """``compute_dtype="bfloat16"``: MXU ops contract in bf16 with f32
    accumulation — outputs stay f32 and within bf16 rounding of the
    exact import; composes with ``quantize_weights``. The idiomatic TPU
    serving mode for imported graphs (the default stays f32-faithful)."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    tf.keras.utils.set_random_seed(3)
    model = tf.keras.Sequential(
        [
            tf.keras.layers.Input((12, 12, 3)),
            tf.keras.layers.Conv2D(8, 3, padding="same", activation="relu"),
            tf.keras.layers.DepthwiseConv2D(3, padding="same"),
            tf.keras.layers.GlobalAveragePooling2D(),
            tf.keras.layers.Dense(4),
        ]
    )
    fn = tf.function(lambda x: model(x, training=False))
    cf = fn.get_concrete_function(tf.TensorSpec([None, 12, 12, 3], tf.float32))
    p = tmp_path / "cd.pb"
    p.write_bytes(
        convert_variables_to_constants_v2(cf).graph.as_graph_def(
        ).SerializeToString()
    )
    rng = np.random.default_rng(4)
    x = rng.standard_normal((5, 12, 12, 3)).astype(np.float32)
    want = model(x, training=False).numpy()

    bf16 = tfs.load_graphdef(str(p), relax_lead_dim=True,
                             compute_dtype="bfloat16")
    got = np.asarray(bf16.fn({bf16.inputs[0].name: x})[bf16.fetch_order[0]])
    assert got.dtype == np.float32  # accumulation/output stay f32
    assert not np.array_equal(got, want)  # genuinely reduced precision
    np.testing.assert_allclose(got, want, atol=5e-3, rtol=5e-2)

    both = tfs.load_graphdef(str(p), relax_lead_dim=True,
                             quantize_weights=True, compute_dtype="bfloat16")
    got2 = np.asarray(both.fn({both.inputs[0].name: x})[both.fetch_order[0]])
    np.testing.assert_allclose(got2, want, atol=2e-2, rtol=0.1)


def test_frozen_keras_transformer_matches_tf():
    """Transformer-family import (round 3): a frozen keras encoder block —
    Embedding (GatherV2), MultiHeadAttention (Einsum/BatchMatMulV2/
    SelectV2), LayerNormalization (Mean/SquaredDifference/Rsqrt), gelu
    (Erfc) — golden-compared against TF executing the same frozen bytes.
    The reference's "any TF program" claim (PythonInterface.scala:115-118)
    extended past CNNs to the attention family."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    tf.keras.utils.set_random_seed(0)
    seq, vocab, dim, heads = 16, 100, 32, 4
    inp = tf.keras.Input((seq,), dtype=tf.int32)
    x = tf.keras.layers.Embedding(vocab, dim)(inp)
    att = tf.keras.layers.MultiHeadAttention(heads, dim // heads)(x, x)
    x = tf.keras.layers.LayerNormalization()(x + att)
    h = tf.keras.layers.Dense(dim * 2, activation="gelu")(x)
    x = tf.keras.layers.LayerNormalization()(x + tf.keras.layers.Dense(dim)(h))
    out = tf.keras.layers.Dense(8)(x[:, 0])
    model = tf.keras.Model(inp, out)
    fn = tf.function(lambda t: model(t, training=False))
    cf = fn.get_concrete_function(tf.TensorSpec([None, seq], tf.int32))
    data = convert_variables_to_constants_v2(cf).graph.as_graph_def(
    ).SerializeToString()

    prog = program_from_graphdef(parse_graphdef(data), relax_lead_dim=True)
    rng = np.random.default_rng(1)
    t = rng.integers(0, vocab, (3, seq)).astype(np.int32)
    got = np.asarray(prog.fn({prog.inputs[0].name: t})[prog.fetch_order[0]])

    gd = tf.compat.v1.GraphDef()
    gd.ParseFromString(data)
    with tf.Graph().as_default() as g:
        tf.import_graph_def(gd, name="")
        with tf.compat.v1.Session(graph=g) as sess:
            want = sess.run(
                f"{prog.fetch_order[0]}:0", {f"{prog.inputs[0].name}:0": t}
            )
    np.testing.assert_allclose(got, want, atol=1e-5)

    # the bf16 serving policy reaches einsum/batched-matmul attention too
    p2 = program_from_graphdef(
        parse_graphdef(data), relax_lead_dim=True, compute_dtype="bfloat16"
    )
    got2 = np.asarray(p2.fn({p2.inputs[0].name: t})[p2.fetch_order[0]])
    assert got2.dtype == np.float32
    np.testing.assert_allclose(got2, want, atol=5e-2, rtol=5e-2)


def test_bf16_int8_import_roundtrips_stablehlo(tmp_path):
    """The serving-precision knobs survive the StableHLO artifact: a
    bf16-policy int8-weight import exports via save_program and reloads
    to the same outputs — the deployable TF-to-TPU serving artifact with
    reduced precision baked in (weights ship as s8 + scales in the
    artifact, contractions in bf16 with f32 accumulation)."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    tf.keras.utils.set_random_seed(17)
    model = tf.keras.Sequential(
        [
            tf.keras.layers.Input((8, 8, 3)),
            tf.keras.layers.Conv2D(4, 3, padding="same", activation="relu"),
            tf.keras.layers.GlobalAveragePooling2D(),
            tf.keras.layers.Dense(3),
        ]
    )
    fn = tf.function(lambda x: model(x, training=False))
    cf = fn.get_concrete_function(tf.TensorSpec([None, 8, 8, 3], tf.float32))
    p = tmp_path / "m.pb"
    p.write_bytes(
        convert_variables_to_constants_v2(cf).graph.as_graph_def(
        ).SerializeToString()
    )

    prog = tfs.load_graphdef(
        str(p), relax_lead_dim=True, quantize_weights=True,
        compute_dtype="bfloat16",
    )
    art = str(tmp_path / "m.stablehlo")
    tfs.save_program(prog, art)
    back = tfs.load_program(art)

    rng = np.random.default_rng(18)
    x = rng.standard_normal((6, 8, 8, 3)).astype(np.float32)
    want = np.asarray(prog.fn({prog.inputs[0].name: x})[prog.fetch_order[0]])
    got = np.asarray(back.fn({back.inputs[0].name: x})[back.fetch_order[0]])
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_saved_model_variable_free_loads_without_tensorflow(tmp_path):
    """A VARIABLE-FREE SavedModel (pure tf.function export) loads with
    NO TensorFlow: the clean-room parser reads saved_model.pb directly
    (MetaGraphDef graph + signature map), prunes the dead saver
    subgraph via data reachability, and evaluates the PartitionedCall
    body from the function library. TF is used here only to BUILD the
    fixture; the load runs in a subprocess with tensorflow imports
    blocked."""
    import subprocess
    import sys

    class M(tf.Module):
        @tf.function(
            input_signature=[tf.TensorSpec([None, 4], tf.float32)]
        )
        def score(self, x):
            w = tf.constant(np.ones((4, 2), np.float32))
            return {"out": tf.nn.relu(x) @ w}

    m = M()
    sm = str(tmp_path / "sm_pure")
    tf.saved_model.save(m, sm, signatures={"serving_default": m.score})

    probe = (
        "import builtins\n"
        "real = builtins.__import__\n"
        "def guard(name, *a, **k):\n"
        "    if name == 'tensorflow' or name.startswith('tensorflow.'):\n"
        "        raise ImportError('TF BLOCKED')\n"
        "    return real(name, *a, **k)\n"
        "builtins.__import__ = guard\n"
        "import numpy as np\n"
        "import tensorframes_tpu as tfs\n"
        f"prog = tfs.load_saved_model({sm!r}, relax_lead_dim=True)\n"
        "x = np.arange(12, dtype=np.float32).reshape(3, 4) - 5.0\n"
        "got = np.asarray(prog.fn({prog.inputs[0].name: x})"
        "[prog.fetch_order[0]])\n"
        "want = np.maximum(x, 0) @ np.ones((4, 2), np.float32)\n"
        "assert np.allclose(got, want), (got, want)\n"
        "print('TFFREE-OK')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    proc = subprocess.run(
        [sys.executable, "-c", probe], env=env, capture_output=True,
        text=True, timeout=300,
    )
    assert proc.returncode == 0 and "TFFREE-OK" in proc.stdout, (
        proc.stdout[-1500:] + proc.stderr[-1500:]
    )

    # signature-faithful IO naming in-process too: inputs use the
    # signature arg name ('x', not the mangled graph placeholder), and
    # ALIASED output names both materialize
    class M2(tf.Module):
        @tf.function(
            input_signature=[tf.TensorSpec([None, 3], tf.float32)]
        )
        def score(self, x):
            y = x * 2.0
            return {"a": y, "b": y}

    m2 = M2()
    sm2 = str(tmp_path / "sm_alias")
    tf.saved_model.save(m2, sm2, signatures={"serving_default": m2.score})
    prog = tfs.load_saved_model(sm2, relax_lead_dim=True)
    assert [i.name for i in prog.inputs] == ["x"]
    out = prog.fn({"x": np.ones((2, 3), np.float32)})
    assert sorted(prog.fetch_order) == ["a", "b"]
    np.testing.assert_allclose(np.asarray(out["a"]), np.asarray(out["b"]))


def test_saved_model_variables_restore_without_tensorflow(tmp_path):
    """VERDICT r3 #9: a VARIABLE-BEARING SavedModel imports with NO
    TensorFlow at all — the clean-room bundle reader
    (tensorframes_tpu/bundle.py) parses variables.index (SSTable +
    BundleEntryProto) and the data shard directly, VarHandleOp binds to
    the restored value, and ReadVariableOp is an identity. TF builds
    the fixture only; the load runs in a subprocess with tensorflow
    imports hard-blocked, and the result golden-matches TF running the
    same SavedModel in THIS process."""
    import subprocess
    import sys

    w0 = np.arange(12, dtype=np.float32).reshape(3, 4)
    b0 = np.asarray([1.0, 2.0, 3.0, 4.0], np.float32)

    class M(tf.Module):
        def __init__(self):
            super().__init__()
            self.w = tf.Variable(w0, name="w")
            self.b = tf.Variable(b0, name="b")

        @tf.function(
            input_signature=[tf.TensorSpec([None, 3], tf.float32)]
        )
        def score(self, x):
            return {"y": tf.matmul(x, self.w) + self.b}

    m = M()
    sm = str(tmp_path / "sm_vars")
    tf.saved_model.save(m, sm, signatures={"serving_default": m.score})

    rng = np.random.default_rng(0)
    x = rng.standard_normal((5, 3)).astype(np.float32)
    want = m.score(tf.constant(x))["y"].numpy()
    np.save(str(tmp_path / "x.npy"), x)
    np.save(str(tmp_path / "want.npy"), want)

    probe = (
        "import builtins\n"
        "real = builtins.__import__\n"
        "def guard(name, *a, **k):\n"
        "    if name == 'tensorflow' or name.startswith('tensorflow.'):\n"
        "        raise ImportError('TF BLOCKED')\n"
        "    return real(name, *a, **k)\n"
        "builtins.__import__ = guard\n"
        "import numpy as np\n"
        "import tensorframes_tpu as tfs\n"
        f"prog = tfs.load_saved_model({sm!r}, relax_lead_dim=True)\n"
        f"x = np.load({str(tmp_path / 'x.npy')!r})\n"
        f"want = np.load({str(tmp_path / 'want.npy')!r})\n"
        "got = np.asarray(prog.fn({prog.inputs[0].name: x})"
        "[prog.fetch_order[0]])\n"
        "assert np.allclose(got, want, atol=1e-5), (got, want)\n"
        "print('TFFREE-VARS-OK')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    proc = subprocess.run(
        [sys.executable, "-c", probe], env=env, capture_output=True,
        text=True, timeout=300,
    )
    assert proc.returncode == 0 and "TFFREE-VARS-OK" in proc.stdout, (
        proc.stdout[-1500:] + proc.stderr[-1500:]
    )


def test_saved_model_keras_variables_object_path_keys(tmp_path):
    """Keras SavedModels store variables under OBJECT-PATH checkpoint
    keys (_operations/1/_kernel/…), not variable names — the bundle
    reader recovers the name mapping from the checkpoint's
    TrackableObjectGraph (full_name -> checkpoint_key) and the import
    golden-matches TF executing the same signature. Also pins the
    bundle reader's standalone contract."""
    from tensorframes_tpu.bundle import restore_variables

    inp = tf.keras.Input((5,), dtype="float32")
    hid = tf.keras.layers.Dense(3, activation="relu")(inp)
    outp = tf.keras.layers.Dense(2)(hid)
    model = tf.keras.Model(inp, outp)
    sm = str(tmp_path / "sm_keras")
    tf.saved_model.save(model, sm)

    vars_ = restore_variables(os.path.join(sm, "variables"))
    # the contract the importer depends on: the GRAPH's VarHandleOp
    # shared_names resolve in the restored map (recovered via the object
    # graph's full_name -> checkpoint_key entries; keras checkpoint keys
    # themselves are object paths like _operations/1/_kernel)
    from tensorframes_tpu.graphdef import parse_saved_model

    with open(os.path.join(sm, "saved_model.pb"), "rb") as fh:
        g_nodes, _sigs = parse_saved_model(fh.read())
    shared = [
        n.attrs["shared_name"].s.decode("utf-8")
        for n in g_nodes
        if n.op == "VarHandleOp" and n.attrs.get("shared_name") is not None
        and n.attrs["shared_name"].s
    ]
    resolved = [s for s in shared if s in vars_]
    # two Dense layers -> at least kernel+bias per layer resolve
    assert len(resolved) >= 4, (sorted(shared), sorted(vars_))

    prog = tfs.load_saved_model(sm, relax_lead_dim=True)
    rng = np.random.default_rng(1)
    x = rng.standard_normal((4, 5)).astype(np.float32)
    m = tf.saved_model.load(sm)
    want = m.signatures["serving_default"](tf.constant(x))
    got = prog.fn({prog.inputs[0].name: x})
    for name, w in want.items():
        np.testing.assert_allclose(
            np.asarray(got[name]), w.numpy(), atol=1e-5, err_msg=name
        )


def test_bf16_serving_halves_hoisted_weight_bytes():
    """Round 5: under compute_dtype="bfloat16", the HOISTED constants
    (the per-call HBM weight traffic under hoist_constants) must be
    bf16 — i.e. the importer's serving cast applies to the weight
    Consts THEMSELVES (numpy astype is eager), not as a per-call
    convert on hoisted f32 arrays. Biases and other non-MXU constants
    stay f32 ("all other ops stay exact")."""
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    import jax

    from tensorframes_tpu.program import HoistedProgram

    tf.keras.utils.set_random_seed(9)
    inp = tf.keras.Input((32,), dtype="float32")
    h = tf.keras.layers.Dense(64, activation="relu")(inp)
    outp = tf.keras.layers.Dense(10)(h)
    model = tf.keras.Model(inp, outp)
    fn = tf.function(lambda x: model(x, training=False))
    cf = fn.get_concrete_function(tf.TensorSpec([None, 32], tf.float32))
    data = convert_variables_to_constants_v2(cf).graph.as_graph_def(
    ).SerializeToString()

    sizes = {}
    outs = {}
    x = np.random.default_rng(0).standard_normal((4, 32)).astype(np.float32)
    for label, cd in (("f32", None), ("bf16", "bfloat16")):
        prog = program_from_graphdef(
            parse_graphdef(data), relax_lead_dim=True, compute_dtype=cd
        )
        abstract = {
            prog.inputs[0].name: jax.ShapeDtypeStruct((4, 32), np.float32)
        }
        sizes[label] = HoistedProgram(prog.fn, abstract).const_bytes()
        outs[label] = np.asarray(
            prog.fn({prog.inputs[0].name: x})[prog.fetch_order[0]],
            np.float32,
        )
    # weight matrices halve; f32 biases keep the ratio above exactly 0.5
    assert sizes["bf16"] < 0.6 * sizes["f32"], sizes
    # and the eager cast is numerically identical to serving rounding
    np.testing.assert_allclose(outs["f32"], outs["bf16"], atol=0.05)
