"""Fleet-supervision tests (ISSUE 8 tentpole acceptance): 2-process CPU
subprocess fleets (the tests/test_trace_merge.py pattern) where a rank
is SIGKILLed mid-``run_resumable`` (supervisor restarts, resumed state
bit-identical to an uninterrupted run), a deliberately hung collective
trips the dispatch-deadline watchdog with a flight-recorder postmortem
naming the missing rank, and a drop-heartbeat injection is detected by
the surviving peer — plus in-process units for the heartbeat files,
status classification, coordinated abort, barrier and deadline
watchdog."""

import json
import os
import signal
import subprocess
import sys
import time

import jax.numpy as jnp
import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.observability import flight
from tensorframes_tpu.resilience import faults, fleet, supervisor

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _fleet_env(extra=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # fast chaos cadence: beats every 0.1s, death verdict at 1.5s
    env["TFTPU_HEARTBEAT_INTERVAL_S"] = "0.1"
    env["TFTPU_HEARTBEAT_TIMEOUT_S"] = "1.5"
    env.update(extra or {})
    return env


# ---------------------------------------------------------------------------
# kill -9 of a non-zero rank mid-run_resumable: supervise() restarts and
# the resumed run converges bit-identically (tentpole acceptance #1)
# ---------------------------------------------------------------------------

# each rank trains its own float32 multiply-accumulate replica (replay
# order changes the result bits, so a wrong resume point is detectable);
# rank `kill_rank` SIGKILLs itself at the `kill_after` step edge of its
# FIRST incarnation via the fleet.rank.kill site instrumented in
# run_resumable's loop
_TRAINER = """
import contextlib, os, sys, time
ckroot, num_steps, save_every, kill_rank, kill_after, slow0 = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]),
    int(sys.argv[5]), float(sys.argv[6]),
)
import jax.numpy as jnp
import numpy as np
from tensorframes_tpu.checkpoint import Checkpointer
from tensorframes_tpu.resilience import faults
from tensorframes_tpu.training import run_resumable

rank = int(os.environ["TFTPU_PROCESS_INDEX"])
attempt = int(os.environ.get("TFTPU_FLEET_ATTEMPT", "0"))
stack = contextlib.ExitStack()
if rank == kill_rank and attempt == 0 and kill_after > 0:
    stack.enter_context(faults.inject(
        "fleet.rank.kill", faults.KillRank, after=kill_after, max_times=1,
    ))

sleep_s = slow0 if rank == 0 else 0.01

def step(state, batch):
    time.sleep(sleep_s)
    new = {"w": state["w"] * jnp.float32(1.01) + batch}
    return new, {"loss": new["w"].sum()}

batches = [jnp.full((4,), float(i % 7), jnp.float32) for i in range(num_steps)]
ck = Checkpointer(os.path.join(ckroot, f"rank{rank}"), backend="npz")
state, ran = run_resumable(
    step, {"w": jnp.zeros((4,), jnp.float32)}, ck, batches,
    num_steps=num_steps, save_every=save_every,
)
np.save(os.path.join(ckroot, f"final_rank{rank}.npy"), np.asarray(state["w"]))
print("DONE", rank, ran, flush=True)
"""


def _reference(num_steps: int) -> np.ndarray:
    import jax.numpy as jnp

    w = jnp.zeros((4,), jnp.float32)
    for i in range(num_steps):
        w = w * jnp.float32(1.01) + jnp.full((4,), float(i % 7), jnp.float32)
    return np.asarray(w)


def _supervise_trainer(tmp_path, *, n, kill_rank, num_steps=40,
                       save_every=2, kill_after=3, max_restarts=2):
    ckroot = str(tmp_path / "ck")
    os.makedirs(ckroot, exist_ok=True)
    fdir = str(tmp_path / "fleet")
    bdir = str(tmp_path / "blackbox")
    result = supervisor.supervise(
        [sys.executable, "-c", _TRAINER, ckroot, str(num_steps),
         str(save_every), str(kill_rank), str(kill_after), "0.05"],
        n,
        rendezvous_dir=fdir,
        flight_dir=bdir,
        max_restarts=max_restarts,
        heartbeat_timeout_s=5.0,
        grace_s=5.0,
        env=_fleet_env(),
        inherit_env=False,
    )
    return result, ckroot, bdir


def test_kill9_rank_mid_run_supervisor_restarts_and_resumes(tmp_path):
    """SIGKILL rank 1 mid-run: the supervisor reaps it (exit -9),
    survivors abort via the coordinated protocol (no indefinite hang),
    the fleet restarts resuming from the latest intact checkpoint, and
    EVERY rank's final state is bit-identical to an uninterrupted run."""
    result, ckroot, bdir = _supervise_trainer(tmp_path, n=2, kill_rank=1)
    assert result.ok
    assert result.restarts == 1
    assert result.attempts == 2
    # the first incarnation recorded the SIGKILL of rank 1
    assert result.exit_codes[0][1] == -signal.SIGKILL
    assert result.failures[0].rank == 1
    assert result.failures[0].kind in ("signal", "abort")
    # the second incarnation finished clean on every rank
    assert result.exit_codes[1] == {0: 0, 1: 0}
    ref = _reference(40)
    for rank in range(2):
        final = np.load(os.path.join(ckroot, f"final_rank{rank}.npy"))
        np.testing.assert_array_equal(final, ref)
    # the black box shows the fleet history: the injected kill is the
    # last thing rank 1's line-flushed spool recorded before dying, and
    # the survivor's coordinated abort names rank 1 (the abort FILE is
    # gone by design — clear_fleet removes it before the restart so the
    # new incarnation isn't killed at birth)
    records = flight.read_blackbox(bdir)
    kinds = {r.get("kind") for r in records}
    assert "fault.kill_rank" in kinds
    aborts = [r for r in records if r.get("kind") == "fleet.abort_seen"]
    assert aborts and aborts[0]["ranks"] == [1]
    # the survivor left a fleet_abort postmortem
    posts = [f for f in os.listdir(bdir)
             if f.startswith("postmortem_") and "_p0_" in f]
    assert posts


@pytest.mark.slow
def test_kill9_on_4_process_fleet_converges(tmp_path):
    """The 4-process variant: kill rank 2; all four replicas converge
    bit-identically after the restart."""
    result, ckroot, _ = _supervise_trainer(
        tmp_path, n=4, kill_rank=2, num_steps=60,
    )
    assert result.ok and result.restarts == 1
    assert result.exit_codes[0][2] == -signal.SIGKILL
    ref = _reference(60)
    for rank in range(4):
        final = np.load(os.path.join(ckroot, f"final_rank{rank}.npy"))
        np.testing.assert_array_equal(final, ref)


def test_supervise_restart_budget_exhausted_raises(tmp_path):
    with pytest.raises(supervisor.SuperviseError) as ei:
        supervisor.supervise(
            [sys.executable, "-c", "import sys; sys.exit(9)"], 2,
            rendezvous_dir=str(tmp_path / "f"), max_restarts=1,
            grace_s=0.5, env=_fleet_env(), inherit_env=False,
        )
    assert ei.value.result.attempts == 2
    assert not ei.value.result.ok
    assert all(f.kind == "exit" for f in ei.value.result.failures)


def test_supervise_partial_spawn_failure_reaps_started_ranks(tmp_path):
    """If spawning rank k fails, ranks 0..k-1 must be killed and
    reaped, not orphaned to run unsupervised."""
    pid_file = str(tmp_path / "rank0.pid")
    sleeper = (
        "import os, time\n"
        f"open({pid_file!r}, 'w').write(str(os.getpid()))\n"
        "time.sleep(120)\n"
    )

    def cmd(rank):
        if rank == 1:
            # rank 0 is already spawned; wait until it has genuinely
            # started (pid file written) so the reap is observable
            deadline = time.monotonic() + 60
            while not os.path.exists(pid_file):
                assert time.monotonic() < deadline, "rank 0 never started"
                time.sleep(0.05)
            raise RuntimeError("no argv for rank 1")
        return [sys.executable, "-c", sleeper]

    with pytest.raises(RuntimeError, match="no argv for rank 1"):
        supervisor.supervise(
            cmd, 2, rendezvous_dir=str(tmp_path / "f"),
            env=_fleet_env(), inherit_env=False,
        )
    pid = int(open(pid_file).read())
    # rank 0 must be gone (kill(pid, 0) raises once reaped)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            break
        time.sleep(0.05)
    else:
        os.kill(pid, signal.SIGKILL)
        raise AssertionError(f"rank 0 (pid {pid}) left running")


def test_supervise_clean_single_attempt(tmp_path):
    script = (
        "import time\n"
        "from tensorframes_tpu.resilience import fleet\n"
        "assert fleet.enroll() is not None\n"
        "time.sleep(0.5)\n"
    )
    result = supervisor.supervise(
        [sys.executable, "-c", script], 2,
        rendezvous_dir=str(tmp_path / "f"), max_restarts=0,
        env=_fleet_env(), inherit_env=False,
    )
    assert result.ok and result.attempts == 1 and result.restarts == 0
    assert result.exit_codes == [{0: 0, 1: 0}]


# ---------------------------------------------------------------------------
# hung collective: the delay-collective injection stalls one rank; the
# peer's deadline watchdog fires, dumps a postmortem naming the missing
# rank, and aborts instead of blocking forever (tentpole acceptance #2)
# ---------------------------------------------------------------------------

_BARRIER_WORKER = """
import contextlib, os, sys
import tensorframes_tpu  # config import order
from tensorframes_tpu.resilience import faults, fleet
from tensorframes_tpu.observability.metrics import REGISTRY

rank = int(os.environ["TFTPU_PROCESS_INDEX"])
stack = contextlib.ExitStack()
if rank == 1:
    # delay-collective: rank 1 stalls 60s on its way INTO the barrier
    stack.enter_context(faults.inject("fleet.barrier", faults.Delay(60.0)))
fleet.enroll(monitor=False)
try:
    fleet.barrier("step0", deadline=1.5)
except fleet.HungDispatchError as e:
    print("HUNG", str(e), flush=True)
    hung = [m for m in REGISTRY.collect()
            if m.name == "tftpu_fleet_hung_dispatches_total"][0]
    aborts = [m for m in REGISTRY.collect()
              if m.name == "tftpu_fleet_aborts_total"][0]
    print(f"COUNTERS hung={hung.value:.0f} aborts={aborts.value:.0f}",
          flush=True)
    sys.exit(7)
print("NOHANG", flush=True)
"""


def test_hung_collective_watchdog_names_missing_rank(tmp_path):
    """Rank 1 stalls at the rendezvous via the delay-collective fault;
    rank 0's deadline watchdog trips within the deadline, the postmortem
    names rank 1 and the stalled dispatch, and the fleet counters
    reflect the event."""
    fdir = str(tmp_path / "fleet")
    bdir = str(tmp_path / "blackbox")
    env = _fleet_env({
        "TFTPU_RUN_ID": "hungtest",
        "TFTPU_FLEET_DIR": fdir,
        "TFTPU_NUM_PROCESSES": "2",
        "TFTPU_FLIGHT_DIR": bdir,
    })
    procs = []
    for i in range(2):
        e = dict(env)
        e["TFTPU_PROCESS_INDEX"] = str(i)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _BARRIER_WORKER],
            env=e, cwd=_REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    try:
        t0 = time.monotonic()
        out0, err0 = procs[0].communicate(timeout=120)
        elapsed = time.monotonic() - t0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    assert procs[0].returncode == 7, (
        f"rank 0 rc={procs[0].returncode}\nstdout: {out0}\nstderr: {err0}"
    )
    # it fired via the watchdog, not the 60s stall draining
    assert elapsed < 60
    assert "HUNG" in out0
    # the error names the missing rank and the stalled dispatch
    assert "[1]" in out0 and "step0" in out0
    assert "COUNTERS hung=1 aborts=1" in out0
    # the coordinated abort landed for any surviving peer to see
    ab = fleet.abort_requested(fdir, "hungtest")
    assert ab is not None and ab["ranks"] == [1]
    # the flight postmortem names the stalled dispatch + missing rank
    posts = [f for f in os.listdir(bdir) if f.startswith("postmortem_")]
    assert posts, f"no postmortem in {os.listdir(bdir)}"
    p0 = [f for f in posts if "_p0_" in f]
    assert p0
    lines = [json.loads(line) for line in
             open(os.path.join(bdir, sorted(p0)[0]))]
    assert lines[0]["reason"] == "hung_dispatch"
    hung = [r for r in lines if r.get("kind") == "fleet.hung_dispatch"]
    assert hung and hung[0]["missing_ranks"] == [1]
    assert "step0" in hung[0]["entry"]


def test_dispatch_deadline_trips_on_delayed_executor_dispatch(tmp_path):
    """In-process: a Delay injection at the executor dispatch site under
    a dispatch deadline raises HungDispatchError and dumps a postmortem
    naming the dispatch."""
    df = tfs.frame_from_arrays({"x": np.arange(16.0)}, num_blocks=1)
    program = tfs.compile_program(lambda x: {"y": x + 1.0}, df)
    before = fleet._HUNG_DISPATCHES.value
    prev_spool = flight.RECORDER.spool_dir
    flight.set_spool_dir(str(tmp_path))
    tfs.configure(dispatch_deadline_s=0.4)
    try:
        with faults.inject("executor.dispatch", faults.Delay(10.0),
                           max_times=1):
            with pytest.raises(fleet.HungDispatchError, match="deadline"):
                tfs.map_blocks(program, df).collect()
    finally:
        tfs.configure(dispatch_deadline_s=0.0)
        flight.set_spool_dir(prev_spool)
    assert fleet._HUNG_DISPATCHES.value == before + 1
    posts = [f for f in os.listdir(str(tmp_path))
             if f.startswith("postmortem_")]
    assert posts
    lines = [json.loads(line) for line in
             open(os.path.join(str(tmp_path), sorted(posts)[-1]))]
    assert lines[0]["reason"] == "hung_dispatch"
    hung = [r for r in lines if r.get("kind") == "fleet.hung_dispatch"]
    assert hung and "executor.run_block" in hung[0]["entry"]


def test_deadline_exemption_scoped_to_fallback_compiles(monkeypatch):
    """ISSUE 10 regression: since the unified AOT dispatch, the ONLY
    deadline-exempt dispatches are genuine cache-miss lazy compiles on
    the counted jit fallback (AOT build raised — the XLA compile runs
    lazily INSIDE the call). A normal first dispatch compiles/loads
    OUTSIDE the watchdog scope and stays bounded — the old blanket
    first-dispatch exemption must be gone."""
    from tensorframes_tpu.ops.executor import CompiledProgram

    df = tfs.frame_from_arrays({"x": np.arange(12.0) + 100.0},
                               num_blocks=1)
    tfs.configure(dispatch_deadline_s=0.3)
    try:
        # --- AOT path, first dispatch: NOT exempt. The injected stall
        # wedges the dispatch body (post-build), so the watchdog fires.
        program = tfs.compile_program(lambda x: {"y": x + 1.0}, df)
        exempt_before = fleet._DEADLINE_EXEMPTIONS.value
        with faults.inject("executor.dispatch", faults.Delay(10.0),
                           max_times=1):
            with pytest.raises(fleet.HungDispatchError):
                tfs.map_blocks(program, df).collect()
        assert fleet._DEADLINE_EXEMPTIONS.value == exempt_before

        # --- fallback path (AOT build raises): first dispatch is the
        # lazy compile — exempt, counted, and it must complete.
        monkeypatch.setattr(
            CompiledProgram, "_build_aot_impl",
            lambda self, *a, **k: (_ for _ in ()).throw(
                RuntimeError("forced AOT build failure")
            ),
        )
        program2 = tfs.compile_program(lambda x: {"y": x - 1.0}, df)
        fb = tfs.map_blocks(program2, df)
        with faults.inject("executor.dispatch", faults.Delay(0.6),
                           max_times=1):
            out = fb.column_values("y")
        np.testing.assert_array_equal(out, np.arange(12.0) + 99.0)
        assert fleet._DEADLINE_EXEMPTIONS.value == exempt_before + 1

        # --- fallback steady state (same shape again): the compile is
        # done, so the watchdog is armed — no second exemption.
        with faults.inject("executor.dispatch", faults.Delay(10.0),
                           max_times=1):
            with pytest.raises(fleet.HungDispatchError):
                tfs.map_blocks(program2, df).collect()
        assert fleet._DEADLINE_EXEMPTIONS.value == exempt_before + 1
    finally:
        tfs.configure(dispatch_deadline_s=0.0)


def test_aot_jit_scalar_leaf_exemption_is_first_dispatch_only():
    """A Python-scalar leaf keeps an aot_jit entry on the lazy-jit path
    (no AOT key) — but the deadline exemption must still cover only the
    FIRST dispatch of each trace-cache signature, never every call: a
    steady-state hang of a scalar-carrying train step must stay visible
    to the fleet watchdog."""
    from tensorframes_tpu.ops.executor import aot_jit

    tfs.configure(dispatch_deadline_s=30.0)
    try:
        exempt_before = fleet._DEADLINE_EXEMPTIONS.value
        f = aot_jit(lambda x, s: x * s, label="scalar-exempt")
        for _ in range(3):
            f(jnp.ones((4,)), 2.5)  # same lazy signature every call
        assert fleet._DEADLINE_EXEMPTIONS.value == exempt_before + 1
        f(jnp.ones((8,)), 2.5)  # new shape: one more genuine lazy compile
        assert fleet._DEADLINE_EXEMPTIONS.value == exempt_before + 2
        f(jnp.ones((8,)), 7.5)  # new VALUE only: same trace, no exemption
        assert fleet._DEADLINE_EXEMPTIONS.value == exempt_before + 2
    finally:
        tfs.configure(dispatch_deadline_s=0.0)


def test_hung_handshake_leaves_no_abort_record(tmp_path, monkeypatch):
    """A handshake timeout is RETRIED — it must not write the
    coordinated-abort signal (a stale record would kill every rank the
    moment it enrolled after a successful redial)."""
    monkeypatch.setenv("TFTPU_FLEET_DIR", str(tmp_path))
    with pytest.raises(fleet.HungDispatchError):
        fleet.run_with_deadline(
            lambda: time.sleep(5), describe="distributed.init",
            deadline=0.2, signal=False,
        )
    assert fleet.abort_requested(str(tmp_path)) is None
    # the default (a mid-run collective) DOES signal
    with pytest.raises(fleet.HungDispatchError):
        fleet.run_with_deadline(
            lambda: time.sleep(5), describe="executor.run_block",
            deadline=0.2,
        )
    assert fleet.abort_requested(str(tmp_path)) is not None


def test_dispatch_without_deadline_is_unbounded_and_unchanged():
    """Deadline off (the default): the watchdog adds nothing to the
    dispatch path and results are identical."""
    df = tfs.frame_from_arrays({"x": np.arange(8.0)}, num_blocks=2)
    program = tfs.compile_program(lambda x: {"y": x * 3.0}, df)
    out = tfs.map_blocks(program, df).column_values("y")
    np.testing.assert_array_equal(out, np.arange(8.0) * 3.0)


# ---------------------------------------------------------------------------
# drop-heartbeat: the silent rank is detected by its peer, which aborts
# with a postmortem naming it (tentpole acceptance #3)
# ---------------------------------------------------------------------------

_SILENT_WORKER = """
import contextlib, time
from tensorframes_tpu.resilience import faults, fleet
stack = contextlib.ExitStack()
# beats 1..3 publish, then every beat is dropped: the process is alive
# but silent — exactly what a wedged rank looks like from outside
stack.enter_context(faults.inject("fleet.heartbeat", RuntimeError, after=3))
fleet.enroll(monitor=False)
time.sleep(60)
"""

_WATCHER_WORKER = """
import sys, time
from tensorframes_tpu.resilience import fleet
member = fleet.enroll(abort_on_dead=True)
assert member is not None
time.sleep(60)  # the monitor thread aborts us long before this drains
print("UNDETECTED", flush=True)
sys.exit(1)
"""


def test_drop_heartbeat_detected_and_peer_aborts(tmp_path):
    """Rank 0 drops its beats (injection); rank 1's monitor declares it
    dead within the heartbeat timeout, dumps the postmortem naming rank
    0, signals the coordinated abort and exits ABORT_EXIT_CODE."""
    fdir = str(tmp_path / "fleet")
    bdir = str(tmp_path / "blackbox")
    env = _fleet_env({
        "TFTPU_RUN_ID": "droptest",
        "TFTPU_FLEET_DIR": fdir,
        "TFTPU_NUM_PROCESSES": "2",
        "TFTPU_FLIGHT_DIR": bdir,
    })
    workers = [_SILENT_WORKER, _WATCHER_WORKER]
    procs = []
    for i, src in enumerate(workers):
        e = dict(env)
        e["TFTPU_PROCESS_INDEX"] = str(i)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", src], env=e, cwd=_REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    try:
        t0 = time.monotonic()
        out1, err1 = procs[1].communicate(timeout=120)
        elapsed = time.monotonic() - t0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    assert procs[1].returncode == fleet.ABORT_EXIT_CODE, (
        f"watcher rc={procs[1].returncode}\nstdout: {out1}\nstderr: {err1}"
    )
    assert "UNDETECTED" not in out1
    assert elapsed < 30  # detected within the (1.5s) timeout + slack
    ab = fleet.abort_requested(fdir, "droptest")
    assert ab is not None
    assert ab["ranks"] == [0]
    assert "heartbeat" in ab["reason"]
    # black box: the watcher recorded the loss before aborting
    records = flight.read_blackbox(bdir)
    lost = [r for r in records if r.get("kind") == "fleet.heartbeat_lost"]
    assert lost and lost[0]["rank"] == 0
    posts = [f for f in os.listdir(bdir)
             if f.startswith("postmortem_") and "_p1_" in f]
    assert posts
    header = json.loads(open(os.path.join(bdir, sorted(posts)[0])).readline())
    assert header["reason"] == "fleet_abort"


# ---------------------------------------------------------------------------
# in-process units: heartbeat files, classification, abort, barrier
# ---------------------------------------------------------------------------

@pytest.fixture
def member_hygiene():
    yield
    fleet._reset_member_for_tests()


def test_heartbeat_write_read_roundtrip(tmp_path):
    d = str(tmp_path)
    fleet.write_beat(d, seq=1, rank=3)
    fleet.write_beat(d, seq=2, rank=3)
    beats = fleet.read_heartbeats(d)
    assert set(beats) == {3}
    assert beats[3]["seq"] == 2
    assert beats[3]["pid"] == os.getpid()
    assert not beats[3]["stopped"]
    fleet.write_beat(d, seq=3, rank=3, stopped=True)
    assert fleet.read_heartbeats(d)[3]["stopped"]


def test_fleet_status_classification(tmp_path):
    d = str(tmp_path)
    now = time.time()
    fleet.write_beat(d, rank=0)                      # fresh → alive
    fleet.write_beat(d, rank=1, stopped=True)        # clean exit
    fleet.write_beat(d, rank=2)
    # age rank 2's beat into the straggler band and rank 3's past dead
    run = json.load(open(os.path.join(
        d, [f for f in os.listdir(d) if f.startswith("hb_")][0])))["run_id"]
    for rank, age in ((2, 1.0), (3, 5.0)):
        rec = {"run_id": run, "process_index": rank, "pid": 1,
               "seq": 1, "ts": now - age, "interval_s": 0.1,
               "stopped": False}
        with open(os.path.join(d, f"hb_{run}_p{rank}.json"), "w") as f:
            json.dump(rec, f)
    st = fleet.fleet_status(d, num_processes=5, timeout_s=2.0,
                            straggler_s=0.5, now=now)
    assert st.alive == [0]
    assert st.stopped == [1]
    assert st.stragglers == [2]
    assert st.dead == [3]
    assert st.missing == [4]
    assert st.unresponsive() == [2, 3, 4]


def test_heartbeater_drop_injection_counts_skips(tmp_path, member_hygiene):
    hb = fleet.Heartbeater(str(tmp_path), interval_s=0.05)
    with faults.inject("fleet.heartbeat", RuntimeError):
        assert hb.beat_once() is False
    assert hb.skipped == 1
    assert hb.beat_once() is True
    hb.stop()
    beats = fleet.read_heartbeats(str(tmp_path))
    assert beats[hb.rank]["stopped"]  # graceful final beat


def test_signal_abort_first_writer_wins(tmp_path):
    d = str(tmp_path)
    fleet.signal_abort(d, "first cause", dead_ranks=[1], run_id="r")
    fleet.signal_abort(d, "cascade", dead_ranks=[0], run_id="r")
    ab = fleet.abort_requested(d, "r")
    assert ab["reason"] == "first cause"
    assert ab["ranks"] == [1]


def test_clear_fleet_resets_state(tmp_path):
    d = str(tmp_path)
    fleet.write_beat(d, rank=0)
    fleet.signal_abort(d, "x", run_id=None)
    assert fleet.clear_fleet(d) >= 2
    assert fleet.read_heartbeats(d) == {}
    assert fleet.abort_requested(d) is None


def test_monitor_detects_dead_and_straggler(tmp_path):
    d = str(tmp_path)
    run = "montest"
    now = time.time()
    for rank, age in ((1, 0.8), (2, 3.0)):
        rec = {"run_id": run, "process_index": rank, "pid": 1,
               "seq": 1, "ts": now - age, "interval_s": 0.1,
               "stopped": False}
        with open(os.path.join(d, f"hb_{run}_p{rank}.json"), "w") as f:
            json.dump(rec, f)
    dead, stragglers = [], []
    mon = fleet.FleetMonitor(
        d, run_id=run, timeout_s=2.0, straggler_s=0.5, self_rank=0,
        on_dead=lambda rs, st: dead.extend(rs),
        on_straggler=lambda rs, st: stragglers.extend(rs),
    )
    mon.check_once()
    mon.check_once()  # second scan must not re-report
    assert dead == [2]
    assert stragglers == [1]


def test_monitor_declares_never_started_rank_dead_after_grace(tmp_path):
    """A rank that crashes before its FIRST beat must not stay
    invisible: once the startup grace lapses, expected-but-silent ranks
    are dead."""
    d = str(tmp_path)
    fleet.write_beat(d, rank=0)
    run = fleet.read_heartbeats(d)[0]["run_id"]
    dead = []
    mon = fleet.FleetMonitor(
        d, run_id=run, num_processes=3, timeout_s=1.0, self_rank=0,
        startup_grace_s=0.2,
        on_dead=lambda rs, st: dead.extend(rs),
    )
    mon.check_once()
    assert dead == []  # inside the grace: not yet judged
    time.sleep(0.25)
    mon.check_once()
    assert dead == [1, 2]
    mon.check_once()  # reported once
    assert dead == [1, 2]


def test_monitor_never_judges_self(tmp_path):
    d = str(tmp_path)
    run = "selftest"
    rec = {"run_id": run, "process_index": 0, "pid": 1, "seq": 1,
           "ts": time.time() - 100.0, "interval_s": 0.1, "stopped": False}
    with open(os.path.join(d, f"hb_{run}_p0.json"), "w") as f:
        json.dump(rec, f)
    dead = []
    mon = fleet.FleetMonitor(
        d, run_id=run, timeout_s=1.0, self_rank=0,
        on_dead=lambda rs, st: dead.extend(rs),
    )
    mon.check_once()
    assert dead == []


def test_monitor_sees_abort_signal(tmp_path):
    d = str(tmp_path)
    aborts = []
    mon = fleet.FleetMonitor(
        d, run_id="abtest", self_rank=0, on_abort=aborts.append,
    )
    mon.check_once()
    assert aborts == []
    fleet.signal_abort(d, "down we go", run_id="abtest")
    mon.check_once()
    mon.check_once()  # reported once
    assert len(aborts) == 1
    assert aborts[0]["reason"] == "down we go"


def test_enroll_noop_without_fleet_dir(monkeypatch, member_hygiene):
    monkeypatch.delenv("TFTPU_FLEET_DIR", raising=False)
    assert fleet.enroll() is None


def test_enroll_idempotent_and_heartbeats(tmp_path, monkeypatch,
                                          member_hygiene):
    monkeypatch.setenv("TFTPU_FLEET_DIR", str(tmp_path))
    m1 = fleet.enroll(monitor=False, interval_s=0.05)
    m2 = fleet.enroll(monitor=False)
    assert m1 is m2
    assert fleet.current_member() is m1
    time.sleep(0.25)
    beats = fleet.read_heartbeats(str(tmp_path))
    assert beats and beats[m1.heartbeater.rank]["seq"] >= 2


def test_barrier_noop_single_process(tmp_path):
    # no fleet dir, no peers: must return immediately
    fleet.barrier("lonely", num_processes=1, directory=None)
    fleet.barrier("lonely", num_processes=4, directory=None)


def _write_peer_arrival(d, name, rank, gen=0):
    """Simulate a peer rank's barrier arrival (one process = one rank,
    so the in-process generation counter only advances for OUR calls —
    the peer's file is written straight through the file protocol)."""
    from tensorframes_tpu.observability import context

    attempt = os.environ.get("TFTPU_FLEET_ATTEMPT", "0")
    tag = f"barrier_{context.run_id()}_a{attempt}_{name}.g{gen}"
    with open(os.path.join(d, f"{tag}_p{rank}"), "w") as f:
        f.write(str(time.time()))


def test_barrier_completes_when_all_arrive(tmp_path):
    d = str(tmp_path)
    _write_peer_arrival(d, "b1", rank=1, gen=0)
    fleet.barrier("b1", directory=d, num_processes=2, rank=0,
                  deadline=10.0)  # must return, not time out


def test_barrier_name_reuse_synchronizes_each_use(tmp_path):
    """Reusing a barrier name must synchronize EVERY use (per-use
    generations), not silently match the first use's stale arrival
    files."""
    d = str(tmp_path)
    _write_peer_arrival(d, "epoch", rank=1, gen=0)
    fleet.barrier("epoch", directory=d, num_processes=2, rank=0,
                  deadline=5.0)
    # second use: the peer has NOT arrived at generation 1 — a stale
    # match on g0's files would return instantly; the fix times out
    with pytest.raises(fleet.HungDispatchError):
        fleet.barrier("epoch", directory=d, num_processes=2, rank=0,
                      deadline=0.3)
    # and once the peer arrives at g2, the third use completes (clear
    # the abort record the g1 timeout signalled first)
    fleet.clear_fleet(d)
    _write_peer_arrival(d, "epoch", rank=1, gen=2)
    fleet.barrier("epoch", directory=d, num_processes=2, rank=0,
                  deadline=5.0)


def test_barrier_prunes_spent_generations(tmp_path):
    """Per-epoch barrier reuse must not grow the rendezvous dir without
    bound: generations <= current-2 are pruned on entry (every rank
    provably observed them)."""
    d = str(tmp_path)
    for gen in range(4):
        _write_peer_arrival(d, "loop", rank=1, gen=gen)
        fleet.barrier("loop", directory=d, num_processes=2, rank=0,
                      deadline=5.0)
    remaining = sorted(os.listdir(d))
    # only the last two generations' files survive (g2, g3 × 2 ranks)
    gens = {f.split(".g")[1].split("_p")[0] for f in remaining
            if ".g" in f}
    assert gens == {"2", "3"}, remaining


def test_barrier_explicit_zero_deadline_means_default_not_instant(tmp_path):
    """deadline=0 must follow the module's 0-disables convention
    (fall back to the default bound), never an instant fleet-wide
    abort."""
    d = str(tmp_path)
    _write_peer_arrival(d, "z0", rank=1, gen=0)
    before = fleet._HUNG_DISPATCHES.value
    # peer already arrived: with 0 normalized to the default bound this
    # completes; an instant-trip bug would abort before the first poll
    fleet.barrier("z0", directory=d, num_processes=2, rank=0, deadline=0)
    assert fleet._HUNG_DISPATCHES.value == before
    assert fleet.abort_requested(d) is None


def test_barrier_names_missing_rank_on_deadline(tmp_path):
    d = str(tmp_path)
    before = fleet._HUNG_DISPATCHES.value
    with pytest.raises(fleet.HungDispatchError) as ei:
        fleet.barrier("b2", directory=d, num_processes=3, rank=0,
                      deadline=0.3)
    msg = str(ei.value)
    assert "[1, 2]" in msg and "b2" in msg
    assert fleet._HUNG_DISPATCHES.value == before + 1
    # the hung barrier signalled the coordinated abort for its peers
    ab = fleet.abort_requested(d)
    assert ab is not None and ab["ranks"] == [1, 2]


def test_barrier_aborts_on_peer_signal(tmp_path):
    d = str(tmp_path)
    fleet.signal_abort(d, "peer died elsewhere", run_id=None)
    with pytest.raises(fleet.CoordinatedAbortError, match="peer died"):
        fleet.barrier("b3", directory=d, num_processes=2, rank=0,
                      deadline=5.0)


def test_run_with_deadline_passthrough_and_errors():
    assert fleet.run_with_deadline(lambda: 5, describe="x") == 5
    assert fleet.run_with_deadline(
        lambda: 6, describe="x", deadline=2.0) == 6
    with pytest.raises(ValueError, match="boom"):
        fleet.run_with_deadline(
            lambda: (_ for _ in ()).throw(ValueError("boom")),
            describe="x", deadline=2.0,
        )


def test_run_with_deadline_times_out():
    before = fleet._HUNG_DISPATCHES.value
    with pytest.raises(fleet.HungDispatchError, match="0.2s deadline"):
        fleet.run_with_deadline(
            lambda: time.sleep(5), describe="wedged", deadline=0.2,
        )
    assert fleet._HUNG_DISPATCHES.value == before + 1


def test_fleet_metrics_preregistered():
    """The tftpu_fleet_* family must ride every exposition from import
    (a run that never lost a rank reads 0 — it does not vanish)."""
    from tensorframes_tpu.observability.metrics import REGISTRY

    names = {m.name for m in REGISTRY.collect()}
    for expected in (
        "tftpu_fleet_heartbeats_total",
        "tftpu_fleet_heartbeats_skipped_total",
        "tftpu_fleet_missed_beats_total",
        "tftpu_fleet_stragglers_total",
        "tftpu_fleet_dead_ranks_total",
        "tftpu_fleet_aborts_total",
        "tftpu_fleet_hung_dispatches_total",
        "tftpu_fleet_restarts_total",
        "tftpu_fleet_recovery_seconds",
        "tftpu_fleet_alive_ranks",
    ):
        assert expected in names, expected
