"""Test fixture: force a virtual 8-device CPU platform BEFORE jax loads.

≙ the reference's shared local-mode fixture (TensorFramesTestSparkContext:
local[1] Spark with 4 shuffle partitions) — here "distributed" is tested by
device count, not hosts: 8 virtual CPU devices stand in for a TPU slice.
"""

import os

# Force CPU: the environment pre-sets JAX_PLATFORMS=axon (the real TPU
# tunnel) and its sitecustomize imports jax at interpreter start, so both
# the env var and jax's already-captured config must be overridden here —
# before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass  # a backend already initialized; the assert below is the arbiter
assert len(jax.devices()) >= 8, (
    f"conftest expected >=8 virtual CPU devices, got {jax.devices()}"
)

import pytest  # noqa: E402


def pytest_sessionstart(session):
    """TFTPU_OBS_EXPORT=<dir>: arm the structured tracer for the whole
    suite so the session-end export (below) carries a real timeline —
    CI uploads the pair as its observability artifact."""
    if os.environ.get("TFTPU_OBS_EXPORT"):
        from tensorframes_tpu.observability import events

        events.enable()


def pytest_sessionfinish(session, exitstatus):
    """Write the suite's metrics snapshot (JSONL) + Chrome trace into
    $TFTPU_OBS_EXPORT. Best-effort: telemetry export must never turn a
    green suite red."""
    out = os.environ.get("TFTPU_OBS_EXPORT")
    if not out:
        return
    try:
        from tensorframes_tpu.observability import REGISTRY, events

        os.makedirs(out, exist_ok=True)
        REGISTRY.write_jsonl(os.path.join(out, "tier1_metrics.jsonl"))
        events.save(os.path.join(out, "tier1_trace.json"))
    except Exception as e:  # pragma: no cover - diagnostic path
        print(f"TFTPU_OBS_EXPORT failed: {e}")
    try:
        # static-analysis findings the suite produced, next to the
        # metrics artifact (ISSUE 3: lint posture rides along with CI).
        # Own try: an analysis-import failure must not take the
        # metrics/trace exports above down with it.
        from tensorframes_tpu.analysis import save_jsonl as _save_diag

        _save_diag(os.path.join(out, "tier1_diagnostics.jsonl"))
    except Exception as e:  # pragma: no cover - diagnostic path
        print(f"TFTPU_OBS_EXPORT diagnostics export failed: {e}")


@pytest.fixture(autouse=True)
def _fresh_graph():
    """Graph-state hygiene: every test runs in a fresh naming context
    (≙ GraphScoping.testGraph, dsl/GraphScoping.scala:8-15)."""
    from tensorframes_tpu.dsl import with_graph

    with with_graph():
        yield


@pytest.fixture(autouse=True)
def _strategy_walls_isolated():
    """Latency-feedback hygiene: the strategy-wall EWMA table
    (plan/stats) is process-global BY DESIGN — in production every
    pipeline's observed walls inform every decision. Across a test
    suite that design makes decision-kind assertions order-dependent
    (one test's recorded walls can flip a later test's decide_*), so
    each test starts from an empty in-memory table. Memory only: the
    sidecar file is untouched, and tests that exercise persistence
    re-arm loading themselves via plan_stats.clear_memory()."""
    from tensorframes_tpu.plan import stats as _plan_stats

    _plan_stats.reset_strategy_walls(unlink_sidecar=False)
    yield
