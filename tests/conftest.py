"""Test fixture: force a virtual 8-device CPU platform BEFORE jax loads.

≙ the reference's shared local-mode fixture (TensorFramesTestSparkContext:
local[1] Spark with 4 shuffle partitions) — here "distributed" is tested by
device count, not hosts: 8 virtual CPU devices stand in for a TPU slice.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_graph():
    """Graph-state hygiene: every test runs in a fresh naming context
    (≙ GraphScoping.testGraph, dsl/GraphScoping.scala:8-15)."""
    from tensorframes_tpu.dsl import with_graph

    with with_graph():
        yield
