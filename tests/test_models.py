"""Model-family tests: logreg scoring through map_blocks, transformer
forward/training incl. the sharded (dp/tp/sp) step."""

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.models import logreg
from tensorframes_tpu.models import transformer as tr
from tensorframes_tpu.parallel import device_count, make_mesh


def test_logreg_scoring_via_map_blocks():
    x, _ = logreg.make_synthetic_mnist(64, num_features=16)
    df = tfs.frame_from_arrays({"features": x})
    params = logreg.init_params(num_features=16)
    scoring = logreg.scoring_program(params)
    out = tfs.map_blocks(lambda features: scoring(features), df)
    probs = np.stack([r["scores"] for r in out.collect()])
    assert probs.shape == (64, 10)
    assert np.allclose(probs.sum(axis=1), 1.0, atol=1e-5)
    labels = out.column_values("label")
    assert labels.dtype == np.int32
    assert (labels >= 0).all() and (labels < 10).all()


def test_logreg_training_reduces_loss():
    import optax

    x, y = logreg.make_synthetic_mnist(256, num_features=16, seed=1)
    params = logreg.init_params(num_features=16, seed=1)
    tx = optax.sgd(0.5)
    opt_state = tx.init(params)
    import jax

    first = None
    step = jax.jit(lambda p, s, f, l: logreg.train_step(p, s, f, l, tx))
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state, x, y)
    first = float(logreg.loss_fn(logreg.init_params(num_features=16, seed=1), x, y))
    assert float(loss) < first


def test_transformer_forward_shapes():
    cfg = tr.tiny()
    params = tr.init_params(cfg)
    tokens, _ = tr.synthetic_batch(cfg, 2, 8)
    hs = tr.forward(cfg, params, tokens)
    assert hs.shape == (2, 8, cfg.hidden)
    assert hs.dtype == cfg.dtype


def test_transformer_mask():
    import jax.numpy as jnp

    cfg = tr.tiny()
    params = tr.init_params(cfg)
    tokens, _ = tr.synthetic_batch(cfg, 2, 8)
    mask = np.ones((2, 8), dtype=bool)
    mask[:, 4:] = False
    hs = tr.forward(cfg, params, tokens, mask=jnp.asarray(mask))
    assert np.isfinite(np.asarray(hs, dtype=np.float32)).all()


def test_transformer_embed_program_via_map_blocks():
    cfg = tr.tiny()
    params = tr.init_params(cfg)
    tokens, _ = tr.synthetic_batch(cfg, 12, 8)
    df = tfs.frame_from_arrays({"tokens": tokens})
    prog = tr.embed_program(cfg, params)
    out = tfs.map_blocks(lambda tokens: prog(tokens), df)
    emb = np.stack([r["embedding"] for r in out.collect()])
    assert emb.shape == (12, cfg.hidden)
    assert np.isfinite(emb).all()


def test_transformer_train_step_single_device():
    import optax

    cfg = tr.tiny()
    params = tr.init_params(cfg)
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)
    step = tr.make_train_step(cfg, tx)
    import jax

    step = jax.jit(step)
    tokens, targets = tr.synthetic_batch(cfg, 4, 8)
    l0 = None
    for i in range(5):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        if i == 0:
            l0 = float(loss)
    assert float(loss) < l0


@pytest.mark.skipif(device_count() < 8, reason="needs 8 virtual devices")
def test_transformer_sharded_train_step():
    import jax
    import optax

    mesh = make_mesh({"dp": 2, "tp": 2, "sp": 2})
    cfg = tr.tiny()
    params = tr.init_params(cfg)
    tx = optax.adamw(1e-3)
    step, data_sharding, param_sh, init_opt = tr.make_sharded_train_step(cfg, mesh, tx)
    tokens, targets = tr.synthetic_batch(cfg, 4, 16)
    tokens = jax.device_put(tokens, data_sharding)
    targets = jax.device_put(targets, data_sharding)
    params = jax.device_put(params, param_sh)
    opt_state = init_opt(params)
    params2, opt_state, loss = step(params, opt_state, tokens, targets)
    assert np.isfinite(float(loss))
    # tp sharding preserved on outputs (round-trip through the step)
    qkv = params2["layers"][0]["attn"]["qkv"]
    assert len(qkv.sharding.spec) == 2 and qkv.sharding.spec[1] == "tp"
    # optimizer state mirrors the param sharding (mu of qkv is tp-sharded)
    mu_qkv = opt_state[0].mu["layers"][0]["attn"]["qkv"]
    assert mu_qkv.sharding.spec == qkv.sharding.spec


@pytest.mark.skipif(device_count() < 8, reason="needs 8 virtual devices")
def test_sharded_matches_unsharded_loss():
    import jax
    import optax

    cfg = tr.tiny()
    params = tr.init_params(cfg)
    tokens, targets = tr.synthetic_batch(cfg, 4, 16)
    ref_loss = float(tr.loss_fn(cfg, params, tokens, targets))

    mesh = make_mesh({"dp": 2, "tp": 2, "sp": 2})
    tx = optax.adamw(1e-3)
    step, data_sharding, param_sh, init_opt = tr.make_sharded_train_step(cfg, mesh, tx)
    p = jax.device_put(params, param_sh)
    opt_state = init_opt(p)
    t = jax.device_put(tokens, data_sharding)
    g = jax.device_put(targets, data_sharding)
    _, _, loss = step(p, opt_state, t, g)
    assert abs(float(loss) - ref_loss) < 5e-2  # bf16 tolerance


def test_bert_embed_row_program_via_map_rows():
    cfg = tr.tiny()
    params = tr.init_params(cfg, seed=0)
    tokens, _ = tr.synthetic_batch(cfg, 6, 8, seed=0)
    df = tfs.frame_from_arrays({"tokens": tokens}, num_blocks=2)
    prog = tr.embed_row_program(cfg, params)
    out = tfs.map_rows(lambda tokens: prog(tokens), df)
    emb = np.stack([r["embedding"] for r in out.collect()])
    assert emb.shape == (6, cfg.hidden)
    # per-row program equals the block program
    block_prog = tr.embed_program(cfg, params)
    import jax.numpy as jnp
    want = np.asarray(block_prog(jnp.asarray(tokens))["embedding"])
    # bf16 activations: different-but-valid fusion orders between the
    # vmapped verb path and the block path round differently
    np.testing.assert_allclose(emb, want, rtol=3e-2, atol=3e-2)


def test_remat_matches_no_remat_gradients():
    """jax.checkpoint rematerialization changes memory, not math."""
    import jax
    import jax.numpy as jnp

    base = tr.tiny(dtype=jnp.float32)
    remat = tr.tiny(dtype=jnp.float32, remat=True)
    params = tr.init_params(base, seed=0)
    tokens, targets = tr.synthetic_batch(base, 4, 8, seed=0)

    def loss_of(cfg):
        return lambda p: tr.loss_fn(cfg, p, jnp.asarray(tokens), jnp.asarray(targets))

    l0, g0 = jax.value_and_grad(loss_of(base))(params)
    l1, g1 = jax.value_and_grad(loss_of(remat))(params)
    assert float(l0) == pytest.approx(float(l1), rel=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
