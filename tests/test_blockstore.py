"""Out-of-core data plane (ISSUE 15): spillable block store, streaming
partitioner, chunked multi-part ingest, file-shuffle transport, TFG111.

The multi-process shuffle correctness workers (2 real OS processes,
bit-identity to the single-process oracle, kill -9 mid-shuffle) live in
tests/test_distributed.py next to the other subprocess fleets.
"""

import json
import os
import threading

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu import configure
from tensorframes_tpu.config import get_config
from tensorframes_tpu.blockstore import (
    BlockCorruptionError,
    BlockStore,
    SpilledFrame,
    shuffle as fshuffle,
    stream_chain,
)
from tensorframes_tpu.blockstore.store import (
    QUARANTINES,
    RELOAD_SECONDS,
    SPILL_SECONDS,
)
from tensorframes_tpu.observability.metrics import REGISTRY
from tensorframes_tpu.resilience import inject


@pytest.fixture
def store(tmp_path):
    st = BlockStore(root=str(tmp_path / "store"), budget_bytes=1 << 16)
    yield st
    st.close()


def _mk_block(i, rows=4096):
    return {
        "x": np.arange(rows, dtype=np.float64) + i,
        "y": (np.arange(rows) % 7).astype(np.int64),
        "s": [f"r{i}-{j}" for j in range(rows)],
    }


# ---------------------------------------------------------------------------
# store: budget, spill, reload, CRC
# ---------------------------------------------------------------------------

def test_put_get_roundtrip_resident(store):
    b = _mk_block(0, rows=16)
    ref = store.put(b)
    got = store.get(ref)
    np.testing.assert_array_equal(got["x"], b["x"])
    np.testing.assert_array_equal(got["y"], b["y"])
    assert got["s"] == b["s"]
    assert ref.num_rows == 16


def test_budget_enforced_lru_spill(store):
    refs = [store.put(_mk_block(i)) for i in range(8)]
    assert store.resident_bytes <= store.budget_bytes
    assert store.spilled_bytes > 0
    # reload of a spilled block is CRC-checked and bit-identical
    for i, ref in enumerate(refs):
        got = store.get(ref)
        np.testing.assert_array_equal(got["x"], _mk_block(i)["x"])
        assert got["s"][0] == f"r{i}-0"
    # the gauges track the live store
    snap = {m["name"]: m for m in REGISTRY.snapshot()}
    assert snap["tftpu_blockstore_resident_bytes"]["value"] >= 0
    assert SPILL_SECONDS.count > 0
    assert RELOAD_SECONDS.count > 0


def test_mmap_reload_zero_copy_view(store):
    ref = store.put(_mk_block(3))
    store.spill(ref)
    got = store.get(ref, mmap=True)
    assert isinstance(got["x"], np.ndarray)
    np.testing.assert_array_equal(np.asarray(got["x"]), _mk_block(3)["x"])


def test_pinned_blocks_never_lru_spilled(store):
    pinned = store.put(_mk_block(0), pin=True)
    for i in range(1, 8):
        store.put(_mk_block(i))
    e = store._entries[pinned.block_id]
    assert e.block is not None and not e.spilled


def test_crc_corruption_quarantined_counted_and_recomputed(store):
    b = _mk_block(5)
    ref = store.put(b)
    store.spill(ref)
    # flip bytes in the dense segment behind the store's back
    seg = store._seg_dir(ref.block_id)
    with open(os.path.join(seg, "manifest.json")) as f:
        manifest = json.load(f)
    dense = [c for c in manifest["columns"] if c["kind"] == "dense"][0]
    path = os.path.join(seg, dense["file"])
    with open(path, "r+b") as f:
        f.seek(13)
        f.write(b"\xde\xad\xbe\xef")
    before = QUARANTINES.value
    with pytest.raises(BlockCorruptionError):
        store.get(ref)
    assert QUARANTINES.value == before + 1
    # the bad segment was renamed aside, never served again
    assert not os.path.isdir(seg)
    assert any(
        e.startswith(os.path.basename(seg)) and ".quarantine." in e
        for e in os.listdir(store.root)
    )
    # recompute-from-lineage heals: segment republishes, reload is clean
    healed = store.get_or_recompute(ref, lambda: _mk_block(5))
    np.testing.assert_array_equal(healed["x"], b["x"])
    np.testing.assert_array_equal(store.get(ref)["x"], b["x"])


def test_spill_fault_site_fails_the_put(store):
    with inject("blockstore.spill", OSError("disk gone")) as inj:
        with pytest.raises(OSError):
            for i in range(8):  # enough puts to cross the budget
                store.put(_mk_block(i))
    assert inj.fired >= 1


def test_drop_frees_segment_and_accounting(store):
    ref = store.put(_mk_block(1))
    store.spill(ref)
    assert store.spilled_bytes > 0
    store.drop(ref)
    assert store.spilled_bytes == 0
    with pytest.raises(KeyError):
        store.get(ref)


def test_dataplane_metrics_preregistered_at_import():
    names = {m["name"] for m in REGISTRY.snapshot()}
    for want in (
        "tftpu_blockstore_resident_bytes",
        "tftpu_blockstore_spilled_bytes",
        "tftpu_blockstore_spill_seconds",
        "tftpu_blockstore_reload_seconds",
        "tftpu_blockstore_shuffle_bytes_total",
        "tftpu_blockstore_quarantines_total",
        "tftpu_blockstore_hostgather_bytes_total",
    ):
        assert want in names, want


# ---------------------------------------------------------------------------
# streaming partitioner
# ---------------------------------------------------------------------------

def _dataset(n=20_000, seed=0):
    rng = np.random.default_rng(seed)
    return (
        rng.integers(0, 13, size=n).astype(np.int64),
        rng.integers(0, 100, size=n).astype(np.float64),
    )


def _chunks(k, v, size=1000):
    for lo in range(0, len(k), size):
        yield {"k": k[lo:lo + size], "v": v[lo:lo + size]}


def _agg(f):
    with tfs.with_graph():
        w_in = tfs.block(f, "w", tf_name="w_input")
        return tfs.aggregate(
            tfs.reduce_sum(w_in, axis=0, name="w"), f.group_by("k")
        )


def _chain(f):
    g = tfs.map_blocks(lambda v: {"w": v * 2.0}, f)
    g = g.filter(lambda w: w > 50.0)
    return _agg(g)


def test_stream_chain_fold_bit_identical_to_in_memory(tmp_path):
    k, v = _dataset()
    st = BlockStore(root=str(tmp_path / "s"), budget_bytes=1 << 14)
    res = stream_chain(_chunks(k, v), chain_fn=_chain, fold_fn=_agg, store=st)
    # the walk spilled: a tiny budget cannot hold the partials resident
    assert st.resident_bytes <= st.budget_bytes
    oracle = _chain(tfs.frame_from_arrays({"k": k, "v": v}, num_blocks=20))
    np.testing.assert_array_equal(
        res.column_values("k"), oracle.column_values("k")
    )
    np.testing.assert_array_equal(
        res.column_values("w"), oracle.column_values("w")
    )
    st.close()


def test_stream_chain_map_filter_spilled_frame_roundtrip(tmp_path):
    k, v = _dataset()

    def mf(f):
        g = tfs.map_blocks(lambda v: {"w": v * 3.0}, f)
        return g.filter(lambda w: w > 30.0)

    st = BlockStore(root=str(tmp_path / "s"), budget_bytes=1 << 14)
    sf = stream_chain(_chunks(k, v), chain_fn=mf, store=st)
    assert isinstance(sf, SpilledFrame)
    assert st.spilled_bytes > 0
    mem = mf(tfs.frame_from_arrays({"k": k, "v": v}, num_blocks=20))
    out = sf.to_frame()
    np.testing.assert_array_equal(
        out.column_values("w"), mem.column_values("w")
    )
    np.testing.assert_array_equal(
        out.column_values("k"), mem.column_values("k")
    )
    assert sf.num_rows == mem.num_rows
    sf.drop()
    st.close()


def test_stream_chain_empty_source_raises(tmp_path):
    with pytest.raises(ValueError, match="no chunks"):
        stream_chain(iter(()))


def test_spill_to_and_back(tmp_path):
    f = tfs.frame_from_arrays(
        {"a": np.arange(1000, dtype=np.float64),
         "s": [f"n{i}" for i in range(1000)]},
        num_blocks=4,
    )
    st = BlockStore(root=str(tmp_path / "s"), budget_bytes=0)
    sf = f.spill_to(st)
    assert sf.num_blocks == 4 and st.spilled_bytes > 0
    back = sf.to_frame()
    np.testing.assert_array_equal(
        back.column_values("a"), f.column_values("a")
    )
    assert list(back.column_values("s")) == list(f.column_values("s"))
    st.close()


# ---------------------------------------------------------------------------
# chunked multi-part ingest
# ---------------------------------------------------------------------------

def _write_csv_parts(d, nparts=3, rows=100):
    os.makedirs(d, exist_ok=True)
    paths = []
    for i in range(nparts):
        p = os.path.join(d, f"part-{i}.csv")
        with open(p, "w") as f:
            f.write("k,v,s\n")
            for j in range(rows):
                f.write(f"{i * rows + j},{j / 2},name{i}_{j}\n")
        paths.append(p)
    return paths


def test_read_csv_directory_chunked_through_store(tmp_path):
    d = str(tmp_path / "parts")
    _write_csv_parts(d)
    frame = tfs.read_csv(d)
    assert frame.num_rows == 300
    kv = frame.column_values("k")
    assert kv[0] == 0 and kv[-1] == 299 and kv.dtype == np.int64
    assert frame.column_values("v").dtype == np.float64
    assert frame.blocks()[0]["s"][0] == "name0_0"
    # the dense blocks are store-backed views pinned to the frame
    assert hasattr(frame, "_data_plane")


def test_read_csv_part_list_preserves_order(tmp_path):
    d = str(tmp_path / "parts")
    paths = _write_csv_parts(d)
    frame = tfs.read_csv(list(reversed(paths)))
    kv = frame.column_values("k")
    assert kv[0] == 200 and kv[-1] == 99  # caller order IS row order


def test_read_csv_single_file_unchanged(tmp_path):
    d = str(tmp_path / "parts")
    [p0, *_] = _write_csv_parts(d)
    frame = tfs.read_csv(p0)
    assert frame.num_rows == 100 and not hasattr(frame, "_data_plane")


def test_scan_csv_chunk_bound(tmp_path):
    d = str(tmp_path / "parts")
    _write_csv_parts(d, nparts=2, rows=100)
    chunks = list(tfs.scan_csv(d, rows_per_chunk=32))
    assert all(len(c["k"]) <= 32 for c in chunks)
    assert sum(len(c["k"]) for c in chunks) == 200
    # first-part inference is pinned for later parts
    assert all(c["k"].dtype == np.int64 for c in chunks)


def test_read_parquet_directory(tmp_path):
    pytest.importorskip("pyarrow")
    d = str(tmp_path / "pq")
    os.makedirs(d)
    for i in range(2):
        t = tfs.frame_from_arrays({
            "a": np.arange(50, dtype=np.int64) + i * 50,
            "b": np.linspace(0.0, 1.0, 50),
        })
        tfs.write_parquet(t, os.path.join(d, f"p{i}.parquet"))
    frame = tfs.read_parquet(d)
    assert frame.num_rows == 100
    np.testing.assert_array_equal(
        frame.column_values("a"), np.arange(100, dtype=np.int64)
    )


def test_read_csv_empty_dir_raises(tmp_path):
    d = tmp_path / "empty"
    d.mkdir()
    with pytest.raises(ValueError, match="no part files"):
        tfs.read_csv(str(d))


def test_read_csv_all_header_only_parts_gives_typed_empty_frame(tmp_path):
    d = tmp_path / "hdr"
    d.mkdir()
    for i in range(2):
        (d / f"p{i}.csv").write_text("k,v,s\n")
    frame = tfs.read_csv(str(d))
    assert frame.num_rows == 0
    assert frame.columns == ["k", "v", "s"]  # same as the 1-file path


def test_read_csv_header_only_first_part_does_not_poison_types(tmp_path):
    d = tmp_path / "mix"
    d.mkdir()
    (d / "a0.csv").write_text("k,s\n")  # header-only, sorts FIRST
    (d / "a1.csv").write_text("k,s\n1,alice\n2,bob\n")
    frame = tfs.read_csv(str(d))
    assert frame.num_rows == 2
    assert frame.column_values("k").dtype == np.int64  # not float64
    assert list(frame.column_values("s")) == ["alice", "bob"]


def test_gauges_aggregate_across_live_stores(tmp_path):
    from tensorframes_tpu.blockstore.store import RESIDENT_BYTES

    base = RESIDENT_BYTES.value
    a = BlockStore(root=str(tmp_path / "a"), budget_bytes=1 << 30)
    b = BlockStore(root=str(tmp_path / "b"), budget_bytes=1 << 30)
    a.put({"x": np.arange(1000.0)})
    b.put({"x": np.arange(500.0)})
    assert RESIDENT_BYTES.value - base == 1500 * 8
    a.close()
    assert RESIDENT_BYTES.value - base == 500 * 8  # b still counted
    b.close()
    assert RESIDENT_BYTES.value - base == 0


# ---------------------------------------------------------------------------
# TFG111 — larger-than-budget materialization
# ---------------------------------------------------------------------------

def test_tfg111_flags_oversized_to_host_with_streaming_fix():
    old = get_config().block_budget_bytes
    try:
        configure(block_budget_bytes=1 << 10)
        f = tfs.frame_from_arrays({"a": np.arange(10_000, dtype=np.float64)})
        h = tfs.map_blocks(lambda a: {"b": a * 2.0}, f).to_host()
        rep = tfs.lint_plan(h)
        finds = rep.by_code("TFG111")
        assert len(finds) == 1
        assert "stream_chain" in finds[0].fix  # names the alternative
        assert "TFTPU_BLOCK_BUDGET_MB" in finds[0].message
        assert "stream" in finds[0].explain()
        # a chain rooted on the oversized materialization flags too
        h2 = tfs.map_blocks(lambda b: {"c": b + 1.0}, h)
        assert tfs.lint_plan(h2).by_code("TFG111")
    finally:
        configure(block_budget_bytes=old)


def test_tfg111_silent_under_budget():
    f = tfs.frame_from_arrays({"a": np.arange(100, dtype=np.float64)})
    h = tfs.map_blocks(lambda a: {"b": a * 2.0}, f).to_host()
    assert not tfs.lint_plan(h).by_code("TFG111")


def test_estimated_bytes_lower_bound():
    f = tfs.frame_from_arrays({
        "a": np.arange(1000, dtype=np.float64),
        "b": np.arange(1000, dtype=np.int64),
    })
    assert f.estimated_bytes == 1000 * 16
    lazy = tfs.map_blocks(lambda a: {"c": a * 2.0}, f)
    assert lazy.estimated_bytes is not None  # maps preserve the count


# ---------------------------------------------------------------------------
# file-shuffle transport (single-rank legs; 2-process correctness +
# kill -9 live in tests/test_distributed.py)
# ---------------------------------------------------------------------------

@pytest.fixture
def shuffle_env(tmp_path, monkeypatch):
    monkeypatch.setenv("TFTPU_SHUFFLE_DIR", str(tmp_path / "shuffle"))
    monkeypatch.setenv("TFTPU_SHUFFLE_RANK", "0")
    monkeypatch.setenv("TFTPU_SHUFFLE_NPROCS", "1")
    fshuffle._reset_for_tests()
    yield
    fshuffle._reset_for_tests()


def test_exchange_rows_picks_file_transport(shuffle_env):
    from tensorframes_tpu.ops import exchange as ex

    cols = {"k": np.asarray([3, 1, 2], np.int64), "s": ["a", "b", "c"]}
    out = ex.exchange_rows(cols, np.zeros(3, np.int64))
    np.testing.assert_array_equal(out["k"], cols["k"])
    assert out["s"] == cols["s"]
    assert ex.last_exchange_stats["transport"] == "files"


def test_exchange_rows_collective_transport_without_shuffle_dir(monkeypatch):
    monkeypatch.delenv("TFTPU_SHUFFLE_DIR", raising=False)
    monkeypatch.delenv("TFTPU_FLEET_DIR", raising=False)
    fshuffle._reset_for_tests()
    assert not fshuffle.enabled()
    from tensorframes_tpu.ops import exchange as ex

    # single jax process: the collective path degenerates to identity
    cols = {"k": np.asarray([1, 2], np.int64)}
    out = ex.exchange_rows(cols, np.zeros(2, np.int64))
    np.testing.assert_array_equal(out["k"], cols["k"])
    assert "transport" not in (ex.last_exchange_stats or {})


def test_fleet_dir_fallback_requires_transport_opt_in(tmp_path, monkeypatch):
    monkeypatch.delenv("TFTPU_SHUFFLE_DIR", raising=False)
    monkeypatch.setenv("TFTPU_FLEET_DIR", str(tmp_path / "fleet"))
    monkeypatch.delenv("TFTPU_SHUFFLE_TRANSPORT", raising=False)
    fshuffle._reset_for_tests()
    assert not fshuffle.enabled()  # supervised fleets keep collectives
    monkeypatch.setenv("TFTPU_SHUFFLE_TRANSPORT", "files")
    fshuffle._reset_for_tests()
    assert fshuffle.enabled()
    assert fshuffle.shuffle_dir().endswith(os.path.join("fleet", "shuffle"))
    fshuffle._reset_for_tests()


def test_framed_read_transient_retried_then_persistent_quarantines(
    tmp_path,
):
    # (the self-partition short-circuits in memory, so single-rank
    # exchanges never read files — drive the framed read directly)
    p = str(tmp_path / "x.part")
    fshuffle._publish(p, b"payload")
    # one transient read fault: absorbed by the framed read's retries
    with inject("shuffle.exchange", OSError("torn read"),
                max_times=1) as inj:
        assert fshuffle._read_framed(p, describe="t") == b"payload"
    assert inj.fired == 1
    # persistent faults exhaust retries -> quarantine + raise
    with inject("shuffle.exchange", OSError("bad disk")):
        with pytest.raises(fshuffle.ShuffleCorruptionError):
            fshuffle._read_framed(p, describe="t")
    assert not os.path.exists(p)  # renamed aside, never served again


def test_corrupt_peer_payload_raises_and_keeps_round_lockstep(
    tmp_path, monkeypatch,
):
    """Act as rank 0 of a 2-rank fleet whose peer published a CORRUPT
    payload: the exchange quarantines it and raises — and still
    advances the local round counter, so a caller that survives the
    error stays in lockstep with the peers that completed the round."""
    monkeypatch.setenv("TFTPU_SHUFFLE_DIR", str(tmp_path / "sh"))
    monkeypatch.setenv("TFTPU_SHUFFLE_RANK", "0")
    monkeypatch.setenv("TFTPU_SHUFFLE_NPROCS", "2")
    fshuffle._reset_for_tests()
    ctx = fshuffle.context()
    rd = os.path.join(ctx.root, f"round-{ctx.rounds:06d}-rc")
    os.makedirs(rd)
    with open(os.path.join(rd, "s00001-d00000.part"), "wb") as f:
        f.write(b"garbage, not a framed payload")
    fshuffle._publish(os.path.join(rd, "src-00001.done"), b"")
    r0 = ctx.rounds
    with pytest.raises(fshuffle.ShuffleCorruptionError):
        fshuffle.exchange([b"a", b"b"], name="rc", timeout=10.0)
    assert ctx.rounds == r0 + 1  # advanced despite the failure
    fshuffle._reset_for_tests()


def test_shuffle_hang_names_missing_rank(tmp_path, monkeypatch):
    monkeypatch.setenv("TFTPU_SHUFFLE_DIR", str(tmp_path / "sh"))
    monkeypatch.setenv("TFTPU_SHUFFLE_RANK", "0")
    monkeypatch.setenv("TFTPU_SHUFFLE_NPROCS", "2")
    fshuffle._reset_for_tests()
    from tensorframes_tpu.resilience.fleet import HungDispatchError

    with pytest.raises(HungDispatchError, match=r"rank\(s\) \[1\]"):
        fshuffle.exchange([b"a", b"b"], name="hang", timeout=0.5)
    fshuffle._reset_for_tests()


def test_vote_all_and_allshare_single_rank(shuffle_env):
    assert fshuffle.vote_all(True, name="v1") is True
    assert fshuffle.vote_all(False, name="v2") is False
    t = fshuffle.allshare_table(
        {"k": np.asarray([1, 2], np.int64), "s": ["x", "y"]}, name="t"
    )
    np.testing.assert_array_equal(t["k"], [1, 2])
    assert t["s"] == ["x", "y"]


def test_distributed_aggregate_single_rank_matches_local(shuffle_env):
    k = np.asarray([2, 1, 2, 1, 3], np.int64)
    v = np.asarray([1.0, 2.0, 3.0, 4.0, 5.0])
    f = tfs.frame_from_arrays({"k": k, "v": v})

    def agg(fr):
        with tfs.with_graph():
            v_in = tfs.block(fr, "v", tf_name="v_input")
            return tfs.aggregate(
                tfs.reduce_sum(v_in, axis=0, name="v"), fr.group_by("k")
            )

    res = fshuffle.distributed_aggregate(f, ["k"], agg)
    oracle = agg(f)
    np.testing.assert_array_equal(
        res.column_values("k"), oracle.column_values("k")
    )
    np.testing.assert_array_equal(
        res.column_values("v"), oracle.column_values("v")
    )


# ---------------------------------------------------------------------------
# kv pool host-swap tier
# ---------------------------------------------------------------------------

def test_kvpool_spill_restore_bit_identical(tmp_path):
    from tensorframes_tpu.models import generation as gen
    from tensorframes_tpu.serving.kvpool import (
        PagedKVPool, PoolAccountingError,
    )

    st = BlockStore(root=str(tmp_path / "kv"), budget_bytes=0)
    pool = PagedKVPool(
        gen.gpt_tiny(), num_pages=9, page_size=4, max_pages_per_seq=4
    )
    pool.alloc(1, 2)
    pool.alloc(2, 3)
    snap = pool.spill(st)
    assert st.spilled_bytes > 0  # pool snapshots are pushed to disk
    before = {k: np.asarray(v).copy() for k, v in pool.columns.items()}
    pool.free_seq(1)
    pool.free_seq(2)
    pool.restore(st, snap)
    for k in before:
        np.testing.assert_array_equal(np.asarray(pool.columns[k]), before[k])
    assert pool.owned(1) == snap["owned"][1]
    assert pool.owned(2) == snap["owned"][2]
    pool.check()
    # geometry mismatch refuses before touching anything
    other = PagedKVPool(
        gen.gpt_tiny(), num_pages=17, page_size=4, max_pages_per_seq=4
    )
    with pytest.raises(PoolAccountingError):
        other.restore(st, snap)
    st.close()


def test_kvpool_spill_folds_swap_segments(tmp_path):
    """PR 18 follow-up: per-sequence host-swap segments ride the
    whole-pool spill() snapshot (keyed by the request's cross-restart
    trace id) and adopt_swapped() re-homes them into a FRESH engine's
    swap store bit-identically — swap segments no longer die with the
    engine that wrote them."""
    from tensorframes_tpu.models import generation as gen
    from tensorframes_tpu.serving.kvpool import PagedKVPool

    st = BlockStore(root=str(tmp_path / "kv"), budget_bytes=0)
    swap = BlockStore(root=str(tmp_path / "swap"), budget_bytes=0)
    pool = PagedKVPool(
        gen.gpt_tiny(), num_pages=9, page_size=4, max_pages_per_seq=4
    )
    pool.alloc(1, 2)
    payload = {
        k: np.asarray(v)[1:3].copy() for k, v in pool.columns.items()
    }
    snap1 = pool.swap_out_seq(swap, 1, payload)
    # the engine rides pos/generated/replay on the same snapshot dict
    snap1["pos"] = 7
    snap1["generated"] = [3, 1]
    snap1["replay"] = []
    whole = pool.spill(st, swaps={"tid-1": snap1}, swap_store=swap)
    assert set(whole["swapped"]) == {"tid-1"}
    # the folded entry re-published the segment into the spill store:
    # dropping the ORIGINAL swap store must not lose it
    swap.drop(snap1["ref"])
    swap.close()
    swap2 = BlockStore(root=str(tmp_path / "swap2"), budget_bytes=0)
    manifest = pool.adopt_swapped(st, whole, swap2)
    assert set(manifest) == {"tid-1"}
    entry = manifest["tid-1"]
    assert entry["pos"] == 7 and entry["generated"] == [3, 1]
    assert int(entry["pages"]) == 2
    got = swap2.get(entry["ref"])
    for k in payload:
        np.testing.assert_array_equal(np.asarray(got[k]), payload[k])
    # restore() with a swap_store returns the same manifest alongside
    # the bit-identical pool rehydration
    pool.free_seq(1)
    swap3 = BlockStore(root=str(tmp_path / "swap3"), budget_bytes=0)
    manifest2 = pool.restore(st, whole, swap_store=swap3)
    assert set(manifest2) == {"tid-1"}
    pool.check()
    for s in (st, swap2, swap3):
        s.close()


# ---------------------------------------------------------------------------
# concurrency: loader-thread puts while the consumer gets
# ---------------------------------------------------------------------------

def test_store_threaded_put_get(store):
    errs = []

    def producer():
        try:
            for i in range(16):
                store.put(_mk_block(i, rows=512))
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=producer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    for ref in store.refs():
        got = store.get(ref)
        assert len(got["x"]) == 512
    assert store.resident_bytes <= store.budget_bytes
