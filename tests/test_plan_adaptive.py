"""Adaptive query optimizer (ISSUE 14): aggregate pushdown below
joins, multi-join reordering, and stats-sidecar re-optimization must be
BIT-IDENTICAL to the ``TFTPU_FUSION=0`` per-stage replay across join
orders × hows × key dtypes × fetch shapes; ineligible shapes must keep
the static path (counted, TFG110-diagnosed); and the feedback loop
must record ``reoptimized`` decisions on a recurring pipeline's second
execution without changing a single bit.

Like tests/test_relational_pipeline.py, the equivalence sweeps honor
the AMBIENT ``TFTPU_REOPT`` configuration — under the CI REOPT=0 smoke
leg the same assertions pin the static path. Tests that assert the
adaptive machinery ENGAGED skip when re-optimization is off."""

import glob
import os

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.observability.metrics import REGISTRY
from tensorframes_tpu.plan import stats as plan_stats


@pytest.fixture(autouse=True)
def _fusion_on():
    """Pin fusion on (the sweeps compare against the escape hatch);
    leave plan_reopt at its AMBIENT value so the CI REOPT=0 leg
    exercises the static decisions through the same assertions."""
    cfg = tfs.configure()
    before = (cfg.plan_fusion, cfg.plan_reopt)
    tfs.configure(plan_fusion=True)
    yield
    tfs.configure(plan_fusion=before[0], plan_reopt=before[1])


_reopt_only = pytest.mark.skipif(
    not tfs.configure().plan_reopt,
    reason="adaptive optimizer disabled (TFTPU_REOPT=0)",
)


def _unfused(build):
    tfs.configure(plan_fusion=False)
    try:
        return build()
    finally:
        tfs.configure(plan_fusion=True)


def _count(kind):
    for d in REGISTRY.snapshot():
        if (
            d["name"] == "tftpu_plan_cost_decisions_total"
            and d["labels"].get("decision") == kind
        ):
            return float(d.get("value", 0.0))
    return 0.0


def _sidecar_count(event):
    for d in REGISTRY.snapshot():
        if (
            d["name"] == "tftpu_plan_reopt_sidecar_total"
            and d["labels"].get("event") == event
        ):
            return float(d.get("value", 0.0))
    return 0.0


def _rows_equal(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert ra.keys() == rb.keys()
        for k in ra:
            va, vb = np.asarray(ra[k]), np.asarray(rb[k])
            assert va.dtype == vb.dtype, (k, va.dtype, vb.dtype)
            np.testing.assert_array_equal(va, vb)


def _fact(n=240, key_kind="int", num_blocks=3, seed=3):
    rng = np.random.default_rng(seed)
    k1 = rng.integers(0, 8, n)
    k2 = rng.integers(0, 6, n)
    cols = {
        "x": (np.arange(n) % 7).astype(np.int64),
        "dead": np.ones(n, np.float32),
    }
    if key_kind == "str":
        rows = [
            {"k1": f"g{int(a)}", "k2": int(b), "x": int(c),
             "dead": 1.0}
            for a, b, c in zip(k1, k2, cols["x"])
        ]
        return tfs.frame_from_rows(rows, num_blocks=num_blocks)
    cols["k1"] = k1.astype(np.int32)
    cols["k2"] = k2.astype(np.int32)
    return tfs.frame_from_arrays(cols, num_blocks=num_blocks)


def _dim(key, values, extra_name, n_extra_dtype=np.int64,
         key_kind="int"):
    if key_kind == "str":
        rows = [
            {key: f"g{int(v)}", extra_name: int(v) * 10}
            for v in values
        ]
        return tfs.frame_from_rows(rows, num_blocks=1)
    return tfs.frame_from_arrays({
        key: np.asarray(values, dtype=np.int32),
        extra_name: (np.asarray(values) * 10).astype(n_extra_dtype),
    }, num_blocks=1)


def _agg_over_join(fact, dims, group_keys, op="reduce_sum",
                   hows=None, fills=None):
    """map → join(s) → aggregate(sum/min/max/mean of the mapped probe
    column) — the canonical pushdown shape."""
    f1 = tfs.map_blocks(lambda x: {"z": x * x}, fact)
    j = f1
    for i, dim in enumerate(dims):
        how = (hows or ["inner"] * len(dims))[i]
        fill = (fills or [None] * len(dims))[i]
        on = list(dim.schema.names)[0]
        j = j.join(dim, on=on, how=how, fill_value=fill)
    with tfs.with_graph():
        z_in = tfs.block(j, "z", tf_name="z_input")
        red = {
            "reduce_sum": tfs.reduce_sum,
            "reduce_min": tfs.reduce_min,
            "reduce_max": tfs.reduce_max,
            "reduce_mean": tfs.reduce_mean,
        }[op]
        agg = tfs.aggregate(
            red(z_in, axis=0, name="z"), j.group_by(*group_keys)
        )
    return agg


# ---------------------------------------------------------------------------
# equivalence property sweep: join orders × hows × key dtypes × fetches
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("key_kind", ["int", "str"])
@pytest.mark.parametrize("how,fill", [
    ("inner", None), ("left", -1), ("right", -1), ("outer", -1),
])
@pytest.mark.parametrize(
    "op", ["reduce_sum", "reduce_min", "reduce_max", "reduce_mean"]
)
def test_agg_over_join_equivalence(key_kind, how, fill, op):
    fact = _fact(key_kind=key_kind)
    dim = _dim("k1", range(0, 8, 2), "w1", key_kind=key_kind)

    def build():
        return _agg_over_join(
            fact, [dim], ["k1"], op=op, hows=[how], fills=[fill]
        ).collect()

    _rows_equal(build(), _unfused(build))


@pytest.mark.parametrize("dim_order", [(0, 1), (1, 0)])
@pytest.mark.parametrize("op", ["reduce_sum", "reduce_min"])
def test_two_join_agg_equivalence_across_orders(dim_order, op):
    fact = _fact()
    dims = [
        _dim("k1", range(0, 8, 2), "w1"),
        _dim("k2", range(6), "w2"),
    ]
    ordered = [dims[i] for i in dim_order]

    def build():
        return _agg_over_join(
            fact, ordered, ["k1", "k2"], op=op
        ).collect()

    _rows_equal(build(), _unfused(build))


@pytest.mark.parametrize("value_dtype", [np.float32, np.float64])
def test_float_fetch_keeps_static_path_and_matches(value_dtype):
    """Order-sensitive float sums never push below joins — and the
    result still matches the escape hatch exactly."""
    n = 120
    fact = tfs.frame_from_arrays({
        "k1": (np.arange(n) % 5).astype(np.int32),
        "x": (np.arange(n) % 9).astype(value_dtype),
    }, num_blocks=2)
    dim = _dim("k1", range(5), "w1")

    def build():
        return _agg_over_join(fact, [dim], ["k1"]).collect()

    before = _count("pushdown_aggregate")
    got = build()
    assert _count("pushdown_aggregate") == before
    _rows_equal(got, _unfused(build))


def test_nonalgebraic_fetch_stays_generic_and_matches():
    fact = _fact(n=60)
    dim = _dim("k1", range(8), "w1")

    def build():
        f1 = tfs.map_blocks(lambda x: {"z": x * x}, fact)
        j = f1.join(dim, on="k1")

        with tfs.with_graph():
            z_in = tfs.block(j, "z", tf_name="z_input")
            # non-algebraic: sum of squares has no segment lowering
            fetch = tfs.reduce_sum(z_in * z_in, axis=0, name="z")
            agg = tfs.aggregate(fetch, j.group_by("k1"))
        return agg.collect()

    _rows_equal(build(), _unfused(build))


# ---------------------------------------------------------------------------
# the adaptive paths engage (and are counted)
# ---------------------------------------------------------------------------

@_reopt_only
def test_pushdown_engages_and_is_counted():
    fact = _fact()
    dim = _dim("k1", range(0, 8, 2), "w1")  # selective inner join
    before = _count("pushdown_aggregate")
    agg = _agg_over_join(fact, [dim], ["k1"])
    got = agg.collect()
    assert _count("pushdown_aggregate") == before + 1
    # inner join on half the key space drops the odd groups
    assert {r["k1"] for r in got} == {0, 2, 4, 6}


@_reopt_only
def test_multilevel_pushdown_below_two_joins():
    fact = _fact()
    dims = [_dim("k1", range(8), "w1"), _dim("k2", range(0, 6, 2), "w2")]
    before = _count("pushdown_aggregate")

    def build():
        return _agg_over_join(fact, dims, ["k1", "k2"]).collect()

    got = build()
    assert _count("pushdown_aggregate") == before + 1
    _rows_equal(got, _unfused(build))


@_reopt_only
def test_build_side_pushdown_with_unique_probe_keys():
    """Group keys + values from the build side push when the probe's
    keys are unique (each build row joins at most once)."""
    probe = tfs.frame_from_arrays({
        "k1": np.arange(0, 8, 2, dtype=np.int32),
        "junk": np.ones(4, np.float32),
    }, num_blocks=1)
    rng = np.random.default_rng(5)
    big = tfs.frame_from_arrays({
        "k1": rng.integers(0, 8, 100).astype(np.int32),
        "w1": np.arange(100, dtype=np.int64),
    }, num_blocks=1)

    def build():
        j = probe.join(big, on="k1")
        with tfs.with_graph():
            w_in = tfs.block(j, "w1", tf_name="w1_input")
            agg = tfs.aggregate(
                tfs.reduce_max(w_in, axis=0, name="w1"),
                j.group_by("k1"),
            )
        return agg.collect()

    before = _count("pushdown_aggregate")
    got = build()
    assert _count("pushdown_aggregate") == before + 1
    _rows_equal(got, _unfused(build))


@_reopt_only
def test_duplicate_build_keys_fall_back_counted_and_match():
    fact = _fact()
    dup = tfs.frame_from_arrays({
        "k1": np.asarray([0, 0, 2, 4], np.int32),
        "w1": np.asarray([1, 2, 3, 4], np.int64),
    }, num_blocks=1)

    def build():
        return _agg_over_join(fact, [dup], ["k1"]).collect()

    before_push = _count("pushdown_aggregate")
    before_inel = _count("pushdown_ineligible")
    got = build()
    assert _count("pushdown_aggregate") == before_push
    assert _count("pushdown_ineligible") == before_inel + 1
    _rows_equal(got, _unfused(build))


@_reopt_only
def test_join_chain_reorders_by_build_size_and_matches():
    rng = np.random.default_rng(7)
    n = 600
    fact = tfs.frame_from_arrays({
        "k1": rng.integers(0, 32, n).astype(np.int32),
        "k2": rng.integers(0, 8, n).astype(np.int32),
        "x": (np.arange(n) % 5).astype(np.int64),
    }, num_blocks=2)
    big_dim = _dim("k1", range(32), "w1")     # bigger build, keeps all
    small_dim = _dim("k2", range(0, 8, 2), "w2")  # smaller, selective

    def build():
        f1 = tfs.map_blocks(lambda x: {"z": x + 1}, fact)
        out = f1.join(big_dim, on="k1").join(small_dim, on="k2")
        return out.select(["k1", "k2", "z", "w1", "w2"]).collect()

    before = _count("reorder_joins")
    got = build()
    # smaller build side (small_dim) should run first: a reorder
    assert _count("reorder_joins") == before + 1
    _rows_equal(got, _unfused(build))


@_reopt_only
def test_left_join_chain_keeps_order_and_matches():
    """Reordering is inner-only: a left join in the chain keeps the
    recorded order (counted static) and stays bit-identical."""
    fact = _fact(n=100)
    d1 = _dim("k1", range(0, 8, 2), "w1")
    d2 = _dim("k2", range(6), "w2")

    def build():
        f1 = tfs.map_blocks(lambda x: {"z": x + 1}, fact)
        out = f1.join(d1, on="k1", how="left", fill_value=-1).join(
            d2, on="k2"
        )
        return out.select(["k1", "k2", "z", "w1", "w2"]).collect()

    before = _count("join_order_static")
    got = build()
    assert _count("join_order_static") >= before + 1
    _rows_equal(got, _unfused(build))


# ---------------------------------------------------------------------------
# the feedback loop: second execution re-optimizes, bit-identically
# ---------------------------------------------------------------------------

@_reopt_only
def test_second_execution_records_reoptimized_and_is_bit_identical():
    plan_stats.clear_memory()
    fact = _fact(seed=11)
    dims = [_dim("k1", range(0, 8, 2), "w1"), _dim("k2", range(6), "w2")]

    def build():
        return _agg_over_join(fact, dims, ["k1", "k2"]).collect()

    r0 = _count("reoptimized")
    first = build()
    first_delta = _count("reoptimized") - r0
    r1 = _count("reoptimized")
    second = build()
    assert _count("reoptimized") > r1, (
        "second execution of a recurring pipeline must record "
        "reoptimized decisions"
    )
    assert first_delta == 0 or first_delta <= _count("reoptimized") - r1
    _rows_equal(first, second)
    _rows_equal(second, _unfused(build))


@_reopt_only
def test_observed_selectivity_reoptimizes_join_order():
    """First run orders by build size; the second consults the
    sidecar's observed selectivities (counted reoptimized) and still
    matches the escape hatch bit-for-bit."""
    plan_stats.clear_memory()
    rng = np.random.default_rng(13)
    n = 400
    fact = tfs.frame_from_arrays({
        "k1": rng.integers(0, 4, n).astype(np.int32),
        "k2": rng.integers(0, 16, n).astype(np.int32),
        "x": (np.arange(n) % 5).astype(np.int64),
    }, num_blocks=2)
    # small build that keeps everything vs larger build that is
    # selective: static (size) order is wrong, observed order fixes it
    keep_all = _dim("k1", range(4), "w1")
    selective = _dim("k2", range(0, 16, 4), "w2")

    def build():
        f1 = tfs.map_blocks(lambda x: {"z": x + 1}, fact)
        out = f1.join(keep_all, on="k1").join(selective, on="k2")
        return out.select(["k1", "k2", "z", "w1", "w2"]).collect()

    first = build()
    r0 = _count("reoptimized")
    second = build()
    assert _count("reoptimized") > r0
    _rows_equal(first, second)
    _rows_equal(second, _unfused(build))


@_reopt_only
def test_pushdown_reoptimized_away_when_joins_are_selective(tmp_path):
    """Observed survival below the threshold flips the second run to
    the aggregate-above path — a genuinely different lowering, still
    bit-identical."""
    plan_stats.clear_memory()
    n = 400
    fact = tfs.frame_from_arrays({
        "k1": np.arange(n, dtype=np.int32),  # keys 0..n-1
        "x": (np.arange(n) % 5).astype(np.int64),
    }, num_blocks=2)
    # build side matches 2 of 400 keys: survival ~0.005 < threshold
    dim = _dim("k1", [0, 1], "w1")

    def build():
        return _agg_over_join(fact, [dim], ["k1"]).collect()

    p0 = _count("pushdown_aggregate")
    first = build()
    assert _count("pushdown_aggregate") == p0 + 1
    s0 = _count("pushdown_skipped_selective")
    second = build()
    assert _count("pushdown_skipped_selective") == s0 + 1
    _rows_equal(first, second)
    _rows_equal(second, _unfused(build))


# ---------------------------------------------------------------------------
# stats-sidecar hygiene: corrupt/stale records quarantine, never fail
# ---------------------------------------------------------------------------

@_reopt_only
def test_sidecar_roundtrip_corruption_and_stale_quarantine(tmp_path):
    import json

    was = tfs.configure().compilation_cache_dir
    tfs.configure(compilation_cache_dir=str(tmp_path))
    try:
        plan_stats.clear_memory()
        fact = _fact(seed=17)
        dim = _dim("k1", range(0, 8, 2), "w1")

        def build():
            return _agg_over_join(fact, [dim], ["k1"]).collect()

        first = build()
        files = [
            # the strategy-wall table (ISSUE 17) shares the directory;
            # this test pins the per-FINGERPRINT record contract
            f for f in glob.glob(str(tmp_path / "planstats" / "*.json"))
            if not f.endswith("strategy_walls.json")
        ]
        assert len(files) == 1, "one sidecar record per plan fingerprint"
        rec = json.load(open(files[0]))
        assert rec["v"] == plan_stats.FORMAT_VERSION
        assert rec["execs"] >= 1 and "push" in rec

        # corrupt record: quarantined (counted + unlinked), decisions
        # fall back to static, results unchanged — never a failure
        plan_stats.clear_memory()
        with open(files[0], "w") as f:
            f.write("{definitely not json")
        q0 = _sidecar_count("quarantine")
        second = build()
        assert _sidecar_count("quarantine") == q0 + 1
        _rows_equal(first, second)
        # the run after quarantine re-recorded a fresh sidecar
        assert os.path.exists(files[0])

        # stale record (format bump): same contract
        plan_stats.clear_memory()
        rec2 = json.load(open(files[0]))
        rec2["v"] = plan_stats.FORMAT_VERSION + 999
        json.dump(rec2, open(files[0], "w"))
        q1 = _sidecar_count("quarantine")
        third = build()
        assert _sidecar_count("quarantine") == q1 + 1
        _rows_equal(first, third)
    finally:
        tfs.configure(compilation_cache_dir=was)
        plan_stats.clear_memory()


def test_reopt_off_disables_recording_and_rewrites(tmp_path):
    was_reopt = tfs.configure().plan_reopt
    was_dir = tfs.configure().compilation_cache_dir
    tfs.configure(plan_reopt=False, compilation_cache_dir=str(tmp_path))
    try:
        plan_stats.clear_memory()
        fact = _fact(seed=19)
        dim = _dim("k1", range(0, 8, 2), "w1")

        def build():
            return _agg_over_join(fact, [dim], ["k1"]).collect()

        p0 = _count("pushdown_aggregate")
        r0 = _count("reorder_joins")
        o0 = _count("reoptimized")
        first = build()
        second = build()
        assert _count("pushdown_aggregate") == p0
        assert _count("reorder_joins") == r0
        assert _count("reoptimized") == o0
        assert not glob.glob(str(tmp_path / "planstats" / "*.json"))
        _rows_equal(first, second)
        _rows_equal(second, _unfused(build))
    finally:
        tfs.configure(plan_reopt=was_reopt,
                      compilation_cache_dir=was_dir)


# ---------------------------------------------------------------------------
# TFG110 — missed-aggregate-pushdown diagnostics
# ---------------------------------------------------------------------------

def test_tfg110_float_fetch_names_the_blocking_fetch():
    n = 60
    fact = tfs.frame_from_arrays({
        "k1": (np.arange(n) % 4).astype(np.int32),
        "x": (np.arange(n) % 7).astype(np.float32),
    }, num_blocks=2)
    dim = _dim("k1", range(4), "w1")
    agg = _agg_over_join(fact, [dim], ["k1"])
    rep = tfs.lint_plan(agg)
    found = rep.by_code("TFG110")
    assert found, "float fetch above a join must flag TFG110"
    assert found[0].subject == "z"
    assert "fix:" in found[0].explain()


def test_tfg110_key_not_grouped_names_the_join_key():
    fact = _fact(n=60)
    dim = _dim("k2", range(6), "w2")
    agg = _agg_over_join(fact, [dim], ["k1"])  # groups miss join key k2
    rep = tfs.lint_plan(agg)
    found = rep.by_code("TFG110")
    assert found
    assert found[0].subject == "k2"


def test_tfg110_clean_for_eligible_and_joinless_shapes():
    fact = _fact(n=60)
    dim = _dim("k1", range(8), "w1")
    agg = _agg_over_join(fact, [dim], ["k1"])  # eligible: no finding
    assert not tfs.lint_plan(agg).by_code("TFG110")
    f1 = tfs.map_blocks(lambda x: {"z": x * x}, fact)
    with tfs.with_graph():
        z_in = tfs.block(f1, "z", tf_name="z_input")
        plain = tfs.aggregate(
            tfs.reduce_sum(z_in, axis=0, name="z"), f1.group_by("k1")
        )
    assert not tfs.lint_plan(plain).by_code("TFG110")


@_reopt_only
def test_tfg110_runtime_duplicate_keys_recorded_after_force():
    fact = _fact(n=60)
    dup = tfs.frame_from_arrays({
        "k1": np.asarray([0, 0, 2], np.int32),
        "w1": np.asarray([1, 2, 3], np.int64),
    }, num_blocks=1)
    agg = _agg_over_join(fact, [dup], ["k1"])
    agg.collect()
    rep = tfs.lint_plan(agg)
    found = rep.by_code("TFG110")
    assert found
    assert any(
        "duplicate" in d.message for d in found
    ), [d.message for d in found]


def test_tfg110_counter_preregistered():
    prom = REGISTRY.to_prometheus()
    assert 'tftpu_analysis_diagnostics_total{code="TFG110"}' in prom


def test_decision_counters_preregistered():
    prom = REGISTRY.to_prometheus()
    for kind in (
        "pushdown_aggregate", "pushdown_ineligible",
        "pushdown_skipped_selective", "reorder_joins",
        "join_order_static", "reoptimized",
    ):
        assert (
            f'tftpu_plan_cost_decisions_total{{decision="{kind}"}}'
            in prom
        ), kind
    for event in ("load", "store", "quarantine"):
        assert (
            f'tftpu_plan_reopt_sidecar_total{{event="{event}"}}' in prom
        ), event


def test_estimated_rows_never_forces():
    fact = _fact(n=60)
    assert fact.estimated_rows == 60
    f1 = tfs.map_blocks(lambda x: {"z": x * x}, fact)
    assert f1.estimated_rows == 60
    assert not f1.is_materialized
    flt = f1.filter(lambda z: {"keep": z > 3})
    assert flt.estimated_rows is None  # data-dependent row count
    assert not flt.is_materialized
