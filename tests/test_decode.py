"""Iterative decode engine tests (ISSUE 11): the paged-KV contracts.

What must hold, stated in serving/decode.py: batched decode is
bit-identical per request to solo decode (and, for this formulation, to
the dense-cache ``gen.generate`` oracle); a warmed engine performs zero
steady-state XLA compiles under any join/leave mix; the pool's page
accounting never leaks or double-frees under random join/leave/evict
interleavings; an undersized pool preempts (evict + requeue + replay)
and still completes every request bit-identically; a full pool cannot
hold a request past its deadline (the pull-mode batcher's expirer
covers the slot-wait queue); and the slot/prompt bucket ladders are the
ONE serving ladder (``compilecache`` single source of truth).
"""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.models import generation as gen
from tensorframes_tpu.models import transformer as tr
from tensorframes_tpu.serving import (
    DeadlineExceededError,
    DecodeConfig,
    DecodeEngine,
    PagedKVPool,
    PoolAccountingError,
    PoolExhaustedError,
    RejectedError,
    Server,
    ServingConfig,
    ServingError,
    serve_http,
)
from tensorframes_tpu.serving import metrics as sm
from tensorframes_tpu.validation import ValidationError


@pytest.fixture(scope="module")
def model():
    cfg = gen.gpt_tiny()
    params = tr.quantize_params(tr.init_params(cfg, seed=0))
    return cfg, params


@pytest.fixture(scope="module")
def engine(model):
    """One started engine shared by the read-only tests (compiles are
    the expensive part; every test below uses distinct prompts)."""
    cfg, params = model
    eng = DecodeEngine("t_shared", cfg, params, DecodeConfig(
        max_slots=4, page_size=8, max_prompt_len=16, max_new_tokens=8,
    ))
    eng.start()
    yield eng
    eng.stop(drain=True, timeout=120)


def _prompts(n, lo, hi, seed, vocab):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, vocab, (int(rng.integers(lo, hi + 1)),)).astype(
            np.int32
        )
        for _ in range(n)
    ]


def _reference(model, prompt, new):
    cfg, params = model
    return np.asarray(
        gen.generate(cfg, params, prompt[None], new, kv_quant=True)
    )


def _hog_pool(pool):
    """Deterministically exhaust a pool from the outside (respecting
    the per-sequence cap) so no join can find prompt pages."""
    seqs = []
    while pool.num_free:
        seq = 10_000 + len(seqs)
        pool.alloc(seq, min(pool.num_free, pool.max_pages_per_seq))
        seqs.append(seq)
    return seqs


def _unhog_pool(pool, seqs):
    for s in seqs:
        pool.free_seq(s)


# ---------------------------------------------------------------------------
# KV pool accounting invariants
# ---------------------------------------------------------------------------

def test_kvpool_property_sweep_no_leak_no_double_free(model):
    """Random join/extend/leave/evict interleavings: after EVERY
    mutation the page partition holds (free ∪ owned = all usable pages,
    nothing in two places)."""
    cfg, _ = model
    pool = PagedKVPool(cfg, num_pages=17, page_size=4,
                       max_pages_per_seq=4)
    rng = np.random.default_rng(7)
    live = {}
    next_seq = 0
    for _ in range(500):
        op = rng.integers(0, 3)
        if op == 0:  # join: allocate a fresh sequence's prompt pages
            n = int(rng.integers(1, 4))
            if pool.num_free >= n:
                pool.alloc(next_seq, n)
                live[next_seq] = n
                next_seq += 1
        elif op == 1 and live:  # extend a random live sequence
            seq = int(rng.choice(list(live)))
            if live[seq] < pool.max_pages_per_seq and pool.num_free:
                pool.alloc(seq, 1)
                live[seq] += 1
        elif op == 2 and live:  # leave/evict
            seq = int(rng.choice(list(live)))
            assert pool.free_seq(seq) == live.pop(seq)
        pool.check()
    for seq in list(live):
        pool.free_seq(seq)
    pool.check()
    assert pool.num_free == pool.usable_pages


def test_kvpool_exhaustion_and_double_free_raise(model):
    cfg, _ = model
    pool = PagedKVPool(cfg, num_pages=4, page_size=4,
                       max_pages_per_seq=3)
    pool.alloc(0, 3)
    with pytest.raises(PoolExhaustedError):
        pool.alloc(1, 1)
    pool.check()
    # double free via corrupted ownership: simulate by freeing twice
    assert pool.free_seq(0) == 3
    assert pool.free_seq(0) == 0  # idempotent by absence, not an error
    pool._owned[5] = [1]          # page 1 is free: corruption
    with pytest.raises(PoolAccountingError):
        pool.free_seq(5)
    del pool._owned[5]
    pool.check()


def test_kvpool_floor_and_table(model):
    cfg, _ = model
    with pytest.raises(ValueError):
        # cannot hold the null page + one full sequence
        PagedKVPool(cfg, num_pages=3, page_size=4, max_pages_per_seq=3)
    pool = PagedKVPool(cfg, num_pages=5, page_size=4,
                       max_pages_per_seq=3)
    got = pool.alloc(9, 2)
    table = pool.table(9)
    assert table.shape == (3,) and table.dtype == np.int32
    assert list(table[:2]) == got and table[2] == 0
    assert not pool.null_table().any()
    fr = pool.as_frame()
    assert fr.num_rows == 5
    assert set(fr.schema.names) == {"k", "v", "k_scale", "v_scale"}


# ---------------------------------------------------------------------------
# Bucket-ladder single source of truth (satellite)
# ---------------------------------------------------------------------------

def test_decode_slot_buckets_are_the_serving_ladder():
    from tensorframes_tpu.compilecache import (
        decode_slot_buckets,
        decode_warmup_grid,
        serving_row_buckets,
    )
    from tensorframes_tpu.ops.executor import bucket_rows, bucket_table

    assert decode_slot_buckets(13) == serving_row_buckets(13)
    assert set(decode_slot_buckets(13)) <= set(bucket_table())
    for n in range(1, 14):
        assert bucket_rows(n) in decode_slot_buckets(13)
    grid = decode_warmup_grid(4, 16)
    assert grid["decode"] == serving_row_buckets(4)
    assert grid["prefill"] == serving_row_buckets(16)
    with pytest.raises(ValueError):
        decode_slot_buckets(0)


# ---------------------------------------------------------------------------
# Engine correctness: bit-identity, zero compiles
# ---------------------------------------------------------------------------

def test_batched_decode_bit_identical_to_solo_and_reference(
    model, engine
):
    cfg, _ = model
    prompts = _prompts(6, 3, 16, seed=11, vocab=cfg.vocab_size)
    futs = [engine.submit({"prompt": p}) for p in prompts]
    outs = [f.result(300)["tokens"] for f in futs]
    solo = [engine.call({"prompt": p}, timeout=300)["tokens"]
            for p in prompts]
    for i, p in enumerate(prompts):
        assert outs[i].shape == (1, 8)
        assert np.array_equal(outs[i], solo[i]), (
            f"request {i}: batched != solo (bit-identity)"
        )
        assert np.array_equal(outs[i], _reference(model, p, 8)), (
            f"request {i}: engine != dense-cache generate() oracle"
        )


def test_warmed_engine_zero_steady_state_compiles(model, engine):
    from tensorframes_tpu.ops.executor import _JIT_MISSES

    cfg, _ = model
    prompts = _prompts(10, 3, 16, seed=23, vocab=cfg.vocab_size)
    # pipeline through every phase once (module fixture already did,
    # but be independent of test order)
    engine.call({"prompt": prompts[0]}, timeout=300)
    miss0 = _JIT_MISSES.value
    futs = []
    for i, p in enumerate(prompts):  # staggered join/leave mix
        futs.append(engine.submit({"prompt": p}))
        if i % 3 == 0:
            futs[0].rows  # no-op; keep the submit loop non-uniform
            time.sleep(0.003)
    for f in futs:
        f.result(300)
    assert int(_JIT_MISSES.value - miss0) == 0, (
        "warmed decode engine hit XLA in steady state"
    )


def test_variable_max_new_tokens_per_request(model, engine):
    cfg, _ = model
    p = _prompts(1, 5, 10, seed=31, vocab=cfg.vocab_size)[0]
    out3 = engine.call({"prompt": p, "max_new_tokens": 3}, timeout=300)
    out8 = engine.call({"prompt": p, "max_new_tokens": 8}, timeout=300)
    assert out3["tokens"].shape == (1, 3)
    assert out8["tokens"].shape == (1, 8)
    # same greedy path: the shorter request is a prefix of the longer
    assert np.array_equal(out3["tokens"][0], out8["tokens"][0, :3])


# ---------------------------------------------------------------------------
# Preemption / eviction under an undersized pool (acceptance)
# ---------------------------------------------------------------------------

def test_undersized_pool_preempts_evicts_and_completes(model):
    cfg, params = model
    # horizon 16+8=24 -> 3 pages of 8; pool holds one horizon + 1 spare
    eng = DecodeEngine("t_small_pool", cfg, params, DecodeConfig(
        max_slots=4, page_size=8, num_pages=5,
        max_prompt_len=16, max_new_tokens=8,
    ))
    eng.start()
    try:
        pre0 = sm.DECODE_PREEMPTIONS.value
        ev0 = sm.DECODE_EVICTIONS.value
        tok0 = sm.DECODE_TOKENS.value
        prompts = _prompts(5, 12, 16, seed=41, vocab=cfg.vocab_size)
        futs = [eng.submit({"prompt": p}) for p in prompts]
        outs = [f.result(600)["tokens"] for f in futs]
        assert sm.DECODE_PREEMPTIONS.value - pre0 > 0, (
            "undersized pool never preempted"
        )
        assert sm.DECODE_EVICTIONS.value - ev0 > 0
        # replayed resume tokens are recompute, not progress: the
        # fresh-token counter must see exactly requests × new tokens
        # even across (repeated) preemptions
        assert sm.DECODE_TOKENS.value - tok0 == 5 * 8
        # none lost, and every preempted/resumed request is
        # bit-identical to the never-preempted oracle
        assert len(outs) == len(prompts)
        for p, o in zip(prompts, outs):
            assert np.array_equal(o, _reference(model, p, 8)), (
                "preempted request did not resume bit-identically"
            )
    finally:
        eng.stop(drain=True, timeout=300)
    eng.pool.check()
    assert eng.pool.num_free == eng.pool.usable_pages


def test_minimal_pool_forward_progress_no_livelock(model):
    cfg, params = model
    # the floor configuration: exactly one full horizon of pages —
    # maximum preemption pressure; completion proves no livelock
    eng = DecodeEngine("t_floor_pool", cfg, params, DecodeConfig(
        max_slots=3, page_size=4, num_pages=5,
        max_prompt_len=8, max_new_tokens=8,
    ))
    eng.start()
    try:
        prompts = _prompts(4, 6, 8, seed=43, vocab=cfg.vocab_size)
        futs = [eng.submit({"prompt": p}) for p in prompts]
        outs = [f.result(600)["tokens"] for f in futs]
        for p, o in zip(prompts, outs):
            assert np.array_equal(o, _reference(model, p, 8))
    finally:
        eng.stop(drain=True, timeout=300)
    eng.pool.check()


# ---------------------------------------------------------------------------
# Slot-wait deadlines + admission taxonomy (satellite)
# ---------------------------------------------------------------------------

def test_full_pool_cannot_hold_request_past_deadline(model):
    """The ISSUE 11 satellite: a request waiting for a free slot/pages
    expires on the CLOCK (the pull-mode batcher's expirer covers the
    slot-wait queue) — a full pool is not a hang."""
    cfg, params = model
    eng = DecodeEngine("t_deadline", cfg, params, DecodeConfig(
        max_slots=2, page_size=4, max_prompt_len=8, max_new_tokens=4,
    ))
    eng.start()
    try:
        # deterministically exhaust the pool from the outside while the
        # engine is idle: no join can find prompt pages
        hogs = _hog_pool(eng.pool)
        d0 = sm.DEADLINE_EXPIRED.value
        fut = eng.submit(
            {"prompt": np.arange(5, dtype=np.int32)}, deadline_s=0.2
        )
        t0 = time.perf_counter()
        with pytest.raises(DeadlineExceededError):
            fut.result(10)
        assert time.perf_counter() - t0 < 5.0
        assert sm.DEADLINE_EXPIRED.value - d0 >= 1
        # the engine is healthy: free the pages, the next request runs
        _unhog_pool(eng.pool, hogs)
        out = eng.call(
            {"prompt": np.arange(5, dtype=np.int32)}, timeout=300
        )
        assert out["tokens"].shape == (1, 4)
    finally:
        eng.stop(drain=True, timeout=120)


def test_admission_taxonomy_and_validation(model):
    cfg, params = model
    eng = DecodeEngine("t_taxonomy", cfg, params, DecodeConfig(
        max_slots=1, page_size=4, max_prompt_len=8, max_new_tokens=4,
        max_queue_requests=2, warmup=False,
    ))
    # closed before start
    with pytest.raises(RejectedError) as ri:
        eng.submit({"prompt": np.arange(3, dtype=np.int32)})
    assert ri.value.reason == "closed"
    eng.start()
    try:
        # malformed feeds
        with pytest.raises(ValidationError):
            eng.submit([1, 2, 3])
        with pytest.raises(ValidationError):
            eng.submit({"tokens": [1, 2]})
        with pytest.raises(ValidationError):
            eng.submit({"prompt": [1, 2], "temperature": 0.5})
        with pytest.raises(ValidationError):
            eng.submit({"prompt": []})
        with pytest.raises(ValidationError):
            eng.submit({"prompt": [[1, 2], [3, 4]]})
        with pytest.raises(ValidationError):
            eng.submit({"prompt": [0, cfg.vocab_size]})
        with pytest.raises(ValidationError):
            eng.submit({"prompt": [1], "max_new_tokens": 0})
        with pytest.raises(ValueError):
            eng.submit({"prompt": [1]}, deadline_s=0.0)
        # oversized prompt: too_large, counted
        with pytest.raises(RejectedError) as ri:
            eng.submit({"prompt": np.zeros(9, np.int32)})
        assert ri.value.reason == "too_large"
        # queue_full: exhaust the pool so nothing joins, then overfill
        hogs = _hog_pool(eng.pool)
        futs = [eng.submit({"prompt": np.arange(4, dtype=np.int32)})
                for _ in range(2)]
        with pytest.raises(RejectedError) as ri:
            eng.submit({"prompt": np.arange(4, dtype=np.int32)})
        assert ri.value.reason == "queue_full"
        _unhog_pool(eng.pool, hogs)
        for f in futs:
            assert f.result(300)["tokens"].shape == (1, 4)
    finally:
        eng.stop(drain=True, timeout=120)
    # closed after stop
    with pytest.raises(RejectedError) as ri:
        eng.submit({"prompt": np.arange(3, dtype=np.int32)})
    assert ri.value.reason == "closed"


def test_stop_without_drain_fails_loudly(model):
    cfg, params = model
    eng = DecodeEngine("t_nodrain", cfg, params, DecodeConfig(
        max_slots=1, page_size=4, max_prompt_len=8, max_new_tokens=4,
        warmup=False,
    ))
    eng.start()
    _hog_pool(eng.pool)  # keep requests queued
    futs = [eng.submit({"prompt": np.arange(4, dtype=np.int32)})
            for _ in range(2)]
    eng.stop(drain=False, timeout=60)
    for f in futs:
        with pytest.raises(ServingError):
            f.result(10)


def test_engine_config_validation(model):
    cfg, params = model
    with pytest.raises(ValueError):
        DecodeEngine("t_bad", cfg, params, DecodeConfig(
            max_prompt_len=40, max_new_tokens=40,  # > max_seq_len=48
        ))
    with pytest.raises(ValueError):
        DecodeEngine("t_bad2", cfg, params, DecodeConfig(max_slots=0))


# ---------------------------------------------------------------------------
# Server integration + HTTP
# ---------------------------------------------------------------------------

def test_register_decode_server_and_http(model):
    cfg, params = model
    srv = Server(ServingConfig(max_batch_rows=8))
    eng = srv.register_decode("gen", cfg, params, DecodeConfig(
        max_slots=2, page_size=4, max_prompt_len=8, max_new_tokens=4,
    ))
    with pytest.raises(ValueError):
        srv.register_decode("gen", cfg, params)  # name collision
    srv.start()
    httpd = serve_http(srv, port=0)
    port = httpd.server_address[1]
    try:
        assert srv.endpoints() == ["gen"]
        out = srv.call("gen", {"prompt": [1, 2, 3]}, timeout=300)
        assert out["tokens"].shape == (1, 4)
        body = json.dumps({"inputs": {"prompt": [1, 2, 3]}}).encode()
        r = urllib.request.urlopen(
            urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/gen", body,
                {"Content-Type": "application/json"},
            ),
            timeout=120,
        )
        assert r.status == 200
        payload = json.loads(r.read())
        # streaming-final: ONE reply carrying the whole sequence,
        # bit-identical to the in-process call
        assert payload["outputs"]["tokens"] == out["tokens"].tolist()
        h = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=30
        ).read())
        assert h["running"] is True
        assert h["decode"]["gen"]["running_slots"] == 0
        assert h["decode"]["gen"]["free_pages"] == eng.pool.usable_pages
        # 504 taxonomy on slot-wait expiry
        hogs = _hog_pool(eng.pool)
        body = json.dumps({
            "inputs": {"prompt": [1, 2, 3]}, "deadline_s": 0.2,
        }).encode()
        with pytest.raises(urllib.error.HTTPError) as he:
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{port}/v1/gen", body,
                    {"Content-Type": "application/json"},
                ),
                timeout=120,
            )
        assert he.value.code == 504
        _unhog_pool(eng.pool, hogs)
    finally:
        httpd.shutdown()
        srv.stop(drain=True, timeout=120)


def test_register_decode_name_clash_with_flush_endpoint(model):
    cfg, params = model
    srv = Server(ServingConfig(max_batch_rows=8, warmup=False))
    schema = tfs.Schema([tfs.ColumnInfo(
        "x", tfs.dtypes.float32, tfs.Shape((tfs.Unknown, 4))
    )])
    holder = type("F", (), {"schema": schema})()
    import jax.numpy as jnp

    srv.register(
        "score", tfs.compile_program(
            lambda x: {"y": jnp.tanh(x)}, holder, block=False
        ),
    )
    with pytest.raises(ValueError):
        srv.register_decode("score", cfg, params)


# ---------------------------------------------------------------------------
# Observability
# ---------------------------------------------------------------------------

def test_decode_metrics_preregistered():
    from tensorframes_tpu.observability.metrics import REGISTRY

    names = {m.name for m in REGISTRY.collect()}
    for want in (
        "tftpu_decode_tokens_total",
        "tftpu_decode_steps_total",
        "tftpu_decode_ttft_seconds",
        "tftpu_decode_slot_occupancy",
        "tftpu_decode_free_pages",
        "tftpu_decode_preemptions_total",
        "tftpu_decode_evictions_total",
    ):
        assert want in names, f"{want} not pre-registered"
    assert set(sm.DECODE_STEPS) == {"prefill", "decode"}


def test_decode_flight_records(model):
    from tensorframes_tpu.observability import flight

    cfg, params = model
    eng = DecodeEngine("t_flight", cfg, params, DecodeConfig(
        max_slots=1, page_size=4, max_prompt_len=8, max_new_tokens=2,
    ))
    eng.start()
    try:
        eng.call({"prompt": [1, 2, 3]}, timeout=300)
    finally:
        eng.stop(drain=True, timeout=120)
    kinds = [r["kind"] for r in flight.RECORDER.records()
             if str(r.get("kind", "")).startswith("serving.decode")]
    for want in ("serving.decode.start", "serving.decode.join",
                 "serving.decode.finish", "serving.decode.stop"):
        assert want in kinds, f"missing flight record {want}"


# ---------------------------------------------------------------------------
# KV memory hierarchy (ISSUE 19): prefix cache + per-sequence host swap
# ---------------------------------------------------------------------------

def test_kvpool_property_sweep_swap_and_prefix_interleaved(model, tmp_path):
    """Random interleaving of swap_out/swap_in/prefix-share/copy-on-
    extend with join/extend/leave — the extended three-way partition
    (free + exclusive + shared-with-refcount) must hold after EVERY op,
    and draining everything returns the pool to fully allocatable."""
    from tensorframes_tpu.blockstore import BlockStore

    cfg, _ = model
    ps = 4
    pool = PagedKVPool(cfg, num_pages=33, page_size=ps,
                       max_pages_per_seq=6)
    store = BlockStore(root=str(tmp_path / "swap"), budget_bytes=0)
    rng = np.random.default_rng(19)
    vocab = 40
    # joins draw from shared templates so page-granular prefixes really
    # repeat (pure random prompts would never collide at 4 tokens)
    templates = [
        rng.integers(0, vocab, (ps * 4,)).astype(np.int32)
        for _ in range(3)
    ]
    live = {}      # seq -> prompt tokens
    swapped = []   # swap snapshots (with their prompt riding along)
    next_seq = [0]

    def fresh_seq():
        next_seq[0] += 1
        return next_seq[0] - 1

    ops = 0
    hits = cows = outs = resumes = published = 0
    for _ in range(650):
        op = int(rng.integers(0, 7))
        if op == 0:  # join, riding the prefix cache when it matches
            t = templates[int(rng.integers(0, len(templates)))]
            plen = int(rng.integers(1, ps * 4 + 1))
            cut = int(rng.integers(0, plen + 1))
            tokens = np.concatenate([
                t[:cut],
                rng.integers(0, vocab, (plen - cut,)).astype(np.int32),
            ]).astype(np.int32)
            need = pool.pages_needed(plen)
            if pool.num_allocatable < need:
                continue
            seq = fresh_seq()
            matched, covered, cow, _r = pool.prefix_match(tokens)
            if matched:
                pool.prefix_acquire(seq, matched)
                hits += 1
            if cow is not None:
                pool.copy_on_extend(seq, cow)
                cows += 1
            else:
                pool.alloc(seq, need - len(matched))
            if rng.integers(0, 2):
                published += pool.publish_prefix(seq, tokens)
            live[seq] = tokens
        elif op == 1 and live:  # extend (a decode step crossed a page)
            seq = int(rng.choice(sorted(live)))
            if (len(pool.seq_pages(seq)) < pool.max_pages_per_seq
                    and pool.num_allocatable >= 1):
                pool.alloc(seq, 1)
        elif op == 2 and live:  # leave (finish / evict-without-swap)
            seq = int(rng.choice(sorted(live)))
            pool.free_seq(seq)
            del live[seq]
        elif op == 3 and live:  # preempt with host-swap
            seq = int(rng.choice(sorted(live)))
            npg = len(pool.seq_pages(seq))
            block = {"payload": np.full((npg, 3), seq, np.int32)}
            snap = pool.swap_out_seq(store, seq, block)
            assert int(snap["pages"]) == npg
            snap["tokens"] = live.pop(seq)
            swapped.append(snap)
            outs += 1
        elif op == 4 and swapped:  # swap-resume under a fresh seq id
            snap = swapped.pop(int(rng.integers(0, len(swapped))))
            if pool.num_allocatable < int(snap["pages"]):
                swapped.append(snap)
                continue
            seq = fresh_seq()
            pages, block = pool.swap_in_seq(store, snap, seq)
            assert len(pages) == int(snap["pages"])
            assert block["payload"].shape == (len(pages), 3)
            live[seq] = snap["tokens"]
            resumes += 1
        elif op == 5 and live:  # publish again (idempotent at collisions)
            seq = int(rng.choice(sorted(live)))
            published += pool.publish_prefix(seq, live[seq])
        elif op == 6 and pool.num_allocatable >= 2:  # pressure burst
            seq = fresh_seq()
            pool.alloc(seq, 2)
            live[seq] = np.zeros(0, np.int32)
        pool.check()
        ops += 1
    assert ops >= 500
    # the sweep actually exercised every new op at least once
    assert hits > 0 and cows > 0 and outs > 0 and resumes > 0
    assert published > 0
    # drain: every page comes back, swap segments drop cleanly
    for seq in sorted(live):
        pool.free_seq(seq)
    for snap in swapped:
        store.drop(snap["ref"])
    pool.check()
    assert pool.num_allocatable == pool.usable_pages
    # cached refcount-0 shared pages reclaim under real demand
    big = fresh_seq()
    pool.alloc(big, pool.max_pages_per_seq)
    pool.check()
    pool.free_seq(big)
    store.close()


def test_kvpool_swap_misuse_raises(model, tmp_path):
    from tensorframes_tpu.blockstore import BlockStore

    cfg, _ = model
    pool = PagedKVPool(cfg, num_pages=9, page_size=4, max_pages_per_seq=4)
    store = BlockStore(root=str(tmp_path / "swap"), budget_bytes=0)
    with pytest.raises(PoolAccountingError):
        pool.swap_out_seq(store, 7, {"x": np.zeros((1, 2), np.int8)})
    pool.alloc(1, 2)
    snap = pool.swap_out_seq(
        store, 1, {"x": np.zeros((2, 2), np.int8)}
    )
    other = PagedKVPool(cfg, num_pages=9, page_size=8,
                        max_pages_per_seq=4)
    with pytest.raises(PoolAccountingError):
        other.swap_in_seq(store, snap, 1)  # page-size mismatch
    pages, _ = pool.swap_in_seq(store, snap, 2)
    assert len(pages) == 2
    pool.free_seq(2)
    pool.check()
    store.close()


def test_prefix_cache_hits_bit_identical_and_counted(model):
    """Cold -> exact repeat (copy-on-extend) -> shared-page + fresh
    suffix (suffix prefill): every reply bit-identical to the dense
    oracle, hits counted, zero steady-state compiles."""
    from tensorframes_tpu.ops.executor import _JIT_MISSES

    cfg, params = model
    eng = DecodeEngine("t_prefix", cfg, params, DecodeConfig(
        max_slots=4, page_size=8, max_prompt_len=16, max_new_tokens=8,
        prefix_cache=True,
    ))
    eng.start()
    try:
        h0 = sm.PREFIX_HITS.value
        miss0 = _JIT_MISSES.value
        rng = np.random.default_rng(53)
        shared = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
        cold = eng.call({"prompt": shared}, timeout=300)["tokens"]
        assert np.array_equal(cold, _reference(model, shared, 8))
        # exact repeat: whole-prompt reuse through copy-on-extend
        hot = eng.call({"prompt": shared}, timeout=300)["tokens"]
        assert np.array_equal(hot, cold)
        # shared first page, fresh suffix: suffix-only prefill
        p2 = np.concatenate([
            shared[:8],
            rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32),
        ])
        out2 = eng.call({"prompt": p2}, timeout=300)["tokens"]
        assert np.array_equal(out2, _reference(model, p2, 8))
        assert sm.PREFIX_HITS.value - h0 >= 2
        snap = eng.counters()
        assert snap["prefix_hits"] >= 2
        assert snap["shared_pages"] > 0
        assert int(_JIT_MISSES.value - miss0) == 0, \
            "prefix-cache path compiled in steady state"
        assert eng.pool.num_shared > 0
    finally:
        eng.stop(drain=True, timeout=300)
    eng.pool.check()


def test_swap_resume_undersized_pool_bit_identical(model, tmp_path):
    """kv_swap on an undersized pool: preemptions swap out instead of
    discarding, resumes restore pages instead of replaying, and every
    request still completes bit-identically to the dense oracle."""
    cfg, params = model
    new = 8
    eng = DecodeEngine("t_swap", cfg, params, DecodeConfig(
        max_slots=4, page_size=8, num_pages=1 + 2 * 3,
        max_prompt_len=16, max_new_tokens=new,
        kv_swap=True, swap_dir=str(tmp_path / "swap"),
    ))
    eng.start()
    try:
        o0, r0 = sm.KVSWAP_OUTS.value, sm.KVSWAP_RESUMES.value
        f0 = sm.KVSWAP_FALLBACKS.value
        t0 = sm.DECODE_TOKENS.value
        prompts = _prompts(8, 9, 16, seed=61, vocab=cfg.vocab_size)
        futs = [eng.submit({"prompt": p}) for p in prompts]
        outs = [f.result(600)["tokens"] for f in futs]
        for p, o in zip(prompts, outs):
            assert np.array_equal(o, _reference(model, p, new))
        assert sm.KVSWAP_OUTS.value - o0 > 0
        assert sm.KVSWAP_RESUMES.value - r0 > 0
        assert sm.KVSWAP_FALLBACKS.value - f0 == 0
        # swap resume regenerates nothing: fresh tokens only, once each
        assert sm.DECODE_TOKENS.value - t0 == len(prompts) * new
        snap = eng.counters()
        assert snap["swap_outs"] > 0 and snap["swap_resumes"] > 0
    finally:
        eng.stop(drain=True, timeout=600)
    eng.pool.check()
    assert eng.pool.num_free == eng.pool.usable_pages


def test_corrupted_swap_segment_counted_fallback_bit_identical(
    model, tmp_path
):
    """Flip a byte in every swap segment as it lands: swap-in hits a
    real CRC failure, the engine falls back to recompute-replay (the
    counted path), and NO request is lost — outputs stay bit-identical
    to the oracle."""
    import os

    cfg, params = model
    new = 8
    eng = DecodeEngine("t_swapcorrupt", cfg, params, DecodeConfig(
        max_slots=4, page_size=8, num_pages=1 + 2 * 3,
        max_prompt_len=16, max_new_tokens=new,
        kv_swap=True, swap_dir=str(tmp_path / "swap"),
    ))
    eng.start()
    store = eng._swap_store
    orig_put = store.put_spilled

    def corrupting_put(block):
        ref = orig_put(block)
        seg = store._seg_dir(ref.block_id)
        for fn in sorted(os.listdir(seg)):
            if fn.endswith(".bin"):
                path = os.path.join(seg, fn)
                with open(path, "r+b") as f:
                    b = f.read(1)
                    f.seek(0)
                    f.write(bytes([b[0] ^ 0xFF]))
                break
        return ref

    store.put_spilled = corrupting_put
    try:
        o0 = sm.KVSWAP_OUTS.value
        f0 = sm.KVSWAP_FALLBACKS.value
        r0 = sm.KVSWAP_RESUMES.value
        prompts = _prompts(8, 9, 16, seed=67, vocab=cfg.vocab_size)
        futs = [eng.submit({"prompt": p}) for p in prompts]
        outs = [f.result(600)["tokens"] for f in futs]
        assert sm.KVSWAP_OUTS.value - o0 > 0
        assert sm.KVSWAP_FALLBACKS.value - f0 > 0, \
            "corruption never engaged the counted fallback"
        assert sm.KVSWAP_RESUMES.value - r0 == 0
        for p, o in zip(prompts, outs):
            assert np.array_equal(o, _reference(model, p, new))
        assert eng.counters()["swap_fallbacks"] > 0
    finally:
        eng.stop(drain=True, timeout=600)
    eng.pool.check()


def test_swap_segments_survive_engine_restart(model, tmp_path):
    """PR 18 follow-up: a hard stop PARKS pending keyed swap segments
    instead of dropping them, spill() folds them into the whole-pool
    snapshot, and a FRESH engine restore()s them — redriven requests
    (same trace ids) resume through the counted swap-in path and every
    output stays bit-identical to the dense oracle."""
    from tensorframes_tpu.blockstore import BlockStore
    from tensorframes_tpu.observability import context as _ctx

    cfg, params = model
    new = 8

    def mk(name, swap_dir):
        return DecodeEngine(name, cfg, params, DecodeConfig(
            max_slots=4, page_size=8, num_pages=1 + 2 * 3,
            max_prompt_len=16, max_new_tokens=new,
            kv_swap=True, swap_dir=swap_dir,
        ))

    prompts = _prompts(8, 9, 16, seed=71, vocab=cfg.vocab_size)

    def drive(eng):
        futs = []
        for i, p in enumerate(prompts):
            with _ctx.request_scope(f"restart-{i}"):
                futs.append(eng.submit({"prompt": p}))
        return futs

    # catch the engine with at least one sequence swapped out: the
    # undersized pool preempts continuously, but a swap entry is
    # transient (it rejoins), so retry the hard stop until one is
    # pending at the instant the loop sees the stop flag
    eng = None
    for attempt in range(8):
        eng = mk(f"t_swapstop{attempt}",
                 str(tmp_path / f"swap{attempt}"))
        eng.start()
        drive(eng)
        deadline = time.time() + 120
        while time.time() < deadline and not eng._swap:
            time.sleep(0.001)
        eng.stop(drain=False, timeout=300)
        if eng._swap_parked:
            break
        eng.pool.check()
    assert eng._swap_parked, \
        "never caught a pending swapped sequence across 8 hard stops"

    st = BlockStore(root=str(tmp_path / "handoff"), budget_bytes=0)
    snap = eng.spill(st)
    assert len(snap["swapped"]) == len(set(snap["swapped"]))
    assert snap["swapped"], "spill() dropped the parked segments"
    assert eng._swap_store is None  # spill() closed the donor store

    eng2 = mk("t_swaprestored", str(tmp_path / "swap-b"))
    eng2.start()
    try:
        adopted = eng2.restore(st, snap)
        assert adopted == len(snap["swapped"])
        r0 = sm.KVSWAP_RESUMES.value
        outs = [f.result(600)["tokens"] for f in drive(eng2)]
        for p, o in zip(prompts, outs):
            assert np.array_equal(o, _reference(model, p, new))
        # at least one redriven request resumed from its restored
        # segment (the rest decode fresh — their segments were
        # consumed or never swapped)
        assert sm.KVSWAP_RESUMES.value - r0 > 0
        assert not eng2._swap_restored  # all adopted entries consumed
    finally:
        eng2.stop(drain=True, timeout=600)
    eng2.pool.check()
    assert eng2.pool.num_free == eng2.pool.usable_pages
    st.close()


def test_tfg113_prefix_cache_ineligible_diagnostic(model):
    """Repeated prompt prefixes on an engine with the cache OFF leave
    store_unarmed evidence while the engine runs; lint_plan surfaces
    it as TFG113 with the arm-the-cache fix; stopping the engine
    withdraws its evidence (a stopped endpoint's config can no longer
    be fixed — and later lint tests in this process stay clean)."""
    from tensorframes_tpu.serving import decode as dec

    cfg, params = model

    def lint():
        fr = tfs.frame_from_arrays(
            {"x": np.arange(8, dtype=np.float32)}
        )
        f2 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
        return tfs.lint_plan(f2)

    eng = DecodeEngine("t_tfg113", cfg, params, DecodeConfig(
        max_slots=2, page_size=8, max_prompt_len=16,
        max_new_tokens=2,
    ))
    eng.start()
    try:
        rng = np.random.default_rng(59)
        p = rng.integers(0, cfg.vocab_size, (12,)).astype(np.int32)
        eng.call({"prompt": p}, timeout=300)
        # one miss is not evidence...
        assert not any(
            e["reason"] == "store_unarmed"
            and e["endpoint"] == "t_tfg113"
            for e in dec.prefix_cache_events()
        )
        eng.call({"prompt": p.copy()}, timeout=300)
        # ...an OBSERVED repeat of the first page is
        evs = dec.prefix_cache_events()
        assert any(
            e["reason"] == "store_unarmed"
            and e["endpoint"] == "t_tfg113" for e in evs
        )
        found = lint().by_code("TFG113")
        assert found, "lint_plan did not surface TFG113"
        mine = [d for d in found if d.subject == "t_tfg113"]
        assert mine, "TFG113 finding not bound to the endpoint"
        assert "prefix_cache=True" in mine[0].fix
        assert "docs/analysis.md#tfg113" in mine[0].explain()
    finally:
        eng.stop(drain=True, timeout=300)
    # stop() withdrew the endpoint's evidence: later lints are clean
    assert not any(
        e["endpoint"] == "t_tfg113" for e in dec.prefix_cache_events()
    )
    assert not any(
        d.subject == "t_tfg113" for d in lint().by_code("TFG113")
    )


def test_kvswap_prefix_metrics_preregistered():
    from tensorframes_tpu.observability.metrics import REGISTRY

    names = {m.name for m in REGISTRY.collect()}
    for want in (
        "tftpu_kvswap_out_total",
        "tftpu_kvswap_resume_total",
        "tftpu_kvswap_fallback_total",
        "tftpu_kvswap_bytes_total",
        "tftpu_prefix_cache_hits_total",
        "tftpu_prefix_cache_misses_total",
        "tftpu_prefix_cache_shared_pages",
        "tftpu_prefix_cache_evictions_total",
    ):
        assert want in names, f"{want} not pre-registered"
