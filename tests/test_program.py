"""Program capture + static analysis tests (≙ TFInitializationSuite graph
import/analysis; graph file loading, test/dsl.scala:109-112)."""

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu import dtypes as dt
from tensorframes_tpu.program import (
    TensorSpec,
    analyze_program,
    load_program,
    program_from_function,
    save_program,
)
from tensorframes_tpu.shape import Shape, Unknown


def _specs(**kw):
    return {
        name: TensorSpec(name, dtype, Shape.from_any(shape))
        for name, (dtype, shape) in kw.items()
    }


def test_analysis_discovers_batch_dims():
    # output dims co-varying with an Unknown input dim are marked Unknown
    prog = program_from_function(
        lambda x: {"z": x + 1.0},
        _specs(x=(dt.float64, [None, 3])),
    )
    prog = analyze_program(prog)
    out = prog.output("z")
    assert out.shape.dims == (Unknown, 3)
    assert out.dtype is dt.float64


def test_analysis_static_dims_stay_static():
    import jax.numpy as jnp

    prog = program_from_function(
        lambda x: {"z": jnp.sum(x, axis=0)},
        _specs(x=(dt.float64, [None, 4])),
    )
    prog = analyze_program(prog)
    assert prog.output("z").shape.dims == (4,)


def test_analysis_hint_override():
    # the hint-override rule (TensorFlowOps.scala:126-133)
    prog = program_from_function(
        lambda x: {"z": x * 2.0},
        _specs(x=(dt.float64, [None])),
    )
    prog = analyze_program(prog, hints={"z": Shape.of(7)})
    assert prog.output("z").shape.dims == (7,)


def test_dsl_compile_inputs_outputs():
    with tfs.with_graph():
        a = tfs.placeholder(dt.float64, [None], name="a")
        b = tfs.placeholder(dt.float64, [None], name="b")
        s = tfs.add(a, b, name="s")
        prog = analyze_program(tfs.dsl.compile_fetches([s]))
    assert set(prog.input_names) == {"a", "b"}
    assert prog.output_names == ["s"]


def test_dsl_duplicate_fetch_names_rejected():
    # ≙ core.py:106-108 unique-column-name check
    with tfs.with_graph():
        a = tfs.placeholder(dt.float64, [None], name="a")
        f1 = tfs.identity(a).named("z")
        f2 = tfs.identity(a).named("z")
        with pytest.raises(ValueError):
            tfs.dsl.compile_fetches([f1, f2])


def test_dsl_name_dedup_counters():
    # TF-style name_1, name_2 dedup (dsl/Paths.scala:40-55)
    with tfs.with_graph():
        a = tfs.placeholder(dt.float64, [None], name="a")
        n1 = tfs.identity(a)
        n2 = tfs.identity(a)
        assert n1.name == "identity"
        assert n2.name == "identity_1"


def test_dsl_scopes():
    with tfs.with_graph():
        with tfs.scope("outer"):
            a = tfs.placeholder(dt.float64, [None], name="a")
            assert a.name == "outer/a"
            with tfs.scope("inner"):
                b = tfs.constant(1.0, name="c")
                assert b.name == "outer/inner/c"


def test_rename_inputs():
    prog = program_from_function(
        lambda x: {"z": x + 1.0}, _specs(x=(dt.float64, [None]))
    )
    prog2 = prog.rename_inputs({"x": "col"})
    assert prog2.input_names == ["col"]
    import jax.numpy as jnp

    out = prog2.fn({"col": jnp.asarray([1.0, 2.0])})
    assert np.allclose(np.asarray(out["z"]), [2.0, 3.0])


def test_save_load_roundtrip(tmp_path):
    # serialized StableHLO artifacts ≙ proto GraphDef files
    # (PythonInterface.scala:115-118)
    prog = program_from_function(
        lambda x: {"z": x * 3.0}, _specs(x=(dt.float32, [None]))
    )
    prog = analyze_program(prog)
    path = str(tmp_path / "prog.tfpu")
    save_program(prog, path)
    loaded = load_program(path)
    assert loaded.input_names == ["x"]
    import jax.numpy as jnp

    out = loaded.fn({"x": jnp.asarray(np.array([1.0, 2.0], np.float32))})
    z = np.asarray(out["z"])
    assert np.allclose(z, [3.0, 6.0])


def test_loaded_program_drives_map_blocks(tmp_path):
    prog = program_from_function(
        lambda x: {"z": x + 10.0}, _specs(x=(dt.float64, [None]))
    )
    prog = analyze_program(prog)
    path = str(tmp_path / "prog.tfpu")
    save_program(prog, path)
    loaded = load_program(path)
    df = tfs.frame_from_rows([{"x": float(i)} for i in range(4)])
    out = tfs.map_blocks(loaded, df).collect()
    assert [r["z"] for r in out] == [10.0 + i for i in range(4)]


def test_cost_analysis():
    import tensorframes_tpu as tfs

    frame = tfs.frame_from_arrays({"x": np.arange(16, dtype=np.float32)})
    program = tfs.compile_program(lambda x: {"y": x @ x * 2.0 + x}, frame)
    costs = program.cost_analysis(probe=16)
    assert isinstance(costs, dict) and costs
    assert any("flops" in k for k in costs), sorted(costs)[:10]


def test_recompile_accounting():
    """Ragged map_rows compiles once per distinct (cell shape, lead-dim
    bucket) group — through the vmapped entrypoint, not per row; the
    cache sizes are queryable (honest recompile accounting, SURVEY §7)."""
    import tensorframes_tpu as tfs

    rows = [{"v": [1.0, 2.0]}, {"v": [3.0]}, {"v": [4.0, 5.0, 6.0]},
            {"v": [7.0]}]
    frame = tfs.frame_from_rows(rows, num_blocks=1)
    program = tfs.compile_program(
        lambda v: {"s": v.sum()}, frame, block=False
    )
    tfs.map_rows(program, frame).collect()
    sizes = program.compiled().cache_sizes()
    # cell shapes (2,), (1,), (3,) — each group one bucketed vmap compile
    assert sizes["vmap"] == 3
    assert sizes["block"] == 0  # no per-row dispatches
    assert "compiled_shapes" in program.explain()


def test_compile_program_shape_hints():
    """Per-call output shape hints override discovery (≙ ShapeDescription
    + the hint-override rule)."""
    import tensorframes_tpu as tfs

    import jax.numpy as jnp

    frame = tfs.frame_from_arrays({"x": np.arange(12, dtype=np.float32)})
    # outer product: analysis marks BOTH dims Unknown (they co-vary with
    # the probe); the user knows the frame is 12 rows and pins dim 2
    plain = tfs.compile_program(lambda x: {"y": jnp.outer(x, x)}, frame)
    assert plain.output("y").shape.dims[-1] == tfs.Unknown
    hinted = tfs.compile_program(
        lambda x: {"y": jnp.outer(x, x)}, frame, shape_hints={"y": (None, 12)}
    )
    assert hinted.output("y").shape.dims[-1] == 12
