"""Dtype-parameterized verb replay: the same behavioral tests executed for
every core scalar type.

≙ the reference's type-genericity harness: ``CommonOperationsSuite[T]``
defines tests once and replays them per dtype
(CommonOperationsSuite.scala:10-86, type_suites.scala:190-213 over shared
BasicIdentityTests/BasicMonoidTests).
"""

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu import dtypes as dt

CORE_TYPES = [dt.float64, dt.float32, dt.int32, dt.int64]


def _mk(values, t):
    arr = np.asarray(values, dtype=t.np_dtype)
    return tfs.frame_from_arrays({"x": arr}, num_blocks=2)


@pytest.mark.parametrize("t", CORE_TYPES, ids=lambda t: t.name)
def test_identity_scalar(t):
    df = _mk([1, 2, 3, 4], t)
    x = tfs.block(df, "x")
    out = tfs.map_blocks(tfs.identity(x, name="y"), df).collect()
    assert [r["y"] for r in out] == [1, 2, 3, 4]
    assert tfs.map_blocks(tfs.identity(x, name="y2"), df).schema["y2"].dtype is t


@pytest.mark.parametrize("t", CORE_TYPES, ids=lambda t: t.name)
def test_add_constant(t):
    df = _mk([1, 2, 3], t)
    x = tfs.block(df, "x")
    c = tfs.constant(np.asarray(2, dtype=t.np_dtype))
    out = tfs.map_blocks(tfs.add(x, c, name="y"), df).collect()
    assert [r["y"] for r in out] == [3, 4, 5]


@pytest.mark.parametrize("t", CORE_TYPES, ids=lambda t: t.name)
def test_reduce_blocks_monoid(t):
    # ≙ BasicMonoidTests: sum over blocks
    df = _mk([1, 2, 3, 4, 5], t)
    x_input = tfs.block(df, "x", tf_name="x_input")
    x = tfs.reduce_sum(x_input, axis=0, name="x")
    assert tfs.reduce_blocks(x, df) == 15


@pytest.mark.parametrize("t", CORE_TYPES, ids=lambda t: t.name)
def test_reduce_rows_monoid(t):
    df = _mk([1, 2, 3, 4], t)
    x1 = tfs.placeholder(t, [], name="x_1")
    x2 = tfs.placeholder(t, [], name="x_2")
    x = tfs.add(x1, x2, name="x")
    assert tfs.reduce_rows(x, df) == 10


@pytest.mark.parametrize("t", CORE_TYPES, ids=lambda t: t.name)
def test_map_rows_identity(t):
    df = _mk([7, 8, 9], t)
    x = tfs.row(df, "x")
    out = tfs.map_rows(tfs.identity(x, name="y"), df).collect()
    assert [r["y"] for r in out] == [7, 8, 9]


@pytest.mark.parametrize("t", [dt.float64, dt.float32], ids=lambda t: t.name)
def test_vector_roundtrip(t):
    arr = np.arange(12, dtype=t.np_dtype).reshape(6, 2)
    df = tfs.frame_from_arrays({"v": arr}, num_blocks=3)
    v = tfs.block(df, "v")
    out = tfs.map_blocks((v * 2).named("w"), df)
    got = np.stack([r["w"] for r in out.collect()])
    assert np.allclose(got, arr * 2)
    assert out.schema["w"].dtype is t
