"""Checkpoint/resume tests: roundtrips through both backends, step
bookkeeping, retention, and sharded-state save/restore."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorframes_tpu.checkpoint import Checkpointer


def _state(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "params": {
            "w": jnp.asarray(rng.standard_normal((8, 4)), jnp.float32),
            "b": jnp.zeros((4,), jnp.float32),
        },
        "step": jnp.asarray(7, jnp.int32),
        "layers": [
            {"scale": jnp.ones((3,), jnp.float32)},
            {"scale": jnp.full((3,), 2.0, jnp.float32)},
        ],
    }


def _assert_tree_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize("backend", ["orbax", "npz"])
def test_roundtrip(tmp_path, backend):
    ckpt = Checkpointer(str(tmp_path / "run"), backend=backend)
    state = _state()
    ckpt.save(10, state)
    restored = ckpt.restore(like=_state(seed=1))
    _assert_tree_equal(state, restored)


@pytest.mark.parametrize("backend", ["orbax", "npz"])
def test_latest_and_retention(tmp_path, backend):
    ckpt = Checkpointer(str(tmp_path / "run"), backend=backend, keep=2)
    for step in (5, 10, 15):
        ckpt.save(step, _state(seed=step))
    assert ckpt.latest_step() == 15
    assert ckpt.all_steps() == [10, 15]  # keep=2 dropped step 5
    r10 = ckpt.restore(step=10, like=_state())
    _assert_tree_equal(_state(seed=10), r10)


def test_restore_missing_raises(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "empty"), backend="npz")
    with pytest.raises(FileNotFoundError):
        ckpt.restore()
    with pytest.raises(FileNotFoundError):
        ckpt.restore(step=99)


def test_npz_template_mismatch_raises(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "run"), backend="npz")
    ckpt.save(1, {"a": jnp.ones((2,))})
    with pytest.raises(ValueError):
        ckpt.restore(like={"a": jnp.ones((2,)), "b": jnp.ones((2,))})


def test_overwrite_same_step(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "run"), backend="npz")
    ckpt.save(1, {"a": jnp.ones((2,))})
    ckpt.save(1, {"a": jnp.full((2,), 5.0)})
    got = ckpt.restore(step=1, like={"a": jnp.zeros((2,))})
    np.testing.assert_array_equal(np.asarray(got["a"]), [5.0, 5.0])


def test_sharded_state_roundtrip(tmp_path):
    """Save from a sharded train state, restore, resume: the checkpoint
    layer handles device arrays living on an 8-device mesh."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tensorframes_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": 8})
    sh = NamedSharding(mesh, P("dp"))
    w = jax.device_put(jnp.arange(16, dtype=jnp.float32), sh)
    ckpt = Checkpointer(str(tmp_path / "run"), backend="npz")
    ckpt.save(3, {"w": w})
    got = ckpt.restore(like={"w": jnp.zeros((16,), jnp.float32)})
    np.testing.assert_array_equal(np.asarray(got["w"]), np.arange(16))


def test_npz_bfloat16_roundtrip(tmp_path):
    """bf16 leaves survive the npz byte-format (numpy's own npz loader
    can't reconstruct ml_dtypes — regression guard)."""
    ckpt = Checkpointer(str(tmp_path / "run"), backend="npz")
    state = {"w": jnp.full((3, 2), 1.5, jnp.bfloat16)}
    ckpt.save(1, state)
    got = ckpt.restore(like={"w": jnp.zeros((3, 2), jnp.bfloat16)})
    assert str(np.asarray(got["w"]).dtype) == "bfloat16"
    np.testing.assert_array_equal(
        np.asarray(got["w"]).astype(np.float32), np.full((3, 2), 1.5)
    )


def test_restore_sniffs_format_across_backends(tmp_path):
    """A checkpoint written by one backend restores under the other (the
    on-disk format, not the configured backend, decides)."""
    w = jnp.arange(4, dtype=jnp.float32)
    Checkpointer(str(tmp_path / "a"), backend="npz").save(1, {"w": w})
    got = Checkpointer(str(tmp_path / "a"), backend="orbax").restore(
        like={"w": jnp.zeros((4,), jnp.float32)}
    )
    np.testing.assert_array_equal(np.asarray(got["w"]), np.arange(4))
    Checkpointer(str(tmp_path / "b"), backend="orbax").save(1, {"w": w})
    got = Checkpointer(str(tmp_path / "b"), backend="npz").restore(
        like={"w": jnp.zeros((4,), jnp.float32)}
    )
    np.testing.assert_array_equal(np.asarray(got["w"]), np.arange(4))


def test_npz_zero_dim_leaf_roundtrip(tmp_path):
    """0-d scalars keep their rank (np.ascontiguousarray promotes 0-d to
    1-d — regression guard for the manifest shape)."""
    ckpt = Checkpointer(str(tmp_path / "run"), backend="npz")
    ckpt.save(1, {"step": jnp.asarray(7, jnp.int32), "w": jnp.ones((2,))})
    got = ckpt.restore(
        like={"step": jnp.asarray(0, jnp.int32), "w": jnp.zeros((2,))}
    )
    assert np.shape(got["step"]) == ()
    assert int(got["step"]) == 7
