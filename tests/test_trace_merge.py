"""Distributed trace correlation: N subprocess "processes" of one run
each write a per-process shard (``events.save_shard``), and
``observability merge`` must reassemble one JSON-valid Chrome trace
with a distinct track per process (ISSUE 6 tentpole acceptance;
subprocess pattern follows tests/test_crash_resume.py)."""

import json
import os
import subprocess
import sys

import pytest

from tensorframes_tpu.observability import cli, context, events, merge

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

# one "process" of the run: real verb dispatches land executor spans on
# the timeline, then the shard is written into the shared directory
_WORKER = """
import os, sys
shard_dir = sys.argv[1]
import numpy as np
import tensorframes_tpu as tfs
from tensorframes_tpu.observability import events
events.enable()
df = tfs.frame_from_arrays({"x": np.arange(64.0)}, num_blocks=2)
program = tfs.compile_program(lambda x: {"y": x * 2.0 + 1.0}, df)
tfs.map_blocks(program, df).collect()
events.instant("worker.done",
               rank=int(os.environ["TFTPU_PROCESS_INDEX"]))
path = events.save_shard(shard_dir)
print("SHARD", path, flush=True)
"""


def _run_fleet(shard_dir: str, n: int, run_id: str = "mergetest"):
    """Spawn n concurrent worker processes sharing one run id."""
    procs = []
    for i in range(n):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["TFTPU_RUN_ID"] = run_id
        env["TFTPU_PROCESS_INDEX"] = str(i)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _WORKER, shard_dir],
            env=env, cwd=_REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    for i, p in enumerate(procs):
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, (
            f"worker {i} failed (rc={p.returncode})\n"
            f"stdout: {out}\nstderr: {err}"
        )
        assert "SHARD" in out


def _check_merged(merged: dict, n: int, run_id: str = "mergetest"):
    # strict-JSON valid (what Perfetto/chrome://tracing require)
    merged = json.loads(json.dumps(merged))
    evs = merged["traceEvents"]
    # every process contributed a track, pids are the ranks
    pids = {e["pid"] for e in evs}
    assert pids == set(range(n))
    # per-process tracks are labeled and ordered
    names = {
        e["pid"]: e["args"]["name"]
        for e in evs if e.get("ph") == "M" and e["name"] == "process_name"
    }
    assert set(names) == set(range(n))
    for rank, label in names.items():
        assert label.startswith(f"process {rank}")
    # the real dispatch spans came through on every track
    for rank in range(n):
        rank_names = {e["name"] for e in evs if e["pid"] == rank}
        assert "executor.run_block" in rank_names
        assert "worker.done" in rank_names
    # timestamps were re-anchored: all non-metadata events non-negative
    assert all(e["ts"] >= 0 for e in evs if "ts" in e)
    other = merged["otherData"]
    assert other["run_id"] == run_id
    assert other["num_shards"] == n
    assert len(other["processes"]) == n


def test_two_process_run_merges_into_one_timeline(tmp_path):
    """ISSUE 6 satellite: subprocess-spawned 2-process run → shards →
    ``merge`` → JSON-valid Chrome trace keeping both process tracks."""
    shard_dir = str(tmp_path / "shards")
    _run_fleet(shard_dir, 2)
    shards = merge.find_shards(shard_dir, run_id="mergetest")
    assert len(shards) == 2
    assert [os.path.basename(p) for p in shards] == [
        "trace_mergetest_p0.json", "trace_mergetest_p1.json",
    ]
    # per-shard context stamps are intact
    for i, p in enumerate(shards):
        other = json.load(open(p))["otherData"]
        assert other["run_id"] == "mergetest"
        assert other["process_index"] == i
        assert other["trace_epoch_unix_us"] > 0

    # the CLI face: merge via the subcommand, validating the written file
    out_path = str(tmp_path / "merged.json")
    rc = cli.main(["merge", "--dir", shard_dir, "--run-id", "mergetest",
                   "-o", out_path])
    assert rc == 0
    _check_merged(json.load(open(out_path)), 2)


@pytest.mark.slow
def test_eight_process_dryrun_merges(tmp_path):
    """The 8-process acceptance dryrun (JAX_PLATFORMS=cpu forked):
    8 shards merge into one timeline with 8 distinct tracks."""
    shard_dir = str(tmp_path / "shards")
    _run_fleet(shard_dir, 8)
    shards = merge.find_shards(shard_dir, run_id="mergetest")
    assert len(shards) == 8
    _check_merged(merge.merge_traces(shards), 8)


# ---------------------------------------------------------------------------
# merge semantics (no subprocesses: shards built in-memory)
# ---------------------------------------------------------------------------

def _fake_shard(tmp_path, run_id, rank, epoch_us, name="ev"):
    shard = {
        "traceEvents": [
            {"ph": "X", "name": name, "cat": "t", "ts": 10.0, "dur": 5.0,
             "pid": 9999 + rank, "tid": 1},
        ],
        "otherData": {
            "run_id": run_id, "process_index": rank, "pid": 9999 + rank,
            "trace_epoch_unix_us": epoch_us, "dropped_events": rank,
        },
    }
    path = tmp_path / f"trace_{run_id}_p{rank}.json"
    path.write_text(json.dumps(shard))
    return str(path)


def test_merge_realigns_clocks_and_sums_drops(tmp_path):
    a = _fake_shard(tmp_path, "r1", 0, epoch_us=1_000_000)
    b = _fake_shard(tmp_path, "r1", 1, epoch_us=1_250_000)
    merged = merge.merge_traces([a, b])
    xs = {e["pid"]: e for e in merged["traceEvents"] if e["ph"] == "X"}
    # shard 1 started 0.25s later: its events shift by +250000µs
    assert xs[0]["ts"] == 10.0
    assert xs[1]["ts"] == 250_010.0
    assert merged["otherData"]["dropped_events"] == 1  # 0 + 1


def test_merge_refuses_mixed_runs_unless_forced(tmp_path):
    a = _fake_shard(tmp_path, "runA", 0, 1_000_000)
    b = _fake_shard(tmp_path, "runB", 1, 1_000_000)
    with pytest.raises(ValueError, match="different runs"):
        merge.merge_traces([a, b])
    merged = merge.merge_traces([a, b], force=True)
    assert merged["otherData"]["run_id"] == ["runA", "runB"]


def test_merge_refuses_duplicate_ranks_unless_forced(tmp_path):
    a = _fake_shard(tmp_path, "r1", 0, 1_000_000)
    sub = tmp_path / "sub"
    sub.mkdir()
    b = _fake_shard(sub, "r1", 0, 2_000_000)
    with pytest.raises(ValueError, match="duplicate process_index"):
        merge.merge_traces([a, b])
    merge.merge_traces([a, b], force=True)  # forced keeps both


def test_context_env_binding(monkeypatch):
    monkeypatch.setenv("TFTPU_PROCESS_INDEX", "5")
    saved = (context._run_id, context._process_index, context._num_processes)
    context._reset_for_tests()
    try:
        assert context.process_index() == 5
        context.bind(process_index=2, num_processes=4)
        assert context.process_index() == 2  # explicit bind beats env
        assert context.num_processes() == 4
        env = context.child_env(3)
        assert env["TFTPU_PROCESS_INDEX"] == "3"
        assert env["TFTPU_RUN_ID"] == context.run_id()
    finally:
        context._reset_for_tests()
        context.bind(run_id=saved[0], process_index=saved[1],
                     num_processes=saved[2])


def test_shard_metadata_rides_save(tmp_path):
    was_enabled = events.TRACER.enabled
    events.enable()
    try:
        with events.span("meta-probe"):
            pass
        path = events.save_shard(str(tmp_path))
        other = json.load(open(path))["otherData"]
        assert other["run_id"] == context.run_id()
        assert other["process_index"] == context.process_index()
        assert other["trace_epoch_unix_us"] > 0
        assert os.path.basename(path) == (
            f"trace_{context.run_id()}_p{context.process_index()}.json"
        )
    finally:
        if not was_enabled:
            events.disable()
