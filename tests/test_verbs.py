"""Integration tests of the five verbs through the full public path
(≙ BasicOperationsSuite / TrimmingOperationsSuite / core_test.py)."""

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu import dtypes as dt
from tensorframes_tpu.validation import ValidationError


# -- map_blocks --------------------------------------------------------------

def test_readme_add3():
    # README.md:62-93
    df = tfs.frame_from_rows([{"x": float(x)} for x in range(10)])
    x = tfs.block(df, "x")
    z = tfs.add(x, 3, name="z")
    df2 = tfs.map_blocks(z, df)
    rows = df2.collect()
    assert [r["z"] for r in rows] == [float(x) + 3 for x in range(10)]
    assert [r["x"] for r in rows] == [float(x) for x in range(10)]


def test_map_blocks_is_lazy():
    df = tfs.frame_from_rows([{"x": 1.0}])
    x = tfs.block(df, "x")
    df2 = tfs.map_blocks((x + 1.0).named("y"), df)
    assert not df2.is_materialized
    df2.collect()
    assert df2.is_materialized


def test_map_blocks_multi_output_sorted_first():
    # output cols first, sorted by name (DebugRowOps.scala:353-379)
    df = tfs.frame_from_rows([{"x": 2.0}])
    x = tfs.block(df, "x")
    b = (x * 3.0).named("b")
    a = (x + 1.0).named("a")
    df2 = tfs.map_blocks([b, a], df)
    assert df2.columns == ["a", "b", "x"]


def test_map_blocks_feed_dict():
    # placeholder renamed onto another column (core_test.py:95-107)
    df = tfs.frame_from_rows([{"col": 5.0}])
    ph = tfs.placeholder(dt.float64, [None], name="ph")
    z = (ph + 1.0).named("z")
    df2 = tfs.map_blocks(z, df, feed_dict={"ph": "col"})
    assert df2.first()["z"] == 6.0


def test_map_blocks_trimmed_changes_row_count():
    # ≙ TrimmingOperationsSuite.scala:17-47
    df = tfs.frame_from_rows([{"x": float(i)} for i in range(8)], num_blocks=2)
    x = tfs.block(df, "x")
    # keep every other row: output rows != input rows, requires trim
    half = tfs.apply_fn(lambda v: v[::2], x, name="half")
    out = tfs.map_blocks(half, df, trim=True)
    assert out.columns == ["half"]
    assert out.num_rows == 4


def test_map_blocks_row_count_mismatch_errors_without_trim():
    df = tfs.frame_from_rows([{"x": float(i)} for i in range(8)], num_blocks=1)
    x = tfs.block(df, "x")
    half = tfs.apply_fn(lambda v: v[::2], x, name="half")
    df2 = tfs.map_blocks(half, df)
    with pytest.raises(ValidationError):
        df2.collect()


def test_map_blocks_output_collision_error():
    df = tfs.frame_from_rows([{"x": 1.0}])
    x = tfs.block(df, "x")
    clash = tfs.identity(x, name="x_out").named("x")
    with pytest.raises(ValidationError) as e:
        tfs.map_blocks(clash, df)
    assert "x" in str(e.value)


def test_map_blocks_missing_column_error_enumerates():
    df = tfs.frame_from_rows([{"x": 1.0}])
    ph = tfs.placeholder(dt.float64, [None], name="nope")
    with pytest.raises(ValidationError) as e:
        tfs.map_blocks((ph + 1.0).named("z"), df)
    msg = str(e.value)
    assert "nope" in msg and "x" in msg  # both sides enumerated


def test_map_blocks_dtype_mismatch_error():
    df = tfs.frame_from_rows([{"x": 1.0}])  # float64
    ph = tfs.placeholder(dt.float32, [None], name="x")
    with pytest.raises(ValidationError) as e:
        tfs.map_blocks((ph + 1.0).named("z"), df)
    assert "casting" in str(e.value)


def test_map_blocks_vectors():
    # 1-tensor in, 1-tensor out (BasicOperationsSuite 2-tensor cases)
    df = tfs.analyze(
        tfs.frame_from_rows([{"y": [float(i), 1.0]} for i in range(6)])
    )
    y = tfs.block(df, "y")
    z = tfs.reduce_sum(y, axis=1, name="z")
    out = tfs.map_blocks(z, df).collect()
    assert [r["z"] for r in out] == [float(i) + 1.0 for i in range(6)]


def test_map_blocks_int_types():
    df = tfs.frame_from_rows([{"x": i} for i in range(5)])
    assert df.schema["x"].dtype is dt.int64
    x = tfs.block(df, "x")
    out = tfs.map_blocks((x * 2).named("z"), df).collect()
    assert [r["z"] for r in out] == [2 * i for i in range(5)]


# -- map_rows ----------------------------------------------------------------

def test_map_rows_scalar():
    df = tfs.frame_from_rows([{"x": float(i)} for i in range(7)], num_blocks=2)
    x = tfs.row(df, "x")
    z = (x * x).named("z")
    out = tfs.map_rows(z, df).collect()
    assert [r["z"] for r in out] == [float(i * i) for i in range(7)]


def test_map_rows_ragged():
    # ragged vectors: the map_rows-only case (core.py:288-289)
    df = tfs.frame_from_rows(
        [{"y": [1.0]}, {"y": [1.0, 2.0]}, {"y": [1.0, 2.0, 3.0]}]
    )
    df = tfs.analyze(df)
    y = tfs.row(df, "y")
    s = tfs.reduce_sum(y, axis=0, name="s")
    out = tfs.map_rows(s, df).collect()
    assert [r["s"] for r in out] == [1.0, 3.0, 6.0]


def test_map_rows_vector_output():
    df = tfs.analyze(tfs.frame_from_rows([{"y": [1.0, 2.0]} for _ in range(3)]))
    y = tfs.row(df, "y")
    z = (y * 10.0).named("z")
    out = tfs.map_rows(z, df).collect()
    assert np.allclose(out[0]["z"], [10.0, 20.0])


# -- reduce_rows -------------------------------------------------------------

def test_reduce_rows_sum():
    df = tfs.frame_from_rows([{"x": float(i)} for i in range(1, 11)], num_blocks=3)
    x1 = tfs.placeholder(dt.float64, [], name="x_1")
    x2 = tfs.placeholder(dt.float64, [], name="x_2")
    x = tfs.add(x1, x2, name="x")
    assert tfs.reduce_rows(x, df) == 55.0


def test_reduce_rows_vector():
    df = tfs.analyze(
        tfs.frame_from_rows([{"y": [float(i), 1.0]} for i in range(4)])
    )
    y1 = tfs.placeholder(dt.float64, [2], name="y_1")
    y2 = tfs.placeholder(dt.float64, [2], name="y_2")
    y = tfs.add(y1, y2, name="y")
    res = tfs.reduce_rows(y, df)
    assert np.allclose(res, [6.0, 4.0])


def test_reduce_rows_naming_contract_error():
    df = tfs.frame_from_rows([{"x": 1.0}])
    bad = tfs.placeholder(dt.float64, [], name="x_only")
    with pytest.raises(ValidationError) as e:
        tfs.reduce_rows(tfs.identity(bad, name="x"), df)
    assert "x_1" in str(e.value) and "x_2" in str(e.value)


# -- reduce_blocks -----------------------------------------------------------

def test_readme_reduce_example():
    # README.md:98-129
    df = tfs.analyze(
        tfs.frame_from_rows([{"y": [float(y), float(-y)]} for y in range(10)])
    )
    df3 = df.alias_column("y", "z")
    y_input = tfs.block(df3, "y", tf_name="y_input")
    z_input = tfs.block(df3, "z", tf_name="z_input")
    y = tfs.reduce_sum(y_input, axis=0, name="y")
    z = tfs.reduce_min(z_input, axis=0, name="z")
    data_sum, data_min = tfs.reduce_blocks([y, z], df3)
    assert np.allclose(data_sum, [45.0, -45.0])
    assert np.allclose(data_min, [0.0, -9.0])


def test_reduce_blocks_naming_contract_error():
    df = tfs.frame_from_rows([{"x": 1.0}])
    ph = tfs.placeholder(dt.float64, [None], name="wrong_name")
    with pytest.raises(ValidationError) as e:
        tfs.reduce_blocks(tfs.reduce_sum(ph, axis=0, name="x"), df)
    assert "x_input" in str(e.value)


def test_reduce_blocks_fetch_must_be_column():
    df = tfs.frame_from_rows([{"x": 1.0}])
    ph = tfs.placeholder(dt.float64, [None], name="z_input")
    with pytest.raises(ValidationError) as e:
        tfs.reduce_blocks(tfs.reduce_sum(ph, axis=0, name="z"), df)
    assert "existing column" in str(e.value)


# -- aggregate ---------------------------------------------------------------

def test_aggregate_sum_segment_path():
    # ≙ core_test.py groupBy aggregate (:255-264)
    df = tfs.frame_from_rows(
        [{"key": i % 3, "x": float(i)} for i in range(12)], num_blocks=3
    )
    x_input = tfs.block(df, "x", tf_name="x_input")
    x = tfs.reduce_sum(x_input, axis=0, name="x")
    res = tfs.aggregate(x, df.group_by("key")).collect()
    assert res == [
        {"key": 0, "x": 18.0},
        {"key": 1, "x": 22.0},
        {"key": 2, "x": 26.0},
    ]


def test_aggregate_generic_path():
    # a non-reducer-node graph forces the generic chunked-compaction path
    # (UDAF semantics: the program must be algebraic — re-applying it to
    # partials must be valid, as with the reference's compact/merge,
    # DebugRowOps.scala:651-683). 30 rows per group exercises chunking
    # (buffer = 10).
    df = tfs.frame_from_rows(
        [{"key": i % 2, "x": float(i + 1)} for i in range(60)]
    )
    x_input = tfs.block(df, "x", tf_name="x_input")
    x = tfs.apply_fn(lambda v: v.sum(axis=0), x_input, name="x")
    res = tfs.aggregate(x, df.group_by("key")).collect()
    odd = sum(float(i + 1) for i in range(60) if i % 2 == 0)
    even = sum(float(i + 1) for i in range(60) if i % 2 == 1)
    assert res[0]["x"] == pytest.approx(odd)
    assert res[1]["x"] == pytest.approx(even)


def test_aggregate_string_keys():
    df = tfs.frame_from_rows(
        [{"k": "ab"[i % 2], "x": float(i)} for i in range(6)]
    )
    x_input = tfs.block(df, "x", tf_name="x_input")
    x = tfs.reduce_sum(x_input, axis=0, name="x")
    res = tfs.aggregate(x, df.group_by("k")).collect()
    assert res == [{"k": "a", "x": 6.0}, {"k": "b", "x": 9.0}]


def test_aggregate_vector_values():
    df = tfs.analyze(
        tfs.frame_from_rows(
            [{"key": i % 2, "v": [float(i), 1.0]} for i in range(4)]
        )
    )
    v_input = tfs.block(df, "v", tf_name="v_input")
    v = tfs.reduce_sum(v_input, axis=0, name="v")
    res = tfs.aggregate(v, df.group_by("key")).collect()
    assert np.allclose(res[0]["v"], [2.0, 2.0])
    assert np.allclose(res[1]["v"], [4.0, 2.0])


# -- python function + pandas paths -----------------------------------------

def test_function_program():
    df = tfs.frame_from_rows([{"a": float(i), "b": float(2 * i)} for i in range(6)])

    def prog(a, b):
        return {"s": a + b}

    out = tfs.map_blocks(prog, df).collect()
    assert [r["s"] for r in out] == [3.0 * i for i in range(6)]


def test_pandas_local_path():
    # ≙ core_test.py:68-79 pandas map path
    import pandas as pd

    pdf = pd.DataFrame({"x": [1.0, 2.0, 3.0]})
    ph = tfs.placeholder(dt.float64, [None], name="x")
    z = (ph + 1.0).named("z")
    out = tfs.map_blocks(z, pdf)
    assert isinstance(out, pd.DataFrame)
    assert out["z"].tolist() == [2.0, 3.0, 4.0]


def test_variablelike_closure_constants():
    # closure-captured arrays play the role of frozen tf.Variables
    # (core.py:42-56)
    df = tfs.frame_from_rows([{"x": float(i)} for i in range(4)])
    w = np.array(10.0)

    def prog(x):
        import jax.numpy as jnp

        return {"z": x * jnp.asarray(w)}

    out = tfs.map_blocks(prog, df).collect()
    assert [r["z"] for r in out] == [10.0 * i for i in range(4)]


# -- empty blocks (the reference's TODO gap, DebugRowOps.scala:386) ----------

def test_empty_block_map():
    df = tfs.frame_from_rows([{"x": 1.0}, {"x": 2.0}], num_blocks=2)
    df3 = df.repartition(4)  # creates empty blocks
    x = tfs.block(df3, "x")
    out = tfs.map_blocks((x + 1.0).named("z"), df3).collect()
    assert [r["z"] for r in out] == [2.0, 3.0]


# -- regression tests from review findings -----------------------------------

def test_reduce_rows_function_fetches():
    # plain-function programs may use the x_1/x_2 naming contract
    df = tfs.frame_from_rows([{"x": float(i)} for i in range(1, 5)])

    def pair(x_1, x_2):
        return {"x": x_1 + x_2}

    assert tfs.reduce_rows(pair, df) == 10.0


def test_reduce_blocks_function_fetches():
    df = tfs.frame_from_rows([{"x": float(i)} for i in range(1, 5)])

    def red(x_input):
        return {"x": x_input.sum(axis=0)}

    assert tfs.reduce_blocks(red, df) == 10.0


def test_reduce_rows_ragged_friendly_error():
    df = tfs.frame_from_rows(
        [{"y": [1.0]}, {"y": [1.0, 2.0]}, {"y": [3.0]}], num_blocks=1
    )
    y1 = tfs.placeholder(dt.float64, [None], name="y_1")
    y2 = tfs.placeholder(dt.float64, [None], name="y_2")
    y = tfs.add(y1, y2, name="y")
    with pytest.raises(ValueError, match="ragged"):
        tfs.reduce_rows(y, df)


def test_map_rows_empty_block_vector_output():
    df = tfs.analyze(
        tfs.frame_from_rows([{"y": [1.0, 2.0]} for _ in range(3)])
    ).repartition(4)  # creates an empty block
    y = tfs.row(df, "y")
    out = tfs.map_rows((y * 10.0).named("z"), df)
    vals = out.column_values("z")
    assert vals.shape == (3, 2)


def test_aggregate_empty_frame():
    import numpy as np

    df = tfs.frame_from_arrays(
        {"key": np.empty((0,), np.int64), "x": np.empty((0,), np.float64)},
        num_blocks=1,
    )
    x_input = tfs.placeholder(dt.float64, [None], name="x_input")
    x = tfs.reduce_sum(x_input, axis=0, name="x")
    res = tfs.aggregate(x, df.group_by("key"))
    assert res.num_rows == 0
    assert res.columns == ["key", "x"]


def test_aggregate_mean_preserves_int_dtype():
    df = tfs.frame_from_rows([{"key": i % 2, "x": i} for i in range(8)])
    assert df.schema["x"].dtype is dt.int64
    x_input = tfs.block(df, "x", tf_name="x_input")
    x = tfs.reduce_mean(x_input, axis=0, name="x")
    res = tfs.aggregate(x, df.group_by("key"))
    assert res.schema["x"].dtype is dt.int64
    vals = res.column_values("x")
    assert vals.dtype == np.int64


def test_map_blocks_pipeline_depths_agree():
    """The pipelined in-flight window produces identical results to the
    synchronous path at every depth."""
    import numpy as np

    from tensorframes_tpu.config import configure, get_config

    df = tfs.frame_from_arrays({"x": np.arange(1000.0)}, num_blocks=7)
    old = get_config().map_pipeline_depth
    results = {}
    try:
        for depth in (0, 1, 3):
            configure(map_pipeline_depth=depth)
            out = tfs.map_blocks(lambda x: {"y": x * 2.0 + 1.0}, df)
            results[depth] = out.column_values("y")
    finally:
        configure(map_pipeline_depth=old)
    for depth, got in results.items():
        np.testing.assert_array_equal(got, np.arange(1000.0) * 2.0 + 1.0)


def test_map_blocks_prefetch_depths_agree():
    """Background host→device feed staging (io.prefetch_to_device wired
    into the map_blocks host path, VERDICT r3 #2) is a pure overlap
    optimization: results match the unstaged path at every depth, and
    non-input columns ride along untouched."""
    import numpy as np

    from tensorframes_tpu.config import configure, get_config

    df = tfs.frame_from_arrays(
        {"x": np.arange(2000.0), "tag": np.arange(2000)}, num_blocks=5
    )
    old = get_config().map_prefetch_depth
    results = {}
    try:
        for depth in (0, 1, 4):
            configure(map_prefetch_depth=depth)
            out = tfs.map_blocks(lambda x: {"y": x * 3.0 - 1.0}, df)
            results[depth] = (
                out.column_values("y"), out.column_values("tag")
            )
    finally:
        configure(map_prefetch_depth=old)
    for depth, (y, tag) in results.items():
        np.testing.assert_array_equal(y, np.arange(2000.0) * 3.0 - 1.0)
        np.testing.assert_array_equal(tag, np.arange(2000))


def test_run_block_donate_flag_safe_everywhere():
    """donate=True must be correctness-neutral: gated off on XLA:CPU
    (which doesn't implement donation), and never applied to
    device-resident frame columns — a device frame maps twice with
    identical results while donation config is on."""
    import numpy as np

    from tensorframes_tpu.config import configure, get_config
    from tensorframes_tpu.ops.executor import donation_supported

    assert donation_supported() is False  # suite runs on the cpu mesh

    old = get_config().donate_inputs
    try:
        configure(donate_inputs=True)
        # host frame: the donate branch is exercised (and gated off)
        df = tfs.frame_from_arrays({"x": np.arange(100.0)}, num_blocks=4)
        out = tfs.map_blocks(lambda x: {"y": x + 1.0}, df)
        np.testing.assert_array_equal(
            out.column_values("y"), np.arange(100.0) + 1.0
        )
        # device frame mapped TWICE: columns must survive the first map
        dev = tfs.frame_from_arrays({"x": np.arange(64.0)}).to_device()
        a = tfs.map_blocks(lambda x: {"y": x * 2.0}, dev)
        _ = a.column_values("y")
        b = tfs.map_blocks(lambda x: {"z": x * 5.0}, dev)
        np.testing.assert_array_equal(
            np.asarray(b.column_values("z")), np.arange(64.0) * 5.0
        )
    finally:
        configure(donate_inputs=old)


def test_aggregate_string_keys_plain_fn():
    """groupBy on a host string column (≙ Catalyst groupBy on strings —
    keys never touch the device; values aggregate on it)."""
    fr = tfs.frame_from_rows(
        [{"k": ["a", "b", "a", "c", "b"][i], "v": float(i)} for i in range(5)]
    )
    agg = fr.group_by("k").aggregate(lambda v_input: {"v": v_input.sum(0)})
    assert {r["k"]: r["v"] for r in agg.collect()} == {
        "a": 2.0, "b": 5.0, "c": 3.0
    }


def test_aggregate_multiple_keys():
    """Composite group keys (≙ groupBy(col1, col2))."""
    import numpy as np

    fr = tfs.frame_from_arrays(
        {
            "a": np.array([1, 1, 1, 2, 2]),
            "b": np.array([0, 0, 1, 0, 1]),
            "v": np.array([1.0, 2.0, 4.0, 8.0, 16.0]),
        }
    )
    agg = fr.group_by("a", "b").aggregate(
        lambda v_input: {"v": v_input.sum(0)}
    )
    got = {(r["a"], r["b"]): r["v"] for r in agg.collect()}
    assert got == {(1, 0): 3.0, (1, 1): 4.0, (2, 0): 8.0, (2, 1): 16.0}


def test_aggregate_int8_full_span_host_path():
    """Host-path grouping must widen narrow int keys before the offset
    subtraction (int8 -128..127 wraps otherwise)."""
    df = tfs.frame_from_rows(
        [{"k": np.int8([-128, 127][i % 2]), "v": float(i)} for i in range(10)]
    )
    res = tfs.aggregate(
        lambda v_input: {"v": v_input.sum(0)}, df.group_by("k")
    ).collect()
    assert {int(r["k"]): r["v"] for r in res} == {-128: 20.0, 127: 25.0}


def test_aggregate_nan_keys_group_together():
    """NaN float keys form ONE group — the Catalyst/Spark groupBy
    convention (NaNs compare equal for grouping); pinned intentionally."""
    df = tfs.frame_from_arrays(
        {
            "k": np.array([1.0, np.nan, 2.0, np.nan, 1.0]),
            "v": np.arange(5, dtype=np.float64),
        }
    )
    res = tfs.aggregate(
        lambda v_input: {"v": v_input.sum(0)}, df.group_by("k")
    ).collect()
    by_key = {("nan" if np.isnan(r["k"]) else r["k"]): r["v"] for r in res}
    assert by_key == {1.0: 4.0, 2.0: 2.0, "nan": 4.0}
