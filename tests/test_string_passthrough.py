"""The host-column (string/binary) pass-through contract, specified.

The reference supported strings only as single scalars
(datatypes.scala:571-622); on TPU they are host-resident columns that
never feed device programs. This file pins the behavior of every verb in
the presence of host columns — the contract VERDICT r1 flagged as
undocumented/untested (docs/api.md "Host columns" section is the prose
form):

* map verbs append outputs and carry host columns through unchanged,
  row-aligned;
* trim=True replaces the schema entirely (host columns drop with the
  rest — the reference's trimmed output schema was the fetches alone);
* reduce verbs reduce the named device columns and ignore host columns;
* aggregate groups BY host keys (device or dictionary plan) but rejects
  host columns as aggregation VALUES with the host-only error;
* host columns round-trip binary content exactly.
"""

import pytest

import tensorframes_tpu as tfs


@pytest.fixture
def frame():
    return tfs.frame_from_rows(
        [
            {"name": f"row{i}", "blob": bytes([i]) * 3, "x": float(i)}
            for i in range(6)
        ],
        num_blocks=2,
    )


def test_map_blocks_carries_host_columns_aligned(frame):
    out = tfs.map_blocks(lambda x: {"z": x * 2.0}, frame).collect()
    for i, r in enumerate(out):
        assert r["name"] == f"row{i}"
        assert r["blob"] == bytes([i]) * 3
        assert r["z"] == 2.0 * i


def test_map_rows_carries_host_columns_aligned(frame):
    out = tfs.map_rows(lambda x: {"r": x + 1.0}, frame).collect()
    assert [r["name"] for r in out] == [f"row{i}" for i in range(6)]


def test_trim_replaces_schema_dropping_host_columns(frame):
    t = tfs.map_blocks(lambda x: {"x": x[:3]}, frame, trim=True)
    assert t.schema.names == ["x"]  # fetches only, ≙ trimmed output schema


def test_reduce_verbs_ignore_host_columns(frame):
    assert float(
        tfs.reduce_blocks(lambda x_input: {"x": x_input.sum(axis=0)}, frame)
    ) == 15.0
    assert float(
        tfs.reduce_rows(lambda x_1, x_2: {"x": x_1 + x_2}, frame)
    ) == 15.0


def test_aggregate_by_host_key_carries_key_values(frame):
    agg = frame.group_by("name").aggregate(
        lambda x_input: {"x": x_input.sum(0)}
    ).collect()
    assert {r["name"]: r["x"] for r in agg} == {
        f"row{i}": float(i) for i in range(6)
    }


def test_host_column_as_aggregation_value_rejected(frame):
    # DSL route: block() refuses to make a placeholder from a host column
    with pytest.raises((TypeError, ValueError), match="host|string"):
        tfs.block(frame, "name")
    # aggregate route (plain-function fetch): the value column never
    # becomes a program input — parameter matching rejects it
    with pytest.raises(ValueError, match="name_input"):
        tfs.aggregate(
            lambda name_input: {"name": name_input}, frame.group_by("x")
        )


def test_host_column_cannot_feed_device_program(frame):
    with pytest.raises((TypeError, KeyError, ValueError)):
        tfs.map_blocks(lambda name: {"z": name}, frame).collect()


def test_sharded_frame_carries_host_columns(frame):
    dev = frame.to_device()
    out = tfs.map_blocks(lambda x: {"z": x + 1.0}, dev).collect()
    assert [r["name"] for r in out] == [f"row{i}" for i in range(6)]
    assert [r["blob"] for r in out] == [bytes([i]) * 3 for i in range(6)]
