"""Thread-safety under concurrent use (SURVEY §5-race: the reference had
no race detection and documented its DSL naming context as
single-threaded only, dsl/Paths.scala:10-11). Here thread-local graph
contexts and the frame's force-once lock make concurrent use safe —
these tests race real threads over the public API to pin that."""

import threading

import numpy as np

import tensorframes_tpu as tfs


def _run_threads(fn, n=8):
    errs = []
    results = [None] * n

    def wrap(i):
        try:
            results[i] = fn(i)
        except Exception as e:  # pragma: no cover - failure reporting
            errs.append((i, e))

    ts = [threading.Thread(target=wrap, args=(i,)) for i in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in ts), "worker thread timed out"
    assert not errs, errs
    return results


def test_concurrent_map_blocks_same_frame():
    df = tfs.frame_from_arrays({"x": np.arange(1000, dtype=np.float64)})

    def work(i):
        out = tfs.map_blocks(lambda x: {"z": x * float(i)}, df)
        return np.asarray(out.column_values("z"))

    results = _run_threads(work)
    for i, got in enumerate(results):
        np.testing.assert_array_equal(got, np.arange(1000) * float(i))


def test_concurrent_dsl_graphs_are_thread_local():
    """Each thread builds its own scoped graph; TF-style name dedup
    counters must not bleed across threads (the reference's Paths was a
    process-global mutable context — explicitly unsafe)."""
    df = tfs.frame_from_arrays({"x": np.arange(64, dtype=np.float64)})

    def work(i):
        with tfs.with_graph():
            x = tfs.block(df, "x")
            z = tfs.add(x, float(i), name="z")
            out = tfs.map_blocks(z, df)
            # same fetch name in every thread: thread-local contexts mean
            # no _1/_2 dedup suffix ever appears
            assert "z" in out.schema.names
            return np.asarray(out.column_values("z"))

    results = _run_threads(work)
    for i, got in enumerate(results):
        np.testing.assert_array_equal(got, np.arange(64) + float(i))


def test_lazy_frame_forces_once_under_races():
    calls = []

    def compute():
        calls.append(1)
        return [{"x": np.arange(100, dtype=np.float64)}]

    from tensorframes_tpu import ColumnInfo, Schema, Shape, Unknown
    from tensorframes_tpu import dtypes as dt

    frame = tfs.TensorFrame(
        None,
        Schema([ColumnInfo("x", dt.float64, Shape((Unknown,)))]),
        pending=compute,
    )

    def work(_):
        return frame.num_rows

    results = _run_threads(work)
    assert set(results) == {100}
    assert len(calls) == 1  # the force-once lock held


def test_concurrent_aggregates():
    rng = np.random.default_rng(0)
    df = tfs.frame_from_arrays(
        {
            "k": rng.integers(0, 16, 4000),
            "v": rng.standard_normal(4000),
        }
    )

    def work(_):
        res = tfs.aggregate(
            lambda v_input: {"v": v_input.sum(0)}, df.group_by("k")
        )
        return {r["k"]: r["v"] for r in res.collect()}

    results = _run_threads(work, n=6)
    for r in results[1:]:
        assert r.keys() == results[0].keys()
        for k in r:
            assert r[k] == results[0][k]
