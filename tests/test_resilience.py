"""Resilience-subsystem tests: fault injection, retry policies, NaN
guards, checkpoint integrity (CRC manifest, truncation fallback, orphan
GC), and the fault sites wired through the executor / io / checkpoint
layers — the guarantees the reference delegated to Spark task retry
(SURVEY.md §5) re-owned natively."""

import os
import time
import zlib

import jax.numpy as jnp
import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.checkpoint import Checkpointer, CheckpointCorruptionError
from tensorframes_tpu.resilience import (
    AttemptTimeout,
    NonFiniteError,
    RetryError,
    RetryPolicy,
    StepGuard,
    active_sites,
    fault_point,
    inject,
    retry_call,
    retryable,
    tree_all_finite,
)


# ---------------------------------------------------------------------------
# faults.py
# ---------------------------------------------------------------------------

def test_fault_point_noop_when_unarmed():
    fault_point("executor.run_block")  # no injection: must not raise
    assert active_sites() == ()


def test_inject_every_n_deterministic():
    with inject("t.site", OSError, every_n=3) as inj:
        outcomes = []
        for _ in range(9):
            try:
                fault_point("t.site")
                outcomes.append("ok")
            except OSError:
                outcomes.append("err")
    assert outcomes == ["ok", "ok", "err"] * 3
    assert inj.hits == 9 and inj.fired == 3
    fault_point("t.site")  # disarmed on exit


def test_inject_after_and_max_times():
    with inject("t.site", RuntimeError, every_n=1, after=2, max_times=2) as inj:
        fired = 0
        for _ in range(10):
            try:
                fault_point("t.site")
            except RuntimeError:
                fired += 1
    assert fired == 2 and inj.fired == 2
    assert inj.hits == 10


def test_inject_probabilistic_is_reproducible():
    def run():
        hits = []
        with inject("t.site", ValueError, p=0.5, seed=42):
            for _ in range(20):
                try:
                    fault_point("t.site")
                    hits.append(0)
                except ValueError:
                    hits.append(1)
        return hits

    a, b = run(), run()
    assert a == b  # seeded PRNG: bit-for-bit replay
    assert 0 < sum(a) < 20  # actually fires sometimes, not always


def test_inject_error_instance_vs_class():
    sentinel = OSError("the very one")
    with inject("t.site", sentinel):
        with pytest.raises(OSError) as ei:
            fault_point("t.site")
        assert ei.value is sentinel
    with inject("t.site", ConnectionError):
        with pytest.raises(ConnectionError, match="t.site"):
            fault_point("t.site")


def test_inject_site_isolation_and_introspection():
    with inject("t.a", OSError):
        assert active_sites() == ("t.a",)
        fault_point("t.b")  # other sites unaffected
        with pytest.raises(OSError):
            fault_point("t.a")


def test_executor_site_fires_through_verbs():
    frame = tfs.frame_from_arrays({"x": np.arange(8.0)}, num_blocks=2)
    with inject("executor.run_block", OSError, every_n=1):
        with pytest.raises(OSError):
            # verbs are lazy: materialize inside the injection scope
            tfs.map_blocks(lambda x: {"y": x * 2.0}, frame).column_values("y")
    out = tfs.map_blocks(lambda x: {"y": x * 2.0}, frame)  # disarmed
    np.testing.assert_array_equal(out.column_values("y"), np.arange(8.0) * 2)


def test_io_frame_sites_fire(tmp_path):
    frame = tfs.frame_from_arrays({"x": np.arange(4.0)})
    with inject("io.save_frame", OSError):
        with pytest.raises(OSError):
            tfs.save_frame(frame, str(tmp_path / "fr"))
    tfs.save_frame(frame, str(tmp_path / "fr"))
    with inject("io.load_frame", OSError):
        with pytest.raises(OSError):
            tfs.load_frame(str(tmp_path / "fr"))


# ---------------------------------------------------------------------------
# retry.py
# ---------------------------------------------------------------------------

def test_retry_absorbs_transient_then_succeeds():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "done"

    out = retry_call(flaky, policy=RetryPolicy(max_attempts=5, backoff=0.001))
    assert out == "done" and len(calls) == 3


def test_retry_exhaustion_raises_retry_error_with_cause():
    def always():
        raise ConnectionError("down")

    with pytest.raises(RetryError) as ei:
        retry_call(always, policy=RetryPolicy(max_attempts=3, backoff=0.001))
    assert isinstance(ei.value.__cause__, ConnectionError)


def test_retry_non_retryable_propagates_immediately():
    calls = []

    def bug():
        calls.append(1)
        raise ValueError("a real bug")

    with pytest.raises(ValueError):
        retry_call(bug, policy=RetryPolicy(max_attempts=5, backoff=0.001))
    assert len(calls) == 1  # no second attempt for a classified bug


def test_retry_backoff_schedule_is_deterministic():
    pol = RetryPolicy(backoff=0.1, backoff_max=0.5, jitter=0.5, seed=7)
    import random

    d1 = [pol.delay(k, random.Random(7)) for k in (1, 2, 3, 4)]
    d2 = [pol.delay(k, random.Random(7)) for k in (1, 2, 3, 4)]
    assert d1 == d2
    # exponential up to the cap, jitter bounded
    assert 0.1 <= d1[0] <= 0.15
    assert all(base <= d <= base * 1.5 for d, base in zip(d1, (0.1, 0.2, 0.4, 0.5)))


def test_retry_watchdog_timeout_classified_and_retried():
    slow_calls = []

    def sometimes_hangs():
        slow_calls.append(1)
        if len(slow_calls) == 1:
            time.sleep(3.0)  # wedged first attempt (abandoned by watchdog)
        return "recovered"

    out = retry_call(
        sometimes_hangs,
        policy=RetryPolicy(max_attempts=2, backoff=0.001, timeout=0.2),
    )
    assert out == "recovered"


def test_retry_watchdog_exhaustion():
    with pytest.raises(RetryError) as ei:
        retry_call(
            lambda: time.sleep(2.0),
            policy=RetryPolicy(max_attempts=2, backoff=0.001, timeout=0.05),
        )
    assert isinstance(ei.value.__cause__, AttemptTimeout)


def test_retryable_decorator():
    calls = []

    @retryable(max_attempts=4, backoff=0.001)
    def op(x):
        calls.append(x)
        if len(calls) < 2:
            raise OSError("blip")
        return x + 1

    assert op(41) == 42 and calls == [41, 41]
    with pytest.raises(ValueError):
        retryable(RetryPolicy(), max_attempts=2)  # both forms at once


def test_retry_on_retry_hook_observes_attempts():
    seen = []

    def flaky():
        if len(seen) < 2:
            raise OSError("x")
        return True

    assert retry_call(
        flaky,
        policy=RetryPolicy(max_attempts=5, backoff=0.001),
        on_retry=lambda attempt, exc: seen.append((attempt, type(exc).__name__)),
    )
    assert seen == [(1, "OSError"), (2, "OSError")]


# ---------------------------------------------------------------------------
# guards.py
# ---------------------------------------------------------------------------

def test_tree_all_finite():
    assert tree_all_finite({"a": jnp.ones(3), "b": [np.arange(2), "str"]})
    assert not tree_all_finite({"a": jnp.array([1.0, np.nan])})
    assert not tree_all_finite({"a": np.array([np.inf])})
    assert tree_all_finite({"i": np.array([1, 2], np.int64)})  # ints vacuous
    assert not tree_all_finite(
        {"b": jnp.array([1.0, np.nan], jnp.bfloat16)}
    )  # ml_dtypes leaves are checked too


def test_guard_skip_keeps_prev_state():
    g = StepGuard(policy="skip", max_consecutive=5)
    good = {"w": jnp.ones(2)}
    bad = {"w": jnp.array([1.0, np.nan])}
    state, admitted = g.admit(1, bad, {"loss": 0.5}, prev_state=good)
    assert not admitted and state is good and g.skipped == 1
    state, admitted = g.admit(2, good, {"loss": 0.4}, prev_state=good)
    assert admitted and g.admitted == 1


def test_guard_rollback_returns_last_good_snapshot():
    g = StepGuard(policy="rollback", max_consecutive=5)
    s1 = {"w": jnp.full(2, 1.0)}
    s2 = {"w": jnp.full(2, 2.0)}
    bad = {"w": jnp.full(2, np.nan)}
    g.admit(1, s1, {"loss": 1.0}, prev_state={"w": jnp.zeros(2)})
    g.admit(2, s2, {"loss": 0.9}, prev_state=s1)
    state, admitted = g.admit(3, bad, {"loss": float("nan")}, prev_state=bad)
    assert not admitted and state is s2 and g.rollbacks == 1


def test_guard_raise_policy_and_streak_escalation():
    g = StepGuard(policy="raise")
    with pytest.raises(NonFiniteError):
        g.admit(1, {"w": jnp.array([np.nan])}, {}, prev_state=None)
    g2 = StepGuard(policy="skip", max_consecutive=3)
    good = {"w": jnp.ones(1)}
    bad = {"w": jnp.array([np.nan])}
    g2.admit(1, bad, {}, prev_state=good)
    g2.admit(2, bad, {}, prev_state=good)
    with pytest.raises(NonFiniteError, match="3 consecutive"):
        g2.admit(3, bad, {}, prev_state=good)


def test_guard_metrics_only_check():
    g = StepGuard(policy="skip", check="metrics")
    bad_state = {"w": jnp.array([np.nan])}
    state, admitted = g.admit(1, bad_state, {"loss": 1.0}, prev_state=None)
    assert admitted and state is bad_state  # state not inspected
    _, admitted = g.admit(2, bad_state, {"loss": float("inf")}, prev_state={})
    assert not admitted


def test_guard_coerce_and_validation():
    assert StepGuard.coerce("skip").policy == "skip"
    g = StepGuard(policy="rollback")
    assert StepGuard.coerce(g) is g
    with pytest.raises(ValueError, match="policy"):
        StepGuard(policy="explode")
    with pytest.raises(TypeError):
        StepGuard.coerce(42)


def test_run_resumable_guard_skips_poison_batch(tmp_path):
    """A NaN batch mid-stream must cost one update, not the run: guarded
    training matches training that never saw the poison batch."""
    import jax

    from tensorframes_tpu.training import run_resumable

    @jax.jit
    def step(state, batch):
        new = {"w": state["w"] + batch}
        return new, {"loss": new["w"].sum()}

    clean = [jnp.full((2,), float(i)) for i in range(6)]
    poisoned = list(clean)
    poisoned[3] = jnp.full((2,), np.nan)

    guard = StepGuard(policy="skip", max_consecutive=3)
    got, ran = run_resumable(
        step, {"w": jnp.zeros(2)},
        Checkpointer(str(tmp_path / "a"), backend="npz"),
        poisoned, num_steps=6, save_every=0, guard=guard,
    )
    assert ran == 6 and guard.skipped == 1
    want = sum(float(i) for i in range(6) if i != 3)
    np.testing.assert_allclose(np.asarray(got["w"]), np.full(2, want))


def test_run_resumable_guard_rollback_and_escalation(tmp_path):
    import jax

    from tensorframes_tpu.training import run_resumable

    @jax.jit
    def step(state, batch):
        new = {"w": state["w"] + batch}
        return new, {"loss": new["w"].sum()}

    all_bad = [jnp.full((2,), np.nan)] * 5
    with pytest.raises(NonFiniteError):
        run_resumable(
            step, {"w": jnp.zeros(2)},
            Checkpointer(str(tmp_path / "b"), backend="npz"),
            all_bad, num_steps=5, save_every=0,
            guard=StepGuard(policy="rollback", max_consecutive=3),
        )


def test_train_on_frame_guard_plain_loop():
    """guard= works in the non-checkpointed train_on_frame path too."""
    import jax

    from tensorframes_tpu.training import train_on_frame

    frame = tfs.frame_from_arrays({"x": np.ones((32, 2), np.float32)})

    calls = []

    @jax.jit
    def _step(state, batch):
        new = {"w": state["w"] + batch["x"].sum()}
        return new, {"loss": new["w"].sum()}

    def step(state, batch):
        calls.append(1)
        if len(calls) == 2:  # poison exactly one update
            return {"w": jnp.full(2, np.nan)}, {"loss": jnp.float32(np.nan)}
        return _step(state, batch)

    guard = StepGuard(policy="skip", max_consecutive=4)
    state, ran = train_on_frame(
        step, {"w": jnp.zeros(2)}, frame, ["x"], batch_size=8,
        num_steps=4, prefetch=0, shuffle=False, guard=guard,
    )
    assert ran == 4 and guard.skipped == 1
    assert np.all(np.isfinite(np.asarray(state["w"])))


# ---------------------------------------------------------------------------
# checkpoint integrity
# ---------------------------------------------------------------------------

def _save_steps(root, steps, backend="npz"):
    ck = Checkpointer(str(root), backend=backend)
    for s in steps:
        ck.save(s, {"w": jnp.full((4,), float(s))})
    return ck


def test_manifest_records_crc_and_size(tmp_path):
    import json

    _save_steps(tmp_path / "run", [1])
    with open(tmp_path / "run" / "step_1" / "manifest.json") as f:
        manifest = json.load(f)
    entry = manifest[0]
    assert entry["nbytes"] == 4 * np.dtype(np.float64).itemsize or entry["nbytes"] > 0
    # crc matches an independent recomputation of the payload bytes
    assert entry["crc32"] == zlib.crc32(
        np.ascontiguousarray(np.full((4,), 1.0, np.dtype(entry["dtype"]))).tobytes()
    )


def test_truncated_newest_falls_back_to_previous(tmp_path):
    ck = _save_steps(tmp_path / "run", [1, 2, 3])
    payload = tmp_path / "run" / "step_3" / "arrays.npz"
    data = payload.read_bytes()
    payload.write_bytes(data[: len(data) // 2])
    got = ck.restore(like={"w": jnp.zeros(4)})
    np.testing.assert_array_equal(np.asarray(got["w"]), np.full(4, 2.0))


def test_crc_mismatch_falls_back(tmp_path):
    """A bit-rotted payload that is still a VALID zip is caught by the
    per-array CRC, not just by zipfile structure checks."""
    ck = _save_steps(tmp_path / "run", [1, 2])
    # rewrite step_2's payload with same-shape wrong bytes
    np.savez_compressed(
        tmp_path / "run" / "step_2" / "arrays.npz",
        a0=np.zeros(32, np.uint8),
    )
    got = ck.restore(like={"w": jnp.zeros(4)})
    np.testing.assert_array_equal(np.asarray(got["w"]), np.full(4, 1.0))


def test_explicit_step_corruption_raises(tmp_path):
    ck = _save_steps(tmp_path / "run", [1, 2])
    (tmp_path / "run" / "step_2" / "arrays.npz").write_bytes(b"garbage")
    with pytest.raises(CheckpointCorruptionError):
        ck.restore(step=2, like={"w": jnp.zeros(4)})
    # the older step is still explicitly restorable
    got = ck.restore(step=1, like={"w": jnp.zeros(4)})
    np.testing.assert_array_equal(np.asarray(got["w"]), np.full(4, 1.0))


def test_all_steps_corrupt_raises(tmp_path):
    ck = _save_steps(tmp_path / "run", [1, 2])
    for s in (1, 2):
        (tmp_path / "run" / f"step_{s}" / "arrays.npz").write_bytes(b"x")
    with pytest.raises(CheckpointCorruptionError, match="no intact checkpoint"):
        ck.restore(like={"w": jnp.zeros(4)})


def test_corrupt_manifest_falls_back(tmp_path):
    ck = _save_steps(tmp_path / "run", [1, 2])
    (tmp_path / "run" / "step_2" / "manifest.json").write_text("{not json")
    got = ck.restore(like={"w": jnp.zeros(4)})
    np.testing.assert_array_equal(np.asarray(got["w"]), np.full(4, 1.0))


def test_verify_audit_mode(tmp_path):
    ck = _save_steps(tmp_path / "run", [1, 2, 3])
    (tmp_path / "run" / "step_2" / "arrays.npz").write_bytes(b"zzz")
    report = ck.verify()
    assert report[1]["ok"] is True and report[3]["ok"] is True
    assert report[2]["ok"] is False and report[2]["errors"]
    assert ck.verify(2)[2]["ok"] is False
    # verify is read-only: the corrupted step is still on disk
    assert ck.all_steps() == [1, 2, 3]


def test_orphaned_tmp_gc_on_init(tmp_path):
    root = tmp_path / "run"
    _save_steps(root, [1])
    corpse = root / "step_5.tmp9999"
    corpse.mkdir()
    (corpse / "arrays.npz").write_bytes(b"partial")
    ck = Checkpointer(str(root), backend="npz")
    assert not corpse.exists()
    assert ck.all_steps() == [1]  # real steps untouched


def test_save_restore_under_injected_io_faults(tmp_path):
    """Transient IO faults (fail every 2nd attempt) are absorbed by the
    retry policy: every save and restore succeeds."""
    ck = Checkpointer(
        str(tmp_path / "run"), backend="npz",
        retry=RetryPolicy(max_attempts=3, backoff=0.001),
    )
    with inject("checkpoint.save", OSError, every_n=2) as inj:
        for s in (1, 2, 3, 4):
            ck.save(s, {"w": jnp.full((2,), float(s))})
    assert inj.fired >= 1  # faults really happened
    assert ck.all_steps() == [1, 2, 3, 4]
    with inject("checkpoint.restore", OSError, every_n=2) as inj:
        for s in (1, 2, 3, 4):
            got = ck.restore(step=s, like={"w": jnp.zeros(2)})
            np.testing.assert_array_equal(
                np.asarray(got["w"]), np.full(2, float(s))
            )
    assert inj.fired >= 1


def test_unretried_fault_propagates(tmp_path):
    ck = Checkpointer(str(tmp_path / "run"), backend="npz")  # no retry
    with inject("checkpoint.save", OSError, every_n=1):
        with pytest.raises(OSError):
            ck.save(1, {"w": jnp.ones(2)})
    assert ck.all_steps() == []  # nothing published


def test_run_resumable_survives_transient_save_faults(tmp_path):
    """End-to-end: periodic checkpoint saves hit every-2nd-attempt IO
    faults; the retrying checkpointer absorbs them and training output
    matches a fault-free run."""
    import jax

    from tensorframes_tpu.training import run_resumable

    @jax.jit
    def step(state, batch):
        new = {"w": state["w"] + batch}
        return new, {"loss": new["w"].sum()}

    batches = [jnp.full((2,), float(i)) for i in range(8)]
    ck = Checkpointer(
        str(tmp_path / "run"), backend="npz",
        retry=RetryPolicy(max_attempts=3, backoff=0.001),
    )
    with inject("checkpoint.save", OSError, every_n=2) as inj:
        got, ran = run_resumable(
            step, {"w": jnp.zeros(2)}, ck, batches, num_steps=8, save_every=2
        )
    assert ran == 8 and inj.fired >= 1
    np.testing.assert_allclose(np.asarray(got["w"]), np.full(2, sum(range(8))))
    assert ck.latest_step() == 8


# ---------------------------------------------------------------------------
# prefetch device-put retry
# ---------------------------------------------------------------------------

def test_prefetch_retry_absorbs_device_put_faults():
    from tensorframes_tpu import io as tfio

    frame = tfs.frame_from_arrays({"x": np.arange(16.0)})
    with inject("io.prefetch.device_put", OSError, every_n=2) as inj:
        out = list(
            tfio.prefetch_to_device(
                tfio.iterate_batches(frame, batch_size=4),
                size=2,
                retry=RetryPolicy(max_attempts=3, backoff=0.001),
            )
        )
    assert len(out) == 4 and inj.fired >= 1
    got = np.concatenate([np.asarray(b["x"]) for b in out])
    np.testing.assert_array_equal(got, np.arange(16.0))


def test_prefetch_unretried_fault_propagates():
    from tensorframes_tpu import io as tfio

    frame = tfs.frame_from_arrays({"x": np.arange(8.0)})
    with inject("io.prefetch.device_put", OSError, every_n=1):
        with pytest.raises(OSError):
            list(
                tfio.prefetch_to_device(
                    tfio.iterate_batches(frame, batch_size=4), size=2
                )
            )


# ---------------------------------------------------------------------------
# review-fix regressions
# ---------------------------------------------------------------------------

def test_run_resumable_resumes_past_corrupted_newest(tmp_path):
    """A relaunch whose newest checkpoint is torn must fall back to the
    previous intact step and still converge to the uninterrupted result
    (restore_latest + matching batch replay)."""
    import jax

    from tensorframes_tpu.training import run_resumable

    @jax.jit
    def step(state, batch):
        new = {"w": state["w"] * 1.01 + batch}
        return new, {"loss": new["w"].sum()}

    batches = [jnp.full((2,), float(i), jnp.float32) for i in range(10)]
    init = {"w": jnp.zeros(2, jnp.float32)}
    ck = Checkpointer(str(tmp_path / "run"), backend="npz")
    run_resumable(step, init, ck, batches, num_steps=6, save_every=2)
    assert ck.latest_step() == 6
    # tear the newest step, as a crash mid-write would
    payload = tmp_path / "run" / "step_6" / "arrays.npz"
    payload.write_bytes(payload.read_bytes()[:10])
    got, ran = run_resumable(step, init, ck, batches, num_steps=10, save_every=2)
    assert ran == 6  # resumed from step 4, not 6
    ref, _ = run_resumable(
        step, init, Checkpointer(str(tmp_path / "ref"), backend="npz"),
        batches, num_steps=10, save_every=100,
    )
    np.testing.assert_array_equal(np.asarray(got["w"]), np.asarray(ref["w"]))


def test_train_on_frame_resumes_past_corrupted_newest(tmp_path):
    """The host-side replay fast-forward must skip to the step that
    actually restores (latest_intact_step), not the torn latest."""
    import jax

    import tensorframes_tpu.training as tn

    frame = tfs.frame_from_arrays(
        {"x": np.arange(64, dtype=np.float32).reshape(16, 4)}
    )

    @jax.jit
    def step(state, batch):
        new = {"w": state["w"] * 1.01 + batch["x"].sum()}
        return new, {"loss": new["w"].sum()}

    init = {"w": jnp.zeros((), jnp.float32)}
    ck = Checkpointer(str(tmp_path / "run"), backend="npz")
    tn.train_on_frame(step, init, frame, ["x"], batch_size=4, num_steps=3,
                      checkpointer=ck, save_every=1, shuffle=False, prefetch=0)
    payload = tmp_path / "run" / "step_3" / "arrays.npz"
    payload.write_bytes(payload.read_bytes()[:10])
    got, ran = tn.train_on_frame(
        step, init, frame, ["x"], batch_size=4, num_steps=4,
        checkpointer=ck, save_every=1, shuffle=False, prefetch=0,
    )
    assert ran == 2  # resumed from intact step 2, re-ran 3 and 4
    ref, _ = tn.train_on_frame(
        step, init, frame, ["x"], batch_size=4, num_steps=4,
        checkpointer=Checkpointer(str(tmp_path / "ref"), backend="npz"),
        save_every=100, shuffle=False, prefetch=0,
    )
    np.testing.assert_array_equal(np.asarray(got["w"]), np.asarray(ref["w"]))


def test_guard_raise_emergency_checkpoint_is_finite(tmp_path):
    """When the guard aborts on NaN, the save-before-raise emergency
    checkpoint must hold the last GOOD state — resuming from a poisoned
    checkpoint would recreate the crash loop forever."""
    import jax

    from tensorframes_tpu.training import run_resumable

    @jax.jit
    def step(state, batch):
        new = {"w": state["w"] + batch}
        return new, {"loss": new["w"].sum()}

    batches = [jnp.full((2,), v, jnp.float32)
               for v in (1.0, 2.0, np.nan, 4.0)]
    ck = Checkpointer(str(tmp_path / "run"), backend="npz")
    with pytest.raises(NonFiniteError):
        run_resumable(
            step, {"w": jnp.zeros(2, jnp.float32)}, ck, batches,
            num_steps=4, save_every=0, guard="raise",
        )
    assert ck.latest_step() == 2  # the last admitted step, not the NaN one
    got = ck.restore(like={"w": jnp.zeros(2, jnp.float32)})
    assert np.isfinite(np.asarray(got["w"])).all()
    np.testing.assert_array_equal(np.asarray(got["w"]), np.full(2, 3.0))


def test_retry_call_none_policy_is_plain_call():
    calls = []

    def once():
        calls.append(1)
        raise OSError("boom")

    with pytest.raises(OSError):
        retry_call(once, policy=None)
    assert len(calls) == 1  # no surprise retries without an opt-in


def test_tmp_gc_liveness_rules(tmp_path):
    """Init-time GC: spares another LIVE process's temp and this
    process's registered in-flight temp; collects dead-pid corpses AND
    same-pid temps that are not registered — a restarted pid-1 container
    reuses the pid, so unregistered same-pid temps are corpses from the
    previous incarnation, not live saves."""
    import subprocess
    import sys

    from tensorframes_tpu import checkpoint as ckp

    root = tmp_path / "run"
    root.mkdir()
    # same pid, not in the live registry: previous-incarnation corpse
    stale_same_pid = root / f"step_7.tmp{os.getpid()}_deadbeef"
    stale_same_pid.mkdir()
    # same pid, registered: a save in flight on another thread
    in_flight = root / f"step_9.tmp{os.getpid()}_cafef00d"
    in_flight.mkdir()
    ckp._live_tmps.add(str(in_flight))
    # dead foreign pid: corpse
    dead_pid = subprocess.run(
        [sys.executable, "-c", "import os; print(os.getpid())"],
        capture_output=True, text=True,
    ).stdout.strip()
    dead = root / f"step_8.tmp{dead_pid}_cafebabe"
    dead.mkdir()
    # live foreign pid: spared
    sleeper = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(30)"])
    live_foreign = root / f"step_6.tmp{sleeper.pid}_beefcafe"
    live_foreign.mkdir()
    try:
        Checkpointer(str(root), backend="npz")
        assert not stale_same_pid.exists()  # pid-reuse corpse collected
        assert not dead.exists()            # dead corpse collected
        assert in_flight.exists()           # registered in-flight spared
        assert live_foreign.exists()        # live writer spared
    finally:
        ckp._live_tmps.discard(str(in_flight))
        sleeper.kill()


def test_crashed_publish_heals_on_init(tmp_path):
    """A save SIGKILLed between moving the old step aside and publishing
    the new one leaves only step_N.old; the next Checkpointer init must
    rename it back so the step is never lost."""
    root = tmp_path / "run"
    _save_steps(root, [2, 4])
    os.rename(root / "step_4", root / "step_4.old")  # simulate the window
    ck2 = Checkpointer(str(root), backend="npz")
    assert ck2.all_steps() == [2, 4]
    got = ck2.restore(like={"w": jnp.zeros(4)})
    np.testing.assert_array_equal(np.asarray(got["w"]), np.full(4, 4.0))
    # superseded refuse (both dirs present) is deleted, step kept
    _save_steps(root, [6])
    (root / "step_6.old").mkdir()
    Checkpointer(str(root), backend="npz")
    assert not (root / "step_6.old").exists()
    assert (root / "step_6").exists()


def test_resave_same_step_never_leaves_gap(tmp_path):
    """Re-saving an existing step publishes via rename-aside: at no point
    is the step unpublished, and the final content is the new save's."""
    root = tmp_path / "run"
    ck = _save_steps(root, [3])
    ck.save(3, {"w": jnp.full((4,), 99.0)})
    got = ck.restore(step=3, like={"w": jnp.zeros(4)})
    np.testing.assert_array_equal(np.asarray(got["w"]), np.full(4, 99.0))
    assert not (root / "step_3.old").exists()


def test_prefetch_worker_base_exception_surfaces():
    """A BaseException killing the worker must raise in the consumer,
    not truncate the stream into a clean-looking end (silent data loss)."""
    from tensorframes_tpu import io as tfio

    def dying_source():
        yield {"x": np.zeros(2)}
        raise KeyboardInterrupt  # BaseException, not Exception

    it = tfio.prefetch_to_device(dying_source(), size=2)
    next(it)
    with pytest.raises(KeyboardInterrupt):
        next(it)


def test_verify_returns_report_on_transient_read_errors(tmp_path, monkeypatch):
    """verify() must return its report — never raise — even when the
    payload read fails transiently (and keeps failing past the retry
    budget)."""
    _save_steps(tmp_path / "run", [1])
    ck_flaky = Checkpointer(
        str(tmp_path / "run"), backend="npz",
        retry=RetryPolicy(max_attempts=2, backoff=0.001),
    )
    monkeypatch.setattr(
        type(ck_flaky), "_read_npz_payload",
        lambda self, path: (_ for _ in ()).throw(OSError("EIO")),
    )
    report = ck_flaky.verify()
    assert report[1]["ok"] is None  # unknown, not corrupt
    assert any("transient read error" in e for e in report[1]["errors"])


def test_tree_all_finite_sharded_arrays():
    """Guards must actually inspect sharded device arrays (a guard that
    silently passes uncheckable leaves is no guard)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:8]), ("dp",))
    sh = NamedSharding(mesh, P("dp"))
    good = jax.device_put(jnp.arange(16, dtype=jnp.float32), sh)
    bad = jax.device_put(jnp.full((16,), np.nan, jnp.float32), sh)
    assert tree_all_finite({"w": good})
    assert not tree_all_finite({"w": bad})


# ---------------------------------------------------------------------------
# retry deadline (ISSUE 8 satellite): the total-elapsed cap
# ---------------------------------------------------------------------------

def test_retry_deadline_caps_total_elapsed():
    """A huge attempt budget must not stretch past deadline_s: the cap
    is a wall-clock promise, not an attempt count."""
    calls = {"n": 0}

    def always_flaky():
        calls["n"] += 1
        raise OSError("coordinator not up")

    policy = RetryPolicy(max_attempts=10**6, backoff=0.02, deadline_s=0.4)
    t0 = time.monotonic()
    with pytest.raises(RetryError, match="deadline_s=0.4 exceeded"):
        retry_call(always_flaky, policy=policy, describe="flaky")
    elapsed = time.monotonic() - t0
    assert elapsed < 3.0
    assert calls["n"] >= 2  # it genuinely retried before giving up


def test_retry_deadline_bounds_blocked_attempt():
    """deadline_s arms a watchdog window even when per-attempt timeout
    is unset: a single blocked attempt cannot eat the whole budget and
    then some."""
    policy = RetryPolicy(max_attempts=3, backoff=0.01, deadline_s=0.3)
    t0 = time.monotonic()
    with pytest.raises(RetryError, match="deadline_s"):
        retry_call(lambda: time.sleep(30), policy=policy, describe="wedged")
    assert time.monotonic() - t0 < 5.0


def test_retry_deadline_validation_and_success_path():
    with pytest.raises(ValueError, match="deadline_s"):
        RetryPolicy(deadline_s=0)
    with pytest.raises(ValueError, match="deadline_s"):
        RetryPolicy(deadline_s=-1)
    # a call that succeeds within the deadline is unaffected
    policy = RetryPolicy(max_attempts=3, backoff=0.01, deadline_s=5.0)
    assert retry_call(lambda: 17, policy=policy) == 17


def test_retry_deadline_attempt_cap_still_wins_when_faster():
    """max_attempts exhaustion inside the deadline keeps the classic
    error (the deadline is a cap, not a reclassification)."""
    policy = RetryPolicy(max_attempts=2, backoff=0.001, deadline_s=30.0)
    with pytest.raises(RetryError, match="all 2 attempts failed"):
        retry_call(
            lambda: (_ for _ in ()).throw(OSError("x")), policy=policy,
            describe="quick",
        )


def test_init_distributed_flaky_coordinator_bounded_by_deadline(tmp_path):
    """Subprocess flaky-coordinator drill: every handshake attempt fails
    (distributed.init fault injection), the retry budget is effectively
    infinite, and deadline_s must still bound init_distributed to
    wall-clock seconds."""
    import subprocess
    import sys

    script = """
import time
from tensorframes_tpu.resilience import RetryError, RetryPolicy, inject
from tensorframes_tpu.parallel import init_distributed

t0 = time.monotonic()
with inject("distributed.init", ConnectionError("coordinator down")) as inj:
    try:
        init_distributed(
            coordinator_address="127.0.0.1:1",
            num_processes=2,
            process_id=0,
            retry=RetryPolicy(
                max_attempts=10**6, backoff=0.05, deadline_s=1.0,
            ),
        )
        raise SystemExit("init unexpectedly succeeded")
    except RetryError as e:
        print("BOUNDED", f"{time.monotonic() - t0:.2f}", flush=True)
        print("ATTEMPTS", inj.fired, flush=True)
        assert "deadline_s=1" in str(e), e
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [__import__("sys").executable, "-c", script], env=env, cwd=repo,
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstdout: {proc.stdout}\nstderr: {proc.stderr}"
    )
    assert "BOUNDED" in proc.stdout
    wall = float(proc.stdout.split("BOUNDED")[1].split()[0])
    assert wall < 10.0  # the deadline held (1.0s + scheduling slack)
    assert int(proc.stdout.split("ATTEMPTS")[1].split()[0]) >= 2


# ---------------------------------------------------------------------------
# fault-site registry drift guard (ISSUE 8 satellite): every site name
# instrumented across the package is registered AND documented
# ---------------------------------------------------------------------------

def test_fault_sites_registered_and_documented():
    import pathlib
    import re

    import tensorframes_tpu
    from tensorframes_tpu.resilience import faults as faults_mod

    registered = set(faults_mod.list_sites())
    # 1) every literal site name at an instrumentation point in the
    # package is registered (a new fault_point without register_site is
    # exactly the silent drift this guard exists to catch)
    src_root = pathlib.Path(tensorframes_tpu.__file__).parent
    pat = re.compile(
        r"(?:fault_point|delay_point|kill_point)\(\s*[\"']([\w.]+)[\"']"
    )
    instrumented = set()
    for path in src_root.rglob("*.py"):
        instrumented |= set(pat.findall(path.read_text()))
    missing = instrumented - registered
    assert not missing, (
        f"fault sites instrumented but not registered: {sorted(missing)} "
        "— add faults.register_site(...) next to the instrumentation"
    )
    # 2) the classic SITES tuple stays a subset of the registry
    assert set(faults_mod.SITES) <= registered
    # 3) every registered site is documented in docs/resilience.md
    docs = (
        pathlib.Path(__file__).parent.parent / "docs" / "resilience.md"
    ).read_text()
    undocumented = [s for s in sorted(registered) if s not in docs]
    assert not undocumented, (
        f"fault sites registered but absent from docs/resilience.md: "
        f"{undocumented}"
    )


def test_register_site_validates_and_lists_sorted():
    from tensorframes_tpu.resilience import faults as faults_mod

    with pytest.raises(ValueError):
        faults_mod.register_site("", "nowhere")
    sites = faults_mod.list_sites()
    assert list(sites) == sorted(sites)
    assert "executor.dispatch" in sites
    assert "fleet.heartbeat" in sites
    # the serving-fleet chaos sites (ISSUE 13): registered centrally in
    # faults.py so drills see them even before the serving package loads
    assert "router.dispatch" in sites
    assert "serving.replica" in sites


# ---------------------------------------------------------------------------
# delay_point / kill_point semantics
# ---------------------------------------------------------------------------

def test_delay_point_sleeps_instead_of_raising():
    from tensorframes_tpu.resilience import Delay, delay_point

    t0 = time.monotonic()
    with inject("unit.delay", Delay(0.15)):
        delay_point("unit.delay")  # must not raise
    assert time.monotonic() - t0 >= 0.14
    # a non-Delay injection still raises through delay_point
    with inject("unit.delay", RuntimeError("hard fault")):
        with pytest.raises(RuntimeError, match="hard fault"):
            delay_point("unit.delay")


def test_delay_point_noop_unarmed():
    from tensorframes_tpu.resilience import delay_point

    t0 = time.monotonic()
    delay_point("unit.delay")
    assert time.monotonic() - t0 < 0.05


def test_kill_point_sigkills_own_process():
    """kill_point + KillRank must die by SIGKILL — no exception path, no
    cleanup (subprocess-verified; in-process it would kill pytest)."""
    import signal as _signal
    import subprocess

    script = """
from tensorframes_tpu.resilience import KillRank, inject, kill_point
with inject("fleet.rank.kill", KillRank):
    kill_point()
print("SURVIVED", flush=True)
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [__import__("sys").executable, "-c", script], env=env, cwd=repo,
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == -_signal.SIGKILL
    assert "SURVIVED" not in proc.stdout


def test_kill_point_noop_unarmed_and_passthrough():
    from tensorframes_tpu.resilience import kill_point

    kill_point()  # un-armed: a dict check, nothing else
    with inject("fleet.rank.kill", RuntimeError("not a kill")):
        with pytest.raises(RuntimeError, match="not a kill"):
            kill_point()
