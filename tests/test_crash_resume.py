"""Crash/resume integration: a training subprocess is killed with
SIGKILL mid-loop (the real preemption shape — no atexit, no exception
path, no emergency checkpoint), relaunched, and must converge to exactly
the state an uninterrupted run produces. This is the end-to-end proof of
the checkpoint subsystem's atomicity+fsync+fallback story: whatever
instant the KILL lands — including mid-``save`` — the relaunch finds an
intact step and replays deterministically."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

# the trainer run as a subprocess: float32 multiply-accumulate steps so
# replay order matters (a wrong resume point changes the result bits)
_TRAINER = """
import os, sys, time
ckdir, num_steps, sleep_s, save_every = (
    sys.argv[1], int(sys.argv[2]), float(sys.argv[3]), int(sys.argv[4])
)
import jax.numpy as jnp
import numpy as np
from tensorframes_tpu.checkpoint import Checkpointer
from tensorframes_tpu.training import run_resumable

def step(state, batch):
    time.sleep(sleep_s)   # slow the loop so SIGKILL lands mid-run
    new = {"w": state["w"] * jnp.float32(1.01) + batch}
    return new, {"loss": new["w"].sum()}

batches = [jnp.full((4,), float(i % 7), jnp.float32) for i in range(num_steps)]
init = {"w": jnp.zeros((4,), jnp.float32)}
state, ran = run_resumable(
    step, init, Checkpointer(ckdir, backend="npz"), batches,
    num_steps=num_steps, save_every=save_every,
)
np.save(os.path.join(ckdir, "final.npy"), np.asarray(state["w"]))
print("DONE", ran, flush=True)
"""


def _spawn(ckdir: str, num_steps: int, sleep_s: float, save_every: int):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-c", _TRAINER, ckdir, str(num_steps),
         str(sleep_s), str(save_every)],
        env=env, cwd=_REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )


def _wait_for_checkpoint(proc, ckdir: str, min_step: int, timeout: float = 180.0):
    """Block until a step_>=min_step dir exists; fail fast if the trainer
    exits first (its stderr is the diagnosis)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        steps = [
            int(n.split("_")[1]) for n in os.listdir(ckdir)
            if n.startswith("step_") and ".tmp" not in n
        ]
        if steps and max(steps) >= min_step:
            return max(steps)
        if proc.poll() is not None:
            out, err = proc.communicate()
            raise AssertionError(
                f"trainer exited (rc={proc.returncode}) before writing a "
                f"checkpoint >= {min_step}\nstdout: {out}\nstderr: {err}"
            )
        time.sleep(0.01)
    proc.kill()
    raise AssertionError(f"no checkpoint >= {min_step} within {timeout}s")


def _run_to_completion(ckdir: str, num_steps: int, save_every: int,
                       timeout: float = 300.0) -> np.ndarray:
    proc = _spawn(ckdir, num_steps, 0.0, save_every)
    out, err = proc.communicate(timeout=timeout)
    assert proc.returncode == 0, f"trainer failed\nstdout: {out}\nstderr: {err}"
    assert "DONE" in out
    return np.load(os.path.join(ckdir, "final.npy"))


def _reference(num_steps: int) -> np.ndarray:
    import jax.numpy as jnp

    w = jnp.zeros((4,), jnp.float32)
    for i in range(num_steps):
        w = w * jnp.float32(1.01) + jnp.full((4,), float(i % 7), jnp.float32)
    return np.asarray(w)


def test_kill9_mid_training_resumes_to_identical_state(tmp_path):
    """Single-kill fast variant (tier-1): SIGKILL after the first
    checkpoint lands, relaunch, final state bit-identical to an
    uninterrupted run."""
    ckdir = str(tmp_path / "run")
    os.makedirs(ckdir)
    num_steps, save_every = 60, 2
    proc = _spawn(ckdir, num_steps, 0.05, save_every)
    try:
        killed_at = _wait_for_checkpoint(proc, ckdir, min_step=save_every)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
        assert proc.returncode == -signal.SIGKILL
    finally:
        if proc.poll() is None:  # pragma: no cover - cleanup on test bugs
            proc.kill()
    assert killed_at < num_steps  # genuinely mid-run
    assert not os.path.exists(os.path.join(ckdir, "final.npy"))

    final = _run_to_completion(ckdir, num_steps, save_every)
    np.testing.assert_array_equal(final, _reference(num_steps))


@pytest.mark.slow
def test_repeated_kill9_still_converges(tmp_path):
    """Three consecutive preemptions at whatever instants the scheduler
    deals — including possibly mid-save — then a clean finish; the result
    must still match the uninterrupted run exactly."""
    ckdir = str(tmp_path / "run")
    os.makedirs(ckdir)
    num_steps, save_every = 80, 2
    for round_ in range(3):
        proc = _spawn(ckdir, num_steps, 0.04, save_every)
        try:
            prev = [
                int(n.split("_")[1]) for n in os.listdir(ckdir)
                if n.startswith("step_") and ".tmp" not in n
            ]
            target = (max(prev) if prev else 0) + save_every
            if target >= num_steps:
                proc.kill()
                break
            _wait_for_checkpoint(proc, ckdir, min_step=target)
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:  # pragma: no cover
                proc.kill()
    final = _run_to_completion(ckdir, num_steps, save_every)
    np.testing.assert_array_equal(final, _reference(num_steps))
