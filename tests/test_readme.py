"""The README quickstart must actually run — extracted and executed
verbatim so the front-page example can never rot."""

import os
import re


def test_readme_quickstart_executes():
    readme = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "README.md"
    )
    with open(readme) as f:
        text = f.read()
    blocks = re.findall(r"```python\n(.*?)```", text, re.DOTALL)
    assert blocks, "README has no python blocks"
    # the quickstart is the first python block; the distribution snippet
    # (second block) references a placeholder `big_array`/`program`, so
    # only fully self-contained blocks execute
    env: dict = {}
    exec(compile(blocks[0], "README.md#quickstart", "exec"), env)
    # the quickstart defines df2/total/sums; sanity-check their values
    assert [r["z"] for r in env["df2"].collect()][:3] == [3.0, 4.0, 5.0]
    assert float(env["total"]) == sum(range(10))
    got = {r["k"]: r["v"] for r in env["sums"].collect()}
    assert got == {1: 3.0, 2: 3.0}
