"""Persistent AOT executable cache + warmup (ISSUE 5).

Covers the acceptance contracts: cross-process round trip (a subprocess
warms the store, the parent hits it), two-writer races on one store
dir, corrupt/truncated entries falling back to a fresh compile with the
fallback counter bumped, byte-bound eviction, fused plan Programs
hitting the same store, bit-identical outputs cache-on vs cache-off,
and the executor's split compile/first-run accounting.
"""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.compilecache import (
    active_store,
    partitioner_row_counts,
    program_fingerprint,
    store_for,
    warmup,
)
from tensorframes_tpu.observability.metrics import REGISTRY


def _metric(name, labels=()):
    for d in REGISTRY.snapshot():
        if d["name"] == name and tuple(sorted(d["labels"].items())) == tuple(
            sorted(labels)
        ):
            return d
    return {"value": 0.0, "count": 0}


def _counter_val(name, labels=()):
    return _metric(name, labels)["value"]


def _hist_count(name):
    return _metric(name)["count"]


@pytest.fixture
def store_dir(tmp_path):
    """Point the runtime at a fresh store for one test; always restore
    the disabled default afterwards."""
    d = str(tmp_path / "cc")
    tfs.configure(compilation_cache_dir=d)
    try:
        yield d
    finally:
        tfs.configure(compilation_cache_dir="")


def _entries(store_dir):
    aot = os.path.join(store_dir, "aot")
    if not os.path.isdir(aot):
        return []
    return sorted(f for f in os.listdir(aot) if f.endswith(".xc"))


# ---------------------------------------------------------------------------
# defaults + fallback guarantees
# ---------------------------------------------------------------------------

def test_disabled_by_default_no_store_no_metrics(tmp_path):
    from tensorframes_tpu.config import get_config

    # active_store honors the live config; with the field empty it is None
    prev = get_config().compilation_cache_dir
    tfs.configure(compilation_cache_dir="")
    try:
        assert active_store() is None
        h0 = _counter_val("tftpu_compilecache_hits_total")
        m0 = _counter_val("tftpu_compilecache_misses_total")
        f = tfs.frame_from_arrays({"x": np.arange(8.0)})
        tfs.map_blocks(lambda x: {"y": x * 3.0}, f).blocks()
        assert _counter_val("tftpu_compilecache_hits_total") == h0
        assert _counter_val("tftpu_compilecache_misses_total") == m0
    finally:
        tfs.configure(compilation_cache_dir=prev)


def test_store_error_never_fails_dispatch(tmp_path):
    """An unusable cache dir (a FILE where the store dir should be)
    degrades to normal compiles — the dispatch still succeeds."""
    bad = tmp_path / "not-a-dir"
    bad.write_text("occupied")
    tfs.configure(compilation_cache_dir=str(bad))
    try:
        f = tfs.frame_from_arrays({"x": np.arange(8.0)})
        out = tfs.map_blocks(lambda x: {"y": x + 0.5}, f).blocks()
        np.testing.assert_array_equal(
            np.concatenate([b["y"] for b in out]), np.arange(8.0) + 0.5
        )
    finally:
        tfs.configure(compilation_cache_dir="")


# ---------------------------------------------------------------------------
# round trips
# ---------------------------------------------------------------------------

def test_in_process_roundtrip_bit_identical(store_dir):
    """Second (fresh) Program of the same fn+shape deserializes from
    disk: zero compiles, outputs bitwise equal to the cache-off run."""
    rng = np.random.default_rng(7)
    x = rng.standard_normal(64)
    frame = tfs.frame_from_arrays({"x": x}, num_blocks=2)

    def fn(x):
        return {"y": np.float64(2.0) * x * x - x / np.float64(3.0)}

    # reference run with the cache OFF
    tfs.configure(compilation_cache_dir="")
    ref = tfs.map_blocks(tfs.compile_program(fn, frame), frame).blocks()
    tfs.configure(compilation_cache_dir=store_dir)

    p1 = tfs.compile_program(fn, frame)
    warm_out = tfs.map_blocks(p1, frame).blocks()
    assert _entries(store_dir), "first run must publish store entries"

    h0 = _counter_val("tftpu_compilecache_hits_total")
    c0 = _hist_count("tftpu_executor_compile_seconds")
    p2 = tfs.compile_program(fn, frame)
    hit_out = tfs.map_blocks(p2, frame).blocks()
    assert _counter_val("tftpu_compilecache_hits_total") > h0
    assert _hist_count("tftpu_executor_compile_seconds") == c0
    assert _hist_count("tftpu_compilecache_load_seconds") >= 1
    for a, b, c in zip(ref, warm_out, hit_out):
        assert np.array_equal(a["y"], b["y"])
        assert np.array_equal(a["y"], c["y"])  # bit-identical, cache on/off


def test_cross_process_roundtrip(store_dir, tmp_path):
    """A subprocess warms the store; the parent's identical program
    hits it — the fingerprint survives process restarts."""
    script = tmp_path / "warm_child.py"
    script.write_text(
        "import os, sys\n"
        "sys.path.insert(0, %r)\n"
        "import numpy as np\n"
        "import tensorframes_tpu as tfs\n"
        "frame = tfs.frame_from_arrays({'v': np.arange(24.0)}, num_blocks=3)\n"
        "p = tfs.compile_program(lambda v: {'w': v * 7.0 + 1.0}, frame)\n"
        "tfs.map_blocks(p, frame).blocks()\n"
        "from tensorframes_tpu.compilecache import active_store\n"
        "print('entries=', len(active_store().stats()['entry_list']))\n"
        % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env = {**os.environ, "TFTPU_COMPILE_CACHE": store_dir,
           "JAX_PLATFORMS": "cpu"}
    r = subprocess.run([sys.executable, str(script)], env=env,
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stderr[-2000:]
    assert _entries(store_dir), "child must have published entries"

    h0 = _counter_val("tftpu_compilecache_hits_total")
    c0 = _hist_count("tftpu_executor_compile_seconds")
    frame = tfs.frame_from_arrays({"v": np.arange(24.0)}, num_blocks=3)
    p = tfs.compile_program(lambda v: {"w": v * 7.0 + 1.0}, frame)
    out = tfs.map_blocks(p, frame).blocks()
    np.testing.assert_array_equal(out[0]["w"], np.arange(8.0) * 7.0 + 1.0)
    assert _counter_val("tftpu_compilecache_hits_total") > h0, \
        "parent must hit the child's entries"
    assert _hist_count("tftpu_executor_compile_seconds") == c0, \
        "a disk hit must not compile"


def test_fused_plan_programs_hit_store(store_dir):
    """A fused lazy chain's composed Program goes through the same
    store: an identical fresh chain deserializes instead of compiling."""
    x = np.arange(48.0)

    def build_and_force():
        frame = tfs.frame_from_arrays({"x": x}, num_blocks=2)
        f1 = tfs.map_blocks(lambda x: {"y": x * 2.0 + 1.0}, frame)
        f2 = tfs.map_blocks(lambda y: {"z": y * 0.5 - 3.0}, f1)
        return f2.select(["z"]).blocks()

    first = build_and_force()
    assert _entries(store_dir)
    h0 = _counter_val("tftpu_compilecache_hits_total")
    second = build_and_force()
    assert _counter_val("tftpu_compilecache_hits_total") > h0
    for a, b in zip(first, second):
        assert np.array_equal(a["z"], b["z"])


# ---------------------------------------------------------------------------
# durability: corruption, races, eviction
# ---------------------------------------------------------------------------

def test_corrupt_entry_falls_back_to_compile(store_dir):
    frame = tfs.frame_from_arrays({"x": np.arange(16.0)}, num_blocks=2)

    def fn(x):
        return {"y": x - 11.0}

    tfs.map_blocks(tfs.compile_program(fn, frame), frame).blocks()
    entries = _entries(store_dir)
    assert entries
    # truncate one entry and bit-flip another byte range via rewrite
    path = os.path.join(store_dir, "aot", entries[0])
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[: max(8, len(blob) // 2)])

    fb0 = _counter_val("tftpu_compilecache_fallback_total",
                       (("reason", "corrupt"),))
    out = tfs.map_blocks(tfs.compile_program(fn, frame), frame).blocks()
    np.testing.assert_array_equal(out[0]["y"], np.arange(8.0) - 11.0)
    assert _counter_val("tftpu_compilecache_fallback_total",
                        (("reason", "corrupt"),)) > fb0
    # the defective entry was quarantined and re-published by the
    # fallback compile: the store heals itself
    assert _entries(store_dir)


def test_two_writer_race_same_store(store_dir):
    """Concurrent writers publishing the same and different entries
    leave a consistent store (atomic replace; no torn entries)."""
    store = store_for(os.path.join(store_dir, "aot"))
    frame = tfs.frame_from_arrays({"x": np.arange(32.0)}, num_blocks=2)
    programs = [
        tfs.compile_program((lambda k: lambda x: {"y": x + float(k)})(k),
                            frame)
        for k in range(4)
    ]

    errs = []

    def worker(p):
        try:
            for _ in range(3):
                tfs.map_blocks(p, frame).blocks()
        except Exception as e:  # pragma: no cover - the assertion target
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(p,))
               for p in programs for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    report = store.verify()
    assert report["ok"], report


def test_eviction_respects_byte_bound(store_dir):
    store = store_for(os.path.join(store_dir, "aot"))
    frame = tfs.frame_from_arrays({"x": np.arange(16.0)}, num_blocks=1)
    for k in range(4):
        p = tfs.compile_program(
            (lambda kk: lambda x: {"y": x * float(kk + 2)})(k), frame
        )
        tfs.map_blocks(p, frame).blocks()
    entries = [(os.path.join(store_dir, "aot", e),
                os.path.getsize(os.path.join(store_dir, "aot", e)))
               for e in _entries(store_dir)]
    assert len(entries) == 4
    # bound that fits roughly two entries
    bound = sum(s for _, s in entries[:2]) + 1
    ev0 = _counter_val("tftpu_compilecache_evictions_total")
    store.max_bytes = bound
    store._evict()
    left = _entries(store_dir)
    total = sum(
        os.path.getsize(os.path.join(store_dir, "aot", e)) for e in left
    )
    assert total <= bound
    assert len(left) < 4
    assert _counter_val("tftpu_compilecache_evictions_total") > ev0


# ---------------------------------------------------------------------------
# warmup
# ---------------------------------------------------------------------------

def test_warmup_precompiles_partitioner_buckets(store_dir):
    frame = tfs.frame_from_arrays({"x": np.arange(21.0)}, num_blocks=4)
    program = tfs.compile_program(lambda x: {"y": x * x}, frame)
    report = warmup(frame, program)
    # 21 rows over 4 blocks → blocks of 5 and 6 rows: both warmed
    assert {e["rows"] for e in report.entries} == {5, 6}
    assert report.compiled == 2

    c0 = _hist_count("tftpu_executor_compile_seconds")
    m0 = _counter_val("tftpu_executor_jit_cache_misses_total")
    h0 = _counter_val("tftpu_executor_jit_cache_hits_total")
    out = tfs.map_blocks(program, frame).blocks()
    assert _hist_count("tftpu_executor_compile_seconds") == c0, \
        "warmed dispatch must not compile"
    assert _counter_val("tftpu_executor_jit_cache_misses_total") == m0
    assert _counter_val("tftpu_executor_jit_cache_hits_total") > h0
    np.testing.assert_array_equal(
        np.concatenate([b["y"] for b in out]), np.arange(21.0) ** 2
    )


def test_warmup_rows_mode_buckets(store_dir):
    from tensorframes_tpu.ops.executor import bucket_rows

    frame = tfs.frame_from_arrays({"x": np.arange(10.0)}, num_blocks=1)
    program = tfs.compile_program(
        lambda x: {"s": x * 2.0}, frame, block=False
    )
    report = warmup(frame, program, block=False)
    # both regimes warmed: the exact size (adaptive pre-bucket phase)
    # and its power-of-two bucket (shape-proliferation phase)
    assert {e["rows"] for e in report.entries} == {10, bucket_rows(10)}
    c0 = _hist_count("tftpu_executor_compile_seconds")
    tfs.map_rows(program, frame).blocks()
    assert _hist_count("tftpu_executor_compile_seconds") == c0


def test_warmup_from_manifest(store_dir):
    """The executor records miss shapes; warmup replays them for a
    fresh program so a new process precompiles yesterday's traffic."""
    frame = tfs.frame_from_arrays({"x": np.arange(12.0)}, num_blocks=2)

    def fn(x):
        return {"y": x + 100.0}

    tfs.map_blocks(tfs.compile_program(fn, frame), frame).blocks()
    manifest = os.path.join(store_dir, "aot", "manifest.jsonl")
    rows = [json.loads(ln) for ln in open(manifest)]
    assert rows and rows[0]["inputs"][0][0] == "x"

    fresh = tfs.compile_program(fn, frame)
    report = warmup(None, fresh, manifest=manifest)
    assert report.entries, "manifest rows must map onto the program"
    c0 = _hist_count("tftpu_executor_compile_seconds")
    tfs.map_blocks(fresh, frame).blocks()
    assert _hist_count("tftpu_executor_compile_seconds") == c0


def test_warmup_manifest_requires_matching_dtype_and_cells(store_dir):
    """The manifest is store-wide: rows recorded for one program must
    not warm an unrelated program that happens to share input names."""
    f64 = tfs.frame_from_arrays({"x": np.arange(12.0)}, num_blocks=2)
    tfs.map_blocks(
        tfs.compile_program(lambda x: {"y": x + 1.0}, f64), f64
    ).blocks()
    manifest = os.path.join(store_dir, "aot", "manifest.jsonl")
    assert os.path.exists(manifest)

    # same input name 'x', different dtype: the recorded f64 shapes
    # must not be replayed into an i64 program
    i64 = tfs.frame_from_arrays({"x": np.arange(12)}, num_blocks=2)
    other = tfs.compile_program(lambda x: {"y": x * 2}, i64)
    report = warmup(None, other, manifest=manifest)
    assert not report.entries


def test_warmup_manifest_skips_sharded_rows(store_dir):
    """record_miss(sharded=True) rows under-specify the executable's
    layout (shapes alone carry no mesh): replaying them would burn an
    XLA compile on an UNSHARDED key the real sharded dispatch never
    hits — the replay must skip them, reported, zero compiles."""
    frame = tfs.frame_from_arrays({"x": np.arange(12.0)}, num_blocks=2)

    def fn(x):
        return {"y": x + 100.0}

    tfs.map_blocks(tfs.compile_program(fn, frame), frame).blocks()
    manifest = os.path.join(store_dir, "aot", "manifest.jsonl")
    rows = [json.loads(ln) for ln in open(manifest)]
    with open(manifest, "w") as f:
        for row in rows:
            row["sharded"] = True
            f.write(json.dumps(row) + "\n")

    fresh = tfs.compile_program(fn, frame)
    c0 = _hist_count("tftpu_executor_compile_seconds")
    report = warmup(None, fresh, manifest=manifest)
    assert _hist_count("tftpu_executor_compile_seconds") == c0
    assert report.entries and all(
        e["status"] == "skipped" and "sharded" in e["detail"]
        for e in report.entries
    )


def test_warmup_manifest_true_without_store_raises():
    tfs.configure(compilation_cache_dir="")
    frame = tfs.frame_from_arrays({"x": np.arange(4.0)})
    program = tfs.compile_program(lambda x: {"y": x}, frame)
    with pytest.raises(ValueError, match="persistent store"):
        warmup(None, program, manifest=True)
    with pytest.raises(ValueError, match="does not exist"):
        warmup(None, program, manifest="/nonexistent/manifest.jsonl")


def test_warmup_without_frame_needs_rows():
    frame = tfs.frame_from_arrays({"x": np.arange(4.0)})
    program = tfs.compile_program(lambda x: {"y": x}, frame)
    with pytest.raises(ValueError, match="rows"):
        warmup(None, program)


def test_partitioner_row_counts():
    assert partitioner_row_counts(21, 4) == [5, 6]
    assert partitioner_row_counts(20, 4) == [5]
    assert partitioner_row_counts(3, 8) == [1]


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------

def test_fingerprint_stable_across_rebuilds_and_distinct_by_shape():
    frame = tfs.frame_from_arrays({"x": np.arange(8.0)})
    p = tfs.compile_program(lambda x: {"y": x * 5.0}, frame)
    a = program_fingerprint(p, probe=8)
    b = program_fingerprint(p, probe=8)
    assert a == b
    assert program_fingerprint(p, probe=16) != a  # shape in the key
    p2 = tfs.compile_program(lambda x: {"y": x * 6.0}, frame)
    assert program_fingerprint(p2, probe=8) != a  # content in the key


def test_fingerprint_donate_and_kind_in_key():
    frame = tfs.frame_from_arrays({"x": np.arange(8.0)})
    p = tfs.compile_program(lambda x: {"y": x * 5.0}, frame)
    base = program_fingerprint(p, probe=8)
    assert program_fingerprint(p, probe=8, donate=True) != base
    assert program_fingerprint(p, probe=8, kind="vmap") != base


def test_fingerprint_kernel_selection_in_key():
    """ISSUE 12 key-axis regression: the straggler-kernel selection
    state lives in the env component, so a ``disable_pallas()`` flip,
    ``TFTPU_PALLAS=0``, or the force hook can never serve a stale
    executable from the store — and restoring the state restores the
    key (warmed entries stay warm across a no-op round trip)."""
    from tensorframes_tpu import configure
    from tensorframes_tpu.ops import segment

    frame = tfs.frame_from_arrays({"x": np.arange(8.0)})
    p = tfs.compile_program(lambda x: {"y": x * 3.0}, frame)
    base = program_fingerprint(p, probe=8)

    was = segment._pallas_disabled
    try:
        segment.disable_pallas("fingerprint key test")
        tripped = program_fingerprint(p, probe=8)
    finally:
        segment._pallas_disabled = was
    assert tripped != base  # the kill-switch is a key axis

    configure(pallas_kernels=False)
    try:
        off = program_fingerprint(p, probe=8)
    finally:
        configure(pallas_kernels=True)
    assert off != base
    # both spell 'kernels disabled' — one executable family serves them
    assert off == tripped

    configure(pallas_force=True)
    try:
        forced = program_fingerprint(p, probe=8)
    finally:
        configure(pallas_force=False)
    assert forced not in (base, off)

    # round trip: restored state keys identically (no gratuitous miss)
    assert program_fingerprint(p, probe=8) == base


# ---------------------------------------------------------------------------
# topology-fingerprinted keys (ISSUE 10 tentpole)
# ---------------------------------------------------------------------------

def _mesh_or_skip(axes=None):
    from tensorframes_tpu.parallel import device_count, make_mesh

    if device_count() < 8:
        pytest.skip("needs 8 (virtual) devices")
    return make_mesh(axes)


def test_fingerprint_sharding_axes_in_key():
    """Per-input shardings key separate executables: an AOT executable
    is layout-specialized, so mesh axis names, mesh shape, and the
    per-dim partition spec must all invalidate — while the TRIVIAL
    placement (host feeds, default device) keys exactly like no
    sharding at all (warmed host shapes must match however data
    arrives)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh_or_skip()
    frame = tfs.frame_from_arrays({"x": np.arange(64.0)})
    p = tfs.compile_program(lambda x: {"y": x * 5.0}, frame)
    base = program_fingerprint(p, probe=64)
    sharded = program_fingerprint(
        p, probe=64, shardings={"x": NamedSharding(mesh, P("dp"))}
    )
    assert sharded != base  # layout in the key
    # replicated-over-the-mesh is a different layout than dp-sharded
    repl = program_fingerprint(
        p, probe=64, shardings={"x": NamedSharding(mesh, P())}
    )
    assert repl not in (base, sharded)
    # axis NAMES are identity: same shape, renamed axis → different key
    mesh2 = _mesh_or_skip({"data": 8})
    renamed = program_fingerprint(
        p, probe=64, shardings={"x": NamedSharding(mesh2, P("data"))}
    )
    assert renamed not in (base, sharded, repl)
    # mesh SHAPE is identity: dp=2 x tp=4 keys differently from dp=8
    mesh3 = _mesh_or_skip({"dp": 2, "tp": 4})
    reshaped = program_fingerprint(
        p, probe=64, shardings={"x": NamedSharding(mesh3, P("dp"))}
    )
    assert reshaped not in (base, sharded, repl, renamed)
    # an explicit None / trivial sharding is the SAME key as no map
    assert program_fingerprint(p, probe=64, shardings={}) == base
    assert program_fingerprint(p, probe=64, shardings={"x": None}) == base


def test_fingerprint_process_topology_in_key(monkeypatch):
    """The fleet topology (device→process map) is in the env component:
    a resized fleet must miss cleanly instead of loading an executable
    compiled for the wrong collective schedule — while the key is
    process-INDEX-independent (every rank computes the same key, so one
    rank's published executable is every peer's hit)."""
    from tensorframes_tpu.compilecache import fingerprint as fp_mod
    from tensorframes_tpu.parallel import process_topology

    frame = tfs.frame_from_arrays({"x": np.arange(8.0)})
    p = tfs.compile_program(lambda x: {"y": x + 2.0}, frame)
    base = program_fingerprint(p, probe=8)
    real = process_topology()
    assert real["n_processes"] == 1  # single-process test env

    resized = dict(real, n_processes=4)
    monkeypatch.setattr(
        fp_mod, "_env_parts",
        _patched_env_parts(fp_mod._env_parts, resized),
    )
    assert program_fingerprint(p, probe=8) != base  # resize → clean miss


def _patched_env_parts(orig, topology):
    def env_parts(kind, donate, hoisted):
        parts = orig(kind, donate, hoisted)
        parts["topology"] = topology
        return parts

    return env_parts


def test_sharded_dispatch_roundtrip_bit_identical(store_dir):
    """A sharded frame's dispatch publishes its executable; a FRESH
    program instance over the same computation loads it from disk (hit
    counter, zero compile delta) and the cached result is bit-identical
    to cache-off dispatch."""
    _mesh_or_skip()

    def build():
        df = tfs.frame_from_arrays(
            {"x": np.arange(128.0, dtype=np.float32)}
        ).to_device()
        assert df.is_sharded
        return df, tfs.compile_program(
            lambda x: {"y": x * 1.5 + x.sum()}, df
        )

    # reference: cache OFF
    tfs.configure(compilation_cache_dir="")
    df, p = build()
    want = np.asarray(tfs.map_blocks(p, df).column_values("y"))

    tfs.configure(compilation_cache_dir=store_dir)
    df, p = build()
    c0 = _hist_count("tftpu_executor_compile_seconds")
    got_cold = np.asarray(tfs.map_blocks(p, df).column_values("y"))
    assert _hist_count("tftpu_executor_compile_seconds") > c0  # published
    assert _entries(store_dir)  # the sharded executable is durable

    df, p = build()  # fresh Program: its in-memory jit cache is empty
    h0 = _counter_val("tftpu_compilecache_hits_total")
    c1 = _hist_count("tftpu_executor_compile_seconds")
    got_warm = np.asarray(tfs.map_blocks(p, df).column_values("y"))
    assert _counter_val("tftpu_compilecache_hits_total") > h0
    assert _hist_count("tftpu_executor_compile_seconds") == c1  # ZERO
    np.testing.assert_array_equal(got_warm, got_cold)
    np.testing.assert_array_equal(got_warm, want)


def test_warm_sharded_key_makes_first_dispatch_a_hit(store_dir):
    """warm() with sharding-annotated abstract feeds precompiles the
    SHARDED placement's key: the first real sharded dispatch is a
    jit-cache hit with zero compile (the multi-process refusal is gone
    — every dispatch rides the unified AOT path the warm targets)."""
    import jax

    _mesh_or_skip()
    df = tfs.frame_from_arrays(
        {"x": np.arange(128.0, dtype=np.float32)}
    ).to_device()
    p = tfs.compile_program(lambda x: {"y": x - 2.0}, df)
    col = df.blocks()[0]["x"]
    abstract = {
        "x": jax.ShapeDtypeStruct(col.shape, col.dtype,
                                  sharding=col.sharding),
    }
    status = p.compiled().warm("block", abstract)
    assert status in ("compiled", "disk")
    h0 = _counter_val("tftpu_executor_jit_cache_hits_total")
    c0 = _hist_count("tftpu_executor_compile_seconds")
    out = tfs.map_blocks(p, df).column_values("y")
    np.testing.assert_array_equal(
        np.asarray(out), np.arange(128.0, dtype=np.float32) - 2.0
    )
    assert _counter_val("tftpu_executor_jit_cache_hits_total") > h0
    assert _hist_count("tftpu_executor_compile_seconds") == c0


def test_aot_jit_sharded_store_roundtrip(store_dir):
    """aot_jit (the unified pipeline for arbitrary pytree functions —
    what the MULTICHIP train steps dispatch through) publishes sharded
    executables a fresh instance loads from disk, bit-identically."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tensorframes_tpu.ops.executor import aot_jit

    mesh = _mesh_or_skip()
    sh = NamedSharding(mesh, P("dp"))
    x = jax.device_put(np.arange(64.0, dtype=np.float32), sh)

    def f(a):
        return a * 2.0 + a.sum()

    c0 = _hist_count("tftpu_executor_compile_seconds")
    cold = np.asarray(aot_jit(f, label="t")(x))
    assert _hist_count("tftpu_executor_compile_seconds") == c0 + 1

    h0 = _counter_val("tftpu_compilecache_hits_total")
    warm = np.asarray(aot_jit(f, label="t")(x))  # fresh instance
    assert _counter_val("tftpu_compilecache_hits_total") > h0
    assert _hist_count("tftpu_executor_compile_seconds") == c0 + 1
    np.testing.assert_array_equal(cold, warm)


def test_aot_jit_weak_type_keys_apart_and_promotes_like_jit():
    """A weak-typed 0-d array leaf (jnp.asarray(python_scalar)) must
    trace with weak_type preserved — dropping it promotes int8 + weak
    int to the weak leaf's dtype, a result the wrapped jax.jit never
    produces — and must not share an executable with a strong-typed
    leaf of the same dtype."""
    import jax
    import jax.numpy as jnp

    from tensorframes_tpu.ops.executor import aot_jit

    xi = jnp.ones((3,), jnp.int8)
    weak = jnp.asarray(1)
    strong = jnp.array(1, weak.dtype)
    assert weak.weak_type and not strong.weak_type

    f = aot_jit(lambda a, b: a + b, label="weak")
    ref = jax.jit(lambda a, b: a + b)
    assert f(xi, weak).dtype == ref(xi, weak).dtype == jnp.int8
    assert f(xi, strong).dtype == ref(xi, strong).dtype == weak.dtype
    # both variants rode the AOT path under DISTINCT keys — neither
    # fell back nor reused the other's strongly-typed executable
    assert len(f._builds.built) == 2 and not f._builds.failed


# ---------------------------------------------------------------------------
# accounting split (ISSUE 5 satellite)
# ---------------------------------------------------------------------------

def test_compile_vs_first_run_split():
    """With the cache off, a fresh shape observes compile-seconds AND
    first-run-seconds exactly once each; a repeat dispatch observes
    neither."""
    tfs.configure(compilation_cache_dir="")
    frame = tfs.frame_from_arrays({"x": np.arange(8.0)}, num_blocks=1)
    program = tfs.compile_program(lambda x: {"y": x / 4.0}, frame)
    c0 = _hist_count("tftpu_executor_compile_seconds")
    r0 = _hist_count("tftpu_executor_first_run_seconds")
    tfs.map_blocks(program, frame).blocks()
    assert _hist_count("tftpu_executor_compile_seconds") == c0 + 1
    assert _hist_count("tftpu_executor_first_run_seconds") == r0 + 1
    tfs.map_blocks(program, frame).blocks()
    assert _hist_count("tftpu_executor_compile_seconds") == c0 + 1
    assert _hist_count("tftpu_executor_first_run_seconds") == r0 + 1


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_stats_verify_prune(store_dir, capsys):
    from tensorframes_tpu.compilecache.cli import main

    frame = tfs.frame_from_arrays({"x": np.arange(8.0)})
    tfs.map_blocks(
        tfs.compile_program(lambda x: {"y": x * 9.0}, frame), frame
    ).blocks()
    assert _entries(store_dir)

    assert main(["--store", store_dir, "stats", "--json"]) == 0
    stats = json.loads(capsys.readouterr().out)
    assert stats["entries"] >= 1 and stats["bytes"] > 0

    assert main(["--store", store_dir, "verify", "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["ok"] and report["good"] >= 1

    # corrupt → verify fails → verify --delete-bad heals
    path = os.path.join(store_dir, "aot", _entries(store_dir)[0])
    with open(path, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        last = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([last[0] ^ 0xFF]))
    assert main(["--store", store_dir, "verify", "--json"]) == 1
    capsys.readouterr()
    assert main(["--store", store_dir, "verify", "--json",
                 "--delete-bad"]) == 1
    capsys.readouterr()
    assert main(["--store", store_dir, "verify", "--json"]) == 0
    capsys.readouterr()

    assert main(["--store", store_dir, "prune", "--clear"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["entries"] == 0
    assert not _entries(store_dir)


def test_cli_warm_bundle(store_dir, tmp_path, capsys):
    from tensorframes_tpu.compilecache.cli import main
    from tensorframes_tpu.program import save_program

    frame = tfs.frame_from_arrays({"x": np.arange(8.0)})
    program = tfs.compile_program(lambda x: {"y": x + 2.5}, frame)
    bundle = str(tmp_path / "prog.pb")
    save_program(program, bundle)
    assert main(["--store", store_dir, "warm", bundle, "--rows", "8"]) == 0
    out = capsys.readouterr().out
    assert "compiled" in out or "disk" in out
    assert _entries(store_dir), "CLI warm must populate the store"
