"""Shape lattice tests (≙ the reference's Shape behaviors,
Shape.scala:16-109, exercised through ExtraOperationsSuite)."""

import numpy as np
import pytest

from tensorframes_tpu.shape import (
    Shape,
    Unknown,
    infer_physical_shape,
    shape_of_nested,
)


def test_basic_construction():
    s = Shape.of(2, 3)
    assert s.dims == (2, 3)
    assert s.rank == 2
    assert not s.has_unknown
    assert Shape.empty().is_scalar
    assert Shape.unknown(2).dims == (Unknown, Unknown)


def test_from_any_none_is_unknown():
    # None → -1, the Python client convention (core.py:38-40)
    s = Shape.from_any([None, 3])
    assert s.dims == (Unknown, 3)


def test_prepend_tail_drop_inner():
    s = Shape.of(3, 4)
    assert s.prepend(10).dims == (10, 3, 4)
    assert s.prepend(None).dims == (Unknown, 3, 4)
    assert s.prepend(10).tail == s
    assert s.drop_inner().dims == (3,)
    with pytest.raises(ValueError):
        Shape.empty().tail


def test_num_elements():
    assert Shape.of(2, 3).num_elements == 6
    assert Shape.empty().num_elements == 1
    assert Shape.of(2, Unknown).num_elements is None


def test_precision_lattice():
    # ≙ Shape.checkMorePreciseThan (Shape.scala:54-59)
    assert Shape.of(2, 3).is_more_precise_than(Shape.of(Unknown, 3))
    assert Shape.of(2, 3).is_more_precise_than(Shape.of(2, 3))
    assert not Shape.of(Unknown, 3).is_more_precise_than(Shape.of(2, 3))
    assert not Shape.of(2).is_more_precise_than(Shape.of(2, 3))


def test_merge_to_unknown():
    # ≙ ExperimentalOperations.scala:168-178
    m = Shape.of(2, 3).merge(Shape.of(2, 5))
    assert m.dims == (2, Unknown)
    assert Shape.of(2).merge(Shape.of(2, 3)) is None
    assert Shape.of(2, 3).merge(Shape.of(2, 3)).dims == (2, 3)


def test_refine_hint_override():
    # hint dims win where known (TensorFlowOps.scala:126-133)
    s = Shape.of(Unknown, 3)
    assert s.refine(Shape.of(5, Unknown)).dims == (5, 3)
    assert s.refine(Shape.of(Unknown, 7)).dims == (Unknown, 7)


def test_infer_physical_shape():
    # ≙ DataOps.inferPhysicalShape (DataOps.scala:103-144)
    assert infer_physical_shape(12, Shape.of(Unknown, 3)).dims == (4, 3)
    assert infer_physical_shape(12, Shape.of(4, 3)).dims == (4, 3)
    with pytest.raises(ValueError):
        infer_physical_shape(13, Shape.of(Unknown, 3))
    with pytest.raises(ValueError):
        infer_physical_shape(12, Shape.of(Unknown, Unknown))
    with pytest.raises(ValueError):
        infer_physical_shape(10, Shape.of(5, 3))
    assert infer_physical_shape(0, Shape.of(Unknown, 0)).dims == (0, 0)


def test_shape_of_nested():
    assert shape_of_nested(1.0).dims == ()
    assert shape_of_nested([1.0, 2.0]).dims == (2,)
    assert shape_of_nested([[1, 2, 3], [4, 5, 6]]).dims == (2, 3)
    assert shape_of_nested(np.zeros((4, 5))).dims == (4, 5)


def test_str_rendering():
    assert str(Shape.of(Unknown, 2)) == "[?,2]"
