"""Whole-pipeline relational correctness — map→join→aggregate chains
checked against plain-numpy references.

Unlike tests/test_plan.py (which pins the fusion knob to compare the
fused and per-stage executions against each other), this suite honors
the AMBIENT ``TFTPU_FUSION`` configuration: under tier-1 it exercises
the plan-fused pipelines, and under the CI fusion-off smoke step the
very same assertions hold for the per-stage replay — the two runs
together are the end-to-end statement that fusion changes *when* work
happens, never *what* computes."""

import numpy as np
import pytest

import tensorframes_tpu as tfs


def _np_group_sum(keys, vals):
    return {k: vals[keys == k].sum(dtype=np.float64) for k in np.unique(keys)}


def test_map_join_aggregate_pipeline_matches_numpy():
    rng = np.random.default_rng(11)
    n, ng = 200, 8
    k = rng.integers(0, ng, n).astype(np.int32)
    x = (np.arange(n) % 16).astype(np.float32)
    left = tfs.frame_from_arrays({"k": k, "x": x}, num_blocks=3)
    right = tfs.frame_from_arrays(
        {"k": np.arange(ng, dtype=np.int32),
         "w": (np.arange(ng) * 3.0).astype(np.float32)},
    )
    f1 = tfs.map_blocks(lambda x: {"y": x * 2.0 + 1.0}, left)
    f2 = tfs.map_blocks(lambda y: {"z": y * y}, f1)
    j = f2.join(right, on="k")
    with tfs.with_graph():
        z_in = tfs.block(j, "z", tf_name="z_input")
        w_in = tfs.block(j, "w", tf_name="w_input")
        fz = tfs.reduce_sum(z_in, axis=0, name="z")
        fw = tfs.reduce_sum(w_in, axis=0, name="w")
        agg = tfs.aggregate([fz, fw], j.group_by("k"))
    rows = {r["k"]: r for r in agg.collect()}

    z = (x * 2.0 + 1.0) ** 2
    exp_z = _np_group_sum(k, z)
    counts = np.bincount(k, minlength=ng)
    assert set(rows) == set(int(g) for g in np.unique(k))
    for g, expected in exp_z.items():
        got = rows[int(g)]
        np.testing.assert_allclose(got["z"], expected, rtol=1e-6)
        np.testing.assert_allclose(
            got["w"], counts[g] * g * 3.0, rtol=1e-6
        )


@pytest.mark.parametrize("how,fill,exp_rows", [
    ("inner", None, 4),
    ("left", -1.0, 5),
    ("outer", -1.0, 6),
])
def test_join_after_map_matches_reference(how, fill, exp_rows):
    left = tfs.frame_from_arrays(
        {"k": np.array([0, 1, 2, 1, 5], np.int64),
         "x": np.arange(5, dtype=np.float32)},
        num_blocks=2,
    )
    right = tfs.frame_from_arrays(
        {"k": np.array([0, 1, 2, 7], np.int64),
         "w": np.array([10.0, 20.0, 30.0, 70.0], np.float32)},
    )
    f1 = tfs.map_blocks(lambda x: {"y": x + 0.5}, left)
    kw = {} if fill is None else {"fill_value": fill}
    out = f1.join(right, on="k", how=how, **kw)
    rows = out.collect()
    assert len(rows) == exp_rows
    for r in rows:
        if r["k"] in (0, 1, 2):  # matched rows carry both sides
            assert r["w"] == {0: 10.0, 1: 20.0, 2: 30.0}[r["k"]]
            assert r["y"] == r["x"] + 0.5
        elif r["k"] == 5:  # unmatched left
            assert r["w"] == -1.0
        elif r["k"] == 7:  # unmatched right (outer only)
            assert r["x"] == -1.0 and r["y"] == -1.0


def test_reduce_after_map_chain_matches_numpy():
    x = np.arange(101, dtype=np.float64)
    fr = tfs.frame_from_arrays({"x": x}, num_blocks=4)
    f1 = tfs.map_blocks(lambda x: {"y": x * 3.0}, fr)
    f2 = f1.map_rows(lambda y: {"z": y + 1.0})
    total = tfs.reduce_blocks(
        lambda z_input: {"z": z_input.sum(axis=0)}, f2
    )
    np.testing.assert_allclose(float(total), (x * 3.0 + 1.0).sum())
    pair = tfs.reduce_rows(lambda z_1, z_2: {"z": z_1 + z_2}, f2)
    np.testing.assert_allclose(float(pair), (x * 3.0 + 1.0).sum())


def test_string_key_aggregate_after_map_matches_numpy():
    rows = [
        {"k": f"grp{i % 3}", "v": float(i)} for i in range(30)
    ]
    fr = tfs.frame_from_rows(rows, num_blocks=2)
    f1 = tfs.map_blocks(lambda v: {"y": v * 2.0}, fr)
    with tfs.with_graph():
        y_in = tfs.block(f1, "y", tf_name="y_input")
        agg = tfs.aggregate(
            tfs.reduce_sum(y_in, axis=0, name="y"), f1.group_by("k")
        )
    got = {r["k"]: r["y"] for r in agg.collect()}
    v = np.arange(30, dtype=np.float64) * 2.0
    for g in range(3):
        np.testing.assert_allclose(
            got[f"grp{g}"], v[np.arange(30) % 3 == g].sum(), rtol=1e-6
        )


def test_filter_then_aggregate_pipeline():
    fr = tfs.frame_from_arrays(
        {"k": (np.arange(40) % 4).astype(np.int64),
         "x": np.arange(40, dtype=np.float32)},
        num_blocks=3,
    )
    f1 = tfs.map_blocks(lambda x: {"y": x * 2.0}, fr)
    f2 = f1.filter(lambda y: {"keep": y >= 20.0})
    with tfs.with_graph():
        y_in = tfs.block(f2, "y", tf_name="y_input")
        agg = tfs.aggregate(
            tfs.reduce_sum(y_in, axis=0, name="y"), f2.group_by("k")
        )
    got = {r["k"]: r["y"] for r in agg.collect()}
    x = np.arange(40, dtype=np.float64)
    y = x * 2.0
    mask = y >= 20.0
    for g in range(4):
        np.testing.assert_allclose(
            got[g], y[mask & (np.arange(40) % 4 == g)].sum(), rtol=1e-6
        )
