"""Pipeline-parallelism tests: the ppermute/scan schedule reproduces the
sequential composition of stages, and a full pp training step runs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorframes_tpu.parallel import make_mesh, make_pp_train_step, pipeline_apply


def _stage_fn(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def _stack_params(n_stages, width, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(
            rng.standard_normal((n_stages, width, width)) / np.sqrt(width),
            jnp.float32,
        ),
        "b": jnp.asarray(rng.standard_normal((n_stages, width)) * 0.1, jnp.float32),
    }


def _sequential(params, x, n_stages):
    h = x
    for s in range(n_stages):
        h = _stage_fn(jax.tree_util.tree_map(lambda a: a[s], params), h)
    return h


@pytest.mark.parametrize("n_micro", [None, 8])
def test_pipeline_matches_sequential(n_micro):
    n_stages, width = 4, 8
    mesh = make_mesh({"pp": n_stages, "dp": 2})
    params = _stack_params(n_stages, width)
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((16, width)), jnp.float32
    )
    out = pipeline_apply(
        _stage_fn, params, x, mesh, axis="pp", num_microbatches=n_micro
    )
    ref = _sequential(params, x, n_stages)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_pipeline_with_dp_axis():
    # pp composes with a dp axis on the same mesh
    n_stages, width = 2, 8
    mesh = make_mesh({"pp": n_stages, "dp": 4})
    params = _stack_params(n_stages, width, seed=2)
    x = jnp.asarray(
        np.random.default_rng(2).standard_normal((8, width)), jnp.float32
    )
    out = pipeline_apply(_stage_fn, params, x, mesh, axis="pp")
    ref = _sequential(params, x, n_stages)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_batch_divisibility_error():
    mesh = make_mesh({"pp": 4, "dp": 2})
    params = _stack_params(4, 8)
    x = jnp.zeros((10, 8), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(_stage_fn, params, x, mesh, num_microbatches=4)


def test_pp_train_step_learns():
    import optax

    n_stages, width = 4, 8
    mesh = make_mesh({"pp": n_stages, "dp": 2})
    params = _stack_params(n_stages, width, seed=3)

    def loss_head(out, targets):
        return jnp.mean((out - targets) ** 2)

    tx = optax.adam(5e-3)
    jit_for = make_pp_train_step(_stage_fn, loss_head, mesh, tx, axis="pp")
    step, init_opt, sh = jit_for(params)
    params = jax.device_put(params, sh)
    opt_state = init_opt(params)
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((16, width)), jnp.float32)
    t = jnp.asarray(rng.standard_normal((16, width)), jnp.float32)
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, x, t)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_stage_param_dim_mismatch_raises():
    mesh = make_mesh({"pp": 4, "dp": 2})
    params = _stack_params(8, 8)  # 8 stage slices on a pp=4 mesh
    x = jnp.zeros((16, 8), jnp.float32)
    with pytest.raises(ValueError, match="num_stages"):
        pipeline_apply(_stage_fn, params, x, mesh)
