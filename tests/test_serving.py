"""Serving-layer tests (ISSUE 9): the continuous batcher's contracts.

The four guarantees under test, stated in serving/__init__.py:
bit-identity of coalesced vs solo dispatch, zero steady-state compiles
on a warmed server under any admissible request-size mix, bounded
admission (counted rejections, deadline expiry — never a hang), and
graceful drain on shutdown. Plus the HTTP adapter's status taxonomy and
the round-5 frame.py/quantize satellites' regressions.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.serving import (
    DeadlineExceededError,
    RejectedError,
    Server,
    ServingConfig,
    ServingError,
    serve_http,
)
from tensorframes_tpu.serving import metrics as sm
from tensorframes_tpu.serving.batcher import ContinuousBatcher
from tensorframes_tpu.validation import ValidationError

WIDTH = 4


def _schema(width=WIDTH):
    return tfs.Schema([
        tfs.ColumnInfo(
            "x", tfs.dtypes.float32, tfs.Shape((tfs.Unknown, width))
        )
    ])


def _program(width=WIDTH):
    holder = type("F", (), {"schema": _schema(width)})()
    return tfs.compile_program(
        lambda x: {"y": x * 2.0 + 1.0}, holder, block=False
    )


def _req(rows, seed, width=WIDTH):
    rng = np.random.default_rng(seed)
    return {"x": rng.standard_normal((rows, width)).astype(np.float32)}


@pytest.fixture
def server():
    srv = Server(ServingConfig(
        max_batch_rows=16, max_latency_s=0.002, max_queue_rows=256,
    ))
    srv.register("double", _program())
    srv.start()
    yield srv
    srv.stop(drain=True, timeout=10)


# ---------------------------------------------------------------------------
# coalescing correctness: bit-identity with solo dispatch
# ---------------------------------------------------------------------------

def test_coalesced_results_bit_identical_to_solo_dispatch(server):
    reqs = [_req(1 + (i % 5), seed=i) for i in range(24)]
    flushes0 = sum(c.value for c in sm.FLUSHES.values())
    futs = [server.submit("double", r) for r in reqs]
    outs = [f.result(10) for f in futs]
    flushes = sum(c.value for c in sm.FLUSHES.values()) - flushes0
    # coalescing actually happened: fewer flushes than requests
    assert flushes < len(reqs)
    # solo reference through a FRESH program (its own executable cache),
    # dispatched one request at a time through the same bucketed entry
    solo = _program().compiled()
    for r, out in zip(reqs, outs):
        want = solo.run_rows_bucketed(dict(r))
        assert out["y"].shape == (r["x"].shape[0], WIDTH)
        np.testing.assert_array_equal(out["y"], want["y"])  # BIT-equal


def test_single_row_convenience_and_ordering(server):
    # a bare cell is one row; results scatter back per request, in order
    futs = [
        server.submit("double", {"x": np.full((WIDTH,), float(i),
                                              np.float32)})
        for i in range(10)
    ]
    for i, f in enumerate(futs):
        got = f.result(10)["y"]
        np.testing.assert_array_equal(
            got, np.full((1, WIDTH), 2.0 * i + 1.0, np.float32)
        )


# ---------------------------------------------------------------------------
# zero steady-state compiles (the warmed bucket-ladder contract)
# ---------------------------------------------------------------------------

def test_zero_steady_state_compiles_under_mixed_sizes():
    from tensorframes_tpu.ops.executor import _JIT_MISSES

    srv = Server(ServingConfig(
        max_batch_rows=32, max_latency_s=0.001, max_queue_rows=512,
    ))
    srv.register("double", _program())
    srv.start()  # warmup precompiles the whole ladder
    try:
        m0 = _JIT_MISSES.value
        for round_ in range(6):
            futs = [
                srv.submit("double", _req(rows, seed=round_ * 100 + rows))
                for rows in (1, 2, 3, 5, 8, 13, 21, 32)
            ]
            for f in futs:
                f.result(10)
        assert _JIT_MISSES.value - m0 == 0, (
            "a warmed server must never compile in steady state — some "
            "flush missed the warmed bucket ladder"
        )
    finally:
        srv.stop(drain=True, timeout=10)


def test_serving_row_buckets_match_executor_policy():
    from tensorframes_tpu.compilecache import serving_row_buckets
    from tensorframes_tpu.ops.executor import bucket_rows, bucket_table

    buckets = serving_row_buckets(100)
    # every admissible flush size pads into a warmed bucket
    for n in range(1, 101):
        assert bucket_rows(n) in buckets
    # nothing beyond the cap is warmed
    assert max(buckets) == bucket_rows(100)
    assert buckets == sorted(set(buckets))
    assert set(buckets) <= set(bucket_table()) | {bucket_rows(100)}
    with pytest.raises(ValueError):
        serving_row_buckets(0)


def test_max_batch_rows_beyond_bucket_ladder_rejected():
    # beyond the ladder bucket_rows dispatches EXACT shapes no warmup
    # can cover — the zero-steady-state-compile contract cannot hold,
    # so both the warmer and the Server refuse the config up front
    from tensorframes_tpu.compilecache import serving_row_buckets
    from tensorframes_tpu.ops.executor import bucket_table

    top = bucket_table()[-1]
    with pytest.raises(ValueError, match="ladder"):
        serving_row_buckets(top * 2)
    with pytest.raises(ValueError, match="max_batch_rows"):
        Server(ServingConfig(max_batch_rows=top * 2, warmup=False))


def test_not_running_until_warmup_finishes(monkeypatch):
    # healthz must never say running=true while submits would shed as
    # 'closed': during start()'s warmup the server reports
    # running=False, and flips only once the batchers are open
    seen = {}
    orig = Server._warm

    def observing_warm(self, ep):
        seen["running_during_warm"] = self.running
        return orig(self, ep)

    monkeypatch.setattr(Server, "_warm", observing_warm)
    srv = Server(ServingConfig(max_batch_rows=16, max_latency_s=0.001))
    srv.register("double", _program())
    srv.start()
    try:
        assert seen["running_during_warm"] is False
        assert srv.running is True
        out = srv.call("double", _req(2, seed=0), timeout=10)
        assert out["y"].shape == (2, WIDTH)
    finally:
        srv.stop(drain=True, timeout=10)


def test_register_during_start_warmup_still_warms(monkeypatch):
    # a register() racing start()'s warm loop must warm its own
    # endpoint: start() snapshotted the endpoint list before warming,
    # but its final loop starts EVERY batcher — an unwarmed one would
    # silently break the zero-steady-state-compile contract
    gate = threading.Event()
    mid_warm = threading.Event()
    warmed = []
    orig = Server._warm

    def gated_warm(self, ep):
        warmed.append(ep.name)
        if ep.name == "double":
            mid_warm.set()
            assert gate.wait(10)
        return orig(self, ep)

    monkeypatch.setattr(Server, "_warm", gated_warm)
    srv = Server(ServingConfig(max_batch_rows=16, max_latency_s=0.001))
    srv.register("double", _program())
    t = threading.Thread(target=srv.start)
    t.start()
    try:
        assert mid_warm.wait(10)  # start() is mid-warm on 'double'
        srv.register("late", _program())  # the racing registration
        gate.set()
        t.join(30)
        assert srv.running
        assert set(warmed) == {"double", "late"}
        out = srv.call("late", _req(2, seed=0), timeout=10)
        assert out["y"].shape == (2, WIDTH)
    finally:
        gate.set()
        srv.stop(drain=True, timeout=10)


def test_stop_during_start_warmup_wins(monkeypatch):
    # a stop() that lands while start() is mid-warmup must win: start()
    # finishing later may not open the batchers and flip running=True,
    # or the process would believe it shut down while admission is open
    gate = threading.Event()
    mid_warm = threading.Event()
    orig = Server._warm

    def gated_warm(self, ep):
        mid_warm.set()
        assert gate.wait(10)
        return orig(self, ep)

    monkeypatch.setattr(Server, "_warm", gated_warm)
    srv = Server(ServingConfig(max_batch_rows=16, max_latency_s=0.001))
    srv.register("double", _program())
    t = threading.Thread(target=srv.start)
    t.start()
    try:
        assert mid_warm.wait(10)       # start() is inside the warm loop
        srv.stop(drain=True, timeout=5)  # shutdown during warmup
        gate.set()
        t.join(30)
        assert srv.running is False
        with pytest.raises(RejectedError) as ei:
            srv.submit("double", _req(1, seed=0))
        assert ei.value.reason == "closed"
    finally:
        gate.set()
        srv.stop(drain=False)


def test_failed_live_register_leaves_no_zombie(monkeypatch):
    # a live register() whose warmup raises must roll the endpoint back
    # out: otherwise its batcher never starts (every submit sheds as
    # 'closed') and the name can never be re-registered with a fixed
    # program
    srv = Server(ServingConfig(max_batch_rows=16, max_latency_s=0.001))
    srv.register("double", _program())
    srv.start()
    orig = Server._warm

    def failing_warm(self, ep):
        if ep.name == "broken":
            raise RuntimeError("ladder bucket failed to compile")
        return orig(self, ep)

    monkeypatch.setattr(Server, "_warm", failing_warm)
    try:
        with pytest.raises(RuntimeError, match="failed to compile"):
            srv.register("broken", _program())
        assert "broken" not in srv.endpoints()
        with pytest.raises(ValidationError, match="unknown endpoint"):
            srv.submit("broken", _req(1, seed=0))
        # the name is free again: a fixed registration serves normally
        monkeypatch.setattr(Server, "_warm", orig)
        srv.register("broken", _program())
        out = srv.call("broken", _req(2, seed=0), timeout=10)
        assert out["y"].shape == (2, WIDTH)
    finally:
        srv.stop(drain=True, timeout=10)


def test_failed_register_during_start_stops_started_batcher(monkeypatch):
    # the nastier interleaving: register('broken') lands while start()
    # is mid-warmup, so start()'s final loop starts broken's batcher —
    # THEN broken's own warm fails. The rollback must stop that batcher,
    # or its worker/expirer threads outlive the rollback serving a queue
    # no endpoint will ever drain
    gate = threading.Event()
    mid_warm = threading.Event()
    orig = Server._warm

    def scripted_warm(self, ep):
        if ep.name == "double":
            mid_warm.set()
            assert gate.wait(10)
            return orig(self, ep)
        # broken: let start() finish (its final loop starts every
        # registered batcher, including broken's) before failing
        gate.set()
        deadline = time.monotonic() + 10
        while not self.running and time.monotonic() < deadline:
            time.sleep(0.01)
        assert self.running
        raise RuntimeError("bucket compile failed")

    monkeypatch.setattr(Server, "_warm", scripted_warm)
    srv = Server(ServingConfig(max_batch_rows=16, max_latency_s=0.001))
    srv.register("double", _program())
    t = threading.Thread(target=srv.start)
    t.start()
    try:
        assert mid_warm.wait(10)
        with pytest.raises(RuntimeError, match="bucket compile failed"):
            srv.register("broken", _program())
        t.join(30)
        assert srv.running
        assert "broken" not in srv.endpoints()
        # the started-then-rolled-back batcher's threads must be gone
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            alive = [th.name for th in threading.enumerate()
                     if th.name.startswith("tfs-serving-broken")]
            if not alive:
                break
            time.sleep(0.05)
        assert alive == []
        # 'double' is untouched by the rollback
        out = srv.call("double", _req(2, seed=0), timeout=10)
        assert out["y"].shape == (2, WIDTH)
    finally:
        gate.set()
        srv.stop(drain=True, timeout=10)


def test_register_rejects_unknown_cell_dims():
    # a non-lead Unknown cell dim breaks both serving contracts: mixed
    # concrete extents poison each other's flush concatenate, and even
    # homogeneous flushes dispatch at shapes no warmup ladder covers
    schema = tfs.Schema([
        tfs.ColumnInfo("x", tfs.dtypes.float32,
                       tfs.Shape((tfs.Unknown, tfs.Unknown)))
    ])
    holder = type("F", (), {"schema": schema})()
    prog = tfs.compile_program(
        lambda x: {"y": x * 2.0}, holder, block=False
    )
    srv = Server(ServingConfig(warmup=False))
    with pytest.raises(ValueError, match="Unknown dim"):
        srv.register("ragged", prog)


def test_padding_rows_metric_counts_ladder_roundup():
    from tensorframes_tpu.ops.executor import bucket_rows

    srv = Server(ServingConfig(
        max_batch_rows=16, max_latency_s=0.0, max_queue_rows=64,
        warmup=False,
    ))
    srv.register("double", _program())
    srv.start()
    try:
        p0 = sm.PADDING_ROWS.value
        srv.call("double", _req(3, seed=0), timeout=10)
        assert sm.PADDING_ROWS.value - p0 == bucket_rows(3) - 3
    finally:
        srv.stop(drain=True, timeout=10)


# ---------------------------------------------------------------------------
# deadlines (RetryPolicy.deadline_s semantics) and admission bounds
# ---------------------------------------------------------------------------

def test_deadline_expiry_fails_queued_request():
    # flush timer far beyond the deadline: the request must expire IN
    # QUEUE, at its deadline (not at the timer), with the counted error
    srv = Server(ServingConfig(
        max_batch_rows=64, max_latency_s=30.0, max_queue_rows=256,
        warmup=False,
    ))
    srv.register("double", _program())
    srv.start()
    try:
        d0 = sm.DEADLINE_EXPIRED.value
        t0 = time.perf_counter()
        fut = srv.submit("double", _req(2, seed=0), deadline_s=0.05)
        with pytest.raises(DeadlineExceededError):
            fut.result(10)
        waited = time.perf_counter() - t0
        assert waited < 5.0  # expired at the deadline, not the timer
        assert sm.DEADLINE_EXPIRED.value - d0 == 1
    finally:
        srv.stop(drain=False)


def test_deadline_validation():
    srv = Server(ServingConfig(warmup=False))
    srv.register("double", _program())
    srv.start()
    try:
        with pytest.raises(ValueError, match="deadline_s"):
            srv.submit("double", _req(1, seed=0), deadline_s=0.0)
    finally:
        srv.stop(drain=False)


def test_backpressure_rejects_instead_of_hanging():
    # a dispatch wedged on purpose: the queue fills behind it and the
    # next offer sheds with a counted rejection, instantly
    release = threading.Event()
    entered = threading.Event()

    def blocking_dispatch(feeds, rows):
        entered.set()
        assert release.wait(30)
        return {"y": np.asarray(feeds["x"]) * 2.0 + 1.0}

    b = ContinuousBatcher(
        "blocked", blocking_dispatch,
        max_batch_rows=4, max_latency_s=0.0, max_queue_rows=8,
    )
    b.start()
    try:
        first = b.offer(_req(1, seed=0), 1, None)
        assert entered.wait(10)  # the worker is now wedged in dispatch
        queued = [b.offer(_req(4, seed=i), 4, None) for i in (1, 2)]
        r0 = sm.rejected("queue_full").value
        t0 = time.perf_counter()
        with pytest.raises(RejectedError) as ei:
            b.offer(_req(1, seed=3), 1, None)
        assert time.perf_counter() - t0 < 1.0  # shed, not a hang
        assert ei.value.reason == "queue_full"
        assert sm.rejected("queue_full").value - r0 == 1
    finally:
        release.set()
        b.stop(drain=True, timeout=10)
    for f in [first] + queued:  # the wedge cleared; queued work completed
        assert f.result(10)["y"].shape[1] == WIDTH


def test_deadline_expires_while_dispatch_wedged():
    # the worker is blocked inside a slow flush; a queued request's
    # deadline must still expire promptly — clock-bounded, not
    # traffic-bounded — via the batcher's dedicated expirer thread
    release = threading.Event()
    entered = threading.Event()

    def blocking_dispatch(feeds, rows):
        entered.set()
        assert release.wait(30)
        return {"y": np.asarray(feeds["x"]) * 2.0 + 1.0}

    b = ContinuousBatcher(
        "wedged-deadline", blocking_dispatch,
        max_batch_rows=4, max_latency_s=0.0, max_queue_rows=64,
    )
    b.start()
    try:
        b.offer(_req(1, seed=0), 1, None)
        assert entered.wait(10)  # worker now wedged in dispatch
        t0 = time.perf_counter()
        fut = b.offer(_req(1, seed=1), 1, 0.05)
        with pytest.raises(DeadlineExceededError):
            fut.result(5)
        assert time.perf_counter() - t0 < 2.0  # expired MID-dispatch
        assert not release.is_set()  # the wedge never cleared
    finally:
        release.set()
        b.stop(drain=True, timeout=10)


def test_oversized_request_rejected(server):
    with pytest.raises(RejectedError) as ei:
        server.submit("double", _req(17, seed=0))  # max_batch_rows=16
    assert ei.value.reason == "too_large"


# ---------------------------------------------------------------------------
# lifecycle: drain-on-shutdown, closed admission
# ---------------------------------------------------------------------------

def test_drain_on_shutdown_completes_queued_work():
    srv = Server(ServingConfig(
        max_batch_rows=64, max_latency_s=30.0, max_queue_rows=256,
        warmup=False,
    ))
    srv.register("double", _program())
    srv.start()
    reqs = [_req(2, seed=i) for i in range(5)]
    futs = [srv.submit("double", r) for r in reqs]
    assert not any(f.done() for f in futs)  # timer is 30s: all queued
    srv.stop(drain=True, timeout=30)
    solo = _program().compiled()
    for r, f in zip(reqs, futs):
        np.testing.assert_array_equal(
            f.result(0)["y"], solo.run_rows_bucketed(dict(r))["y"]
        )
    c0 = sm.rejected("closed").value
    with pytest.raises(RejectedError) as ei:
        srv.submit("double", _req(1, seed=99))
    assert ei.value.reason == "closed"
    assert sm.rejected("closed").value - c0 == 1


def test_stop_without_drain_fails_pending_loudly():
    srv = Server(ServingConfig(
        max_batch_rows=64, max_latency_s=30.0, warmup=False,
    ))
    srv.register("double", _program())
    srv.start()
    fut = srv.submit("double", _req(1, seed=0))
    srv.stop(drain=False)
    with pytest.raises(ServingError):
        fut.result(5)


def test_context_manager_drains():
    with Server(ServingConfig(max_latency_s=0.001, warmup=False)) as srv:
        srv.register("double", _program())
        fut = srv.submit("double", _req(3, seed=1))
    assert fut.result(0)["y"].shape == (3, WIDTH)


# ---------------------------------------------------------------------------
# failure containment: a flush fault fails its batch, futures resolve
# ---------------------------------------------------------------------------

def test_injected_flush_fault_resolves_futures_with_the_error():
    from tensorframes_tpu.resilience import inject

    srv = Server(ServingConfig(
        max_batch_rows=8, max_latency_s=0.001, warmup=False,
    ))
    srv.register("double", _program())
    srv.start()
    try:
        e0 = sm.DISPATCH_ERRORS.value
        with inject("serving.flush", RuntimeError("chaos")):
            futs = [srv.submit("double", _req(1, seed=i)) for i in range(3)]
            errs = [f.exception(10) for f in futs]
        assert all(isinstance(e, RuntimeError) for e in errs)
        assert sm.DISPATCH_ERRORS.value - e0 >= 1
        # the server survives: post-fault requests succeed
        assert srv.call("double", _req(2, seed=9), timeout=10)["y"].shape \
            == (2, WIDTH)
    finally:
        srv.stop(drain=True, timeout=10)


def test_feed_validation_errors(server):
    with pytest.raises(ValidationError, match="unknown endpoint"):
        server.submit("nope", _req(1, seed=0))
    with pytest.raises(ValidationError, match="do not match"):
        server.submit("double", {"z": np.zeros((1, WIDTH), np.float32)})
    with pytest.raises(ValidationError, match="cell shape"):
        server.submit("double", {"x": np.zeros((1, WIDTH + 1),
                                               np.float32)})
    with pytest.raises(ValidationError, match="zero-row"):
        server.submit("double", {"x": np.zeros((0, WIDTH), np.float32)})
    with pytest.raises(ValidationError, match="non-empty"):
        server.submit("double", {})


def test_multi_input_lead_dim_mismatch():
    schema = tfs.Schema([
        tfs.ColumnInfo("a", tfs.dtypes.float32,
                       tfs.Shape((tfs.Unknown,))),
        tfs.ColumnInfo("b", tfs.dtypes.float32,
                       tfs.Shape((tfs.Unknown,))),
    ])
    holder = type("F", (), {"schema": schema})()
    prog = tfs.compile_program(
        lambda a, b: {"s": a + b}, holder, block=False
    )
    srv = Server(ServingConfig(max_latency_s=0.001, warmup=False))
    srv.register("add", prog)
    srv.start()
    try:
        with pytest.raises(ValidationError, match="share the lead dim"):
            srv.submit("add", {
                "a": np.zeros(2, np.float32), "b": np.zeros(3, np.float32),
            })
        got = srv.call("add", {
            "a": np.asarray([1.0, 2.0], np.float32),
            "b": np.asarray([10.0, 20.0], np.float32),
        }, timeout=10)
        np.testing.assert_array_equal(got["s"], [11.0, 22.0])
    finally:
        srv.stop(drain=True, timeout=10)


# ---------------------------------------------------------------------------
# HTTP adapter
# ---------------------------------------------------------------------------

def test_http_adapter_roundtrip_and_status_taxonomy(server):
    httpd = serve_http(server)
    port = httpd.server_address[1]
    base = f"http://127.0.0.1:{port}"
    try:
        req = urllib.request.Request(
            f"{base}/v1/double",
            data=json.dumps(
                {"inputs": {"x": [1.0, 2.0, 3.0, 4.0]}}
            ).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            body = json.load(r)
        assert body["rows"] == 1
        assert body["outputs"]["y"] == [[3.0, 5.0, 7.0, 9.0]]
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            health = json.load(r)
        assert health["running"] is True
        assert "double" in health["endpoints"]
        # 404: unknown endpoint; 400: malformed feeds
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/v1/nope",
                data=json.dumps({"inputs": {"x": [1.0]}}).encode(),
                method="POST",
            ), timeout=10)
        assert ei.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/v1/double",
                data=json.dumps({"inputs": {"x": [1.0, 2.0]}}).encode(),
                method="POST",
            ), timeout=10)
        assert ei.value.code == 400
        # a feed NAMED 'unknown endpoint' on a real endpoint is still a
        # 400 (the 404 branch keys on the exception type, not on a
        # message substring a client can plant)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/v1/double",
                data=json.dumps(
                    {"inputs": {"unknown endpoint": [1.0]}}
                ).encode(),
                method="POST",
            ), timeout=10)
        assert ei.value.code == 400
        # a syntactically-valid JSON body that is not an object is a
        # clean 400, not a dropped connection (req.get on a list used
        # to raise an uncaught AttributeError in the handler thread)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/v1/double",
                data=json.dumps([1.0, 2.0]).encode(),
                method="POST",
            ), timeout=10)
        assert ei.value.code == 400
        assert "must be a JSON object" in json.load(ei.value)["error"]
    finally:
        httpd.shutdown()


def test_http_deadline_maps_to_504(server):
    # a fresh non-started server would reject; instead use a deadline so
    # tiny against a long flush timer that expiry is deterministic
    srv = Server(ServingConfig(
        max_batch_rows=64, max_latency_s=30.0, warmup=False,
    ))
    srv.register("double", _program())
    srv.start()
    httpd = serve_http(srv)
    port = httpd.server_address[1]
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/double",
                data=json.dumps({
                    "inputs": {"x": [1.0, 2.0, 3.0, 4.0]},
                    "deadline_s": 0.05,
                }).encode(),
                method="POST",
            ), timeout=10)
        assert ei.value.code == 504
    finally:
        httpd.shutdown()
        srv.stop(drain=False)


def test_http_dispatch_valueerror_is_500_not_400():
    # a ValueError raised AT DISPATCH (surfacing through fut.result())
    # is a server fault and must take the 500 path — the 400 catch
    # exists only for submit()'s own argument errors. A 400 here would
    # tell clients/load balancers the request was malformed, so they
    # would never retry a transient server-side failure
    from tensorframes_tpu.resilience import inject

    srv = Server(ServingConfig(
        max_batch_rows=8, max_latency_s=0.001, warmup=False,
    ))
    srv.register("double", _program())
    srv.start()
    httpd = serve_http(srv)
    port = httpd.server_address[1]
    try:
        with inject("serving.flush", ValueError("bad operand")):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}/v1/double",
                    data=json.dumps(
                        {"inputs": {"x": [1.0, 2.0, 3.0, 4.0]}}
                    ).encode(),
                    method="POST",
                ), timeout=10)
        assert ei.value.code == 500
        assert "ValueError" in json.load(ei.value)["error"]
    finally:
        httpd.shutdown()
        srv.stop(drain=False)


# ---------------------------------------------------------------------------
# registration / lifecycle odds and ends
# ---------------------------------------------------------------------------

def test_register_validation():
    srv = Server(ServingConfig(warmup=False))
    srv.register("double", _program())
    with pytest.raises(ValueError, match="already registered"):
        srv.register("double", _program())
    with pytest.raises(ValueError, match="non-empty"):
        srv.register("a/b", _program())
    with pytest.raises(ValueError, match="frame_or_schema"):
        srv.register("fn", lambda x: {"y": x})


def test_register_fetches_against_schema():
    # map_rows-style callable fetches normalize against a schema, same
    # as the verbs; registering on a LIVE server warms + serves it
    srv = Server(ServingConfig(max_latency_s=0.001))
    srv.start()
    srv.register("sq", lambda x: {"y": x * x}, _schema())
    try:
        got = srv.call(
            "sq", {"x": np.full((2, WIDTH), 3.0, np.float32)}, timeout=10
        )
        np.testing.assert_array_equal(
            got["y"], np.full((2, WIDTH), 9.0, np.float32)
        )
    finally:
        srv.stop(drain=True, timeout=10)


def test_stats_are_per_server_not_process_global():
    # stats() is documented as the healthz body for THIS server: a fresh
    # server in the same process must report zero admissions even after
    # another instance served (and shed) traffic. The process-wide
    # tftpu_serving_* registry series are unaffected by this split.
    a = Server(ServingConfig(
        max_batch_rows=8, max_latency_s=0.001, warmup=False,
    ))
    a.register("double", _program())
    a.start()
    try:
        for i in range(3):
            a.call("double", _req(1, seed=i), timeout=10)
        with pytest.raises(RejectedError):
            a.submit("double", _req(64, seed=9))  # too_large, counted
        sa = a.stats()
        assert sa["admitted_requests"] == 3
        assert sa["admitted_rows"] == 3
        assert sa["rejected"]["too_large"] == 1
    finally:
        a.stop(drain=True, timeout=10)
    b = Server(ServingConfig(max_batch_rows=8, warmup=False))
    b.register("double", _program())
    sb = b.stats()
    assert sb["admitted_requests"] == 0
    assert sb["admitted_rows"] == 0
    assert sb["rejected"] == {r: 0 for r in sm.REJECT_REASONS}
    assert sb["deadline_expired"] == 0


def test_serving_metrics_preregistered():
    from tensorframes_tpu.observability.metrics import REGISTRY

    names = {m.name for m in REGISTRY.collect()}
    for want in (
        "tftpu_serving_requests_total",
        "tftpu_serving_rows_total",
        "tftpu_serving_rejected_total",
        "tftpu_serving_queue_depth_rows",
        "tftpu_serving_flushes_total",
        "tftpu_serving_batch_rows",
        "tftpu_serving_padding_rows_total",
        "tftpu_serving_request_latency_seconds",
        "tftpu_serving_queue_wait_seconds",
        "tftpu_serving_dispatch_seconds",
        "tftpu_serving_deadline_expired_total",
        "tftpu_serving_dispatch_errors_total",
    ):
        assert want in names, f"{want} not pre-registered"


# ---------------------------------------------------------------------------
# round-5 satellites: frame.py and quantize regressions
# ---------------------------------------------------------------------------

def test_join_right_validates_fill_before_swap():
    f1 = tfs.frame_from_arrays(
        {"k": np.array([1, 2, 3]), "a": np.array([1.0, 2.0, 3.0])}
    )
    f2 = tfs.frame_from_arrays(
        {"k": np.array([2, 3, 4]), "b": np.array([5.0, 6.0, 7.0])}
    )
    with pytest.raises(ValueError) as ei:
        f1.join(f2, on="k", how="right")
    assert "how='right'" in str(ei.value)  # not the swapped how='left'
    with pytest.raises(ValueError) as ei:
        f1.join(f2, on="k", how="right", fill_value={"b": 0.0})
    # names how='right' AND the LEFT frame's unfilled column
    assert "how='right'" in str(ei.value)
    assert "'a'" in str(ei.value)
    out = f1.join(f2, on="k", how="right", fill_value={"a": 0.0}).collect()
    assert [(r["k"], r["a"], r["b"]) for r in out] == [
        (2, 2.0, 5.0), (3, 3.0, 6.0), (4, 0.0, 7.0),
    ]


def test_sort_values_layout_tripwire_once(monkeypatch, caplog):
    import logging

    from tensorframes_tpu import frame as frame_mod

    monkeypatch.setattr(frame_mod, "_sort_layout_warned", False)
    with caplog.at_level(logging.WARNING, "tensorframes_tpu.frame"):
        frame_mod._warn_sort_layout_switch(100 << 20, 64 << 20)
        frame_mod._warn_sort_layout_switch(100 << 20, 64 << 20)
    hits = [
        r for r in caplog.records
        if "range-partitioned exchange" in r.getMessage()
    ]
    assert len(hits) == 1  # one-time tripwire
    assert "replicated" in hits[0].getMessage().lower()


def test_replicated_fleetwide_and_local_dedup_semantics():
    from tensorframes_tpu.frame import _replicated_fleetwide

    # single process: trivially replicated (no collective taken)
    assert _replicated_fleetwide({"k": np.array([1, 2, 1])})
    # single-process dedup unchanged: keep-first in global row order
    f = tfs.frame_from_arrays({
        "k": np.array([3, 1, 3, 2, 1]),
        "v": np.array([0.0, 1.0, 2.0, 3.0, 4.0]),
    }, num_blocks=2)
    got = [(r["k"], r["v"]) for r in f.drop_duplicates(subset="k").collect()]
    assert got == [(3, 0.0), (1, 1.0), (2, 3.0)]


def test_pallas_int8_eligibility_restricted_to_probed_dtypes(monkeypatch):
    import jax.numpy as jnp

    from tensorframes_tpu.ops import quantize as q

    assert q._pallas_dtype_ok(jnp.dtype(jnp.float32))
    assert q._pallas_dtype_ok(jnp.dtype(jnp.bfloat16))
    assert not q._pallas_dtype_ok(jnp.dtype(jnp.float64))
    assert not q._pallas_dtype_ok(jnp.dtype(jnp.float16))
    assert not q._pallas_dtype_ok(jnp.dtype(jnp.int8))
    # even with the flag on, a probe-ok state, and a TPU backend, an
    # unprobed dtype must NOT route to the pallas kernel (it could fail
    # Mosaic inside the caller's outer jit — the probe-gate's purpose)
    monkeypatch.setattr(
        "tensorframes_tpu.ops.quantize.jax.default_backend",
        lambda: "tpu",
    )
    monkeypatch.setitem(q._pallas_int8_state, "probed", True)
    monkeypatch.setitem(q._pallas_int8_state, "ok", True)
    from tensorframes_tpu.config import get_config

    cfg = get_config()
    old = cfg.pallas_int8_matmul
    try:
        cfg.pallas_int8_matmul = True
        w = q.quantize(np.ones((8, 4), np.float32))
        assert q._pallas_int8_eligible(jnp.ones((2, 8), jnp.float32), w)
        assert q._pallas_int8_eligible(jnp.ones((2, 8), jnp.bfloat16), w)
        assert not q._pallas_int8_eligible(
            jnp.ones((2, 8), jnp.float64), w
        )
    finally:
        cfg.pallas_int8_matmul = old


def test_f64_quantized_matmul_falls_back_correctly():
    import jax.numpy as jnp

    from tensorframes_tpu.ops import quantize as q

    rng = np.random.default_rng(0)
    w = q.quantize(rng.standard_normal((8, 4)).astype(np.float32))
    x = rng.standard_normal((3, 8))
    out = np.asarray(q.matmul(jnp.asarray(x), w))
    ref = x @ np.asarray(w.dequantize(jnp.float64))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-6)
