"""Verified UDF lifting (ISSUE 18): numpy host-callback UDFs compile
into the plan IR via synthesis + bounded equivalence checking.

The contract under test, in the paper's terms: a ``numpy_udf`` stage is
a fusion barrier (host callback) UNLESS the static pass can (a) inspect
its Python into the closed allowlist of elementwise/reduction numpy
ops, (b) synthesize an equivalent plan-IR program, and (c) verify the
synthesis BIT-EXACTLY against the real numpy function on a bounded
corpus of the actual block dtypes (boundary values, NaN/Inf, empty and
ragged-edge blocks). Lift only on proof; every decline carries a named
TFG112 reason; ``TFTPU_LIFT=0`` replays the callback path as the
bit-identity oracle.

* **liftable corpus** — arith/compare/where/clip chains and int
  reductions across int/float/bool dtypes lift, and the lifted run is
  bit-identical to the callback run (dtype + shape + payload bytes);
* **decline corpus** — loops, data-dependent branches, np.random,
  mutable closures, augmented assignment, float reductions each decline
  with the right reason (never a wrong answer, never a silent fall-through);
* **plan integration** — a fully-lifted chain reports ZERO fusion
  barriers (TFG107 counter clean) and TFG112 surfaces the decisions in
  ``lint_plan``; the lift token keys the compile-cache fingerprint;
* **per-workload strategy walls** (same PR) — observed-wall lookups
  prefer the workload's own evidence-grade table and fall back to the
  host-global one; v1 sidecars quarantine on the format bump.
"""

import json

import numpy as np
import pytest

import tensorframes_tpu as tfs
import tensorframes_tpu.ops.verbs as V
from tensorframes_tpu.analysis.diagnostics import DIAGNOSTIC_LOG
from tensorframes_tpu.ops.verbs import numpy_udf
from tensorframes_tpu.plan import ir as plan_ir
from tensorframes_tpu.plan import lift as plan_lift
from tensorframes_tpu.plan import stats as plan_stats


@pytest.fixture(autouse=True)
def _lift_state():
    """Every test starts lifting-enabled with an empty decision log and
    leaves the config the way it found it."""
    was = tfs.configure().udf_lifting
    tfs.configure(udf_lifting=True)
    plan_lift.clear_lift_log()
    yield
    tfs.configure(udf_lifting=was)


def _assert_bit_identical(blocks_a, blocks_b):
    assert len(blocks_a) == len(blocks_b)
    for ba, bb in zip(blocks_a, blocks_b):
        assert sorted(ba) == sorted(bb)
        for k in ba:
            va, vb = np.asarray(ba[k]), np.asarray(bb[k])
            assert va.dtype == vb.dtype, (k, va.dtype, vb.dtype)
            assert va.shape == vb.shape, (k, va.shape, vb.shape)
            assert va.tobytes() == vb.tobytes(), (k, va, vb)


def _lift_vs_oracle(fr, fn):
    """Run ``fn`` lifted and with lifting disabled (the callback path =
    the bit-identity oracle, ≙ TFTPU_LIFT=0); returns both block lists
    plus the lift decision record."""
    plan_lift.clear_lift_log()
    lifted = tfs.map_blocks(numpy_udf(fn), fr).blocks()
    recs = [r for r in plan_lift.lift_log() if r["udf"] == fn.__name__]
    assert recs, "no lift decision recorded"
    tfs.configure(udf_lifting=False)
    try:
        oracle = tfs.map_blocks(numpy_udf(fn), fr).blocks()
    finally:
        tfs.configure(udf_lifting=True)
    return lifted, oracle, recs[-1]


# ---------------------------------------------------------------------------
# liftable corpus: forms × dtypes, bit-identical to the callback oracle
# ---------------------------------------------------------------------------

def _arith(x):
    return {"y": (x * 2 + 1) - 3}


def _where_compare(x):
    return {"y": np.where(x > 3, x - 3, 3 - x)}


def _clip(x):
    return {"y": np.clip(x, 1, 25)}


def _demean(x):
    return {"y": x - x.mean()}


def _span(x):
    return {"lo": x - x.min(), "hi": x.max() - x}


def _chain(x):
    z = np.abs(x) + 1
    return {"y": np.maximum(z, x) * np.minimum(z, 7)}


_FLOAT_VALUES = [0.0, -0.0, 1.5, -2.25, 1e30, -1e30, np.inf, -np.inf,
                 np.nan, 5.0, 8.0, 13.0]
_INT_VALUES = [0, 1, -1, 7, -8, 2**30, -(2**30), 2**31 - 1, -(2**31),
               5, 3, 2]


@pytest.mark.parametrize("dtype,values", [
    (np.float32, _FLOAT_VALUES),
    (np.float64, _FLOAT_VALUES),
    (np.int32, _INT_VALUES),
    (np.int64, _INT_VALUES),
])
@pytest.mark.parametrize("fn", [
    _arith, _where_compare, _clip, _chain,
], ids=lambda f: f.__name__)
def test_liftable_elementwise_bit_identical(dtype, values, fn):
    fr = tfs.frame_from_arrays(
        {"x": np.asarray(values, dtype=dtype)}, num_blocks=3
    )
    lifted, oracle, rec = _lift_vs_oracle(fr, fn)
    assert rec["lifted"], rec
    _assert_bit_identical(lifted, oracle)


@pytest.mark.parametrize("dtype,fn", [
    (np.int32, _demean),
    (np.int32, _span),
    (np.int64, _span),
], ids=["demean-int32", "span-int32", "span-int64"])
def test_liftable_int_reductions_bit_identical(dtype, fn):
    # includes values whose int32 sum wraps: modular arithmetic must
    # match numpy's exactly, not "approximately in f64"
    fr = tfs.frame_from_arrays(
        {"x": np.asarray(_INT_VALUES, dtype=dtype)}, num_blocks=3
    )
    lifted, oracle, rec = _lift_vs_oracle(fr, fn)
    assert rec["lifted"], rec
    _assert_bit_identical(lifted, oracle)


def test_int64_mean_policy_declines():
    # int64 mean runs in f64 — inexact past 2^53, order-sensitive —
    # so it draws the same policy decline as float reductions
    fr = tfs.frame_from_arrays(
        {"x": np.asarray(_INT_VALUES, dtype=np.int64)}, num_blocks=2
    )
    plan_lift.clear_lift_log()
    V.compile_program(numpy_udf(_demean), fr)
    rec = plan_lift.lift_log()[-1]
    assert not rec["lifted"]
    assert rec["reason"] == "float-reduction"


def test_float_minmax_reduction_policy_declines():
    # measured: np.min([+0.,-0.]) = -0 but np.min([-0.,+0.]) = +0 —
    # numpy resolves signed-zero ties position-dependently, XLA
    # order-free, so float min/max REDUCTIONS stay callbacks (the
    # elementwise np.minimum/np.maximum are positional and lift fine)
    fr = tfs.frame_from_arrays(
        {"x": np.asarray([1.5, -2.0, 0.25, 8.0, -0.0, 0.0, 7.5, 3.0],
                         np.float32)},
        num_blocks=2,
    )
    lifted, oracle, rec = _lift_vs_oracle(fr, _span)
    assert not rec["lifted"]
    assert rec["reason"] == "float-reduction"
    # the decline is not a correctness event: both paths ran the
    # callback and agree bit-exactly
    _assert_bit_identical(lifted, oracle)


def test_liftable_bool_logic_bit_identical():
    def masks(x):
        return {"m": np.logical_and(x > 2, x < 9),
                "n": np.logical_or(x == 0, x == 5)}

    fr = tfs.frame_from_arrays(
        {"x": np.arange(12, dtype=np.int32)}, num_blocks=3
    )
    lifted, oracle, rec = _lift_vs_oracle(fr, masks)
    assert rec["lifted"], rec
    _assert_bit_identical(lifted, oracle)
    for b in lifted:
        assert np.asarray(b["m"]).dtype == np.bool_


def test_liftable_multi_input():
    def hyp(x, y):
        return {"h": np.sqrt(x * x + y * y), "d": np.where(x > y, x, y)}

    fr = tfs.frame_from_arrays(
        {"x": np.asarray([3.0, 0.0, -3.0, 1e20, np.nan, 5.0], np.float64),
         "y": np.asarray([4.0, -0.0, 4.0, 1e20, 1.0, 12.0], np.float64)},
        num_blocks=2,
    )
    lifted, oracle, rec = _lift_vs_oracle(fr, hyp)
    assert rec["lifted"], rec
    _assert_bit_identical(lifted, oracle)


# ---------------------------------------------------------------------------
# decline corpus: each wrong shape gets the RIGHT named reason
# ---------------------------------------------------------------------------

def _decline_reason(fr, fn):
    plan_lift.clear_lift_log()
    V.compile_program(numpy_udf(fn), fr)
    rec = plan_lift.lift_log()[-1]
    assert not rec["lifted"], rec
    return rec


@pytest.fixture()
def _ffr():
    return tfs.frame_from_arrays(
        {"x": np.arange(8, dtype=np.float32)}, num_blocks=2
    )


def test_decline_loop(_ffr):
    def loopy(x):
        acc = x
        for _ in range(3):
            acc = acc + x
        return {"a": acc}

    rec = _decline_reason(_ffr, loopy)
    assert rec["reason"] == "unsupported-syntax:For"
    assert rec["node"] == "For"


def test_decline_data_dependent_branch(_ffr):
    def branchy(x):
        if x.sum() > 0:
            return {"y": x}
        return {"y": -x}

    rec = _decline_reason(_ffr, branchy)
    assert rec["reason"] == "data-dependent-branch"


def test_decline_np_random(_ffr):
    def rng(x):
        return {"r": x + np.random.rand(*x.shape)}

    rec = _decline_reason(_ffr, rng)
    assert rec["reason"] == "unsupported-call:np.random.rand"


def test_decline_mutable_closure(_ffr):
    state = [1.0]

    def closed(x):
        return {"c": x * state[0]}

    rec = _decline_reason(_ffr, closed)
    assert rec["reason"] == "mutable-closure:state"


def test_decline_augmented_assignment(_ffr):
    def aug(x):
        y = x * 2
        y += 1
        return {"y": y}

    rec = _decline_reason(_ffr, aug)
    assert rec["reason"] == "augmented-assignment"


def test_decline_float_reduction(_ffr):
    # float sums are pairwise in numpy and tree-reduced in XLA: the
    # policy declines rather than verify-fail block-size-dependently
    def fsum(x):
        return {"s": x - np.sum(x)}

    rec = _decline_reason(_ffr, fsum)
    assert rec["reason"] == "float-reduction"


def test_decline_unsupported_call(_ffr):
    def sorter(x):
        return {"y": np.sort(x)}

    rec = _decline_reason(_ffr, sorter)
    assert rec["reason"] == "unsupported-call:np.sort"


def test_decline_attribute_access(_ffr):
    def fft(x):
        return {"y": np.fft.fft(x).real}

    rec = _decline_reason(_ffr, fft)
    assert rec["reason"] == "unsupported-syntax:Attribute"


def test_decline_is_not_an_error(_ffr):
    # a declined lift still EXECUTES (callback path) — lifting is an
    # optimization, never a correctness gate
    state = {"k": 2.0}

    def closed(x):
        return {"c": x * state["k"]}

    out = tfs.map_blocks(numpy_udf(closed), _ffr).blocks()
    got = np.concatenate([np.asarray(b["c"]) for b in out])
    np.testing.assert_array_equal(
        got, np.arange(8, dtype=np.float32) * 2.0
    )


def test_lifting_disabled_records_reason(_ffr):
    tfs.configure(udf_lifting=False)
    try:
        plan_lift.clear_lift_log()
        V.compile_program(numpy_udf(_arith), _ffr)
        rec = plan_lift.lift_log()[-1]
        assert not rec["lifted"]
        assert rec["reason"] == "lifting-disabled"
    finally:
        tfs.configure(udf_lifting=True)


# ---------------------------------------------------------------------------
# capture-time hygiene: mutable closures warn loudly at numpy_udf()
# ---------------------------------------------------------------------------

def test_mutable_closure_capture_warns():
    state = [1.0]

    def closed(x):
        return {"c": x * state[0]}

    n0 = len(DIAGNOSTIC_LOG)
    numpy_udf(closed)
    warns = [d for d in list(DIAGNOSTIC_LOG)[n0:] if d.code == "TFG112"]
    assert warns, "capture of a mutable closure must warn (TFG112)"
    assert warns[0].severity == "warn"
    assert "state" in warns[0].message


def test_clean_capture_does_not_warn():
    n0 = len(DIAGNOSTIC_LOG)
    numpy_udf(_arith)
    assert not [d for d in list(DIAGNOSTIC_LOG)[n0:] if d.code == "TFG112"]


# ---------------------------------------------------------------------------
# plan integration: barriers, lint_plan, fingerprint keying
# ---------------------------------------------------------------------------

def test_fully_lifted_chain_has_zero_barriers():
    fr = tfs.frame_from_arrays(
        {"x": np.arange(16, dtype=np.float32),
         "y": np.arange(16, dtype=np.float32) - 7.5},
        num_blocks=2,
    )

    def blend(u, v):
        return {"z": np.where(u > v, u - v, v - u)}

    f1 = tfs.map_blocks(lambda x, y: {"u": x * 2.0, "v": y + 1.0}, fr)
    f2 = tfs.map_blocks(numpy_udf(blend), f1)
    n_maps, barriers = plan_ir.chain_barriers(f2)
    assert n_maps == 2
    assert barriers == [], barriers


def test_declined_chain_keeps_barrier():
    fr = tfs.frame_from_arrays(
        {"x": np.arange(16, dtype=np.float32)}, num_blocks=2
    )

    def rng(w):
        return {"y": w + np.random.rand(*w.shape)}

    f1 = tfs.map_blocks(lambda x: {"w": x * 2.0}, fr)
    f2 = tfs.map_blocks(numpy_udf(rng), f1)
    _, barriers = plan_ir.chain_barriers(f2)
    assert barriers, "a declined lift must stay a counted barrier"


def test_lint_plan_reports_tfg112():
    fr = tfs.frame_from_arrays(
        {"x": np.arange(16, dtype=np.float32)}, num_blocks=2
    )
    lifted_frame = tfs.map_blocks(numpy_udf(_arith), fr)
    report = tfs.lint_plan(lifted_frame)
    hits = [d for d in report.diagnostics if d.code == "TFG112"]
    assert hits and hits[0].severity == "info"
    assert "lifted" in hits[0].message

    def rng(x):
        return {"y": x + np.random.rand(*x.shape)}

    declined_frame = tfs.map_blocks(numpy_udf(rng), fr)
    report = tfs.lint_plan(declined_frame)
    hits = [d for d in report.diagnostics if d.code == "TFG112"]
    assert hits and hits[0].severity == "warn"
    assert "unsupported-call:np.random.rand" in hits[0].message


def test_lift_token_keys_fingerprint_env():
    from tensorframes_tpu.compilecache.fingerprint import _env_parts

    on = _env_parts("block", False, True)
    tfs.configure(udf_lifting=False)
    try:
        off = _env_parts("block", False, True)
    finally:
        tfs.configure(udf_lifting=True)
    assert on["lift"]["enabled"] is True
    assert off["lift"]["enabled"] is False
    assert on != off, "a TFTPU_LIFT flip must re-key the compile cache"


def test_lifted_program_not_flagged_as_callback():
    fr = tfs.frame_from_arrays(
        {"x": np.arange(8, dtype=np.float32)}, num_blocks=2
    )
    prog = V.compile_program(numpy_udf(_arith), fr)
    assert not plan_ir.program_has_callback(prog)
    tfs.configure(udf_lifting=False)
    try:
        prog_cb = V.compile_program(numpy_udf(_arith), fr)
    finally:
        tfs.configure(udf_lifting=True)
    assert plan_ir.program_has_callback(prog_cb)


def test_lift_report_renders():
    fr = tfs.frame_from_arrays(
        {"x": np.arange(8, dtype=np.float32)}, num_blocks=2
    )
    plan_lift.clear_lift_log()
    V.compile_program(numpy_udf(_arith), fr)

    def loopy(x):
        acc = x
        for _ in range(2):
            acc = acc + x
        return {"a": acc}

    V.compile_program(numpy_udf(loopy), fr)
    text = plan_lift.lift_report()
    assert "LIFTED" in text and "DECLINED" in text
    assert "unsupported-syntax:For" in text


# ---------------------------------------------------------------------------
# per-workload strategy walls (satellite): keyed lookups + v1 quarantine
# ---------------------------------------------------------------------------

_reopt_only = pytest.mark.skipif(
    not plan_stats.reopt_enabled(), reason="TFTPU_REOPT=0"
)


@_reopt_only
def test_workload_walls_prefer_local_evidence():
    plan_stats.reset_strategy_walls(unlink_sidecar=False)
    with plan_stats.workload_scope("wlA"):
        for _ in range(3):
            plan_stats.observe_strategy_wall("epi", "per_block", 0.010)
            plan_stats.observe_strategy_wall("epi", "concat", 0.020)
    with plan_stats.workload_scope("wlB"):
        for _ in range(3):
            plan_stats.observe_strategy_wall("epi", "per_block", 0.050)
            plan_stats.observe_strategy_wall("epi", "concat", 0.001)
    with plan_stats.workload_scope("wlA"):
        wa = plan_stats.strategy_walls("epi")
    with plan_stats.workload_scope("wlB"):
        wb = plan_stats.strategy_walls("epi")
    # the same decision ranks OPPOSITE ways for the two workloads
    assert wa["per_block"]["ewma_s"] < wa["concat"]["ewma_s"]
    assert wb["concat"]["ewma_s"] < wb["per_block"]["ewma_s"]


@_reopt_only
def test_workload_walls_fall_back_to_global():
    plan_stats.reset_strategy_walls(unlink_sidecar=False)
    for _ in range(2):
        plan_stats.observe_strategy_wall("fuse", "fused", 0.010)
        plan_stats.observe_strategy_wall("fuse", "split", 0.030)
    with plan_stats.workload_scope("wl-thin"):
        # one strategy, one sample: not evidence-grade → global answers
        plan_stats.observe_strategy_wall("fuse", "fused", 0.005)
        walls = plan_stats.strategy_walls("fuse")
    assert set(walls) == {"fused", "split"}
    assert walls["split"]["n"] >= 2


@_reopt_only
def test_workload_scope_is_thread_local_and_nests():
    assert plan_stats.current_workload() is None
    with plan_stats.workload_scope("outer"):
        assert plan_stats.current_workload() == "outer"
        with plan_stats.workload_scope("inner"):
            assert plan_stats.current_workload() == "inner"
        assert plan_stats.current_workload() == "outer"
    assert plan_stats.current_workload() is None


@_reopt_only
def test_v1_strategy_wall_sidecar_quarantines(tmp_path):
    was = tfs.configure().compilation_cache_dir
    tfs.configure(compilation_cache_dir=str(tmp_path))
    try:
        plan_stats.clear_memory()
        path = tmp_path / "planstats" / "strategy_walls.json"
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps({
            "v": 1, "kind": "strategy_walls",
            "tables": {"fuse": {"obs": 3, "strategies": {
                "fused": {"ewma_s": 0.1, "n": 3, "last_obs": 3}}}},
        }))
        assert plan_stats.strategy_walls("fuse") == {}
        assert not path.exists(), "v1 sidecars quarantine on format bump"

        # a fresh observation rewrites the sidecar at v2 with both slots
        plan_stats.observe_strategy_wall("fuse", "fused", 0.5)
        rec = json.loads(path.read_text())
        assert rec["v"] == plan_stats.SW_FORMAT_VERSION
        assert "workloads" in rec and "tables" in rec
    finally:
        plan_stats.reset_strategy_walls()
        tfs.configure(compilation_cache_dir=was)
        plan_stats.clear_memory()
