"""Elastic-recovery tests: the resumable training loop survives a
mid-run crash and continues from the checkpoint with deterministic
results (the preemption-recovery model SURVEY §5 notes the reference
delegates to Spark)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorframes_tpu.checkpoint import Checkpointer
from tensorframes_tpu.training import run_resumable


def _make_step():
    @jax.jit
    def step(state, batch):
        new = {"w": state["w"] + batch, "count": state["count"] + 1}
        return new, {"w_sum": new["w"].sum()}

    return step


def _batches(n):
    return [jnp.full((2,), float(i)) for i in range(n)]


def _init():
    return {"w": jnp.zeros((2,)), "count": jnp.asarray(0, jnp.int32)}


def test_full_run_and_final_checkpoint(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "run"), backend="npz")
    state, ran = run_resumable(
        _make_step(), _init(), ckpt, _batches(10), num_steps=10, save_every=4
    )
    assert ran == 10
    assert int(state["count"]) == 10
    assert float(state["w"][0]) == sum(range(10))
    assert ckpt.latest_step() == 10  # trailing partial interval saved


def test_crash_and_resume_matches_uninterrupted(tmp_path):
    crashing_step = _make_step()
    calls = []

    def flaky(state, batch):
        if len(calls) == 6 and not flaky.resumed:
            raise RuntimeError("preempted")
        calls.append(1)
        return crashing_step(state, batch)

    flaky.resumed = False
    ckpt = Checkpointer(str(tmp_path / "run"), backend="npz")
    with pytest.raises(RuntimeError, match="preempted"):
        run_resumable(flaky, _init(), ckpt, _batches(10), num_steps=10, save_every=3)
    # emergency checkpoint landed at the crash point
    assert ckpt.latest_step() == 6

    # "new process": same call, resumes from step 6 and skips 6 batches
    flaky.resumed = True
    state, ran = run_resumable(
        flaky, _init(), ckpt, _batches(10), num_steps=10, save_every=3
    )
    assert ran == 4  # only the remaining steps
    # identical to an uninterrupted run
    ref, _ = run_resumable(
        _make_step(), _init(),
        Checkpointer(str(tmp_path / "ref"), backend="npz"),
        _batches(10), num_steps=10, save_every=100,
    )
    np.testing.assert_array_equal(np.asarray(state["w"]), np.asarray(ref["w"]))
    assert int(state["count"]) == int(ref["count"]) == 10


def test_on_step_callback_sees_metrics(tmp_path):
    seen = []
    run_resumable(
        _make_step(), _init(),
        Checkpointer(str(tmp_path / "run"), backend="npz"),
        _batches(3), num_steps=3, save_every=0,
        on_step=lambda s, m: seen.append((s, float(m["w_sum"]))),
    )
    assert [s for s, _ in seen] == [1, 2, 3]


def test_resume_with_short_dataset_raises(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "run"), backend="npz")
    run_resumable(_make_step(), _init(), ckpt, _batches(10), num_steps=6, save_every=3)
    assert ckpt.latest_step() == 6
    with pytest.raises(ValueError, match="shorter than the original"):
        run_resumable(_make_step(), _init(), ckpt, _batches(4), num_steps=10, save_every=3)


def test_already_complete_run_is_noop(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "run"), backend="npz")
    run_resumable(_make_step(), _init(), ckpt, _batches(5), num_steps=5, save_every=5)

    def exploding():
        raise AssertionError("iterator must not be consumed")
        yield  # pragma: no cover

    state, ran = run_resumable(
        _make_step(), _init(), ckpt, exploding(), num_steps=5, save_every=5
    )
    assert ran == 0 and int(state["count"]) == 5
