"""Elastic-recovery tests: the resumable training loop survives a
mid-run crash and continues from the checkpoint with deterministic
results (the preemption-recovery model SURVEY §5 notes the reference
delegates to Spark)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorframes_tpu.checkpoint import Checkpointer
from tensorframes_tpu.training import run_resumable


def _make_step():
    @jax.jit
    def step(state, batch):
        new = {"w": state["w"] + batch, "count": state["count"] + 1}
        return new, {"w_sum": new["w"].sum()}

    return step


def _batches(n):
    return [jnp.full((2,), float(i)) for i in range(n)]


def _init():
    return {"w": jnp.zeros((2,)), "count": jnp.asarray(0, jnp.int32)}


def test_full_run_and_final_checkpoint(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "run"), backend="npz")
    state, ran = run_resumable(
        _make_step(), _init(), ckpt, _batches(10), num_steps=10, save_every=4
    )
    assert ran == 10
    assert int(state["count"]) == 10
    assert float(state["w"][0]) == sum(range(10))
    assert ckpt.latest_step() == 10  # trailing partial interval saved


def test_crash_and_resume_matches_uninterrupted(tmp_path):
    crashing_step = _make_step()
    calls = []

    def flaky(state, batch):
        if len(calls) == 6 and not flaky.resumed:
            raise RuntimeError("preempted")
        calls.append(1)
        return crashing_step(state, batch)

    flaky.resumed = False
    ckpt = Checkpointer(str(tmp_path / "run"), backend="npz")
    with pytest.raises(RuntimeError, match="preempted"):
        run_resumable(flaky, _init(), ckpt, _batches(10), num_steps=10, save_every=3)
    # emergency checkpoint landed at the crash point
    assert ckpt.latest_step() == 6

    # "new process": same call, resumes from step 6 and skips 6 batches
    flaky.resumed = True
    state, ran = run_resumable(
        flaky, _init(), ckpt, _batches(10), num_steps=10, save_every=3
    )
    assert ran == 4  # only the remaining steps
    # identical to an uninterrupted run
    ref, _ = run_resumable(
        _make_step(), _init(),
        Checkpointer(str(tmp_path / "ref"), backend="npz"),
        _batches(10), num_steps=10, save_every=100,
    )
    np.testing.assert_array_equal(np.asarray(state["w"]), np.asarray(ref["w"]))
    assert int(state["count"]) == int(ref["count"]) == 10


def test_on_step_callback_sees_metrics(tmp_path):
    seen = []
    run_resumable(
        _make_step(), _init(),
        Checkpointer(str(tmp_path / "run"), backend="npz"),
        _batches(3), num_steps=3, save_every=0,
        on_step=lambda s, m: seen.append((s, float(m["w_sum"]))),
    )
    assert [s for s, _ in seen] == [1, 2, 3]


def test_resume_with_short_dataset_raises(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "run"), backend="npz")
    run_resumable(_make_step(), _init(), ckpt, _batches(10), num_steps=6, save_every=3)
    assert ckpt.latest_step() == 6
    with pytest.raises(ValueError, match="shorter than the original"):
        run_resumable(_make_step(), _init(), ckpt, _batches(4), num_steps=10, save_every=3)


def test_already_complete_run_is_noop(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "run"), backend="npz")
    run_resumable(_make_step(), _init(), ckpt, _batches(5), num_steps=5, save_every=5)

    def exploding():
        raise AssertionError("iterator must not be consumed")
        yield  # pragma: no cover

    state, ran = run_resumable(
        _make_step(), _init(), ckpt, exploding(), num_steps=5, save_every=5
    )
    assert ran == 0 and int(state["count"]) == 5


def test_grad_accum_matches_full_batch():
    """accum_steps microbatches must produce the same update as the full
    batch (linear model + SGD → exact up to float assoc)."""
    import optax

    import tensorframes_tpu.training as tn

    rng = np.random.default_rng(0)
    w0 = {"w": jnp.asarray(rng.standard_normal(4), jnp.float32)}
    x = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)
    y = jnp.asarray(rng.standard_normal(8), jnp.float32)

    def loss_fn(params, batch):
        bx, by = batch
        pred = bx @ params["w"]
        return jnp.mean((pred - by) ** 2)

    tx = optax.sgd(0.1)

    full_step = tn.make_grad_accum_step(loss_fn, tx, 1)
    accum_step = tn.make_grad_accum_step(loss_fn, tx, 4)
    p1, _, l1 = full_step(w0, tx.init(w0), (x, y))
    p4, _, l4 = accum_step(w0, tx.init(w0), (x, y))
    # mean-of-microbatch-means == full-batch mean for equal splits
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l4), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(p1["w"]), np.asarray(p4["w"]), rtol=1e-5, atol=1e-6
    )


def test_grad_accum_validates():
    import optax

    import tensorframes_tpu.training as tn

    with pytest.raises(ValueError, match="accum_steps"):
        tn.make_grad_accum_step(lambda p, b: 0.0, optax.sgd(0.1), 0)
    step = tn.make_grad_accum_step(
        lambda p, b: jnp.mean(b[0]) * p["w"].sum(), optax.sgd(0.1), 3
    )
    w = {"w": jnp.ones((2,), jnp.float32)}
    with pytest.raises(ValueError, match="not divisible"):
        step(w, optax.sgd(0.1).init(w), (jnp.ones((8, 2)),))


def test_grad_accum_float64_loss():
    """x64 is on by default in this package; a float64 loss must not
    break the scan carry."""
    import optax

    import tensorframes_tpu.training as tn

    w = {"w": jnp.asarray(np.ones(3), jnp.float64)}
    x = jnp.asarray(np.ones((4, 3)), jnp.float64)
    step = tn.make_grad_accum_step(
        lambda p, b: jnp.mean((b[0] @ p["w"]) ** 2), optax.sgd(0.01), 2
    )
    p, _, loss = step(w, optax.sgd(0.01).init(w), (x,))
    assert np.isfinite(float(loss))


def test_train_on_frame_logreg_converges():
    """Frame columns → minibatch stream → jitted step: loss must drop."""
    import optax

    import tensorframes_tpu as tfs
    import tensorframes_tpu.training as tn
    from tensorframes_tpu.models import logreg

    x, y = logreg.make_synthetic_mnist(512, seed=0)
    frame = tfs.frame_from_arrays({"features": x, "label_true": y})
    params = logreg.init_params(seed=0)
    tx = optax.adam(1e-2)

    @jax.jit
    def step(state, batch):
        params, opt = state
        params, opt, loss = logreg.train_step(
            params, opt, batch["features"], batch["label_true"], tx
        )
        return (params, opt), loss

    losses = []
    (params, _), ran = tn.train_on_frame(
        step,
        (params, tx.init(params)),
        frame,
        ["features", "label_true"],
        batch_size=128,
        num_steps=30,
        on_step=lambda i, l: losses.append(float(l)),
    )
    assert ran == 30
    assert losses[-1] < losses[0] * 0.9, (losses[0], losses[-1])


def test_train_on_frame_resumes(tmp_path):
    import optax

    import tensorframes_tpu as tfs
    import tensorframes_tpu.training as tn

    frame = tfs.frame_from_arrays(
        {"x": np.random.default_rng(0).standard_normal((64, 4)).astype(np.float32)}
    )
    w0 = {"w": jnp.zeros((4,), jnp.float32)}
    tx = optax.sgd(0.1)

    @jax.jit
    def step(state, batch):
        p, o = state
        loss, g = jax.value_and_grad(
            lambda p: jnp.mean((batch["x"] @ p["w"] - 1.0) ** 2)
        )(p)
        up, o = tx.update(g, o, p)
        import optax as _ox

        return (_ox.apply_updates(p, up), o), loss

    ck = Checkpointer(str(tmp_path), backend="npz")
    state0 = (w0, tx.init(w0))
    _, ran1 = tn.train_on_frame(
        step, state0, frame, ["x"], batch_size=16, num_steps=7,
        checkpointer=ck, save_every=5, shuffle=False,
    )
    assert ran1 == 7
    # relaunch: resumes at 7, runs 5 more
    _, ran2 = tn.train_on_frame(
        step, state0, frame, ["x"], batch_size=16, num_steps=12,
        checkpointer=ck, save_every=5, shuffle=False,
    )
    assert ran2 == 5


def test_mixed_precision_step_keeps_f32_masters():
    """compute_dtype="bfloat16": forward/backward run in bf16 (MXU-rate
    on TPU) while the optimizer updates f32 MASTER weights — params stay
    f32, the update direction matches the f32 step to bf16 tolerance,
    and no loss scaling is involved (bf16 keeps f32's exponent range)."""
    import optax

    import tensorframes_tpu.training as tn

    rng = np.random.default_rng(1)
    w0 = {"w": jnp.asarray(rng.standard_normal(8), jnp.float32)}
    x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    y = jnp.asarray(rng.standard_normal(16), jnp.float32)

    seen_dtypes = []

    def loss_fn(params, batch):
        bx, by = batch
        pred = bx @ params["w"]
        # record the dtype the forward actually SAW at trace time, so a
        # regression that silently drops the cast fails below
        seen_dtypes.append(params["w"].dtype)
        return jnp.mean((pred - by) ** 2)

    tx = optax.sgd(0.05)
    f32_step = tn.make_grad_accum_step(loss_fn, tx, 2)
    mp_step = tn.make_grad_accum_step(
        loss_fn, tx, 2, compute_dtype="bfloat16"
    )
    p_f32, _, l_f32 = f32_step(w0, tx.init(w0), (x, y))
    seen_dtypes.clear()
    p_mp, _, l_mp = mp_step(w0, tx.init(w0), (x, y))
    assert jnp.bfloat16 in seen_dtypes, seen_dtypes  # cast reached fwd
    assert p_mp["w"].dtype == jnp.float32  # masters stay f32
    np.testing.assert_allclose(
        np.asarray(l_mp), np.asarray(l_f32), rtol=5e-2
    )
    np.testing.assert_allclose(
        np.asarray(p_mp["w"]), np.asarray(p_f32["w"]), rtol=0.1, atol=5e-3
    )
    # several steps reduce the loss — the bf16 path genuinely trains
    p, s = w0, tx.init(w0)
    losses = []
    for _ in range(10):
        p, s, loss = mp_step(p, s, (x, y))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses
