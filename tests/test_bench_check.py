"""The bench regression gate must actually catch regressions: the round-2
verdict showed 20x floors let a 25% drift through. The rewritten gate
compares against a recorded same-machine baseline with a 2x default
factor — these tests inject a 2.2x slowdown and assert it trips."""

import importlib.util
import json
import os

import pytest

_MOD_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "dev",
    "bench_check.py",
)


@pytest.fixture()
def gate(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location("bench_check", _MOD_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    baseline = tmp_path / "bench_baseline.json"
    baseline.write_text(
        json.dumps(
            {
                "cpu": {
                    "logreg_map_blocks_rows_per_sec": 1000.0,
                    "reduce_blocks_1M_wall_s": 1.0,
                }
            }
        )
    )
    monkeypatch.setattr(mod, "BASELINE_PATH", str(baseline))

    def run(text: str, *argv: str) -> int:
        out = tmp_path / "bench_out.txt"
        out.write_text(text)
        return mod.main([str(out), *argv])

    return run


def test_healthy_run_passes(gate):
    assert gate(
        "# logreg_map_blocks_rows_per_sec=980\n# reduce_blocks_1M_wall_s=1.05\n"
    ) == 0


def test_two_x_throughput_slowdown_trips(gate):
    assert gate(
        "# logreg_map_blocks_rows_per_sec=450\n# reduce_blocks_1M_wall_s=1.0\n"
    ) == 1


def test_two_x_wallclock_slowdown_trips(gate):
    assert gate(
        "# logreg_map_blocks_rows_per_sec=1000\n# reduce_blocks_1M_wall_s=2.3\n"
    ) == 1


def test_wider_factor_tolerates(gate):
    assert gate(
        "# logreg_map_blocks_rows_per_sec=450\n# reduce_blocks_1M_wall_s=2.3\n",
        "--factor", "10",
    ) == 0


def test_import_error_metric_skips_without_tf(gate):
    """ADVICE r2 (medium): a fixture that can't build because tensorflow
    is not installed reports ERROR ImportError — the gate must soften
    that to a skip, not fail every CI run."""
    assert gate(
        "# logreg_map_blocks_rows_per_sec=ERROR ImportError: no tensorflow\n"
        "# reduce_blocks_1M_wall_s=1.0\n"
    ) == 0


def test_import_error_fails_when_required(gate):
    assert gate(
        "# logreg_map_blocks_rows_per_sec=ERROR ImportError: no tensorflow\n"
        "# reduce_blocks_1M_wall_s=1.0\n",
        "--require-all",
    ) == 1


def test_genuinely_missing_metric_fails(gate):
    assert gate("# reduce_blocks_1M_wall_s=1.0\n") == 1


def test_platform_sections_do_not_cross_fire(gate):
    """A TPU run must not be compared against the CPU baseline (different
    metric names and incomparable values): with no tpu section recorded,
    the gate passes with a notice instead of spraying MISSING failures."""
    assert gate(
        "# chips=1 devices=[TpuDevice(id=0)]\n"
        "# bert_base_map_rows_rows_per_sec=50000\n"
    ) == 0


def test_zero_baseline_skips_instead_of_permanent_fail(gate, tmp_path):
    import json

    (tmp_path / "bench_baseline.json").write_text(
        json.dumps({"cpu": {"reduce_blocks_1M_wall_s": 0.0}})
    )
    assert gate("# reduce_blocks_1M_wall_s=0.001\n") == 0
