"""Profiling subsystem tests: span accumulation, verb auto-instrumentation,
and the report format."""

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.utils import profiling


@pytest.fixture(autouse=True)
def _clean_metrics():
    profiling.reset_metrics()
    yield
    profiling.reset_metrics()


def test_span_accumulates():
    with profiling.span("work", rows=10):
        pass
    with profiling.span("work", rows=5):
        pass
    m = profiling.metrics()
    assert m["work"].calls == 2
    assert m["work"].rows == 15
    assert m["work"].seconds >= 0


def test_verbs_are_instrumented():
    df = tfs.frame_from_arrays({"x": np.arange(20.0)}, num_blocks=2)
    out = tfs.map_blocks(lambda x: {"y": x * 2}, df)
    out.collect()
    s = tfs.reduce_blocks(lambda x_input: {"x": x_input.sum(0)}, df)
    assert float(s) == np.arange(20.0).sum()
    m = profiling.metrics()
    assert m["map_blocks"].calls == 1 and m["map_blocks"].rows == 20
    assert m["reduce_blocks"].calls == 1 and m["reduce_blocks"].rows == 20


def test_aggregate_instrumented():
    fr = tfs.frame_from_arrays(
        {"k": np.array([1, 1, 2]), "v": np.array([1.0, 2.0, 3.0])}
    )
    tfs.aggregate(lambda v_input: {"v": v_input.sum(0)}, fr.group_by("k"))
    assert profiling.metrics()["aggregate"].rows == 3


def test_report_format():
    assert profiling.report() == "no spans recorded"
    with profiling.span("alpha", rows=100):
        pass
    rep = profiling.report()
    assert "alpha" in rep and "rows/s" in rep


def test_trace_writes_profile(tmp_path):
    import jax.numpy as jnp

    with profiling.trace(str(tmp_path)):
        jnp.arange(10).sum().block_until_ready()
    # jax writes a plugins/profile dir when tracing is supported
    found = list(tmp_path.rglob("*.xplane.pb")) + list(
        tmp_path.rglob("*.trace.json.gz")
    )
    assert found, f"no trace output under {tmp_path}"


def test_compilation_cache_config_plumbs_through(tmp_path):
    """TFTPU_COMPILE_CACHE wires jax's persistent compilation cache at
    import (fresh process: import-time config)."""
    import subprocess
    import sys

    cache = str(tmp_path / "xla-cache")
    script = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import os, sys\n"
        f"os.environ['TFTPU_COMPILE_CACHE'] = {cache!r}\n"
        "sys.path.insert(0, os.getcwd())\n"
        "import tensorframes_tpu\n"
        "print('dir=', jax.config.jax_compilation_cache_dir)\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120,
        env={**__import__('os').environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert cache in r.stdout


def test_relational_methods_record_spans():
    """filter/sort_values/join record profiling spans when forced — the
    same observability contract as the verbs."""
    import numpy as np

    import tensorframes_tpu as tfs
    from tensorframes_tpu.utils import profiling

    profiling.reset_metrics()
    lf = tfs.frame_from_arrays(
        {"k": np.asarray([1, 2, 3]), "v": np.asarray([1.0, 2.0, 3.0])}
    )
    rf = tfs.frame_from_arrays(
        {"k": np.asarray([2, 3]), "w": np.asarray([20.0, 30.0])}
    )
    flt = lf.filter(lambda v: {"keep": v > 1.0})
    m0 = profiling.metrics()
    assert "filter" not in m0  # lazy: nothing recorded before forcing
    flt.sort_values("v").collect()
    lf.join(rf, on="k").collect()
    m = profiling.metrics()
    # INPUT-rows convention, same as the verbs: a filter that kept 2 of
    # 3 rows did 3 rows of work
    assert m["filter"].rows == 3
    assert m["sort_values"].rows == 2  # sort ran on the filtered frame
    assert m["join"].rows == 5  # 3 left + 2 right
    profiling.reset_metrics()
