"""Arrow / Parquet interop tests (skipped as a unit when pyarrow is
absent — pyarrow is an optional dependency)."""

import numpy as np
import pytest

import tensorframes_tpu as tfs

# ---------------------------------------------------------------------------
# Arrow / Parquet interop
# ---------------------------------------------------------------------------

pa = pytest.importorskip("pyarrow")


def test_arrow_roundtrip_zero_copy():
    t = pa.table(
        {
            "i": pa.array(np.arange(6)),
            "f": pa.array(np.linspace(0, 1, 6)),
            "s": pa.array([f"r{i}" for i in range(6)]),
        }
    )
    fr = tfs.frame_from_arrow(t, num_blocks=2)
    np.testing.assert_array_equal(fr.column_values("i"), np.arange(6))
    assert [r["s"] for r in fr.collect()] == [f"r{i}" for i in range(6)]
    back = tfs.frame_to_arrow(fr)
    assert back.column_names == ["i", "f", "s"]
    np.testing.assert_array_equal(back.column("i").to_numpy(), np.arange(6))


def test_arrow_list_columns_and_verbs():
    t = pa.table({"v": pa.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])})
    fr = tfs.frame_from_arrow(t)
    out = tfs.map_blocks(lambda v: {"s": v.sum(axis=1)}, fr)
    np.testing.assert_allclose(out.column_values("s"), [3.0, 7.0, 11.0])


def test_arrow_null_int_rejected():
    t = pa.table({"i": pa.array([1, None, 3])})
    with pytest.raises(ValueError, match="nulls"):
        tfs.frame_from_arrow(t)
    # null floats become NaN
    tf2 = tfs.frame_from_arrow(pa.table({"f": pa.array([1.0, None])}))
    vals = tf2.column_values("f")
    assert vals[0] == 1.0 and np.isnan(vals[1])


def test_parquet_roundtrip(tmp_path):
    d = {
        "i": np.arange(10),
        "f": np.linspace(0, 1, 10),
        "s": [f"n{i}" for i in range(10)],
        "vec": np.arange(20.0).reshape(10, 2),
    }
    fr = tfs.frame_from_arrays(d)
    path = str(tmp_path / "t.parquet")
    tfs.write_parquet(fr, path)
    back = tfs.read_parquet(path, num_blocks=3)
    np.testing.assert_array_equal(back.column_values("i"), d["i"])
    np.testing.assert_allclose(
        np.stack([np.asarray(r["vec"]) for r in back.collect()]), d["vec"]
    )
    # frames from parquet run through the verbs
    tot = tfs.reduce_blocks(lambda f_input: {"f": f_input.sum(axis=0)}, back)
    assert float(tot) == pytest.approx(float(d["f"].sum()))
