"""Lazy verb-chain fusion (tensorframes_tpu/plan): fused vs per-stage
execution must be BIT-IDENTICAL across verb chains × dtypes × frame
layouts; barriers must split the plan instead of changing semantics;
and a fused chain must dispatch exactly one compiled program per block
(asserted via the executor's jit-cache hit/miss counters)."""

import itertools

import jax
import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.observability.metrics import REGISTRY
from tensorframes_tpu.ops.executor import (
    _GATHER_BYTES,
    _JIT_HITS,
    _JIT_MISSES,
)


@pytest.fixture(autouse=True)
def _fusion_on():
    """Every test starts from the default-on knob and restores it."""
    before = tfs.configure().plan_fusion
    tfs.configure(plan_fusion=True)
    yield
    tfs.configure(plan_fusion=before)


def _unfused(build):
    """Run ``build()`` with the TFTPU_FUSION=0 escape hatch active."""
    tfs.configure(plan_fusion=False)
    try:
        return build()
    finally:
        tfs.configure(plan_fusion=True)


def _snap():
    return {
        (d["name"], tuple(sorted(d["labels"].items()))): d
        for d in REGISTRY.snapshot()
    }


def _rows_equal(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert ra.keys() == rb.keys()
        for k in ra:
            va, vb = np.asarray(ra[k]), np.asarray(rb[k])
            assert va.dtype == vb.dtype, (k, va.dtype, vb.dtype)
            np.testing.assert_array_equal(va, vb)


# ---------------------------------------------------------------------------
# equivalence property sweep: chains × dtypes × layouts, bit-identical
# ---------------------------------------------------------------------------

DTYPES = [np.float32, np.float64, np.int32, np.int64]
LAYOUTS = ["dense", "ragged", "sharded"]


def _chain(frame, dtype):
    """A representative chain: map_blocks → map_rows → select — block
    and row stages composing, with a projection pruning the tail."""
    two = dtype(2)
    one = dtype(1)
    f1 = tfs.map_blocks(lambda x: {"y": x * two + one}, frame)
    f2 = f1.map_rows(lambda y: {"z": y * y})
    return f2.select(["z", "x"]).collect()


def _make_frame(layout, dtype, n=24):
    if layout == "ragged":
        rng = np.random.default_rng(7)
        rows = [
            {"x": np.arange(k, dtype=dtype)}
            for k in rng.integers(1, 5, n)
        ]
        return tfs.frame_from_rows(rows, num_blocks=3)
    x = np.arange(n, dtype=dtype)
    frame = tfs.frame_from_arrays({"x": x}, num_blocks=3)
    if layout == "sharded":
        frame = frame.to_device()
    return frame


@pytest.mark.parametrize(
    "dtype,layout",
    list(itertools.product(DTYPES, LAYOUTS)),
    ids=lambda v: str(getattr(v, "__name__", v)),
)
def test_fused_unfused_bit_identical(dtype, layout):
    if layout == "sharded":
        try:
            _make_frame(layout, dtype)
        except AttributeError:
            pytest.skip("mesh creation unavailable on this jax build")
    if layout == "ragged":
        # ragged cells keep per-row map semantics; chain through
        # map_rows only (map_blocks on ragged raises by contract)
        def build():
            fr = _make_frame(layout, dtype)
            g1 = tfs.map_rows(lambda x: {"s": x.sum()}, fr)
            g2 = g1.map_rows(lambda s: {"t": s * dtype(2)})
            return g2.select(["t", "s"]).collect()
    else:
        def build():
            return _chain(_make_frame(layout, dtype), dtype)
    _rows_equal(build(), _unfused(build))


def test_longer_mixed_chain_bit_identical():
    def build():
        fr = tfs.frame_from_arrays(
            {
                "a": np.arange(30, dtype=np.float64),
                "b": np.arange(30, dtype=np.float64) * 0.5,
            },
            num_blocks=4,
        )
        f1 = tfs.map_blocks(lambda a, b: {"c": a + b}, fr)
        f2 = f1.map_rows(lambda c: {"d": c * c})
        f3 = tfs.map_blocks(lambda d, a: {"e": d - a}, f2)
        return f3.select(["e", "c"]).collect()

    _rows_equal(build(), _unfused(build))


def test_filter_chain_bit_identical():
    def build():
        fr = tfs.frame_from_arrays(
            {"x": np.arange(40, dtype=np.float32)}, num_blocks=3
        )
        f1 = tfs.map_blocks(lambda x: {"y": x * 2.0}, fr)
        f2 = f1.filter(lambda y: {"keep": y > 20.0})
        f3 = f2.map_rows(lambda y: {"q": y + 0.5})
        return f3.collect()

    fused = build()
    assert len(fused) == 29
    _rows_equal(fused, _unfused(build))


def test_filter_contract_errors_survive_fusion():
    df = tfs.frame_from_arrays({"x": np.arange(4, dtype=np.float32)})
    with pytest.raises(ValueError, match="bool"):
        df.filter(lambda x: {"keep": x * 2.0}).collect()
    with pytest.raises(ValueError, match="exactly one"):
        df.filter(lambda x: {"a": x > 1.0, "b": x > 2.0})


def test_host_string_columns_ride_through_fused_chains():
    # host-resident string columns never feed programs; they must pass
    # through a fused run (and subset through a fused filter) unchanged
    def build():
        fr = tfs.frame_from_rows(
            [{"x": float(i), "tag": f"r{i}"} for i in range(12)],
            num_blocks=2,
        )
        f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
        f2 = f1.map_rows(lambda y: {"z": y * 3.0})
        return f2.filter(lambda z: {"keep": z > 9.0}).collect()

    _rows_equal(build(), _unfused(build))


# ---------------------------------------------------------------------------
# one dispatch per block (jit-cache accounting)
# ---------------------------------------------------------------------------

def test_fused_chain_compiles_once_per_block_shape():
    n = 32  # divisible: every block has the same shape
    fr = tfs.frame_from_arrays(
        {"x": np.arange(n, dtype=np.float32)}, num_blocks=4
    )
    p1 = tfs.compile_program(lambda x: {"y": x + 1.0}, fr)
    f1 = tfs.map_blocks(p1, fr)
    p2 = tfs.compile_program(lambda y: {"z": y * 2.0}, f1)
    f2 = tfs.map_blocks(p2, f1)
    p3 = tfs.compile_program(lambda z: {"w": z - 3.0}, f2)

    def build():
        return tfs.map_blocks(p3, tfs.map_blocks(p2, tfs.map_blocks(p1, fr)))

    m0, h0 = _JIT_MISSES.value, _JIT_HITS.value
    build().blocks()
    misses = _JIT_MISSES.value - m0
    hits = _JIT_HITS.value - h0
    # ONE composed program, compiled once (one block shape), dispatched
    # once per block — not 3 stages × 4 blocks
    assert misses == 1, misses
    assert hits == 3, hits  # remaining 3 blocks reuse the executable

    # steady-state: rebuilding the chain from the same stage Programs
    # reuses the cached fused program — zero fresh compiles
    m1 = _JIT_MISSES.value
    build().blocks()
    assert _JIT_MISSES.value - m1 == 0


def test_fused_stage_metrics_and_trace():
    from tensorframes_tpu.observability import events

    fr = tfs.frame_from_arrays(
        {"x": np.arange(16, dtype=np.float32)}, num_blocks=2
    )
    fused0 = _snap()[("tftpu_plan_fused_stages_total", ())]["value"]
    events.clear()
    events.enable()
    try:
        f2 = tfs.map_blocks(
            lambda y: {"z": y * 2.0},
            tfs.map_blocks(lambda x: {"y": x + 1.0}, fr),
        )
        f2.blocks()
    finally:
        events.disable()
    assert (
        _snap()[("tftpu_plan_fused_stages_total", ())]["value"]
        == fused0 + 2
    )
    names = {e["name"] for e in events.TRACER.to_chrome_trace()["traceEvents"]}
    assert "plan.lower" in names and "plan.execute" in names


# ---------------------------------------------------------------------------
# select pushdown: pruned columns are never gathered or computed
# ---------------------------------------------------------------------------

def test_select_pushdown_skips_pruned_stage_and_gather():
    wide = 256
    n = 64

    def build():
        fr = tfs.frame_from_arrays(
            {
                "x": np.arange(n, dtype=np.float32),
                "w": np.zeros((n, wide), dtype=np.float32),
            },
            num_blocks=2,
        )
        f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
        f2 = tfs.map_blocks(lambda w: {"big": w * 2.0}, f1)
        return f2.select(["y"]).collect()

    g0 = _GATHER_BYTES.value
    fused = build()
    fused_bytes = _GATHER_BYTES.value - g0

    g1 = _GATHER_BYTES.value
    unfused = _unfused(build)
    unfused_bytes = _GATHER_BYTES.value - g1

    _rows_equal(fused, unfused)
    w_bytes = n * wide * 4
    # per-stage execution gathers the wide column for the pruned stage;
    # the plan never does — w is dead once select drops 'big'
    assert unfused_bytes >= w_bytes
    assert fused_bytes <= unfused_bytes - w_bytes

    assert (
        _snap()[("tftpu_plan_intermediate_bytes_avoided_total", ())]["value"]
        > 0
    )


def test_select_over_pending_frame_prunes_intermediate():
    fr = tfs.frame_from_arrays(
        {"x": np.arange(10, dtype=np.float64)}, num_blocks=2
    )
    f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
    f2 = f1.map_rows(lambda y: {"z": y * 2.0})
    out = f2.select(["z"])
    blocks = out.blocks()
    assert all(set(b.keys()) == {"z"} for b in blocks)
    np.testing.assert_array_equal(
        out.column_values("z"), (np.arange(10, dtype=np.float64) + 1) * 2
    )


# ---------------------------------------------------------------------------
# barriers split the plan, never change semantics
# ---------------------------------------------------------------------------

def test_trim_map_is_a_barrier_and_chain_still_correct():
    def build():
        fr = tfs.frame_from_arrays(
            {"x": np.arange(12, dtype=np.float32)}, num_blocks=2
        )
        f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
        trimmed = f1.map_blocks_trimmed(lambda y: {"t": y[:3]})
        return tfs.map_blocks(lambda t: {"u": t * 2.0}, trimmed).collect()

    fused = build()
    assert len(fused) == 6  # 2 blocks × 3 trimmed rows
    _rows_equal(fused, _unfused(build))


def test_host_callback_stage_falls_back_per_stage():
    calls = []

    def cb(a):
        calls.append(len(a))
        return np.asarray(a) + 1.0

    def cb_stage(y):
        return {
            "c": jax.pure_callback(
                cb, jax.ShapeDtypeStruct(y.shape, y.dtype), y
            )
        }

    fr = tfs.frame_from_arrays(
        {"x": np.arange(8, dtype=np.float32)}, num_blocks=2
    )
    f1 = tfs.map_blocks(lambda x: {"y": x * 2.0}, fr)
    f2 = tfs.map_blocks(cb_stage, f1)
    f3 = tfs.map_blocks(lambda c: {"d": c - 1.0}, f2)
    got = [r["d"] for r in f3.collect()]
    assert got == [float(x) * 2.0 for x in range(8)]
    assert calls  # the callback genuinely ran


def test_plan_dropped_after_force_frees_chain():
    fr = tfs.frame_from_arrays(
        {"x": np.arange(6, dtype=np.float32)}, num_blocks=2
    )
    f2 = tfs.map_blocks(
        lambda y: {"z": y * 2.0},
        tfs.map_blocks(lambda x: {"y": x + 1.0}, fr),
    )
    assert f2._plan is not None
    f2.blocks()
    # the recorded chain is spent on materialization — keeping it would
    # pin the source frame's buffers for this frame's lifetime
    assert f2._plan is None


def test_pruned_callback_stage_still_fires_side_effect():
    calls = []

    def cb(a):
        calls.append(len(a))
        return np.asarray(a) + 1.0

    def cb_stage(y):
        return {
            "c": jax.pure_callback(
                cb, jax.ShapeDtypeStruct(y.shape, y.dtype), y
            )
        }

    fr = tfs.frame_from_arrays(
        {"x": np.arange(8, dtype=np.float32)}, num_blocks=2
    )
    f1 = tfs.map_blocks(lambda x: {"y": x * 2.0}, fr)
    f2 = tfs.map_blocks(cb_stage, f1)
    # select drops the callback's output — pushdown must NOT elide the
    # stage (TFTPU_FUSION=0 executes it, so fusion must too)
    out = f2.select(["y"]).collect()
    assert [r["y"] for r in out] == [float(x) * 2.0 for x in range(8)]
    assert calls, "pushdown elided the host callback's side effect"


def test_fusion_knob_honored_at_force_time():
    fr = tfs.frame_from_arrays(
        {"x": np.arange(12, dtype=np.float32)}, num_blocks=2
    )
    chain = tfs.map_blocks(
        lambda y: {"z": y * 2.0},
        tfs.map_blocks(lambda x: {"y": x + 1.0}, fr),
    )
    assert chain._plan is not None  # recorded while fusion was on
    fused0 = _snap()[("tftpu_plan_fused_stages_total", ())]["value"]
    tfs.configure(plan_fusion=False)
    try:
        rows = chain.collect()
    finally:
        tfs.configure(plan_fusion=True)
    assert [r["z"] for r in rows] == [(x + 1.0) * 2.0 for x in range(12)]
    # the escape hatch ruled fusion out even for the pre-recorded chain
    assert (
        _snap()[("tftpu_plan_fused_stages_total", ())]["value"] == fused0
    )


def test_ragged_source_falls_back_and_matches():
    def build():
        rows = [
            {"v": np.arange(k, dtype=np.float64)} for k in (2, 5, 2, 3, 5)
        ]
        fr = tfs.frame_from_rows(rows, num_blocks=1)
        g1 = tfs.map_rows(lambda v: {"s": v.sum()}, fr)
        return g1.map_rows(lambda s: {"t": s + 1.0}).collect()

    _rows_equal(build(), _unfused(build))


def test_branched_chain_materializes_shared_prefix_once():
    # DAG-shaped pipelines: the first consumer fuses through the shared
    # frame; later consumers source on it, so forcing them caches the
    # shared prefix instead of re-running it inside every branch
    fr = tfs.frame_from_arrays(
        {"x": np.arange(10, dtype=np.float32)}, num_blocks=2
    )
    f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
    f2 = tfs.map_blocks(lambda y: {"z": y * 2.0}, f1)  # extends f1
    f3 = tfs.map_blocks(lambda y: {"w": y - 1.0}, f1)  # branches off
    np.testing.assert_array_equal(
        f2.column_values("z"), (np.arange(10, dtype=np.float32) + 1) * 2
    )
    assert not f1.is_materialized  # branch 1 fused through it
    np.testing.assert_array_equal(
        f3.column_values("w"), np.arange(10, dtype=np.float32)
    )
    assert f1.is_materialized  # branch 2 sourced on (and cached) it
    # a third branch reuses the cached prefix
    f4 = tfs.map_blocks(lambda y: {"v": y * 0.0}, f1)
    np.testing.assert_array_equal(f4.column_values("v"), np.zeros(10))


def test_all_pruned_segment_dispatches_nothing():
    # select pushdown pruning EVERY stage degrades to a projection —
    # no composed program is compiled or dispatched for it
    fr = tfs.frame_from_arrays(
        {"x": np.arange(8, dtype=np.float32)}, num_blocks=2
    )
    out = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr).select(["x"])
    m0 = _JIT_MISSES.value
    h0 = _JIT_HITS.value
    blocks = out.blocks()
    assert _JIT_MISSES.value - m0 == 0
    assert _JIT_HITS.value - h0 == 0
    assert all(set(b.keys()) == {"x"} for b in blocks)
    np.testing.assert_array_equal(
        out.column_values("x"), np.arange(8, dtype=np.float32)
    )


def test_lint_plan_sees_to_host_with_num_blocks():
    fr = tfs.frame_from_arrays({"x": np.arange(8, dtype=np.float32)})
    f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
    f2 = tfs.map_blocks(
        lambda y: {"z": y * 2.0}, f1.to_host(num_blocks=2)
    )
    rep = tfs.lint_plan(f2)
    assert any(
        d.code == "TFG107" and "to_host" in d.message for d in rep
    )


def test_forced_intermediate_re_roots_the_chain():
    fr = tfs.frame_from_arrays(
        {"x": np.arange(6, dtype=np.float32)}, num_blocks=2
    )
    f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
    f1.blocks()  # user forces the intermediate
    assert f1.is_materialized
    f2 = tfs.map_blocks(lambda y: {"z": y * 2.0}, f1)
    np.testing.assert_array_equal(
        f2.column_values("z"), (np.arange(6, dtype=np.float32) + 1) * 2
    )


# ---------------------------------------------------------------------------
# TFG107 fusion-barrier lint
# ---------------------------------------------------------------------------

def test_lint_plan_names_materialization_barrier():
    fr = tfs.frame_from_arrays({"x": np.arange(8, dtype=np.float32)})
    f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
    f2 = tfs.map_blocks(lambda y: {"z": y * 2.0}, f1.to_host())
    rep = tfs.lint_plan(f2)
    hits = [d for d in rep if d.code == "TFG107"]
    assert hits and "to_host" in hits[0].message
    assert "to_host" in hits[0].explain()  # explain() names the barrier


def test_lint_plan_names_callback_barrier():
    def cb_stage(y):
        return {
            "c": jax.pure_callback(
                lambda a: np.asarray(a) + 1.0,
                jax.ShapeDtypeStruct(y.shape, y.dtype),
                y,
            )
        }

    fr = tfs.frame_from_arrays({"x": np.arange(8, dtype=np.float32)})
    f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
    f2 = tfs.map_blocks(cb_stage, f1)
    f3 = tfs.map_blocks(lambda c: {"d": c * 3.0}, f2)
    rep = tfs.lint_plan(f3)
    assert any(
        d.code == "TFG107" and "callback" in d.message for d in rep
    )


def test_lint_plan_clean_chain_has_no_findings():
    fr = tfs.frame_from_arrays({"x": np.arange(8, dtype=np.float32)})
    f2 = tfs.map_blocks(
        lambda y: {"z": y * 2.0},
        tfs.map_blocks(lambda x: {"y": x + 1.0}, fr),
    )
    assert len(tfs.lint_plan(f2)) == 0
    assert len(tfs.lint_plan(fr)) == 0  # plan-less frames lint clean


def test_tfg107_counter_is_preregistered():
    prom = REGISTRY.to_prometheus()
    assert 'tftpu_analysis_diagnostics_total{code="TFG107"}' in prom
    for name in (
        "tftpu_plan_fused_stages_total",
        "tftpu_plan_intermediate_bytes_avoided_total",
        "tftpu_plan_lowering_seconds",
        "tftpu_plan_fallback_total",
    ):
        assert name in prom


# ---------------------------------------------------------------------------
# plan surface
# ---------------------------------------------------------------------------

def test_explain_plan_renders_chain():
    fr = tfs.frame_from_arrays({"x": np.arange(4, dtype=np.float32)})
    f2 = tfs.map_blocks(
        lambda y: {"z": y * 2.0},
        tfs.map_blocks(lambda x: {"y": x + 1.0}, fr),
    ).select(["z"])
    text = tfs.explain_plan(f2)
    assert "map_blocks(y)" in text
    assert "map_blocks(z)" in text
    assert "select(['z'])" in text


def test_fusion_off_records_no_plan():
    tfs.configure(plan_fusion=False)
    fr = tfs.frame_from_arrays({"x": np.arange(4, dtype=np.float32)})
    f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
    assert getattr(f1, "_plan", None) is None
    np.testing.assert_array_equal(
        f1.column_values("y"), np.arange(4, dtype=np.float32) + 1
    )


def test_sharded_chain_keeps_mesh_and_matches():
    try:
        fr = tfs.frame_from_arrays(
            {"x": np.arange(16, dtype=np.float32)}
        ).to_device()
    except AttributeError:
        pytest.skip("mesh creation unavailable on this jax build")

    def build(frame):
        f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, frame)
        return f1.map_rows(lambda y: {"z": y * 2.0})

    out = build(fr)
    assert out.is_sharded  # map chains keep the mesh
    got = np.asarray(out.column_values("z"))
    exp_frame = _unfused(lambda: build(fr))
    np.testing.assert_array_equal(
        got, np.asarray(exp_frame.column_values("z"))
    )


# ---------------------------------------------------------------------------
# whole-pipeline compilation (ISSUE 7): aggregate / reduce / join
# epilogues fuse into the plan; fused == unfused bit-identical
# ---------------------------------------------------------------------------

def _agg_chain(dtype, op, keys_kind="int", n=48, num_blocks=3):
    """map->map->aggregate over a multi-block frame. Data is exact for
    every dtype (small integers), so fused and unfused results must be
    BIT-identical even for float sums."""
    rng = np.random.default_rng(5)
    cols = {"x": (np.arange(n) % 7).astype(dtype)}
    if keys_kind == "int":
        cols["k"] = rng.integers(0, 5, n).astype(np.int64)
        fr = tfs.frame_from_arrays(cols, num_blocks=num_blocks)
    else:
        rows = [
            {"k": f"g{rng.integers(0, 5)}", "x": dtype(int(v))}
            for v in cols["x"]
        ]
        fr = tfs.frame_from_rows(rows, num_blocks=num_blocks)
    f1 = tfs.map_blocks(lambda x: {"y": x + x}, fr)
    f2 = f1.map_rows(lambda y: {"z": y * y})
    with tfs.with_graph():
        z_in = tfs.block(f2, "z", tf_name="z_input")
        fetch = getattr(tfs, op)(z_in, axis=0, name="z")
        agg = tfs.aggregate(fetch, f2.group_by("k"))
    return agg.collect(), f2


AGG_DTYPES = [np.int32, np.int64, np.float32, np.float64]
AGG_OPS = ["reduce_sum", "reduce_min", "reduce_max", "reduce_mean"]


@pytest.mark.parametrize("dtype", AGG_DTYPES, ids=lambda d: d.__name__)
@pytest.mark.parametrize("op", AGG_OPS)
def test_aggregate_epilogue_bit_identical(dtype, op):
    fused, chain_frame = _agg_chain(dtype, op)
    assert not chain_frame.is_materialized, (
        "fused aggregate must not materialize the mapped chain"
    )
    unfused, _ = _unfused(lambda: _agg_chain(dtype, op))
    _rows_equal(fused, unfused)


@pytest.mark.parametrize("op", ["reduce_sum", "reduce_mean"])
def test_aggregate_epilogue_string_keys_bit_identical(op):
    fused, _ = _agg_chain(np.float32, op, keys_kind="str")
    unfused, _ = _unfused(lambda: _agg_chain(np.float32, op, "str"))
    _rows_equal(fused, unfused)


def test_aggregate_epilogue_decisions_counted():
    def count(kind):
        key = ("tftpu_plan_cost_decisions_total", (("decision", kind),))
        d = _snap().get(key)
        return d["value"] if d else 0.0

    pb0, cc0 = count("epilogue_per_block"), count("epilogue_concat")
    _agg_chain(np.int64, "reduce_sum")   # int sum: exact tree-combine
    assert count("epilogue_per_block") == pb0 + 1
    _agg_chain(np.float32, "reduce_sum")  # float sum: concat epilogue
    assert count("epilogue_concat") == cc0 + 1


def test_aggregate_epilogue_metrics_and_laziness():
    before = _snap().get(
        ("tftpu_plan_fused_epilogues_total", (("verb", "aggregate"),))
    )
    before = before["value"] if before else 0.0
    fused, chain_frame = _agg_chain(np.float32, "reduce_sum")
    after = _snap()[
        ("tftpu_plan_fused_epilogues_total", (("verb", "aggregate"),))
    ]["value"]
    assert after == before + 1
    assert not chain_frame.is_materialized


def test_aggregate_computed_key_falls_back_and_matches():
    """A group key computed by a chained stage cannot pre-encode on the
    host: the epilogue falls back (counted, TFG109-marked) and results
    still match the escape hatch exactly."""
    def build():
        fr = tfs.frame_from_arrays(
            {"x": (np.arange(24) % 6).astype(np.int64)}, num_blocks=2
        )
        f1 = tfs.map_blocks(lambda x: {"kk": x % 3, "y": x * 2}, fr)
        with tfs.with_graph():
            y_in = tfs.block(f1, "y", tf_name="y_input")
            fetch = tfs.reduce_sum(y_in, axis=0, name="y")
            return tfs.aggregate(fetch, f1.group_by("kk"))

    key = ("tftpu_plan_fallback_total", (("reason", "computed_key"),))
    b0 = _snap().get(key)
    b0 = b0["value"] if b0 else 0.0
    agg = build()
    fused = agg.collect()
    assert _snap()[key]["value"] == b0 + 1
    rep = tfs.lint_plan(agg)
    assert any(d.code == "TFG109" for d in rep)
    _rows_equal(fused, _unfused(lambda: build().collect()))


def test_aggregate_nonalgebraic_fetch_marks_tfg109():
    fr = tfs.frame_from_arrays(
        {"k": np.array([0, 1, 0, 1]), "x": np.arange(4, dtype=np.float32)}
    )
    f1 = tfs.map_blocks(lambda x: {"y": x * 2.0}, fr)
    agg = tfs.aggregate(
        lambda y_input: {"y": y_input.max(axis=0) - y_input.min(axis=0)},
        f1.group_by("k"),
    )
    rep = tfs.lint_plan(agg)
    assert any(d.code == "TFG109" for d in rep)
    assert "non-algebraic" in next(
        d for d in rep if d.code == "TFG109"
    ).explain()


def test_aggregate_ragged_source_falls_back_and_matches():
    def build():
        rows = [
            {"k": i % 3, "v": np.arange(1 + i % 4, dtype=np.float32)}
            for i in range(18)
        ]
        fr = tfs.frame_from_rows(rows, num_blocks=2)
        f1 = tfs.map_rows(lambda v: {"s": v.sum()}, fr)
        with tfs.with_graph():
            s_in = tfs.block(f1, "s", tf_name="s_input")
            fetch = tfs.reduce_sum(s_in, axis=0, name="s")
            return tfs.aggregate(fetch, f1.group_by("k")).collect()

    key = ("tftpu_plan_fallback_total", (("reason", "ragged"),))
    b0 = _snap().get(key)
    b0 = b0["value"] if b0 else 0.0
    fused = build()
    assert _snap()[key]["value"] >= b0 + 1
    _rows_equal(fused, _unfused(build))


def test_aggregate_empty_after_filter_keeps_schema():
    def build():
        fr = tfs.frame_from_arrays(
            {"k": np.arange(8, dtype=np.int64),
             "x": np.arange(8, dtype=np.float32)}, num_blocks=2
        )
        f1 = tfs.map_blocks(lambda x: {"y": x * 2.0}, fr)
        f2 = f1.filter(lambda y: {"keep": y > 1e9})
        with tfs.with_graph():
            y_in = tfs.block(f2, "y", tf_name="y_input")
            fetch = tfs.reduce_sum(y_in, axis=0, name="y")
            return tfs.aggregate(fetch, f2.group_by("k"))

    agg = build()
    assert agg.num_rows == 0
    assert agg.schema.names == ["k", "y"]
    _rows_equal(agg.collect(), _unfused(lambda: build().collect()))


def test_aggregate_one_compile_per_block_shape_steady_state():
    n = 64
    fr = tfs.frame_from_arrays(
        {"k": (np.arange(n) % 4).astype(np.int64),
         "x": (np.arange(n) % 8).astype(np.int64)},
        num_blocks=4,
    )
    p1 = tfs.compile_program(lambda x: {"y": x * 2}, fr)
    f0 = tfs.map_blocks(p1, fr)
    with tfs.with_graph():
        y_in = tfs.block(f0, "y", tf_name="y_input")
        fetch = tfs.reduce_sum(y_in, axis=0, name="y")
        agg_program = tfs.compile_program([fetch], f0, reduce_mode="blocks")

    def run():
        f1 = tfs.map_blocks(p1, fr)
        return tfs.aggregate(agg_program, f1.group_by("k")).blocks()

    run()  # warm: compiles once per block shape
    m0 = _JIT_MISSES.value
    run()
    run()
    assert _JIT_MISSES.value - m0 == 0


def test_segment_bucket_decision_engages_on_varying_group_counts():
    key = ("tftpu_plan_cost_decisions_total",
           (("decision", "bucket_segments"),))
    b0 = _snap().get(key)
    b0 = b0["value"] if b0 else 0.0
    for ng in (3, 5, 6, 7):  # 4 distinct counts for one op set
        n = 40
        fr = tfs.frame_from_arrays(
            {"k": (np.arange(n) % ng).astype(np.int64),
             "x": (np.arange(n) % 4).astype(np.int64)},
            num_blocks=2,
        )
        f1 = tfs.map_blocks(lambda x: {"zq": x + 1}, fr)
        with tfs.with_graph():
            z_in = tfs.block(f1, "zq", tf_name="zq_input")
            fetch = tfs.reduce_sum(z_in, axis=0, name="zq")
            tfs.aggregate(fetch, f1.group_by("k")).blocks()
    assert _snap()[key]["value"] > b0


# -- reduce epilogues -------------------------------------------------------

@pytest.mark.parametrize("dtype", AGG_DTYPES, ids=lambda d: d.__name__)
def test_reduce_blocks_fused_bit_identical(dtype):
    def build():
        fr = tfs.frame_from_arrays(
            {"x": (np.arange(30) % 5).astype(dtype)}, num_blocks=3
        )
        f1 = tfs.map_blocks(lambda x: {"y": x + x}, fr)
        f2 = f1.map_rows(lambda y: {"z": y * y})
        out = tfs.reduce_blocks(
            # dtype= pins the fetch dtype (int sums otherwise promote)
            lambda z_input: {"z": z_input.sum(axis=0, dtype=z_input.dtype)},
            f2,
        )
        return out, f2

    fused, chain_frame = build()
    assert not chain_frame.is_materialized
    unfused, _ = _unfused(build)
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(unfused))


@pytest.mark.parametrize("dtype", [np.float32, np.int64],
                         ids=lambda d: d.__name__)
def test_reduce_rows_fused_bit_identical(dtype):
    def build():
        fr = tfs.frame_from_arrays(
            {"x": (np.arange(17) % 5).astype(dtype)}, num_blocks=4
        )
        f1 = tfs.map_blocks(lambda x: {"y": x * dtype(2)}, fr)
        out = tfs.reduce_rows(lambda y_1, y_2: {"y": y_1 + y_2}, f1)
        return out, f1

    fused, chain_frame = build()
    assert not chain_frame.is_materialized
    unfused, _ = _unfused(build)
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(unfused))


def test_reduce_epilogue_metrics():
    key = ("tftpu_plan_fused_epilogues_total", (("verb", "reduce_blocks"),))
    b0 = _snap().get(key)
    b0 = b0["value"] if b0 else 0.0
    fr = tfs.frame_from_arrays({"x": np.arange(8, dtype=np.float32)})
    f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
    tfs.reduce_blocks(lambda y_input: {"y": y_input.sum(axis=0)}, f1)
    assert _snap()[key]["value"] == b0 + 1


def test_reduce_callback_chain_falls_back():
    import jax

    def cb(a):
        return a + 1.0

    def cb_stage(y):
        return {
            "z": jax.pure_callback(
                cb, jax.ShapeDtypeStruct(y.shape, y.dtype), y
            )
        }

    fr = tfs.frame_from_arrays({"x": np.arange(6, dtype=np.float32)},
                               num_blocks=2)
    f1 = tfs.map_blocks(lambda x: {"y": x * 2.0}, fr)
    f2 = tfs.map_blocks(cb_stage, f1)
    out = tfs.reduce_blocks(lambda z_input: {"z": z_input.sum(axis=0)}, f2)
    exp = ((np.arange(6) * 2.0) + 1.0).sum()
    assert float(out) == exp


# -- joins in the plan ------------------------------------------------------

def _join_frames(keys_kind="int"):
    if keys_kind == "int":
        left = tfs.frame_from_arrays(
            {"k": np.array([0, 1, 2, 1, 3], np.int64),
             "x": np.arange(5, dtype=np.float32)},
            num_blocks=2,
        )
        right = tfs.frame_from_arrays(
            {"k": np.array([1, 2, 4], np.int64),
             "w": np.array([10.0, 20.0, 40.0], np.float32)},
        )
    else:
        left = tfs.frame_from_rows(
            [{"k": f"g{i % 3}", "x": float(i)} for i in range(6)],
            num_blocks=2,
        )
        right = tfs.frame_from_rows(
            [{"k": "g0", "w": 10.0}, {"k": "g2", "w": 20.0}],
        )
    return left, right


@pytest.mark.parametrize("how,fill", [
    ("inner", None), ("left", 0.0), ("right", 0.0), ("outer", -1.0),
])
@pytest.mark.parametrize("keys_kind", ["int", "str"])
def test_join_plan_matches_unfused(how, fill, keys_kind):
    def build():
        left, right = _join_frames(keys_kind)
        f1 = tfs.map_blocks(lambda x: {"y": x * 2.0}, left)
        kw = {} if fill is None else {"fill_value": fill}
        return f1.join(right, on="k", how=how, **kw).collect()

    _rows_equal(build(), _unfused(build))


def test_join_result_is_lazy_and_plan_carrying():
    left, right = _join_frames()
    j = left.join(right, on="k")
    assert not j.is_materialized
    assert getattr(j, "_plan", None) is not None
    assert "join(on=['k'], how='inner')" in tfs.explain_plan(j)


def test_join_pushdown_prunes_both_sides():
    """A select after the join prunes dead columns through it on BOTH
    sides: wide stage outputs nobody reads are never computed and their
    wide source inputs never gather — probe chain and build chain
    alike (asserted via the executor's gather-bytes counter)."""
    wide = 256
    n = 64

    def build(select_cols):
        left = tfs.frame_from_arrays(
            {
                "k": (np.arange(n) % 8).astype(np.int64),
                "x": np.arange(n, dtype=np.float32),
                "lsrc": np.ones((n, wide), np.float32),
            },
            num_blocks=2,
        )
        # the build side is LARGER than the probe side, so the assertion
        # below fails unless pushdown genuinely prunes the build chain
        # too (probe-side savings alone cannot carry the 4x margin)
        nr = 2048
        right_src = tfs.frame_from_arrays(
            {
                "k": (np.arange(nr) % 8).astype(np.int64),
                "w": np.arange(nr, dtype=np.float32),
                "rsrc": np.ones((nr, wide), np.float32),
            },
        )
        right = tfs.map_blocks(lambda rsrc: {"rbig": rsrc * 2.0}, right_src)
        f1 = tfs.map_blocks(lambda x: {"y": x * 2.0}, left)
        f2 = tfs.map_blocks(lambda lsrc: {"lbig": lsrc * 2.0}, f1)
        return f2.join(right, on="k").select(select_cols).collect()

    g0 = _GATHER_BYTES.value
    build(["k", "y", "w"])
    pruned_bytes = _GATHER_BYTES.value - g0
    g0 = _GATHER_BYTES.value
    build(["k", "y", "w", "lbig", "rbig"])
    full_bytes = _GATHER_BYTES.value - g0
    assert pruned_bytes < full_bytes / 4, (pruned_bytes, full_bytes)


def test_map_join_aggregate_pipeline_bit_identical():
    """The chain3_join bench shape at test size: probe maps fuse, the
    join runs in-plan, the aggregate epilogue consumes the join output
    — bit-identical to the per-stage replay, zero steady-state
    compiles."""
    n, ng = 96, 8

    def build():
        rng = np.random.default_rng(2)
        left = tfs.frame_from_arrays(
            {
                "k": rng.integers(0, ng, n).astype(np.int32),
                "x": (np.arange(n) % 16).astype(np.float32),
                "dead": np.ones(n, np.float32),
            },
            num_blocks=3,
        )
        right = tfs.frame_from_arrays(
            {"k": np.arange(ng, dtype=np.int32),
             "w": np.arange(ng, dtype=np.float32)},
        )
        f1 = tfs.map_blocks(lambda x: {"y": x * 2.0 + 1.0}, left)
        f2 = tfs.map_blocks(lambda y: {"z": y * y}, f1)
        j = f2.join(right, on="k")
        with tfs.with_graph():
            z_in = tfs.block(j, "z", tf_name="z_input")
            w_in = tfs.block(j, "w", tf_name="w_input")
            fz = tfs.reduce_sum(z_in, axis=0, name="z")
            fw = tfs.reduce_sum(w_in, axis=0, name="w")
            return tfs.aggregate([fz, fw], j.group_by("k")).collect()

    _rows_equal(build(), _unfused(build))


def test_tfg109_counter_is_preregistered():
    prom = REGISTRY.to_prometheus()
    assert 'tftpu_analysis_diagnostics_total{code="TFG109"}' in prom
    for name in (
        "tftpu_plan_fused_epilogues_total",
        "tftpu_plan_cost_decisions_total",
    ):
        assert name in prom


def test_join_lossy_fill_raises_even_when_pruned():
    """Pushdown must not launder a lossy fill: a fill that cannot
    represent exactly in a column's dtype raises at join() time, even
    if a later select prunes that column out of the fused pipeline —
    fused and TFTPU_FUSION=0 must fail identically."""
    left = tfs.frame_from_arrays(
        {"k": np.array([0, 1, 9], np.int64),
         "x": np.arange(3, dtype=np.float32)},
    )
    right = tfs.frame_from_arrays(
        {"k": np.array([0, 1], np.int64),
         "w": np.array([1.0, 2.0], np.float32),
         "tag": np.array([7, 8], np.int64)},
    )
    f1 = tfs.map_blocks(lambda x: {"y": x * 2.0}, left)
    for fused in (True, False):
        tfs.configure(plan_fusion=fused)
        with pytest.raises(ValueError, match="representable"):
            f1.join(
                right, on="k", how="left",
                fill_value={"w": 0.0, "tag": -1.5},
            ).select(["k", "y", "w"]).collect()
    tfs.configure(plan_fusion=True)
