"""Lazy verb-chain fusion (tensorframes_tpu/plan): fused vs per-stage
execution must be BIT-IDENTICAL across verb chains × dtypes × frame
layouts; barriers must split the plan instead of changing semantics;
and a fused chain must dispatch exactly one compiled program per block
(asserted via the executor's jit-cache hit/miss counters)."""

import itertools

import jax
import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.observability.metrics import REGISTRY
from tensorframes_tpu.ops.executor import (
    _GATHER_BYTES,
    _JIT_HITS,
    _JIT_MISSES,
)


@pytest.fixture(autouse=True)
def _fusion_on():
    """Every test starts from the default-on knob and restores it."""
    before = tfs.configure().plan_fusion
    tfs.configure(plan_fusion=True)
    yield
    tfs.configure(plan_fusion=before)


def _unfused(build):
    """Run ``build()`` with the TFTPU_FUSION=0 escape hatch active."""
    tfs.configure(plan_fusion=False)
    try:
        return build()
    finally:
        tfs.configure(plan_fusion=True)


def _snap():
    return {
        (d["name"], tuple(sorted(d["labels"].items()))): d
        for d in REGISTRY.snapshot()
    }


def _rows_equal(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert ra.keys() == rb.keys()
        for k in ra:
            va, vb = np.asarray(ra[k]), np.asarray(rb[k])
            assert va.dtype == vb.dtype, (k, va.dtype, vb.dtype)
            np.testing.assert_array_equal(va, vb)


# ---------------------------------------------------------------------------
# equivalence property sweep: chains × dtypes × layouts, bit-identical
# ---------------------------------------------------------------------------

DTYPES = [np.float32, np.float64, np.int32, np.int64]
LAYOUTS = ["dense", "ragged", "sharded"]


def _chain(frame, dtype):
    """A representative chain: map_blocks → map_rows → select — block
    and row stages composing, with a projection pruning the tail."""
    two = dtype(2)
    one = dtype(1)
    f1 = tfs.map_blocks(lambda x: {"y": x * two + one}, frame)
    f2 = f1.map_rows(lambda y: {"z": y * y})
    return f2.select(["z", "x"]).collect()


def _make_frame(layout, dtype, n=24):
    if layout == "ragged":
        rng = np.random.default_rng(7)
        rows = [
            {"x": np.arange(k, dtype=dtype)}
            for k in rng.integers(1, 5, n)
        ]
        return tfs.frame_from_rows(rows, num_blocks=3)
    x = np.arange(n, dtype=dtype)
    frame = tfs.frame_from_arrays({"x": x}, num_blocks=3)
    if layout == "sharded":
        frame = frame.to_device()
    return frame


@pytest.mark.parametrize(
    "dtype,layout",
    list(itertools.product(DTYPES, LAYOUTS)),
    ids=lambda v: str(getattr(v, "__name__", v)),
)
def test_fused_unfused_bit_identical(dtype, layout):
    if layout == "sharded":
        try:
            _make_frame(layout, dtype)
        except AttributeError:
            pytest.skip("mesh creation unavailable on this jax build")
    if layout == "ragged":
        # ragged cells keep per-row map semantics; chain through
        # map_rows only (map_blocks on ragged raises by contract)
        def build():
            fr = _make_frame(layout, dtype)
            g1 = tfs.map_rows(lambda x: {"s": x.sum()}, fr)
            g2 = g1.map_rows(lambda s: {"t": s * dtype(2)})
            return g2.select(["t", "s"]).collect()
    else:
        def build():
            return _chain(_make_frame(layout, dtype), dtype)
    _rows_equal(build(), _unfused(build))


def test_longer_mixed_chain_bit_identical():
    def build():
        fr = tfs.frame_from_arrays(
            {
                "a": np.arange(30, dtype=np.float64),
                "b": np.arange(30, dtype=np.float64) * 0.5,
            },
            num_blocks=4,
        )
        f1 = tfs.map_blocks(lambda a, b: {"c": a + b}, fr)
        f2 = f1.map_rows(lambda c: {"d": c * c})
        f3 = tfs.map_blocks(lambda d, a: {"e": d - a}, f2)
        return f3.select(["e", "c"]).collect()

    _rows_equal(build(), _unfused(build))


def test_filter_chain_bit_identical():
    def build():
        fr = tfs.frame_from_arrays(
            {"x": np.arange(40, dtype=np.float32)}, num_blocks=3
        )
        f1 = tfs.map_blocks(lambda x: {"y": x * 2.0}, fr)
        f2 = f1.filter(lambda y: {"keep": y > 20.0})
        f3 = f2.map_rows(lambda y: {"q": y + 0.5})
        return f3.collect()

    fused = build()
    assert len(fused) == 29
    _rows_equal(fused, _unfused(build))


def test_filter_contract_errors_survive_fusion():
    df = tfs.frame_from_arrays({"x": np.arange(4, dtype=np.float32)})
    with pytest.raises(ValueError, match="bool"):
        df.filter(lambda x: {"keep": x * 2.0}).collect()
    with pytest.raises(ValueError, match="exactly one"):
        df.filter(lambda x: {"a": x > 1.0, "b": x > 2.0})


def test_host_string_columns_ride_through_fused_chains():
    # host-resident string columns never feed programs; they must pass
    # through a fused run (and subset through a fused filter) unchanged
    def build():
        fr = tfs.frame_from_rows(
            [{"x": float(i), "tag": f"r{i}"} for i in range(12)],
            num_blocks=2,
        )
        f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
        f2 = f1.map_rows(lambda y: {"z": y * 3.0})
        return f2.filter(lambda z: {"keep": z > 9.0}).collect()

    _rows_equal(build(), _unfused(build))


# ---------------------------------------------------------------------------
# one dispatch per block (jit-cache accounting)
# ---------------------------------------------------------------------------

def test_fused_chain_compiles_once_per_block_shape():
    n = 32  # divisible: every block has the same shape
    fr = tfs.frame_from_arrays(
        {"x": np.arange(n, dtype=np.float32)}, num_blocks=4
    )
    p1 = tfs.compile_program(lambda x: {"y": x + 1.0}, fr)
    f1 = tfs.map_blocks(p1, fr)
    p2 = tfs.compile_program(lambda y: {"z": y * 2.0}, f1)
    f2 = tfs.map_blocks(p2, f1)
    p3 = tfs.compile_program(lambda z: {"w": z - 3.0}, f2)

    def build():
        return tfs.map_blocks(p3, tfs.map_blocks(p2, tfs.map_blocks(p1, fr)))

    m0, h0 = _JIT_MISSES.value, _JIT_HITS.value
    build().blocks()
    misses = _JIT_MISSES.value - m0
    hits = _JIT_HITS.value - h0
    # ONE composed program, compiled once (one block shape), dispatched
    # once per block — not 3 stages × 4 blocks
    assert misses == 1, misses
    assert hits == 3, hits  # remaining 3 blocks reuse the executable

    # steady-state: rebuilding the chain from the same stage Programs
    # reuses the cached fused program — zero fresh compiles
    m1 = _JIT_MISSES.value
    build().blocks()
    assert _JIT_MISSES.value - m1 == 0


def test_fused_stage_metrics_and_trace():
    from tensorframes_tpu.observability import events

    fr = tfs.frame_from_arrays(
        {"x": np.arange(16, dtype=np.float32)}, num_blocks=2
    )
    fused0 = _snap()[("tftpu_plan_fused_stages_total", ())]["value"]
    events.clear()
    events.enable()
    try:
        f2 = tfs.map_blocks(
            lambda y: {"z": y * 2.0},
            tfs.map_blocks(lambda x: {"y": x + 1.0}, fr),
        )
        f2.blocks()
    finally:
        events.disable()
    assert (
        _snap()[("tftpu_plan_fused_stages_total", ())]["value"]
        == fused0 + 2
    )
    names = {e["name"] for e in events.TRACER.to_chrome_trace()["traceEvents"]}
    assert "plan.lower" in names and "plan.execute" in names


# ---------------------------------------------------------------------------
# select pushdown: pruned columns are never gathered or computed
# ---------------------------------------------------------------------------

def test_select_pushdown_skips_pruned_stage_and_gather():
    wide = 256
    n = 64

    def build():
        fr = tfs.frame_from_arrays(
            {
                "x": np.arange(n, dtype=np.float32),
                "w": np.zeros((n, wide), dtype=np.float32),
            },
            num_blocks=2,
        )
        f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
        f2 = tfs.map_blocks(lambda w: {"big": w * 2.0}, f1)
        return f2.select(["y"]).collect()

    g0 = _GATHER_BYTES.value
    fused = build()
    fused_bytes = _GATHER_BYTES.value - g0

    g1 = _GATHER_BYTES.value
    unfused = _unfused(build)
    unfused_bytes = _GATHER_BYTES.value - g1

    _rows_equal(fused, unfused)
    w_bytes = n * wide * 4
    # per-stage execution gathers the wide column for the pruned stage;
    # the plan never does — w is dead once select drops 'big'
    assert unfused_bytes >= w_bytes
    assert fused_bytes <= unfused_bytes - w_bytes

    assert (
        _snap()[("tftpu_plan_intermediate_bytes_avoided_total", ())]["value"]
        > 0
    )


def test_select_over_pending_frame_prunes_intermediate():
    fr = tfs.frame_from_arrays(
        {"x": np.arange(10, dtype=np.float64)}, num_blocks=2
    )
    f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
    f2 = f1.map_rows(lambda y: {"z": y * 2.0})
    out = f2.select(["z"])
    blocks = out.blocks()
    assert all(set(b.keys()) == {"z"} for b in blocks)
    np.testing.assert_array_equal(
        out.column_values("z"), (np.arange(10, dtype=np.float64) + 1) * 2
    )


# ---------------------------------------------------------------------------
# barriers split the plan, never change semantics
# ---------------------------------------------------------------------------

def test_trim_map_is_a_barrier_and_chain_still_correct():
    def build():
        fr = tfs.frame_from_arrays(
            {"x": np.arange(12, dtype=np.float32)}, num_blocks=2
        )
        f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
        trimmed = f1.map_blocks_trimmed(lambda y: {"t": y[:3]})
        return tfs.map_blocks(lambda t: {"u": t * 2.0}, trimmed).collect()

    fused = build()
    assert len(fused) == 6  # 2 blocks × 3 trimmed rows
    _rows_equal(fused, _unfused(build))


def test_host_callback_stage_falls_back_per_stage():
    calls = []

    def cb(a):
        calls.append(len(a))
        return np.asarray(a) + 1.0

    def cb_stage(y):
        return {
            "c": jax.pure_callback(
                cb, jax.ShapeDtypeStruct(y.shape, y.dtype), y
            )
        }

    fr = tfs.frame_from_arrays(
        {"x": np.arange(8, dtype=np.float32)}, num_blocks=2
    )
    f1 = tfs.map_blocks(lambda x: {"y": x * 2.0}, fr)
    f2 = tfs.map_blocks(cb_stage, f1)
    f3 = tfs.map_blocks(lambda c: {"d": c - 1.0}, f2)
    got = [r["d"] for r in f3.collect()]
    assert got == [float(x) * 2.0 for x in range(8)]
    assert calls  # the callback genuinely ran


def test_plan_dropped_after_force_frees_chain():
    fr = tfs.frame_from_arrays(
        {"x": np.arange(6, dtype=np.float32)}, num_blocks=2
    )
    f2 = tfs.map_blocks(
        lambda y: {"z": y * 2.0},
        tfs.map_blocks(lambda x: {"y": x + 1.0}, fr),
    )
    assert f2._plan is not None
    f2.blocks()
    # the recorded chain is spent on materialization — keeping it would
    # pin the source frame's buffers for this frame's lifetime
    assert f2._plan is None


def test_pruned_callback_stage_still_fires_side_effect():
    calls = []

    def cb(a):
        calls.append(len(a))
        return np.asarray(a) + 1.0

    def cb_stage(y):
        return {
            "c": jax.pure_callback(
                cb, jax.ShapeDtypeStruct(y.shape, y.dtype), y
            )
        }

    fr = tfs.frame_from_arrays(
        {"x": np.arange(8, dtype=np.float32)}, num_blocks=2
    )
    f1 = tfs.map_blocks(lambda x: {"y": x * 2.0}, fr)
    f2 = tfs.map_blocks(cb_stage, f1)
    # select drops the callback's output — pushdown must NOT elide the
    # stage (TFTPU_FUSION=0 executes it, so fusion must too)
    out = f2.select(["y"]).collect()
    assert [r["y"] for r in out] == [float(x) * 2.0 for x in range(8)]
    assert calls, "pushdown elided the host callback's side effect"


def test_fusion_knob_honored_at_force_time():
    fr = tfs.frame_from_arrays(
        {"x": np.arange(12, dtype=np.float32)}, num_blocks=2
    )
    chain = tfs.map_blocks(
        lambda y: {"z": y * 2.0},
        tfs.map_blocks(lambda x: {"y": x + 1.0}, fr),
    )
    assert chain._plan is not None  # recorded while fusion was on
    fused0 = _snap()[("tftpu_plan_fused_stages_total", ())]["value"]
    tfs.configure(plan_fusion=False)
    try:
        rows = chain.collect()
    finally:
        tfs.configure(plan_fusion=True)
    assert [r["z"] for r in rows] == [(x + 1.0) * 2.0 for x in range(12)]
    # the escape hatch ruled fusion out even for the pre-recorded chain
    assert (
        _snap()[("tftpu_plan_fused_stages_total", ())]["value"] == fused0
    )


def test_ragged_source_falls_back_and_matches():
    def build():
        rows = [
            {"v": np.arange(k, dtype=np.float64)} for k in (2, 5, 2, 3, 5)
        ]
        fr = tfs.frame_from_rows(rows, num_blocks=1)
        g1 = tfs.map_rows(lambda v: {"s": v.sum()}, fr)
        return g1.map_rows(lambda s: {"t": s + 1.0}).collect()

    _rows_equal(build(), _unfused(build))


def test_branched_chain_materializes_shared_prefix_once():
    # DAG-shaped pipelines: the first consumer fuses through the shared
    # frame; later consumers source on it, so forcing them caches the
    # shared prefix instead of re-running it inside every branch
    fr = tfs.frame_from_arrays(
        {"x": np.arange(10, dtype=np.float32)}, num_blocks=2
    )
    f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
    f2 = tfs.map_blocks(lambda y: {"z": y * 2.0}, f1)  # extends f1
    f3 = tfs.map_blocks(lambda y: {"w": y - 1.0}, f1)  # branches off
    np.testing.assert_array_equal(
        f2.column_values("z"), (np.arange(10, dtype=np.float32) + 1) * 2
    )
    assert not f1.is_materialized  # branch 1 fused through it
    np.testing.assert_array_equal(
        f3.column_values("w"), np.arange(10, dtype=np.float32)
    )
    assert f1.is_materialized  # branch 2 sourced on (and cached) it
    # a third branch reuses the cached prefix
    f4 = tfs.map_blocks(lambda y: {"v": y * 0.0}, f1)
    np.testing.assert_array_equal(f4.column_values("v"), np.zeros(10))


def test_all_pruned_segment_dispatches_nothing():
    # select pushdown pruning EVERY stage degrades to a projection —
    # no composed program is compiled or dispatched for it
    fr = tfs.frame_from_arrays(
        {"x": np.arange(8, dtype=np.float32)}, num_blocks=2
    )
    out = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr).select(["x"])
    m0 = _JIT_MISSES.value
    h0 = _JIT_HITS.value
    blocks = out.blocks()
    assert _JIT_MISSES.value - m0 == 0
    assert _JIT_HITS.value - h0 == 0
    assert all(set(b.keys()) == {"x"} for b in blocks)
    np.testing.assert_array_equal(
        out.column_values("x"), np.arange(8, dtype=np.float32)
    )


def test_lint_plan_sees_to_host_with_num_blocks():
    fr = tfs.frame_from_arrays({"x": np.arange(8, dtype=np.float32)})
    f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
    f2 = tfs.map_blocks(
        lambda y: {"z": y * 2.0}, f1.to_host(num_blocks=2)
    )
    rep = tfs.lint_plan(f2)
    assert any(
        d.code == "TFG107" and "to_host" in d.message for d in rep
    )


def test_forced_intermediate_re_roots_the_chain():
    fr = tfs.frame_from_arrays(
        {"x": np.arange(6, dtype=np.float32)}, num_blocks=2
    )
    f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
    f1.blocks()  # user forces the intermediate
    assert f1.is_materialized
    f2 = tfs.map_blocks(lambda y: {"z": y * 2.0}, f1)
    np.testing.assert_array_equal(
        f2.column_values("z"), (np.arange(6, dtype=np.float32) + 1) * 2
    )


# ---------------------------------------------------------------------------
# TFG107 fusion-barrier lint
# ---------------------------------------------------------------------------

def test_lint_plan_names_materialization_barrier():
    fr = tfs.frame_from_arrays({"x": np.arange(8, dtype=np.float32)})
    f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
    f2 = tfs.map_blocks(lambda y: {"z": y * 2.0}, f1.to_host())
    rep = tfs.lint_plan(f2)
    hits = [d for d in rep if d.code == "TFG107"]
    assert hits and "to_host" in hits[0].message
    assert "to_host" in hits[0].explain()  # explain() names the barrier


def test_lint_plan_names_callback_barrier():
    def cb_stage(y):
        return {
            "c": jax.pure_callback(
                lambda a: np.asarray(a) + 1.0,
                jax.ShapeDtypeStruct(y.shape, y.dtype),
                y,
            )
        }

    fr = tfs.frame_from_arrays({"x": np.arange(8, dtype=np.float32)})
    f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
    f2 = tfs.map_blocks(cb_stage, f1)
    f3 = tfs.map_blocks(lambda c: {"d": c * 3.0}, f2)
    rep = tfs.lint_plan(f3)
    assert any(
        d.code == "TFG107" and "callback" in d.message for d in rep
    )


def test_lint_plan_clean_chain_has_no_findings():
    fr = tfs.frame_from_arrays({"x": np.arange(8, dtype=np.float32)})
    f2 = tfs.map_blocks(
        lambda y: {"z": y * 2.0},
        tfs.map_blocks(lambda x: {"y": x + 1.0}, fr),
    )
    assert len(tfs.lint_plan(f2)) == 0
    assert len(tfs.lint_plan(fr)) == 0  # plan-less frames lint clean


def test_tfg107_counter_is_preregistered():
    prom = REGISTRY.to_prometheus()
    assert 'tftpu_analysis_diagnostics_total{code="TFG107"}' in prom
    for name in (
        "tftpu_plan_fused_stages_total",
        "tftpu_plan_intermediate_bytes_avoided_total",
        "tftpu_plan_lowering_seconds",
        "tftpu_plan_fallback_total",
    ):
        assert name in prom


# ---------------------------------------------------------------------------
# plan surface
# ---------------------------------------------------------------------------

def test_explain_plan_renders_chain():
    fr = tfs.frame_from_arrays({"x": np.arange(4, dtype=np.float32)})
    f2 = tfs.map_blocks(
        lambda y: {"z": y * 2.0},
        tfs.map_blocks(lambda x: {"y": x + 1.0}, fr),
    ).select(["z"])
    text = tfs.explain_plan(f2)
    assert "map_blocks(y)" in text
    assert "map_blocks(z)" in text
    assert "select(['z'])" in text


def test_fusion_off_records_no_plan():
    tfs.configure(plan_fusion=False)
    fr = tfs.frame_from_arrays({"x": np.arange(4, dtype=np.float32)})
    f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, fr)
    assert getattr(f1, "_plan", None) is None
    np.testing.assert_array_equal(
        f1.column_values("y"), np.arange(4, dtype=np.float32) + 1
    )


def test_sharded_chain_keeps_mesh_and_matches():
    try:
        fr = tfs.frame_from_arrays(
            {"x": np.arange(16, dtype=np.float32)}
        ).to_device()
    except AttributeError:
        pytest.skip("mesh creation unavailable on this jax build")

    def build(frame):
        f1 = tfs.map_blocks(lambda x: {"y": x + 1.0}, frame)
        return f1.map_rows(lambda y: {"z": y * 2.0})

    out = build(fr)
    assert out.is_sharded  # map chains keep the mesh
    got = np.asarray(out.column_values("z"))
    exp_frame = _unfused(lambda: build(fr))
    np.testing.assert_array_equal(
        got, np.asarray(exp_frame.column_values("z"))
    )
