"""Sharded on-device aggregate (ops/device_agg.py): dense per-shard
segment reduction + one collective over the 8-device virtual mesh, checked
against the host sort path on the same data."""

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.ops import device_agg


def _dsl_agg(frame, col, op, name=None):
    name = name or col
    with tfs.with_graph():
        v_input = tfs.block(frame, col, tf_name=f"{name}_input")
        fetch = op(v_input, axis=0, name=name)
        return tfs.aggregate(fetch, frame.group_by("k"))


def _rows(agg, keys=("k",)):
    return sorted(
        tuple(r[c] for c in (*keys, *sorted(set(agg.columns) - set(keys))))
        for r in agg.collect()
    )


@pytest.fixture
def data():
    rng = np.random.default_rng(0)
    n = 1000
    return {
        "k": rng.integers(-3, 12, n),
        "v": rng.standard_normal(n).astype(np.float32),
    }


def test_device_path_taken_and_matches_host(data):
    host = tfs.frame_from_arrays(dict(data))
    dev = tfs.frame_from_arrays(dict(data)).to_device()
    assert dev.is_sharded

    for op in (tfs.reduce_sum, tfs.reduce_min, tfs.reduce_max, tfs.reduce_mean):
        a_host = _dsl_agg(host, "v", op)
        a_dev = _dsl_agg(dev, "v", op)
        hk = np.asarray(a_host.column_values("k"))
        dk = np.asarray(a_dev.column_values("k"))
        np.testing.assert_array_equal(hk, dk)  # same group order (lex)
        np.testing.assert_allclose(
            np.asarray(a_dev.column_values("v")),
            np.asarray(a_host.column_values("v")),
            rtol=1e-5, atol=1e-6,
        )


def test_try_aggregate_device_is_used(data, monkeypatch):
    dev = tfs.frame_from_arrays(dict(data)).to_device()
    called = {}
    real = device_agg.try_aggregate_device

    def spy(*a, **kw):
        called["yes"] = True
        return real(*a, **kw)

    monkeypatch.setattr(device_agg, "try_aggregate_device", spy)
    _dsl_agg(dev, "v", tfs.reduce_sum)
    assert called.get("yes")


def test_tail_rows_fold_in(data):
    # 1001 rows over 8 devices → 1 host tail row; result must include it
    d = {k: np.concatenate([v, v[:1]]) for k, v in data.items()}
    host = tfs.frame_from_arrays(dict(d))
    dev = tfs.frame_from_arrays(dict(d)).to_device()
    assert dev.num_blocks == 2  # main + tail
    for op in (tfs.reduce_sum, tfs.reduce_min):
        np.testing.assert_allclose(
            np.asarray(_dsl_agg(dev, "v", op).column_values("v")),
            np.asarray(_dsl_agg(host, "v", op).column_values("v")),
            rtol=1e-5, atol=1e-6,
        )


def test_vector_values(data):
    rng = np.random.default_rng(1)
    d = {"k": data["k"], "v": rng.standard_normal((1000, 3)).astype(np.float32)}
    host = tfs.frame_from_arrays(dict(d))
    dev = tfs.frame_from_arrays(dict(d)).to_device()
    a_host = _dsl_agg(host, "v", tfs.reduce_sum)
    a_dev = _dsl_agg(dev, "v", tfs.reduce_sum)
    np.testing.assert_allclose(
        np.asarray(a_dev.column_values("v")),
        np.asarray(a_host.column_values("v")),
        rtol=1e-5, atol=1e-5,
    )


def test_multi_key_mixed_radix():
    rng = np.random.default_rng(2)
    n = 640
    d = {
        "a": rng.integers(0, 5, n),
        "b": rng.integers(10, 14, n),
        "v": rng.standard_normal(n).astype(np.float32),
    }
    host = tfs.frame_from_arrays(dict(d))
    dev = tfs.frame_from_arrays(dict(d)).to_device()

    def agg(fr):
        with tfs.with_graph():
            v_input = tfs.block(fr, "v", tf_name="v_input")
            return tfs.aggregate(
                tfs.reduce_sum(v_input, axis=0, name="v"),
                fr.group_by("a", "b"),
            )

    ah, ad = agg(host), agg(dev)
    for c in ("a", "b"):
        np.testing.assert_array_equal(
            np.asarray(ah.column_values(c)), np.asarray(ad.column_values(c))
        )
    np.testing.assert_allclose(
        np.asarray(ad.column_values("v")),
        np.asarray(ah.column_values("v")),
        rtol=1e-5, atol=1e-6,
    )


def test_huge_key_span_rides_dictionary_plan(data):
    # keys spanning > _KEY_LIMIT buckets exceed the dense plan but ride
    # the dictionary plan (K = #groups); results still match via the
    # public API
    d = dict(data)
    d["k"] = d["k"].copy()
    d["k"][0] = 5_000_000
    dev = tfs.frame_from_arrays(dict(d)).to_device()
    assert (
        device_agg.try_aggregate_device(
            dev, ["k"], ((("v"), "reduce_sum", 1),), ["v"]
        )
        is not None
    )
    a = _dsl_agg(dev, "v", tfs.reduce_sum)
    assert 5_000_000 in set(np.asarray(a.column_values("k")).tolist())


def test_wide_features_exceeding_table_limit_fall_back(data):
    """Both device plans decline when K × feature-elems exceeds the
    table limit; the host path answers."""
    n = len(data["k"])
    wide = np.ones((n, 4096), np.float32)
    d = {"k": data["k"].copy(), "v": wide}
    d["k"][0] = 5_000_000  # dense plan out (span), dict plan out (table)
    old = device_agg._TABLE_ELEM_LIMIT
    device_agg._TABLE_ELEM_LIMIT = 1 << 14
    try:
        dev = tfs.frame_from_arrays(dict(d)).to_device()
        assert (
            device_agg.try_aggregate_device(
                dev, ["k"], (("v", "reduce_sum", 2),), ["v"]
            )
            is None
        )
    finally:
        device_agg._TABLE_ELEM_LIMIT = old


def test_float_keys_fall_back():
    rng = np.random.default_rng(3)
    d = {
        "k": rng.standard_normal(64).astype(np.float32),
        "v": rng.standard_normal(64).astype(np.float32),
    }
    dev = tfs.frame_from_arrays(dict(d)).to_device()
    a = _dsl_agg(dev, "v", tfs.reduce_sum)
    assert len(a.collect()) == 64  # every float key unique → 64 groups


def test_multikey_span_overflow_rides_dictionary_plan():
    """Two huge-span key columns must not wrap the dense plan's bucket
    product past its gate (int64 overflow → K=0 'passes'); they skip to
    the dictionary plan, whose K is the distinct-group count, and the
    result matches the host path."""
    rng = np.random.default_rng(4)
    n = 64
    a = rng.integers(0, 10, n).astype(np.int64)
    b = rng.integers(0, 10, n).astype(np.int64)
    a[0], b[0] = -(2**31), -(2**31)
    a[1], b[1] = 2**31 - 1, 2**31 - 1
    d = {"a": a, "b": b, "v": np.ones(n, np.float32)}
    dev = tfs.frame_from_arrays(dict(d)).to_device()
    got = device_agg.try_aggregate_device(
        dev, ["a", "b"], (("v", "reduce_sum", 1),), ["v"]
    )
    assert got is not None
    key_cols, out_cols = got
    want = {}
    for ka, kb, v in zip(a, b, d["v"]):
        want[(int(ka), int(kb))] = want.get((int(ka), int(kb)), 0.0) + float(v)
    got_map = {
        (int(ka), int(kb)): float(v)
        for ka, kb, v in zip(key_cols["a"], key_cols["b"], out_cols["v"])
    }
    assert got_map == want

    with tfs.with_graph():
        v_input = tfs.block(dev, "v", tf_name="v_input")
        agg = tfs.aggregate(
            tfs.reduce_sum(v_input, axis=0, name="v"), dev.group_by("a", "b")
        )
    assert float(np.asarray(agg.column_values("v")).sum()) == n


def test_groupby_count_sharded():
    rng = np.random.default_rng(5)
    k = rng.integers(0, 5, 640)
    dev = tfs.frame_from_arrays(
        {"k": k, "v": rng.standard_normal(640).astype(np.float32)}
    ).to_device()
    counted = dev.group_by("k").count()
    got = {r["k"]: r["count"] for r in counted.collect()}
    for key in np.unique(k):
        assert got[int(key)] == int((k == key).sum())


def test_int8_full_span_keys_no_wrap():
    """int8 keys spanning -128..127: the 255-wide offset must widen
    before subtraction — a wrap would silently drop whole groups."""
    keys = np.array(([-128] * 8 + [127] * 8) * 100, np.int8)
    vals = np.ones(len(keys), np.float32)
    dev = tfs.frame_from_arrays({"k": keys, "v": vals}).to_device()
    got = device_agg.try_aggregate_device(
        dev, ["k"], (("v", "reduce_sum", 1),), ["v"]
    )
    assert got is not None
    key_cols, out_cols = got
    assert list(key_cols["k"]) == [-128, 127]
    assert list(out_cols["v"]) == [800.0, 800.0]


def test_repeated_aggregates_hit_memos_and_stay_correct():
    """Round 5: repeated aggregates over the same immutable device
    columns memoize the dense plan's span probe and the dictionary
    plan's encode+staged ids (each a relay round trip per call on
    tunnel-attached chips). Results must be IDENTICAL across calls and
    the memos must actually populate."""
    rng = np.random.default_rng(11)
    # dense plan (int keys): minmax memo
    di = tfs.frame_from_arrays(
        {"k": rng.integers(0, 32, 4096),
         "v": rng.standard_normal(4096).astype(np.float32)}
    ).to_device()
    first = {r["k"]: r["v"] for r in _dsl_agg(di, "v", tfs.reduce_sum).collect()}
    assert any(id(b["k"]) in device_agg._minmax_memo for b in di.blocks())
    for _ in range(3):
        again = {
            r["k"]: r["v"] for r in _dsl_agg(di, "v", tfs.reduce_sum).collect()
        }
        assert again == first
    # dictionary plan (huge-span keys): encode memo
    dk = tfs.frame_from_arrays(
        {"k": rng.integers(0, 2**40, 4096),
         "v": rng.standard_normal(4096).astype(np.float32)}
    ).to_device()
    want = {r["k"]: r["v"] for r in _dsl_agg(dk, "v", tfs.reduce_sum).collect()}
    assert any(
        id(b["k"]) in {i for key in device_agg._dict_encode_memo for i in key}
        for b in dk.blocks()
    )
    for _ in range(3):
        got = {
            r["k"]: r["v"] for r in _dsl_agg(dk, "v", tfs.reduce_sum).collect()
        }
        assert got == want
