"""Causal LM generation: KV-cache decode vs the naive full-forward oracle,
map_blocks integration, and sampling behavior."""

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.models import generation as gen
from tensorframes_tpu.models import transformer as tr


@pytest.fixture(scope="module")
def setup():
    cfg = gen.gpt_tiny()
    params = tr.init_params(cfg, seed=0)
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size, (3, 8)).astype(np.int32)
    return cfg, params, prompts


def test_cached_decode_matches_naive_oracle(setup):
    """The one-program KV-cache scan must produce exactly the greedy
    tokens of the O(n²) re-run-everything reference."""
    cfg, params, prompts = setup
    got = np.asarray(gen.generate(cfg, params, prompts, 12))
    want = np.asarray(gen.generate_naive(cfg, params, prompts, 12))
    np.testing.assert_array_equal(got, want)


def test_shapes_dtype_and_determinism(setup):
    cfg, params, prompts = setup
    a = np.asarray(gen.generate(cfg, params, prompts, 5))
    b = np.asarray(gen.generate(cfg, params, prompts, 5))
    assert a.shape == (3, 5) and a.dtype == np.int32
    np.testing.assert_array_equal(a, b)  # greedy is deterministic
    assert (a >= 0).all() and (a < cfg.vocab_size).all()


def test_single_token(setup):
    cfg, params, prompts = setup
    a = np.asarray(gen.generate(cfg, params, prompts, 1))
    assert a.shape == (3, 1)
    np.testing.assert_array_equal(
        a, np.asarray(gen.generate_naive(cfg, params, prompts, 1))
    )


def test_sampling_respects_seed(setup):
    cfg, params, prompts = setup
    a = np.asarray(gen.generate(cfg, params, prompts, 6, temperature=1.0, seed=1))
    b = np.asarray(gen.generate(cfg, params, prompts, 6, temperature=1.0, seed=1))
    c = np.asarray(gen.generate(cfg, params, prompts, 6, temperature=1.0, seed=2))
    np.testing.assert_array_equal(a, b)
    assert (a != c).any()  # different seed should diverge somewhere


def test_length_guard(setup):
    cfg, params, prompts = setup
    with pytest.raises(ValueError, match="exceeds"):
        gen.generate(cfg, params, prompts, cfg.max_seq_len)


def test_generate_via_map_blocks(setup):
    """A frame of prompt rows → a generated-continuation column, through
    the same verb as every other workload."""
    cfg, params, prompts = setup
    df = tfs.frame_from_arrays({"prompts": prompts}, num_blocks=1)
    out = tfs.map_blocks(gen.generate_program(cfg, params, 4), df)
    gen_col = np.stack([r["generated"] for r in out.collect()])
    want = np.asarray(gen.generate(cfg, params, prompts, 4))
    np.testing.assert_array_equal(gen_col, want)


def test_sampling_differs_across_blocks(setup):
    """Multi-block frames fold block content into the sampling seed, so
    distinct blocks don't replay the same RNG stream."""
    cfg, params, _ = setup
    rng = np.random.default_rng(7)
    prompts = rng.integers(0, cfg.vocab_size, (4, 8)).astype(np.int32)
    df = tfs.frame_from_arrays({"prompts": prompts}, num_blocks=2)
    out = tfs.map_blocks(
        gen.generate_program(cfg, params, 8, temperature=1.0, seed=3), df
    )
    blocks = out.blocks()
    assert len(blocks) == 2
    # the two blocks hold different prompts → different salts → streams
    # diverge (probabilistic but overwhelmingly likely over 2x8 tokens)
    a, b = (np.asarray(blk["generated"]) for blk in blocks)
    assert a.shape == b.shape == (2, 8)
    assert not np.array_equal(a, b)
