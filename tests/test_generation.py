"""Causal LM generation: KV-cache decode vs the naive full-forward oracle,
map_blocks integration, and sampling behavior."""

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.models import generation as gen
from tensorframes_tpu.models import transformer as tr


@pytest.fixture(scope="module")
def setup():
    cfg = gen.gpt_tiny()
    params = tr.init_params(cfg, seed=0)
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size, (3, 8)).astype(np.int32)
    return cfg, params, prompts


def test_cached_decode_matches_naive_oracle(setup):
    """The one-program KV-cache scan must produce exactly the greedy
    tokens of the O(n²) re-run-everything reference."""
    cfg, params, prompts = setup
    got = np.asarray(gen.generate(cfg, params, prompts, 12))
    want = np.asarray(gen.generate_naive(cfg, params, prompts, 12))
    np.testing.assert_array_equal(got, want)


def test_shapes_dtype_and_determinism(setup):
    cfg, params, prompts = setup
    a = np.asarray(gen.generate(cfg, params, prompts, 5))
    b = np.asarray(gen.generate(cfg, params, prompts, 5))
    assert a.shape == (3, 5) and a.dtype == np.int32
    np.testing.assert_array_equal(a, b)  # greedy is deterministic
    assert (a >= 0).all() and (a < cfg.vocab_size).all()


def test_single_token(setup):
    cfg, params, prompts = setup
    a = np.asarray(gen.generate(cfg, params, prompts, 1))
    assert a.shape == (3, 1)
    np.testing.assert_array_equal(
        a, np.asarray(gen.generate_naive(cfg, params, prompts, 1))
    )


def test_sampling_respects_seed(setup):
    cfg, params, prompts = setup
    a = np.asarray(gen.generate(cfg, params, prompts, 6, temperature=1.0, seed=1))
    b = np.asarray(gen.generate(cfg, params, prompts, 6, temperature=1.0, seed=1))
    c = np.asarray(gen.generate(cfg, params, prompts, 6, temperature=1.0, seed=2))
    np.testing.assert_array_equal(a, b)
    assert (a != c).any()  # different seed should diverge somewhere


def test_length_guard(setup):
    cfg, params, prompts = setup
    with pytest.raises(ValueError, match="exceeds"):
        gen.generate(cfg, params, prompts, cfg.max_seq_len)


def test_generate_via_map_blocks(setup):
    """A frame of prompt rows → a generated-continuation column, through
    the same verb as every other workload."""
    cfg, params, prompts = setup
    df = tfs.frame_from_arrays({"prompts": prompts}, num_blocks=1)
    out = tfs.map_blocks(gen.generate_program(cfg, params, 4), df)
    gen_col = np.stack([r["generated"] for r in out.collect()])
    want = np.asarray(gen.generate(cfg, params, prompts, 4))
    np.testing.assert_array_equal(gen_col, want)


def test_sampling_differs_across_blocks(setup):
    """Multi-block frames fold block content into the sampling seed, so
    distinct blocks don't replay the same RNG stream."""
    cfg, params, _ = setup
    rng = np.random.default_rng(7)
    prompts = rng.integers(0, cfg.vocab_size, (4, 8)).astype(np.int32)
    df = tfs.frame_from_arrays({"prompts": prompts}, num_blocks=2)
    out = tfs.map_blocks(
        gen.generate_program(cfg, params, 8, temperature=1.0, seed=3), df
    )
    blocks = out.blocks()
    assert len(blocks) == 2
    # the two blocks hold different prompts → different salts → streams
    # diverge (probabilistic but overwhelmingly likely over 2x8 tokens)
    a, b = (np.asarray(blk["generated"]) for blk in blocks)
    assert a.shape == b.shape == (2, 8)
    assert not np.array_equal(a, b)


def test_int8_kv_cache_decode_close_and_smaller():
    """VERDICT r3 #4: the int8 KV cache must (a) shrink the cache's HBM
    footprint (the per-step traffic that grows with sequence), and
    (b) decode numerically close to the full-precision cache — scales
    commute out of the score contraction and fold into the softmax
    weights, so the math is the same modulo int8 rounding."""
    import jax.numpy as jnp

    from tensorframes_tpu.models import generation as gen
    from tensorframes_tpu.models import transformer as tr

    cfg = gen.gpt_tiny()
    params = tr.init_params(cfg, seed=0)
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size, (2, 5)).astype(np.int32)

    # footprint: int8 cache well under half the bf16 cache (1 byte + the
    # 1/head_dim scale overhead vs 2 bytes), 4x under an f32 cache
    c_full = gen.init_kv_cache(cfg, 2, length=12)
    c_q = gen.init_kv_cache(cfg, 2, length=12, quant=True)
    assert gen.kv_cache_nbytes(c_q) < 0.6 * gen.kv_cache_nbytes(c_full)

    # prefill hidden states: quantization noise stays small
    hs_f, _ = gen._forward_cached(cfg, params, jnp.asarray(prompts), c_full, 0)
    hs_q, _ = gen._forward_cached(cfg, params, jnp.asarray(prompts), c_q, 0)
    err = float(jnp.linalg.norm(hs_q.astype(jnp.float32) - hs_f.astype(jnp.float32)))
    ref = float(jnp.linalg.norm(hs_f.astype(jnp.float32)))
    assert err / ref < 0.05, f"relative error {err / ref:.3f}"

    # end-to-end greedy decode agrees with the full-precision cache on
    # a large majority of tokens (greedy argmax can flip on ties)
    out_f = np.asarray(gen.generate(cfg, params, prompts, 8))
    out_q = np.asarray(gen.generate(cfg, params, prompts, 8, kv_quant=True))
    assert out_q.shape == out_f.shape == (2, 8)
    agree = float((out_f == out_q).mean())
    assert agree >= 0.75, f"token agreement {agree:.2f}"


def test_int8_kv_cache_with_quantized_weights():
    """The int8 cache composes with weight-only int8 params (the bench's
    int8 decode config): runs end to end, right shape/dtype."""
    from tensorframes_tpu.models import generation as gen
    from tensorframes_tpu.models import transformer as tr

    cfg = gen.gpt_tiny()
    params = tr.quantize_params(tr.init_params(cfg, seed=0))
    rng = np.random.default_rng(1)
    prompts = rng.integers(0, cfg.vocab_size, (2, 4)).astype(np.int32)
    out = np.asarray(
        gen.generate(cfg, params, prompts, 6, kv_quant=True)
    )
    assert out.shape == (2, 6) and out.dtype == np.int32
    # the program variant threads the flag through too
    prog = gen.generate_program(cfg, params, 6, kv_quant=True)
    out2 = prog(prompts)
    assert np.asarray(out2["generated"]).shape == (2, 6)
