"""Dtype registry tests (≙ datatypes.scala contracts: closed registry,
no implicit casting, host-only strings)."""

import numpy as np
import pytest

from tensorframes_tpu import dtypes as dt


def test_registry_roundtrip():
    for t in dt.device_types():
        assert dt.from_numpy(t.np_dtype) is t
        assert dt.by_name(t.name) is t


def test_core_four_present():
    # the reference's supported set (datatypes.scala:265-267)
    for name in ("float64", "float32", "int32", "int64"):
        assert dt.by_name(name).device


def test_host_only_types():
    assert not dt.string.device
    assert not dt.binary.device
    with pytest.raises(TypeError):
        dt.string.jax_dtype


def test_python_value_inference():
    assert dt.from_python_value(1.5) is dt.float64
    assert dt.from_python_value(3) is dt.int64
    assert dt.from_python_value(True) is dt.bool_
    assert dt.from_python_value("s") is dt.string
    assert dt.from_python_value(b"b") is dt.binary
    assert dt.from_python_value(np.float32(1)) is dt.float32


def test_unsupported_rejected():
    with pytest.raises(dt.UnsupportedTypeError):
        dt.from_numpy(np.complex128)
    with pytest.raises(dt.UnsupportedTypeError):
        dt.by_name("float128")


def test_bfloat16_column_end_to_end():
    """bf16 (the TPU-native compute dtype) rides frames and verbs."""
    import ml_dtypes
    import numpy as np

    import tensorframes_tpu as tfs

    x = np.arange(16, dtype=ml_dtypes.bfloat16)
    df = tfs.frame_from_arrays({"x": x}, num_blocks=2)
    assert df.schema["x"].dtype.name == "bfloat16"
    out = df.map_blocks(lambda x: {"y": x * 2})
    y = out.column_values("y")
    assert y.dtype == ml_dtypes.bfloat16
    assert y.astype(np.float32).tolist() == (np.arange(16) * 2.0).tolist()
    s = df.reduce_blocks(lambda x_input: {"x": x_input.sum(0)})
    assert float(s) == float(np.arange(16).sum())
