"""Dtype registry tests (≙ datatypes.scala contracts: closed registry,
no implicit casting, host-only strings)."""

import numpy as np
import pytest

from tensorframes_tpu import dtypes as dt


def test_registry_roundtrip():
    for t in dt.device_types():
        assert dt.from_numpy(t.np_dtype) is t
        assert dt.by_name(t.name) is t


def test_core_four_present():
    # the reference's supported set (datatypes.scala:265-267)
    for name in ("float64", "float32", "int32", "int64"):
        assert dt.by_name(name).device


def test_host_only_types():
    assert not dt.string.device
    assert not dt.binary.device
    with pytest.raises(TypeError):
        dt.string.jax_dtype


def test_python_value_inference():
    assert dt.from_python_value(1.5) is dt.float64
    assert dt.from_python_value(3) is dt.int64
    assert dt.from_python_value(True) is dt.bool_
    assert dt.from_python_value("s") is dt.string
    assert dt.from_python_value(b"b") is dt.binary
    assert dt.from_python_value(np.float32(1)) is dt.float32


def test_unsupported_rejected():
    with pytest.raises(dt.UnsupportedTypeError):
        dt.from_numpy(np.complex128)
    with pytest.raises(dt.UnsupportedTypeError):
        dt.by_name("float128")
