"""Full-scale AOT compile checks (VERDICT r2 #2): BASELINE configs 4-5
at their REAL sizes — full-width Inception-v3 at 299x299 and BERT-base —
must lower + compile without pathological constant-folding stalls, on
any backend (CPU included). The class of bug this catches: the round-2
``ops/windows.py`` fix, where XLA constant-folded a full-size avg-pool
per shape and stalled 8-12s — found at 1/8 scale; nothing before this
test proved full scale held no more of them.

Opt-in (slow: ~2-4 min total on CPU): run with ``TFTPU_FULLSCALE=1``.
Measured on this round's container (CPU): inception lower 1.3s +
compile 6.4s; bert_base lower+compile 68s. Bounds are ~4x those.
"""

import os
import time

import numpy as np
import pytest

_ENABLED = os.environ.get("TFTPU_FULLSCALE", "") == "1"
pytestmark = pytest.mark.skipif(
    not _ENABLED, reason="full-scale AOT compile is opt-in (TFTPU_FULLSCALE=1)"
)


def test_inception_299_full_width_compiles():
    import jax

    from tensorframes_tpu.models import inception as inc

    cfg = inc.inception_v3(channel_scale=1.0)
    params = inc.init_params(cfg, seed=0)
    prog = inc.scoring_program(cfg, params)
    x = jax.ShapeDtypeStruct((8, 299, 299, 3), np.float32)
    t0 = time.time()
    compiled = jax.jit(lambda im: prog(im)).lower(x).compile()
    dt = time.time() - t0
    assert dt < 120, f"inception-299 full-width compile took {dt:.0f}s"
    n_ops = len(compiled.as_text().splitlines())
    assert n_ops > 500  # sanity: the whole network lowered, not a stub


def test_bert_base_row_program_compiles():
    import jax

    from tensorframes_tpu.models import transformer as tr

    cfg = tr.bert_base()
    params = tr.init_params(cfg, seed=0)
    rowprog = tr.embed_row_program(cfg, params)
    tok = jax.ShapeDtypeStruct((16, 128), np.int32)
    t0 = time.time()
    compiled = jax.jit(jax.vmap(lambda t: rowprog(t))).lower(tok).compile()
    dt = time.time() - t0
    assert dt < 300, f"bert-base compile took {dt:.0f}s"
    assert len(compiled.as_text().splitlines()) > 1000
