"""validation.py error contract (ISSUE 3 satellite): every
``ValidationError`` branch asserted via ``pytest.raises(match=...)`` on
BOTH halves of the message — what the frame has ("available": columns /
got-inputs) and what the program asked for ("requested": placeholders /
expected inputs) — the reference's ``SchemaTransforms`` contract of
enumerating both sides of every mismatch (DebugRowOps.scala:53-273).
"""

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu import dtypes as dt
from tensorframes_tpu.program import Program, TensorSpec
from tensorframes_tpu.shape import Shape
from tensorframes_tpu.validation import (
    ValidationError,
    validate_map,
    validate_reduce_blocks,
    validate_reduce_rows,
)


def _noop(feeds):
    return feeds


def _scalar_col_schema():
    """One float32 column 'x' of scalar cells (block shape [?])."""
    return tfs.frame_from_arrays({"x": np.arange(6, dtype=np.float32)}).schema


def _vector_col_schema():
    """One float32 column 'y' of (2,)-vector cells (block shape [?,2])."""
    return tfs.frame_from_arrays(
        {"y": np.zeros((6, 2), dtype=np.float32)}
    ).schema


# ---------------------------------------------------------------------------
# validate_map
# ---------------------------------------------------------------------------

def test_map_unmatched_input_names_both_sides():
    program = Program(_noop, [TensorSpec("zz", dt.float32, Shape([-1]))])
    with pytest.raises(
        ValidationError,
        match=r"(?s)Program input 'zz' does not match any column"
              r".*Graph inputs: \['zz'\].*frame columns: \['x'\]",
    ):
        validate_map(program, _scalar_col_schema(), block=True)


def test_map_dtype_mismatch_names_both_dtypes():
    program = Program(_noop, [TensorSpec("x", dt.float64, Shape([-1]))])
    with pytest.raises(
        ValidationError,
        match=r"Placeholder 'x' has dtype float64 but column 'x' has "
              r"dtype float32\. No implicit casting",
    ):
        validate_map(program, _scalar_col_schema(), block=True)


def test_map_rank_mismatch_names_both_ranks():
    program = Program(_noop, [TensorSpec("x", dt.float32, Shape([-1, 3]))])
    with pytest.raises(
        ValidationError,
        match=r"(?s)Placeholder 'x' has rank 2 \(shape \[\?,3\]\) but the "
              r"column's block shape is \[\?\] \(rank 1\)",
    ):
        validate_map(program, _scalar_col_schema(), block=True)


def test_map_incompatible_shape_names_both_shapes():
    program = Program(_noop, [TensorSpec("y", dt.float32, Shape([-1, 3]))])
    with pytest.raises(
        ValidationError,
        match=r"(?s)Placeholder 'y' declares shape \[\?,3\] which is "
              r"incompatible with column shape \[\?,2\]",
    ):
        validate_map(program, _vector_col_schema(), block=True)


def test_map_output_collision_names_outputs_and_columns():
    program = Program(
        _noop,
        [TensorSpec("x", dt.float32, Shape([-1]))],
        outputs=[TensorSpec("x", dt.float32, Shape([-1]))],
    )
    with pytest.raises(
        ValidationError,
        match=r"(?s)Output name\(s\) \['x'\] already exist as column\(s\)"
              r".*\(columns: \['x'\]\).*must all differ",
    ):
        validate_map(program, _scalar_col_schema(), block=True)


def test_map_scalar_block_output_rejected_with_alternatives():
    program = Program(
        _noop,
        [TensorSpec("x", dt.float32, Shape([-1]))],
        outputs=[TensorSpec("s", dt.float32, Shape(()))],
    )
    with pytest.raises(
        ValidationError,
        match=r"(?s)output 's' is a scalar; block outputs must have a "
              r"leading row dimension.*trim=True.*reduce_blocks",
    ):
        validate_map(program, _scalar_col_schema(), block=True)


def test_map_trim_allows_collision_and_scalars():
    program = Program(
        _noop,
        [TensorSpec("x", dt.float32, Shape([-1]))],
        outputs=[TensorSpec("x", dt.float32, Shape([-1]))],
    )
    validate_map(program, _scalar_col_schema(), block=True, trim=True)


def test_map_demotion_exception_is_sanctioned(monkeypatch):
    # the single allowed cast: f64 column → demoted f32 placeholder
    schema = tfs.frame_from_arrays(
        {"x": np.arange(6, dtype=np.float64)}
    ).schema
    program = Program(_noop, [TensorSpec("x", dt.float32, Shape([-1]))])
    tfs.configure(demote_x64_on_tpu="always")
    try:
        validate_map(program, schema, block=True)  # no raise
    finally:
        tfs.configure(demote_x64_on_tpu=False)
    with pytest.raises(ValidationError, match="No implicit casting"):
        validate_map(program, schema, block=True)  # demotion off: rejected


# ---------------------------------------------------------------------------
# validate_reduce_blocks
# ---------------------------------------------------------------------------

def test_reduce_blocks_unknown_fetch_names_both_sides():
    program = Program(
        _noop,
        [TensorSpec("nope_input", dt.float32, Shape([-1]))],
        outputs=[TensorSpec("nope", dt.float32, Shape(()))],
    )
    with pytest.raises(
        ValidationError,
        match=r"(?s)reduce_blocks output 'nope' must correspond to an "
              r"existing column.*Outputs: \['nope'\].*columns: \['y'\]",
    ):
        validate_reduce_blocks(program, _vector_col_schema())


def test_reduce_blocks_wrong_input_set_names_expected_and_got():
    program = Program(
        _noop,
        [TensorSpec("bad_input", dt.float32, Shape([-1, 2]))],
        outputs=[TensorSpec("y", dt.float32, Shape([2]))],
    )
    with pytest.raises(
        ValidationError,
        match=r"(?s)exactly one placeholder '<x>_input' per fetch"
              r".*Expected inputs: \['y_input'\].*got: \['bad_input'\]",
    ):
        validate_reduce_blocks(program, _vector_col_schema())


def test_reduce_blocks_placeholder_dtype_mismatch():
    program = Program(
        _noop,
        [TensorSpec("y_input", dt.float64, Shape([-1, 2]))],
        outputs=[TensorSpec("y", dt.float64, Shape([2]))],
    )
    with pytest.raises(
        ValidationError,
        match=r"Placeholder 'y_input' has dtype float64 but column 'y' "
              r"has dtype float32",
    ):
        validate_reduce_blocks(program, _vector_col_schema())


def test_reduce_blocks_fetch_vs_input_dtype_mismatch():
    program = Program(
        _noop,
        [TensorSpec("y_input", dt.float32, Shape([-1, 2]))],
        outputs=[TensorSpec("y", dt.float64, Shape([2]))],
    )
    with pytest.raises(
        ValidationError,
        match=r"Fetch 'y' has dtype float64 but its input 'y_input' has "
              r"dtype float32; they must match",
    ):
        validate_reduce_blocks(program, _vector_col_schema())


def test_reduce_blocks_rank_contract_names_both_shapes():
    program = Program(
        _noop,
        [TensorSpec("y_input", dt.float32, Shape([-1, 2, 2]))],
        outputs=[TensorSpec("y", dt.float32, Shape([2]))],
    )
    with pytest.raises(
        ValidationError,
        match=r"(?s)Placeholder 'y_input' \(shape \[\?,2,2\]\) must have "
              r"exactly one more dimension than fetch 'y' \(shape \[2\]\)",
    ):
        validate_reduce_blocks(program, _vector_col_schema())


def test_reduce_blocks_block_shape_incompatible():
    program = Program(
        _noop,
        [TensorSpec("y_input", dt.float32, Shape([-1, 3]))],
        outputs=[TensorSpec("y", dt.float32, Shape([3]))],
    )
    with pytest.raises(
        ValidationError,
        match=r"(?s)Placeholder 'y_input' declares shape \[\?,3\], "
              r"incompatible with column block shape \[\?,2\]",
    ):
        validate_reduce_blocks(program, _vector_col_schema())


# ---------------------------------------------------------------------------
# validate_reduce_rows
# ---------------------------------------------------------------------------

def test_reduce_rows_unknown_fetch_names_both_sides():
    program = Program(
        _noop,
        [
            TensorSpec("nope_1", dt.float32, Shape(())),
            TensorSpec("nope_2", dt.float32, Shape(())),
        ],
        outputs=[TensorSpec("nope", dt.float32, Shape(()))],
    )
    with pytest.raises(
        ValidationError,
        match=r"(?s)reduce_rows output 'nope' must correspond to an "
              r"existing column.*Outputs: \['nope'\].*columns: \['x'\]",
    ):
        validate_reduce_rows(program, _scalar_col_schema())


def test_reduce_rows_pairing_contract_names_expected_and_got():
    program = Program(
        _noop,
        [TensorSpec("x_1", dt.float32, Shape(()))],  # x_2 missing
        outputs=[TensorSpec("x", dt.float32, Shape(()))],
    )
    with pytest.raises(
        ValidationError,
        match=r"(?s)exactly two placeholders '<x>_1' and '<x>_2' per fetch"
              r".*Expected: \['x_1', 'x_2'\].*got: \['x_1'\]",
    ):
        validate_reduce_rows(program, _scalar_col_schema())


def test_reduce_rows_placeholder_dtype_mismatch():
    program = Program(
        _noop,
        [
            TensorSpec("x_1", dt.float64, Shape(())),
            TensorSpec("x_2", dt.float64, Shape(())),
        ],
        outputs=[TensorSpec("x", dt.float64, Shape(()))],
    )
    with pytest.raises(
        ValidationError,
        match=r"Placeholder 'x_1' has dtype float64 but column 'x' has "
              r"dtype float32",
    ):
        validate_reduce_rows(program, _scalar_col_schema())


def test_reduce_rows_shape_contract_names_both_shapes():
    program = Program(
        _noop,
        [
            TensorSpec("x_1", dt.float32, Shape([3])),
            TensorSpec("x_2", dt.float32, Shape(())),
        ],
        outputs=[TensorSpec("x", dt.float32, Shape(()))],
    )
    with pytest.raises(
        ValidationError,
        match=r"(?s)Placeholder 'x_1' \(shape \[3\]\) must have the same "
              r"shape as fetch 'x' \(shape \[\]\)",
    ):
        validate_reduce_rows(program, _scalar_col_schema())


def test_reduce_rows_cell_shape_incompatible():
    program = Program(
        _noop,
        [
            TensorSpec("y_1", dt.float32, Shape([3])),
            TensorSpec("y_2", dt.float32, Shape([3])),
        ],
        outputs=[TensorSpec("y", dt.float32, Shape([3]))],
    )
    with pytest.raises(
        ValidationError,
        match=r"(?s)Placeholder 'y_1' declares shape \[3\], incompatible "
              r"with column cell shape \[2\]",
    ):
        validate_reduce_rows(program, _vector_col_schema())
