"""Multi-process distributed backend test: two real OS processes, each
owning one CPU device, coordinate through ``init_distributed``
(jax.distributed) and run a psum across process boundaries.

This is the test the reference never had (SURVEY §4: "no multi-node test
infrastructure anywhere in the repo" — distribution was tested by
partition count only). Here the control plane (coordinator service) and
the collective path are exercised across actual process boundaries — the
single-host analogue of multi-host DCN.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
from tensorframes_tpu.parallel import init_distributed, is_multiprocess, process_index

init_distributed(
    coordinator_address={coord!r},
    num_processes=2,
    process_id=int(sys.argv[1]),
)
assert is_multiprocess(), f"process_count={{jax.process_count()}}"
assert process_index() == int(sys.argv[1])
assert len(jax.devices()) == 2, jax.devices()  # both processes' devices visible

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(jax.devices(), ("dp",))
# each process contributes its own shard; the jitted sum crosses the
# process boundary through the collective
arr = jax.make_array_from_callback(
    (2,), NamedSharding(mesh, P("dp")),
    lambda idx: jnp.asarray([float(process_index()) + 1.0]),
)
total = jax.jit(lambda x: x.sum(), out_shardings=NamedSharding(mesh, P()))(arr)
assert float(total) == 3.0, float(total)  # 1.0 (proc 0) + 2.0 (proc 1)

# frame-level: each process contributes local rows; verbs run SPMD and the
# reduction crosses the host boundary (≙ partitions on two executors)
import tensorframes_tpu as tfs
from tensorframes_tpu.parallel import frame_from_process_local

pid = process_index()
local = np.asarray([10.0 * pid + 1.0, 10.0 * pid + 2.0])  # p0: 1,2; p1: 11,12
frame = frame_from_process_local({{"v": local}}, mesh=mesh, axis="dp")
assert frame.num_rows == 4  # global rows, both processes' shards
doubled = tfs.map_blocks(lambda v: {{"w": v * 2.0}}, frame)
s = tfs.reduce_blocks(lambda w_input: {{"w": w_input.sum(axis=0)}}, doubled)
assert float(s) == 2.0 * (1 + 2 + 11 + 12), float(s)
# keyed aggregate across processes: the sharded dense-bucket plan
# (ops/device_agg.py) reduces per shard and merges with one psum over the
# process boundary; only the tiny replicated bucket table reaches numpy,
# so the non-addressable input columns are never host-gathered
kf = frame_from_process_local(
    {{"k": np.asarray([pid, pid + 1]), "v": local}}, mesh=mesh, axis="dp"
)
with tfs.with_graph():
    v_input = tfs.block(kf, "v", tf_name="v_input")
    agg = tfs.aggregate(
        tfs.reduce_sum(v_input, axis=0, name="v"), kf.group_by("k")
    )
got = {{r["k"]: r["v"] for r in agg.collect()}}
# p0 contributes k=0:1.0, k=1:2.0; p1 contributes k=1:11.0, k=2:12.0
assert got == {{0: 1.0, 1: 13.0, 2: 12.0}}, got
# sharded persistence: each process writes its part, reloads, and the
# reassembled global frame reduces to the same total across hosts
sf_dir = {sf_dir!r}
tfs.io.save_frame_sharded(frame, sf_dir)
back = tfs.io.load_frame_sharded(sf_dir, mesh=mesh, axis="dp")
s2 = tfs.reduce_blocks(lambda v_input: {{"v": v_input.sum(axis=0)}}, back)
assert float(s2) == (1 + 2 + 11 + 12), float(s2)
print(f"proc {{sys.argv[1]}} OK total={{float(total)}} frame_sum={{float(s)}}", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_psum(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    coord = f"localhost:{_free_port()}"
    script = tmp_path / "worker.py"
    script.write_text(
        _WORKER.format(repo=repo, coord=coord, sf_dir=str(tmp_path / "sf"))
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(2)
    ]
    try:
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=110)
            outs.append(out)
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
            assert f"proc {i} OK total=3.0" in out, out[-2000:]
    finally:
        # a hung coordinator rendezvous must not orphan workers into CI
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
