"""Multi-process distributed backend tests: N real OS processes, each
owning one CPU device, coordinate through ``init_distributed``
(jax.distributed) and run collectives across process boundaries.

This is the test the reference never had (SURVEY §4: "no multi-node test
infrastructure anywhere in the repo" — distribution was tested by
partition count only). Here the control plane (coordinator service) and
the collective path are exercised across actual process boundaries — the
single-host analogue of multi-host DCN — at 2 and at 4 processes
(the 4-way run additionally covers multi-hop collective schedules and
the sharded save/load round-trip with four writers).
"""

import os
import socket
import subprocess
import sys


_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
from tensorframes_tpu.parallel import init_distributed, is_multiprocess, process_index

NPROC = int(sys.argv[2])
init_distributed(
    coordinator_address={coord!r},
    num_processes=NPROC,
    process_id=int(sys.argv[1]),
)
assert is_multiprocess(), f"process_count={{jax.process_count()}}"
assert process_index() == int(sys.argv[1])
assert len(jax.devices()) == NPROC, jax.devices()  # every process's device visible

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(jax.devices(), ("dp",))
# each process contributes its own shard; the jitted sum crosses the
# process boundary through the collective
arr = jax.make_array_from_callback(
    (NPROC,), NamedSharding(mesh, P("dp")),
    lambda idx: jnp.asarray([float(process_index()) + 1.0]),
)
total = jax.jit(lambda x: x.sum(), out_shardings=NamedSharding(mesh, P()))(arr)
want_total = float(sum(range(1, NPROC + 1)))
assert float(total) == want_total, float(total)

# frame-level: each process contributes local rows; verbs run SPMD and the
# reduction crosses the host boundary (≙ partitions on N executors)
import tensorframes_tpu as tfs
from tensorframes_tpu.parallel import frame_from_process_local

pid = process_index()
local = np.asarray([10.0 * pid + 1.0, 10.0 * pid + 2.0])
frame = frame_from_process_local({{"v": local}}, mesh=mesh, axis="dp")
assert frame.num_rows == 2 * NPROC  # global rows, all processes' shards
doubled = tfs.map_blocks(lambda v: {{"w": v * 2.0}}, frame)
s = tfs.reduce_blocks(lambda w_input: {{"w": w_input.sum(axis=0)}}, doubled)
want_s = 2.0 * sum(10.0 * p + 1.0 + 10.0 * p + 2.0 for p in range(NPROC))
assert float(s) == want_s, float(s)
# keyed aggregate across processes: the sharded dense-bucket plan
# (ops/device_agg.py) reduces per shard and merges with one psum over the
# process boundary; only the tiny replicated bucket table reaches numpy,
# so the non-addressable input columns are never host-gathered
kf = frame_from_process_local(
    {{"k": np.asarray([pid, pid + 1]), "v": local}}, mesh=mesh, axis="dp"
)
with tfs.with_graph():
    v_input = tfs.block(kf, "v", tf_name="v_input")
    agg = tfs.aggregate(
        tfs.reduce_sum(v_input, axis=0, name="v"), kf.group_by("k")
    )
got = {{r["k"]: r["v"] for r in agg.collect()}}
want = {{}}
for p in range(NPROC):
    want[p] = want.get(p, 0.0) + 10.0 * p + 1.0
    want[p + 1] = want.get(p + 1, 0.0) + 10.0 * p + 2.0
assert got == want, (got, want)
# STRING keys across processes (VERDICT r2 #4): host-only key columns are
# process-local; the dictionary plan unions the per-process dictionaries
# with one allgather and reduces through the same segment plan — no
# process ever gathers another's raw keys
skf = frame_from_process_local(
    {{"k": ["shared", "p%d" % pid], "v": local}}, mesh=mesh, axis="dp"
)
with tfs.with_graph():
    v_input = tfs.block(skf, "v", tf_name="v_input")
    sagg = tfs.aggregate(
        tfs.reduce_sum(v_input, axis=0, name="v"), skf.group_by("k")
    )
sgot = {{str(r["k"]): r["v"] for r in sagg.collect()}}
swant = {{"shared": float(sum(10.0 * p + 1.0 for p in range(NPROC)))}}
for p in range(NPROC):
    swant["p%d" % p] = 10.0 * p + 2.0
assert sgot == swant, (sgot, swant)
# GENERIC (non-reducer) combiner across processes (VERDICT r2 missing #5):
# an apply_fn program is not segment-lowerable, so the device plans
# decline — the multiprocess generic path compacts locally and merges
# one partial per (process, group) through an allgather
with tfs.with_graph():
    v_input2 = tfs.block(kf, "v", tf_name="v_input")
    gagg = tfs.aggregate(
        tfs.apply_fn(lambda v: v.sum(axis=0), v_input2, name="v"),
        kf.group_by("k"),
    )
ggot = {{int(r["k"]): float(r["v"]) for r in gagg.collect()}}
assert ggot == want, (ggot, want)
# multi-process JOIN (VERDICT r3 #7 — replaces the spans-processes
# raise): broadcast hash join — every process allgathers the right
# side (device key/value columns AND a host string column), joins its
# LOCAL left rows, and holds its share of the output process-locally
rt = frame_from_process_local(
    {{"k": np.asarray([pid]), "w": np.asarray([100.0 * pid]),
      "name": ["proc%d" % pid]}},
    mesh=mesh, axis="dp",
)
joined = kf.join(rt, on="k")
jrows = joined.collect()
jwant = [(pid, 10.0 * pid + 1.0, 100.0 * pid, "proc%d" % pid)]
if pid + 1 < NPROC:
    jwant.append(
        (pid + 1, 10.0 * pid + 2.0, 100.0 * (pid + 1),
         "proc%d" % (pid + 1))
    )
jgot = [
    (int(r["k"]), float(r["v"]), float(r["w"]), str(r["name"]))
    for r in jrows
]
assert jgot == jwant, (jgot, jwant)
# multi-process FILTER: process-local subset (each process keeps its
# own passing rows; no collective)
fgot = [
    (int(r["k"]), float(r["v"]))
    for r in kf.filter(lambda v: {{"keep": v > 10.0 * pid + 1.5}}).collect()
]
assert fgot == [(pid + 1, 10.0 * pid + 2.0)], fgot
# multi-process SORT: allgather in process order -> every process holds
# the SAME replicated globally-sorted frame. EXACT sequence asserted:
# python's sorted() over the global-row-order list is stable, so equal
# keys must appear in global row order — tie stability included
sgot2 = [
    (int(r["k"]), float(r["v"]))
    for r in kf.sort_values("k").collect()
]
global_rows = []
for p in range(NPROC):
    global_rows.append((p, 10.0 * p + 1.0))
    global_rows.append((p + 1, 10.0 * p + 2.0))
swant2 = sorted(global_rows, key=lambda t: t[0])
assert sgot2 == swant2, (sgot2, swant2)
# EXCHANGE paths (VERDICT r4 #2): force the broadcast budget tiny so
# sort_values takes the RANGE exchange and join the HASH exchange —
# no process may hold the global frame. Asserted: correctness (global
# order / join values), the O(global/P) memory bound (per-process row
# share), and the disabled-exchange guard.
from jax.experimental import multihost_utils as mhx
from tensorframes_tpu.config import configure
from tensorframes_tpu.ops import exchange as xch

configure(relational_broadcast_bytes=64)
NLOC = 400
rngx = np.random.default_rng(1000 + pid)
xk = rngx.integers(0, 1000, NLOC).astype(np.int64)
xv = (xk * 2).astype(np.float64)
xf = frame_from_process_local({{"k": xk, "v": xv}}, mesh=mesh, axis="dp")
part_rows = xf.sort_values("k").collect()  # this process's key RANGE
pk = np.asarray([r["k"] for r in part_rows], np.int64)
pv = np.asarray([r["v"] for r in part_rows])
assert (np.diff(pk) >= 0).all()  # locally sorted
np.testing.assert_array_equal(pv, pk * 2.0)  # rows kept intact
lens = np.asarray(
    mhx.process_allgather(np.asarray([len(pk)], np.int64))
).reshape(-1)
assert int(lens.sum()) == NPROC * NLOC  # nothing lost or duplicated
# memory bound: no process holds the global frame (a replicating plan
# would put all NPROC*NLOC rows here); 2x over the balanced share is
# the skew allowance for random keys
assert int(lens.max()) <= max(2 * NLOC, 64), lens
# partitions form disjoint ordered ranges: concatenating processes in
# order IS the global sort (pad-allgather the variable-length parts)
W = int(lens.max())
buf = np.full(W, -1, np.int64)
buf[: len(pk)] = pk
allb = np.asarray(mhx.process_allgather(buf)).reshape(NPROC, W)
cat = np.concatenate(
    [allb[p, : int(lens[p])] for p in range(NPROC)]
)
gk = np.asarray(mhx.process_allgather(xk)).reshape(-1)
np.testing.assert_array_equal(cat, np.sort(gk, kind="stable"))
# SHUFFLE JOIN: right side over budget → hash-partition both sides
rk = np.arange(pid, 1000, NPROC).astype(np.int64)
rframe = frame_from_process_local(
    {{"k": rk, "w": (rk * 10).astype(np.float64)}}, mesh=mesh, axis="dp"
)
jrows = xf.join(rframe, on="k").collect()
for r in jrows:
    assert float(r["w"]) == int(r["k"]) * 10.0
    assert float(r["v"]) == int(r["k"]) * 2.0
jlen = np.asarray(
    mhx.process_allgather(np.asarray([len(jrows)], np.int64))
).reshape(-1)
# right side covers every key 0..999 exactly once → one output row per
# left row, spread across processes by key hash
assert int(jlen.sum()) == NPROC * NLOC, jlen
assert int(jlen.max()) <= max(2 * NLOC, 64), jlen
# OUTER join across processes rides the exchange (broadcast would
# duplicate unmatched right rows on every process): global row count =
# matched left rows + each unmatched right key exactly ONCE
orows = xf.join(
    rframe, on="k", how="outer",
    fill_value={{"v": -1.0, "w": -1.0}},
).collect()
olen = np.asarray(
    mhx.process_allgather(np.asarray([len(orows)], np.int64))
).reshape(-1)
n_distinct = len(np.unique(gk))
assert int(olen.sum()) == NPROC * NLOC + (1000 - n_distinct), (
    int(olen.sum()), NPROC * NLOC, n_distinct
)
for r in orows:  # every left row matches, so only v carries fills
    assert float(r["w"]) == int(r["k"]) * 10.0
# CO-PARTITIONING (repartition_by_key): pay the shuffle once, then
# joins run process-locally (spans=False on the local host frames) and
# the union of local joins equals the global join
lp = xf.repartition_by_key("k")
rp = rframe.repartition_by_key("k")
from tensorframes_tpu.ops.exchange import partition_by_hash
lk = np.asarray(lp.column_values("k"), np.int64)
assert (partition_by_hash([lk], NPROC) == pid).all()  # keys colocated
cj = lp.join(rp, on="k").collect()
cjlen = np.asarray(
    mhx.process_allgather(np.asarray([len(cj)], np.int64))
).reshape(-1)
assert int(cjlen.sum()) == NPROC * NLOC, cjlen
for r in cj:
    assert float(r["w"]) == int(r["k"]) * 10.0
    assert float(r["v"]) == int(r["k"]) * 2.0
# distributed drop_duplicates: duplicates COLOCATE under the hash
# exchange, so each process's local dedup is the global dedup; survivors
# carry the GLOBAL-first-occurrence row (v encodes (proc, row))
dupf = frame_from_process_local(
    {{"k": np.asarray([0, 10 + pid, 0, 10 + pid], np.int64),
      "v": np.asarray([100.0 * pid + i for i in range(4)])}},
    mesh=mesh, axis="dp",
)
surv = dupf.drop_duplicates(subset="k").collect()
for r in surv:
    kk, vv = int(r["k"]), float(r["v"])
    if kk == 0:
        assert vv == 0.0, r  # global first occurrence: proc 0, row 0
    else:
        p_src = kk - 10
        assert vv == 100.0 * p_src + 1.0, r  # proc p_src, row 1
slen = np.asarray(
    mhx.process_allgather(np.asarray([len(surv)], np.int64))
).reshape(-1)
assert int(slen.sum()) == 1 + NPROC, slen  # key 0 plus one 10+p per proc
# the round-5 review's blind spot: dedup of a process-LOCAL frame on a
# key OTHER than its partition key must still be global (the exchange
# runs for every layout) — column b duplicates span every process
pl2 = frame_from_process_local(
    {{"a": np.asarray([pid, pid], np.int64),
      "b": np.asarray([7, 7], np.int64)}},
    mesh=mesh, axis="dp",
).repartition_by_key("a")
sb = pl2.drop_duplicates(subset="b").collect()
sblen = np.asarray(
    mhx.process_allgather(np.asarray([len(sb)], np.int64))
).reshape(-1)
assert int(sblen.sum()) == 1, sblen  # one global survivor, not one/proc
# replicated-in → replicated-out (ADVICE r5): a frame built IDENTICALLY
# on every process (all columns byte-equal fleet-wide) dedups LOCALLY —
# every process keeps every unique row, instead of being converted into
# per-process hash partitions like the process-local frames above
repf = tfs.frame_from_arrays(
    {{"k": np.asarray([1, 2, 1, 3], np.int64),
      "v": np.asarray([1.0, 2.0, 3.0, 4.0])}})
rsurv = repf.drop_duplicates(subset="k").collect()
assert [int(r["k"]) for r in rsurv] == [1, 2, 3], rsurv
assert [float(r["v"]) for r in rsurv] == [1.0, 2.0, 4.0], rsurv
rlen = np.asarray(
    mhx.process_allgather(np.asarray([len(rsurv)], np.int64))
).reshape(-1)
assert (rlen == 3).all(), rlen  # replicated result on every process
# ...but a SHARDED frame whose per-process shards happen to be
# byte-identical (symmetric seed data) is NOT replicated — its global
# frame is the concatenation of the shards, so dedup must still
# exchange and collapse to ONE global survivor; the content hash alone
# would misclassify this (review r9: the layout check precedes it)
sym = frame_from_process_local(
    {{"k": np.asarray([5, 5], np.int64)}}, mesh=mesh, axis="dp",
)
ssurv = sym.drop_duplicates(subset="k").collect()
sslen = np.asarray(
    mhx.process_allgather(np.asarray([len(ssurv)], np.int64))
).reshape(-1)
assert int(sslen.sum()) == 1, sslen  # one GLOBAL survivor, not one/proc
# ... and the sort_values layout-switch tripwire (ADVICE r5) fired when
# the over-budget sort above took the range exchange (budget was 64B)
from tensorframes_tpu import frame as _frame_mod
assert _frame_mod._sort_layout_warned  # one-time warning happened
# exchange observability: the shuffle plans record their own spans
from tensorframes_tpu.utils import profiling as _prof
_rep = _prof.report()
for spanname in ("sort_values.exchange", "join.exchange", "repartition_by_key"):
    assert spanname in _rep, (spanname, _rep[-2000:])
# guard: with the exchange disabled, over-budget plans raise the
# actionable error on EVERY process instead of replicating
configure(relational_exchange=False)
for plan in (
    lambda: xf.sort_values("k").collect(),
    lambda: xf.join(rframe, on="k").collect(),
):
    try:
        plan()
        raise SystemExit("exchange guard did not fire")
    except RuntimeError as e:
        assert "relational_broadcast_bytes" in str(e), e
configure(relational_exchange=True, relational_broadcast_bytes=64 << 20)
# replication tripwire: repartitioning a REPLICATED frame (the
# under-budget sort result — every process holds the same rows) must
# warn about P-fold duplication
import logging as _lg
_msgs = []
class _CapH(_lg.Handler):
    def emit(self, r):
        _msgs.append(r.getMessage())
_h = _CapH()
_lg.getLogger("tensorframes_tpu.frame").addHandler(_h)
replicated = kf.sort_values("k")  # small -> replicated plan
_ = replicated.repartition_by_key("k")
_lg.getLogger("tensorframes_tpu.frame").removeHandler(_h)
assert any("identical" in m for m in _msgs), _msgs
# sharded persistence: each process writes its part, reloads, and the
# reassembled global frame reduces to the same total across hosts
sf_dir = {sf_dir!r}
tfs.io.save_frame_sharded(frame, sf_dir)
back = tfs.io.load_frame_sharded(sf_dir, mesh=mesh, axis="dp")
s2 = tfs.reduce_blocks(lambda v_input: {{"v": v_input.sum(axis=0)}}, back)
assert float(s2) == want_s / 2.0, float(s2)
print(f"proc {{sys.argv[1]}} OK total={{float(total)}} frame_sum={{float(s)}}", flush=True)
"""


# ---------------------------------------------------------------------------
# sharded compile-cache round trip (ISSUE 10): the same worker runs twice
# against ONE persistent store; its sharded dispatches ride the unified
# AOT path, so run 2 must load every executable from disk — zero XLA
# compiles — and produce bit-identical results. The metrics JSONL the
# worker writes is the same artifact shape CI asserts on
# (tftpu_compilecache_hits_total / tftpu_executor_compile_seconds).
# ---------------------------------------------------------------------------

_CACHE_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
sys.path.insert(0, {repo!r})
import json
import numpy as np
import tensorframes_tpu as tfs
from tensorframes_tpu.observability import REGISTRY

df = tfs.frame_from_arrays(
    {{"x": np.arange(640, dtype=np.float32)}}
).to_device()
assert df.is_sharded, "worker needs the 8-device virtual mesh"
program = tfs.compile_program(
    lambda x: {{"y": x * 3.0 + 1.0, "z": x.sum() + x}}, df
)
out = tfs.map_blocks(program, df)
y = np.asarray(out.column_values("y"))
z = np.asarray(out.column_values("z"))
np.save(sys.argv[2], np.stack([y, z]))
REGISTRY.write_jsonl(sys.argv[1])
print("CACHE WORKER OK", flush=True)
"""


def _metric(path, name, field="value"):
    import json as _json

    total = 0.0
    for line in open(path):
        d = _json.loads(line)
        if d["name"] == name:
            total += d.get(field) or 0
    return total


def test_sharded_cache_roundtrip_across_processes(tmp_path):
    """Two fresh subprocesses share one TFTPU_COMPILE_CACHE: the second
    performs ZERO XLA compiles (all sharded executables load from the
    store) and its results are bit-identical to the first's — the
    tentpole acceptance, in-suite."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "cache_worker.py"
    script.write_text(_CACHE_WORKER.format(repo=repo))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["TFTPU_COMPILE_CACHE"] = str(tmp_path / "store")
    outs = []
    for run in (1, 2):
        metrics = tmp_path / f"metrics_{run}.jsonl"
        results = tmp_path / f"results_{run}.npy"
        r = subprocess.run(
            [sys.executable, str(script), str(metrics), str(results)],
            capture_output=True, text=True, env=env, timeout=240,
        )
        assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
        outs.append((metrics, results))
    import numpy as np

    m1, r1 = outs[0]
    m2, r2 = outs[1]
    # run 1 is the cold publisher: it compiled, and anything it read
    # from the store was published by... nobody (fresh dir)
    assert _metric(m1, "tftpu_executor_compile_seconds", "count") > 0
    # run 2 is the warm loader: disk hits, ZERO XLA compiles, and the
    # dispatch never fell back to lazy jit
    assert _metric(m2, "tftpu_compilecache_hits_total") > 0
    assert _metric(m2, "tftpu_executor_compile_seconds", "count") == 0
    assert _metric(m2, "tftpu_executor_fallback_dispatch_total") == 0
    # sharded cached results are bit-identical across the round trip
    np.testing.assert_array_equal(np.load(r1), np.load(r2))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_workers(tmp_path, nproc: int, timeout: float, attempts: int = 3):
    """Launch the worker fleet; retries with a FRESH coordinator port.
    The rendezvous is exposed to two load-dependent transients a retry
    cures: the _free_port bind/close/reuse race, and slow worker
    interpreter startup under a loaded machine blowing the distributed
    init window (observed as rare full-suite-only failures; round 5
    reproduced one by running a SECOND fleet concurrently — hence the
    third attempt)."""
    last = None
    for attempt in range(attempts):
        try:
            return _run_workers_once(tmp_path, nproc, timeout, attempt)
        except AssertionError as e:
            last = e
    raise last


def _run_workers_once(tmp_path, nproc: int, timeout: float, attempt: int):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    coord = f"localhost:{_free_port()}"
    script = tmp_path / f"worker_{attempt}.py"
    script.write_text(
        _WORKER.format(repo=repo, coord=coord, sf_dir=str(tmp_path / "sf"))
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    # CPU workers must not touch the accelerator plugin: with the pool
    # var cleared the axon sitecustomize no-ops, so a wedged TPU relay
    # can't hang or crash worker interpreter startup (the intermittent
    # full-suite failure of the 4-process test)
    env["PALLAS_AXON_POOL_IPS"] = ""
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(nproc)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(nproc)
    ]
    try:
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
        want_total = float(sum(range(1, nproc + 1)))
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
            assert f"proc {i} OK total={want_total}" in out, out[-2000:]
    finally:
        # a hung coordinator rendezvous must not orphan workers into CI
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()


def test_two_process_psum(tmp_path):
    _run_workers(tmp_path, 2, timeout=110)


def test_four_process_psum(tmp_path):
    """4 processes ≙ 4 hosts: multi-hop collectives, 4-writer sharded
    save/load, and the device-aggregate merge at process_count=4
    (VERDICT r1 next-step 7: scale the multi-process story past 2).
    Generous timeout: each worker pays the full jax import + compile,
    and the suite may be sharing the machine."""
    _run_workers(tmp_path, 4, timeout=420)


# ---------------------------------------------------------------------------
# file-shuffle fleet (ISSUE 15): the distributed data plane WITHOUT jax
# collectives — ranks exchange hash-partitioned partial tables through
# per-rank spill files in a shared shuffle dir. Unlike the psum fleets
# above, these workers need no coordinator and no cross-process XLA
# collectives, so they run on every jaxlib (including ones whose
# multi-process CPU collectives are missing).
# ---------------------------------------------------------------------------

_SHUFFLE_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
sys.path.insert(0, {repo!r})
rank = int(sys.argv[1])
os.environ["TFTPU_SHUFFLE_RANK"] = str(rank)
os.environ["TFTPU_SHUFFLE_NPROCS"] = "2"
import numpy as np
import tensorframes_tpu as tfs
from tensorframes_tpu.blockstore import shuffle
from tensorframes_tpu.blockstore.store import HOSTGATHER_BYTES

# the shared dataset recipe (seed-deterministic): rank r holds half the
# rows, so the union across ranks IS the oracle's frame
rng = np.random.default_rng(7)
N = 4000
k_i64 = rng.integers(0, 50, size=N).astype(np.int64)
k_i64[: N // 2] = 7  # skewed: one hot key owns half the rows
k_f64 = (k_i64 % 11).astype(np.float64) / 2.0
vals = rng.integers(0, 1000, size=N).astype(np.float64)  # int-valued: exact sums
k_str = [f"g{{int(x) % 5}}" for x in k_i64]
lo, hi = (0, N // 2) if rank == 0 else (N // 2, N)
local = tfs.frame_from_arrays(
    {{"k": k_i64[lo:hi], "kf": k_f64[lo:hi], "v": vals[lo:hi],
      "s": k_str[lo:hi]}}
)

def agg_sum(key):
    def fn(f):
        with tfs.with_graph():
            v_in = tfs.block(f, "v", tf_name="v_input")
            return tfs.aggregate(
                tfs.reduce_sum(v_in, axis=0, name="v"), f.group_by(key)
            )
    return fn

def agg_min(key):
    def fn(f):
        with tfs.with_graph():
            v_in = tfs.block(f, "v", tf_name="v_input")
            return tfs.aggregate(
                tfs.reduce_min(v_in, axis=0, name="v"), f.group_by(key)
            )
    return fn

# shuffled aggregates across every key dtype (+ the skewed int key)
r_i = shuffle.distributed_aggregate(local, ["k"], agg_sum("k"), name="a-i64")
r_f = shuffle.distributed_aggregate(local, ["kf"], agg_min("kf"), name="a-f64")
r_s = shuffle.distributed_aggregate(local, ["s"], agg_sum("s"), name="a-str")

# shuffled join: rank-local right side, union across ranks = full dim table
right = tfs.frame_from_arrays(
    {{"k": np.arange(rank * 25, rank * 25 + 25, dtype=np.int64),
      "w": np.arange(25, dtype=np.float64) + rank * 100}}
)
jcols = shuffle.distributed_join(
    local.select(["k", "v"]), right, on="k", name="j"
)

# THE acceptance gate: zero host-gathered partial tables anywhere
assert HOSTGATHER_BYTES.value == 0.0, HOSTGATHER_BYTES.value

if rank == 0:
    np.savez(
        {out!r},
        k=r_i.column_values("k"), v=r_i.column_values("v"),
        fk=r_f.column_values("kf"), fv=r_f.column_values("v"),
        sk=np.asarray(r_s.column_values("s"), dtype=object),
        sv=r_s.column_values("v"),
        jk=np.asarray(jcols["k"]), jv=np.asarray(jcols["v"]),
        jw=np.asarray(jcols["w"]),
        allow_pickle=True,
    )
print("SHUFFLE_WORKER_OK", rank, flush=True)
'''


def _shuffle_env(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["TFTPU_SHUFFLE_DIR"] = str(tmp_path / "shuffle")
    env.pop("TFTPU_FLEET_DIR", None)
    return env


def test_two_process_file_shuffle_matches_single_process_oracle(tmp_path):
    """2 real OS processes, NO jax.distributed: shuffled aggregate
    (int64 / float64 / string keys, one hot key owning half the rows)
    and shuffled join, all bit-identical to the single-process oracle —
    with the host-gather metric asserted ZERO in every worker."""
    import numpy as np

    out = str(tmp_path / "rank0.npz")
    script = tmp_path / "shuffle_worker.py"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script.write_text(_SHUFFLE_WORKER.format(repo=repo, out=out))
    env = _shuffle_env(tmp_path)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(r)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for r in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=240)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for r, (p, o) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{o[-3000:]}"
        assert f"SHUFFLE_WORKER_OK {r}" in o, o[-2000:]

    # the single-process oracle over the union of both ranks' rows
    import tensorframes_tpu as tfs

    rng = np.random.default_rng(7)
    N = 4000
    k_i64 = rng.integers(0, 50, size=N).astype(np.int64)
    k_i64[: N // 2] = 7
    k_f64 = (k_i64 % 11).astype(np.float64) / 2.0
    vals = rng.integers(0, 1000, size=N).astype(np.float64)
    k_str = [f"g{int(x) % 5}" for x in k_i64]
    full = tfs.frame_from_arrays(
        {"k": k_i64, "kf": k_f64, "v": vals, "s": k_str}
    )

    def agg(key, red):
        with tfs.with_graph():
            v_in = tfs.block(full, "v", tf_name="v_input")
            return tfs.aggregate(
                red(v_in, axis=0, name="v"), full.group_by(key)
            )

    z = np.load(str(tmp_path / "rank0.npz"), allow_pickle=True)
    oi = agg("k", tfs.reduce_sum)
    np.testing.assert_array_equal(z["k"], oi.column_values("k"))
    np.testing.assert_array_equal(z["v"], oi.column_values("v"))
    of = agg("kf", tfs.reduce_min)
    np.testing.assert_array_equal(z["fk"], of.column_values("kf"))
    np.testing.assert_array_equal(z["fv"], of.column_values("v"))
    os_ = agg("s", tfs.reduce_sum)
    assert list(z["sk"]) == list(os_.column_values("s"))
    np.testing.assert_array_equal(z["sv"], os_.column_values("v"))
    # join: same multiset of rows, bit-identical after canonical sort
    right = tfs.frame_from_arrays({
        "k": np.arange(50, dtype=np.int64),
        "w": np.concatenate(
            [np.arange(25.0), np.arange(25.0) + 100]
        ),
    })
    oj = full.select(["k", "v"]).join(right, on="k", how="inner")

    def canon(cols):
        arrs = [np.asarray(cols[c]) for c in ("k", "v", "w")]
        order = np.lexsort(arrs[::-1])
        return [a[order] for a in arrs]

    got = canon({"k": z["jk"], "v": z["jv"], "w": z["jw"]})
    want = canon({c: oj.column_values(c) for c in ("k", "v", "w")})
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)


_SHUFFLE_KILL_WORKER = r'''
import os, signal, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
sys.path.insert(0, {repo!r})
rank = int(sys.argv[1])
os.environ["TFTPU_SHUFFLE_RANK"] = str(rank)
os.environ["TFTPU_SHUFFLE_NPROCS"] = "2"
from tensorframes_tpu.blockstore import shuffle
from tensorframes_tpu.resilience.fleet import HungDispatchError

if rank == 1:
    # die MID-shuffle: part files published, done-marker never lands —
    # the torn state a real kill -9 leaves behind
    _orig = shuffle._publish
    def _dying(path, payload):
        if "src-00001.done" in path:
            os.kill(os.getpid(), signal.SIGKILL)
        return _orig(path, payload)
    shuffle._publish = _dying
try:
    shuffle.exchange([b"a", b"b"], name="killdrill", timeout=10.0)
    print("NO_ABORT", flush=True)
except HungDispatchError as e:
    assert "[1]" in str(e), str(e)
    print("WATCHDOG_ABORT_NAMED", flush=True)
'''


def test_kill9_mid_shuffle_watchdog_abort_names_the_rank(tmp_path):
    """kill -9 of rank 1 between its part files and its done marker:
    rank 0's deadline-bounded wait raises HungDispatchError NAMING rank
    1 (never an indefinite hang), and the flight recorder's disk spool
    holds the shuffle.hang postmortem."""
    import glob

    script = tmp_path / "kill_worker.py"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script.write_text(_SHUFFLE_KILL_WORKER.format(repo=repo))
    env = _shuffle_env(tmp_path)
    env["TFTPU_FLIGHT_DIR"] = str(tmp_path / "flight")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(r)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for r in (0, 1)
    ]
    try:
        outs = [p.communicate(timeout=120)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    assert procs[1].returncode == -9, outs[1][-1000:]  # really SIGKILLed
    assert procs[0].returncode == 0, outs[0][-3000:]
    assert "WATCHDOG_ABORT_NAMED" in outs[0], outs[0][-2000:]
    # the black box survived: a postmortem naming the hang is on disk
    dumps = glob.glob(str(tmp_path / "flight" / "postmortem_*.jsonl"))
    assert dumps, os.listdir(str(tmp_path / "flight"))
    joined = "".join(open(d).read() for d in dumps)
    assert "shuffle.hang" in joined
