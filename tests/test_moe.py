"""MoE tests: routing invariants, dense == expert-parallel equivalence on
the 8-device mesh, and a full EP training step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensorframes_tpu.models import moe
from tensorframes_tpu.parallel import make_mesh


def _cfg(**kw):
    kw.setdefault("hidden", 16)
    kw.setdefault("mlp_hidden", 32)
    kw.setdefault("num_experts", 4)
    # capacity == tokens: nothing drops, so dense and EP agree exactly
    kw.setdefault("capacity_factor", float(kw["num_experts"]))
    return moe.MoEConfig(**kw)


def test_routing_dispatch_invariants():
    cfg = _cfg(capacity_factor=1.0)
    params = moe.init_moe_params(cfg, seed=0)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((24, 16)), jnp.float32)
    cap = cfg.capacity(24)
    dispatch, combine, (frac, prob) = moe._route(cfg, params["router"], x, cap)
    # each token goes to at most one (expert, slot)
    assert dispatch.shape == (24, 4, cap)
    assert float(dispatch.sum(axis=(1, 2)).max()) <= 1.0
    # no expert slot double-booked
    assert float(dispatch.sum(axis=0).max()) <= 1.0
    # stats are distributions
    assert np.isclose(float(frac.sum()), 1.0, atol=1e-6)
    assert np.isclose(float(prob.sum()), 1.0, atol=1e-5)


def test_moe_ffn_changes_by_expert():
    cfg = _cfg()
    params = moe.init_moe_params(cfg, seed=1)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((32, 16)), jnp.float32)
    y = moe.moe_ffn(cfg, params, x)
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(y)).all()


def test_dense_equals_expert_parallel():
    cfg = _cfg(num_experts=8, capacity_factor=8.0)
    params = moe.init_moe_params(cfg, seed=2)
    mesh = make_mesh({"ep": 4, "dp": 2})
    x = jnp.asarray(np.random.default_rng(2).standard_normal((64, 16)), jnp.float32)
    dense = moe.moe_ffn(cfg, params, x)
    ep = moe.moe_ffn_ep(cfg, params, x, mesh, axis="ep")
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ep), rtol=1e-5, atol=1e-5)


def test_ep_train_step_runs_and_learns():
    import optax

    cfg = _cfg(num_experts=4, capacity_factor=4.0)
    mesh = make_mesh({"ep": 4, "dp": 2})
    params = moe.init_moe_params(cfg, seed=3)
    tx = optax.adam(1e-2)
    step, data_sh, param_sh, init_opt = moe.make_ep_train_step(cfg, mesh, tx)
    rng = np.random.default_rng(3)
    x = jax.device_put(
        jnp.asarray(rng.standard_normal((32, 16)), jnp.float32), data_sh
    )
    y = jax.device_put(
        jnp.asarray(rng.standard_normal((32, 16)), jnp.float32), data_sh
    )
    params = jax.device_put(params, param_sh)
    opt_state = init_opt(params)
    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, x, y)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_num_experts_must_divide_ep():
    cfg = _cfg(num_experts=6)
    mesh = make_mesh({"ep": 4, "dp": 2})
    params = moe.init_moe_params(cfg, seed=0)
    x = jnp.zeros((8, 16), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        moe.moe_ffn_ep(cfg, params, x, mesh)


def test_moe_scoring_via_map_blocks():
    import tensorframes_tpu as tfs

    cfg = moe.MoEConfig(hidden=16, mlp_hidden=32, num_experts=4)
    params = moe.init_moe_params(cfg, seed=0)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((12, 16)).astype(np.float32)
    df = tfs.frame_from_arrays({"features": x}, num_blocks=2)
    out = tfs.map_blocks(
        lambda features: moe.scoring_program(cfg, params)(features), df
    )
    y = np.stack([r["moe_out"] for r in out.collect()])
    assert y.shape == (12, 16)
    assert np.isfinite(y).all()
    # block semantics: per-block routing equals direct per-block calls
    blocks = df.blocks()
    direct = np.concatenate(
        [np.asarray(moe.moe_ffn(cfg, params, b["features"])) for b in blocks]
    )
    np.testing.assert_allclose(y, direct, rtol=1e-5, atol=1e-6)
