"""Straggler pallas kernels (ISSUE 12): bit-identity property sweeps
against the reference lowerings (CPU pallas interpreter), cost-model
selection wiring, kill-switch recovery, metrics pre-registration, and
the zero-row edge pins from the bugfix sweep.

Every kernel gate here is EXACT equality, not allclose: the same-spec
plain-jnp emulation is bit-identical by construction, the order-free
op classes (min/max, integer sums) are bit-identical to the XLA
scatter, and the decode-attention kernel reproduces the XLA
gather→dequant→attend chain bit-for-bit on the interpreter.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import tensorframes_tpu as tfs
from tensorframes_tpu import configure
from tensorframes_tpu import kernels
from tensorframes_tpu.kernels import decode_attention as kda
from tensorframes_tpu.kernels import ragged_gather as krg
from tensorframes_tpu.kernels import segment_reduce as ksr
from tensorframes_tpu.observability.metrics import REGISTRY
from tensorframes_tpu.ops import segment
from tensorframes_tpu.plan import rules as prules


@pytest.fixture
def forced():
    """Select the kernels on CPU (interpreter). Also pins
    ``pallas_kernels=True`` so the selection tests stay meaningful
    under the CI kernels-off smoke (``TFTPU_PALLAS=0``) — these tests
    exercise the kernels themselves; the off-smoke's point is the
    suites that merely COULD select them."""
    from tensorframes_tpu.config import get_config

    cfg = get_config()
    was_force, was_kernels = cfg.pallas_force, cfg.pallas_kernels
    configure(pallas_force=True, pallas_kernels=True)
    try:
        yield
    finally:
        configure(pallas_force=was_force, pallas_kernels=was_kernels)


def _assert_eq(a, b, msg):
    assert a.dtype == b.dtype, (msg, a.dtype, b.dtype)
    assert a.shape == b.shape, (msg, a.shape, b.shape)
    np.testing.assert_array_equal(a, b, err_msg=msg)


# ---------------------------------------------------------------------------
# segment reduce
# ---------------------------------------------------------------------------

_SWEEP_DTYPES = ("float32", "int32", "int8", "bool")


@pytest.mark.parametrize("n,s", [(0, 3), (1, 1), (37, 5), (1000, 64),
                                 (300, 1), (513, 9)])
def test_segment_reduce_sweep_bit_identical(n, s):
    """ops × dtypes × segment counts (0-row, 1-segment, tile-crossing):
    pallas == same-spec reference emulation bitwise, and == the XLA
    scatter for the order-free classes."""
    rng = np.random.default_rng(n * 31 + s)
    ids = rng.integers(0, s, n).astype(np.int32)  # unsorted by nature
    cols = {
        "f_sum": rng.standard_normal(n).astype(np.float32),
        "f_mean": rng.standard_normal(n).astype(np.float32),
        "f_min": rng.standard_normal((n, 3)).astype(np.float32),
        "i_sum": rng.integers(-50, 50, (n, 2)).astype(np.int32),
        "i_max": rng.integers(-50, 50, n).astype(np.int8),
        "b_min": rng.integers(0, 2, n).astype(bool),
    }
    ops = (
        ("f_sum", "reduce_sum"), ("f_mean", "reduce_mean"),
        ("f_min", "reduce_min"), ("i_sum", "reduce_sum"),
        ("i_max", "reduce_max"), ("b_min", "reduce_min"),
    )
    assert ksr.eligible(ops, cols, s)
    got = ksr.segment_reduce_pallas(ops, s, cols, ids, interpret=True)
    ref = ksr.segment_reduce_reference(ops, s, cols, ids)
    for k in got:
        assert np.array_equal(got[k], ref[k], equal_nan=True), k
        assert got[k].dtype == ref[k].dtype
    if n:
        # order-free classes are additionally exactly the scatter
        sidx = jnp.asarray(ids)
        _assert_eq(
            got["i_sum"],
            np.asarray(jax.ops.segment_sum(
                jnp.asarray(cols["i_sum"]), sidx, num_segments=s
            )),
            "int sum vs scatter",
        )
        _assert_eq(
            got["f_min"],
            np.asarray(jax.ops.segment_min(
                jnp.asarray(cols["f_min"]), sidx, num_segments=s
            )),
            "float min vs scatter",
        )
        _assert_eq(
            got["i_max"],
            np.asarray(jax.ops.segment_max(
                jnp.asarray(cols["i_max"]), sidx, num_segments=s
            )),
            "int8 max vs scatter",
        )


def test_segment_reduce_empty_segments_mean_is_nan():
    """Segments past the max observed id (the bucketing shape): sums
    read 0, means read NaN — and pallas matches the emulation on the
    NaN slots bit-for-bit."""
    ids = np.asarray([0, 0, 2], np.int32)
    cols = {"v": np.asarray([1.0, 3.0, 5.0], np.float32)}
    ops = (("v", "reduce_mean"),)
    got = ksr.segment_reduce_pallas(ops, 5, cols, ids, interpret=True)
    ref = ksr.segment_reduce_reference(ops, 5, cols, ids)
    assert np.array_equal(got["v"], ref["v"], equal_nan=True)
    assert got["v"][0] == pytest.approx(2.0)
    assert np.isnan(got["v"][1]) and np.isnan(got["v"][3])


def test_segment_reduce_eligibility_gates():
    f64 = {"v": np.zeros(4, np.float64)}
    assert not ksr.eligible((("v", "reduce_sum"),), f64, 2)
    i64 = {"v": np.zeros(4, np.int64)}
    assert not ksr.eligible((("v", "reduce_sum"),), i64, 2)
    ok = {"v": np.zeros(4, np.float32)}
    assert not ksr.eligible((("v", "reduce_sum"),), ok, 0)
    assert not ksr.eligible(
        (("v", "reduce_sum"),), ok, ksr.MAX_SEGMENTS + 1
    )
    # a min/max whose [tile, segments, d] broadcast cannot fit the
    # budget even at the 8-row tile floor is refused
    wide = {"v": np.zeros((4, 4096), np.float32)}
    assert not ksr.eligible((("v", "reduce_min"),), wide, 4096)
    assert ksr.eligible((("v", "reduce_min"),), ok, 64)


def test_aggregate_forced_kernel_bit_identical(forced):
    """End-to-end: the cost model selects pallas_segment_reduce under
    force, and the aggregate result is bit-identical to the unforced
    run (exact op classes: min + integer sum)."""
    before = REGISTRY.counter(
        "tftpu_plan_cost_decisions_total",
        labels={"decision": "pallas_segment_reduce"},
    ).value

    def run():
        rng = np.random.default_rng(7)
        n = 400
        frame = tfs.frame_from_arrays(
            {
                "k": rng.integers(0, 9, n),
                "v": rng.standard_normal(n).astype(np.float32),
                "w": rng.integers(-10, 10, n).astype(np.int32),
            },
            num_blocks=3,
        )
        with tfs.with_graph():
            v_input = tfs.block(frame, "v", tf_name="v_input")
            w_input = tfs.block(frame, "w", tf_name="w_input")
            agg = tfs.aggregate(
                [tfs.reduce_min(v_input, axis=0, name="v"),
                 tfs.reduce_sum(w_input, axis=0, name="w")],
                frame.group_by("k"),
            )
        return sorted(
            (int(r["k"]), float(r["v"]), int(r["w"]))
            for r in agg.collect()
        )

    forced_res = run()
    assert REGISTRY.counter(
        "tftpu_plan_cost_decisions_total",
        labels={"decision": "pallas_segment_reduce"},
    ).value > before
    configure(pallas_force=False)
    assert run() == forced_res


def test_segment_reduce_kill_switch_recovery(forced, monkeypatch):
    """A Mosaic failure in the kernel trips the process-wide
    kill-switch and the SAME call returns the jitted scatter's answer —
    the PR 7 recovery contract."""
    from tensorframes_tpu.ops import verbs

    was = segment._pallas_disabled
    calls = {"n": 0}

    def boom(*a, **k):
        calls["n"] += 1
        raise RuntimeError("Mosaic lowering failed (test)")

    monkeypatch.setattr(ksr, "segment_reduce_pallas", boom)
    try:
        rng = np.random.default_rng(3)
        cols = {"v": rng.integers(-5, 5, 64).astype(np.int32)}
        ids = rng.integers(0, 4, 64).astype(np.int32)
        out = verbs._segment_reduce_best(
            (("v", "reduce_sum"),), 4, cols, ids
        )
        assert calls["n"] == 1
        assert not segment.pallas_enabled()  # switch tripped
        _assert_eq(
            out["v"],
            np.asarray(jax.ops.segment_sum(
                jnp.asarray(cols["v"]), jnp.asarray(ids),
                num_segments=4,
            )),
            "fallback answer",
        )
    finally:
        segment._pallas_disabled = was


def test_non_mosaic_kernel_error_stays_loud(forced, monkeypatch):
    from tensorframes_tpu.ops import verbs

    def boom(*a, **k):
        raise RuntimeError("genuine bug, not a kernel-compile failure")

    monkeypatch.setattr(ksr, "segment_reduce_pallas", boom)
    with pytest.raises(RuntimeError, match="genuine bug"):
        verbs._segment_reduce_best(
            (("v", "reduce_sum"),), 2,
            {"v": np.zeros(8, np.int32)},
            np.zeros(8, np.int32),
        )
    assert segment.pallas_enabled()  # the switch must NOT trip


# ---------------------------------------------------------------------------
# ragged gather
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", ["float32", "float64", "int32"])
def test_ragged_gather_bit_identical_to_stack(dtype):
    rng = np.random.default_rng(11)
    cells = [
        rng.standard_normal(int(rng.integers(1, 40))).astype(dtype)
        for _ in range(80)
    ]
    lens = np.asarray([len(c) for c in cells])
    starts = np.zeros(len(cells), np.int64)
    np.cumsum(lens[:-1], out=starts[1:])
    flat = np.concatenate(cells)
    flat_dev = jnp.asarray(flat)
    for L in np.unique(lens):
        idx = np.flatnonzero(lens == L)
        st = starts[idx]
        got = np.asarray(krg.ragged_gather_rows(
            flat_dev, st, int(L), interpret=True
        ))
        _assert_eq(got, krg.gather_reference(flat, st, int(L)),
                   f"length {L}")
    # padding rows re-reading offset 0 (the bucket-pad convention)
    st = np.zeros(4, np.int32)
    st[:2] = starts[:2]
    got = np.asarray(krg.ragged_gather_rows(
        flat_dev, st, int(lens[0]), interpret=True
    ))
    _assert_eq(got, krg.gather_reference(flat, st, int(lens[0])),
               "padded rows")


def test_ragged_gather_rejects_zero_length():
    with pytest.raises(ValueError, match="length >= 1"):
        krg.ragged_gather_rows(jnp.zeros(4), np.zeros(2), 0)


def test_ragged_map_rows_forced_kernel_bit_identical(forced):
    before = REGISTRY.counter(
        "tftpu_plan_cost_decisions_total",
        labels={"decision": "pallas_ragged_gather"},
    ).value

    def run():
        rng = np.random.default_rng(0)
        lens = rng.choice([3, 5, 8, 13], 150)
        rows = [{"v": np.arange(n, dtype=np.float32) + 0.25}
                for n in lens]
        frame = tfs.frame_from_rows(rows, num_blocks=3)
        program = tfs.compile_program(
            lambda v: {"s": v.sum()}, frame, block=False
        )
        out = tfs.map_rows(program, frame)
        return np.concatenate(
            [np.asarray(b["s"]) for b in out.blocks()]
        )

    forced_res = run()
    assert REGISTRY.counter(
        "tftpu_plan_cost_decisions_total",
        labels={"decision": "pallas_ragged_gather"},
    ).value > before
    configure(pallas_force=False)
    _assert_eq(run(), forced_res, "ragged map_rows forced vs host")


# -- bugfix-sweep pins: zero-row edges of the ragged fallback ---------------

def test_group_rows_by_shape_zero_rows_yields_no_groups():
    from tensorframes_tpu.ops.verbs import _group_rows_by_shape

    assert _group_rows_by_shape({"v": []}, ["v"], 0) == []


def test_ragged_rows_outs_zero_rows_returns_typed_empties():
    from tensorframes_tpu.ops.verbs import _ragged_rows_outs

    tiny = tfs.frame_from_rows(
        [{"v": np.arange(3, dtype=np.float32)}]
    )
    program = tfs.compile_program(
        lambda v: {"s": v.sum()}, tiny, block=False
    )
    outs = _ragged_rows_outs(
        {"v": []}, ["v"], 0, program, program.compiled()
    )
    assert outs["s"].shape == (0,)
    assert outs["s"].dtype == np.float32


# ---------------------------------------------------------------------------
# paged decode attention
# ---------------------------------------------------------------------------

@pytest.mark.parametrize(
    "S,maxp,page,nh,hd",
    [(1, 1, 4, 2, 8), (5, 3, 8, 4, 16), (8, 2, 16, 2, 4)],
)
def test_paged_decode_attention_bit_identical(S, maxp, page, nh, hd):
    """Kernel vs the XLA gather→dequant→attend chain across slot/page
    mixes — including a padding slot with an all-null table."""
    rng = np.random.default_rng(S * 7 + maxp)
    P, L = maxp * S + 1, 2
    q = jnp.asarray(rng.standard_normal((S, nh, hd)), jnp.float32)
    kp = jnp.asarray(
        rng.integers(-127, 128, (P, L, nh, page, hd)), jnp.int8
    )
    vp = jnp.asarray(
        rng.integers(-127, 128, (P, L, nh, page, hd)), jnp.int8
    )
    ks = jnp.asarray(
        rng.uniform(0.01, 0.1, (P, L, nh, page, 1)), jnp.float32
    )
    vs = jnp.asarray(
        rng.uniform(0.01, 0.1, (P, L, nh, page, 1)), jnp.float32
    )
    tables = jnp.asarray(
        rng.integers(1, P, (S, maxp)), jnp.int32
    ).at[-1].set(0)  # padding slot: all-null table
    pos = jnp.asarray(
        rng.integers(0, maxp * page, S), jnp.int32
    ).at[-1].set(0)
    for li in range(L):
        got = np.asarray(kda.paged_decode_attention(
            q, kp, vp, ks, vs, li, tables, pos, interpret=True
        ))
        ref = np.asarray(kda.paged_attention_reference(
            q, kp, vp, ks, vs, li, tables, pos
        ))
        _assert_eq(got, ref, f"layer {li}")


def test_ops_attention_paged_wrapper():
    from tensorframes_tpu.ops.attention import paged_decode_attention

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((2, 2, 4)), jnp.float32)
    kp = jnp.asarray(rng.integers(-5, 5, (3, 1, 2, 4, 4)), jnp.int8)
    ks = jnp.ones((3, 1, 2, 4, 1), jnp.float32)
    tables = jnp.asarray([[1, 2], [0, 0]], jnp.int32)
    pos = jnp.asarray([5, 0], jnp.int32)
    got = paged_decode_attention(
        q, kp, kp, ks, ks, 0, tables, pos, interpret=True
    )
    ref = kda.paged_attention_reference(
        q, kp, kp, ks, ks, 0, tables, pos
    )
    _assert_eq(np.asarray(got), np.asarray(ref), "public wrapper")


def test_decode_engine_forced_kernel_matches_oracle(forced):
    """Slot/page mixes through the real engine with the kernel
    selected: tokens bit-identical to the unforced engine AND to the
    dense int8-KV ``generate()`` oracle."""
    from tensorframes_tpu.models import generation as gen
    from tensorframes_tpu.models import transformer as tr
    from tensorframes_tpu.serving.decode import (
        DecodeConfig, DecodeEngine,
    )

    cfg = gen.gpt_tiny()
    params = tr.init_params(cfg, seed=0)
    rng = np.random.default_rng(0)
    new = 4
    prompts = [
        rng.integers(
            0, cfg.vocab_size, (int(rng.integers(2, 9)),)
        ).astype(np.int32)
        for _ in range(4)
    ]

    def run():
        eng = DecodeEngine("kern-t", cfg, params, DecodeConfig(
            max_slots=2, page_size=4, max_prompt_len=8,
            max_new_tokens=new,
        ))
        eng.start()
        try:
            futs = [eng.submit({"prompt": p}) for p in prompts]
            return [f.result(300)["tokens"] for f in futs]
        finally:
            eng.stop(drain=True, timeout=120)

    forced_outs = run()
    assert kernels.DISPATCHES["decode_attn"].value > 0
    configure(pallas_force=False)
    base_outs = run()
    for i, p in enumerate(prompts):
        _assert_eq(forced_outs[i], base_outs[i], f"req {i} vs XLA chain")
        oracle = np.asarray(
            gen.generate(cfg, params, p[None, :], new, kv_quant=True)
        )
        _assert_eq(forced_outs[i], oracle, f"req {i} vs oracle")


def test_decode_engine_mosaic_failure_recovers(forced):
    """The engine survives a kernel-compile failure: kill-switch trips,
    the step rebuilds on the XLA chain, the request still completes."""
    from tensorframes_tpu.models import generation as gen
    from tensorframes_tpu.models import transformer as tr
    from tensorframes_tpu.serving.decode import (
        DecodeConfig, DecodeEngine,
    )

    cfg = gen.gpt_tiny()
    params = tr.init_params(cfg, seed=0)
    was = segment._pallas_disabled
    eng = DecodeEngine("kern-moz", cfg, params, DecodeConfig(
        max_slots=2, page_size=4, max_prompt_len=8, max_new_tokens=3,
        warmup=False,
    ))
    assert eng._attn_kernel == "pallas"
    real_step = eng._step
    state = {"failed": False}

    def flaky(*args):
        if not state["failed"]:
            state["failed"] = True
            raise RuntimeError("Mosaic lowering failed (test)")
        return real_step(*args)

    eng._step = flaky
    try:
        eng.start()
        out = eng.call(
            {"prompt": np.asarray([1, 2, 3], np.int32)}, timeout=300
        )
        assert out["tokens"].shape == (1, 3)
        assert state["failed"]
        assert eng._attn_kernel is None  # rebuilt on the XLA chain
        assert not segment.pallas_enabled()
        oracle = np.asarray(gen.generate(
            cfg, params, np.asarray([[1, 2, 3]], np.int32), 3,
            kv_quant=True,
        ))
        _assert_eq(out["tokens"], oracle, "post-recovery tokens")
    finally:
        eng.stop(drain=False, timeout=60)
        segment._pallas_disabled = was


# ---------------------------------------------------------------------------
# selection, registry, and switches
# ---------------------------------------------------------------------------

def test_decisions_on_cpu_default_to_non_pallas():
    cols = {"v": np.zeros(8, np.int32)}
    assert prules.decide_segment_reduce(
        (("v", "reduce_sum"),), cols, 4
    ).kind == "jit_segment_reduce"
    assert prules.decide_decode_attention(4, 8, 4, 2).kind == \
        "xla_decode_attn"
    assert prules.decide_ragged_gather(10, 2, np.float32) is None


def test_decisions_under_force_pick_pallas(forced):
    cols = {"v": np.zeros(8, np.int32)}
    assert prules.decide_segment_reduce(
        (("v", "reduce_sum"),), cols, 4
    ).kind == "pallas_segment_reduce"
    assert prules.decide_decode_attention(4, 8, 4, 2).kind == \
        "pallas_decode_attn"
    assert prules.decide_ragged_gather(
        10, 2, np.float32
    ).kind == "pallas_ragged_gather"


def test_host_segment_reduce_still_wins_cpu_float_sums(forced):
    """The measured CPU bincount win outranks the kernel even under
    force: 1-D float sums/means stay on the host path."""
    cols = {"v": np.zeros(8, np.float32)}
    assert prules.decide_segment_reduce(
        (("v", "reduce_mean"),), cols, 4
    ).kind == "host_segment_reduce"


def test_tftpu_pallas_off_removes_kernels_everywhere(forced):
    configure(pallas_kernels=False)
    assert not kernels.enabled()
    cols = {"v": np.zeros(8, np.int32)}
    assert prules.decide_segment_reduce(
        (("v", "reduce_sum"),), cols, 4
    ).kind == "jit_segment_reduce"
    assert prules.decide_decode_attention(4, 8, 4, 2).kind == \
        "xla_decode_attn"
    assert prules.decide_ragged_gather(
        10, 2, np.float32
    ) is None  # the forced fixture restores the prior switch state


def test_kill_switch_disables_kernels_package():
    was = segment._pallas_disabled
    try:
        segment.disable_pallas("kernels package test")
        assert not kernels.enabled()
        assert kernels.fingerprint_token()["enabled"] is False
    finally:
        segment._pallas_disabled = was


def test_kernels_metrics_preregistered():
    names = {m.name for m in REGISTRY.collect()}
    assert "tftpu_kernels_dispatch_total" in names
    assert "tftpu_kernels_interpret_fallback_total" in names
    assert "tftpu_kernels_build_seconds" in names
    labels = {
        dict(m.labels).get("kernel")
        for m in REGISTRY.collect()
        if m.name == "tftpu_kernels_dispatch_total"
    }
    assert labels == set(kernels.KERNELS)
