"""Registered query endpoints (ISSUE 20): the result-cache and
incremental-maintenance contracts.

What must hold, stated in serving/query.py: a repeat query over
unchanged inputs is a cache hit (memo or persistent store — zero chunk
reads, zero plan executions); appending a chunk to the scan directory
invalidates with a COUNTED invalidation and an eligible aggregate
refreshes by re-reading/re-executing ONLY the new chunk, bit-identical
to the one-shot full-table query across ops × dtypes × key kinds ×
ragged chunk sizes; anything outside the incremental contract degrades
to counted full recompute with a named reason (and TFG114 evidence) —
never a wrong answer; a damaged cached partial is quarantined, counted
as ``corrupt_partial``, and recomputed exactly; and a re-registered
endpoint over the same cache dir warms from DISK with zero chunk
executions.
"""

import json
import os
import urllib.request

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.config import get_config
from tensorframes_tpu.observability import context as _ctx
from tensorframes_tpu.plan import ir as plan_ir
from tensorframes_tpu.plan.lower import canonical_table_order
from tensorframes_tpu.serving import (
    QueryEndpoint,
    QuerySource,
    RejectedError,
    Server,
    query_cache_events,
    serve_http,
)
from tensorframes_tpu.validation import ValidationError


@pytest.fixture()
def cache_dir(tmp_path):
    """Point the compile cache (and hence the result store + plan-stats
    sidecar) at a per-test dir; restore afterwards."""
    prev = get_config().compilation_cache_dir
    d = str(tmp_path / "cache")
    tfs.configure(compilation_cache_dir=d)
    yield d
    tfs.configure(compilation_cache_dir=prev)


def _write_chunk(data_dir, i, rows):
    """One CSV part; ``rows`` is a list of (k, v) tuples (may be empty:
    header-only parts must parse as zero rows, not fail)."""
    path = os.path.join(data_dir, f"part-{i:04d}.csv")
    with open(path, "w") as fh:
        fh.write("k,v\n")
        for k, v in rows:
            fh.write(f"{k},{v}\n")
    return path


def _ragged_rows(n, seed, key_kind, dtype):
    rng = np.random.default_rng(seed)
    out = []
    for j in range(n):
        g = int(rng.integers(0, 3))
        k = f"grp{g}" if key_kind == "string" else g
        v = int(rng.integers(-50, 50))
        out.append((k, v if dtype == "int64" else float(v) + 0.5))
    return out


def _table_rows(table, keys):
    """(key-tuple → {out: scalar}) for order-insensitive comparison."""
    names = [n for n in table if n not in keys]
    n = len(next(iter(table.values())))
    out = {}
    for i in range(n):
        kt = tuple(np.asarray(table[k])[i] for k in keys)
        out[kt] = {m: np.asarray(table[m])[i] for m in names}
    return out


def _assert_tables_equal(got, want, keys):
    a, b = _table_rows(got, keys), _table_rows(want, keys)
    assert set(a) == set(b), (sorted(a), sorted(b))
    for kt in a:
        for m in a[kt]:
            ga, gb = a[kt][m], b[kt][m]
            assert ga.dtype == gb.dtype, (kt, m, ga.dtype, gb.dtype)
            assert np.array_equal(ga, gb), (kt, m, ga, gb)


def _build_fn(op):
    """map (dtype-preserving) → keyed aggregate: the canonical
    registered pipeline. ``op`` ∈ sum|min|max|mean."""
    red = {
        "sum": tfs.reduce_sum, "min": tfs.reduce_min,
        "max": tfs.reduce_max, "mean": tfs.reduce_mean,
    }[op]

    def build(f):
        f1 = tfs.map_blocks(lambda v: {"y": v * 2}, f)
        with tfs.with_graph():
            y_in = tfs.block(f1, "y", tf_name="y_input")
            return tfs.aggregate(
                [red(y_in, axis=0, name="y")], f1.group_by("k")
            )

    return build


def _oracle(data_dir, build, dtypes):
    """The one-shot full-table query a non-registered user would run:
    every part concatenated into ONE frame, the same build fn executed
    once over it. The registered endpoint's answer (cached, folded, or
    recomputed) must equal this bit-for-bit."""
    from tensorframes_tpu.io import part_frame, part_manifest

    frames = [
        part_frame(p, kind="csv", dtypes=dtypes)
        for p, _ in part_manifest(data_dir, kind="csv")
    ]
    frames = [f for f in frames if f.num_rows > 0]
    cols = {}
    for info in frames[0].schema:
        parts = [f.column_values(info.name) for f in frames]
        if any(p.dtype == object for p in parts):
            merged = []
            for p in parts:
                merged.extend(p.tolist())
            cols[info.name] = merged
        else:
            cols[info.name] = np.concatenate(parts)
    full = tfs.frame_from_arrays(cols, num_blocks=1)
    out = build(full)
    return {n: out.column_values(n) for n in out.schema.names}


# ---------------------------------------------------------------------------
# property sweep: ops × dtypes × key kinds × ragged chunks, every
# refresh bit-equal to the one-shot full-table query
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("op", ["sum", "min", "max", "mean"])
@pytest.mark.parametrize("key_kind", ["string", "int"])
@pytest.mark.parametrize("dtype", ["int64", "float64"])
def test_refresh_bit_equal_full_recompute(
    tmp_path, cache_dir, op, key_kind, dtype
):
    data = str(tmp_path / "data")
    os.makedirs(data)
    sizes = [7, 0, 23, 1]  # ragged, incl. a header-only part
    for i, n in enumerate(sizes):
        _write_chunk(data, i, _ragged_rows(n, 100 + i, key_kind, dtype))
    build = _build_fn(op)
    q = QueryEndpoint(
        f"sweep-{op}-{key_kind}-{dtype}",
        QuerySource(path=data, kind="csv"), build,
    )
    dtypes = q._csv_dtypes
    # eligibility is a pure function of (op, dtype) — mean never folds,
    # float sums reassociate, min/max fold at any dtype
    if plan_ir.fusion_enabled():
        expect_inc = op in ("min", "max") or (
            op == "sum" and dtype == "int64"
        )
        assert q.cache_stats()["incremental"] == expect_inc
        assert q.cache_stats()["cacheable"]
    _assert_tables_equal(q.execute(), _oracle(data, build, dtypes),
                         ("k",))
    # append a ragged tail (incl. another empty part), refresh each time
    for i, n in enumerate([5, 0, 31], start=len(sizes)):
        _write_chunk(data, i, _ragged_rows(n, 200 + i, key_kind, dtype))
        _assert_tables_equal(q.execute(), _oracle(data, build, dtypes),
                             ("k",))
    # rewrite chunk 0 in place (same path, new content + signature)
    _write_chunk(data, 0, _ragged_rows(11, 999, key_kind, dtype))
    _assert_tables_equal(q.execute(), _oracle(data, build, dtypes),
                         ("k",))
    cs = q.cache_stats()
    assert cs["invalidations"] == 4  # 3 appends + 1 rewrite
    if plan_ir.fusion_enabled() and q.cache_stats()["incremental"]:
        # each refresh re-executed ONLY the changed/new chunks: 5 non-
        # empty initial + 3 appended (one empty still folds its typed
        # empty partial... it executes once) + 1 rewrite
        assert cs["chunks_folded"] > 0
        assert cs["chunks_executed"] == len(sizes) + 3 + 1


def test_incremental_refresh_reexecutes_only_new_chunks(
    tmp_path, cache_dir
):
    if not plan_ir.fusion_enabled():
        pytest.skip("plan chain does not record under TFTPU_FUSION=0")
    data = str(tmp_path / "data")
    os.makedirs(data)
    for i in range(6):
        _write_chunk(data, i, _ragged_rows(20, i, "string", "int64"))
    build = _build_fn("sum")
    q = QueryEndpoint("inc", QuerySource(path=data, kind="csv"), build)
    q.execute()
    base = q.cache_stats()
    assert base["chunks_executed"] == 6
    _write_chunk(data, 6, _ragged_rows(20, 60, "string", "int64"))
    q.execute()
    cs = q.cache_stats()
    assert cs["chunks_executed"] == 7, "an old chunk was re-executed"
    assert cs["chunks_folded"] - base["chunks_folded"] == 6
    assert cs["invalidations"] == 1
    assert cs["recomputes"]["cold"] >= 1
    # repeat: pure memo hit, nothing read or folded
    q.execute()
    cs2 = q.cache_stats()
    assert cs2["hits"] == cs["hits"] + 1
    assert cs2["chunks_executed"] == cs["chunks_executed"]
    assert cs2["chunks_folded"] == cs["chunks_folded"]


# ---------------------------------------------------------------------------
# server lifecycle: warm-at-start, repeat hits, restart-from-disk,
# admission taxonomy
# ---------------------------------------------------------------------------

def test_server_registered_query_lifecycle(tmp_path, cache_dir):
    data = str(tmp_path / "data")
    os.makedirs(data)
    for i in range(3):
        _write_chunk(data, i, _ragged_rows(15, i, "string", "int64"))
    build = _build_fn("sum")
    srv = Server()
    q = srv.register_query(
        "daily", QuerySource(path=data, kind="csv"), build
    )
    # pre-start: admission closed, counted rejection
    with pytest.raises(RejectedError) as ei:
        q.submit(None)
    assert ei.value.reason == "closed"
    # duplicate names refuse across every endpoint kind
    with pytest.raises(ValueError):
        srv.register_query(
            "daily", QuerySource(path=data, kind="csv"), build
        )
    with pytest.raises(ValueError):
        srv.register_query(
            "a/b", QuerySource(path=data, kind="csv"), build
        )
    srv.start()
    try:
        assert "daily" in srv.endpoints()
        assert srv.warmup_reports["daily"]["rows"] == 3
        t1 = srv.call("daily", None)
        t2 = srv.call("daily", {})
        for k in t1:
            assert np.array_equal(t1[k], t2[k])
        cs = q.cache_stats()
        assert cs["hits"] >= 2  # warm primed the cache
        # feeds are meaningless for a registered query: loud refusal
        with pytest.raises(ValidationError):
            srv.call("daily", {"x": np.zeros(3)})
        with pytest.raises(ValueError):
            srv.call("daily", None, deadline_s=-1)
        st = srv.stats()
        assert st["queries"]["daily"]["hits"] >= 2
        assert st["admitted_requests"] >= 2
        assert "daily" in st["latency"]
    finally:
        srv.stop()
    with pytest.raises(RejectedError):
        q.submit(None)


def test_reregistration_warms_from_disk(tmp_path, cache_dir):
    if not plan_ir.fusion_enabled():
        pytest.skip("persistent result store disarms under FUSION=0")
    data = str(tmp_path / "data")
    os.makedirs(data)
    for i in range(4):
        _write_chunk(data, i, _ragged_rows(12, i, "string", "int64"))
    build = _build_fn("sum")
    srv = Server()
    srv.register_query(
        "q", QuerySource(path=data, kind="csv"), build
    )
    srv.start()
    first = srv.call("q", None)
    srv.stop()
    # a FRESH server over the same cache dir: registration re-probes
    # (reads one chunk), but warm answers from the persistent store —
    # zero chunk executions, bit-identical table
    srv2 = Server()
    q2 = srv2.register_query(
        "q", QuerySource(path=data, kind="csv"), build
    )
    srv2.start()
    try:
        cs = q2.cache_stats()
        assert cs["chunks_executed"] == 0
        assert cs["hits"] == 1 and cs["misses"] == 0
        again = srv2.call("q", None)
        for k in first:
            assert first[k].dtype == again[k].dtype
            assert np.array_equal(first[k], again[k])
    finally:
        srv2.stop()


# ---------------------------------------------------------------------------
# corruption: a damaged cached partial degrades to counted recompute,
# never a wrong answer
# ---------------------------------------------------------------------------

def test_corrupt_partial_counted_recompute_exact(tmp_path, cache_dir):
    if not plan_ir.fusion_enabled():
        pytest.skip("persistent partials disarm under FUSION=0")
    data = str(tmp_path / "data")
    os.makedirs(data)
    for i in range(4):
        _write_chunk(data, i, _ragged_rows(10, i, "string", "int64"))
    build = _build_fn("sum")
    src = QuerySource(path=data, kind="csv")
    q = QueryEndpoint("qc", src, build)
    q.execute()
    results_dir = os.path.join(cache_dir, "results")
    partials = [f for f in os.listdir(results_dir) if "-p" in f]
    assert len(partials) == 4
    for fn in partials:  # flip one payload byte in EVERY partial
        p = os.path.join(results_dir, fn)
        with open(p, "r+b") as fh:
            fh.seek(-1, os.SEEK_END)
            b = fh.read(1)
            fh.seek(-1, os.SEEK_END)
            fh.write(bytes([b[0] ^ 0xFF]))
    # fresh endpoint (empty memo), grown directory (forces the
    # incremental path past the cached full result)
    _write_chunk(data, 4, _ragged_rows(10, 40, "string", "int64"))
    q2 = QueryEndpoint("qc", src, build)
    table = q2.execute()
    cs = q2.cache_stats()
    assert cs["recomputes"]["corrupt_partial"] == 4
    assert cs["chunks_executed"] == 5  # every damaged partial re-ran
    _assert_tables_equal(
        table, _oracle(data, build, q2._csv_dtypes), ("k",)
    )
    # the quarantine renamed the damaged entries: a THIRD endpoint
    # sees clean rewritten partials and folds without re-executing
    q3 = QueryEndpoint("qc", src, build)
    q3.execute()
    assert q3.cache_stats()["chunks_executed"] == 0


# ---------------------------------------------------------------------------
# TFG114: the decline taxonomy names the blocking stage
# ---------------------------------------------------------------------------

def test_tfg114_decline_reasons_and_lint(tmp_path, cache_dir):
    if not plan_ir.fusion_enabled():
        pytest.skip("declines are operator-chosen under FUSION=0, "
                    "no TFG114 evidence by design")
    from tensorframes_tpu.analysis import lint_plan

    data = str(tmp_path / "data")
    os.makedirs(data)
    _write_chunk(data, 0, _ragged_rows(25, 0, "string", "float64"))
    src = QuerySource(path=data, kind="csv")

    mine = {"e_float", "e_mean", "e_ck", "e_map"}

    def by_reason():
        # events are process-global and survive earlier tests in the
        # session: filter to THIS test's endpoints
        out = {}
        for e in query_cache_events():
            if e["endpoint"] in mine:
                out.setdefault(e["reason"], []).append(e)
        return out

    # float accumulation: sum over float64 reassociates across chunks
    QueryEndpoint("e_float", src, _build_fn("sum"))
    # mean: partials would need a (sum, count) companion pair
    QueryEndpoint("e_mean", src, _build_fn("mean"))

    # computed key: the group key comes out of a map stage
    def build_ck(f):
        f1 = tfs.map_blocks(lambda v: {"k2": (v > 0)}, f)
        with tfs.with_graph():
            v_in = tfs.block(f1, "v", tf_name="v_input")
            return tfs.aggregate(
                [tfs.reduce_min(v_in, axis=0, name="v")],
                f1.group_by("k2"),
            )
    QueryEndpoint("e_ck", src, build_ck)

    # no terminal aggregate: a map-only pipeline still caches, but
    # refreshes re-execute everything
    QueryEndpoint(
        "e_map", src,
        lambda f: tfs.map_blocks(lambda v: {"y": v * 3.0}, f),
    )
    evs = by_reason()
    assert [e["endpoint"] for e in evs["float_accumulation"]] == \
        ["e_float"]
    assert [e["endpoint"] for e in evs["reduce_mean"]] == ["e_mean"]
    assert [e["endpoint"] for e in evs["computed_key"]] == ["e_ck"]
    assert [e["endpoint"] for e in evs["no_terminal_aggregate"]] == \
        ["e_map"]
    assert all(
        e["mode"] == "incremental"
        for es in evs.values() for e in es
    )
    # lint_plan surfaces each with an actionable fix
    fr = tfs.frame_from_arrays({"v": np.arange(4.0)})
    lazy = tfs.map_blocks(lambda v: {"y": v + 1.0}, fr)
    rep = lint_plan(lazy)
    found = [d for d in rep.diagnostics
             if d.code == "TFG114" and d.subject in mine]
    assert len(found) == 4
    for d in found:
        assert d.fix, d
    # every decline still answers (counted full recompute)
    q = QueryEndpoint("e_exec", src, _build_fn("mean"))
    q.execute()
    assert q.cache_stats()["recomputes"]["ineligible"] == 1


def test_registration_rollback_withdraws_tfg114(tmp_path, cache_dir):
    if not plan_ir.fusion_enabled():
        pytest.skip("no TFG114 evidence under FUSION=0")
    data = str(tmp_path / "data")
    os.makedirs(data)
    _write_chunk(data, 0, _ragged_rows(8, 0, "string", "float64"))
    srv = Server()
    srv.start()
    try:
        # live registration: probe succeeds (evidence recorded), warm
        # fails → rollback must withdraw the endpoint AND its evidence
        class Boom(RuntimeError):
            pass

        def build(f):
            out = _build_fn("mean")(f)
            if getattr(build, "armed", False):
                raise Boom()
            return out

        srv.register_query(
            "ghost", QuerySource(path=data, kind="csv"), build
        )
        assert any(e["endpoint"] == "ghost"
                   for e in query_cache_events())
        srv2_names = srv.endpoints()
        assert "ghost" in srv2_names
    finally:
        srv.stop()
    # stopping is not withdrawal (the endpoint still exists on the
    # server object); rollback is exercised via a warm failure
    srv3 = Server()
    srv3.start()
    try:
        def build_fail(f):
            raise RuntimeError("broken build")

        with pytest.raises(RuntimeError):
            srv3.register_query(
                "broken", QuerySource(path=data, kind="csv"),
                build_fail,
            )
        assert "broken" not in srv3.endpoints()
        assert not any(e["endpoint"] == "broken"
                       for e in query_cache_events())
    finally:
        srv3.stop()


# ---------------------------------------------------------------------------
# sources: frames, parquet gating, empty dirs
# ---------------------------------------------------------------------------

def test_frame_source_and_validation(tmp_path, cache_dir):
    fr = tfs.frame_from_arrays({
        "k": np.arange(12, dtype=np.int64) % 3,
        "v": np.arange(12, dtype=np.int64),
    })
    q = QueryEndpoint(
        "mem", QuerySource(frame=fr), _build_fn("sum")
    )
    t = q.execute()
    want = canonical_table_order(
        {"k": np.arange(3, dtype=np.int64),
         "y": np.array([2 * (0 + 3 + 6 + 9), 2 * (1 + 4 + 7 + 10),
                        2 * (2 + 5 + 8 + 11)])},
        ("k",),
    )
    _assert_tables_equal(t, want, ("k",))
    q.execute()
    assert q.cache_stats()["hits"] == 1  # digest-stable frame memoizes
    with pytest.raises(ValueError):
        QuerySource()  # neither path nor frame
    with pytest.raises(ValueError):
        QuerySource(path="/x", frame=fr)  # both
    with pytest.raises(ValueError):
        QuerySource(path="/x", kind="orc")
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    with pytest.raises((ValueError, FileNotFoundError)):
        QueryEndpoint(
            "e", QuerySource(path=empty, kind="csv"), _build_fn("sum")
        )


# ---------------------------------------------------------------------------
# HTTP: a registered query rides the same adapter (string keys take
# the object-dtype serialization path)
# ---------------------------------------------------------------------------

def test_http_serves_registered_query(tmp_path, cache_dir):
    data = str(tmp_path / "data")
    os.makedirs(data)
    for i in range(2):
        _write_chunk(data, i, _ragged_rows(9, i, "string", "int64"))
    srv = Server()
    srv.register_query(
        "web", QuerySource(path=data, kind="csv"), _build_fn("sum")
    )
    srv.start()
    httpd = serve_http(srv, port=0)
    port = httpd.server_address[1]
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/web",
            data=json.dumps({}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            body = json.loads(resp.read())
        want = _oracle(data, _build_fn("sum"), None)
        want = canonical_table_order(want, ("k",))
        srt = np.argsort(np.asarray(body["outputs"]["k"], dtype=object))
        got_k = [body["outputs"]["k"][i] for i in srt]
        got_y = [body["outputs"]["y"][i] for i in srt]
        assert got_k == list(want["k"])
        assert got_y == list(want["y"])
        assert body["rows"] == len(want["k"])
    finally:
        httpd.shutdown()
        srv.stop()
