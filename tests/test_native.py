"""Native marshalling layer tests: the C++ gather/scatter kernels agree
with the pure-Python path and honour the same error contracts
(≙ the reference's convert/convertBack correctness checks through
DebugRowOpsSuite + ConvertPerformanceSuite harnesses)."""

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu import native


pytestmark = pytest.mark.skipif(
    not native.available(), reason="native extension unavailable"
)


def test_gather_scalar_dtypes():
    rows = [{"d": float(i), "f": np.float32(i / 2), "i": np.int32(i), "l": i}
            for i in range(7)]
    d = native.gather_column(rows, "d", np.float64)
    f = native.gather_column(rows, "f", np.float32)
    i = native.gather_column(rows, "i", np.int32)
    l = native.gather_column(rows, "l", np.int64)
    assert d.dtype == np.float64 and np.allclose(d, np.arange(7))
    assert f.dtype == np.float32 and np.allclose(f, np.arange(7) / 2)
    assert i.dtype == np.int32 and (i == np.arange(7)).all()
    assert l.dtype == np.int64 and (l == np.arange(7)).all()


def test_gather_error_contracts():
    with pytest.raises(KeyError):
        native.gather_column([{"x": 1.0}, {"y": 2.0}], "x", np.float64)
    with pytest.raises(OverflowError):
        native.gather_column([{"x": 2**40}], "x", np.int32)
    with pytest.raises(TypeError):
        native.gather_column([{"x": "nope"}], "x", np.float64)


def test_scatter_roundtrip():
    names = ["a", "b"]
    arrays = [np.arange(5, dtype=np.float64), np.arange(5, dtype=np.int64)]
    rows = native.columns_to_rows(names, arrays)
    assert rows == [{"a": float(i), "b": i} for i in range(5)]
    # cells are Python scalars, not numpy
    assert type(rows[0]["a"]) is float and type(rows[0]["b"]) is int


def test_frame_from_rows_uses_native_and_matches():
    rows = [{"x": float(i), "n": i} for i in range(103)]
    df = tfs.frame_from_rows(rows, num_blocks=4)
    # gathered into dense 1-D numpy storage (the native path's signature)
    [b0] = df.blocks()[:1]
    assert isinstance(b0["x"], np.ndarray) and b0["x"].dtype == np.float64
    assert isinstance(b0["n"], np.ndarray) and b0["n"].dtype == np.int64
    assert df.collect() == rows


def test_mixed_typed_columns_fall_back():
    # a string column can't ride the native path; the frame still builds
    rows = [{"x": float(i), "s": f"r{i}"} for i in range(9)]
    df = tfs.frame_from_rows(rows, num_blocks=2)
    got = df.collect()
    assert got == rows


def test_vector_cells_fall_back():
    rows = [{"v": [1.0 * i, 2.0 * i]} for i in range(6)]
    df = tfs.frame_from_rows(rows, num_blocks=2)
    got = df.collect()
    assert np.allclose(np.stack([r["v"] for r in got]),
                       np.stack([r["v"] for r in rows]))


def test_collect_native_equals_python(monkeypatch):
    rows = [{"x": float(i), "n": i} for i in range(50)]
    df = tfs.frame_from_rows(rows, num_blocks=3)
    fast = df.collect()
    # force the pure-Python collect path and compare
    monkeypatch.setattr(native, "supported_dtype", lambda _dt: False)
    slow = df.collect()
    assert fast == slow == rows


def test_int_column_with_float_cell_falls_back():
    # first row says int64, a later float cell breaks the native pass —
    # the column must fall back, not corrupt
    rows = [{"x": 1}, {"x": 2.5}]
    df = tfs.frame_from_rows(rows)
    got = [r["x"] for r in df.collect()]
    assert got[1] == pytest.approx(2.5) or got[1] == 2  # numpy coercion class


def test_parse_csv_rejects_non_int_dtype_code():
    """A non-int element in the dtype-code list must raise cleanly (the
    C++ loop checks the PyLong_AsLong sentinel) instead of continuing
    with a garbage code and surfacing a SystemError later."""
    mod = native._load()
    with pytest.raises(TypeError):
        mod.parse_csv(b"1,2\n3,4\n", ord(","), ["not-an-int", 1])


def test_dict_encode_matches_numpy_unique():
    """Native O(n) hash dictionary encode (round 3: replaces the
    sort-based np.unique that dominated string-key aggregate cost) must
    agree with numpy on codes and lexicographic unique order."""
    from tensorframes_tpu import native
    from tensorframes_tpu.ops.keys import _unique_inverse

    if not native.available():
        pytest.skip("native module unavailable")
    rng = np.random.default_rng(3)
    labels = np.array(["b", "a", "c", "a"], object)[rng.integers(0, 4, 5000)]
    u_np, inv_np = np.unique(labels, return_inverse=True)
    u_nat, inv_nat = _unique_inverse(labels)
    assert list(u_nat) == list(u_np)
    np.testing.assert_array_equal(inv_nat, inv_np)
    # mixed hashables (ints as object cells) work too
    mixed = np.array([3, 1, 2, 1, 3], object)
    u2, inv2 = _unique_inverse(mixed)
    assert list(u2) == [1, 2, 3]
    np.testing.assert_array_equal(inv2, [2, 0, 1, 0, 2])


def test_dict_encode_unhashable_cell_raises():
    from tensorframes_tpu import native

    if not native.available():
        pytest.skip("native module unavailable")
    with pytest.raises(TypeError):
        native.dict_encode([["unhashable"], "x"])


def test_unique_inverse_fixed_width_str_dtype():
    """The '<U' branch (how the host aggregate path actually hits this —
    np.asarray(list_of_str)): dtype must be preserved and order match
    numpy."""
    from tensorframes_tpu.ops.keys import _unique_inverse

    labels = np.asarray(["pear", "apple", "fig", "apple", "pear"])
    assert labels.dtype.kind == "U"
    u, inv = _unique_inverse(labels)
    u_np, inv_np = np.unique(labels, return_inverse=True)
    assert u.dtype == labels.dtype
    assert list(u) == list(u_np)
    np.testing.assert_array_equal(inv, inv_np)


@pytest.mark.parametrize("use_native", [True, False])
def test_unique_inverse_nan_keys_collapse_to_one_group(
    monkeypatch, use_native
):
    """Catalyst grouping convention: NaN keys compare equal — and the
    answer must NOT depend on whether the native build succeeded (two
    DISTINCT nan objects still form one group). use_native=False forces
    the pure-python fallback a host without the C extension gets."""
    from tensorframes_tpu import native
    from tensorframes_tpu.ops import keys

    if use_native and not native.available():
        pytest.skip("native module unavailable")
    if not use_native:
        monkeypatch.setattr(native, "dict_encode", lambda cells: None)

    a = np.empty(5, object)
    a[:] = [float("nan"), "x", float("nan"), "x", float("nan")]
    u, inv = keys._unique_inverse(a)
    assert len(u) == 2
    assert inv[0] == inv[2] == inv[4]
    assert inv[1] == inv[3]

    # pure-float NaN column: one group
    b = np.empty(4, object)
    b[:] = [float("nan"), 1.5, float("nan"), 2.5]
    u2, inv2 = keys._unique_inverse(b)
    assert len(u2) == 3
    assert inv2[0] == inv2[2]


@pytest.mark.parametrize("use_native", [True, False])
def test_unique_inverse_fallback_matches_native(monkeypatch, use_native):
    """The numpy-free fallback and the native hash pass must return
    byte-identical encodes for the same column (codes AND group order) —
    the cross-host determinism contract the round-3 review flagged."""
    from tensorframes_tpu import native
    from tensorframes_tpu.ops import keys

    if use_native and not native.available():
        pytest.skip("native module unavailable")
    if not use_native:
        monkeypatch.setattr(native, "dict_encode", lambda cells: None)

    labels = np.asarray(["pear", "apple", "fig", "apple", "pear"])
    u, inv = keys._unique_inverse(labels)
    u_np, inv_np = np.unique(labels, return_inverse=True)
    assert u.dtype == labels.dtype
    assert list(u) == list(u_np)
    np.testing.assert_array_equal(inv, inv_np)

    obj = np.empty(4, object)
    obj[:] = ["b", 2, "a", 2]  # mixed types: deterministic type-name order
    u3, inv3 = keys._unique_inverse(obj)
    # one shared ground truth for BOTH encode paths: the (type name,
    # repr) total order puts int before str, then 'a' < 'b'
    assert list(u3) == [2, "a", "b"]
    np.testing.assert_array_equal(inv3, [2, 0, 1, 0])


def test_stack_cells_matches_np_stack():
    """Native stack_cells: one memcpy pass over equal-shape cells ==
    np.stack, across dtypes/ranks; mismatched cells raise like np.stack
    (including the same-bytes-different-shape trap: [2,6] vs [3,4])."""
    from tensorframes_tpu import native

    if not native.available():
        pytest.skip("native module unavailable")
    rng = np.random.default_rng(0)
    for dtype, shape in [
        (np.float32, (8,)), (np.float64, (3, 4)), (np.int64, ()),
        (np.int8, (5, 2, 2)),
    ]:
        cells = [
            np.ascontiguousarray(rng.standard_normal(shape).astype(dtype))
            for _ in range(7)
        ]
        got = native.stack_cells(cells)
        assert got is not None
        np.testing.assert_array_equal(got, np.stack(cells))
    with pytest.raises(ValueError):
        native.stack_cells(
            [np.zeros((2, 6), np.float32), np.zeros((3, 4), np.float32)]
        )
    with pytest.raises(ValueError):
        native.stack_cells(
            [np.zeros(4, np.float32), np.zeros(4, np.int32)]
        )


def test_stack_group_falls_back_on_noncontiguous_later_cell():
    """ADVICE r4: a sliced-view (non-C-contiguous) cell AFTER cell 0
    passes the wrapper's cell-0 pre-check but makes PyObject_GetBuffer
    raise BufferError inside rowpack.cpp — _stack_group must fall back
    to np.stack (which handles views fine), not abort the ragged map."""
    from tensorframes_tpu.ops.verbs import _stack_group

    base = np.arange(24, dtype=np.float32).reshape(4, 6)
    cells = [
        np.ascontiguousarray(base[0, ::2]),  # contiguous cell 0
        base[1, ::2],                        # strided view: not contiguous
        np.ascontiguousarray(base[2, ::2]),
    ]
    assert not cells[1].flags.c_contiguous
    got = _stack_group(cells, [0, 1, 2])
    np.testing.assert_array_equal(got, np.stack(cells))
