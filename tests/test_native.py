"""Native marshalling layer tests: the C++ gather/scatter kernels agree
with the pure-Python path and honour the same error contracts
(≙ the reference's convert/convertBack correctness checks through
DebugRowOpsSuite + ConvertPerformanceSuite harnesses)."""

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu import native


pytestmark = pytest.mark.skipif(
    not native.available(), reason="native extension unavailable"
)


def test_gather_scalar_dtypes():
    rows = [{"d": float(i), "f": np.float32(i / 2), "i": np.int32(i), "l": i}
            for i in range(7)]
    d = native.gather_column(rows, "d", np.float64)
    f = native.gather_column(rows, "f", np.float32)
    i = native.gather_column(rows, "i", np.int32)
    l = native.gather_column(rows, "l", np.int64)
    assert d.dtype == np.float64 and np.allclose(d, np.arange(7))
    assert f.dtype == np.float32 and np.allclose(f, np.arange(7) / 2)
    assert i.dtype == np.int32 and (i == np.arange(7)).all()
    assert l.dtype == np.int64 and (l == np.arange(7)).all()


def test_gather_error_contracts():
    with pytest.raises(KeyError):
        native.gather_column([{"x": 1.0}, {"y": 2.0}], "x", np.float64)
    with pytest.raises(OverflowError):
        native.gather_column([{"x": 2**40}], "x", np.int32)
    with pytest.raises(TypeError):
        native.gather_column([{"x": "nope"}], "x", np.float64)


def test_scatter_roundtrip():
    names = ["a", "b"]
    arrays = [np.arange(5, dtype=np.float64), np.arange(5, dtype=np.int64)]
    rows = native.columns_to_rows(names, arrays)
    assert rows == [{"a": float(i), "b": i} for i in range(5)]
    # cells are Python scalars, not numpy
    assert type(rows[0]["a"]) is float and type(rows[0]["b"]) is int


def test_frame_from_rows_uses_native_and_matches():
    rows = [{"x": float(i), "n": i} for i in range(103)]
    df = tfs.frame_from_rows(rows, num_blocks=4)
    # gathered into dense 1-D numpy storage (the native path's signature)
    [b0] = df.blocks()[:1]
    assert isinstance(b0["x"], np.ndarray) and b0["x"].dtype == np.float64
    assert isinstance(b0["n"], np.ndarray) and b0["n"].dtype == np.int64
    assert df.collect() == rows


def test_mixed_typed_columns_fall_back():
    # a string column can't ride the native path; the frame still builds
    rows = [{"x": float(i), "s": f"r{i}"} for i in range(9)]
    df = tfs.frame_from_rows(rows, num_blocks=2)
    got = df.collect()
    assert got == rows


def test_vector_cells_fall_back():
    rows = [{"v": [1.0 * i, 2.0 * i]} for i in range(6)]
    df = tfs.frame_from_rows(rows, num_blocks=2)
    got = df.collect()
    assert np.allclose(np.stack([r["v"] for r in got]),
                       np.stack([r["v"] for r in rows]))


def test_collect_native_equals_python(monkeypatch):
    rows = [{"x": float(i), "n": i} for i in range(50)]
    df = tfs.frame_from_rows(rows, num_blocks=3)
    fast = df.collect()
    # force the pure-Python collect path and compare
    monkeypatch.setattr(native, "supported_dtype", lambda _dt: False)
    slow = df.collect()
    assert fast == slow == rows


def test_int_column_with_float_cell_falls_back():
    # first row says int64, a later float cell breaks the native pass —
    # the column must fall back, not corrupt
    rows = [{"x": 1}, {"x": 2.5}]
    df = tfs.frame_from_rows(rows)
    got = [r["x"] for r in df.collect()]
    assert got[1] == pytest.approx(2.5) or got[1] == 2  # numpy coercion class


def test_parse_csv_rejects_non_int_dtype_code():
    """A non-int element in the dtype-code list must raise cleanly (the
    C++ loop checks the PyLong_AsLong sentinel) instead of continuing
    with a garbage code and surfacing a SystemError later."""
    mod = native._load()
    with pytest.raises(TypeError):
        mod.parse_csv(b"1,2\n3,4\n", ord(","), ["not-an-int", 1])
