"""Plan profiler (ISSUE 17): EXPLAIN ANALYZE and latency-driven plan
decisions.

Four surfaces under test:

* **EXPLAIN ANALYZE** — ``explain(analyze=True)`` on a forced frame
  renders the plan tree followed by the recorded per-stage profile
  (wall, strategy), and the per-stage walls reconcile with the measured
  force wall;
* **latency-driven flips** — an inverted observed-wall table flips
  ``decide_fuse`` to the per-stage replay on the next execution,
  counted as ``reoptimized``, with bit-identical results; the pure
  ``pick_by_observed_wall`` core honors min-samples and the hysteresis
  margin; ``decide_epilogue``/``decide_decode_attention`` flip from an
  injected table and never against the forced-kernel pin;
* **sidecar hygiene** — a corrupt ``strategy_walls.json`` quarantines
  (counted + unlinked, decisions fall back to static) and stale entries
  are pruned, mirroring the selectivity-record contract;
* **observability surface** — ``report --profile`` renders the sidecar
  offline, and the new series are PRE-registered (TFL003)."""

import glob
import json
import os
import time

import numpy as np
import pytest

import tensorframes_tpu as tfs
from tensorframes_tpu.observability import cli, profile
from tensorframes_tpu.observability.metrics import REGISTRY
from tensorframes_tpu.plan import rules
from tensorframes_tpu.plan import stats as plan_stats


@pytest.fixture(autouse=True)
def _fusion_on():
    """Pin fusion on (the flip target is the fused segment); leave
    plan_reopt AMBIENT so the CI REOPT=0 leg still collects this file
    (the engaged-machinery tests skip themselves)."""
    cfg = tfs.configure()
    before = (cfg.plan_fusion, cfg.plan_reopt)
    tfs.configure(plan_fusion=True)
    yield
    tfs.configure(plan_fusion=before[0], plan_reopt=before[1])


_reopt_only = pytest.mark.skipif(
    not tfs.configure().plan_reopt,
    reason="adaptive optimizer disabled (TFTPU_REOPT=0)",
)


def _count(kind):
    for d in REGISTRY.snapshot():
        if (
            d["name"] == "tftpu_plan_cost_decisions_total"
            and d["labels"].get("decision") == kind
        ):
            return float(d.get("value", 0.0))
    return 0.0


def _sidecar_count(event):
    for d in REGISTRY.snapshot():
        if (
            d["name"] == "tftpu_plan_reopt_sidecar_total"
            and d["labels"].get("event") == event
        ):
            return float(d.get("value", 0.0))
    return 0.0


def _rows_equal(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert ra.keys() == rb.keys()
        for k in ra:
            va, vb = np.asarray(ra[k]), np.asarray(rb[k])
            assert va.dtype == vb.dtype, (k, va.dtype, vb.dtype)
            np.testing.assert_array_equal(va, vb)


def _fused_chain(n=256, blocks=4):
    """A 2-stage composable map chain — decide_fuse's 'fuse' territory."""
    df = tfs.frame_from_arrays(
        {"x": np.arange(float(n), dtype=np.float32)}, num_blocks=blocks
    )
    f = tfs.map_blocks(lambda x: {"u": x * 2.0}, df)
    return tfs.map_blocks(lambda u: {"y": u + 1.0}, f)


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE: structure + wall reconciliation
# ---------------------------------------------------------------------------

@_reopt_only
def test_explain_analyze_renders_profile_and_walls_reconcile(tmp_path):
    was = tfs.configure().compilation_cache_dir
    tfs.configure(compilation_cache_dir=str(tmp_path))
    try:
        plan_stats.clear_memory()
        g = _fused_chain()
        t0 = time.perf_counter()
        g.collect()
        measured = time.perf_counter() - t0

        fp = getattr(g, "_plan_fp", None)
        assert fp, "force must stash the plan fingerprint on the frame"
        text = g.explain(analyze=True)
        assert f"profile: fp={fp}" in text
        assert "execs=" in text and "wall=" in text

        rec = plan_stats.lookup(fp)
        assert rec is not None and rec["execs"] >= 1
        prof = rec.get("profile")
        assert isinstance(prof, list) and prof, (
            "EXPLAIN ANALYZE needs a recorded per-stage breakdown"
        )
        for entry in prof:
            assert entry["stage"], entry
            assert float(entry["wall_s"]) >= 0.0
            # every recorded stage renders as an indented profile line
            assert f"{entry['stage']}  wall=" in text
        # reconciliation: stages run sequentially inside the force, so
        # their walls sum to no more than the measured force wall (the
        # recorded total is the exact force wall on a first execution —
        # generous slack keeps slow-CI timer jitter out of the gate)
        stage_sum = sum(float(e["wall_s"]) for e in prof)
        assert 0.0 < stage_sum <= float(rec["wall_s"]) * 1.05 + 0.01
        assert float(rec["wall_s"]) <= measured * 1.5 + 0.05
        # the chosen strategy is part of the profile (the whole point:
        # seeing WHICH lowering the walls were observed under)
        assert any(e.get("strategy") for e in prof)
    finally:
        tfs.configure(compilation_cache_dir=was)
        plan_stats.clear_memory()


@_reopt_only
def test_explain_analyze_before_any_execution_points_at_force(tmp_path):
    was = tfs.configure().compilation_cache_dir
    tfs.configure(compilation_cache_dir=str(tmp_path))
    try:
        plan_stats.clear_memory()
        g = _fused_chain()
        text = g.explain(analyze=True)
        assert "no recorded execution" in text
        assert "->" in text  # the plan tree still renders
    finally:
        tfs.configure(compilation_cache_dir=was)
        plan_stats.clear_memory()


def test_explain_analyze_reopt_off_says_so():
    was = tfs.configure().plan_reopt
    tfs.configure(plan_reopt=False)
    try:
        g = _fused_chain()
        text = g.explain(analyze=True)
        assert "adaptive stats are off" in text
        # once forced with stats off there is nothing recorded to show:
        # the frame drops its plan chain and no fingerprint was stashed
        g.collect()
        text = g.explain(analyze=True)
        assert "no plan chain and no recorded execution" in text
    finally:
        tfs.configure(plan_reopt=was)


# ---------------------------------------------------------------------------
# latency-driven decisions: end-to-end flip + the pure core
# ---------------------------------------------------------------------------

@_reopt_only
def test_inverted_walls_flip_fuse_to_per_stage_bit_identically():
    """The tentpole gate: after the observed-wall table says the
    per-stage replay is faster, the next execution takes it — counted
    as a flip — and moves not a single bit."""
    plan_stats.clear_memory()
    plan_stats.reset_strategy_walls()

    def build():
        return _fused_chain().collect()

    try:
        baseline = build()
        walls = plan_stats.strategy_walls("fuse")
        assert walls.get("fuse", {}).get("n", 0) >= 1, (
            "the fused dispatch must feed its wall back into the table"
        )
        # invert: the fused path 'measures' slow, the replay fast
        for _ in range(max(2, plan_stats.STRATEGY_WALL_MIN_SAMPLES) * 2):
            plan_stats.observe_strategy_wall("fuse", "fuse", 10.0)
            plan_stats.observe_strategy_wall(
                "fuse", "split_single_stage", 1e-4
            )
        s0 = _count("split_single_stage")
        r0 = _count("reoptimized")
        flipped = build()
        assert _count("split_single_stage") > s0, (
            "inverted walls must flip decide_fuse to the replay"
        )
        assert _count("reoptimized") > r0, (
            "a latency flip must count as a re-optimization"
        )
        _rows_equal(baseline, flipped)
    finally:
        plan_stats.reset_strategy_walls()
        plan_stats.clear_memory()


def test_pick_by_observed_wall_min_samples_and_margin():
    pick = rules.pick_by_observed_wall
    # no table / thin evidence → no flip
    assert pick("fuse", ("split_single_stage",), None) is None
    assert pick("fuse", ("split_single_stage",), {}) is None
    thin = {
        "fuse": {"ewma_s": 1.0, "n": 1},
        "split_single_stage": {"ewma_s": 0.01, "n": 9},
    }
    assert pick("fuse", ("split_single_stage",), thin) is None
    thin_alt = {
        "fuse": {"ewma_s": 1.0, "n": 9},
        "split_single_stage": {"ewma_s": 0.01, "n": 1},
    }
    assert pick("fuse", ("split_single_stage",), thin_alt) is None
    # hysteresis: 10% faster is inside the margin, not a flip
    close = {
        "fuse": {"ewma_s": 1.0, "n": 4},
        "split_single_stage": {
            "ewma_s": rules.LATENCY_FLIP_MARGIN + 0.01, "n": 4
        },
    }
    assert pick("fuse", ("split_single_stage",), close) is None
    # decisively faster → flip, with auditable evidence
    clear = {
        "fuse": {"ewma_s": 1.0, "n": 4},
        "split_single_stage": {"ewma_s": 0.5, "n": 4},
    }
    got = pick("fuse", ("split_single_stage",), clear)
    assert got is not None
    kind, evidence = got
    assert kind == "split_single_stage"
    assert evidence["latency_flip"] is True
    assert evidence["observed_wall_s"] == {
        "fuse": 1.0, "split_single_stage": 0.5
    }
    assert evidence["wall_samples"] == {
        "fuse": 4, "split_single_stage": 4
    }


def test_decide_epilogue_flips_only_when_exact():
    walls = {
        "epilogue_per_block": {"ewma_s": 1.0, "n": 4},
        "epilogue_concat": {"ewma_s": 0.1, "n": 4},
    }
    # all-exact ops: the flip is pure latency, allowed
    d = rules.decide_epilogue(
        [("reduce_sum", np.int32)], num_groups=4, value_bytes=1024,
        observed_walls=walls,
    )
    assert d.kind == "epilogue_concat"
    assert d.details["latency_flip"] is True
    # no walls → the static per-block choice
    d = rules.decide_epilogue(
        [("reduce_sum", np.int32)], num_groups=4, value_bytes=1024,
    )
    assert d.kind == "epilogue_per_block"
    # float sums: concat is the CORRECTNESS choice, never a wall flip
    d = rules.decide_epilogue(
        [("reduce_sum", np.float32)], num_groups=4, value_bytes=1024,
        observed_walls=walls,
    )
    assert d.kind == "epilogue_concat"
    assert "latency_flip" not in d.details


def test_decide_decode_attention_flip_and_force_pin(monkeypatch):
    monkeypatch.setattr(rules, "_kernel_backend_ok", lambda: True)
    monkeypatch.setattr(rules, "_force_pins_kernels", lambda: False)
    walls = {
        "pallas_decode_attn": {"ewma_s": 0.02, "n": 4},
        "xla_decode_attn": {"ewma_s": 0.001, "n": 4},
    }
    d = rules.decide_decode_attention(
        8, 64, 16, 32, observed_walls=walls
    )
    assert d.kind == "xla_decode_attn"
    assert d.details["latency_flip"] is True
    # TFTPU_PALLAS_FORCE pins the kernel: the flip must never override
    # the hook that exists to exercise a SPECIFIC lowering
    monkeypatch.setattr(rules, "_force_pins_kernels", lambda: True)
    d = rules.decide_decode_attention(
        8, 64, 16, 32, observed_walls=walls
    )
    assert d.kind == "pallas_decode_attn"


# ---------------------------------------------------------------------------
# strategy-wall sidecar hygiene: corrupt → quarantine, stale → pruned
# ---------------------------------------------------------------------------

@_reopt_only
def test_strategy_wall_sidecar_corruption_quarantines(tmp_path):
    was = tfs.configure().compilation_cache_dir
    tfs.configure(compilation_cache_dir=str(tmp_path))
    try:
        plan_stats.clear_memory()
        plan_stats.observe_strategy_wall("fuse", "fuse", 0.5)
        path = tmp_path / "planstats" / "strategy_walls.json"
        assert path.exists(), "observations must persist to the sidecar"

        plan_stats.clear_memory()
        path.write_text("{definitely not json")
        q0 = _sidecar_count("quarantine")
        assert plan_stats.strategy_walls("fuse") == {}
        assert _sidecar_count("quarantine") == q0 + 1
        assert not path.exists(), "a corrupt table is unlinked, not kept"

        # stale format: same contract
        plan_stats.clear_memory()
        plan_stats.observe_strategy_wall("fuse", "fuse", 0.5)
        rec = json.loads(path.read_text())
        rec["v"] = plan_stats.FORMAT_VERSION + 999
        path.write_text(json.dumps(rec))
        plan_stats.clear_memory()
        q1 = _sidecar_count("quarantine")
        assert plan_stats.strategy_walls("fuse") == {}
        assert _sidecar_count("quarantine") == q1 + 1
    finally:
        plan_stats.reset_strategy_walls()
        tfs.configure(compilation_cache_dir=was)
        plan_stats.clear_memory()


@_reopt_only
def test_strategy_wall_stale_entries_are_pruned(tmp_path):
    was = tfs.configure().compilation_cache_dir
    tfs.configure(compilation_cache_dir=str(tmp_path))
    try:
        plan_stats.clear_memory()
        side = tmp_path / "planstats"
        side.mkdir()
        obs = plan_stats.STRATEGY_STALE_OBS + 10
        (side / "strategy_walls.json").write_text(json.dumps({
            "v": plan_stats.SW_FORMAT_VERSION, "kind": "strategy_walls",
            "tables": {"fuse": {"obs": obs, "strategies": {
                # unrefreshed for > STRATEGY_STALE_OBS observations
                "fuse": {"ewma_s": 1.0, "n": 5, "last_obs": 1},
                "split_single_stage": {
                    "ewma_s": 0.5, "n": 5, "last_obs": obs - 1
                },
            }}},
            "workloads": {},
        }))
        q0 = _sidecar_count("quarantine")
        walls = plan_stats.strategy_walls("fuse")
        assert set(walls) == {"split_single_stage"}, (
            "a months-stale entry is not evidence — it must be dropped"
        )
        assert _sidecar_count("quarantine") == q0 + 1
    finally:
        plan_stats.reset_strategy_walls()
        tfs.configure(compilation_cache_dir=was)
        plan_stats.clear_memory()


# ---------------------------------------------------------------------------
# offline report + pre-registered series
# ---------------------------------------------------------------------------

@_reopt_only
def test_report_profile_renders_sidecar_offline(tmp_path, capsys):
    was = tfs.configure().compilation_cache_dir
    tfs.configure(compilation_cache_dir=str(tmp_path))
    try:
        plan_stats.clear_memory()
        _fused_chain().collect()
        side = str(tmp_path / "planstats")
        assert glob.glob(os.path.join(side, "*.json"))

        text = profile.render_report(side)
        assert "plan-profile sidecar" in text
        assert "1 fingerprint(s)" in text
        assert "slowest recorded plan stage" in text
        assert "wall=" in text and "fp=" in text

        rc = cli.main(["report", "--profile", side])
        assert rc == 0
        assert "plan-profile sidecar" in capsys.readouterr().out

        # a corrupt file is skipped and COUNTED, never quarantined: the
        # report is a read-only visitor over someone else's artifact
        junk = os.path.join(side, "deadbeef" * 4 + ".json")
        with open(junk, "w") as f:
            f.write("{nope")
        text = profile.render_report(side)
        assert "1 unreadable file(s) skipped" in text
        assert os.path.exists(junk)
    finally:
        plan_stats.reset_strategy_walls()
        tfs.configure(compilation_cache_dir=was)
        plan_stats.clear_memory()


def test_profiler_series_are_preregistered():
    """TFL003: the profiler's series exist (zero-valued) before any
    traffic — dashboards never see a label set pop into existence."""
    snap = REGISTRY.snapshot()
    stages = {
        d["labels"].get("stage")
        for d in snap if d["name"] == "tftpu_plan_stage_wall_seconds"
    }
    assert {"fused", "per_stage", "join", "aggregate",
            "pushdown"} <= stages
    pairs = {
        (d["labels"].get("decision"), d["labels"].get("strategy"))
        for d in snap if d["name"] == "tftpu_plan_strategy_wall_seconds"
    }
    assert ("fuse", "fuse") in pairs
    assert ("fuse", "split_single_stage") in pairs
    assert ("epilogue", "epilogue_concat") in pairs
    assert ("segment_reduce", "jit_segment_reduce") in pairs
    assert ("decode_attention", "xla_decode_attn") in pairs
    assert any(
        d["name"] == "tftpu_serving_request_trace_total" for d in snap
    )
