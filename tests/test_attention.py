"""Attention kernel tests: blockwise and ring vs the dense oracle
(forward and gradients), plus the transformer wired to each impl."""

import numpy as np
import pytest

import tensorframes_tpu  # noqa: F401  (x64 config)
import jax
import jax.numpy as jnp

from tensorframes_tpu.ops import attention as att
from tensorframes_tpu.parallel import device_count, make_mesh


def _qkv(b=2, h=4, s=64, d=16, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.standard_normal((b, h, s, d)).astype(np.float32))
        for _ in range(3)
    )


def test_blockwise_matches_dense():
    q, k, v = _qkv()
    ref = att.dense_attention(q, k, v)
    out = att.blockwise_attention(q, k, v, block_size=16)
    assert np.allclose(ref, out, atol=1e-5)


def test_blockwise_causal():
    q, k, v = _qkv()
    ref = att.dense_attention(q, k, v, causal=True)
    out = att.blockwise_attention(q, k, v, causal=True, block_size=16)
    assert np.allclose(ref, out, atol=1e-5)


def test_blockwise_non_divisible_block():
    # seq 60 with block 16 → padding path
    q, k, v = _qkv(s=60)
    ref = att.dense_attention(q, k, v)
    out = att.blockwise_attention(q, k, v, block_size=16)
    assert np.allclose(ref, out, atol=1e-5)


def test_blockwise_grads_match_dense():
    q, k, v = _qkv(s=32)

    def loss_ref(q):
        return att.dense_attention(q, k, v).sum()

    def loss_bw(q):
        return att.blockwise_attention(q, k, v, block_size=8).sum()

    g_ref = jax.grad(loss_ref)(q)
    g_bw = jax.grad(loss_bw)(q)
    assert np.allclose(g_ref, g_bw, atol=1e-4)


@pytest.mark.skipif(device_count() < 8, reason="needs 8 virtual devices")
def test_ring_matches_dense():
    q, k, v = _qkv()
    mesh = make_mesh({"sp": 8})
    ref = att.dense_attention(q, k, v)
    out = att.ring_attention(q, k, v, mesh, axis="sp")
    assert np.allclose(ref, out, atol=1e-5)


@pytest.mark.skipif(device_count() < 8, reason="needs 8 virtual devices")
def test_ring_causal_matches_dense():
    q, k, v = _qkv()
    mesh = make_mesh({"sp": 8})
    ref = att.dense_attention(q, k, v, causal=True)
    out = att.ring_attention(q, k, v, mesh, axis="sp", causal=True)
    assert np.allclose(ref, out, atol=1e-5)


@pytest.mark.skipif(device_count() < 8, reason="needs 8 virtual devices")
def test_ring_dp_sp_mesh():
    q, k, v = _qkv()
    mesh = make_mesh({"dp": 2, "sp": 4})
    ref = att.dense_attention(q, k, v)
    out = att.ring_attention(q, k, v, mesh, axis="sp", batch_axis="dp")
    assert np.allclose(ref, out, atol=1e-5)


@pytest.mark.skipif(device_count() < 8, reason="needs 8 virtual devices")
def test_ring_grads_match_dense():
    q, k, v = _qkv(s=32)
    mesh = make_mesh({"sp": 8})

    g_ref = jax.grad(lambda q: att.dense_attention(q, k, v).sum())(q)
    g_ring = jax.grad(
        lambda q: att.ring_attention(q, k, v, mesh, axis="sp").sum()
    )(q)
    assert np.allclose(g_ref, g_ring, atol=1e-4)


@pytest.mark.skipif(device_count() < 8, reason="needs 8 virtual devices")
def test_ring_rejects_non_divisible_seq():
    q, k, v = _qkv(s=60)
    mesh = make_mesh({"sp": 8})
    with pytest.raises(ValueError, match="divisible"):
        att.ring_attention(q, k, v, mesh, axis="sp")


def test_transformer_blockwise_matches_dense():
    from tensorframes_tpu.models import transformer as tr

    cfg_d = tr.tiny()
    cfg_b = tr.tiny(attention_impl="blockwise")
    params = tr.init_params(cfg_d)
    tokens, _ = tr.synthetic_batch(cfg_d, 2, 16)
    hd = np.asarray(tr.forward(cfg_d, params, tokens), dtype=np.float32)
    hb = np.asarray(tr.forward(cfg_b, params, tokens), dtype=np.float32)
    assert np.allclose(hd, hb, atol=6e-2)  # bf16 accumulation tolerance


@pytest.mark.skipif(device_count() < 8, reason="needs 8 virtual devices")
def test_transformer_ring_sharded_train_step():
    import optax

    from tensorframes_tpu.models import transformer as tr

    mesh = make_mesh({"dp": 2, "tp": 2, "sp": 2})
    cfg = tr.tiny(attention_impl="ring")
    params = tr.init_params(cfg)
    tx = optax.adamw(1e-3)
    step, data_sharding, param_sh, init_opt = tr.make_sharded_train_step(
        cfg, mesh, tx
    )
    tokens, targets = tr.synthetic_batch(cfg, 4, 16)
    tokens = jax.device_put(tokens, data_sharding)
    targets = jax.device_put(targets, data_sharding)
    params = jax.device_put(params, param_sh)
    opt_state = init_opt(params)
    _, _, loss = step(params, opt_state, tokens, targets)
    assert np.isfinite(float(loss))

    # ring loss ≈ dense loss on the same params/batch
    cfg_d = tr.tiny()
    ref = float(tr.loss_fn(cfg_d, tr.init_params(cfg), np.asarray(tokens), np.asarray(targets)))
    assert abs(float(loss) - ref) < 5e-2


def test_mask_rejected_by_non_dense_impls():
    from tensorframes_tpu.models import transformer as tr

    cfg = tr.tiny(attention_impl="blockwise")
    params = tr.init_params(cfg)
    tokens, _ = tr.synthetic_batch(cfg, 2, 8)
    mask = np.ones((2, 8), dtype=bool)
    with pytest.raises(NotImplementedError, match="padding mask"):
        tr.forward(cfg, params, tokens, mask=jnp.asarray(mask))


def test_ring_requires_mesh():
    from tensorframes_tpu.models import transformer as tr

    cfg = tr.tiny(attention_impl="ring")
    params = tr.init_params(cfg)
    tokens, _ = tr.synthetic_batch(cfg, 2, 8)
    with pytest.raises(ValueError, match="'sp' axis"):
        tr.forward(cfg, params, tokens)


def test_sharded_train_step_on_pure_dp_mesh():
    # the library's own default mesh has no 'sp' axis; the step must not
    # demand one
    import optax

    from tensorframes_tpu.models import transformer as tr
    from tensorframes_tpu.parallel import make_mesh

    mesh = make_mesh()  # pure dp
    cfg = tr.tiny()
    params = tr.init_params(cfg)
    tx = optax.adamw(1e-3)
    step, data_sharding, param_sh, init_opt = tr.make_sharded_train_step(
        cfg, mesh, tx
    )
    tokens, targets = tr.synthetic_batch(cfg, 8, 8)
    p = jax.device_put(params, param_sh)
    opt = init_opt(p)
    t = jax.device_put(tokens, data_sharding)
    g = jax.device_put(targets, data_sharding)
    _, _, loss = step(p, opt, t, g)
    assert np.isfinite(float(loss))


def test_seg_info_survives_feed_dict():
    import tensorframes_tpu as tfs
    from tensorframes_tpu import dtypes as dt

    df = tfs.frame_from_arrays(
        {
            "key": np.arange(12, dtype=np.int64) % 2,
            "col": np.arange(12, dtype=np.float64),
        }
    )
    ph = tfs.placeholder(dt.float64, [None], name="col_input")
    fetch = tfs.reduce_sum(ph, axis=0, name="col")
    prog = tfs.compile_program(fetch, df, reduce_mode="blocks")
    renamed = prog.rename_inputs({"col_input": "col_input"})
    assert getattr(renamed, "seg_info", None) is not None


def test_dense_attention_padding_mask():
    q, k, v = _qkv(s=8)
    pm = np.ones((2, 8), dtype=bool)
    pm[:, 6:] = False
    out = att.dense_attention(q, k, v, padding_mask=jnp.asarray(pm))
    ref = att.dense_attention(q[:, :, :, :], k[:, :, :6], v[:, :, :6])
    # queries attend only to the first 6 keys
    assert np.allclose(out, ref, atol=1e-5)


def test_ulysses_matches_dense():
    from tensorframes_tpu.ops import attention as att
    from tensorframes_tpu.parallel import make_mesh

    mesh = make_mesh({"sp": 4, "dp": 2})
    rng = np.random.default_rng(5)
    b, h, s, d = 2, 4, 16, 8
    q, k, v = (
        jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
        for _ in range(3)
    )
    got = att.ulysses_attention(q, k, v, mesh, axis="sp")
    want = att.dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)


def test_ulysses_causal_matches_dense():
    from tensorframes_tpu.ops import attention as att
    from tensorframes_tpu.parallel import make_mesh

    mesh = make_mesh({"sp": 4, "dp": 2})
    rng = np.random.default_rng(6)
    b, h, s, d = 1, 4, 32, 8
    q, k, v = (
        jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
        for _ in range(3)
    )
    got = att.ulysses_attention(q, k, v, mesh, axis="sp", causal=True)
    want = att.dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)


def test_ulysses_head_divisibility_error():
    from tensorframes_tpu.ops import attention as att
    from tensorframes_tpu.parallel import make_mesh

    mesh = make_mesh({"sp": 4, "dp": 2})
    q = jnp.zeros((1, 3, 16, 8), jnp.float32)  # 3 heads, sp=4
    with pytest.raises(ValueError, match="heads 3 not divisible"):
        att.ulysses_attention(q, q, q, mesh, axis="sp")


def test_transformer_ulysses_impl():
    from tensorframes_tpu.models import transformer as tr
    from tensorframes_tpu.parallel import make_mesh

    mesh = make_mesh({"sp": 4, "dp": 2})
    cfg = tr.tiny(attention_impl="ulysses")
    params = tr.init_params(cfg, seed=0)
    tokens, _ = tr.synthetic_batch(cfg, 2, 16, seed=0)
    hs = tr.forward(cfg, params, jnp.asarray(tokens), mesh=mesh)
    dense_cfg = tr.tiny(attention_impl="dense")
    want = tr.forward(dense_cfg, params, jnp.asarray(tokens))
    np.testing.assert_allclose(
        np.asarray(hs, np.float32), np.asarray(want, np.float32),
        rtol=3e-2, atol=3e-2,  # bf16 activations
    )
